"""Autograd mode switches + the tape engine.

Reference design: codegen'd per-op GradNodes walked by egr::RunBackward
(paddle/fluid/eager/backward.cc:105) with GradTensorHolder accumulation.
TPU-native design: one generic engine — every op records a `Node` holding the
`jax.vjp` closure of its forward fn; `backward()` is a reverse-topological walk
with cotangent accumulation. No per-op codegen is needed because JAX already
knows the VJP of every primitive.
"""
import contextlib

from ..profiler import _tracer as _TRACER

_grad_enabled = True


def is_grad_enabled() -> bool:
    return _grad_enabled


def set_grad_enabled(mode: bool):
    global _grad_enabled
    _grad_enabled = bool(mode)


class _GradCtx(contextlib.ContextDecorator):
    def __init__(self, mode: bool):
        self._mode = mode

    def __enter__(self):
        global _grad_enabled
        self._prev = _grad_enabled
        _grad_enabled = self._mode
        return self

    def __exit__(self, *exc):
        global _grad_enabled
        _grad_enabled = self._prev
        return False


def no_grad():
    """paddle.no_grad() — usable as decorator or context manager."""
    return _GradCtx(False)


def enable_grad():
    return _GradCtx(True)


class Node:
    """One tape entry: the vjp closure of a single traced op."""

    __slots__ = ("vjp_fn", "inputs", "outputs", "multi_output", "name", "fwd",
                 "input_versions", "materialize", "once_differentiable",
                 "vjp_fn_tape")

    # unhashable on purpose: double-grad records vjp calls through apply_op
    # with the Node in a closure cell, and an identity-hashed Node would fill
    # the eager op cache with one dead entry per backward pass
    __hash__ = None

    def __init__(self, vjp_fn, inputs, outputs, multi_output, name="",
                 fwd=None):
        self.vjp_fn = vjp_fn
        self.inputs = inputs        # list[Tensor] — the differentiable inputs
        self.outputs = outputs      # list[Tensor]
        self.multi_output = multi_output
        self.name = name
        # PyLayer knobs (reference EagerPyLayerContext): materialize=False
        # passes None (not zeros) for outputs with no incoming cotangent;
        # once_differentiable forbids building a grad-of-grad graph through
        # this node
        self.materialize = True
        self.once_differentiable = False
        # optional create_graph-mode vjp: runs the user backward WITH the
        # tape recording (cotangents as live Tensors), so grads-of-grads
        # flow through saved tensors back to the primals.  Without it, a
        # fwd=None node's vjp under create_graph is re-recorded via
        # apply_op, where saved residuals are closure constants and second
        # order through them is structurally zero — fine for engine-internal
        # nodes (those set fwd), wrong for user PyLayers.
        self.vjp_fn_tape = None
        # inplace-version snapshot of each input (reference: eager
        # TensorWrapper::recover checks wrapper_version_snapshot): backward
        # raises if an input was mutated in place after this op recorded it
        self.input_versions = [getattr(t, "_version", 0) for t in inputs]
        # closed forward over the diff inputs (raw arrays): lets create_graph
        # re-derive the vjp as a function of the PRIMALS, so second-order
        # terms (which live in the residuals) survive. None => second order
        # through this node is zero (e.g. PyLayer with opaque backward).
        self.fwd = fwd

    def release(self):
        self.vjp_fn = None
        self.inputs = None
        for o in self.outputs or ():
            o._node = None
        self.outputs = None
        self.fwd = None


def _topo_from(root_node):
    """Iterative post-order DFS over the tape; returns nodes leaves-first."""
    order, seen = [], set()
    stack = [(root_node, False)]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for t, ver in zip(node.inputs, node.input_versions):
            if getattr(t, "_version", 0) != ver:
                raise RuntimeError(
                    f"in-place modification error in backward of op "
                    f"'{node.name}': an input tensor was mutated after the "
                    f"op recorded it (tensor version "
                    f"{getattr(t, '_version', 0)} != snapshot {ver}); "
                    f"clone() the tensor before the in-place op")
            if t._node is not None and id(t._node) not in seen:
                stack.append((t._node, False))
    return order


def _apply_hooks(tensor, g, create_graph):
    """Run a tensor's registered grad hooks over its finalized cotangent.
    Hooks see (and may return) Tensors — reference: imperative/hooks.h."""
    hooks = getattr(tensor, "_hooks", None)
    if not hooks:
        return g
    from .tensor import Tensor
    gt = g if isinstance(g, Tensor) else Tensor(g)
    for hook in list(hooks.values()):
        out = hook(gt)
        if out is not None:
            gt = out if isinstance(out, Tensor) else Tensor(out)
    if create_graph or isinstance(g, Tensor):
        return gt
    return gt._data


def run_backward(tensor, grad=None, retain_graph=False, create_graph=False,
                 capture=None, accumulate_leaf_grads=True):
    """Tape walk wrapped in a Backward phase span (reference: the Backward
    TracerEventType RunBackward stamps); see _run_backward_impl."""
    if not _TRACER.enabled:
        return _run_backward_impl(tensor, grad, retain_graph, create_graph,
                                  capture, accumulate_leaf_grads)
    rec = _TRACER.begin("backward", "Backward")
    try:
        return _run_backward_impl(tensor, grad, retain_graph, create_graph,
                                  capture, accumulate_leaf_grads)
    finally:
        _TRACER.end(rec)


def _run_backward_impl(tensor, grad=None, retain_graph=False,
                       create_graph=False, capture=None,
                       accumulate_leaf_grads=True):
    """Generic reverse sweep from `tensor`.

    create_graph: cotangents flow as Tensors and every vjp call is recorded
    through apply_op, so the produced gradients are themselves differentiable
    (double grad — reference: eager/general_grad.h).
    capture: optional {id(t): t} of tensors whose finalized cotangent should
    be returned (paddle.grad); leaves still accumulate .grad only when
    accumulate_leaf_grads.
    """
    import jax.numpy as jnp
    from .tensor import Tensor, apply_op

    captured = {}
    if tensor._node is None:
        if capture and id(tensor) in capture:
            g0 = grad if grad is not None else jnp.ones_like(tensor._data)
            captured[id(tensor)] = g0
        return captured
    if grad is None:
        grad = jnp.ones_like(tensor._data)
    if isinstance(grad, Tensor) and not create_graph:
        grad = grad._data
    if create_graph and not isinstance(grad, Tensor):
        grad = Tensor(grad, stop_gradient=False)

    def zero_like(o):
        z = jnp.zeros_like(o._data)
        return Tensor(z, stop_gradient=False) if create_graph else z

    def add(a, b):
        return a + b   # Tensor + Tensor or raw + raw

    order = _topo_from(tensor._node)
    cotangents = {id(tensor): grad}
    leaf_grads = {}    # id -> (leaf tensor, accumulated cotangent)

    for node in reversed(order):
        cts = [cotangents.pop(id(o), None) for o in node.outputs]
        if all(c is None for c in cts):
            continue
        if node.materialize or create_graph:
            # create_graph always materializes: the recorded grad-op's
            # inputs must be arrays, not holes
            cts = [c if c is not None else zero_like(o)
                   for c, o in zip(cts, node.outputs)]
        # cotangents of this node's outputs are final here (reverse topo):
        # fire hooks, record captures
        for o, i in zip(node.outputs, range(len(cts))):
            if cts[i] is None:
                continue
            cts[i] = _apply_hooks(o, cts[i], create_graph)
            if capture and id(o) in capture:
                captured[id(o)] = cts[i]
        if create_graph:
            if node.once_differentiable:
                # the FIRST-order grad must still succeed under
                # create_graph (the pass may be differentiating an
                # unrelated branch); the error fires only if these grads
                # are themselves differentiated (reference/torch
                # once_differentiable semantics)
                raw = [c._data if isinstance(c, Tensor) else c for c in cts]
                gs = node.vjp_fn(tuple(raw) if node.multi_output else raw[0])
                if not isinstance(gs, tuple):
                    gs = (gs,)
                name = node.name

                def poison(_seeds, _name=name):
                    raise RuntimeError(
                        f"grad of grad through once_differentiable backward "
                        f"'{_name}' is not allowed (reference: "
                        f"autograd/py_layer.py once_differentiable)")

                in_grads = []
                poisoned_outs = []
                for g in gs:
                    if g is None:
                        in_grads.append(None)
                    else:
                        tg = Tensor(g, stop_gradient=False)
                        in_grads.append(tg)
                        poisoned_outs.append(tg)
                if poisoned_outs:
                    pnode = Node(poison, list(node.inputs), poisoned_outs,
                                 len(poisoned_outs) > 1,
                                 name=f"once_differentiable:{name}")
                    for tg in poisoned_outs:
                        tg._node = pnode
                in_grads = tuple(in_grads)
            elif node.vjp_fn_tape is not None:
                tcts = [c if isinstance(c, Tensor)
                        else Tensor(c, stop_gradient=False) for c in cts]
                in_grads = node.vjp_fn_tape(
                    tuple(tcts) if node.multi_output else tcts[0])
                if not isinstance(in_grads, tuple):
                    in_grads = (in_grads,)
            elif node.fwd is not None:
                # differentiate-through-backward: rebuild the vjp from the
                # primal inputs so d(grad)/d(primal) is on the tape
                n_in = len(node.inputs)

                def call(*vals, _node=node, _n=n_in):
                    import jax as _jax
                    _, vjp_fn = _jax.vjp(_node.fwd, *vals[:_n])
                    seeds = vals[_n:]
                    return vjp_fn(tuple(seeds) if _node.multi_output
                                  else seeds[0])
                in_grads = apply_op(call, *node.inputs, *cts,
                                    name=f"grad:{node.name}")
            else:
                def call(*seeds, _node=node):
                    return _node.vjp_fn(tuple(seeds) if _node.multi_output
                                        else seeds[0])
                in_grads = apply_op(call, *cts, name=f"grad:{node.name}")
            if not isinstance(in_grads, tuple):
                in_grads = (in_grads,)
        else:
            seed = tuple(cts) if node.multi_output else cts[0]
            in_grads = node.vjp_fn(seed)
        for inp, g in zip(node.inputs, in_grads):
            if inp.stop_gradient or g is None:
                # None from a user backward (PyLayer) = "no grad for this
                # input" (reference py_layer: returned None is skipped)
                continue
            key = id(inp)
            if inp._node is None:
                if key in leaf_grads:
                    leaf_grads[key] = (inp, add(leaf_grads[key][1], g))
                else:
                    leaf_grads[key] = (inp, g)
            elif key in cotangents:
                cotangents[key] = add(cotangents[key], g)
            else:
                cotangents[key] = g

    for key, (leaf, g) in leaf_grads.items():
        g = _apply_hooks(leaf, g, create_graph)
        if capture and key in capture:
            captured[key] = g
        if accumulate_leaf_grads:
            raw = g._data if isinstance(g, Tensor) else g
            if leaf._grad_data is None:
                leaf._grad_data = raw
            else:
                leaf._grad_data = leaf._grad_data + raw

    if not (retain_graph or create_graph):
        for node in order:
            node.release()
    return captured


def backward(tensor, grad=None, retain_graph=False):
    """Reverse-mode sweep from `tensor` accumulating into leaf `.grad`s."""
    run_backward(tensor, grad, retain_graph=retain_graph)
