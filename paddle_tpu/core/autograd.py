"""Autograd mode switches + the tape engine.

Reference design: codegen'd per-op GradNodes walked by egr::RunBackward
(paddle/fluid/eager/backward.cc:105) with GradTensorHolder accumulation.
TPU-native design: one generic engine — every op records a `Node` holding the
`jax.vjp` closure of its forward fn; `backward()` is a reverse-topological walk
with cotangent accumulation. No per-op codegen is needed because JAX already
knows the VJP of every primitive.
"""
import contextlib

_grad_enabled = True


def is_grad_enabled() -> bool:
    return _grad_enabled


def set_grad_enabled(mode: bool):
    global _grad_enabled
    _grad_enabled = bool(mode)


class _GradCtx(contextlib.ContextDecorator):
    def __init__(self, mode: bool):
        self._mode = mode

    def __enter__(self):
        global _grad_enabled
        self._prev = _grad_enabled
        _grad_enabled = self._mode
        return self

    def __exit__(self, *exc):
        global _grad_enabled
        _grad_enabled = self._prev
        return False


def no_grad():
    """paddle.no_grad() — usable as decorator or context manager."""
    return _GradCtx(False)


def enable_grad():
    return _GradCtx(True)


class Node:
    """One tape entry: the vjp closure of a single traced op."""

    __slots__ = ("vjp_fn", "inputs", "outputs", "multi_output", "name")

    def __init__(self, vjp_fn, inputs, outputs, multi_output, name=""):
        self.vjp_fn = vjp_fn
        self.inputs = inputs        # list[Tensor] — the differentiable inputs
        self.outputs = outputs      # list[Tensor]
        self.multi_output = multi_output
        self.name = name

    def release(self):
        self.vjp_fn = None
        self.inputs = None
        for o in self.outputs or ():
            o._node = None
        self.outputs = None


def _topo_from(root_node):
    """Iterative post-order DFS over the tape; returns nodes leaves-first."""
    order, seen = [], set()
    stack = [(root_node, False)]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for t in node.inputs:
            if t._node is not None and id(t._node) not in seen:
                stack.append((t._node, False))
    return order


def backward(tensor, grad=None, retain_graph=False):
    """Reverse-mode sweep from `tensor` accumulating into leaf `.grad`s."""
    import jax.numpy as jnp
    from .tensor import Tensor

    if tensor._node is None:
        return
    if grad is None:
        grad = jnp.ones_like(tensor._data)
    elif isinstance(grad, Tensor):
        grad = grad._data

    order = _topo_from(tensor._node)
    cotangents = {id(tensor): grad}

    for node in reversed(order):
        cts = [cotangents.pop(id(o), None) for o in node.outputs]
        if all(c is None for c in cts):
            continue
        cts = [c if c is not None else jnp.zeros_like(o._data)
               for c, o in zip(cts, node.outputs)]
        seed = tuple(cts) if node.multi_output else cts[0]
        in_grads = node.vjp_fn(seed)
        for inp, g in zip(node.inputs, in_grads):
            if inp.stop_gradient:
                continue
            if inp._node is None:  # leaf: accumulate into .grad (paddle semantics)
                if inp._grad_data is None:
                    inp._grad_data = g
                else:
                    inp._grad_data = inp._grad_data + g
            else:
                key = id(inp)
                if key in cotangents:
                    cotangents[key] = cotangents[key] + g
                else:
                    cotangents[key] = g

    if not retain_graph:
        for node in order:
            node.release()
