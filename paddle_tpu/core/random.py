"""Stateful-feel RNG over stateless JAX PRNG keys.

Reference: per-device `Generator` (paddle/phi/core/generator.h) with a global
seed. On TPU, statefulness cannot live inside compiled programs, so the global
generator hands out keys derived by `fold_in(base_key, counter)`. Inside a
traced (jit) region, the tracer-aware key must be threaded explicitly — the
hapi/jit layers do that by seeding from a per-step counter array (see
paddle_tpu.hapi.model); eager callers just get fresh keys from this module.
"""
import jax
import numpy as np


class Generator:
    """Base keys are materialised lazily: constructing a Generator (and hence
    importing paddle_tpu, which builds the default one below) must not
    initialize the accelerator backend — `jax.random.key` does."""

    def __init__(self, seed_=0):
        self.manual_seed(seed_)

    def manual_seed(self, s):
        self._seed = int(s)
        self._base_key = None
        self._counter = 0
        return self

    @property
    def base_key(self):
        if self._base_key is None:
            self._base_key = jax.random.key(self._seed)
        return self._base_key

    def next_key(self):
        k = jax.random.fold_in(self.base_key, self._counter)
        self._counter += 1
        return k

    def get_state(self):
        return (self._seed, self._counter)

    def set_state(self, state):
        self._seed, self._counter = state
        self._base_key = None
        return self


_default_generator = Generator(np.random.SeedSequence().entropy % (2**31))

# When set (by jit tracing layers), next_key() derives from this traced key
# instead of the stateful global generator, keeping compiled programs pure.
_traced_key = None
_traced_counter = 0


class traced_rng:
    """Context manager installing a traced base key for use under jit."""

    def __init__(self, key):
        self._key = key

    def __enter__(self):
        global _traced_key, _traced_counter
        self._prev = (_traced_key, _traced_counter)
        _traced_key = self._key
        _traced_counter = 0
        return self

    def __exit__(self, *exc):
        global _traced_key, _traced_counter
        _traced_key, _traced_counter = self._prev
        return False


def seed(s):
    """paddle.seed(s)"""
    _default_generator.manual_seed(s)
    return _default_generator


def next_key():
    global _traced_counter
    if _traced_key is not None:
        k = jax.random.fold_in(_traced_key, _traced_counter)
        _traced_counter += 1
        return k
    return _default_generator.next_key()


def get_rng_state():
    return _default_generator.get_state()


def set_rng_state(state):
    _default_generator.set_state(state)


import contextlib  # noqa: E402


@contextlib.contextmanager
def fork_rng(seed_):
    """Run a region under an independent, reproducible RNG stream, restoring
    the previous state on exit (used by the TP RNGStatesTracker)."""
    saved = _default_generator.get_state()
    _default_generator.manual_seed(int(seed_))
    try:
        yield
    finally:
        _default_generator.set_state(saved)
