"""Core runtime: Tensor, autograd tape, dtype/device/random machinery.

TPU-native re-design of the reference's phi/core + eager runtime
(reference: paddle/phi/core/dense_tensor.h:37, paddle/fluid/eager/backward.cc:105).
Instead of a C++ kernel registry dispatching per-backend kernels, every op is a
jax/jnp computation; autograd is a thin tape over `jax.vjp` rather than
codegen'd GradNodes.
"""
from .dtype import (  # noqa: F401
    DType, float16, bfloat16, float32, float64, int8, int16, int32, int64,
    uint8, bool_, complex64, complex128, convert_dtype, get_default_dtype,
    set_default_dtype,
)
from .device import (  # noqa: F401
    set_device, get_device, device_count, is_compiled_with_tpu,
    is_compiled_with_cuda, is_compiled_with_xpu, is_compiled_with_npu,
    default_device, CPUPlace, TPUPlace, Place,
)
from .autograd import no_grad, enable_grad, is_grad_enabled, set_grad_enabled  # noqa: F401
from .random import seed, get_rng_state, set_rng_state, next_key, Generator  # noqa: F401
from .tensor import Tensor, apply_op, to_tensor, wrap, unwrap  # noqa: F401
