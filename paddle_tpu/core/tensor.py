"""Tensor: the user-facing eager tensor.

Reference: `phi::DenseTensor` (paddle/phi/core/dense_tensor.h:37) +
`egr::EagerVariable`/AutogradMeta (paddle/fluid/eager/autograd_meta.h:61).
Here a Tensor wraps a `jax.Array`; autograd metadata is just (stop_gradient,
grad, producer Node). Every op funnels through `apply_op`, which either runs
the jnp computation directly (no grad needed) or runs it through `jax.vjp`
and records a tape Node — the single generic replacement for the reference's
thousands of codegen'd `*_ad_func` + GradNode classes.
"""
import numbers

import jax
import jax.numpy as jnp
import numpy as np

from . import autograd as ag
from . import dtype as _dt
from .autograd import Node
from .device import default_device
from ..profiler import _tracer as _TRACER


def _is_traced(x) -> bool:
    return isinstance(x, jax.core.Tracer)


class Tensor:
    __slots__ = ("_data", "stop_gradient", "_grad_data", "_node", "name",
                 "persistable", "trainable", "_dist_attr", "_asp_mask",
                 "_hooks", "_version", "__weakref__")

    def __init__(self, data, stop_gradient=True, name=None):
        if isinstance(data, Tensor):
            data = data._data
        self._data = data
        self.stop_gradient = stop_gradient
        self._grad_data = None
        self._node = None
        self.name = name
        self.persistable = False
        self.trainable = not stop_gradient
        # inplace version counter (reference: imperative/variable_wrapper.h
        # InplaceVersion / eager TensorWrapper version snapshot): bumped on
        # every in-place mutation; backward raises on mismatch instead of
        # silently using post-mutation values
        self._version = 0

    # -- basic properties ---------------------------------------------------
    @property
    def data(self):
        return self

    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    dim = ndim

    @property
    def dtype(self):
        return jnp.dtype(self._data.dtype)

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def place(self):
        return default_device()

    @property
    def grad(self):
        if self._grad_data is None:
            return None
        return Tensor(self._grad_data, stop_gradient=True)

    @grad.setter
    def grad(self, value):
        if value is None:
            self._grad_data = None
        else:
            self._grad_data = value._data if isinstance(value, Tensor) else jnp.asarray(value)

    @property
    def is_leaf(self):
        return self._node is None

    def numel(self):
        return self.size

    # -- conversion ---------------------------------------------------------
    def numpy(self, force_int64=False):
        """Host copy. `force_int64=True` (or FLAGS_int64_numpy_boundary)
        upcasts integer arrays to int64 at the numpy boundary — the escape
        hatch for the documented on-device int64→int32 policy, for
        consumers that np.save/type-check against reference-written int64
        state. Device layout is untouched."""
        a = np.asarray(self._data)
        if a.dtype == np.int32 and not force_int64:
            from ..framework import flags as _flags
            force_int64 = bool(_flags._FLAGS.get(
                "FLAGS_int64_numpy_boundary", False))
        if force_int64 and a.dtype == np.int32:
            return a.astype(np.int64)
        return a

    def item(self):
        return self._data.item()

    def tolist(self):
        return np.asarray(self._data).tolist()

    def __array__(self, dtype=None):
        arr = np.asarray(self._data)
        return arr.astype(dtype) if dtype is not None else arr

    def astype(self, dtype):
        # canonical() applies the documented int64/f64 policy silently at
        # the API boundary (x64 is off; jax would warn-and-truncate anyway)
        d = _dt.canonical(dtype)
        return apply_op(lambda x: x.astype(d), self)

    cast = astype

    def detach(self):
        t = Tensor(self._data, stop_gradient=True, name=self.name)
        return t

    def clone(self):
        return apply_op(lambda x: x + jnp.zeros((), x.dtype), self)

    def cpu(self):
        return Tensor(jax.device_put(self._data, jax.devices("cpu")[0]),
                      stop_gradient=self.stop_gradient)

    def to(self, device=None, dtype=None):
        out = self
        if dtype is not None:
            out = out.astype(dtype)
        return out

    def pin_memory(self):
        return self

    # -- autograd -----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        ag.backward(self, grad_tensor, retain_graph)

    def clear_grad(self):
        self._grad_data = None

    clear_gradient = clear_grad

    def register_hook(self, hook):
        """Register a gradient hook: hook(grad Tensor) -> new grad or None,
        fired when this tensor's cotangent is finalized during backward
        (reference: imperative/hooks.h TensorHook). Returns a removable
        handle (.remove())."""
        if self.stop_gradient:
            raise RuntimeError(
                "cannot register a grad hook on a tensor with "
                "stop_gradient=True")
        hooks = getattr(self, "_hooks", None)
        if hooks is None:
            hooks = {}
            self._hooks = hooks
        return HookRemoveHelper(hooks, hook)

    # -- in-place helpers ---------------------------------------------------
    def _replace(self, new_tensor):
        """Adopt another tensor's value+tape (for in-place semantics).

        When the adopted op consumed `self` (y.tanh_() records tanh(y)),
        the node's input reference to `self` is swapped for a snapshot of
        the pre-inplace tensor — otherwise the node would be its own input
        and backward would never reach the producers of the old value
        (reference: dygraph inplace keeps the old version alive for the
        grad graph via TensorWrapper snapshots, eager/tensor_wrapper.h)."""
        node = new_tensor._node
        if node is not None and node.inputs:
            snap = None
            for i, t in enumerate(node.inputs):
                if t is self:
                    if self._node is None and not self.stop_gradient:
                        # grad would land on the hidden snapshot, invisible
                        # to the user (reference dygraph raises the same)
                        raise RuntimeError(
                            "a leaf Tensor that requires grad cannot be "
                            "used in an in-place operation; wrap it in "
                            "no_grad() or use the out-of-place op")
                    if snap is None:
                        snap = Tensor(self._data,
                                      stop_gradient=self.stop_gradient)
                        snap._node = self._node
                        snap._version = self._version
                        old_node = self._node
                        if old_node is not None:
                            for j, o in enumerate(old_node.outputs):
                                if o is self:
                                    old_node.outputs[j] = snap
                    node.inputs[i] = snap
        self._data = new_tensor._data
        self._node = node
        self._version += 1
        if node is not None:
            # rewire node output identity to self so backward reaches us
            outs = node.outputs
            for i, o in enumerate(outs):
                if o is new_tensor:
                    outs[i] = self
            # inplace under grad keeps (or gains) differentiability; under
            # no_grad the op result carries stop_gradient=True, which must
            # NOT freeze a previously-trainable tensor
            self.stop_gradient = new_tensor.stop_gradient
        return self

    def set_value(self, value):
        data = value._data if isinstance(value, Tensor) else jnp.asarray(value, dtype=self.dtype)
        self._data = jnp.broadcast_to(data, tuple(self._data.shape)).astype(self._data.dtype)
        self._version += 1
        return self

    def fill_(self, value):
        self._data = jnp.full_like(self._data, value)
        self._version += 1
        return self

    def zero_(self):
        self._data = jnp.zeros_like(self._data)
        self._version += 1
        return self

    def scale_(self, scale):
        self._data = self._data * scale
        self._version += 1
        return self

    def add_(self, other):
        o = other._data if isinstance(other, Tensor) else other
        self._data = self._data + o
        self._version += 1
        return self

    def subtract_(self, other):
        o = other._data if isinstance(other, Tensor) else other
        self._data = self._data - o
        self._version += 1
        return self

    def multiply_(self, other):
        o = other._data if isinstance(other, Tensor) else other
        self._data = self._data * o
        self._version += 1
        return self

    def copy_(self, other, blocking=True):
        return self.set_value(other)

    # -- operators ----------------------------------------------------------
    def __len__(self):
        if not self._data.shape:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __bool__(self):
        return bool(self._data)

    def __int__(self):
        return int(self._data)

    def __float__(self):
        return float(self._data)

    def __neg__(self):
        return apply_op(lambda x: -x, self)

    def __abs__(self):
        return apply_op(jnp.abs, self)

    def __add__(self, o):
        return _binop(jnp.add, self, o)

    __radd__ = __add__

    def __sub__(self, o):
        return _binop(jnp.subtract, self, o)

    def __rsub__(self, o):
        return _binop(jnp.subtract, o, self)

    def __mul__(self, o):
        return _binop(jnp.multiply, self, o)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return _binop(jnp.divide, self, o)

    def __rtruediv__(self, o):
        return _binop(jnp.divide, o, self)

    def __floordiv__(self, o):
        return _binop(jnp.floor_divide, self, o)

    def __mod__(self, o):
        return _binop(jnp.mod, self, o)

    def __pow__(self, o):
        return _binop(jnp.power, self, o)

    def __rpow__(self, o):
        return _binop(jnp.power, o, self)

    def __matmul__(self, o):
        return _binop(jnp.matmul, self, o)

    def __rmatmul__(self, o):
        return _binop(jnp.matmul, o, self)

    def __eq__(self, o):
        return _binop(jnp.equal, self, o)

    def __ne__(self, o):
        return _binop(jnp.not_equal, self, o)

    def __lt__(self, o):
        return _binop(jnp.less, self, o)

    def __le__(self, o):
        return _binop(jnp.less_equal, self, o)

    def __gt__(self, o):
        return _binop(jnp.greater, self, o)

    def __ge__(self, o):
        return _binop(jnp.greater_equal, self, o)

    def __hash__(self):
        return id(self)

    def __invert__(self):
        return apply_op(jnp.logical_not, self)

    def __getitem__(self, idx):
        idx = _convert_index(idx)
        return apply_op(lambda x: x[idx], self)

    def __setitem__(self, idx, value):
        idx = _convert_index(idx)
        if not self.stop_gradient and ag.is_grad_enabled():
            # record the assignment so backward zeroes grads of overwritten
            # positions (and flows into a differentiable value)
            if isinstance(value, Tensor):
                new = apply_op(lambda x, vv: x.at[idx].set(vv.astype(x.dtype)),
                               self, value)
            else:
                v = value if isinstance(value, numbers.Number) \
                    else jnp.asarray(value).astype(self._data.dtype)
                new = apply_op(lambda x: x.at[idx].set(v), self)
            self._replace(new)
        else:
            v = value._data if isinstance(value, Tensor) else value
            self._data = self._data.at[idx].set(
                jnp.asarray(v).astype(self._data.dtype)
                if not isinstance(v, numbers.Number) else v)
            self._version += 1

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __repr__(self):
        grad_txt = "" if self.stop_gradient else ", stop_gradient=False"
        return (f"Tensor(shape={self.shape}, dtype={_dt.dtype_name(self.dtype)}"
                f"{grad_txt},\n       {np.asarray(self._data)!r})")

    # Rich tensor methods (sum/mean/reshape/...) are attached by
    # paddle_tpu.tensor at import time, mirroring how paddle monkey-patches
    # python/paddle/tensor/* methods onto the C tensor type.


class HookRemoveHelper:
    """Handle returned by register_hook (reference:
    python/paddle/fluid/dygraph/base.py HookRemoveHelper)."""

    _next_id = 0

    def __init__(self, hooks_dict, hook):
        self._hooks = hooks_dict
        self._id = HookRemoveHelper._next_id
        HookRemoveHelper._next_id += 1
        hooks_dict[self._id] = hook

    def remove(self):
        self._hooks.pop(self._id, None)


class Parameter(Tensor):
    """Trainable tensor (reference: paddle/fluid/framework.py Parameter)."""

    __slots__ = ("optimize_attr", "regularizer", "need_clip", "is_distributed",
                 "split_axis")

    def __init__(self, data, name=None, trainable=True):
        super().__init__(data, stop_gradient=not trainable, name=name)
        self.persistable = True
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.is_distributed = False
        self.split_axis = None

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


def _convert_index(idx):
    def conv(i):
        return i._data if isinstance(i, Tensor) else i
    if isinstance(idx, tuple):
        return tuple(conv(i) for i in idx)
    return conv(idx)


def wrap(data, stop_gradient=True):
    if isinstance(data, (tuple, list)):
        return type(data)(wrap(d, stop_gradient) for d in data)
    return Tensor(data, stop_gradient=stop_gradient)


def unwrap(x):
    if isinstance(x, Tensor):
        return x._data
    if isinstance(x, (tuple, list)):
        return type(x)(unwrap(i) for i in x)
    return x


def _binop(fn, a, b):
    return apply_op(fn, *_coerce_pair(a, b))


def _coerce_pair(a, b):
    if not isinstance(a, Tensor):
        a = to_tensor(a, dtype=_promote_scalar_dtype(a, b))
    if not isinstance(b, Tensor):
        b = to_tensor(b, dtype=_promote_scalar_dtype(b, a))
    return a, b


def _promote_scalar_dtype(scalar, tensor):
    """Python scalars adopt the tensor operand's dtype (paddle semantics)."""
    if isinstance(tensor, Tensor):
        td = tensor.dtype
        if isinstance(scalar, bool):
            return _dt.bool_
        if isinstance(scalar, numbers.Integral):
            # int scalar adopts the tensor dtype — except bool, where
            # arithmetic must not collapse to logical ops ((x>0)*3)
            return td if td != _dt.bool_ else _dt.get_default_dtype()
        if isinstance(scalar, numbers.Real) and not _dt.is_floating(td):
            return _dt.get_default_dtype()   # float scalar + int tensor
        return td
    return None


# ---------------------------------------------------------------------------
# Eager per-op executable cache (SURVEY §7 hard part #1; VERDICT r1 item 6).
#
# The reference's whole eager/ C++ fast path exists to make per-op dispatch
# cheap; on TPU the equivalent is: never re-trace or re-compile an op the
# runtime has already seen. apply_op keys a cache on the op's IDENTITY
# (code object + closure cells + static args/kwargs + which args are
# differentiable); the cached entry is ONE jax.jit wrapper, and jit's own
# executable cache then keys on input shapes/dtypes. The backward closure
# returned by jax.vjp is a jax.tree_util.Partial pytree, so it crosses the
# jit boundary and the transposed program is jitted (and cached) the same
# way through _BWD_CALL.
#
# Ops whose identity can't be hashed (arrays captured in closures, unhashable
# kwargs) fall back to the direct re-trace path — correct, just uncached.
# ---------------------------------------------------------------------------

_EAGER_CACHE = {}
_CACHE_STATS = {"hits": 0, "misses": 0, "bypass": 0}
eager_op_cache_enabled = True


def _hashable(x):
    try:
        hash(x)
        return True
    except TypeError:
        return False


def _op_cache_key(fn, args, kwargs, diff_idx):
    """Cache key capturing the op's identity + all static (non-Tensor)
    operands, or None when any part is unhashable."""
    if hasattr(fn, "__code__"):
        try:
            cells = tuple(c.cell_contents for c in (fn.__closure__ or ()))
        except ValueError:          # empty cell
            return None
        defaults = (fn.__defaults__ or ()) + tuple(
            sorted((fn.__kwdefaults__ or {}).items()))
        if not (_hashable(cells) and _hashable(defaults)):
            return None
        ident = (fn.__code__, cells, defaults)
    elif _hashable(fn):
        ident = (fn,)
    else:
        return None
    statics = tuple((i, a) for i, a in enumerate(args)
                    if not isinstance(a, Tensor))
    kw = tuple(sorted(kwargs.items()))
    if not (_hashable(statics) and _hashable(kw)):
        return None
    return (ident, statics, kw, tuple(diff_idx), len(args))


def _build_cached_op(fn, args, kwargs, diff_idx, with_grad):
    """One jit-wrapped runner for this op identity; jit caches executables
    per input shape/dtype from here on."""
    tensor_idx = tuple(i for i, a in enumerate(args) if isinstance(a, Tensor))
    static_vals = {i: a for i, a in enumerate(args)
                   if not isinstance(a, Tensor)}
    diff_pos = tuple(tensor_idx.index(i) for i in diff_idx)

    def assemble(tensor_datas):
        full = [None] * len(args)
        for i, v in zip(tensor_idx, tensor_datas):
            full[i] = v
        for i, v in static_vals.items():
            full[i] = v
        return fn(*full, **kwargs)

    if not with_grad:
        def run(td):
            return assemble(td)
        from ..framework import compile_cache as _cc
        if _cc.active() is not None:
            # persistent tier (content-addressed on the lowering hash):
            # the trace still happens once per process per op — what the
            # disk entry skips is the XLA compile. Grad-path runners are
            # excluded: their vjp-closure outputs don't serialize, so
            # they stay on plain jit (a transparent miss, by contract).
            opname = getattr(fn, "__qualname__", None) \
                or getattr(fn, "__name__", "op")
            return _cc.cached_jit(run, f"op.{opname}", key_mode="lowering")
        return jax.jit(run)

    @jax.jit
    def run(td):
        def diff_call(*diff_vals):
            full_td = list(td)
            for p, v in zip(diff_pos, diff_vals):
                full_td[p] = v
            return assemble(full_td)
        return jax.vjp(diff_call, *[td[p] for p in diff_pos])

    return run


@jax.jit
def _BWD_CALL(vjp_fn, seed):
    return vjp_fn(seed)


def _cached_bwd(vjp_fn):
    return lambda seed: _BWD_CALL(vjp_fn, seed)


def _nan_check_enabled():
    from ..framework.flags import _FLAGS
    return _FLAGS.get("FLAGS_check_nan_inf", False)


def _check_finite(outs, opname):
    """FLAGS_check_nan_inf per-op scan (reference: eager/nan_inf_utils.cc,
    framework/details/nan_inf_utils_detail.cc): raise naming the op the
    moment any eager output contains NaN/Inf. Debug-only path — each check
    syncs the device."""
    out_list = outs if isinstance(outs, tuple) else (outs,)
    for i, o in enumerate(out_list):
        d = o._data if isinstance(o, Tensor) else o
        if isinstance(d, jax.core.Tracer):
            # inside a jit/shard_map trace bool() would concretize; the
            # compiled paths have their own guards (GradScaler found_inf)
            continue
        if hasattr(d, "dtype") and jnp.issubdtype(d.dtype, jnp.floating):
            if bool(jnp.logical_or(jnp.isnan(d).any(), jnp.isinf(d).any())):
                raise RuntimeError(
                    f"FLAGS_check_nan_inf: op '{opname or 'unknown'}' "
                    f"produced NaN/Inf in output {i} (shape {d.shape}, "
                    f"dtype {d.dtype})")
    return outs


def _add_op_context(e, fn, name, args):
    """Reference-style op error context (paddle/fluid/platform/enforce.h
    formats every kernel failure with the op name + inputs): attach the op
    and its eager input signature as an exception note so raw XLA errors
    become attributable."""
    try:
        opname = name or getattr(fn, "__name__", "<lambda>")
        sig = ", ".join(
            f"Tensor{tuple(a.shape)}:{a.dtype}" if isinstance(a, Tensor)
            else type(a).__name__ for a in args)
        note = f"  [operator < {opname} > error] inputs: ({sig})"
        if hasattr(e, "add_note"):
            e.add_note(note)
        else:                       # PEP 678 backport for python < 3.11
            e.__notes__ = getattr(e, "__notes__", []) + [note]
    except Exception:                                        # noqa: BLE001
        pass


def _prof_begin_op(fn, name, args, kwargs):
    """Operator span for one apply_op dispatch: input shapes/dtypes in the
    attrs, and (when with_flops) the callable + abstract avals so
    Profiler.analyze() can re-trace the op and price it on the roofline.
    Only ever called while the tracer is RECORD — the CLOSED-state cost of
    profiling is the single `_TRACER.enabled` check at the apply_op top."""
    shapes, dtypes, tensor_idx, avals, statics = [], [], [], [], []
    for i, a in enumerate(args):
        if isinstance(a, Tensor):
            d = a._data
            shapes.append(tuple(int(s) for s in d.shape))
            dtypes.append(str(d.dtype))
            tensor_idx.append(i)
            avals.append(jax.ShapeDtypeStruct(d.shape, d.dtype))
        else:
            statics.append((i, a))
    attrs = {"input_shapes": shapes, "input_dtypes": dtypes}
    opname = name or getattr(fn, "__qualname__", None) \
        or getattr(fn, "__name__", "op")
    # variant: digest of the op's non-tensor identity (closure cells,
    # defaults, static args, kwargs). Two `split` lambdas share a code
    # object and input shapes but close over different sections — without
    # this, analyze() would price both from one roofline estimate.
    okey = _op_cache_key(fn, args, kwargs, ())
    if okey is not None:
        attrs["variant"] = f"{hash(okey) & 0xffffffff:08x}"
    ref = None
    if _TRACER.with_flops:
        # one ref per (op, shapes, variant) bucket per window — refs pin
        # the callable + its closures, so per-event refs would grow host
        # memory without bound on long always-on profiled runs. Ops with
        # unhashable identity (okey None) dedup on name+shapes alone:
        # their variants alias in analyze(), but memory stays bounded.
        dedup = (opname, tuple(shapes), tuple(dtypes), attrs.get("variant"))
        if _TRACER.ref_once(dedup):
            ref = (fn, tuple(tensor_idx), tuple(avals), tuple(statics),
                   len(args), kwargs)
    return _TRACER.begin(opname, "Operator", attrs, ref)


def apply_op(fn, *args, n_outputs=None, name="", **kwargs):
    """Run `fn` over tensor args, recording a tape Node when grads are needed.

    `fn` operates on raw jax arrays. Non-Tensor args pass through unchanged.
    Returns Tensor or tuple-of-Tensor mirroring fn's output structure.
    """
    rec = _prof_begin_op(fn, name, args, kwargs) if _TRACER.enabled else None
    try:
        if _nan_check_enabled():
            outs = _apply_op_inner(fn, *args, n_outputs=n_outputs, name=name,
                                   **kwargs)
            return _check_finite(outs, name or getattr(fn, "__name__", ""))
        return _apply_op_inner(fn, *args, n_outputs=n_outputs, name=name,
                               **kwargs)
    except Exception as e:
        _add_op_context(e, fn, name, args)
        raise
    finally:
        if rec is not None:
            _TRACER.end(rec)


def _apply_op_inner(fn, *args, n_outputs=None, name="", **kwargs):
    datas = [a._data if isinstance(a, Tensor) else a for a in args]
    diff_idx = [i for i, a in enumerate(args)
                if isinstance(a, Tensor) and not a.stop_gradient
                and _dt.is_inexact(a.dtype)]
    need_grad = ag.is_grad_enabled() and bool(diff_idx)

    # compiled-executable fast path: skip inside an outer trace (XLA already
    # owns that program) and for unhashable op identities
    key = None
    if eager_op_cache_enabled and not any(_is_traced(d) for d in datas):
        key = _op_cache_key(fn, args, kwargs, diff_idx)
    if key is not None:
        runner = _EAGER_CACHE.get((key, need_grad))
        if runner is None:
            _CACHE_STATS["misses"] += 1
            if _TRACER.enabled:
                _TRACER.note("cache", "miss")
            runner = _build_cached_op(fn, args, kwargs, diff_idx, need_grad)
            _EAGER_CACHE[(key, need_grad)] = runner
        else:
            _CACHE_STATS["hits"] += 1
            if _TRACER.enabled:
                _TRACER.note("cache", "hit")
        td = tuple(d for d, a in zip(datas, args) if isinstance(a, Tensor))
        if not need_grad:
            return _wrap_out(runner(td), stop_gradient=True)
        out_data, vjp_fn = runner(td)
        multi = isinstance(out_data, (tuple, list))
        outs = _wrap_out(out_data, stop_gradient=False)
        out_list = list(outs) if multi else [outs]

        def closed_cached(*diff_vals, _datas=tuple(datas),
                          _diff=tuple(diff_idx)):
            full = list(_datas)
            for i, v in zip(_diff, diff_vals):
                full[i] = v
            return fn(*full, **kwargs)

        node = Node(_cached_bwd(vjp_fn), [args[i] for i in diff_idx],
                    out_list, multi, name=name or getattr(fn, "__name__", ""),
                    fwd=closed_cached)
        for o in out_list:
            o._node = node
        return outs
    _CACHE_STATS["bypass"] += 1
    if _TRACER.enabled:
        _TRACER.note("cache", "bypass")

    if not need_grad:
        out = fn(*datas, **kwargs)
        return _wrap_out(out, stop_gradient=True)

    def closed(*diff_args):
        full = list(datas)
        for i, v in zip(diff_idx, diff_args):
            full[i] = v
        return fn(*full, **kwargs)

    diff_vals = tuple(datas[i] for i in diff_idx)
    if any(_is_traced(d) for d in datas):
        # Inside an outer trace the PRIMAL ops recorded here are what the
        # outer jax.grad/vjp differentiates — they must come from a direct
        # fn call so custom_vjp rules survive (an eager jax.vjp here would
        # consume them and hand the outer trace the raw linearized forward:
        # e.g. a psum inside shard_map(check_vma=False) then transposes to
        # psum, inflating cotangents). The tape's own vjp is deferred to
        # backward time; if the tape is never walked (functional training),
        # no extra ops are ever traced.
        out_data = closed(*diff_vals)

        def vjp_fn(*cts, _dv=diff_vals, _closed=closed):
            return jax.vjp(_closed, *_dv)[1](*cts)
    else:
        out_data, vjp_fn = jax.vjp(closed, *diff_vals)
    multi = isinstance(out_data, (tuple, list))
    outs = _wrap_out(out_data, stop_gradient=False)
    out_list = list(outs) if multi else [outs]
    node = Node(vjp_fn, [args[i] for i in diff_idx], out_list, multi,
                name=name or getattr(fn, "__name__", ""), fwd=closed)
    for o in out_list:
        o._node = node
    return outs


def _wrap_out(out, stop_gradient):
    if isinstance(out, (tuple, list)):
        return tuple(Tensor(o, stop_gradient=stop_gradient) for o in out)
    return Tensor(out, stop_gradient=stop_gradient)


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor equivalent."""
    dtype = _dt.canonical(dtype)
    if isinstance(data, Tensor):
        arr = data._data
        if dtype is not None and arr.dtype != dtype:
            arr = arr.astype(dtype)
        return Tensor(arr, stop_gradient=stop_gradient)
    if isinstance(data, (jnp.ndarray, jax.Array)) and not isinstance(data, np.ndarray):
        arr = data
        if dtype is not None and arr.dtype != dtype:
            arr = arr.astype(dtype)
        return Tensor(arr, stop_gradient=stop_gradient)
    np_arr = np.asarray(data)
    if dtype is None:
        if np_arr.dtype == np.float64:
            np_arr = np_arr.astype(np.dtype(_dt.get_default_dtype()) if _dt.get_default_dtype() != _dt.bfloat16 else np.float32)
        elif np_arr.dtype == np.int32:
            pass
        elif np_arr.dtype == np.int64:
            pass
    else:
        if jnp.dtype(dtype) == _dt.bfloat16:
            arr = jnp.asarray(np_arr).astype(_dt.bfloat16)
            return Tensor(arr, stop_gradient=stop_gradient)
        np_arr = np_arr.astype(np.dtype(dtype))
    if place is not None:
        dev = place.jax_device() if hasattr(place, "jax_device") else None
        arr = jax.device_put(np_arr, dev)
    else:
        arr = jnp.asarray(np_arr)
    return Tensor(arr, stop_gradient=stop_gradient)
