"""paddle.reader namespace (reference: python/paddle/reader/decorator.py)."""
from .batch import (  # noqa: F401
    batch, chain, compose, firstn, map_readers, shuffle,
)
