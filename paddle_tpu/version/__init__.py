full_version = "0.1.0"
major = "0"
minor = "1"
patch = "0"
rc = "0"
commit = "unknown"
with_gpu = "OFF"
with_tpu = "ON"


def show():
    print(f"paddle_tpu {full_version} (TPU-native, JAX/XLA backend)")
