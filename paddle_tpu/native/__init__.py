"""ctypes bindings to libpaddle_tpu_native.so — the C++ runtime layer.

The compute path is JAX/XLA; this is the native runtime *around* it, the
role C++ plays in the reference:

  ShmRing   — shared-memory batch transport for the multi-process
              DataLoader (≈ mmap_allocator.cc + blocking_queue.h)
  TCPStore  — multi-host rendezvous/coordination KV service
              (≈ distributed/store/tcp_store.cc)
  HostArena — best-fit auto-growth host allocator for staging buffers
              (≈ allocation/auto_growth_best_fit_allocator.cc)
  stats     — named runtime counters (≈ platform/monitor.h StatRegistry)

Built on first use with the in-tree Makefile (g++); if the toolchain is
unavailable everything degrades: `available()` returns False and the
Python fallbacks stay in place.
"""
import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "build", "libpaddle_tpu_native.so")
_lib = None
_build_lock = threading.Lock()
_build_failed = False


def _sources_newer_than_so():
    if not os.path.exists(_SO):
        return True
    so_mtime = os.path.getmtime(_SO)
    src = os.path.join(_DIR, "src")
    return any(os.path.getmtime(os.path.join(src, f)) > so_mtime
               for f in os.listdir(src))


def _load():
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _build_lock:
        if _lib is not None or _build_failed:
            return _lib
        try:
            if _sources_newer_than_so():
                subprocess.run(["make", "-s", "-C", _DIR], check=True,
                               capture_output=True, timeout=120)
            lib = ctypes.CDLL(_SO)
        except (OSError, subprocess.SubprocessError):
            _build_failed = True
            return None
        _declare(lib)
        _lib = lib
    return _lib


def _declare(lib):
    P, U64, I64, I32 = (ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int64,
                        ctypes.c_int)
    S = ctypes.c_char_p
    sigs = {
        "ptn_ring_create": (P, [S, U64]),
        "ptn_ring_attach": (P, [S]),
        "ptn_ring_put": (I32, [P, ctypes.c_char_p, U64, I32]),
        "ptn_ring_get": (I32, [P, ctypes.POINTER(P), ctypes.POINTER(U64), I32]),
        "ptn_ring_close": (None, [P]),
        "ptn_ring_release": (None, [P]),
        "ptn_buf_free": (None, [P]),
        "ptn_store_server_start": (P, [I32]),
        "ptn_store_server_port": (I32, [P]),
        "ptn_store_server_stop": (None, [P]),
        "ptn_store_client_connect": (P, [S, I32, I32]),
        "ptn_store_client_close": (None, [P]),
        "ptn_store_set": (I32, [P, S, ctypes.c_char_p, U64]),
        "ptn_store_get": (I32, [P, S, ctypes.POINTER(P), ctypes.POINTER(U64)]),
        "ptn_store_wait": (I32, [P, S, I64, ctypes.POINTER(P),
                                 ctypes.POINTER(U64)]),
        "ptn_store_add": (I32, [P, S, I64, ctypes.POINTER(I64)]),
        "ptn_store_delete": (I32, [P, S]),
        "ptn_arena_create": (P, [U64]),
        "ptn_arena_alloc": (P, [P, U64]),
        "ptn_arena_free": (I32, [P, P]),
        "ptn_arena_stats": (None, [P, ctypes.POINTER(U64), ctypes.POINTER(U64),
                                   ctypes.POINTER(U64)]),
        "ptn_arena_destroy": (None, [P]),
        "ptn_pstable_create": (P, [I32, S, ctypes.c_float, ctypes.c_float,
                                   U64]),
        "ptn_pstable_pull": (None, [P, ctypes.POINTER(I64), I64,
                                    ctypes.POINTER(ctypes.c_float)]),
        "ptn_pstable_push": (None, [P, ctypes.POINTER(I64), I64,
                                    ctypes.POINTER(ctypes.c_float)]),
        "ptn_pstable_pull_state": (None, [P, ctypes.POINTER(I64), I64,
                                          ctypes.POINTER(ctypes.c_float),
                                          ctypes.POINTER(ctypes.c_float)]),
        "ptn_pstable_assign": (None, [P, ctypes.POINTER(I64), I64,
                                      ctypes.POINTER(ctypes.c_float),
                                      ctypes.POINTER(ctypes.c_float)]),
        "ptn_pstable_erase": (None, [P, ctypes.POINTER(I64), I64]),
        "ptn_pstable_size": (I64, [P]),
        "ptn_pstable_save": (I32, [P, S]),
        "ptn_pstable_load": (I32, [P, S]),
        "ptn_pstable_destroy": (None, [P]),
        "ptn_stat_add": (I64, [S, I64]),
        "ptn_stat_get": (I64, [S]),
        "ptn_stat_peak": (I64, [S]),
        "ptn_stat_reset": (None, [S]),
    }
    for name, (res, args) in sigs.items():
        fn = getattr(lib, name)
        fn.restype = res
        fn.argtypes = args


def available():
    return _load() is not None


def _take_buf(pp, ln):
    data = ctypes.string_at(pp.value, ln.value)
    _lib.ptn_buf_free(pp.value)
    return data


class ShmRing:
    """Cross-process blocking byte-record queue in shared memory."""

    def __init__(self, name, capacity=64 << 20, create=True):
        lib = _load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self.name = name
        self._create = create
        nm = name.encode()
        self._h = (lib.ptn_ring_create(nm, capacity) if create
                   else lib.ptn_ring_attach(nm))
        if not self._h:
            raise RuntimeError(f"ShmRing {'create' if create else 'attach'} "
                               f"failed: {name}")

    def put(self, data: bytes, timeout_ms=-1):
        rc = _lib.ptn_ring_put(self._h, data, len(data), timeout_ms)
        if rc == -2:
            raise EOFError("ring closed")
        if rc == -1:
            raise TimeoutError("ring put timeout")
        if rc == -3:
            raise ValueError(f"record of {len(data)} bytes larger than ring "
                             f"capacity")
        if rc != 0:
            raise RuntimeError(f"ring put failed ({rc})")

    def get(self, timeout_ms=-1):
        """Returns bytes, or None when the ring is closed and drained."""
        pp = ctypes.c_void_p()
        ln = ctypes.c_uint64()
        rc = _lib.ptn_ring_get(self._h, ctypes.byref(pp), ctypes.byref(ln),
                               timeout_ms)
        if rc == -2:
            return None
        if rc == -1:
            raise TimeoutError("ring get timeout")
        if rc != 0:
            raise RuntimeError(f"ring get failed ({rc})")
        return _take_buf(pp, ln)

    def close(self):
        if self._h:
            _lib.ptn_ring_close(self._h)

    def release(self):
        if self._h:
            _lib.ptn_ring_release(self._h)
            self._h = None


class TCPStoreServer:
    def __init__(self, port=0):
        lib = _load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._h = lib.ptn_store_server_start(port)
        if not self._h:
            raise RuntimeError(f"TCPStore server failed to bind port {port}")
        self.port = lib.ptn_store_server_port(self._h)

    def stop(self):
        if self._h:
            _lib.ptn_store_server_stop(self._h)
            self._h = None


class TCPStoreClient:
    def __init__(self, host="127.0.0.1", port=0, timeout_ms=30000):
        lib = _load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._h = lib.ptn_store_client_connect(host.encode(), port, timeout_ms)
        if not self._h:
            raise RuntimeError(f"TCPStore connect failed: {host}:{port}")

    def set(self, key, value: bytes):
        if _lib.ptn_store_set(self._h, key.encode(), value, len(value)) != 0:
            raise RuntimeError(f"store set failed: {key}")

    def get(self, key):
        """Non-blocking; returns None if absent."""
        pp = ctypes.c_void_p()
        ln = ctypes.c_uint64()
        if _lib.ptn_store_get(self._h, key.encode(), ctypes.byref(pp),
                              ctypes.byref(ln)) != 0:
            return None
        return _take_buf(pp, ln)

    def wait(self, key, timeout_ms=-1):
        """Blocks until the key exists (or timeout_ms elapses), returns its
        value."""
        pp = ctypes.c_void_p()
        ln = ctypes.c_uint64()
        rc = _lib.ptn_store_wait(self._h, key.encode(), timeout_ms,
                                 ctypes.byref(pp), ctypes.byref(ln))
        if rc == -2:
            raise TimeoutError(f"store wait timed out: {key}")
        if rc != 0:
            raise RuntimeError(f"store wait failed: {key}")
        return _take_buf(pp, ln)

    def add(self, key, delta=1):
        out = ctypes.c_int64()
        if _lib.ptn_store_add(self._h, key.encode(), delta,
                              ctypes.byref(out)) != 0:
            raise RuntimeError(f"store add failed: {key}")
        return out.value

    def delete(self, key):
        _lib.ptn_store_delete(self._h, key.encode())

    def close(self):
        if self._h:
            _lib.ptn_store_client_close(self._h)
            self._h = None


class HostArena:
    """Best-fit auto-growth host allocator; returns memoryviews over the
    arena's mmap'd chunks."""

    def __init__(self, chunk_bytes=64 << 20):
        lib = _load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._h = lib.ptn_arena_create(chunk_bytes)
        self._live = {}

    def alloc(self, size):
        p = _lib.ptn_arena_alloc(self._h, size)
        if not p:
            raise MemoryError(f"arena alloc({size}) failed")
        buf = (ctypes.c_ubyte * size).from_address(p)
        mv = memoryview(buf).cast("B")
        self._live[id(mv)] = (p, mv)
        return mv

    def free(self, mv):
        entry = self._live.pop(id(mv), None)
        if entry is None:
            raise ValueError("unknown arena buffer")
        mv.release()
        if _lib.ptn_arena_free(self._h, entry[0]) != 0:
            raise RuntimeError("double free")

    def stats(self):
        a = ctypes.c_uint64()
        r = ctypes.c_uint64()
        p = ctypes.c_uint64()
        _lib.ptn_arena_stats(self._h, ctypes.byref(a), ctypes.byref(r),
                             ctypes.byref(p))
        return {"allocated": a.value, "reserved": r.value, "peak": p.value}

    def destroy(self):
        if self._h:
            for ptr, mv in self._live.values():
                mv.release()
            self._live.clear()
            _lib.ptn_arena_destroy(self._h)
            self._h = None


class SparseTable:
    """Sharded feature-id -> embedding-row store with server-side sparse
    optimizer rules (sgd/adagrad/adam). The C++ half of the parameter
    server; see paddle_tpu.distributed.ps."""

    def __init__(self, dim, rule="adagrad", lr=0.05, init_range=0.01,
                 seed=0):
        import numpy as _np
        lib = _load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self.dim = int(dim)
        self.rule = rule
        self.lr = float(lr)
        self._np = _np
        self._h = lib.ptn_pstable_create(self.dim, rule.encode(),
                                         float(lr), float(init_range),
                                         int(seed))

    def _keys_ptr(self, keys):
        arr = self._np.ascontiguousarray(keys, dtype=self._np.int64)
        return arr, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))

    def pull(self, keys):
        """keys: int64 array (n,) -> float32 (n, dim); missing rows are
        created with uniform init."""
        arr, kp = self._keys_ptr(keys)
        out = self._np.empty((arr.size, self.dim), dtype=self._np.float32)
        _lib.ptn_pstable_pull(
            self._h, kp, arr.size,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        return out

    def push(self, keys, grads):
        arr, kp = self._keys_ptr(keys)
        g = self._np.ascontiguousarray(grads, dtype=self._np.float32)
        if g.shape != (arr.size, self.dim):
            raise ValueError(f"grads shape {g.shape} != ({arr.size}, "
                             f"{self.dim})")
        _lib.ptn_pstable_push(
            self._h, kp, arr.size,
            g.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))

    @property
    def slot(self):
        """Optimizer-state floats per row (0 sgd, dim adagrad, 2*dim+1
        adam) — mirrors the Table layout in ps_table.cc."""
        return {"sgd": 0, "adagrad": self.dim, "adam": 2 * self.dim + 1}[
            self.rule]

    def pull_with_state(self, keys):
        """(values (n, dim), state (n, slot)) — rows + optimizer slots for
        the device-resident cache (reference ps_gpu_wrapper BuildPull)."""
        arr, kp = self._keys_ptr(keys)
        out = self._np.empty((arr.size, self.dim), dtype=self._np.float32)
        st = self._np.empty((arr.size, max(self.slot, 1)),
                            dtype=self._np.float32)
        _lib.ptn_pstable_pull_state(
            self._h, kp, arr.size,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            st.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        return out, st[:, :self.slot]

    def assign(self, keys, values, state=None):
        """Directly set row values (+ optimizer state): the end-of-pass
        write-back of device-updated rows (reference ps_gpu_wrapper
        EndPass)."""
        arr, kp = self._keys_ptr(keys)
        v = self._np.ascontiguousarray(values, dtype=self._np.float32)
        if v.shape != (arr.size, self.dim):
            raise ValueError(f"values shape {v.shape} != ({arr.size}, "
                             f"{self.dim})")
        sp = None
        if state is not None and self.slot:
            s = self._np.ascontiguousarray(state, dtype=self._np.float32)
            if s.shape != (arr.size, self.slot):
                raise ValueError(f"state shape {s.shape} != ({arr.size}, "
                                 f"{self.slot})")
            sp = s.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
        _lib.ptn_pstable_assign(
            self._h, kp, arr.size,
            v.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), sp)

    def erase(self, keys):
        """Drop rows entirely (SSD-tier hot-cache eviction): erased keys
        re-init deterministically on next pull unless reloaded first."""
        arr, kp = self._keys_ptr(keys)
        _lib.ptn_pstable_erase(self._h, kp, arr.size)

    def __len__(self):
        return int(_lib.ptn_pstable_size(self._h))

    def save(self, path):
        if _lib.ptn_pstable_save(self._h, path.encode()) != 0:
            raise IOError(f"pstable save failed: {path}")

    def load(self, path):
        rc = _lib.ptn_pstable_load(self._h, path.encode())
        if rc != 0:
            raise IOError(f"pstable load failed ({rc}): {path}")

    def destroy(self):
        if self._h:
            _lib.ptn_pstable_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.destroy()
        except Exception:
            pass


def stat_add(name, delta=1):
    lib = _load()
    return lib.ptn_stat_add(name.encode(), delta) if lib else 0


def stat_get(name):
    lib = _load()
    return lib.ptn_stat_get(name.encode()) if lib else 0


def stat_peak(name):
    lib = _load()
    return lib.ptn_stat_peak(name.encode()) if lib else 0


def stat_reset(name):
    lib = _load()
    if lib:
        lib.ptn_stat_reset(name.encode())
