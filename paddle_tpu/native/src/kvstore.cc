// TCP key-value coordination store: the multi-host rendezvous service.
// Native equivalent of the reference's TCPStore
// (paddle/fluid/distributed/store/tcp_store.cc, tcp_utils.cc): the rank-0
// process runs the server; every rank connects a client and uses
// set/get/wait/add to bootstrap process groups (the role ncclUniqueId
// broadcast + barrier play in the reference's init).
//
// Protocol (length-prefixed, little-endian):
//   request:  u8 cmd | u32 klen | key | u64 vlen | value
//   response: u8 ok  | u64 vlen | value     (ok: 1=found 0=miss/err 2=timeout)
// Commands: 1=SET 2=GET(nonblock) 3=WAIT(get, block until set; optional i64
//           timeout_ms payload) 4=ADD(i64) 5=DELETE
#include <arpa/inet.h>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

// the port is reachable by anything on the network: cap lengths so a stray
// scanner's garbage can't drive a huge allocation (uncaught bad_alloc in a
// worker thread would terminate the whole trainer)
constexpr uint32_t kMaxKeyLen = 1u << 16;
constexpr uint64_t kMaxValLen = 1ull << 30;

bool read_full(int fd, void* buf, size_t n) {
  auto* p = (uint8_t*)buf;
  while (n) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= (size_t)r;
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  auto* p = (const uint8_t*)buf;
  while (n) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= (size_t)r;
  }
  return true;
}

struct Server {
  int listen_fd = -1;
  int port = 0;
  std::map<std::string, std::string> kv;
  std::mutex mu;
  std::condition_variable cv;
  std::vector<std::thread> workers;
  std::vector<int> client_fds;
  std::thread acceptor;
  bool stopping = false;

  void handle(int fd, size_t slot) {
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    for (;;) {
      uint8_t cmd;
      uint32_t klen;
      uint64_t vlen;
      if (!read_full(fd, &cmd, 1) || !read_full(fd, &klen, 4)) break;
      if (klen > kMaxKeyLen) break;  // malformed/hostile: drop connection
      std::string key(klen, '\0');
      if (klen && !read_full(fd, &key[0], klen)) break;
      if (!read_full(fd, &vlen, 8)) break;
      if (vlen > kMaxValLen) break;
      std::string val(vlen, '\0');
      if (vlen && !read_full(fd, &val[0], vlen)) break;

      uint8_t ok = 1;
      std::string out;
      switch (cmd) {
        case 1: {  // SET
          std::lock_guard<std::mutex> g(mu);
          kv[key] = val;
          cv.notify_all();
          break;
        }
        case 2: {  // GET
          std::lock_guard<std::mutex> g(mu);
          auto it = kv.find(key);
          if (it == kv.end()) ok = 0;
          else out = it->second;
          break;
        }
        case 3: {  // WAIT (blocking get, optional i64 timeout_ms payload)
          int64_t tmo = -1;
          if (val.size() == 8) memcpy(&tmo, val.data(), 8);
          std::unique_lock<std::mutex> g(mu);
          auto pred = [&] { return stopping || kv.count(key); };
          bool signalled = true;
          if (tmo < 0) cv.wait(g, pred);
          else signalled = cv.wait_for(g, std::chrono::milliseconds(tmo), pred);
          if (!signalled) ok = 2;           // timeout
          else if (!kv.count(key)) ok = 0;  // stopping
          else out = kv[key];
          break;
        }
        case 4: {  // ADD
          int64_t delta = 0;
          if (val.size() == 8) memcpy(&delta, val.data(), 8);
          std::lock_guard<std::mutex> g(mu);
          int64_t cur = 0;
          auto it = kv.find(key);
          if (it != kv.end() && it->second.size() == 8)
            memcpy(&cur, it->second.data(), 8);
          cur += delta;
          std::string v(8, '\0');
          memcpy(&v[0], &cur, 8);
          kv[key] = v;
          out = v;
          cv.notify_all();
          break;
        }
        case 5: {  // DELETE
          std::lock_guard<std::mutex> g(mu);
          kv.erase(key);
          break;
        }
        default:
          ok = 0;
      }
      uint64_t olen = out.size();
      if (!write_full(fd, &ok, 1) || !write_full(fd, &olen, 8)) break;
      if (olen && !write_full(fd, out.data(), olen)) break;
    }
    // deregister before close so stop() never shutdown()s a recycled fd
    {
      std::lock_guard<std::mutex> g(mu);
      client_fds[slot] = -1;
    }
    ::close(fd);
  }
};

struct Client {
  int fd = -1;
  std::mutex mu;  // one request in flight per client
};

}  // namespace

extern "C" {

void* ptn_store_server_start(int port) {
  auto* s = new Server();
  s->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) {
    delete s;
    return nullptr;
  }
  int one = 1;
  setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons((uint16_t)port);
  if (bind(s->listen_fd, (sockaddr*)&addr, sizeof(addr)) != 0 ||
      listen(s->listen_fd, 128) != 0) {
    ::close(s->listen_fd);
    delete s;
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(s->listen_fd, (sockaddr*)&addr, &alen);
  s->port = ntohs(addr.sin_port);

  s->acceptor = std::thread([s] {
    for (;;) {
      int fd = ::accept(s->listen_fd, nullptr, nullptr);
      if (fd < 0) break;  // listen_fd closed on stop
      std::lock_guard<std::mutex> g(s->mu);
      if (s->stopping) {
        ::close(fd);
        break;
      }
      s->client_fds.push_back(fd);
      size_t slot = s->client_fds.size() - 1;
      s->workers.emplace_back([s, fd, slot] { s->handle(fd, slot); });
    }
  });
  return s;
}

int ptn_store_server_port(void* sp) { return ((Server*)sp)->port; }

void ptn_store_server_stop(void* sp) {
  auto* s = (Server*)sp;
  {
    std::lock_guard<std::mutex> g(s->mu);
    s->stopping = true;
    s->cv.notify_all();
  }
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  if (s->acceptor.joinable()) s->acceptor.join();
  // acceptor is gone: workers/client_fds can no longer grow. Kick every
  // handler off its socket, then join so no thread outlives the Server.
  {
    std::lock_guard<std::mutex> g(s->mu);
    for (int fd : s->client_fds)
      if (fd >= 0) ::shutdown(fd, SHUT_RDWR);
  }
  for (auto& t : s->workers)
    if (t.joinable()) t.join();
  delete s;
}

void* ptn_store_client_connect(const char* host, int port, int timeout_ms) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    ::close(fd);
    return nullptr;
  }
  // simple retry loop: the server rank may come up later
  // (timeout_ms < 0 = retry forever)
  int waited = 0;
  while (connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
    ::close(fd);
    if (timeout_ms >= 0 && waited >= timeout_ms) return nullptr;
    usleep(100 * 1000);
    waited += 100;
    fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return nullptr;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  auto* c = new Client();
  c->fd = fd;
  return c;
}

// returns 0 ok / -1 not-found-or-error / -2 timeout;
// GET/WAIT/ADD fill *out (malloc'd)
static int request(Client* c, uint8_t cmd, const char* key, const void* val,
                   uint64_t vlen, void** out, uint64_t* out_len) {
  std::lock_guard<std::mutex> g(c->mu);
  uint32_t klen = (uint32_t)strlen(key);
  if (!write_full(c->fd, &cmd, 1) || !write_full(c->fd, &klen, 4) ||
      !write_full(c->fd, key, klen) || !write_full(c->fd, &vlen, 8))
    return -1;
  if (vlen && !write_full(c->fd, val, vlen)) return -1;
  uint8_t ok;
  uint64_t olen;
  if (!read_full(c->fd, &ok, 1) || !read_full(c->fd, &olen, 8)) return -1;
  if (olen > kMaxValLen) return -1;
  std::string o(olen, '\0');
  if (olen && !read_full(c->fd, &o[0], olen)) return -1;
  if (ok == 2) return -2;
  if (!ok) return -1;
  if (out) {
    *out = malloc(olen ? olen : 1);
    memcpy(*out, o.data(), olen);
    *out_len = olen;
  }
  return 0;
}

int ptn_store_set(void* cp, const char* key, const void* val, uint64_t len) {
  return request((Client*)cp, 1, key, val, len, nullptr, nullptr);
}

int ptn_store_get(void* cp, const char* key, void** out, uint64_t* len) {
  return request((Client*)cp, 2, key, nullptr, 0, out, len);
}

int ptn_store_wait(void* cp, const char* key, int64_t timeout_ms, void** out,
                   uint64_t* len) {
  if (timeout_ms < 0)
    return request((Client*)cp, 3, key, nullptr, 0, out, len);
  return request((Client*)cp, 3, key, &timeout_ms, 8, out, len);
}

int ptn_store_add(void* cp, const char* key, int64_t delta, int64_t* result) {
  void* out = nullptr;
  uint64_t olen = 0;
  int rc = request((Client*)cp, 4, key, &delta, 8, &out, &olen);
  if (rc == 0 && olen == 8) memcpy(result, out, 8);
  else rc = -1;
  free(out);
  return rc;
}

int ptn_store_delete(void* cp, const char* key) {
  return request((Client*)cp, 5, key, nullptr, 0, nullptr, nullptr);
}

void ptn_store_client_close(void* cp) {
  auto* c = (Client*)cp;
  ::close(c->fd);
  delete c;
}

}  // extern "C"
