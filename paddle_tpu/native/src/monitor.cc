// Named runtime counters with peak tracking.
// Native equivalent of the reference's StatRegistry / STAT_ADD monitors
// (paddle/fluid/platform/monitor.h:80,133) and the memory peak trackers
// (paddle/fluid/memory/stats.h).
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>

namespace {
struct Stat {
  int64_t value = 0;
  int64_t peak = 0;
};
std::mutex g_mu;
std::map<std::string, Stat> g_stats;
}  // namespace

extern "C" {

int64_t ptn_stat_add(const char* name, int64_t delta) {
  std::lock_guard<std::mutex> g(g_mu);
  Stat& s = g_stats[name];
  s.value += delta;
  if (s.value > s.peak) s.peak = s.value;
  return s.value;
}

int64_t ptn_stat_get(const char* name) {
  std::lock_guard<std::mutex> g(g_mu);
  auto it = g_stats.find(name);
  return it == g_stats.end() ? 0 : it->second.value;
}

int64_t ptn_stat_peak(const char* name) {
  std::lock_guard<std::mutex> g(g_mu);
  auto it = g_stats.find(name);
  return it == g_stats.end() ? 0 : it->second.peak;
}

void ptn_stat_reset(const char* name) {
  std::lock_guard<std::mutex> g(g_mu);
  g_stats.erase(name);
}

}  // extern "C"
