// Shared-memory ring buffer: variable-size record MPMC queue across
// processes. Native transport for the multi-process DataLoader — the
// TPU-native equivalent of the reference's shared-memory tensor plumbing
// (paddle/fluid/memory/allocation/mmap_allocator.cc) combined with its
// blocking queue (paddle/fluid/framework/blocking_queue.h): worker
// processes pickle batches into the ring; the trainer process drains it
// without a Python-level pipe round trip.
//
// Layout in the shm segment:
//   [RingHeader][data bytes ...]
// Records are 8-byte aligned: u64 len | payload | pad. A len of SKIP_MARK
// means "wrap to offset 0". head/tail are monotonic byte offsets.
#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <fcntl.h>
#include <pthread.h>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t SKIP_MARK = ~0ull;

struct RingHeader {
  uint64_t magic;
  uint64_t capacity;   // data area size in bytes
  uint64_t head;       // monotonic write offset
  uint64_t tail;       // monotonic read offset
  uint32_t closed;
  uint32_t _pad;
  pthread_mutex_t mu;
  pthread_cond_t not_full;
  pthread_cond_t not_empty;
};

constexpr uint64_t MAGIC = 0x70746e5f72696e67ull;  // "ptn_ring"

struct Ring {
  RingHeader* h;
  uint8_t* data;
  uint64_t map_len;
  std::string name;
  bool owner;
};

uint64_t align8(uint64_t n) { return (n + 7) & ~7ull; }

void abs_deadline(timespec* ts, int timeout_ms) {
  clock_gettime(CLOCK_REALTIME, ts);
  ts->tv_sec += timeout_ms / 1000;
  ts->tv_nsec += (timeout_ms % 1000) * 1000000L;
  if (ts->tv_nsec >= 1000000000L) {
    ts->tv_sec += 1;
    ts->tv_nsec -= 1000000000L;
  }
}

int lock_robust(pthread_mutex_t* mu) {
  int rc = pthread_mutex_lock(mu);
  if (rc == EOWNERDEAD) {           // a worker died holding the lock
    pthread_mutex_consistent(mu);
    rc = 0;
  }
  return rc;
}

}  // namespace

extern "C" {

void* ptn_ring_create(const char* name, uint64_t capacity) {
  shm_unlink(name);  // stale segment from a crashed run
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  uint64_t total = sizeof(RingHeader) + capacity;
  if (ftruncate(fd, (off_t)total) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  auto* h = (RingHeader*)mem;
  memset(h, 0, sizeof(RingHeader));
  h->capacity = capacity;

  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&ma, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mu, &ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&h->not_full, &ca);
  pthread_cond_init(&h->not_empty, &ca);
  h->magic = MAGIC;

  auto* r = new Ring{h, (uint8_t*)mem + sizeof(RingHeader), total, name, true};
  return r;
}

void* ptn_ring_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE,
                   MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  auto* h = (RingHeader*)mem;
  if (h->magic != MAGIC) {
    munmap(mem, (size_t)st.st_size);
    return nullptr;
  }
  auto* r = new Ring{h, (uint8_t*)mem + sizeof(RingHeader),
                     (uint64_t)st.st_size, name, false};
  return r;
}

// 0 ok, -1 timeout, -2 closed, -3 too large, -4 wait/lock failure
int ptn_ring_put(void* rp, const void* buf, uint64_t len, int timeout_ms) {
  auto* r = (Ring*)rp;
  RingHeader* h = r->h;
  uint64_t need = 8 + align8(len);
  if (need > h->capacity) return -3;

  timespec ts;
  if (timeout_ms >= 0) abs_deadline(&ts, timeout_ms);
  if (lock_robust(&h->mu) != 0) return -4;
  for (;;) {
    if (h->closed) {
      pthread_mutex_unlock(&h->mu);
      return -2;
    }
    // empty ring: rewind offsets so a wrap never straddles the boundary
    // with no reader able to free space behind it (deadlock otherwise when
    // to_end + need > capacity)
    if (h->head == h->tail && h->head % h->capacity != 0) {
      h->head = h->tail = 0;
    }
    uint64_t used = h->head - h->tail;
    uint64_t off = h->head % h->capacity;
    uint64_t to_end = h->capacity - off;
    // if the record would wrap, a skip marker consumes `to_end` bytes
    uint64_t eff = (to_end >= need) ? need : to_end + need;
    if (h->capacity - used >= eff) {
      if (to_end < need) {
        if (to_end >= 8) memcpy(r->data + off, &SKIP_MARK, 8);
        h->head += to_end;
        off = 0;
      }
      memcpy(r->data + off, &len, 8);
      memcpy(r->data + off + 8, buf, len);
      h->head += need;
      pthread_cond_signal(&h->not_empty);
      pthread_mutex_unlock(&h->mu);
      return 0;
    }
    int rc = (timeout_ms < 0)
                 ? pthread_cond_wait(&h->not_full, &h->mu)
                 : pthread_cond_timedwait(&h->not_full, &h->mu, &ts);
    if (rc == EOWNERDEAD) {
      // a peer died holding the lock while we were waiting: the implicit
      // re-lock inside cond_wait reported it — recover the mutex or every
      // later lock fails ENOTRECOVERABLE
      pthread_mutex_consistent(&h->mu);
    } else if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return -1;
    } else if (rc != 0) {
      pthread_mutex_unlock(&h->mu);
      return -4;  // wait machinery failed — distinct from -3 (too large)
    }
  }
}

// 0 ok (malloc'd copy in *out, free with ptn_buf_free), -1 timeout,
// -2 closed-and-drained, -4 wait failure
int ptn_ring_get(void* rp, void** out, uint64_t* out_len, int timeout_ms) {
  auto* r = (Ring*)rp;
  RingHeader* h = r->h;
  timespec ts;
  if (timeout_ms >= 0) abs_deadline(&ts, timeout_ms);
  if (lock_robust(&h->mu) != 0) return -4;
  for (;;) {
    while (h->head != h->tail) {
      uint64_t off = h->tail % h->capacity;
      uint64_t len;
      // wrap marker can be implicit (less than 8 bytes left) or explicit
      if (h->capacity - off < 8) {
        h->tail += h->capacity - off;
        continue;
      }
      memcpy(&len, r->data + off, 8);
      if (len == SKIP_MARK) {
        h->tail += h->capacity - off;
        continue;
      }
      void* copy = malloc(len ? len : 1);
      memcpy(copy, r->data + off + 8, len);
      h->tail += 8 + align8(len);
      pthread_cond_signal(&h->not_full);
      pthread_mutex_unlock(&h->mu);
      *out = copy;
      *out_len = len;
      return 0;
    }
    if (h->closed) {
      pthread_mutex_unlock(&h->mu);
      return -2;
    }
    int rc = (timeout_ms < 0)
                 ? pthread_cond_wait(&h->not_empty, &h->mu)
                 : pthread_cond_timedwait(&h->not_empty, &h->mu, &ts);
    if (rc == EOWNERDEAD) {
      pthread_mutex_consistent(&h->mu);
    } else if (rc == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return -1;
    } else if (rc != 0) {
      pthread_mutex_unlock(&h->mu);
      return -4;
    }
  }
}

void ptn_ring_close(void* rp) {
  auto* r = (Ring*)rp;
  if (lock_robust(&r->h->mu) == 0) {
    r->h->closed = 1;
    pthread_cond_broadcast(&r->h->not_empty);
    pthread_cond_broadcast(&r->h->not_full);
    pthread_mutex_unlock(&r->h->mu);
  }
}

void ptn_ring_release(void* rp) {
  auto* r = (Ring*)rp;
  bool owner = r->owner;
  std::string name = r->name;
  munmap((void*)((uint8_t*)r->h), r->map_len);
  if (owner) shm_unlink(name.c_str());
  delete r;
}

void ptn_buf_free(void* p) { free(p); }

}  // extern "C"
