// Auto-growth best-fit host arena allocator.
// Native equivalent of the reference's default GPU allocator strategy
// (paddle/fluid/memory/allocation/auto_growth_best_fit_allocator.cc): a
// free-list keyed by size over mmap'd chunks, with split-on-alloc and
// neighbor coalescing on free. On TPU the device side is owned by
// PjRt/XLA; this arena serves the HOST staging path (DataLoader batch
// assembly, checkpoint IO buffers) where malloc churn on multi-MB blocks
// costs real throughput.
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <sys/mman.h>

namespace {

constexpr uint64_t ALIGN = 64;

struct Block {
  uint64_t size;
  bool free;
  uint64_t chunk_id;  // blocks coalesce only within their chunk
};

struct Arena {
  std::mutex mu;
  uint64_t chunk_bytes;
  uint64_t next_chunk = 0;
  std::map<uint8_t*, Block> blocks;                 // by address
  std::multimap<uint64_t, uint8_t*> free_by_size;   // size -> address
  std::map<uint8_t*, uint64_t> chunks;              // base -> size
  uint64_t allocated = 0;   // bytes handed out
  uint64_t reserved = 0;    // bytes mmap'd
  uint64_t peak = 0;

  void erase_free_entry(uint8_t* p, uint64_t size) {
    auto range = free_by_size.equal_range(size);
    for (auto it = range.first; it != range.second; ++it) {
      if (it->second == p) {
        free_by_size.erase(it);
        return;
      }
    }
  }
};

uint64_t align_up(uint64_t n, uint64_t a) { return (n + a - 1) & ~(a - 1); }

}  // namespace

extern "C" {

void* ptn_arena_create(uint64_t chunk_bytes) {
  auto* a = new Arena();
  a->chunk_bytes = chunk_bytes ? chunk_bytes : (64ull << 20);
  return a;
}

void* ptn_arena_alloc(void* ap, uint64_t size) {
  auto* a = (Arena*)ap;
  size = align_up(size ? size : 1, ALIGN);
  std::lock_guard<std::mutex> g(a->mu);

  auto it = a->free_by_size.lower_bound(size);  // best fit
  if (it == a->free_by_size.end()) {
    // round-up division: chunk_bytes need not be a power of two
    uint64_t chunk = ((size + a->chunk_bytes - 1) / a->chunk_bytes)
                     * a->chunk_bytes;
    void* mem = mmap(nullptr, chunk, PROT_READ | PROT_WRITE,
                     MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
    if (mem == MAP_FAILED) return nullptr;
    auto* base = (uint8_t*)mem;
    a->chunks[base] = chunk;
    a->reserved += chunk;
    a->blocks[base] = {chunk, true, a->next_chunk++};
    a->free_by_size.emplace(chunk, base);
    it = a->free_by_size.lower_bound(size);
  }

  uint8_t* p = it->second;
  Block& b = a->blocks[p];
  a->free_by_size.erase(it);
  if (b.size >= size + ALIGN) {  // split the tail back onto the free list
    uint64_t rest = b.size - size;
    a->blocks[p + size] = {rest, true, b.chunk_id};
    a->free_by_size.emplace(rest, p + size);
    b.size = size;
  }
  b.free = false;
  a->allocated += b.size;
  if (a->allocated > a->peak) a->peak = a->allocated;
  return p;
}

int ptn_arena_free(void* ap, void* ptr) {
  auto* a = (Arena*)ap;
  std::lock_guard<std::mutex> g(a->mu);
  auto it = a->blocks.find((uint8_t*)ptr);
  if (it == a->blocks.end() || it->second.free) return -1;
  it->second.free = true;
  a->allocated -= it->second.size;

  // coalesce with next
  auto next = std::next(it);
  if (next != a->blocks.end() && next->second.free &&
      next->second.chunk_id == it->second.chunk_id &&
      it->first + it->second.size == next->first) {
    a->erase_free_entry(next->first, next->second.size);
    it->second.size += next->second.size;
    a->blocks.erase(next);
  }
  // coalesce with prev
  if (it != a->blocks.begin()) {
    auto prev = std::prev(it);
    if (prev->second.free && prev->second.chunk_id == it->second.chunk_id &&
        prev->first + prev->second.size == it->first) {
      a->erase_free_entry(prev->first, prev->second.size);
      prev->second.size += it->second.size;
      a->blocks.erase(it);
      it = prev;
    }
  }
  a->free_by_size.emplace(it->second.size, it->first);
  return 0;
}

void ptn_arena_stats(void* ap, uint64_t* allocated, uint64_t* reserved,
                     uint64_t* peak) {
  auto* a = (Arena*)ap;
  std::lock_guard<std::mutex> g(a->mu);
  *allocated = a->allocated;
  *reserved = a->reserved;
  *peak = a->peak;
}

void ptn_arena_destroy(void* ap) {
  auto* a = (Arena*)ap;
  for (auto& [base, size] : a->chunks) munmap(base, size);
  delete a;
}

}  // extern "C"
