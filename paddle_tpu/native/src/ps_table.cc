// Sharded sparse embedding table — the parameter-server storage engine.
// Native equivalent of the reference's MemorySparseTable
// (paddle/fluid/distributed/ps/table/memory_sparse_table.cc): a striped
// hash table of feature-id -> embedding row (+ optimizer slots), with the
// sparse update rules (paddle/fluid/distributed/ps/table/sparse_sgd_rule.cc)
// applied server-side on push. Rows are created on first pull with uniform
// init, like the reference's accessor Init.
//
// Threading: N_SHARD stripes, each its own mutex + open hash map, so
// concurrent pulls/pushes from DataLoader workers and the async
// communicator scale (the reference shards by feasign % shard_num the same
// way).
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <random>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

constexpr int N_SHARD = 32;

enum Rule { SGD = 0, ADAGRAD = 1, ADAM = 2 };

struct Shard {
  std::mutex mu;
  std::unordered_map<int64_t, size_t> index;  // key -> row offset
  std::vector<float> rows;                    // row_width per entry
  std::vector<int64_t> slot_keys;             // key at rows offset i*row_width
};

struct Table {
  int dim = 0;
  int slot = 0;     // extra floats per row for optimizer state
  Rule rule = SGD;
  float lr = 0.05f;
  float init_range = 0.01f;
  float beta1 = 0.9f, beta2 = 0.999f, eps = 1e-8f;
  uint64_t seed = 0;
  Shard shards[N_SHARD];

  int row_width() const { return dim + slot; }

  Shard& shard_of(int64_t key) {
    return shards[(uint64_t)key % N_SHARD];
  }

  // caller holds the shard lock
  float* row(Shard& s, int64_t key, bool create) {
    auto it = s.index.find(key);
    if (it != s.index.end()) return s.rows.data() + it->second;
    if (!create) return nullptr;
    size_t off = s.rows.size();
    s.rows.resize(off + row_width());
    // deterministic per-key init (reference: accessor's uniform initializer;
    // determinism means every worker pulling a fresh key agrees)
    std::mt19937_64 gen(seed ^ (uint64_t)key);
    std::uniform_real_distribution<float> u(-init_range, init_range);
    float* r = s.rows.data() + off;
    for (int i = 0; i < dim; i++) r[i] = u(gen);
    for (int i = dim; i < row_width(); i++) r[i] = 0.f;
    s.index.emplace(key, off);
    s.slot_keys.push_back(key);
    return r;
  }
};

}  // namespace

extern "C" {

void* ptn_pstable_create(int dim, const char* rule, float lr,
                         float init_range, uint64_t seed) {
  auto* t = new Table();
  t->dim = dim;
  t->lr = lr;
  t->init_range = init_range;
  t->seed = seed;
  if (strcmp(rule, "adagrad") == 0) {
    t->rule = ADAGRAD;
    t->slot = dim;                // per-dim g2 accumulator
  } else if (strcmp(rule, "adam") == 0) {
    t->rule = ADAM;
    t->slot = 2 * dim + 1;        // m, v, step
  } else {
    t->rule = SGD;
    t->slot = 0;
  }
  return t;
}

void ptn_pstable_pull(void* tp, const int64_t* keys, int64_t n, float* out) {
  auto* t = (Table*)tp;
  for (int64_t i = 0; i < n; i++) {
    Shard& s = t->shard_of(keys[i]);
    std::lock_guard<std::mutex> g(s.mu);
    const float* r = t->row(s, keys[i], true);
    memcpy(out + i * t->dim, r, t->dim * sizeof(float));
  }
}

void ptn_pstable_push(void* tp, const int64_t* keys, int64_t n,
                      const float* grads) {
  auto* t = (Table*)tp;
  const int D = t->dim;
  for (int64_t i = 0; i < n; i++) {
    Shard& s = t->shard_of(keys[i]);
    std::lock_guard<std::mutex> g(s.mu);
    float* r = t->row(s, keys[i], true);
    const float* gr = grads + i * D;
    switch (t->rule) {
      case SGD:
        for (int d = 0; d < D; d++) r[d] -= t->lr * gr[d];
        break;
      case ADAGRAD: {
        float* g2 = r + D;
        for (int d = 0; d < D; d++) {
          g2[d] += gr[d] * gr[d];
          r[d] -= t->lr * gr[d] / (std::sqrt(g2[d]) + t->eps);
        }
        break;
      }
      case ADAM: {
        float* m = r + D;
        float* v = r + 2 * D;
        float& step = r[3 * D];
        step += 1.f;
        float bc1 = 1.f - std::pow(t->beta1, step);
        float bc2 = 1.f - std::pow(t->beta2, step);
        for (int d = 0; d < D; d++) {
          m[d] = t->beta1 * m[d] + (1 - t->beta1) * gr[d];
          v[d] = t->beta2 * v[d] + (1 - t->beta2) * gr[d] * gr[d];
          r[d] -= t->lr * (m[d] / bc1) / (std::sqrt(v[d] / bc2) + t->eps);
        }
        break;
      }
    }
  }
}

// Pull rows AND optimizer-state slots (for the device-resident cache,
// reference: ps_gpu_wrapper.cc BuildPull copies values+slots to GPU).
// out: (n, dim); state: (n, slot) — untouched when slot == 0.
void ptn_pstable_pull_state(void* tp, const int64_t* keys, int64_t n,
                            float* out, float* state) {
  auto* t = (Table*)tp;
  for (int64_t i = 0; i < n; i++) {
    Shard& s = t->shard_of(keys[i]);
    std::lock_guard<std::mutex> g(s.mu);
    const float* r = t->row(s, keys[i], true);
    memcpy(out + i * t->dim, r, t->dim * sizeof(float));
    if (t->slot > 0)
      memcpy(state + i * t->slot, r + t->dim, t->slot * sizeof(float));
  }
}

// Assign row values (and optionally optimizer state) directly — the
// end-of-pass flush of device-updated rows (reference: ps_gpu_wrapper.cc
// EndPass copying GPU values back into the table).
void ptn_pstable_assign(void* tp, const int64_t* keys, int64_t n,
                        const float* vals, const float* state) {
  auto* t = (Table*)tp;
  for (int64_t i = 0; i < n; i++) {
    Shard& s = t->shard_of(keys[i]);
    std::lock_guard<std::mutex> g(s.mu);
    float* r = t->row(s, keys[i], true);
    memcpy(r, vals + i * t->dim, t->dim * sizeof(float));
    if (state != nullptr && t->slot > 0)
      memcpy(r + t->dim, state + i * t->slot, t->slot * sizeof(float));
  }
}

// Remove rows (for the SSD tier's LRU hot-cache eviction: spilled rows
// leave the in-memory table so hot capacity is a real bound). Swap-remove:
// the last row fills the hole, O(1) per key via the slot_keys back-map.
void ptn_pstable_erase(void* tp, const int64_t* keys, int64_t n) {
  auto* t = (Table*)tp;
  const int w = t->row_width();
  for (int64_t i = 0; i < n; i++) {
    Shard& s = t->shard_of(keys[i]);
    std::lock_guard<std::mutex> g(s.mu);
    auto it = s.index.find(keys[i]);
    if (it == s.index.end()) continue;
    size_t off = it->second;
    size_t last = s.rows.size() - w;
    if (off != last) {
      memcpy(s.rows.data() + off, s.rows.data() + last, w * sizeof(float));
      int64_t moved = s.slot_keys.back();
      s.slot_keys[off / w] = moved;
      s.index[moved] = off;
    }
    s.rows.resize(last);
    s.slot_keys.pop_back();
    s.index.erase(it);
  }
}

int64_t ptn_pstable_size(void* tp) {
  auto* t = (Table*)tp;
  int64_t n = 0;
  for (auto& s : t->shards) {
    std::lock_guard<std::mutex> g(s.mu);
    n += (int64_t)s.index.size();
  }
  return n;
}

// binary format: u64 magic | i32 dim | i32 slot | u64 count | (key, row)*
int ptn_pstable_save(void* tp, const char* path) {
  auto* t = (Table*)tp;
  FILE* f = fopen(path, "wb");
  if (!f) return -1;
  uint64_t magic = 0x7073746162ull;
  int64_t count = ptn_pstable_size(tp);
  int32_t dim = t->dim, slot = t->slot;
  fwrite(&magic, 8, 1, f);
  fwrite(&dim, 4, 1, f);
  fwrite(&slot, 4, 1, f);
  fwrite(&count, 8, 1, f);
  int w = t->row_width();
  for (auto& s : t->shards) {
    std::lock_guard<std::mutex> g(s.mu);
    for (auto& kv : s.index) {
      fwrite(&kv.first, 8, 1, f);
      fwrite(s.rows.data() + kv.second, sizeof(float), w, f);
    }
  }
  fclose(f);
  return 0;
}

int ptn_pstable_load(void* tp, const char* path) {
  auto* t = (Table*)tp;
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  uint64_t magic = 0;
  int32_t dim = 0, slot = 0;
  int64_t count = 0;
  if (fread(&magic, 8, 1, f) != 1 || magic != 0x7073746162ull ||
      fread(&dim, 4, 1, f) != 1 || fread(&slot, 4, 1, f) != 1 ||
      fread(&count, 8, 1, f) != 1 || dim != t->dim || slot != t->slot) {
    fclose(f);
    return -2;
  }
  int w = t->row_width();
  std::vector<float> buf(w);
  for (int64_t i = 0; i < count; i++) {
    int64_t key;
    if (fread(&key, 8, 1, f) != 1 ||
        fread(buf.data(), sizeof(float), w, f) != (size_t)w) {
      fclose(f);
      return -3;
    }
    Shard& s = t->shard_of(key);
    std::lock_guard<std::mutex> g(s.mu);
    float* r = t->row(s, key, true);
    memcpy(r, buf.data(), w * sizeof(float));
  }
  fclose(f);
  return 0;
}

void ptn_pstable_destroy(void* tp) { delete (Table*)tp; }

}  // extern "C"
