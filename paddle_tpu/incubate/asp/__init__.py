"""ASP — automatic n:m structured sparsity workflow.

Reference: python/paddle/incubate/asp (fluid/contrib/sparsity): `prune_model`
computes n:m (default 2:4) masks per supported weight, `decorate(optimizer)`
re-applies the masks after every optimizer step so pruned weights stay
zero, `check_sparsity` validates the pattern.

TPU-native note: the reference's payoff is Ampere sparse-tensor-core
GEMMs; the MXU has no 2:4 mode, so here ASP serves mask-correct sparse
TRAINING (model compression research, export to sparse-capable targets),
with masks enforced as elementwise multiplies that XLA fuses for free.
"""
import numpy as np

import jax.numpy as jnp

from ...core.tensor import Tensor

__all__ = ["prune_model", "decorate", "check_sparsity", "calculate_density",
           "create_mask", "reset_excluded_layers", "set_excluded_layers"]

_excluded = set()
# the mask lives ON the parameter (slot `_asp_mask`): it dies with its
# model and can never be mis-applied to another model's weight


def set_excluded_layers(main_program=None, param_names=None):
    for n in (param_names or []):
        _excluded.add(n)


def reset_excluded_layers(main_program=None):
    _excluded.clear()


def create_mask(weight, n=2, m=4):
    """n:m mask along the last axis: keep the n largest-|w| of every m."""
    w = np.asarray(weight, np.float32)
    flat = w.reshape(-1, w.shape[-1])
    cols = flat.shape[1]
    pad = (-cols) % m
    if pad:
        flat = np.pad(flat, ((0, 0), (0, pad)))
    groups = flat.reshape(flat.shape[0], -1, m)          # (..., G, m)
    order = np.argsort(-np.abs(groups), axis=-1)
    mask = np.zeros_like(groups)
    np.put_along_axis(mask, order[..., :n], 1.0, axis=-1)
    mask = mask.reshape(flat.shape)[:, :cols].reshape(w.shape)
    return mask


def _supported(p):
    return p is not None and p._data.ndim >= 2 and \
        p._data.shape[-1] >= 4 and not p.stop_gradient


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Apply n:m masks to every supported weight in the model; masks are
    remembered for `decorate`d optimizers to re-apply."""
    pruned = {}
    for name, p in model.named_parameters():
        if name in _excluded or not _supported(p):
            continue
        if name.endswith("bias"):
            continue
        mask = jnp.asarray(create_mask(np.asarray(p._data, np.float32),
                                       n, m), p._data.dtype)
        p._data = p._data * mask
        p._asp_mask = mask
        pruned[name] = mask
    return pruned


def decorate(optimizer):
    """Wrap an optimizer so every step() re-applies the ASP masks
    (reference: asp.decorate -> OptimizerWithSparsityGuarantee)."""

    class _ASPOptimizer:
        def __init__(self, inner):
            self._inner_opt = inner

        def __getattr__(self, item):
            return getattr(self._inner_opt, item)

        def step(self):
            self._inner_opt.step()
            for p in self._inner_opt._parameters:
                mask = getattr(p, "_asp_mask", None)
                if mask is not None:
                    p._data = p._data * mask

        def clear_grad(self, *a, **k):
            self._inner_opt.clear_grad()

        clear_gradients = clear_grad

        def minimize(self, loss, **kw):
            loss.backward()
            self.step()

    return _ASPOptimizer(optimizer)


def check_sparsity(weight, n=2, m=4):
    """True iff every m-group along the last axis has <= n nonzeros."""
    w = np.asarray(weight, np.float32)
    flat = w.reshape(-1, w.shape[-1])
    cols = flat.shape[1]
    pad = (-cols) % m
    if pad:
        flat = np.pad(flat, ((0, 0), (0, pad)))
    groups = flat.reshape(flat.shape[0], -1, m)
    return bool(((groups != 0).sum(-1) <= n).all())


def calculate_density(weight):
    w = np.asarray(weight)
    return float((w != 0).mean())
