"""Kernel autotune (reference: python/paddle/incubate/autotune.py +
phi/kernels/autotune/cache.h — the runtime kernel-pick cache).

On TPU, XLA already autotunes its own fusions, so the one knob the
framework genuinely owns is Pallas kernel tiling. `autotune_flash_blocks`
measures the flash-attention (block_q, block_k) candidates for a concrete
shape ON THE DEVICE, caches the winner keyed by (backend, H, S, D, causal)
— in memory, in an optional env-path disk cache, and via the shipped
`ops/pallas/flash_blocks_tuned.json` table, the phi AlgorithmsCache role —
and `ops.flash_attention` consults the cache on every call.

The reference's dataloader/layout tuning knobs remain config-only (XLA owns
layout on TPU; the DataLoader sizes its worker pool explicitly).
"""
import json
import os
import time

_config = {"kernel": {"enable": True, "tuning_range": [1, 10]},
           "dataloader": {"enable": False},
           "layout": {"enable": False}}

# Two kernels share the table. Flash keys are UNTAGGED (the original
# format): (backend, H, S, D, causal) -> (block_q, block_k). Paged-
# attention keys lead with a kernel tag: ("paged", backend, H,
# padded_len, D, block_size) -> (q_tile, head_tile) caps. Batch size is
# NOT part of either key: tiling is set by the geometry, so a winner
# tuned at one B serves every batch size (and per-B retuning would be
# dead weight). The tag check runs BEFORE the legacy-6-tuple collapse,
# so old flash caches keep parsing and old frameworks reading a new file
# simply never look tagged keys up.
# _block_cache holds entries tuned IN THIS PROCESS (these get persisted to
# the env-path file); _disk_cache holds entries loaded from the shipped file
# and the env-path file (read-only — never written back, so a framework
# upgrade that improves flash_blocks_tuned.json is never shadowed by a stale
# frozen copy in the user cache).
_KERNEL_TAGS = ("paged",)
_block_cache = {}
_disk_cache = {}
_disk_loaded = False
# geometries whose in-memory entry is a static FALLBACK, not a measured
# winner: excluded from every disk write so they can never shadow shipped
# tuned entries in a future process (ADVICE r4)
_fallback_keys = set()
_CACHE_ENV = "PADDLE_TPU_AUTOTUNE_CACHE"


def set_config(config=None):
    if config:
        for k, v in config.items():
            if isinstance(v, dict) and isinstance(_config.get(k), dict):
                _config[k].update(v)       # per-section merge (reference
            else:                          # set_config semantics)
                _config[k] = v


def get_config():
    return dict(_config)


def kernel_tuning_enabled():
    return bool(_config.get("kernel", {}).get("enable"))


def _cache_path():
    return os.environ.get(_CACHE_ENV, "")


# Tuned blocks shipped with the framework (the phi role of the bundled
# cuDNN-heuristics tables): winners measured on real TPU by
# tools/profile_step.py's sweep get committed here so every process —
# including ones with no PADDLE_TPU_AUTOTUNE_CACHE env — starts from
# chip-measured tilings. The env-path cache (per-user/runtime) overrides.
_SHIPPED_PATH = os.path.join(os.path.dirname(__file__), os.pardir, "ops",
                             "pallas", "flash_blocks_tuned.json")


def _read_cache_file(path):
    if path and os.path.exists(path):
        try:
            with open(path) as f:
                out = {}
                for k, v in json.load(f).items():
                    key = tuple(json.loads(k))
                    if not (key and key[0] in _KERNEL_TAGS):
                        # untagged == flash
                        if len(key) == 6:  # legacy (backend,B,H,S,D,causal)
                            key = key[:1] + key[2:]
                    out[key] = tuple(v)
                return out
        except (OSError, ValueError):
            return {}
    return {}


def _load_disk_cache():
    merged = _read_cache_file(_SHIPPED_PATH)
    merged.update(_read_cache_file(_cache_path()))
    return merged


def _save_disk_cache():
    path = _cache_path()
    if path:
        try:
            # load-then-merge the env-path file only (never clobber entries
            # written by other processes sharing it; never freeze shipped
            # entries into the user cache, where they would shadow future
            # shipped updates)
            merged = _read_cache_file(path)
            merged.update({k: v for k, v in _block_cache.items()
                           if k not in _fallback_keys})
            with open(path, "w") as f:
                json.dump({json.dumps(list(k)): list(v)
                           for k, v in merged.items()}, f)
        except OSError:
            pass


def lookup_flash_blocks(B, H, S, D, causal):
    """Cached (block_q, block_k) for this geometry, or None (B is accepted
    for call-site convenience but is not part of the key). Honors the
    kernel.enable knob. Disk caches (shipped file + env path) are read once
    per process (keeping file IO off the eager dispatch path); entries tuned
    by other processes after that point become visible on the next process
    start. In-process tuned entries win over disk entries."""
    import jax
    global _disk_loaded
    if not kernel_tuning_enabled():
        return None
    key = (jax.default_backend(), H, S, D, bool(causal))
    hit = _block_cache.get(key)
    if hit is not None:
        return hit
    if not _disk_loaded:
        _disk_cache.update(_load_disk_cache())
        _disk_loaded = True
    return _disk_cache.get(key)


def lookup_paged_blocks(H, padded_len, D, block_size):
    """Tuned (q_tile, head_tile) CAPS for the paged-attention kernel's
    geometry, or None. Same caches and enable knob as the flash lookup.

    The fall-back-don't-raise contract (PR 6, extended here): a stale or
    hand-poisoned shipped entry that is not a pair of positive ints is
    treated as absent — the kernel then tiles with its own defaults —
    because an exception from a table lookup inside a traced forward is
    the worst possible place to learn the table rotted. Values are caps,
    not exact tiles: the kernel clamps each to the largest divisor of
    the live extent, so an entry tuned for one prefill bucket serves
    every bucket (and the T=1 decode shape) without retuning."""
    import jax
    global _disk_loaded
    if not kernel_tuning_enabled():
        return None
    key = ("paged", jax.default_backend(), int(H), int(padded_len), int(D),
           int(block_size))
    entry = _block_cache.get(key)
    if entry is None:
        if not _disk_loaded:
            _disk_cache.update(_load_disk_cache())
            _disk_loaded = True
        entry = _disk_cache.get(key)
    if entry is None:
        return None
    try:
        qt, ht = int(entry[0]), int(entry[1])
    except (TypeError, ValueError, IndexError):
        return None                 # rotted entry: fall back, don't raise
    if qt < 1 or ht < 1:
        return None
    return (qt, ht)


def record_flash_blocks(H, S, D, causal, blocks, persist=True):
    """Record an externally-measured (block_q, block_k) winner for a
    geometry (tools/profile_step.py's sweep) and persist it to the env-path
    cache if configured. persist=False keeps the entry in-memory only —
    used for static FALLBACK results, which must never shadow shipped
    tuned entries at the next load (ADVICE r4)."""
    import jax
    key = (jax.default_backend(), H, S, D, bool(causal))
    _block_cache[key] = tuple(blocks)
    if persist:
        _fallback_keys.discard(key)
        _save_disk_cache()
    else:
        _fallback_keys.add(key)


def commit_shipped_table(entries, backend="tpu", path=None, kernel="flash"):
    """Commit measured winners into the SHIPPED table
    (`ops/pallas/flash_blocks_tuned.json`) — the path on-chip sweep
    results (tools/profile_step.py) take into the tree, using the exact
    cache serialization the lookups read back.

    kernel="flash": entries {(H, S, D, causal): (block_q, block_k)}.
    kernel="paged": entries {(H, padded_len, D, block_size):
    (q_tile, head_tile)} — the paged-attention kernel's tile caps,
    served back by `lookup_paged_blocks`. Existing shipped entries for
    other geometries/kernels are preserved (load-then-merge). The
    in-process disk cache is invalidated so the committing process sees
    its own commit."""
    global _disk_loaded
    if kernel not in ("flash",) + _KERNEL_TAGS:
        raise ValueError(f"unknown kernel {kernel!r}; want 'flash' or one "
                         f"of {_KERNEL_TAGS}")
    path = path or _SHIPPED_PATH
    merged = _read_cache_file(path)
    for key, blocks in entries.items():
        if kernel == "paged":
            H, L, D, bs = key
            qt, ht = int(blocks[0]), int(blocks[1])
            if qt < 1 or ht < 1:
                raise ValueError(f"paged tile caps {blocks} must be "
                                 f"positive ints")
            if int(L) % int(bs):
                raise ValueError(f"padded_len {L} is not a multiple of "
                                 f"block_size {bs}")
            merged[("paged", backend, int(H), int(L), int(D), int(bs))] = \
                (qt, ht)
            continue
        H, S, D, causal = key
        bq, bk = int(blocks[0]), int(blocks[1])
        if bq <= 0 or bk <= 0 or bq % 8 or bk % 8:
            raise ValueError(f"blocks {blocks} must be positive multiples "
                             f"of 8 (TPU sublane alignment)")
        if S % bq or S % bk:
            raise ValueError(f"blocks {blocks} do not tile S={S}")
        if causal and bq != bk:
            # the kernel requires square blocks under causal masking;
            # committing a non-square pair would ship an entry the
            # runtime guard silently ignores — reject it here instead
            raise ValueError(f"causal entries need square blocks, got "
                             f"{blocks}")
        merged[(backend, int(H), int(S), int(D), bool(causal))] = (bq, bk)
    with open(path, "w") as f:
        json.dump({json.dumps(list(k)): list(v)
                   for k, v in sorted(merged.items())}, f, indent=1)
    _disk_cache.clear()
    _disk_loaded = False
    return path


def autotune_flash_blocks(B, H, S, D, causal=True, dtype="bfloat16",
                          candidates=(128, 256, 512), n_iters=3):
    """Measure each candidate square block on the live backend and cache the
    fastest. Returns (block_q, block_k). Candidates that don't divide S or
    fail to compile are skipped; measurement uses a host fetch as the sync
    (the only honest sync through remote-device tunnels)."""
    import jax
    import jax.numpy as jnp

    from ..ops.pallas.flash_attention import flash_attention

    hit = lookup_flash_blocks(B, H, S, D, causal)
    if hit is not None:
        return hit
    if not kernel_tuning_enabled():
        from ..ops.pallas.flash_attention import _auto_block
        b = _auto_block(S)
        return (b, b)

    q = (jax.random.normal(jax.random.key(0), (B, H, S, D)) * 0.1) \
        .astype(dtype)
    interpret = jax.default_backend() != "tpu"
    best, best_dt = None, float("inf")
    for b in candidates:
        if S % b or b > S:
            continue
        try:
            f = jax.jit(lambda q, b=b: flash_attention(
                q, q, q, causal=causal, block_q=b, block_k=b,
                interpret=interpret))
            float(jnp.ravel(f(q))[0].astype(jnp.float32))    # compile+warm
            t0 = time.perf_counter()
            for _ in range(n_iters):
                float(jnp.ravel(f(q))[0].astype(jnp.float32))
            dt = time.perf_counter() - t0
        except Exception:                                    # noqa: BLE001
            continue
        if dt < best_dt:
            best, best_dt = (b, b), dt
    fallback = best is None
    if fallback:
        from ..ops.pallas.flash_attention import _auto_block
        b = _auto_block(S)           # always divides S (never poisons cache)
        best = (b, b)
    # fallbacks stay in-memory only: a persisted fallback would override the
    # shipped tuned table for this geometry on every future load (ADVICE r4)
    record_flash_blocks(H, S, D, causal, best, persist=not fallback)
    return best
