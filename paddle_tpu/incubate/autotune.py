"""Kernel autotune (reference: python/paddle/incubate/autotune.py +
phi/kernels/autotune/cache.h — the runtime kernel-pick cache).

On TPU, XLA already autotunes its own fusions, so the one knob the
framework genuinely owns is Pallas kernel tiling. `autotune_flash_blocks`
measures the flash-attention (block_q, block_k) candidates for a concrete
shape ON THE DEVICE, caches the winner keyed by (backend, B, H, S, D,
causal) — in memory and optionally on disk, the phi AlgorithmsCache role —
and `ops.flash_attention` consults the cache on every call.

The reference's dataloader/layout tuning knobs remain config-only (XLA owns
layout on TPU; the DataLoader sizes its worker pool explicitly).
"""
import json
import os
import time

_config = {"kernel": {"enable": True, "tuning_range": [1, 10]},
           "dataloader": {"enable": False},
           "layout": {"enable": False}}

# (backend, B, H, S, D, causal) -> (block_q, block_k)
_block_cache = {}
_disk_loaded = False
_CACHE_ENV = "PADDLE_TPU_AUTOTUNE_CACHE"


def set_config(config=None):
    if config:
        for k, v in config.items():
            if isinstance(v, dict) and isinstance(_config.get(k), dict):
                _config[k].update(v)       # per-section merge (reference
            else:                          # set_config semantics)
                _config[k] = v


def get_config():
    return dict(_config)


def kernel_tuning_enabled():
    return bool(_config.get("kernel", {}).get("enable"))


def _cache_path():
    return os.environ.get(_CACHE_ENV, "")


def _load_disk_cache():
    path = _cache_path()
    if path and os.path.exists(path):
        try:
            with open(path) as f:
                return {tuple(json.loads(k)): tuple(v)
                        for k, v in json.load(f).items()}
        except (OSError, ValueError):
            return {}
    return {}


def _save_disk_cache():
    path = _cache_path()
    if path:
        try:
            # load-then-merge: never clobber entries written by other
            # processes sharing the cache file
            merged = _load_disk_cache()
            merged.update(_block_cache)
            with open(path, "w") as f:
                json.dump({json.dumps(list(k)): list(v)
                           for k, v in merged.items()}, f)
        except OSError:
            pass


def lookup_flash_blocks(B, H, S, D, causal):
    """Cached (block_q, block_k) for this shape, or None. Honors the
    kernel.enable knob. The disk cache is read once per process (keeping
    file IO off the eager dispatch path); entries tuned by other processes
    after that point become visible on the next process start."""
    import jax
    global _disk_loaded
    if not kernel_tuning_enabled():
        return None
    key = (jax.default_backend(), B, H, S, D, bool(causal))
    if key not in _block_cache and not _disk_loaded:
        # one disk read per process (not per miss — this sits on the eager
        # attention dispatch path); tuning refreshes it on save
        _block_cache.update({k: v for k, v in _load_disk_cache().items()
                             if k not in _block_cache})
        _disk_loaded = True
    return _block_cache.get(key)


def autotune_flash_blocks(B, H, S, D, causal=True, dtype="bfloat16",
                          candidates=(128, 256, 512), n_iters=3):
    """Measure each candidate square block on the live backend and cache the
    fastest. Returns (block_q, block_k). Candidates that don't divide S or
    fail to compile are skipped; measurement uses a host fetch as the sync
    (the only honest sync through remote-device tunnels)."""
    import jax
    import jax.numpy as jnp

    from ..ops.pallas.flash_attention import flash_attention

    key = (jax.default_backend(), B, H, S, D, bool(causal))
    hit = lookup_flash_blocks(B, H, S, D, causal)
    if hit is not None:
        return hit
    if not kernel_tuning_enabled():
        from ..ops.pallas.flash_attention import _auto_block
        b = _auto_block(S)
        return (b, b)

    q = (jax.random.normal(jax.random.key(0), (B, H, S, D)) * 0.1) \
        .astype(dtype)
    interpret = jax.default_backend() != "tpu"
    best, best_dt = None, float("inf")
    for b in candidates:
        if S % b or b > S:
            continue
        try:
            f = jax.jit(lambda q, b=b: flash_attention(
                q, q, q, causal=causal, block_q=b, block_k=b,
                interpret=interpret))
            float(jnp.ravel(f(q))[0].astype(jnp.float32))    # compile+warm
            t0 = time.perf_counter()
            for _ in range(n_iters):
                float(jnp.ravel(f(q))[0].astype(jnp.float32))
            dt = time.perf_counter() - t0
        except Exception:                                    # noqa: BLE001
            continue
        if dt < best_dt:
            best, best_dt = (b, b), dt
    if best is None:
        from ..ops.pallas.flash_attention import _auto_block
        b = _auto_block(S)           # always divides S (never poisons cache)
        best = (b, b)
    _block_cache[key] = best
    _save_disk_cache()
    return best
