"""Kernel/dataloader autotune config (reference: python/paddle/incubate/autotune.py).

On TPU, XLA's autotuning (latency-hiding scheduler, fusion) replaces the
reference's runtime kernel autotune cache (phi/kernels/autotune). This module
keeps the config surface and toggles the knobs we do own.
"""
_config = {"kernel": {"enable": True}, "dataloader": {"enable": False},
           "layout": {"enable": False}}


def set_config(config=None):
    if config:
        _config.update(config)


def get_config():
    return dict(_config)
