"""incubate.distributed.fleet (reference: recompute_sequential /
recompute_hybrid — segment-wise activation recompute wrappers)."""

__all__ = ["recompute_sequential", "recompute_hybrid"]


def recompute_sequential(ctx, functions, *args, **kwargs):
    """reference: incubate/distributed/fleet/recompute_sequential — split a
    Sequential into `segments` chunks, recomputing each chunk."""
    from ....distributed.fleet.utils import recompute
    segments = (ctx or {}).get("segments", 1)
    layers = list(functions)
    if segments <= 1:
        chunks = [layers]
    else:
        per = max(len(layers) // segments, 1)
        chunks = [layers[i:i + per] for i in range(0, len(layers), per)]
    out = args[0] if len(args) == 1 else args

    import paddle_tpu.nn as nn
    for chunk in chunks:
        seq = chunk[0] if len(chunk) == 1 else nn.Sequential(*chunk)
        out = recompute(seq, out, **kwargs)
    return out


def recompute_hybrid(ctx, function, *args, **kwargs):
    """reference: recompute_hybrid — recompute with hybrid-parallel RNG
    bookkeeping (mp-aware dropout states). The stateless-PRNG design makes
    dropout reproducible under recompute by construction, so this is
    recompute + the ctx's offload knobs accepted for parity."""
    from ....distributed.fleet.utils import recompute
    return recompute(function, *args, **kwargs)
