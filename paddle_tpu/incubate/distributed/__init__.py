from . import moe  # noqa: F401
from . import fleet  # noqa: F401
