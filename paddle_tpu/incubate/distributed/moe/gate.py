"""MoE gates.

Reference: python/paddle/incubate/distributed/models/moe/gate/
(naive_gate.py, switch_gate.py, gshard_gate.py). All three reduce to the
same capacity-constrained top-k routing (`functional.gshard_dispatch`);
they differ in k, whether the load-balance aux loss applies, and
training-time jitter — each gate is a thin Layer carrying its linear
scorer plus that config, consumed by `MoELayer.forward`.
"""
import numpy as np

from ....nn.layer.layers import Layer
from .functional import compute_capacity


class BaseGate(Layer):
    top_k = 1
    has_aux_loss = True
    jitter_eps = 0.0      # >0: multiply train-time logits by U[1-eps, 1+eps]

    def __init__(self, d_model, num_experts, capacity_factor=1.2):
        super().__init__()
        self.d_model = d_model
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        s = 1.0 / np.sqrt(d_model)
        from ....nn.initializer import Uniform
        self.weight = self.create_parameter(
            (d_model, num_experts), default_initializer=Uniform(-s, s))

    def capacity(self, num_tokens):
        return compute_capacity(self.capacity_factor, self.top_k,
                                num_tokens, self.num_experts)


class NaiveGate(BaseGate):
    """Top-2 routing, no balance loss, no jitter (reference naive_gate.py)."""
    top_k = 2
    has_aux_loss = False


class SwitchGate(BaseGate):
    """Top-1 routing with load-balance aux loss and train-time logit jitter
    (reference switch_gate.py)."""
    top_k = 1

    def __init__(self, d_model, num_experts, capacity_factor=1.2,
                 switch_eps=0.1):
        super().__init__(d_model, num_experts, capacity_factor)
        self.jitter_eps = switch_eps


class GShardGate(BaseGate):
    """Top-2 routing with capacity + aux loss (reference gshard_gate.py)."""
    top_k = 2
