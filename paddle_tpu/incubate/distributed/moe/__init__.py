"""Mixture-of-Experts with expert parallelism.

Reference: python/paddle/incubate/distributed/models/moe/ (MoELayer,
gate/gshard_gate.py, switch_gate.py) with the expert-parallel all-to-all
dispatch implemented by the `global_scatter`/`global_gather` CUDA collective
ops (paddle/fluid/operators/collective/global_scatter_op.cc).

TPU-native design: dispatch/combine are dense einsums against a
(token, expert, capacity) one-hot — XLA fuses them — and the cross-device
exchange is a single `jax.lax.all_to_all` over an "ep" mesh axis inside the
compiled program, riding ICI instead of NCCL.
"""
from .functional import gshard_dispatch, moe_forward, init_moe_experts
from .gate import GShardGate, SwitchGate, NaiveGate
from .moe_layer import MoELayer
from .grad_clip import ClipGradForMOEByGlobalNorm

__all__ = ["ClipGradForMOEByGlobalNorm",
           "gshard_dispatch", "moe_forward", "init_moe_experts",
           "GShardGate", "SwitchGate", "NaiveGate", "MoELayer"]
