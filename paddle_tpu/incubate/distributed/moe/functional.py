"""Functional MoE core: gating, dispatch/combine, expert-parallel exchange.

Pure-jax functions usable both from the eager `MoELayer` (via `apply_op`)
and inside `shard_map`'d SPMD train steps with an "ep" mesh axis.

The (token, expert, capacity) one-hot dispatch follows the GShard
formulation; the reference reaches the same result with index scatter
kernels (moe_layer.py:106-173 prune_gate_by_capacity + global_scatter).
Dense einsum is the right shape for the MXU: no dynamic shapes, no
scatter — XLA fuses dispatch into the expert matmul.
"""
import jax
import jax.numpy as jnp
import numpy as np


def compute_capacity(capacity_factor, k, num_tokens, num_experts):
    """The one place the per-expert buffer size is defined."""
    return int(np.ceil(capacity_factor * k * num_tokens / num_experts))


def gshard_dispatch(gates, k, capacity):
    """Top-k capacity-constrained routing.

    gates: (T, E) softmax probabilities.
    Returns (combine, dispatch, aux_loss):
      combine  (T, E, C) float — normalized routing weights
      dispatch (T, E, C) bool  — combine > 0
      aux_loss scalar — load-balancing loss (E * sum(me * ce), switch-style)
    Tokens beyond an expert's capacity C are dropped (zero rows), matching
    the reference's prune_gate_by_capacity.
    """
    T, E = gates.shape
    C = capacity
    if k > E:
        raise ValueError(f"top-k={k} exceeds num_experts={E}")

    combine = jnp.zeros((T, E, C), jnp.float32)
    remaining = gates
    prev_count = jnp.zeros((E,), jnp.int32)
    kept_weight_sum = jnp.zeros((T,), jnp.float32)
    aux_loss = jnp.float32(0.0)

    parts = []
    for pick in range(k):
        idx = jnp.argmax(remaining, axis=-1)                   # (T,)
        m = jax.nn.one_hot(idx, E, dtype=jnp.float32)          # (T, E)
        w = jnp.sum(gates * m, axis=-1)                        # (T,)

        if pick == 0:
            # load-balance: fraction routed to e × mean prob of e
            me = jnp.mean(gates, axis=0)
            ce = jnp.mean(m, axis=0)
            aux_loss = jnp.float32(E) * jnp.sum(me * ce)

        # position of each token within its expert's buffer
        pos_in_expert = jnp.cumsum(m, axis=0) - m              # (T, E)
        pos = jnp.sum(pos_in_expert * m, axis=-1).astype(jnp.int32)
        pos = pos + jnp.sum(prev_count[None, :] * m, axis=-1).astype(jnp.int32)
        prev_count = prev_count + jnp.sum(m, axis=0).astype(jnp.int32)

        keep = pos < C
        w_kept = jnp.where(keep, w, 0.0)
        kept_weight_sum = kept_weight_sum + w_kept
        onehot_c = jax.nn.one_hot(jnp.clip(pos, 0, C - 1), C,
                                  dtype=jnp.float32) * keep[:, None]
        parts.append((w_kept, m, onehot_c))
        remaining = remaining * (1.0 - m)

    denom = jnp.maximum(kept_weight_sum, 1e-9)[:, None, None]
    for w_kept, m, onehot_c in parts:
        combine = combine + (w_kept[:, None, None]
                             * m[:, :, None] * onehot_c[:, None, :])
    combine = combine / denom
    dispatch = combine > 0.0
    return combine, dispatch, aux_loss


def _expert_ffn(x, params, activation):
    """x: (E_local, C_total, d); params: dict of stacked (E_local, ...) arrays."""
    h = jnp.einsum("ecd,edf->ecf", x, params["w1"]) + params["b1"][:, None, :]
    h = activation(h)
    return jnp.einsum("ecf,efd->ecd", h, params["w2"]) + params["b2"][:, None, :]


def moe_forward(x, gate_w, expert_params, *, k=2, capacity_factor=1.2,
                axis_name=None, num_experts=None,
                activation=jax.nn.gelu, jitter_noise=None):
    """MoE FFN over flattened tokens.

    x: (T, d) local tokens. gate_w: (d, E) with E the GLOBAL expert count.
    expert_params: stacked expert weights — (E, ...) without `axis_name`, or
    the LOCAL (E//ep, ...) shard inside a shard_map with `axis_name="ep"`.

    Returns (out (T, d), aux_loss). With `axis_name`, dispatched tokens are
    exchanged with a single all_to_all each way (the reference's
    global_scatter / global_gather pair).

    jitter_noise: optional (rng_key, eps) — multiplies gate logits by
    U[1-eps, 1+eps] (switch-transformer training jitter).
    """
    T, d = x.shape
    E = num_experts or gate_w.shape[-1]
    ep = jax.lax.axis_size(axis_name) if axis_name else 1
    if E % ep:
        raise ValueError(f"num_experts={E} not divisible by ep={ep}")
    C = compute_capacity(capacity_factor, k, T, E)

    logits = jnp.dot(x, gate_w, preferred_element_type=jnp.float32)
    if jitter_noise is not None:
        key, eps = jitter_noise
        logits = logits * jax.random.uniform(key, logits.shape,
                                             minval=1.0 - eps,
                                             maxval=1.0 + eps)
    gates = jax.nn.softmax(logits, axis=-1)
    combine, dispatch, aux = gshard_dispatch(gates, k, C)
    combine = combine.astype(x.dtype)

    # dispatch: (T, E, C) × (T, d) → (E, C, d)
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)

    if axis_name and ep > 1:
        # send expert-slabs to their owners; receive my experts' tokens from
        # every rank: (E, C, d) → (E/ep, ep*C, d)
        expert_in = jax.lax.all_to_all(expert_in, axis_name,
                                       split_axis=0, concat_axis=1,
                                       tiled=True)
        expert_out = _expert_ffn(expert_in, expert_params, activation)
        expert_out = jax.lax.all_to_all(expert_out, axis_name,
                                        split_axis=1, concat_axis=0,
                                        tiled=True)
    else:
        expert_out = _expert_ffn(expert_in, expert_params, activation)

    out = jnp.einsum("ecd,tec->td", expert_out, combine)
    return out, aux


def init_moe_experts(key, num_experts_local, d_model, d_hidden,
                     dtype=jnp.float32):
    """Stacked FFN expert params: dict of (E_local, ...) arrays."""
    k1, k2 = jax.random.split(key)
    s1 = 1.0 / np.sqrt(d_model)
    s2 = 1.0 / np.sqrt(d_hidden)
    return {
        "w1": jax.random.uniform(k1, (num_experts_local, d_model, d_hidden),
                                 dtype, -s1, s1),
        "b1": jnp.zeros((num_experts_local, d_hidden), dtype),
        "w2": jax.random.uniform(k2, (num_experts_local, d_hidden, d_model),
                                 dtype, -s2, s2),
        "b2": jnp.zeros((num_experts_local, d_model), dtype),
    }
