"""MoE-aware global-norm gradient clip.

Reference: python/paddle/incubate/distributed/models/moe/grad_clip.py
ClipGradForMOEByGlobalNorm — the global norm must count every expert's
gradient exactly once: expert params live only on their owning ep rank,
so their squared norms are all-reduced over the moe group and added to
the (replicated) non-expert norm before the clip ratio is computed.

TPU-native: eager single-controller by default (expert stacks live in one
process); when a moe group / live 'ep' axis exists the expert norm rides
`paddle.distributed.all_reduce` (a cached compiled world/axis program).
"""
import jax.numpy as jnp

from ....core.tensor import Tensor
from ....nn.clip import ClipGradBase

__all__ = ["ClipGradForMOEByGlobalNorm"]


class ClipGradForMOEByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, is_expert_param_func=None, moe_group=None,
                 group_name="default_moe_group"):
        self.clip_norm = float(clip_norm)
        self.moe_group = moe_group
        if moe_group is not None and getattr(moe_group, "nranks", 1) > 1 \
                and is_expert_param_func is None:
            raise AssertionError(
                "is_expert_param_func is required when moe_group spans "
                "multiple ranks")
        self.is_expert_param_func = is_expert_param_func
        self.group_name = group_name

    def _is_expert(self, p):
        if self.is_expert_param_func is not None:
            return bool(self.is_expert_param_func(p))
        return bool(getattr(p, "is_expert", False))

    def __call__(self, params_grads):
        normal_sq, expert_sq = [], []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                continue
            sq = jnp.sum(g._data.astype(jnp.float32) ** 2)
            (expert_sq if self._is_expert(p) else normal_sq).append(sq)
        if not normal_sq and not expert_sq:
            return params_grads

        norm_sq = sum(normal_sq) if normal_sq else jnp.zeros((), jnp.float32)
        if expert_sq:
            e = sum(expert_sq)
            if self.moe_group is not None and \
                    getattr(self.moe_group, "nranks", 1) > 1:
                from ....distributed import collective
                t = Tensor(e)
                collective.all_reduce(t, group=self.moe_group)
                e = t._data
            norm_sq = norm_sq + e

        global_norm = jnp.sqrt(norm_sq)
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
                continue
            out.append((p, Tensor((g._data * scale).astype(g._data.dtype))))
        return out
