"""Eager MoELayer.

Reference: python/paddle/incubate/distributed/models/moe/moe_layer.py
(MoELayer: gate → global_scatter → experts → global_gather → combine).
Here the whole routed FFN is one `apply_op` over the functional core, so
it records a single tape node eagerly and traces into one fused XLA
region under jit. Expert parallelism (ep > 1) is the SPMD path: use
`functional.moe_forward(axis_name="ep")` inside a shard_map — eager mode
keeps all experts local, like the reference with mp_group=None.
"""
import jax
import numpy as np

from ....core.tensor import apply_op
from ....nn.layer.layers import Layer
from ....nn.initializer import Uniform
from .functional import moe_forward
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate

_GATES = {"gshard": GShardGate, "naive": NaiveGate, "switch": SwitchGate}


class MoELayer(Layer):
    """Mixture-of-experts FFN with stacked expert weights.

    Args:
        d_model: token width.
        d_hidden: expert FFN hidden width.
        num_experts: number of experts (global).
        gate: "gshard" | "switch" | "naive" or a BaseGate instance.
        capacity_factor: per-expert buffer slack.

    `forward` returns the routed output; the load-balancing auxiliary loss
    of the latest forward is kept in `self.aux_loss` (a Tensor wired into
    the tape — add `layer.aux_loss * coeff` to the training loss).
    """

    def __init__(self, d_model, d_hidden, num_experts, gate="gshard",
                 capacity_factor=None, activation=jax.nn.gelu):
        super().__init__()
        self.d_model = d_model
        self.d_hidden = d_hidden
        self.num_experts = num_experts
        self.activation = activation
        if isinstance(gate, BaseGate):
            self.gate = gate
            if capacity_factor is not None:
                self.gate.capacity_factor = capacity_factor
        else:
            self.gate = _GATES[gate](d_model, num_experts,
                                     capacity_factor
                                     if capacity_factor is not None else 1.2)

        s1 = 1.0 / np.sqrt(d_model)
        s2 = 1.0 / np.sqrt(d_hidden)
        self.w1 = self.create_parameter((num_experts, d_model, d_hidden),
                                        default_initializer=Uniform(-s1, s1))
        self.b1 = self.create_parameter((num_experts, d_hidden), is_bias=True)
        self.w2 = self.create_parameter((num_experts, d_hidden, d_model),
                                        default_initializer=Uniform(-s2, s2))
        self.b2 = self.create_parameter((num_experts, d_model), is_bias=True)
        self.aux_loss = None

    def forward(self, x):
        k = self.gate.top_k
        cf = self.gate.capacity_factor
        act = self.activation
        jitter = None
        if self.training and self.gate.jitter_eps > 0:
            from ....core.random import next_key
            jitter = (next_key(), self.gate.jitter_eps)

        def fn(xd, gw, w1, b1, w2, b2):
            t = xd.reshape(-1, xd.shape[-1])
            out, aux = moe_forward(
                t, gw, {"w1": w1, "b1": b1, "w2": w2, "b2": b2},
                k=k, capacity_factor=cf, activation=act,
                jitter_noise=jitter)
            return out.reshape(xd.shape), aux

        out, aux = apply_op(fn, x, self.gate.weight, self.w1, self.b1,
                            self.w2, self.b2, name="moe")
        self.aux_loss = aux if self.gate.has_aux_loss else aux * 0.0
        return out

    def extra_repr(self):
        return (f"d_model={self.d_model}, d_hidden={self.d_hidden}, "
                f"num_experts={self.num_experts}, "
                f"gate={type(self.gate).__name__}")
