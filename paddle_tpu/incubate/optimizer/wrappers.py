"""LookAhead and ModelAverage optimizer wrappers (reference:
python/paddle/incubate/optimizer/lookahead.py, modelaverage.py)."""
import jax.numpy as jnp

from ...core.tensor import Tensor


class LookAhead:
    """k-step lookahead (Zhang et al. 2019; reference lookahead.py:33):
    the inner ("fast") optimizer steps normally; every k steps the slow
    weights move alpha of the way toward the fast weights and the fast
    weights reset to the slow ones."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._count = 0
        self._slow = {}

    def __getattr__(self, item):
        return getattr(self.inner_optimizer, item)

    def step(self):
        if not self._slow:
            # slow weights start from the INITIAL parameters (reference
            # lookahead.py seeds them in the startup program), so the
            # first window already pulls back toward the starting point
            for p in self.inner_optimizer._parameters:
                self._slow[id(p)] = p._data
        self.inner_optimizer.step()
        self._count += 1
        if self._count % self.k:
            return
        for p in self.inner_optimizer._parameters:
            slow = self._slow.get(id(p))
            if slow is None:
                slow = p._data
            slow = slow + self.alpha * (p._data - slow)
            self._slow[id(p)] = slow
            p._data = slow
            p._version += 1

    def clear_grad(self, set_to_zero=True):
        self.inner_optimizer.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()

    def state_dict(self):
        out = dict(self.inner_optimizer.state_dict())
        out["LookAhead"] = {"count": self._count,
                            "slow": {str(i): Tensor(self._slow[id(p)])
                                     for i, p in enumerate(
                                         self.inner_optimizer._parameters)
                                     if id(p) in self._slow}}
        return out

    def set_state_dict(self, state_dict):
        state_dict = dict(state_dict)
        la = state_dict.pop("LookAhead", None)
        self.inner_optimizer.set_state_dict(state_dict)
        if la:
            self._count = int(la.get("count", 0))
            params = list(self.inner_optimizer._parameters)
            for i_str, v in la.get("slow", {}).items():
                i = int(i_str)
                if i < len(params):
                    self._slow[id(params[i])] = (
                        v._data if isinstance(v, Tensor) else jnp.asarray(v))


class ModelAverage:
    """Running average of parameters over training (reference
    modelaverage.py:29: sum_1/sum_2/sum_3 windowed accumulators condensed
    to one running sum + count, same average within a window), with
    apply()/restore() swapping like the reference."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self._params = list(parameters or [])
        self._rate = float(average_window_rate)
        self._min_w = int(min_average_window)
        self._max_w = int(max_average_window)
        self._sum = {}
        self._cnt = 0
        self._backup = {}

    def step(self):
        """Accumulate after each optimizer step."""
        self._cnt += 1
        if self._cnt > self._max_w:
            # restart window (reference rolls sum_1/2/3)
            self._sum = {id(p): jnp.zeros_like(p._data)
                         for p in self._params}
            self._cnt = 1
        for p in self._params:
            s = self._sum.get(id(p))
            self._sum[id(p)] = p._data if s is None else s + p._data

    update = step

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            self._backup = {id(p): p._data for p in self._params}
            n = max(self._cnt, 1)
            for p in self._params:
                if id(p) in self._sum:
                    p._data = (self._sum[id(p)] / n).astype(p._data.dtype)
                    p._version += 1
            try:
                yield self
            finally:
                if need_restore:
                    self.restore()
        return ctx()

    def restore(self, executor=None):
        for p in self._params:
            if id(p) in self._backup:
                p._data = self._backup[id(p)]
                p._version += 1
        self._backup = {}
