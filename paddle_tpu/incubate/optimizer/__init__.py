"""paddle.incubate.optimizer — functional optimizers."""
from . import functional  # noqa: F401
