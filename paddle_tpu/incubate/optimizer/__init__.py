"""paddle.incubate.optimizer — functional optimizers."""
from . import functional  # noqa: F401
from .wrappers import LookAhead, ModelAverage  # noqa: F401
