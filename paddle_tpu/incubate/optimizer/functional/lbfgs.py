"""L-BFGS / BFGS minimizers.

Reference: python/paddle/incubate/optimizer/functional/lbfgs.py
(`minimize_lbfgs` — static-graph while_loop over the two-loop recursion
with strong-Wolfe line search).

TPU-native: the two-loop recursion in plain Python over jnp arrays with a
backtracking Armijo line search; the objective is differentiated with
jax.grad (no finite differences). History is a fixed-size deque so the
whole minimize can also run under jit for fixed iteration counts.
"""
from collections import deque, namedtuple

import jax
import jax.numpy as jnp

from ....core.tensor import Tensor

LbfgsResult = namedtuple("LbfgsResult",
                         ["is_converge", "num_func_calls", "x", "fx", "g"])


def _wrap_objective(objective_func):
    def f(x):
        out = objective_func(Tensor(x))
        return out._data if isinstance(out, Tensor) else out
    return f


def _line_search(f, x, fx, g, p, max_steps=20, c1=1e-4, tau=0.5):
    """Backtracking Armijo: returns (alpha, n_evals)."""
    alpha = 1.0
    gtp = jnp.vdot(g, p)
    n = 0
    for _ in range(max_steps):
        n += 1
        if f(x + alpha * p) <= fx + c1 * alpha * gtp:
            break
        alpha *= tau
    return alpha, n


def minimize_lbfgs(objective_func, initial_position, history_size=10,
                   max_iters=50, tolerance_grad=1e-8, tolerance_change=1e-9,
                   initial_inverse_hessian_estimate=None, line_search_fn=
                   "strong_wolfe", dtype="float32", name=None):
    """Returns (is_converge, num_func_calls, position, objective, gradient)
    — the reference's result tuple."""
    f = _wrap_objective(objective_func)
    grad_f = jax.grad(f)
    x = jnp.asarray(initial_position._data
                    if isinstance(initial_position, Tensor)
                    else initial_position, jnp.float32)
    fx = f(x)
    g = grad_f(x)
    calls = 1
    s_hist, y_hist, rho_hist = deque(maxlen=history_size), \
        deque(maxlen=history_size), deque(maxlen=history_size)
    converged = False

    for _ in range(max_iters):
        if jnp.max(jnp.abs(g)) < tolerance_grad:
            converged = True
            break
        # two-loop recursion
        q = g
        alphas = []
        for s, y, rho in zip(reversed(s_hist), reversed(y_hist),
                             reversed(rho_hist)):
            a = rho * jnp.vdot(s, q)
            alphas.append(a)
            q = q - a * y
        if y_hist:
            gamma = jnp.vdot(s_hist[-1], y_hist[-1]) / \
                jnp.maximum(jnp.vdot(y_hist[-1], y_hist[-1]), 1e-12)
        else:
            gamma = 1.0
        r = gamma * q
        for (s, y, rho), a in zip(zip(s_hist, y_hist, rho_hist),
                                  reversed(alphas)):
            b = rho * jnp.vdot(y, r)
            r = r + (a - b) * s
        p = -r

        alpha, n = _line_search(f, x, fx, g, p)
        calls += n
        x_new = x + alpha * p
        fx_new = f(x_new)
        g_new = grad_f(x_new)
        calls += 1
        s = x_new - x
        y = g_new - g
        sy = jnp.vdot(s, y)
        if sy > 1e-10:          # curvature condition
            s_hist.append(s)
            y_hist.append(y)
            rho_hist.append(1.0 / sy)
        if jnp.max(jnp.abs(s)) < tolerance_change:
            x, fx, g = x_new, fx_new, g_new
            converged = True
            break
        x, fx, g = x_new, fx_new, g_new

    return LbfgsResult(Tensor(jnp.asarray(converged)),
                       Tensor(jnp.asarray(calls)),
                       Tensor(x), Tensor(fx), Tensor(g))


def minimize_bfgs(objective_func, initial_position, max_iters=50,
                  tolerance_grad=1e-8, tolerance_change=1e-9,
                  initial_inverse_hessian_estimate=None,
                  line_search_fn="strong_wolfe", dtype="float32", name=None):
    """Dense-Hessian BFGS (reference bfgs.py) — same surface, full H."""
    f = _wrap_objective(objective_func)
    grad_f = jax.grad(f)
    x = jnp.asarray(initial_position._data
                    if isinstance(initial_position, Tensor)
                    else initial_position, jnp.float32)
    n_dim = x.size
    H = jnp.eye(n_dim) if initial_inverse_hessian_estimate is None else \
        jnp.asarray(initial_inverse_hessian_estimate._data
                    if isinstance(initial_inverse_hessian_estimate, Tensor)
                    else initial_inverse_hessian_estimate)
    fx = f(x)
    g = grad_f(x)
    calls = 1
    converged = False
    for _ in range(max_iters):
        if jnp.max(jnp.abs(g)) < tolerance_grad:
            converged = True
            break
        p = -(H @ g.reshape(-1)).reshape(x.shape)
        alpha, n = _line_search(f, x, fx, g, p)
        calls += n
        x_new = x + alpha * p
        g_new = grad_f(x_new)
        fx = f(x_new)
        calls += 1
        s = (x_new - x).reshape(-1)
        y = (g_new - g).reshape(-1)
        sy = jnp.vdot(s, y)
        if sy > 1e-10:
            rho = 1.0 / sy
            I = jnp.eye(n_dim)
            V = I - rho * jnp.outer(s, y)
            H = V @ H @ V.T + rho * jnp.outer(s, s)
        if jnp.max(jnp.abs(x_new - x)) < tolerance_change:
            x, g = x_new, g_new
            converged = True
            break
        x, g = x_new, g_new
    return LbfgsResult(Tensor(jnp.asarray(converged)),
                       Tensor(jnp.asarray(calls)), Tensor(x), Tensor(fx),
                       Tensor(g))
