"""Functional optimizers (reference: python/paddle/incubate/optimizer/
functional/lbfgs.py minimize_lbfgs, bfgs.py minimize_bfgs)."""
from .lbfgs import minimize_bfgs, minimize_lbfgs  # noqa: F401
