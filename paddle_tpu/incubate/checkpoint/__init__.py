"""Training auto-checkpoint — epoch-granular save/resume.

Reference: python/paddle/incubate/checkpoint/auto_checkpoint.py (+
checkpoint_saver.py): Fleet jobs wrap their epoch loop in
`train_epoch_range`, which transparently restores the last completed epoch
from HDFS and saves on each epoch boundary, keyed by a job id.

TPU-native: same contract over the local/posix filesystem (the reference's
fs.py HDFS abstraction collapses to a directory); tensors ride
paddle.save/paddle.load.

Crash safety (ISSUE 5): each epoch saves into its own
`<dir>/epoch-<n>/` through the shared atomic-commit protocol
(framework/ckpt_commit.py) — the `epoch_no` travels in the commit
manifest's metadata, `LATEST` updates only after the rename, and stale
epoch dirs are deleted only AFTER the new one committed (retention
`keep`, default 2, so the previous epoch stays available as the
fallback). A SIGKILL mid-save leaves the prior epoch's checkpoint
intact and resumable; a torn dir never resumes.
"""
import json
import os

from ...framework import ckpt_commit as _commit

__all__ = ["train_epoch_range", "ExeTrainStatus"]

_CKPT_DIR_ENV = "PADDLE_CHECKPOINT_DIR"


class ExeTrainStatus:
    """Tracks (epoch_no, checkpoint paths) for one named training run."""

    def __init__(self, name="auto", save_dir=None, keep=2):
        self.name = name
        self.save_dir = save_dir or os.environ.get(_CKPT_DIR_ENV,
                                                   "./auto_checkpoint")
        self._dir = os.path.join(self.save_dir, name)
        self._meta = os.path.join(self._dir, "status.json")  # legacy mirror
        self._keep = max(int(keep), 1)
        self._resolved = None     # (path, epoch_no) cache for restore()

    def _current(self):
        """(path, epoch_no) of the newest VALID epoch checkpoint, or
        (None, -1). Prefers LATEST; falls back to the newest sibling
        that verifies (the torn-save recovery path). The result is
        cached for the restore() that typically follows last_epoch(), so
        resume verifies the (possibly multi-GB) digests ONCE."""
        candidate, _ = _commit.resolve_valid(self._dir)
        if candidate is not None:
            manifest = _commit.read_manifest(candidate) or {}
            self._resolved = (candidate, int(manifest.get("meta", {})
                                             .get("epoch_no", -1)))
        else:
            self._resolved = (None, -1)
        return self._resolved

    def last_epoch(self):
        path, epoch_no = self._current()
        if path is not None:
            return epoch_no
        # commit artifacts exist but NONE verify: resuming "fresh" here
        # would silently train on uninitialized weights — be loud instead
        if _commit.has_commits(self._dir):
            raise _commit.CheckpointCorruptError(
                f"{self._dir}: epoch checkpoints exist but none verify")
        # legacy flat layout (pre-commit-protocol jobs)
        if os.path.exists(self._meta):
            with open(self._meta) as f:
                return json.load(f).get("epoch_no", -1)
        return -1

    def save(self, epoch_no, layers=None, optimizers=None):
        from ...framework.io import save as _save
        target = os.path.join(self._dir, f"epoch-{int(epoch_no):08d}")
        with _commit.atomic_commit(
                target, extra_meta={"epoch_no": int(epoch_no)}) as tmp:
            for i, layer in enumerate(layers or []):
                _save(layer.state_dict(),
                      os.path.join(tmp, f"layer_{i}.pdparams"))
            for i, opt in enumerate(optimizers or []):
                _save(opt.state_dict(), os.path.join(tmp, f"opt_{i}.pdopt"))
        base = os.path.basename(target)
        self._resolved = None         # state changed: resolve fresh
        _commit.update_latest(self._dir, base)
        # stale epoch dirs go ONLY after the new one is committed and
        # LATEST moved — a crash anywhere above keeps the previous epoch
        _commit.gc_old(self._dir, self._keep, protect={base},
                       same_lineage_as=base)
        tmp_meta = self._meta + ".tmp"
        with open(tmp_meta, "w") as f:
            json.dump({"epoch_no": int(epoch_no)}, f)
        os.replace(tmp_meta, self._meta)  # legacy readers keep working

    def restore(self, layers=None, optimizers=None):
        from ...framework.io import load as _load
        path, _ = self._resolved if self._resolved is not None \
            else self._current()
        self._resolved = None         # one-shot: next resolve is fresh
        if path is None:
            if _commit.has_commits(self._dir):
                raise _commit.CheckpointCorruptError(
                    f"{self._dir}: epoch checkpoints exist but none verify")
            path = self._dir          # legacy flat layout
        for i, layer in enumerate(layers or []):
            p = os.path.join(path, f"layer_{i}.pdparams")
            if os.path.exists(p):
                layer.set_state_dict(_load(p))
        for i, opt in enumerate(optimizers or []):
            p = os.path.join(path, f"opt_{i}.pdopt")
            if os.path.exists(p):
                opt.set_state_dict(_load(p))


def train_epoch_range(max_epoch_num, name="auto", save_dir=None,
                      layers=None, optimizers=None, save_checkpoint_inter=1,
                      keep=2):
    """Resumable epoch generator:

        for epoch in train_epoch_range(10, layers=[net], optimizers=[opt]):
            train_one_epoch(...)

    On restart, already-completed epochs are skipped and layer/optimizer
    state is restored from the last VALID checkpoint (torn saves are
    skipped). `keep` epochs of history are retained."""
    status = ExeTrainStatus(name, save_dir, keep=keep)
    start = status.last_epoch() + 1
    if start > 0:
        status.restore(layers, optimizers)
    for epoch in range(start, max_epoch_num):
        yield epoch
        if (epoch + 1) % save_checkpoint_inter == 0 or \
                epoch == max_epoch_num - 1:
            status.save(epoch, layers, optimizers)
