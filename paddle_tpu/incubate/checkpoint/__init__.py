"""Training auto-checkpoint — epoch-granular save/resume.

Reference: python/paddle/incubate/checkpoint/auto_checkpoint.py (+
checkpoint_saver.py): Fleet jobs wrap their epoch loop in
`train_epoch_range`, which transparently restores the last completed epoch
from HDFS and saves on each epoch boundary, keyed by a job id.

TPU-native: same contract over the local/posix filesystem (the reference's
fs.py HDFS abstraction collapses to a directory); tensors ride
paddle.save/paddle.load.
"""
import json
import os

__all__ = ["train_epoch_range", "ExeTrainStatus"]

_CKPT_DIR_ENV = "PADDLE_CHECKPOINT_DIR"


class ExeTrainStatus:
    """Tracks (epoch_no, checkpoint paths) for one named training run."""

    def __init__(self, name="auto", save_dir=None):
        self.name = name
        self.save_dir = save_dir or os.environ.get(_CKPT_DIR_ENV,
                                                   "./auto_checkpoint")
        self._dir = os.path.join(self.save_dir, name)
        self._meta = os.path.join(self._dir, "status.json")

    def last_epoch(self):
        if not os.path.exists(self._meta):
            return -1
        with open(self._meta) as f:
            return json.load(f).get("epoch_no", -1)

    def save(self, epoch_no, layers=None, optimizers=None):
        from ...framework.io import save as _save
        os.makedirs(self._dir, exist_ok=True)
        for i, layer in enumerate(layers or []):
            _save(layer.state_dict(), os.path.join(self._dir,
                                                   f"layer_{i}.pdparams"))
        for i, opt in enumerate(optimizers or []):
            _save(opt.state_dict(), os.path.join(self._dir,
                                                 f"opt_{i}.pdopt"))
        tmp = self._meta + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"epoch_no": epoch_no}, f)
        os.replace(tmp, self._meta)  # atomic: a crash never corrupts status

    def restore(self, layers=None, optimizers=None):
        from ...framework.io import load as _load
        for i, layer in enumerate(layers or []):
            p = os.path.join(self._dir, f"layer_{i}.pdparams")
            if os.path.exists(p):
                layer.set_state_dict(_load(p))
        for i, opt in enumerate(optimizers or []):
            p = os.path.join(self._dir, f"opt_{i}.pdopt")
            if os.path.exists(p):
                opt.set_state_dict(_load(p))


def train_epoch_range(max_epoch_num, name="auto", save_dir=None,
                      layers=None, optimizers=None, save_checkpoint_inter=1):
    """Resumable epoch generator:

        for epoch in train_epoch_range(10, layers=[net], optimizers=[opt]):
            train_one_epoch(...)

    On restart, already-completed epochs are skipped and layer/optimizer
    state is restored from the last checkpoint."""
    status = ExeTrainStatus(name, save_dir)
    start = status.last_epoch() + 1
    if start > 0:
        status.restore(layers, optimizers)
    for epoch in range(start, max_epoch_num):
        yield epoch
        if (epoch + 1) % save_checkpoint_inter == 0 or \
                epoch == max_epoch_num - 1:
            status.save(epoch, layers, optimizers)
