"""incubate operators (reference: python/paddle/incubate/operators/):
graph sampling/reindex, fused softmax-mask, segment reductions re-exported
at the incubate level, identity_loss.

Graph ops are eager/host-side by design in the reference too (they drive
GNN minibatch construction, not device compute); sampling runs in numpy,
the gathered tensors go to the device afterwards.
"""
import numpy as np

import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op
from ..geometric import (segment_max, segment_mean,  # noqa: F401
                         segment_min, segment_sum)


def _np(x):
    return np.asarray(x._data if isinstance(x, Tensor) else x)


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    """reference: incubate/operators/graph_send_recv.py — gather x rows at
    src_index, reduce into dst_index slots."""
    from ..geometric import send_u_recv
    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size)


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1,
                           return_eids=False, flag_perm_buffer=False,
                           name=None):
    """Uniform neighbor sampling over a CSC graph (reference:
    graph_sample_neighbors.py; kernel phi/kernels/gpu/
    graph_sample_neighbors_kernel.cu). Host-side numpy sampling.

    When `row` is a distributed graph handle — a
    `distributed.ps.DistGraphClient` over sharded graph servers, or a local
    `distributed.ps.GraphTable` shard — sampling is served by the graph
    store (`colptr` is ignored; pass None)."""
    if hasattr(row, "sample_neighbors") and not isinstance(row, Tensor):
        if return_eids:
            raise ValueError(
                "return_eids is not supported on the distributed GraphTable "
                "path: edge ids are not tracked by the sharded store")
        nb, cnt = row.sample_neighbors(input_nodes, sample_size=sample_size)
        return (Tensor(jnp.asarray(np.ascontiguousarray(nb, np.int64))),
                Tensor(jnp.asarray(np.ascontiguousarray(cnt, np.int32))))
    rown, colp, nodes = _np(row), _np(colptr), _np(input_nodes).reshape(-1)
    # np.random's GLOBAL stream: each call draws a fresh sample and
    # np.random.seed / paddle.seed-driven pipelines stay reproducible
    rng = np.random
    out_nb, out_cnt, out_eids = [], [], []
    eid = _np(eids) if eids is not None else None
    for n in nodes:
        beg, end = int(colp[n]), int(colp[n + 1])
        neigh = rown[beg:end]
        ids = np.arange(beg, end)
        if sample_size > 0 and len(neigh) > sample_size:
            pick = rng.choice(len(neigh), sample_size, replace=False)
            neigh = neigh[pick]
            ids = ids[pick]
        out_nb.append(neigh)
        out_cnt.append(len(neigh))
        if return_eids and eid is not None:
            out_eids.append(eid[ids])
    neighbors = Tensor(jnp.asarray(np.concatenate(out_nb)
                                   if out_nb else np.zeros(0, rown.dtype)))
    counts = Tensor(jnp.asarray(np.asarray(out_cnt, np.int32)))
    if return_eids:
        e = Tensor(jnp.asarray(np.concatenate(out_eids)
                               if out_eids else np.zeros(0, np.int64)))
        return neighbors, counts, e
    return neighbors, counts


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """K-hop sampling = repeated neighbor sampling with frontier growth
    (reference: graph_khop_sampler.py). Returns (edge_src, edge_dst,
    sample_index, reindex_x) like the reference."""
    nodes = _np(input_nodes).reshape(-1)
    all_src, all_dst = [], []
    frontier = nodes
    seen = list(nodes)
    for k in sample_sizes:
        nb, cnt = graph_sample_neighbors(row, colptr,
                                         Tensor(jnp.asarray(frontier)),
                                         sample_size=int(k))
        nbn, cntn = _np(nb), _np(cnt)
        dst = np.repeat(frontier, cntn)
        all_src.append(nbn)
        all_dst.append(dst)
        frontier = np.unique(nbn)
        seen.extend(frontier.tolist())
    src = np.concatenate(all_src) if all_src else np.zeros(0, np.int64)
    dst = np.concatenate(all_dst) if all_dst else np.zeros(0, np.int64)
    uniq = np.asarray(sorted(set(seen)), dtype=src.dtype if src.size
                      else np.int64)
    remap = {int(v): i for i, v in enumerate(uniq)}
    src_r = np.asarray([remap[int(s)] for s in src], np.int64)
    dst_r = np.asarray([remap[int(d)] for d in dst], np.int64)
    return (Tensor(jnp.asarray(src_r)), Tensor(jnp.asarray(dst_r)),
            Tensor(jnp.asarray(uniq)),
            Tensor(jnp.asarray(np.asarray([remap[int(n)] for n in nodes],
                                          np.int64))))


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    """reference: graph_reindex.py — contiguous reindex of (x ∪ neighbors).
    Returns (reindex_src, reindex_dst, out_nodes)."""
    xs, nb, cnt = _np(x).reshape(-1), _np(neighbors), _np(count)
    out_nodes, remap = [], {}
    for v in np.concatenate([xs, nb]):
        if int(v) not in remap:
            remap[int(v)] = len(out_nodes)
            out_nodes.append(int(v))
    reindex_src = np.asarray([remap[int(v)] for v in nb], np.int64)
    dst = np.repeat(xs, cnt[:len(xs)])
    reindex_dst = np.asarray([remap[int(v)] for v in dst], np.int64)
    return (Tensor(jnp.asarray(reindex_src)),
            Tensor(jnp.asarray(reindex_dst)),
            Tensor(jnp.asarray(np.asarray(out_nodes, np.int64))))


def softmax_mask_fuse(x, mask, name=None):
    """reference: incubate/operators/softmax_mask_fuse.py (CUDA fused
    kernel fused_softmax_mask op): softmax(x + mask) — one XLA fusion."""
    import jax
    return apply_op(lambda a, m: jax.nn.softmax(a + m, axis=-1), x, mask)


def softmax_mask_fuse_upper_triangle(x, name=None):
    """reference: softmax_mask_fuse_upper_triangle.py — causal-masked
    softmax over the last two dims (scores masked above the diagonal)."""
    def fn(a):
        S = a.shape[-1]
        row = jnp.arange(a.shape[-2])[:, None]
        col = jnp.arange(S)[None]
        masked = jnp.where(row >= col, a, -1e9)
        import jax
        return jax.nn.softmax(masked, axis=-1)
    return apply_op(fn, x)


def identity_loss(x, reduction="none"):
    """reference: incubate identity_loss op (IPU training marker): returns
    x reduced — the graph identity that marks a loss output."""
    red = {0: "sum", 1: "mean", 2: "none"}.get(reduction, reduction)
    if red == "mean":
        return apply_op(jnp.mean, x)
    if red == "sum":
        return apply_op(jnp.sum, x)
    return x
