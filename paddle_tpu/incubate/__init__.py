"""paddle.incubate equivalent (reference: python/paddle/incubate)."""
from . import autotune  # noqa: F401
from . import distributed  # noqa: F401
from . import nn  # noqa: F401
from . import asp  # noqa: F401
from . import checkpoint  # noqa: F401
from . import optimizer  # noqa: F401
