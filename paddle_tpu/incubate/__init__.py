"""paddle.incubate equivalent (reference: python/paddle/incubate)."""
from . import autotune  # noqa: F401
from . import distributed  # noqa: F401
from . import nn  # noqa: F401
from . import asp  # noqa: F401
from . import checkpoint  # noqa: F401
from . import optimizer  # noqa: F401
from . import operators  # noqa: F401
from . import autograd  # noqa: F401
from .operators import (  # noqa: F401
    graph_khop_sampler, graph_reindex, graph_sample_neighbors,
    graph_send_recv, identity_loss, segment_max, segment_mean, segment_min,
    segment_sum, softmax_mask_fuse, softmax_mask_fuse_upper_triangle)
from .optimizer import LookAhead, ModelAverage  # noqa: F401
