"""incubate.autograd (reference: python/paddle/incubate/autograd:
functional vjp/jvp/Jacobian/Hessian + the prim-op switches)."""
import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor

__all__ = ["vjp", "jvp", "Jacobian", "Hessian", "enable_prim",
           "disable_prim", "forward_grad", "grad"]

_prim_enabled = False


def enable_prim():
    """reference: primx prim-op switch. The whole framework already traces
    to primitive HLO ops, so this is a recorded toggle."""
    global _prim_enabled
    _prim_enabled = True


def disable_prim():
    global _prim_enabled
    _prim_enabled = False


def prim_enabled():
    return _prim_enabled


def _raw_fn(func):
    def raw(*datas):
        ts = [Tensor(d) for d in datas]
        out = func(*ts)
        outs = out if isinstance(out, (tuple, list)) else (out,)
        res = tuple(o._data if isinstance(o, Tensor) else o for o in outs)
        return res if len(res) > 1 else res[0]
    return raw


def _datas(xs):
    xs = xs if isinstance(xs, (tuple, list)) else [xs]
    return [x._data if isinstance(x, Tensor) else jnp.asarray(x)
            for x in xs]


def vjp(func, xs, v=None):
    """reference: incubate/autograd/functional.py vjp -> (outputs,
    vjp_result)."""
    datas = _datas(xs)
    out, pull = jax.vjp(_raw_fn(func), *datas)
    if v is None:
        cot = jnp.ones_like(out) if not isinstance(out, tuple) else \
            tuple(jnp.ones_like(o) for o in out)
    else:
        vd = _datas(v)
        cot = vd[0] if len(vd) == 1 and not isinstance(out, tuple) \
            else tuple(vd)
    grads = pull(cot)
    outs = Tensor(out) if not isinstance(out, tuple) else \
        [Tensor(o) for o in out]
    gs = [Tensor(g) for g in grads]
    return outs, (gs if len(gs) > 1 else gs[0])


def jvp(func, xs, v=None):
    """reference: functional.py jvp -> (outputs, jvp_result)."""
    datas = _datas(xs)
    tangents = _datas(v) if v is not None else \
        [jnp.ones_like(d) for d in datas]
    out, tang = jax.jvp(_raw_fn(func), tuple(datas), tuple(tangents))
    outs = Tensor(out) if not isinstance(out, tuple) else \
        [Tensor(o) for o in out]
    tg = Tensor(tang) if not isinstance(tang, tuple) else \
        [Tensor(t) for t in tang]
    return outs, tg


def forward_grad(outputs, inputs, grad_inputs=None):
    """Forward-mode grads of recorded eager outputs are not derivable from
    a reverse tape; use incubate.autograd.jvp(func, xs) with the function
    form (the reference's primal-transform path has the same
    function-level requirement)."""
    raise RuntimeError(
        "forward_grad needs the function form: use "
        "paddle.incubate.autograd.jvp(func, xs, v)")


def grad(outputs, inputs, grad_outputs=None):
    """reference: incubate/autograd grad — alias of paddle.grad."""
    import paddle_tpu
    return paddle_tpu.grad(outputs, inputs, grad_outputs)


class Jacobian:
    """Lazy Jacobian matrix (reference: incubate/autograd/functional.py
    Jacobian): J[i, j] = d out_i / d in_j, computed via jax.jacrev."""

    def __init__(self, func, xs, is_batched=False):
        self._datas = _datas(xs)
        self._J = jax.jacrev(_raw_fn(func),
                             argnums=tuple(range(len(self._datas))))(
            *self._datas)
        if isinstance(self._J, tuple) and len(self._datas) == 1:
            self._J = self._J[0]
        self._batched = is_batched

    def __getitem__(self, idx):
        arr = self._J
        if isinstance(arr, tuple):
            arr = jnp.concatenate(
                [a.reshape(a.shape[0], -1) for a in arr], axis=-1)
        else:
            arr = arr.reshape(arr.shape[0], -1) if arr.ndim > 2 else arr
        return Tensor(arr[idx])

    @property
    def shape(self):
        arr = self._J
        if isinstance(arr, tuple):
            return [int(arr[0].shape[0]),
                    sum(int(np.prod(a.shape[1:])) for a in arr)]
        return list(arr.shape)


class Hessian:
    """Lazy Hessian (reference: functional.py Hessian): H = d^2 f / dx^2
    for scalar-output f, via jax.hessian."""

    def __init__(self, func, xs, is_batched=False):
        self._datas = _datas(xs)
        self._H = jax.hessian(_raw_fn(func))(*self._datas)

    def __getitem__(self, idx):
        arr = self._H
        n = int(np.prod(self._datas[0].shape))
        return Tensor(jnp.reshape(arr, (n, n))[idx])

    @property
    def shape(self):
        n = int(np.prod(self._datas[0].shape))
        return [n, n]
