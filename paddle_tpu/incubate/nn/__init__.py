"""incubate.nn: Fused transformer layers (reference:
python/paddle/incubate/nn/layer/fused_transformer.py backed by
fused_attention_op.cu / fused_feedforward_op.cu).

On TPU the "fusion" is XLA + the Pallas flash-attention kernel, so these are
thin aliases of the standard layers with identical signatures.
"""
from ...nn.layer.transformer import (
    MultiHeadAttention as FusedMultiHeadAttention,
    TransformerEncoderLayer as FusedTransformerEncoderLayer,
)
from ...nn.layer.common import Linear as _Linear
from ...nn.layer.layers import Layer
from ...nn import functional as F


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1, epsilon=1e-05,
                 activation="relu", act_dropout_rate=None, normalize_before=False,
                 linear1_weight_attr=None, linear1_bias_attr=None,
                 linear2_weight_attr=None, linear2_bias_attr=None,
                 ln1_scale_attr=None, ln1_bias_attr=None,
                 ln2_scale_attr=None, ln2_bias_attr=None, name=None):
        super().__init__()
        from ...nn.layer.norm import LayerNorm
        from ...nn.layer.common import Dropout
        self.normalize_before = normalize_before
        self.linear1 = _Linear(d_model, dim_feedforward, linear1_weight_attr,
                               linear1_bias_attr)
        self.linear2 = _Linear(dim_feedforward, d_model, linear2_weight_attr,
                               linear2_bias_attr)
        self.norm = LayerNorm(d_model, epsilon)
        self.dropout = Dropout(dropout_rate)
        self.act_dropout = Dropout(act_dropout_rate if act_dropout_rate is not None
                                   else dropout_rate)
        self.activation = getattr(F, activation)

    def forward(self, src):
        residual = src
        if self.normalize_before:
            src = self.norm(src)
        out = self.linear2(self.act_dropout(self.activation(self.linear1(src))))
        out = residual + self.dropout(out)
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedLinear(_Linear):
    """cublasLt fused_gemm_epilogue equivalent: XLA fuses bias+act into the
    matmul automatically, so plain Linear already is the fused op."""
    pass
