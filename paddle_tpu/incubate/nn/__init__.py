"""incubate.nn: Fused transformer layers (reference:
python/paddle/incubate/nn/layer/fused_transformer.py backed by
fused_attention_op.cu / fused_feedforward_op.cu).

On TPU the "fusion" is XLA + the Pallas flash-attention kernel, so these are
thin aliases of the standard layers with identical signatures.
"""
from ...nn.layer.transformer import (
    MultiHeadAttention as FusedMultiHeadAttention,
    TransformerEncoderLayer as FusedTransformerEncoderLayer,
)
from ...nn.layer.common import Linear as _Linear
from ...nn.layer.layers import Layer
from ...nn import functional as F


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1, epsilon=1e-05,
                 activation="relu", act_dropout_rate=None, normalize_before=False,
                 linear1_weight_attr=None, linear1_bias_attr=None,
                 linear2_weight_attr=None, linear2_bias_attr=None,
                 ln1_scale_attr=None, ln1_bias_attr=None,
                 ln2_scale_attr=None, ln2_bias_attr=None, name=None):
        super().__init__()
        from ...nn.layer.norm import LayerNorm
        from ...nn.layer.common import Dropout
        self.normalize_before = normalize_before
        self.linear1 = _Linear(d_model, dim_feedforward, linear1_weight_attr,
                               linear1_bias_attr)
        self.linear2 = _Linear(dim_feedforward, d_model, linear2_weight_attr,
                               linear2_bias_attr)
        self.norm = LayerNorm(d_model, epsilon)
        self.dropout = Dropout(dropout_rate)
        self.act_dropout = Dropout(act_dropout_rate if act_dropout_rate is not None
                                   else dropout_rate)
        self.activation = getattr(F, activation)

    def forward(self, src):
        residual = src
        if self.normalize_before:
            src = self.norm(src)
        out = self.linear2(self.act_dropout(self.activation(self.linear1(src))))
        out = residual + self.dropout(out)
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedLinear(_Linear):
    """cublasLt fused_gemm_epilogue equivalent: XLA fuses bias+act into the
    matmul automatically, so plain Linear already is the fused op."""
    pass


class FusedBiasDropoutResidualLayerNorm(Layer):
    """reference: incubate/nn/layer/fused_transformer.py:79 (op:
    fused_bias_dropout_residual_layer_norm). out = LN(residual + dropout
    (x + bias)). XLA fuses the chain; the class exists for API parity and
    owns the LN (+ optional bias) parameters."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-05, name=None):
        super().__init__()
        from ...nn.layer.norm import LayerNorm
        from ...nn.layer.common import Dropout
        from ...nn.initializer import Constant
        self.embed_dim = embed_dim
        self.linear_bias = None if bias_attr is False else \
            self.create_parameter((embed_dim,), attr=bias_attr, is_bias=True,
                                  default_initializer=Constant(0.0))
        self.norm = LayerNorm(embed_dim, epsilon, weight_attr, bias_attr)
        self.dropout = Dropout(dropout_rate)

    def forward(self, x, residual):
        if self.linear_bias is not None:
            x = x + self.linear_bias
        return self.norm(residual + self.dropout(x))

    def extra_repr(self):
        return f"embed_dim={self.embed_dim}"


class FusedMultiTransformer(Layer):
    """Inference transformer stack (reference:
    incubate/nn/layer/fused_transformer.py:914 over
    fused_multi_transformer_op.cu): pre-LN attention + FFN per layer, with
    optional per-layer KV caches for autoregressive decode. The CUDA
    mega-kernel's fusion is XLA's job here; attention runs through the
    flash kernel on TPU (ops/flash_attention.py) for full sequences and
    plain dot attention for single-step decode."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu", normalize_before=True,
                 ln_scale_attrs=None, ln_bias_attrs=None,
                 qkv_weight_attrs=None, qkv_bias_attrs=None,
                 linear_weight_attrs=None, linear_bias_attrs=None,
                 ffn_ln_scale_attrs=None, ffn_ln_bias_attrs=None,
                 ffn1_weight_attrs=None, ffn1_bias_attrs=None,
                 ffn2_weight_attrs=None, ffn2_bias_attrs=None,
                 epsilon=1e-5, num_layers=-1, nranks=1, trans_qkvw=True,
                 ring_id=-1, name=None):
        super().__init__()
        if not normalize_before:
            raise ValueError("FusedMultiTransformer only supports "
                             "normalize_before=True (same as the reference)")
        if isinstance(qkv_weight_attrs, (list, tuple)):
            num_layers = len(qkv_weight_attrs)
        if num_layers <= 0:
            raise ValueError("num_layers must be set (or pass per-layer "
                             "attr lists)")
        from ...nn.layer.norm import LayerNorm
        from ...nn.layer.transformer import MultiHeadAttention
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.num_layers = num_layers
        self.activation = activation
        self._eps = epsilon
        self.attns = LayerListHelper([
            MultiHeadAttention(embed_dim, num_heads, dropout=dropout_rate)
            for _ in range(num_layers)])
        self.ffns = LayerListHelper([
            FusedFeedForward(embed_dim, dim_feedforward,
                             dropout_rate=dropout_rate,
                             activation=activation, epsilon=epsilon,
                             normalize_before=True)
            for _ in range(num_layers)])
        self.lns = LayerListHelper([LayerNorm(embed_dim, epsilon)
                                    for _ in range(num_layers)])

    def forward(self, src, attn_mask=None, caches=None, time_step=None):
        out = src
        new_caches = [] if caches is not None else None
        for i in range(self.num_layers):
            residual = out
            h = self.lns[i](out)
            if caches is not None:
                cache = caches[i] if i < len(caches) else None
                if cache is None:
                    # short/empty caches list: start this layer's decode
                    # cache fresh (MHA needs a real cache to return one)
                    cache = self.attns[i].gen_cache(h[:, :0])
                h, cache = self.attns[i](h, h, h, attn_mask=attn_mask,
                                         cache=cache)
                new_caches.append(cache)
            else:
                h = self.attns[i](h, h, h, attn_mask=attn_mask)
            out = residual + h
            out = self.ffns[i](out)
        if new_caches is not None:
            return out, new_caches
        return out


def LayerListHelper(layers):
    from ...nn.layer.container import LayerList
    return LayerList(layers)


from . import functional  # noqa: F401,E402
