"""incubate.nn.functional (reference: incubate/nn/functional/
fused_transformer.py): functional forms of the fused transformer ops.
XLA performs the fusion; these compose the same math with the same
signatures so call sites port unchanged.
"""
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply_op

__all__ = ["fused_multi_head_attention", "fused_feedforward",
           "fused_multi_transformer", "fused_matmul_bias", "fused_linear",
           "fused_bias_dropout_residual_layer_norm",
           "fused_linear_cross_entropy"]


def _layer_norm(h, g, b, eps):
    """Shared LN helper. Module-level on purpose: the traced fns reference
    it as a global, so it never lands in a closure cell where a fresh
    per-call object would invalidate the eager-op cache key."""
    mean = jnp.mean(h, -1, keepdims=True)
    var = jnp.var(h, -1, keepdims=True)
    out = (h - mean) * jax.lax.rsqrt(var + eps)
    if g is not None:
        out = out * g
    if b is not None:
        out = out + b
    return out


def _dropout_key(rate, training):
    """Draw the PRNG key OUTSIDE the traced fn and hand back its raw
    uint32 data as a Tensor operand: unlike a key in a closure cell (which
    is unhashable and would bypass the eager-op cache for the whole fused
    layer), a Tensor operand varies per call while the cache key — and the
    compiled executable — stay stable."""
    if not training or rate <= 0:
        return None
    from ...core.random import next_key
    return Tensor(jax.random.key_data(next_key()))


def _dropout(h, rate, training, mode, kd):
    """Bernoulli dropout for the fused ops (reference fused_attention_op.cu /
    fused_feedforward_op.cu drop after activation and before the residual).
    `kd` is raw key data (from _dropout_key), already unwrapped to an array."""
    if not training or rate <= 0:
        if mode == "downscale_in_infer" and rate > 0:
            return h * (1 - rate)
        return h
    keep = jax.random.bernoulli(jax.random.wrap_key_data(kd), 1 - rate,
                                h.shape)
    if mode == "upscale_in_train":
        return jnp.where(keep, h / (1 - rate), 0)
    return jnp.where(keep, h, 0)


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """reference: fused_matmul_bias (cublasLt epilogue) — XLA fuses the
    bias add into the matmul."""
    def fn(a, b, *bs):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        out = a @ b
        return out + bs[0] if bs else out
    args = [x, y] + ([bias] if bias is not None else [])
    return apply_op(fn, *args)


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    return fused_matmul_bias(x, weight, bias, False, transpose_weight)


def fused_bias_dropout_residual_layer_norm(
        x, residual, bias=None, ln_scale=None, ln_bias=None,
        dropout_rate=0.5, ln_epsilon=1e-5, training=True, mode="upscale_in_train",
        name=None):
    """reference: incubate/nn/functional fused_bias_dropout_residual_
    layer_norm — LN(residual + dropout(x + bias))."""
    key = _dropout_key(dropout_rate, training)
    # fn's closure must hold only hashable statics (names, not Tensors):
    # closure cells are part of the eager-cache identity, and the fresh
    # per-call key Tensor in a cell would turn every call into a cache miss.
    present = tuple(n for n, t in (("b", bias), ("g", ln_scale),
                                   ("be", ln_bias), ("kd", key))
                    if t is not None)

    def fn(xd, rd, *rest):
        named = dict(zip(present, rest))
        b, g, be = named.get("b"), named.get("g"), named.get("be")
        h = xd + b if b is not None else xd
        h = _dropout(h, dropout_rate, training, mode, named.get("kd"))
        h = h + rd
        mean = jnp.mean(h, -1, keepdims=True)
        var = jnp.var(h, -1, keepdims=True)
        out = (h - mean) * jax.lax.rsqrt(var + ln_epsilon)
        if g is not None:
            out = out * g
        if be is not None:
            out = out + be
        return out
    args = [x, residual] + [t for t in (bias, ln_scale, ln_bias, key)
                            if t is not None]
    return apply_op(fn, *args)


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None, ln_bias=None,
                               pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.0,
                               attn_dropout_rate=0.0, ln_epsilon=1e-5,
                               training=True, mode="upscale_in_train",
                               ring_id=-1, add_residual=True, num_heads=None,
                               transpose_qkv_wb=False, name=None):
    """reference: fused_multi_head_attention (fused_attention_op.cu):
    [preLN ->] qkv matmul -> MHA -> out proj [-> residual+LN]. qkv_weight
    layout (3, H, head_dim, hidden), the op's native format."""
    attn_key = _dropout_key(attn_dropout_rate, training)
    out_key = _dropout_key(dropout_rate, training)

    present = tuple(n for n, t in (
        ("pre_g", pre_ln_scale), ("pre_b", pre_ln_bias), ("g", ln_scale),
        ("b", ln_bias), ("qkv_b", qkv_bias), ("lin_b", linear_bias),
        ("mask", attn_mask), ("attn_k", attn_key), ("out_k", out_key))
        if t is not None)

    def fn(xd, qkvw, lw, *rest):
        named = dict(zip(present, rest))
        # NB: helpers must be module-level (a per-call local in a closure
        # cell would defeat the eager-op cache key)
        h = _layer_norm(xd, named.get("pre_g"), named.get("pre_b"),
                        pre_ln_epsilon) if pre_layer_norm else xd
        nh, hd = qkvw.shape[1], qkvw.shape[2]
        qkv = jnp.einsum("bsh,tnda->bstnd" if False else "bsa,tnda->bstnd",
                         h, qkvw)
        if "qkv_b" in named:
            qkv = qkv + named["qkv_b"][None, None]
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]   # (B,S,nh,hd)
        q = jnp.swapaxes(q, 1, 2)
        k = jnp.swapaxes(k, 1, 2)
        v = jnp.swapaxes(v, 1, 2)
        s = q @ jnp.swapaxes(k, -1, -2) / jnp.sqrt(float(hd))
        if "mask" in named:
            s = s + named["mask"]
        p = jax.nn.softmax(s, -1)
        p = _dropout(p, attn_dropout_rate, training, mode,
                     named.get("attn_k"))
        o = jnp.swapaxes(p @ v, 1, 2)
        o = o.reshape(o.shape[0], o.shape[1], nh * hd)
        out = o @ lw
        if "lin_b" in named:
            out = out + named["lin_b"]
        out = _dropout(out, dropout_rate, training, mode, named.get("out_k"))
        if add_residual:
            out = out + xd
        if not pre_layer_norm:
            out = _layer_norm(out, named.get("g"), named.get("b"),
                              ln_epsilon)
        return out

    args = [x, qkv_weight, linear_weight] + [
        t for t in (pre_ln_scale, pre_ln_bias, ln_scale, ln_bias,
                    qkv_bias, linear_bias, attn_mask, attn_key, out_key)
        if t is not None]
    return apply_op(fn, *args)


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True, ring_id=-1,
                      mode="upscale_in_train", name=None):
    """reference: fused_feedforward (fused_feedforward_op.cu)."""
    key1 = _dropout_key(dropout1_rate, training)
    key2 = _dropout_key(dropout2_rate, training)

    present = tuple(n for n, t in (
        ("b1", linear1_bias), ("b2", linear2_bias), ("g1", ln1_scale),
        ("lb1", ln1_bias), ("g2", ln2_scale), ("lb2", ln2_bias),
        ("k1", key1), ("k2", key2)) if t is not None)

    def fn(xd, w1, w2, *rest):
        named = dict(zip(present, rest))

        h = _layer_norm(xd, named.get("g1"), named.get("lb1"),
                        ln1_epsilon) if pre_layer_norm else xd
        u = h @ w1
        if "b1" in named:
            u = u + named["b1"]
        u = getattr(jax.nn, activation)(u)
        u = _dropout(u, dropout1_rate, training, mode, named.get("k1"))
        out = u @ w2
        if "b2" in named:
            out = out + named["b2"]
        out = _dropout(out, dropout2_rate, training, mode, named.get("k2"))
        out = out + xd
        if not pre_layer_norm:
            out = _layer_norm(out, named.get("g2"), named.get("lb2"),
                              ln2_epsilon)
        return out

    args = [x, linear1_weight, linear2_weight] + [
        t for t in (linear1_bias, linear2_bias, ln1_scale, ln1_bias,
                    ln2_scale, ln2_bias, key1, key2) if t is not None]
    return apply_op(fn, *args)


def fused_multi_transformer(x, ln_scales, ln_biases, qkv_weights, qkv_biases,
                            linear_weights, linear_biases, ffn_ln_scales,
                            ffn_ln_biases, ffn1_weights, ffn1_biases,
                            ffn2_weights, ffn2_biases, pre_layer_norm=True,
                            epsilon=1e-05, cache_kvs=None, time_step=None,
                            attn_mask=None, dropout_rate=0.0, activation="gelu",
                            training=False, mode="upscale_in_train",
                            trans_qkvw=True, ring_id=-1, name=None):
    """reference: fused_multi_transformer_op.cu functional form — per-layer
    preLN attention + FFN over weight lists."""
    out = x
    for i in range(len(qkv_weights)):
        out = fused_multi_head_attention(
            out, qkv_weights[i], linear_weights[i], pre_layer_norm=True,
            pre_ln_scale=ln_scales[i], pre_ln_bias=ln_biases[i],
            pre_ln_epsilon=epsilon, qkv_bias=qkv_biases[i],
            linear_bias=linear_biases[i], attn_mask=attn_mask,
            dropout_rate=dropout_rate, training=training, mode=mode)
        out = fused_feedforward(
            out, ffn1_weights[i], ffn2_weights[i], ffn1_biases[i],
            ffn2_biases[i], ln1_scale=ffn_ln_scales[i],
            ln1_bias=ffn_ln_biases[i], pre_layer_norm=True,
            dropout1_rate=dropout_rate, dropout2_rate=dropout_rate,
            activation=activation, ln1_epsilon=epsilon, training=training,
            mode=mode)
    return out


def fused_linear_cross_entropy(x, weight, label, num_chunks=8,
                               reduction="mean", name=None):
    """Fused LM-head linear + softmax cross-entropy over vocab chunks
    (TPU-native extension of the fused-op family; the (tokens, vocab)
    logits never materialize — see ops/fused_ce.py for the memory math).

    x: (..., H) activations; weight: (V, H) classifier rows; label: (...,)
    int. reduction: "mean" | "sum" | "none".
    """
    from ...ops.fused_ce import fused_linear_cross_entropy as _op

    def call(x, w, lab):
        from ...nn.functional.loss import _reduce
        lead = x.shape[:-1]
        nll = _op(x.reshape((-1, x.shape[-1])), w, lab.reshape((-1,)),
                  int(num_chunks))
        return _reduce(nll.reshape(lead), reduction)

    return apply_op(call, x, weight, label,
                    name=f"fused_linear_cross_entropy:{reduction}:"
                         f"{num_chunks}")
