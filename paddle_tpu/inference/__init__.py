"""paddle.inference equivalent — the deploy product.

Reference: paddle/fluid/inference (§2.7 of SURVEY.md): `AnalysisPredictor`
(inference/api/analysis_predictor.h:95) loads a saved ProgramDesc + params,
runs IR fusion passes, optionally offloads subgraphs to TensorRT, and serves
through zero-copy input/output handles (details/zero_copy_tensor.cc), with
`AnalysisConfig` (inference/api/analysis_config.cc) as the knob surface.

TPU-native design: the saved artifact is an AOT-exported StableHLO program
(`paddle_tpu.jit.save`) — the XLA compiler IS the analysis/fusion pass
pipeline, so `switch_ir_optim`-style knobs are accepted-and-absorbed. The
Predictor deserializes the program once, compiles per concrete input shape
(shape-polymorphic artifacts recompile per batch size, cached), and serves
through handle objects whose `copy_from_cpu`/`copy_to_cpu` map to device
put/get — the TPU analogue of zero-copy CPU tensors.
"""
import os

import numpy as np

__all__ = ["Config", "Predictor", "PrecisionType", "PlaceType",
           "create_predictor", "get_version"]


class PrecisionType:
    Float32 = "float32"
    Bfloat16 = "bfloat16"
    Half = "float16"
    Int8 = "int8"


class PlaceType:
    CPU = "cpu"
    TPU = "tpu"
    # reference enum also has GPU/XPU/NPU — single-backend build
    GPU = "tpu"


class Config:
    """AnalysisConfig-compatible surface. Knobs that XLA owns are recorded
    but have no effect (noted per method)."""

    def __init__(self, prog_file=None, params_file=None):
        if prog_file is not None and params_file is None and \
                os.path.isdir(prog_file):
            # Config(model_dir) form: find the single jit.save artifact
            d = prog_file
            models = sorted(f for f in os.listdir(d)
                            if f.endswith(".pdmodel"))
            if not models:
                raise FileNotFoundError(f"no .pdmodel in {d}")
            prog_file = os.path.join(d, models[0])
            params_file = prog_file[:-len(".pdmodel")] + ".pdiparams"
        self._prog_file = prog_file
        self._params_file = params_file
        self._device = "tpu"
        self._precision = PrecisionType.Float32
        self._memory_optim = True
        self._ir_optim = True
        self._profile = False
        self._glog_info = True
        self._cpu_math_threads = 1
        # persistent executable cache: serialized XLA executables live next
        # to the artifact so a second process skips compilation entirely
        # (AnalysisPredictor's pay-analysis-once intent). None = default dir.
        self._compile_cache_dir = None
        self._compile_cache = True
        # AOT serving warmup: when the artifact's .gencfg records a serving
        # engine (save_for_generation(engine_config=...)), the Predictor
        # builds it AT LOAD and precompiles the whole executable set —
        # against a warm compile cache that is a deserialize, not a
        # compile, and the first request pays zero compilation.
        self._aot_warmup = True

    def enable_compile_cache(self, path=None):
        self._compile_cache = True
        self._compile_cache_dir = path

    def disable_compile_cache(self):
        self._compile_cache = False

    def enable_aot_warmup(self):
        self._aot_warmup = True

    def disable_aot_warmup(self):
        """Skip the load-time engine build/warmup (serving executables
        then compile lazily on the first generate(), pre-PR-8 style)."""
        self._aot_warmup = False

    # -- model location ----------------------------------------------------
    def set_prog_file(self, path):
        self._prog_file = path

    def set_params_file(self, path):
        self._params_file = path

    def prog_file(self):
        return self._prog_file

    def params_file(self):
        return self._params_file

    def set_model(self, prog_file, params_file):
        self._prog_file = prog_file
        self._params_file = params_file

    # -- device ------------------------------------------------------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        """Single-backend build: selects the TPU (memory pool is managed by
        the XLA runtime allocator, the size hint is ignored)."""
        self._device = "tpu"

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self):
        return self._device == "tpu"

    def set_cpu_math_library_num_threads(self, n):
        self._cpu_math_threads = n

    # -- optimization knobs (absorbed by XLA) --------------------------------
    def switch_ir_optim(self, x=True):
        """Graph fusion/layout passes are XLA's job; kept for parity."""
        self._ir_optim = x

    def enable_memory_optim(self, x=True):
        """Buffer reuse is XLA's job; kept for parity."""
        self._memory_optim = x

    def enable_tensorrt_engine(self, *a, **k):
        """TensorRT is CUDA-specific; the XLA TPU compiler plays this role.
        Accepted as a no-op so deploy scripts port unchanged."""

    def enable_profile(self):
        self._profile = True

    def disable_glog_info(self):
        self._glog_info = False

    def switch_use_feed_fetch_ops(self, x=False):
        pass

    def switch_specify_input_names(self, x=True):
        pass

    def summary(self):
        return (f"Config(prog={self._prog_file}, params={self._params_file}, "
                f"device={self._device}, precision={self._precision})")


class _Handle:
    """Zero-copy-style IO handle (reference: ZeroCopyTensor). Inputs stage a
    host array and device-put lazily at run(); outputs hold the device
    array and copy_to_cpu fetches it."""

    def __init__(self, name, shape=None, dtype=None):
        self.name = name
        self._shape = shape
        self._dtype = dtype
        self._value = None

    def reshape(self, shape):
        self._shape = tuple(shape)

    def copy_from_cpu(self, arr):
        self._value = np.ascontiguousarray(arr)

    def share_external_data(self, arr):
        self._value = arr  # no copy; caller keeps it alive

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def shape(self):
        v = self._value
        return list(v.shape) if v is not None else list(self._shape or [])

    def type(self):
        return self._dtype


class Predictor:
    """AnalysisPredictor equivalent over a deserialized AOT program."""

    def __init__(self, config):
        from jax import export as jexport

        import jax.numpy as jnp

        from ..framework.io import load as _load

        self._config = config
        if getattr(config, "_compile_cache", False):
            from ..framework.flags import enable_compilation_cache
            cache_dir = config._compile_cache_dir or os.path.join(
                os.path.dirname(os.path.abspath(config.prog_file())),
                "_xla_cache")
            enable_compilation_cache(cache_dir)
        with open(config.prog_file(), "rb") as f:
            self._exported = jexport.deserialize(f.read())
        payload = _load(config.params_file(), return_numpy=True)
        self._params = {n: jnp.asarray(v) for n, v in payload["params"].items()}
        self._buffers = {n: jnp.asarray(v)
                         for n, v in payload["buffers"].items()}

        # in_avals is the FLATTENED calling convention: one aval per
        # param/buffer leaf, then the user inputs
        n_state = len(self._params) + len(self._buffers)
        in_avals = self._exported.in_avals[n_state:]
        # user-facing input names: the REAL names saved with the artifact
        # (jit.save feed_names), falling back to positional input_{i} for
        # legacy artifacts — keeps Predictor / load_inference_model /
        # Executor.run agreeing on one name set
        saved = payload.get("feed_names")
        if saved and len(saved) == len(in_avals):
            self._input_names = list(saved)
        else:
            self._input_names = [f"input_{i}" for i in range(len(in_avals))]
        self._inputs = {n: _Handle(n, tuple(a.shape), str(a.dtype))
                        for n, a in zip(self._input_names, in_avals)}
        self._output_names = []
        self._outputs = {}

        # AOT serving warmup: a .gencfg that records a serving engine is
        # built NOW (executables deserialize from the artifact's compile
        # cache when warm), so the first generate() compiles nothing.
        # Failure degrades to the lazy path — load must never break.
        self._gen_sched = None
        self._gen_sched_from_record = False
        self._serving_meta = self._read_serving_meta()
        if self._serving_meta and getattr(config, "_aot_warmup", False) \
                and getattr(config, "_compile_cache", False):
            import time as _time
            from ..observability import metrics as _obs_metrics
            t0 = _time.perf_counter()
            try:
                self._generation_scheduler()
            except Exception as e:                           # noqa: BLE001
                import warnings
                # the recorded engine cannot be rebuilt under THIS build
                # (config/kind skew): drop the record so the lazy path
                # takes the plain pre-record engine instead of retrying
                # the same deterministic failure on every generate()
                self._serving_meta = None
                warnings.warn(f"AOT serving warmup failed "
                              f"({type(e).__name__}: {str(e)[:200]}); "
                              f"falling back to lazy engine build")
            else:
                _obs_metrics.gauge(
                    "predictor_executable_ready_seconds",
                    "Predictor load-to-serving-ready wall time (AOT "
                    "warmup included)").set(_time.perf_counter() - t0)

    def _read_serving_meta(self):
        """The .gencfg 'serving' record (engine kind + config +
        executable set), or None for pre-recording artifacts."""
        import json

        from ..serving.engine import GENCFG_SUFFIX
        base = self._config.prog_file()
        if base.endswith(".pdmodel"):
            base = base[:-len(".pdmodel")]
        try:
            with open(base + GENCFG_SUFFIX) as f:
                return json.load(f).get("serving")
        except (OSError, ValueError):
            return None

    def get_input_names(self):
        return list(self._input_names)

    def get_input_handle(self, name):
        return self._inputs[name]

    def run(self, inputs=None):
        """Execute. Either feed via handles then run(), or pass a list of
        numpy arrays directly (returns list of numpy outputs)."""
        import jax.numpy as jnp

        if inputs is not None:
            for n, arr in zip(self._input_names, inputs):
                self._inputs[n].copy_from_cpu(np.asarray(arr))
        args = [jnp.asarray(self._inputs[n]._value) for n in self._input_names]
        out = self._exported.call(self._params, self._buffers, *args)
        outs = list(out) if isinstance(out, (tuple, list)) else [out]
        self._output_names = [f"output_{i}" for i in range(len(outs))]
        self._outputs = {}
        for n, o in zip(self._output_names, outs):
            h = _Handle(n, tuple(o.shape), str(o.dtype))
            h._value = o
            self._outputs[n] = h
        if inputs is not None:
            return [np.asarray(o) for o in outs]
        return True

    def get_output_names(self):
        return list(self._output_names)

    def get_output_handle(self, name):
        return self._outputs[name]

    # -- generation entry point (serving/) ----------------------------------
    def _generation_scheduler(self, **engine_kwargs):
        """Build (or return) the serving engine + scheduler from the
        `.gencfg` sidecar `serving.save_for_generation` wrote next to
        the artifact. The params already loaded for the one-shot path
        are reused — one weight copy serves both run() and generate().

        When the sidecar records a serving engine and no explicit engine
        kwargs are given, the RECORDED engine (dense/paged/spec, exact
        config) is rebuilt with the artifact's persistent compile cache
        attached and `precompile()`d — against a warm cache that is all
        deserialization, so a restarted Predictor performs zero fresh
        compilations for the serving set.

        Explicit engine kwargs keep their pre-record contract: they win.
        A scheduler auto-built from the record is REPLACED when the
        first generate() carries engine kwargs (the caller asked for a
        different engine than the artifact recorded); once a
        kwargs-built scheduler exists, later calls reuse it as before."""
        if getattr(self, "_gen_sched", None) is not None:
            if not engine_kwargs or \
                    not getattr(self, "_gen_sched_from_record", False):
                return self._gen_sched
            self._gen_sched = None     # record-built, caller overrides
        from ..serving.engine import (default_compile_cache_dir,
                                      load_generation_model, make_engine)
        model = load_generation_model(self._config.prog_file(), self._params)
        if model is None:
            raise RuntimeError(
                "this artifact has no generation sidecar; save it with "
                "paddle_tpu.serving.save_for_generation to enable "
                "Predictor.generate")
        from ..serving import GenerationEngine, Scheduler
        sched_keys = ("max_queue", "default_max_new_tokens",
                      "default_timeout_s", "metrics_path")
        sched_kwargs = {k: engine_kwargs.pop(k) for k in sched_keys
                        if k in engine_kwargs}
        meta = getattr(self, "_serving_meta", None)
        from_record = bool(meta) and not engine_kwargs
        if from_record:
            cache_dir = None
            if getattr(self._config, "_compile_cache", False):
                cache_dir = self._config._compile_cache_dir or \
                    default_compile_cache_dir(self._config.prog_file())
            engine = make_engine(model, meta["engine"], meta["config"],
                                 compile_cache_dir=cache_dir)
            if getattr(self._config, "_aot_warmup", False):
                try:
                    engine.precompile()
                except Exception as e:                       # noqa: BLE001
                    # the engine itself is healthy — serve lazily (the
                    # executables compile on first use) rather than fail
                    import warnings
                    warnings.warn(f"AOT precompile failed "
                                  f"({type(e).__name__}: {str(e)[:200]});"
                                  f" serving will compile lazily")
        else:
            engine = GenerationEngine(model, **engine_kwargs)
        self._gen_sched = Scheduler(engine, **sched_kwargs)
        self._gen_sched_from_record = from_record
        return self._gen_sched

    def generate(self, input_ids, max_new_tokens=32, **engine_kwargs):
        """Generate continuations for a batch of prompts (list of
        token-id lists, or a [B, S] int array) through the continuous-
        batching engine. Returns list-of-lists of generated ids.
        Engine/scheduler knobs (slots, max_len, decode_strategy,
        temperature, top_k, top_p, eos_token_id, max_queue, ...) pass
        through on the FIRST call; later calls reuse the built engine."""
        from ..serving import QueueFullError
        prompts = [list(map(int, np.asarray(p).reshape(-1)))
                   for p in input_ids]
        sched = self._generation_scheduler(**engine_kwargs)
        handles = []
        for p in prompts:
            while True:
                try:
                    handles.append(sched.submit(
                        p, max_new_tokens=max_new_tokens))
                    break
                except QueueFullError:
                    sched.step()   # drain a slot's worth, then retry
        sched.run_until_idle()
        # the scheduler degrades gracefully for SERVING callers (per-
        # request status), but this batch API has no consumer watching
        # handle.status — a decode failure must be loud, not a silently
        # truncated generation
        failed = [h for h in handles if h.status == "ERROR"]
        if failed:
            raise RuntimeError(
                f"decode failed for {len(failed)}/{len(handles)} "
                f"request(s): {failed[0].error}")
        return [h.tokens for h in handles]

    def clear_intermediate_tensor(self):
        pass

    def try_shrink_memory(self):
        pass


def create_predictor(config):
    return Predictor(config)


def get_version():
    from .. import __version__
    return __version__


class DataType:
    """reference: paddle_infer.DataType enum."""
    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5
    BFLOAT16 = 6


def get_num_bytes_of_data_type(dtype):
    return {DataType.FLOAT32: 4, DataType.INT64: 8, DataType.INT32: 4,
            DataType.UINT8: 1, DataType.INT8: 1, DataType.FLOAT16: 2,
            DataType.BFLOAT16: 2}[dtype]


# paddle_infer.Tensor is the zero-copy handle type; ours is _Handle
Tensor = _Handle


class PredictorPool:
    """reference: paddle_infer.PredictorPool — N predictors sharing one
    config (thread-per-predictor serving)."""

    def __init__(self, config, size=1):
        self._predictors = [Predictor(config) for _ in range(max(size, 1))]

    def retrive(self, idx):
        return self._predictors[idx]

    retrieve = retrive


def convert_to_mixed_precision(model_file, params_file, mixed_model_file,
                               mixed_params_file, mixed_precision=None,
                               backend=None, keep_io_types=True,
                               black_list=None, **kwargs):
    """reference: inference convert_to_mixed_precision — rewrites a saved
    model to fp16/bf16. The StableHLO artifact stays dtype-typed; bf16
    serving comes from exporting the model with bf16 params (jit.save of a
    bf16-cast Layer), so this converter re-saves with a dtype cast."""
    raise NotImplementedError(
        "convert the LAYER before export: cast params to bfloat16 "
        "(layer.to(dtype='bfloat16') / astype) and jit.save it — the "
        "exported StableHLO then serves in bf16 end-to-end")


def get_trt_compile_version():
    """No TensorRT on TPU (PARITY: TensorRT row) — version tuple of 0s."""
    return (0, 0, 0)


def get_trt_runtime_version():
    return (0, 0, 0)


def _get_phi_kernel_name(op_name):
    """reference: maps fluid op names to phi kernel names; one generation
    here — identity."""
    return op_name
