"""paddle.audio.backends + load/save/info (reference:
python/paddle/audio/backends/wave_backend.py — the stdlib `wave` WAV
backend is the default there too; soundfile is an optional extra that is
not bundled in either build).
"""
import wave as _wave

import numpy as np

from ..core.tensor import Tensor, to_tensor

__all__ = ["AudioInfo", "load", "save", "info", "list_available_backends",
           "get_current_audio_backend", "set_backend"]


class AudioInfo:
    """reference: audio/backends/backend.py AudioInfo."""

    def __init__(self, sample_rate, num_frames, num_channels,
                 bits_per_sample, encoding):
        self.sample_rate = sample_rate
        self.num_frames = num_frames
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding


def list_available_backends():
    return ["wave_backend"]


def get_current_audio_backend():
    return "wave_backend"


def set_backend(backend_name):
    if backend_name not in ("wave_backend",):
        raise NotImplementedError(
            f"backend {backend_name!r} unavailable: only the stdlib wave "
            f"backend is bundled (the reference's default, "
            f"wave_backend.py; soundfile is an optional pip extra there)")


def info(filepath):
    """reference: wave_backend.py:36 — WAV header info."""
    with _wave.open(str(filepath), "rb") as f:
        bits = f.getsampwidth() * 8
        return AudioInfo(f.getframerate(), f.getnframes(), f.getnchannels(),
                         bits, f"PCM_{'S' if bits > 8 else 'U'}")


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    """reference: wave_backend.py:87 — PCM WAV -> float32 tensor in [-1, 1]
    (normalize=True) or raw integer dtype."""
    with _wave.open(str(filepath), "rb") as f:
        sr = f.getframerate()
        n = f.getnframes()
        ch = f.getnchannels()
        width = f.getsampwidth()
        f.setpos(min(frame_offset, n))
        count = n - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(count)
    dt = {1: np.uint8, 2: np.int16, 4: np.int32}[width]
    arr = np.frombuffer(raw, dtype=dt).reshape(-1, ch)
    if normalize:
        if width == 1:
            arr = (arr.astype(np.float32) - 128.0) / 128.0
        else:
            arr = arr.astype(np.float32) / float(2 ** (width * 8 - 1))
    if channels_first:
        arr = arr.T
    return to_tensor(np.ascontiguousarray(arr)), sr


def save(filepath, src, sample_rate, channels_first=True,
         encoding="PCM_S", bits_per_sample=16):
    """reference: wave_backend.py:164 — float [-1,1] or int tensor -> WAV."""
    arr = np.asarray(src._data if isinstance(src, Tensor) else src)
    if channels_first:
        arr = arr.T                       # -> (frames, channels)
    if arr.ndim == 1:
        arr = arr[:, None]
    width = bits_per_sample // 8
    if np.issubdtype(arr.dtype, np.floating):
        scale = float(2 ** (bits_per_sample - 1) - 1)
        arr = np.clip(arr, -1.0, 1.0)
        arr = (arr * scale).astype({2: np.int16, 4: np.int32}[width])
    with _wave.open(str(filepath), "wb") as f:
        f.setnchannels(arr.shape[1])
        f.setsampwidth(width)
        f.setframerate(int(sample_rate))
        f.writeframes(arr.tobytes())


def get_current_backend():
    """reference: audio/backends get_current_backend alias."""
    return get_current_audio_backend()
