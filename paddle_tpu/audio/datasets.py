"""paddle.audio.datasets (reference: python/paddle/audio/datasets/
{tess,esc50}.py). Zero-egress build: datasets read an already-downloaded
archive/folder via `archive`/`data_dir`; requesting a download raises with
the expected layout, instead of pretending.
"""
import os

import numpy as np

from ..io import Dataset
from . import backends as _bk
from .features import MFCC, LogMelSpectrogram, MelSpectrogram, Spectrogram

__all__ = ["TESS", "ESC50", "AudioClassificationDataset"]

_FEATS = {"raw": None, "spectrogram": Spectrogram,
          "melspectrogram": MelSpectrogram,
          "logmelspectrogram": LogMelSpectrogram, "mfcc": MFCC}


class AudioClassificationDataset(Dataset):
    """reference: audio/datasets/dataset.py — (wav file, label) list with
    an optional on-the-fly feature transform."""

    def __init__(self, files, labels, feat_type="raw", sample_rate=None,
                 **kwargs):
        if feat_type not in _FEATS:
            raise ValueError(f"feat_type {feat_type!r} not in "
                             f"{sorted(_FEATS)}")
        self.files = list(files)
        self.labels = list(labels)
        self.feat_type = feat_type
        self.sample_rate = sample_rate
        self.feat_cls = _FEATS[feat_type]
        self._feat_kwargs = kwargs
        self._feat_cache = {}      # sr -> extractor (filterbank/DCT reuse)

    def __len__(self):
        return len(self.files)

    def __getitem__(self, idx):
        wav, sr = _bk.load(self.files[idx])
        if self.feat_cls is None:
            return wav, self.labels[idx]
        extractor = self._feat_cache.get(sr)
        if extractor is None:
            kw = dict(self._feat_kwargs)
            if self.feat_cls is not Spectrogram:  # Spectrogram is sr-free
                kw.setdefault("sr", sr)
            extractor = self._feat_cache[sr] = self.feat_cls(**kw)
        return extractor(wav), self.labels[idx]


class TESS(AudioClassificationDataset):
    """Toronto Emotional Speech Set (reference: datasets/tess.py).
    Layout: <data_dir>/**/<anything>_<word>_<emotion>.wav."""

    emotions = ["angry", "disgust", "fear", "happy", "neutral", "ps", "sad"]

    def __init__(self, mode="train", feat_type="raw", archive=None,
                 data_dir=None, n_folds=5, split=1, **kwargs):
        root = data_dir or (archive or {}).get("path")
        if root is None or not os.path.isdir(root):
            raise RuntimeError(
                "TESS needs a local copy (zero-egress build): pass "
                "data_dir=<folder containing the extracted TESS wavs "
                "named *_<emotion>.wav> (reference downloads from "
                "bcebos.com, datasets/tess.py archive)")
        files, labels = [], []
        for dirpath, _, names in sorted(os.walk(root)):
            for nm in sorted(names):
                if not nm.lower().endswith(".wav"):
                    continue
                emo = nm.rsplit(".", 1)[0].rsplit("_", 1)[-1].lower()
                if emo in self.emotions:
                    files.append(os.path.join(dirpath, nm))
                    labels.append(self.emotions.index(emo))
        # fold split like the reference: every n_folds-th item is dev
        keep_f, keep_l = [], []
        for i, (f, l) in enumerate(zip(files, labels)):
            fold = i % n_folds + 1
            if (mode == "train") == (fold != split):
                keep_f.append(f)
                keep_l.append(l)
        super().__init__(keep_f, keep_l, feat_type, **kwargs)


class ESC50(AudioClassificationDataset):
    """ESC-50 environmental sounds (reference: datasets/esc50.py).
    Layout: <data_dir>/audio/<fold>-*.wav + meta/esc50.csv."""

    def __init__(self, mode="train", split=1, feat_type="raw", archive=None,
                 data_dir=None, **kwargs):
        root = data_dir or (archive or {}).get("path")
        meta = os.path.join(root or "", "meta", "esc50.csv")
        if root is None or not os.path.isfile(meta):
            raise RuntimeError(
                "ESC50 needs a local copy (zero-egress build): pass "
                "data_dir=<ESC-50 root with audio/ and meta/esc50.csv> "
                "(reference downloads from github, datasets/esc50.py)")
        files, labels = [], []
        with open(meta) as f:
            header = f.readline().strip().split(",")
            fi = header.index("filename")
            ti = header.index("target")
            fo = header.index("fold")
            for line in f:
                row = line.strip().split(",")
                fold = int(row[fo])
                if (mode == "train") == (fold != split):
                    files.append(os.path.join(root, "audio", row[fi]))
                    labels.append(int(row[ti]))
        super().__init__(files, labels, feat_type, **kwargs)
