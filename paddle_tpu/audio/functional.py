"""audio.functional (reference: python/paddle/audio/functional)."""
import math

import numpy as np

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["get_window", "hz_to_mel", "mel_to_hz", "mel_frequencies",
           "fft_frequencies", "compute_fbank_matrix", "create_dct",
           "power_to_db"]


def get_window(window, win_length, fftbins=True, dtype="float32"):
    """hann/hamming/blackman/... periodic (fftbins) or symmetric."""
    n = win_length
    m = n if fftbins else n - 1
    t = np.arange(n) * (2 * math.pi / max(m, 1))
    if isinstance(window, tuple):
        name, *params = window
    else:
        name, params = window, []
    if name in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(t)
    elif name == "hamming":
        w = 0.54 - 0.46 * np.cos(t)
    elif name == "blackman":
        w = 0.42 - 0.5 * np.cos(t) + 0.08 * np.cos(2 * t)
    elif name in ("boxcar", "rect", "ones"):
        w = np.ones(n)
    elif name == "gaussian":
        std = params[0] if params else 7.0
        k = np.arange(n) - (n - 1) / 2.0
        w = np.exp(-0.5 * (k / std) ** 2)
    else:
        raise ValueError(f"unsupported window {window!r}")
    return Tensor(jnp.asarray(w.astype(dtype)))


def hz_to_mel(freq, htk=False):
    f = np.asarray(freq, np.float64)
    if htk:
        out = 2595.0 * np.log10(1.0 + f / 700.0)
    else:                           # Slaney
        f_min, f_sp = 0.0, 200.0 / 3
        out = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        if np.ndim(f) == 0:
            if f >= min_log_hz:
                out = min_log_mel + math.log(f / min_log_hz) / logstep
        else:
            mask = f >= min_log_hz
            out = np.where(mask, min_log_mel
                           + np.log(np.maximum(f, 1e-10) / min_log_hz)
                           / logstep, out)
    return out


def mel_to_hz(mel, htk=False):
    m = np.asarray(mel, np.float64)
    if htk:
        return 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    f_min, f_sp = 0.0, 200.0 / 3
    out = f_min + f_sp * m
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    if np.ndim(m) == 0:
        if m >= min_log_mel:
            out = min_log_hz * math.exp(logstep * (m - min_log_mel))
    else:
        mask = m >= min_log_mel
        out = np.where(mask,
                       min_log_hz * np.exp(logstep * (m - min_log_mel)), out)
    return out


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False):
    mels = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk), n_mels)
    return mel_to_hz(mels, htk)


def fft_frequencies(sr, n_fft):
    return np.linspace(0, sr / 2.0, 1 + n_fft // 2)


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """(n_mels, 1 + n_fft//2) triangular mel filterbank."""
    f_max = f_max or sr / 2.0
    fft_f = fft_frequencies(sr, n_fft)
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk)
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fft_f[None, :]
    fb = np.zeros((n_mels, fft_f.size))
    for i in range(n_mels):
        lower = -ramps[i] / fdiff[i]
        upper = ramps[i + 2] / fdiff[i + 1]
        fb[i] = np.maximum(0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        fb *= enorm[:, None]
    return Tensor(jnp.asarray(fb.astype(dtype)))


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """(n_mels, n_mfcc) DCT-II basis."""
    n = np.arange(n_mels)
    k = np.arange(n_mfcc)[None, :]
    basis = np.cos(math.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm == "ortho":
        basis[:, 0] *= 1.0 / math.sqrt(2)
        basis *= math.sqrt(2.0 / n_mels)
    return Tensor(jnp.asarray(basis.astype(dtype)))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    from ..core.tensor import apply_op

    def fn(s):
        log_spec = 10.0 * jnp.log10(jnp.maximum(s, amin))
        log_spec = log_spec - 10.0 * jnp.log10(jnp.maximum(ref_value, amin))
        if top_db is not None:
            log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
        return log_spec
    return apply_op(fn, spect)
