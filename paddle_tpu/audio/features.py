"""audio.features layers (reference: python/paddle/audio/features/layers.py).
Spectrogram -> MelSpectrogram -> LogMelSpectrogram -> MFCC, each one traced
program: stft + |.|^2 + (mel matmul) + (log) + (dct matmul)."""
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op
from ..nn.layer.layers import Layer
from . import functional as AF
from ..signal import stft


class Spectrogram(Layer):
    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.window = AF.get_window(window, self.win_length, dtype=dtype)

    def forward(self, x):
        spec = stft(x, self.n_fft, self.hop_length, self.win_length,
                    window=self.window, center=self.center,
                    pad_mode=self.pad_mode)
        p = self.power
        return apply_op(lambda s: jnp.abs(s) ** p, spec)


class MelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 dtype="float32"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length, window,
                                       power, center, pad_mode, dtype)
        self.fbank = AF.compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max,
                                             htk, norm, dtype)

    def forward(self, x):
        s = self.spectrogram(x)                   # (..., freq, time)
        fb = self.fbank
        return apply_op(lambda sp, m: jnp.einsum("mf,...ft->...mt", m, sp),
                        s, fb)


class LogMelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 ref_value=1.0, amin=1e-10, top_db=None, dtype="float32"):
        super().__init__()
        self.mel = MelSpectrogram(sr, n_fft, hop_length, win_length, window,
                                  power, center, pad_mode, n_mels, f_min,
                                  f_max, htk, norm, dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        return AF.power_to_db(self.mel(x), self.ref_value, self.amin,
                              self.top_db)


class MFCC(Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self.logmel = LogMelSpectrogram(sr, n_fft, hop_length, win_length,
                                        window, power, center, pad_mode,
                                        n_mels, f_min, f_max, htk, norm,
                                        ref_value, amin, top_db, dtype)
        self.dct = AF.create_dct(n_mfcc, n_mels, dtype=dtype)

    def forward(self, x):
        lm = self.logmel(x)                      # (..., n_mels, time)
        return apply_op(lambda l, d: jnp.einsum("mk,...mt->...kt", d, l),
                        lm, self.dct)
