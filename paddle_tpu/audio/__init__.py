"""paddle.audio — feature extraction (reference: python/paddle/audio:
functional/{window,filters,functional}.py, features/layers.py).

TPU-first: the mel filterbank is a precomputed host matrix applied as ONE
MXU matmul over the power spectrogram; dct likewise. All layers trace/jit.
"""
from . import functional  # noqa: F401
from .features import LogMelSpectrogram, MFCC, MelSpectrogram, Spectrogram  # noqa: F401
from . import backends  # noqa: F401
from . import datasets  # noqa: F401
from .backends import info, load, save  # noqa: F401
