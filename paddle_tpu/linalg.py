"""paddle.linalg namespace (reference: python/paddle/linalg.py re-exports)."""
from .tensor.linalg import (  # noqa: F401
    cholesky, cholesky_solve, corrcoef, cov, det, eig, eigh, eigvals, eigvalsh,
    inv, lstsq, lu, matmul, matrix_power, matrix_rank, multi_dot, norm, pinv,
    qr, slogdet, solve, svd, triangular_solve,
)
from .tensor.extras import (  # noqa: F401
    cdist, cond, householder_product, lu_unpack, matrix_exp, vector_norm,
)
