"""Quantization — QAT (fake-quant training) + PTQ (post-training calibration).

Reference: python/paddle/fluid/contrib/slim/quantization/ —
`ImperativeQuantAware` (imperative_qat) swaps Linear/Conv2D for quantized
twins with fake-quant on weights+activations (quantization_pass.py's
fake_quantize_abs_max / moving_average_abs_max ops);
`PostTrainingQuantization` calibrates scales (abs_max / KL histogram) over
sample data, then emits a quantized program.

TPU-native: fake-quant is a jit-fusible quant-dequant with a
straight-through estimator (jax.custom_vjp identity) — numerically the
reference's fake_quantize ops. int8 *execution* stays descoped: the TPU
speedup path is bf16 (MXU-native); fake-quant here serves accuracy
simulation and scale export.
"""
import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op
from ..nn.layer.layers import Layer
from .observers import HistogramObserver, channel_abs_max

__all__ = ["fake_quant", "QuantConfig", "ImperativeQuantAware",
           "PostTrainingQuantization", "QuantedLinear", "QuantedConv2D",
           "HistogramObserver", "fuse_conv_bn"]


@jax.custom_vjp
def _ste_round(x):
    return jnp.round(x)


def _ste_fwd(x):
    return jnp.round(x), None


def _ste_bwd(_, g):
    return (g,)   # straight-through


_ste_round.defvjp(_ste_fwd, _ste_bwd)


def _fake_quant_raw(x, scale, bits):
    qmax = 2.0 ** (bits - 1) - 1
    s = jnp.maximum(scale, 1e-8)
    return jnp.clip(_ste_round(x / s * qmax), -qmax, qmax) * s / qmax


def fake_quant(x, scale=None, bits=8, channel_axis=None):
    """Quant-dequant with STE (reference: fake_quantize_abs_max /
    fake_channel_wise_quantize_abs_max ops). With `channel_axis`, `scale`
    is a vector of per-channel scales broadcast along that axis."""
    data = x._data if isinstance(x, Tensor) else x
    if scale is None:
        if channel_axis is None:
            scale = jnp.max(jnp.abs(data))
        else:
            axes = tuple(i for i in range(data.ndim) if i != channel_axis)
            scale = jnp.max(jnp.abs(data), axis=axes)
    if channel_axis is not None:
        shape = [1] * data.ndim
        shape[channel_axis] = -1
        scale = jnp.asarray(scale).reshape(shape)
    if isinstance(x, Tensor):
        return apply_op(_fake_quant_raw, x, scale=scale, bits=bits,
                        name="fake_quant")
    return _fake_quant_raw(x, scale, bits)


class QuantConfig:
    def __init__(self, weight_bits=8, activation_bits=8,
                 weight_quantize_type="abs_max",
                 activation_quantize_type="moving_average_abs_max",
                 moving_rate=0.9):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.weight_quantize_type = weight_quantize_type
        self.activation_quantize_type = activation_quantize_type
        self.moving_rate = moving_rate


class _QuantedBase(Layer):
    """Shared fake-quant plumbing: per-call weight abs-max scale +
    moving-average activation scale (a buffer, like the reference's
    moving_average_abs_max state)."""

    def __init__(self, inner, cfg):
        super().__init__()
        self.inner = inner
        self._cfg = cfg
        from ..core.tensor import to_tensor
        self.register_buffer("act_scale",
                             to_tensor(np.zeros((), np.float32)))

    def _quant_act(self, x):
        cur = jnp.max(jnp.abs(x._data))
        if self.training:
            r = self._cfg.moving_rate
            prev = self.act_scale._data
            new = jnp.where(prev > 0, prev * r + cur * (1 - r), cur)
            self.act_scale._data = new
        else:
            new = jnp.where(self.act_scale._data > 0,
                            self.act_scale._data, cur)
        return fake_quant(x, jax.lax.stop_gradient(new),
                          self._cfg.activation_bits)

    # per-channel scales live on the output-channel axis (reference
    # fake_channel_wise_quantize_abs_max: quant_axis=1 for the (in, out)
    # Linear weight, 0 for the (out, in/g, kh, kw) Conv weight)
    _channel_axis = None

    def _quant_weight(self, w):
        if self._cfg.weight_quantize_type == "channel_wise_abs_max":
            axes = tuple(i for i in range(w._data.ndim)
                         if i != self._channel_axis)
            scale = jax.lax.stop_gradient(
                jnp.max(jnp.abs(w._data), axis=axes))
            return fake_quant(w, scale, self._cfg.weight_bits,
                              channel_axis=self._channel_axis)
        scale = jax.lax.stop_gradient(jnp.max(jnp.abs(w._data)))
        return fake_quant(w, scale, self._cfg.weight_bits)


class QuantedLinear(_QuantedBase):
    _channel_axis = 1

    def forward(self, x):
        from ..nn import functional as F
        x = self._quant_act(x)
        w = self._quant_weight(self.inner.weight)
        return F.linear(x, w, self.inner.bias)


class QuantedConv2D(_QuantedBase):
    _channel_axis = 0

    def forward(self, x):
        from ..nn import functional as F
        x = self._quant_act(x)
        w = self._quant_weight(self.inner.weight)
        inner = self.inner
        return F.conv2d(x, w, inner.bias, stride=inner._stride,
                        padding=inner._padding, dilation=inner._dilation,
                        groups=inner._groups)


class ImperativeQuantAware:
    """QAT driver (reference: imperative/qat.py ImperativeQuantAware):
    `quantize(model)` swaps supported sublayers in place."""

    def __init__(self, weight_bits=8, activation_bits=8,
                 weight_quantize_type="abs_max",
                 activation_quantize_type="moving_average_abs_max",
                 moving_rate=0.9, quantizable_layer_type=None):
        self._cfg = QuantConfig(weight_bits, activation_bits,
                                weight_quantize_type,
                                activation_quantize_type, moving_rate)

    def quantize(self, model):
        from ..nn import Conv2D, Linear
        for parent in model.sublayers(include_self=True):
            if isinstance(parent, _QuantedBase):
                continue   # idempotent: never re-wrap a quantized twin
            for name, child in list(parent.named_children()):
                if isinstance(child, Linear):
                    setattr(parent, name, QuantedLinear(child, self._cfg))
                elif isinstance(child, Conv2D) and \
                        type(child).__name__ == "Conv2D":
                    setattr(parent, name, QuantedConv2D(child, self._cfg))
        return model

    def save_quantized_model(self, model, path, input_spec=None):
        from ..jit import save as jit_save
        model.eval()
        jit_save(model, path, input_spec=input_spec)


class PostTrainingQuantization:
    """PTQ calibration (reference: post_training_quantization.py): run
    sample batches, accumulate per-layer |activation| histograms, derive
    the clip threshold with the chosen algo (KL / hist / mse / avg /
    abs_max / min_max — reference's supported set), emit per-channel (or
    per-tensor) weight scales + a fake-quantized eval model."""

    def __init__(self, model, algo="KL", weight_bits=8,
                 activation_bits=8, percentile=0.9999,
                 weight_quantize_type="channel_wise_abs_max"):
        self._model = model
        self._algo = algo
        self._bits = activation_bits
        self._wbits = weight_bits
        self._pct = percentile
        self._wtype = weight_quantize_type
        self._obs = {}       # layer name -> HistogramObserver
        self._hooks = []

    def _make_hook(self, name):
        def hook(layer, inputs, outputs=None):
            x = inputs[0] if isinstance(inputs, tuple) else inputs
            if isinstance(x, Tensor):
                self._obs.setdefault(name, HistogramObserver()).collect(
                    np.asarray(x.numpy(), np.float32))
        return hook

    def quantize(self, data_loader, batch_nums=8):
        """Calibrate, then return (model, scales)."""
        from ..nn import Conv2D, Linear
        targets = [(n, l) for n, l in self._model.named_sublayers()
                   if isinstance(l, (Linear, Conv2D))]
        for n, l in targets:
            self._hooks.append(l.register_forward_pre_hook(
                self._make_hook(n)))
        self._model.eval()
        for i, batch in enumerate(data_loader):
            x = batch[0] if isinstance(batch, (list, tuple)) else batch
            self._model(x)
            if i + 1 >= batch_nums:
                break
        for h in self._hooks:
            h.remove()
        scales = {}
        for n, l in targets:
            obs = self._obs.get(n)
            act_scale = obs.threshold(self._algo, self._bits, self._pct) \
                if obs else 0.0
            w = l.weight._data
            if self._wtype == "channel_wise_abs_max":
                axis = 1 if isinstance(l, Linear) else 0
                w_scale = channel_abs_max(np.asarray(w), axis)
                l.weight._data = fake_quant(
                    w, jnp.asarray(w_scale, jnp.float32), self._wbits,
                    channel_axis=axis)
                w_scale = w_scale.tolist()
            else:
                w_scale = float(jnp.max(jnp.abs(w)))
                l.weight._data = _fake_quant_raw(
                    w, jnp.float32(w_scale), self._wbits)
            scales[n] = {"activation": float(act_scale), "weight": w_scale}
        return self._model, scales


def fuse_conv_bn(model):
    """Fold eval-mode BatchNorm into the preceding Conv2D (reference:
    slim/quantization/imperative/fuse_utils.py fuse_conv_bn): w' = w*g/s,
    b' = (b-mu)*g/s + beta with s = sqrt(var+eps), per output channel.
    Mutates `model` in place and replaces the BN with Identity."""
    from ..nn import BatchNorm2D, Conv2D, Identity
    for parent in model.sublayers(include_self=True):
        children = list(parent.named_children())
        for (n1, c1), (n2, c2) in zip(children, children[1:]):
            if not (isinstance(c1, Conv2D) and
                    type(c1).__name__ == "Conv2D" and
                    isinstance(c2, BatchNorm2D)):
                continue
            gamma = c2.weight._data
            beta = c2.bias._data
            mu = c2._mean._data
            s = jnp.sqrt(c2._variance._data + c2._epsilon)
            f = (gamma / s).astype(c1.weight._data.dtype)
            c1.weight._data = c1.weight._data * f.reshape(-1, 1, 1, 1)
            b = c1.bias._data if c1.bias is not None else 0.0
            new_b = (b - mu) * (gamma / s) + beta
            if c1.bias is not None:
                c1.bias._data = new_b.astype(c1.bias._data.dtype)
            else:
                from ..core.tensor import to_tensor
                c1.bias = c1.create_parameter(
                    (c1.weight._data.shape[0],), is_bias=True)
                c1.bias._data = new_b.astype(c1.weight._data.dtype)
            setattr(parent, n2, Identity())
    return model
