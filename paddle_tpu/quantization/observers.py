"""Calibration observers for post-training quantization.

Reference: python/paddle/fluid/contrib/slim/quantization/
post_training_quantization.py (algo = KL / hist / mse / avg / abs_max /
min_max, histogram sampling with range growth) and cal_kl_threshold.py
(TensorRT-style KL-divergence threshold search). Reimplemented here as
vectorized numpy over a fixed-bin histogram whose range doubles to absorb
new batches (reference combine_histogram semantics).

All observers are host-side (calibration is a data pass, not a hot loop);
the resulting scales feed the jit-fusible fake-quant ops.
"""
import numpy as np

__all__ = ["HistogramObserver", "kl_threshold", "mse_threshold",
           "hist_percentile_threshold", "channel_abs_max"]

BINS = 2048


class HistogramObserver:
    """Accumulate |x| into a fixed-bin histogram, doubling the range (and
    pairwise-merging counts) whenever a batch exceeds it. Also tracks
    per-batch abs-max (for avg) and the global min/max (for min_max)."""

    def __init__(self, bins=BINS):
        self.bins = bins
        self.hist = np.zeros(bins, np.float64)
        self.hi = 0.0                 # current histogram range [0, hi)
        self.batch_maxes = []
        self.vmin = np.inf
        self.vmax = -np.inf

    def collect(self, arr):
        a = np.asarray(arr, np.float32).reshape(-1)
        if a.size == 0:
            return
        # non-finite samples are DROPPED, not binned: a single inf would
        # otherwise spin the range-doubling loop forever (hi can never
        # catch an infinite batch max), and a NaN poisons vmin/vmax and
        # every threshold derived from them. Calibration data with
        # overflow garbage should clip it upstream; the observer's job
        # is to stay deterministic regardless.
        finite = np.isfinite(a)
        if not finite.all():
            a = a[finite]
            if a.size == 0:
                return
        self.vmin = min(self.vmin, float(a.min()))
        self.vmax = max(self.vmax, float(a.max()))
        a = np.abs(a)
        m = float(a.max())
        self.batch_maxes.append(m)
        if m == 0.0 and self.hi == 0.0:
            return                        # nothing to bin yet (all-zero batch)
        if m > self.hi:
            if self.hi == 0.0:
                self.hi = m
            while self.hi < m:
                # double the range: merge neighbouring bin pairs into the
                # lower half, zero the upper half
                merged = self.hist.reshape(-1, 2).sum(1)
                self.hist[:self.bins // 2] = merged
                self.hist[self.bins // 2:] = 0.0
                self.hi *= 2.0
        idx = np.minimum((a / self.hi * self.bins).astype(np.int64),
                         self.bins - 1)
        self.hist += np.bincount(idx, minlength=self.bins)

    @property
    def bin_width(self):
        return self.hi / self.bins if self.hi > 0 else 0.0

    def abs_max(self):
        return max(self.batch_maxes) if self.batch_maxes else 0.0

    def avg(self):
        return float(np.mean(self.batch_maxes)) if self.batch_maxes else 0.0

    def threshold(self, algo, bits=8, percent=0.9999):
        if self.hi == 0.0:
            return 0.0
        if algo == "abs_max":
            return self.abs_max()
        if algo == "min_max":
            return max(abs(self.vmin), abs(self.vmax))
        if algo == "avg":
            return self.avg()
        if algo == "hist":
            return hist_percentile_threshold(self.hist, self.bin_width,
                                             percent)
        if algo == "KL":
            return kl_threshold(self.hist, self.bin_width, bits)
        if algo == "mse":
            return mse_threshold(self.hist, self.bin_width, bits)
        raise ValueError(
            f"unknown calibration algo '{algo}' (supported: abs_max, "
            "min_max, avg, hist, KL, mse)")


def hist_percentile_threshold(hist, bin_width, percent):
    """Threshold at the `percent` quantile of the |x| histogram (reference
    algo='hist': value of 'hist_percent' quantile)."""
    c = np.cumsum(hist)
    if c[-1] == 0:
        return 0.0
    i = int(np.searchsorted(c, percent * c[-1]))
    return (i + 1) * bin_width


def _quantize_hist(ref, levels):
    """Project a clipped |x| histogram onto `levels` uniform bins and
    expand back, preserving which source bins were empty (the reference's
    expand_quantized_bins semantics, vectorized)."""
    n = ref.shape[0]
    group = np.minimum(np.arange(n) * levels // n, levels - 1)
    q = np.bincount(group, weights=ref, minlength=levels)
    nonzero = (ref > 0).astype(np.float64)
    nz_per_group = np.bincount(group, weights=nonzero, minlength=levels)
    with np.errstate(divide="ignore", invalid="ignore"):
        per_bin = np.where(nz_per_group > 0, q / nz_per_group, 0.0)
    return per_bin[group] * nonzero


def kl_threshold(hist, bin_width, bits=8):
    """TensorRT-style KL calibration: pick the clip point i whose clipped+
    quantized distribution is closest (min KL divergence) to the observed
    one (reference cal_kl_threshold.py, vectorized per-candidate)."""
    hist = np.asarray(hist, np.float64)
    n = hist.shape[0]
    levels = 2 ** (bits - 1) - 1
    total = hist.sum()
    if total == 0:
        return 0.0
    best_i, best_kl = n, np.inf
    for i in range(max(levels, n // 2), n + 1):
        ref = hist[:i].copy()
        if ref[i - 1] == 0:
            continue
        ref[i - 1] += hist[i:].sum()        # fold outliers into the edge
        q = _quantize_hist(ref, levels)
        p_mask = ref > 0
        q_safe = np.where(q > 0, q, 1e-30)
        p = ref[p_mask] / ref.sum()
        qn = q_safe[p_mask] / max(q.sum(), 1e-30)
        kl = float(np.sum(p * np.log(p / qn)))
        if kl < best_kl:
            best_kl, best_i = kl, i
    return (best_i + 0.5) * bin_width


def mse_threshold(hist, bin_width, bits=8):
    """Scale minimizing quantization MSE, evaluated on histogram centers
    (reference algo='mse': threshold search by quant-dequant loss)."""
    hist = np.asarray(hist, np.float64)
    n = hist.shape[0]
    qmax = 2 ** (bits - 1) - 1
    centers = (np.arange(n) + 0.5) * bin_width
    abs_max = n * bin_width
    best_s, best_loss = abs_max, np.inf
    for frac in np.linspace(0.1, 1.0, 91):
        s = frac * abs_max
        q = np.clip(np.round(centers / s * qmax), -qmax, qmax) * s / qmax
        loss = float(np.sum(((centers - q) ** 2) * hist))
        if loss < best_loss:
            best_loss, best_s = loss, s
    return best_s


def channel_abs_max(w, axis):
    """Per-channel |w| max along every dim except `axis` (reference
    fake_channel_wise_quantize_abs_max: one scale per output channel)."""
    w = np.asarray(w)
    reduce_axes = tuple(i for i in range(w.ndim) if i != axis)
    return np.abs(w).max(axis=reduce_axes)
