"""Math ops (reference: python/paddle/tensor/math.py).

Each fn is the eager counterpart of a PHI kernel family; here they are all
jnp calls routed through apply_op so the tape sees them. Under jit the same
code traces straight into XLA, where fusion happens automatically (the
reference needed fused ops + graph passes for that).
"""
import jax
import jax.numpy as jnp

from ..core import dtype as _dt
from ..core.tensor import Tensor, apply_op, _binop, to_tensor


def _u(fn, name=None):
    def op(x, *a, **kw):
        kw.pop("name", None)
        return apply_op(fn, x, **kw)
    op.__name__ = name or getattr(fn, "__name__", "op")
    return op


exp = _u(jnp.exp)
expm1 = _u(jnp.expm1)
log = _u(jnp.log)
log2 = _u(jnp.log2)
log10 = _u(jnp.log10)
log1p = _u(jnp.log1p)
sqrt = _u(jnp.sqrt)
rsqrt = _u(lambda x: jax.lax.rsqrt(x), "rsqrt")
square = _u(jnp.square)
sin = _u(jnp.sin)
cos = _u(jnp.cos)
tan = _u(jnp.tan)
asin = _u(jnp.arcsin)
acos = _u(jnp.arccos)
atan = _u(jnp.arctan)
sinh = _u(jnp.sinh)
cosh = _u(jnp.cosh)
tanh = _u(jnp.tanh)
asinh = _u(jnp.arcsinh)
acosh = _u(jnp.arccosh)
atanh = _u(jnp.arctanh)
abs = _u(jnp.abs)
ceil = _u(jnp.ceil)
floor = _u(jnp.floor)
round = _u(jnp.round)
trunc = _u(jnp.trunc)
reciprocal = _u(jnp.reciprocal)
sign = _u(jnp.sign)
erf = _u(jax.scipy.special.erf, "erf")
erfinv = _u(jax.scipy.special.erfinv, "erfinv")
lgamma = _u(jax.scipy.special.gammaln, "lgamma")
digamma = _u(jax.scipy.special.digamma, "digamma")
neg = _u(jnp.negative)
frac = _u(lambda x: x - jnp.trunc(x), "frac")


def add(x, y, name=None):
    return _binop(jnp.add, x, y)


def subtract(x, y, name=None):
    return _binop(jnp.subtract, x, y)


def multiply(x, y, name=None):
    return _binop(jnp.multiply, x, y)


def divide(x, y, name=None):
    return _binop(jnp.divide, x, y)


def floor_divide(x, y, name=None):
    return _binop(jnp.floor_divide, x, y)


def mod(x, y, name=None):
    return _binop(jnp.mod, x, y)


remainder = mod


def pow(x, y, name=None):
    return _binop(jnp.power, x, y)


def maximum(x, y, name=None):
    return _binop(jnp.maximum, x, y)


def minimum(x, y, name=None):
    return _binop(jnp.minimum, x, y)


def fmax(x, y, name=None):
    return _binop(jnp.fmax, x, y)


def fmin(x, y, name=None):
    return _binop(jnp.fmin, x, y)


def atan2(x, y, name=None):
    return _binop(jnp.arctan2, x, y)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    def fn(a):
        out = a * scale + bias if bias_after_scale else (a + bias) * scale
        return out
    out = apply_op(fn, x)
    if act:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def clip(x, min=None, max=None, name=None):
    return apply_op(lambda a: jnp.clip(a, min, max), x)


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    if isinstance(axis, Tensor):
        return tuple(int(a) for a in axis.numpy().reshape(-1))
    return int(axis)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    d = _dt.convert_dtype(dtype)
    return apply_op(lambda a: jnp.sum(a, axis=_axis(axis), dtype=d, keepdims=keepdim), x)


def mean(x, axis=None, keepdim=False, name=None):
    return apply_op(lambda a: jnp.mean(a, axis=_axis(axis), keepdims=keepdim), x)


def max(x, axis=None, keepdim=False, name=None):
    return apply_op(lambda a: jnp.max(a, axis=_axis(axis), keepdims=keepdim), x)


def min(x, axis=None, keepdim=False, name=None):
    return apply_op(lambda a: jnp.min(a, axis=_axis(axis), keepdims=keepdim), x)


def amax(x, axis=None, keepdim=False, name=None):
    return max(x, axis, keepdim)


def amin(x, axis=None, keepdim=False, name=None):
    return min(x, axis, keepdim)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    d = _dt.convert_dtype(dtype)
    return apply_op(lambda a: jnp.prod(a, axis=_axis(axis), dtype=d, keepdims=keepdim), x)


def logsumexp(x, axis=None, keepdim=False, name=None):
    return apply_op(lambda a: jax.scipy.special.logsumexp(a, axis=_axis(axis), keepdims=keepdim), x)


def cumsum(x, axis=None, dtype=None, name=None):
    d = _dt.convert_dtype(dtype)
    return apply_op(lambda a: jnp.cumsum(a if axis is not None else a.reshape(-1),
                                         axis=axis, dtype=d), x)


def cumprod(x, dim=None, dtype=None, name=None):
    d = _dt.convert_dtype(dtype)
    return apply_op(lambda a: jnp.cumprod(a if dim is not None else a.reshape(-1),
                                          axis=dim, dtype=d), x)


def isnan(x, name=None):
    return apply_op(jnp.isnan, x)


def isinf(x, name=None):
    return apply_op(jnp.isinf, x)


def isfinite(x, name=None):
    return apply_op(jnp.isfinite, x)


def all(x, axis=None, keepdim=False, name=None):
    return apply_op(lambda a: jnp.all(a, axis=_axis(axis), keepdims=keepdim), x)


def any(x, axis=None, keepdim=False, name=None):
    return apply_op(lambda a: jnp.any(a, axis=_axis(axis), keepdims=keepdim), x)


def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        return inputs
    return apply_op(lambda *xs: sum_arrays(xs), *inputs)


def sum_arrays(xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


def multiplex(inputs, index, name=None):
    def fn(idx, *xs):
        stacked = jnp.stack(xs, axis=0)
        sel = idx.reshape(-1).astype(jnp.int32)
        return stacked[sel, jnp.arange(stacked.shape[1])]
    return apply_op(fn, index, *inputs)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply_op(lambda a: scale_b * jnp.tanh(scale_a * a), x)


def kron(x, y, name=None):
    return _binop(jnp.kron, x, y)


def diff(x, n=1, axis=-1, name=None):
    return apply_op(lambda a: jnp.diff(a, n=n, axis=axis), x)


def angle(x, name=None):
    return apply_op(jnp.angle, x)


def conj(x, name=None):
    return apply_op(jnp.conj, x)


def real(x, name=None):
    return apply_op(jnp.real, x)


def imag(x, name=None):
    return apply_op(jnp.imag, x)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply_op(lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf), x)


def lerp(x, y, weight, name=None):
    if isinstance(weight, Tensor):
        return apply_op(lambda a, b, w: a + w * (b - a), x, y, weight)
    return apply_op(lambda a, b: a + weight * (b - a), x, y)


def inner(x, y, name=None):
    return _binop(jnp.inner, x, y)


def outer(x, y, name=None):
    return _binop(jnp.outer, x, y)


def heaviside(x, y, name=None):
    return _binop(jnp.heaviside, x, y)


def rad2deg(x, name=None):
    return apply_op(jnp.rad2deg, x)


def deg2rad(x, name=None):
    return apply_op(jnp.deg2rad, x)


def gcd(x, y, name=None):
    return _binop(jnp.gcd, x, y)


def lcm(x, y, name=None):
    return _binop(jnp.lcm, x, y)


def take(x, index, mode="raise", name=None):
    """Reference take (tensor/math.py): output has INDEX's shape; 'raise'
    mode supports negative indices (idx + numel), 'wrap' takes the
    remainder, 'clip' clamps to [0, numel-1] (negatives -> 0)."""
    if mode not in ("raise", "wrap", "clip"):
        raise ValueError(
            f"'mode' in 'take' should be 'raise', 'wrap', 'clip', but "
            f"received {mode}.")
    if mode == "raise":
        # bounds-check when values are concrete (eager path — under a trace
        # the check is impossible and the reference's static mode doesn't
        # raise either)
        import jax.core as _jc
        xv = getattr(x, "_data", None)
        iv = getattr(index, "_data", None)
        if iv is not None and xv is not None \
                and not isinstance(iv, _jc.Tracer) \
                and not isinstance(xv, _jc.Tracer):
            import numpy as _np
            n = int(_np.prod(xv.shape)) if xv.ndim else 1
            inp = _np.asarray(iv)
            if inp.size and (int(inp.min()) < -n or int(inp.max()) >= n):
                raise ValueError(
                    f"(InvalidArgument) take: index out of range for input "
                    f"with {n} elements (valid range [-{n}, {n}), got "
                    f"min {int(inp.min())} max {int(inp.max())}).")

    def fn(a, i):
        flat = a.reshape(-1)
        n = flat.shape[0]
        idx = i.astype(jnp.int32)
        if mode == "raise":
            idx = jnp.where(idx < 0, idx + n, idx)
            out = jnp.take(flat, idx.reshape(-1), mode="clip")
        elif mode == "wrap":
            out = jnp.take(flat, idx.reshape(-1), mode="wrap")
        else:
            out = jnp.take(flat, idx.reshape(-1), mode="clip")
        return out.reshape(i.shape)
    return apply_op(fn, x, index)


def broadcast_shape(x_shape, y_shape):
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def increment(x, value=1.0, name=None):
    x._data = x._data + value
    return x


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply_op(lambda a: jnp.count_nonzero(a, axis=_axis(axis), keepdims=keepdim)
                    .astype(_dt.canonical(jnp.int64)), x)


def numel(x, name=None):
    return Tensor(jnp.asarray(x.size, dtype=_dt.canonical(jnp.int64)))
