"""Comparison / logic ops (reference: python/paddle/tensor/logic.py)."""
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op, _binop


def equal(x, y, name=None):
    return _binop(jnp.equal, x, y)


def not_equal(x, y, name=None):
    return _binop(jnp.not_equal, x, y)


def greater_than(x, y, name=None):
    return _binop(jnp.greater, x, y)


def greater_equal(x, y, name=None):
    return _binop(jnp.greater_equal, x, y)


def less_than(x, y, name=None):
    return _binop(jnp.less, x, y)


def less_equal(x, y, name=None):
    return _binop(jnp.less_equal, x, y)


def equal_all(x, y, name=None):
    return apply_op(lambda a, b: jnp.array_equal(a, b), x, y)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op(lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol,
                                              equal_nan=equal_nan), x, y)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op(lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol,
                                             equal_nan=equal_nan), x, y)


def logical_and(x, y, out=None, name=None):
    return _binop(jnp.logical_and, x, y)


def logical_or(x, y, out=None, name=None):
    return _binop(jnp.logical_or, x, y)


def logical_xor(x, y, out=None, name=None):
    return _binop(jnp.logical_xor, x, y)


def logical_not(x, out=None, name=None):
    return apply_op(jnp.logical_not, x)


def bitwise_and(x, y, out=None, name=None):
    return _binop(jnp.bitwise_and, x, y)


def bitwise_or(x, y, out=None, name=None):
    return _binop(jnp.bitwise_or, x, y)


def bitwise_xor(x, y, out=None, name=None):
    return _binop(jnp.bitwise_xor, x, y)


def bitwise_not(x, out=None, name=None):
    return apply_op(jnp.bitwise_not, x)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)
