"""In-place op variants (`paddle.tanh_`, `x.clip_()`, ...).

The reference exposes an `op_` twin for most unary/binary tensor ops
(python/paddle/tensor/__init__.py method list; generated in
python/paddle/tensor/math.py via `generate_inplace_fn` and the
`@inplace_apis_in_dygraph_only` wrappers). On TPU every array is immutable
inside XLA, so "in-place" is a frontend notion: compute the out-of-place
result and rebind the tensor's buffer — exactly what the reference's
dygraph inplace ops do to the underlying DenseTensor allocation from the
autograd tape's point of view (the VarBase keeps its identity, the storage
is replaced).

Like the reference (`core/tensor.py` fill_/zero_/add_ precedent in this
repo), the tensor object keeps its Python identity, `stop_gradient`, and
name; only `_data` changes.
"""
from ..core.tensor import Tensor
from . import extras, manipulation, math as _math

__all__ = []


def _make_inplace(fn, name):
    def inplace(x, *args, **kwargs):
        out = fn(x, *args, **kwargs)
        if isinstance(out, Tensor):
            # _replace adopts _data AND the tape node (rewiring the node's
            # outputs to x) so backward sees the op — plain `_data =` would
            # silently drop the gradient contribution.
            return x._replace(out)
        x._data = out
        return x
    inplace.__name__ = name
    inplace.__qualname__ = name
    inplace.__doc__ = (f"In-place variant of `{fn.__name__}`: writes the "
                       f"result back into `x` and returns it.")
    return inplace


# (public name, source module, functional name)
_INPLACE_OPS = [
    ("tanh_", _math, "tanh"),
    ("clip_", _math, "clip"),
    ("exp_", _math, "exp"),
    ("sqrt_", _math, "sqrt"),
    ("rsqrt_", _math, "rsqrt"),
    ("reciprocal_", _math, "reciprocal"),
    ("round_", _math, "round"),
    ("floor_", _math, "floor"),
    ("ceil_", _math, "ceil"),
    ("lerp_", _math, "lerp"),
    ("erfinv_", _math, "erfinv"),
    ("remainder_", _math, "remainder"),
    ("mod_", _math, "remainder"),
    ("squeeze_", manipulation, "squeeze"),
    ("unsqueeze_", manipulation, "unsqueeze"),
    ("flatten_", manipulation, "flatten"),
    ("reshape_", manipulation, "reshape"),
    ("scatter_", manipulation, "scatter"),
    ("put_along_axis_", manipulation, "put_along_axis"),
    ("index_add_", extras, "index_add"),
]

for _pub, _mod, _src in _INPLACE_OPS:
    _fn = getattr(_mod, _src)
    globals()[_pub] = _make_inplace(_fn, _pub)
    __all__.append(_pub)


def _patch_methods():
    for pub in __all__:
        setattr(Tensor, pub, globals()[pub])


_patch_methods()
