"""paddle.tensor equivalent: the functional tensor-op surface.

Mirrors python/paddle/tensor/* from the reference. Also monkey-patches the
op set onto core.Tensor as methods, the same way the reference patches
python ops onto the C tensor type (python/paddle/tensor/__init__.py).
"""
from ..core.tensor import Tensor
from . import creation, einsum as _einsum_mod, extras, linalg, logic, manipulation, math, random, search, stat

from .creation import *  # noqa: F401,F403
from .extras import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403
from .einsum import einsum  # noqa: F401
from .inplace import *  # noqa: F401,F403

_METHOD_MODULES = [math, manipulation, linalg, logic, search, stat, creation,
                   extras]

# names that must not become Tensor methods (creation ops, module helpers)
_SKIP = {
    "zeros", "ones", "full", "empty", "arange", "linspace", "logspace", "eye",
    "meshgrid", "to_tensor", "apply_op", "Tensor", "assign", "scatter_nd",
    "builtins_sum", "sum_arrays", "jax_topk", "broadcast_shape", "is_tensor",
    "tril_indices", "triu_indices", "gaussian",
}


def _patch_tensor_methods():
    for mod in _METHOD_MODULES:
        for name in dir(mod):
            if name.startswith("_") or name in _SKIP:
                continue
            fn = getattr(mod, name)
            if not callable(fn) or isinstance(fn, type):
                continue
            if getattr(fn, "__module__", "").startswith("jax"):
                continue
            if not hasattr(Tensor, name):
                setattr(Tensor, name, fn)
    # a few paddle-specific aliases
    Tensor.abs_ = Tensor.abs  # not truly inplace; acceptable alias


_patch_tensor_methods()


# Export only ops (and Tensor) — NOT the submodules, which would otherwise
# leak into the paddle_tpu top level via its star-import and shadow
# same-named namespace modules there (linalg bit us; math/random/search
# are waiting to). Root-cause fix for the round-3 linalg shadowing.
import types as _types

__all__ = [_n for _n, _v in list(globals().items())
           if not _n.startswith("_") and not isinstance(_v, _types.ModuleType)]
