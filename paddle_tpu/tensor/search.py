"""Search/sort ops (reference: python/paddle/tensor/search.py)."""
import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as _dt
from ..core.tensor import Tensor, apply_op


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    def fn(a):
        out = jnp.argmax(a if axis is not None else a.reshape(-1),
                         axis=axis if axis is not None else 0)
        if keepdim and axis is not None:
            out = jnp.expand_dims(out, axis)
        return out.astype(_dt.canonical(dtype or jnp.int64))
    return apply_op(fn, x)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    def fn(a):
        out = jnp.argmin(a if axis is not None else a.reshape(-1),
                         axis=axis if axis is not None else 0)
        if keepdim and axis is not None:
            out = jnp.expand_dims(out, axis)
        return out.astype(_dt.canonical(dtype or jnp.int64))
    return apply_op(fn, x)


def argsort(x, axis=-1, descending=False, name=None):
    def fn(a):
        idx = jnp.argsort(a, axis=axis)
        if descending:
            idx = jnp.flip(idx, axis=axis)
        return idx.astype(_dt.canonical(jnp.int64))
    return apply_op(fn, x)


def sort(x, axis=-1, descending=False, name=None):
    def fn(a):
        out = jnp.sort(a, axis=axis)
        if descending:
            out = jnp.flip(out, axis=axis)
        return out
    return apply_op(fn, x)


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    if isinstance(k, Tensor):
        k = int(k._data)

    def fn(a):
        ax = axis if axis is not None else a.ndim - 1
        moved = jnp.moveaxis(a, ax, -1)
        vals, idx = jax_topk(moved, k, largest)
        return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx, -1, ax).astype(_dt.canonical(jnp.int64))
    return apply_op(fn, x)


def jax_topk(a, k, largest):
    import jax
    if largest:
        v, i = jax.lax.top_k(a, k)
    else:
        v, i = jax.lax.top_k(-a, k)
        v = -v
    return v, i


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=False)
    return apply_op(lambda c, a, b: jnp.where(c.astype(bool), a, b), condition, x, y)


def nonzero(x, as_tuple=False):
    data = np.asarray(x._data)
    nz = np.nonzero(data)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i[:, None].astype(np.int64))) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1).astype(np.int64)))


def masked_fill(x, mask, value, name=None):
    v = value._data if isinstance(value, Tensor) else value
    return apply_op(lambda a, m: jnp.where(m.astype(bool), jnp.asarray(v, a.dtype), a), x, mask)


def index_put(x, indices, value, accumulate=False, name=None):
    idx = tuple(i._data if isinstance(i, Tensor) else i for i in indices)

    def fn(a, v):
        if accumulate:
            return a.at[idx].add(v.astype(a.dtype))
        return a.at[idx].set(v.astype(a.dtype))
    return apply_op(fn, x, value)


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    def fn(s, v):
        out = jnp.searchsorted(s, v, side="right" if right else "left")
        return out.astype(jnp.int32 if out_int32
                          else _dt.canonical(jnp.int64))
    return apply_op(fn, sorted_sequence, values)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def fn(a):
        srt = jnp.sort(a, axis=axis)
        idx = jnp.argsort(a, axis=axis)
        vals = jnp.take(srt, k - 1, axis=axis)
        inds = jnp.take(idx, k - 1, axis=axis).astype(_dt.canonical(jnp.int64))
        if keepdim:
            vals = jnp.expand_dims(vals, axis)
            inds = jnp.expand_dims(inds, axis)
        return vals, inds
    return apply_op(fn, x)


def _mode_last(a):
    """Mode over the trailing axis, a: (..., n). Module-level so mode()'s op
    closure stays cacheable (a per-call inner function would defeat the eager
    executable cache's code-identity key)."""
    n = a.shape[-1]
    srt = jnp.sort(a, axis=-1)
    lo = jax.vmap(lambda s: jnp.searchsorted(s, s, side="left"))(
        srt.reshape(-1, n)).reshape(srt.shape)
    hi = jax.vmap(lambda s: jnp.searchsorted(s, s, side="right"))(
        srt.reshape(-1, n)).reshape(srt.shape)
    counts = hi - lo
    best = jnp.argmax(counts, axis=-1)            # first max => smallest value
    vals = jnp.take_along_axis(srt, best[..., None], axis=-1)[..., 0]
    pos = jnp.arange(n)
    idx = jnp.argmax(jnp.where(a == vals[..., None], pos, -1), axis=-1)
    return vals, idx.astype(_dt.canonical(jnp.int64))


def mode(x, axis=-1, keepdim=False, name=None):
    """paddle.mode: most frequent value (and its index) along `axis`.

    Reference: paddle/phi/kernels/cpu/mode_kernel.cc. TPU-first shape-static
    algorithm: sort the axis, get each element's run length via two
    searchsorted passes (O(n log n), no S×S equality matrix), pick the
    smallest modal value, then report the index of its last occurrence in the
    unsorted input (paddle tie-break).
    """
    def fn(a):
        ax = axis % a.ndim
        moved = jnp.moveaxis(a, ax, -1)
        vals, idx = _mode_last(moved)
        if keepdim:
            vals = jnp.expand_dims(vals, ax)
            idx = jnp.expand_dims(idx, ax)
        return vals, idx
    return apply_op(fn, x)


def median(x, axis=None, keepdim=False, name=None):
    """Reference-exact median (python/paddle/tensor/stat.py:376): even
    counts average the two middle values; output is float32 (the reference
    keeps float64 only for f64 inputs, which the x64-disabled policy maps
    to f32 anyway); axis=None flattens and returns shape [1] (keepdim ->
    [1]*ndim), NOT a scalar; axis must be an int in [-rank, rank). Any
    NaN OR +-inf in a slice yields NaN — the reference adds
    `sum(isnan(x)*x)` to the result (stat.py:455) and 0*inf is NaN, so
    infs poison slices exactly like NaNs do."""
    def fn(a):
        if axis is not None and (not isinstance(axis, int)
                                 or not -a.ndim <= axis < a.ndim):
            raise ValueError(
                "In median, axis should be none or an integer in range "
                f"[-rank(x), rank(x)), got {axis!r}")
        red = a.reshape(-1) if axis is None else a
        ax = 0 if axis is None else axis
        out = jnp.median(red, axis=ax).astype(jnp.float32)
        # the reference adds `sum(isnan(x)*x)` (stat.py:455), which is NaN
        # when the slice holds a NaN (1*nan) OR an inf (0*inf). The literal
        # form can't be used here: XLA rewrites convert(isnan)*x into a
        # select, folding the 0*inf corner away — so state the poison
        # condition explicitly
        red_f = red.astype(jnp.float32)
        bad = jnp.any(jnp.isnan(red_f) | jnp.isinf(red_f), axis=ax)
        out = jnp.where(bad, jnp.float32(jnp.nan), out)
        if axis is None:
            return out.reshape([1] * a.ndim) if keepdim else out.reshape([1])
        return jnp.expand_dims(out, axis) if keepdim else out
    return apply_op(fn, x)


def nanmedian(x, axis=None, keepdim=True, name=None):
    """Reference signature (stat.py:278): keepdim defaults to TRUE (unlike
    median), axis may be an int or a list/tuple of ints, and the output
    dtype follows the input."""
    if isinstance(axis, (list, tuple)):
        if not axis:
            raise ValueError("Axis list should not be empty.")
        ax = tuple(axis)
    else:
        ax = axis

    def fn(a):
        return jnp.nanmedian(a, axis=ax, keepdims=keepdim).astype(a.dtype)
    return apply_op(fn, x)


def _check_q(q):
    """Reference quantile validation (stat.py:506,602): q must be non-empty
    and each value in [0, 1]. Lists normalize to tuples so the op closure
    stays hashable for the eager compiled-op cache; a single-element list
    behaves like a scalar (reference stacks a leading dim only for
    len(q) > 1, stat.py:595-598)."""
    if isinstance(q, (list, tuple)):
        if not q:
            raise ValueError("q should not be empty")
        qs = tuple(float(v) for v in q)
    else:
        qs = (float(q),)
    for v in qs:
        if not 0 <= v <= 1:
            raise ValueError(
                f"q should be in range [0, 1], but got {v!r}")
    if isinstance(q, (list, tuple)) and len(qs) > 1:
        return qs
    return qs[0]


def quantile(x, q, axis=None, keepdim=False, name=None):
    """Reference semantics (stat.py:602): q may be a scalar or list (a list
    of len > 1 -> leading dim of len(q); a one-element list behaves like a
    scalar) and must lie in [0, 1]; axis may be an int or list; NaN in a
    reduced row yields NaN for that row's quantiles."""
    qv = _check_q(q)
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply_op(lambda a: jnp.quantile(a, jnp.asarray(qv), axis=ax,
                                           keepdims=keepdim), x)
