"""Shape/layout manipulation ops (reference: python/paddle/tensor/manipulation.py)."""
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply_op


def _shape_arg(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy().reshape(-1))

    def one(s):
        if isinstance(s, Tensor):
            return int(s._data)
        try:
            return int(s)
        except Exception:   # export symbolic dim (shape-polymorphic save):
            return s        # int() is inconclusive; jnp takes it verbatim
    return tuple(one(s) for s in shape)


def reshape(x, shape, name=None):
    return apply_op(lambda a: jnp.reshape(a, _shape_arg(shape)), x)


def reshape_(x, shape, name=None):
    return x._replace(reshape(x, shape))


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def fn(a):
        nd = a.ndim
        s = start_axis % nd if nd else 0
        e = stop_axis % nd if nd else 0
        new_shape = a.shape[:s] + (-1,) + a.shape[e + 1:]
        return jnp.reshape(a, new_shape)
    return apply_op(fn, x)


def squeeze(x, axis=None, name=None):
    def fn(a):
        if axis is None:
            return jnp.squeeze(a)
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        axes = tuple(ax % a.ndim for ax in axes if a.shape[ax % a.ndim] == 1)
        return jnp.squeeze(a, axis=axes) if axes else a
    return apply_op(fn, x)


def unsqueeze(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    axes = [int(a._data) if isinstance(a, Tensor) else int(a) for a in axes]

    def fn(a):
        out = a
        for ax in sorted(axes):
            out = jnp.expand_dims(out, ax)
        return out
    return apply_op(fn, x)


def transpose(x, perm=None, name=None):
    return apply_op(lambda a: jnp.transpose(a, perm), x)


def t(x, name=None):
    return apply_op(lambda a: a.T if a.ndim >= 2 else a, x)


def moveaxis(x, source, destination, name=None):
    return apply_op(lambda a: jnp.moveaxis(a, source, destination), x)


def swapaxes(x, axis0, axis1, name=None):
    return apply_op(lambda a: jnp.swapaxes(a, axis0, axis1), x)


def concat(x, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis._data)
    return apply_op(lambda *xs: jnp.concatenate(xs, axis=axis), *x)


def stack(x, axis=0, name=None):
    return apply_op(lambda *xs: jnp.stack(xs, axis=axis), *x)


def split(x, num_or_sections, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis._data)
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        if dim % num_or_sections != 0:
            raise ValueError(
                f"split: dimension {dim} along axis {axis} is not divisible "
                f"by {num_or_sections}")
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [int(s) for s in num_or_sections]
        if any(s < 0 for s in sizes):
            known = builtins_sum(s for s in sizes if s >= 0)
            sizes = [s if s >= 0 else dim - known for s in sizes]
    offsets = np.cumsum([0] + sizes)
    outs = []
    for i in range(len(sizes)):
        lo, hi = int(offsets[i]), int(offsets[i + 1])
        outs.append(apply_op(lambda a, lo=lo, hi=hi: jnp.take(a, jnp.arange(lo, hi), axis=axis), x))
    return outs


def builtins_sum(it):
    total = 0
    for v in it:
        total += v
    return total


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(x, axis=0, name=None):
    n = x.shape[axis]
    return [squeeze(s, axis) for s in split(x, n, axis)]


def tile(x, repeat_times, name=None):
    reps = _shape_arg(repeat_times)
    return apply_op(lambda a: jnp.tile(a, reps), x)


def expand(x, shape, name=None):
    tgt = _shape_arg(shape)

    def fn(a):
        full = list(tgt)
        src = list(a.shape)
        # paddle: -1 keeps the original dim
        src = [1] * (len(full) - len(src)) + src
        for i, s in enumerate(full):
            if s == -1:
                full[i] = src[i]
        return jnp.broadcast_to(a, tuple(full))
    return apply_op(fn, x)


def expand_as(x, y, name=None):
    return apply_op(lambda a, b: jnp.broadcast_to(a, b.shape), x, y)


def broadcast_to(x, shape, name=None):
    return apply_op(lambda a: jnp.broadcast_to(a, _shape_arg(shape)), x)


def broadcast_tensors(inputs, name=None):
    shapes = [tuple(t.shape) for t in inputs]
    tgt = jnp.broadcast_shapes(*shapes)
    return [broadcast_to(t, tgt) for t in inputs]


def flip(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return apply_op(lambda a: jnp.flip(a, axis=tuple(axes)), x)


def roll(x, shifts, axis=None, name=None):
    return apply_op(lambda a: jnp.roll(a, shifts, axis=axis), x)


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply_op(lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), x)


def gather(x, index, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis._data)
    return apply_op(lambda a, i: jnp.take(a, i.reshape(-1).astype(jnp.int32), axis=axis),
                    x, index)


def gather_nd(x, index, name=None):
    def fn(a, idx):
        idx = idx.astype(jnp.int32)
        return a[tuple(jnp.moveaxis(idx, -1, 0))]
    return apply_op(fn, x, index)


def take_along_axis(arr, indices, axis, name=None):
    return apply_op(lambda a, i: jnp.take_along_axis(a, i.astype(jnp.int32), axis=axis),
                    arr, indices)


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    def fn(a, i, v):
        i = i.astype(jnp.int32)
        v = jnp.broadcast_to(v, i.shape).astype(a.dtype)
        dims = [jnp.arange(s).reshape([-1 if k == d else 1 for k in range(i.ndim)])
                for d, s in enumerate(i.shape)]
        full_idx = tuple(i if d == axis else jnp.broadcast_to(dims[d], i.shape)
                         for d in range(i.ndim))
        if reduce == "add":
            return a.at[full_idx].add(v)
        if reduce == "multiply" or reduce == "mul":
            return a.at[full_idx].multiply(v)
        return a.at[full_idx].set(v)
    return apply_op(fn, arr, indices, values)


def scatter(x, index, updates, overwrite=True, name=None):
    def fn(a, i, u):
        i = i.reshape(-1).astype(jnp.int32)
        if overwrite:
            return a.at[i].set(u.astype(a.dtype))
        zeroed = a.at[i].set(jnp.zeros_like(u, dtype=a.dtype))
        return zeroed.at[i].add(u.astype(a.dtype))
    return apply_op(fn, x, index, updates)


def scatter_nd_add(x, index, updates, name=None):
    def fn(a, i, u):
        i = i.astype(jnp.int32)
        return a.at[tuple(jnp.moveaxis(i, -1, 0))].add(u.astype(a.dtype))
    return apply_op(fn, x, index, updates)


def scatter_nd(index, updates, shape, name=None):
    from .creation import zeros
    return scatter_nd_add(zeros(shape, dtype=updates.dtype), index, updates)


def index_select(x, index, axis=0, name=None):
    return gather(x, index, axis)


def index_sample(x, index):
    def fn(a, i):
        rows = jnp.arange(a.shape[0])[:, None]
        return a[rows, i.astype(jnp.int32)]
    return apply_op(fn, x, index)


def masked_select(x, mask, name=None):
    # Dynamic output shape: computed on host (not jittable) — paddle parity.
    data = np.asarray(x._data)
    m = np.asarray(mask._data).astype(bool)
    return Tensor(jnp.asarray(data[np.broadcast_to(m, data.shape)]))


import builtins as _builtins  # noqa: E402


def slice(input, axes, starts, ends, name=None):
    def fn(a):
        idx = [_builtins.slice(None)] * a.ndim
        for ax, s, e in zip(axes, starts, ends):
            s = int(s._data) if isinstance(s, Tensor) else int(s)
            e = int(e._data) if isinstance(e, Tensor) else int(e)
            idx[ax] = _builtins.slice(s, e)
        return a[tuple(idx)]
    return apply_op(fn, input)


def strided_slice(x, axes, starts, ends, strides, name=None):
    def fn(a):
        idx = [_builtins.slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = _builtins.slice(int(s), int(e), int(st))
        return a[tuple(idx)]
    return apply_op(fn, x)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    data = np.asarray(x._data)
    res = np.unique(data, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    return tuple(Tensor(jnp.asarray(r)) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, name=None):
    data = np.asarray(x._data).reshape(-1) if axis is None else np.asarray(x._data)
    keep = np.ones(len(data), dtype=bool)
    keep[1:] = data[1:] != data[:-1]
    out = Tensor(jnp.asarray(data[keep]))
    return out


def repeat_interleave(x, repeats, axis=None, name=None):
    r = repeats._data if isinstance(repeats, Tensor) else repeats
    return apply_op(lambda a: jnp.repeat(a if axis is not None else a.reshape(-1),
                                         r, axis=axis if axis is not None else 0), x)


def as_real(x, name=None):
    return apply_op(lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1), x)


def as_complex(x, name=None):
    return apply_op(lambda a: a[..., 0] + 1j * a[..., 1], x)


def tensordot(x, y, axes=2, name=None):
    return apply_op(lambda a, b: jnp.tensordot(a, b, axes=axes), x, y)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    def fn(a):
        size = index_num // nshards
        lo, hi = shard_id * size, (shard_id + 1) * size
        in_range = (a >= lo) & (a < hi)
        return jnp.where(in_range, a - lo, ignore_value)
    return apply_op(fn, input)
