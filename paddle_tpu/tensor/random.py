"""Random ops (reference: python/paddle/tensor/random.py).

All sampling routes through core.random.next_key() so eager calls are
reproducible after paddle_tpu.seed(n) and jit-traced calls pick up the
traced key installed by the step runner (core/random.py traced_rng).
"""
import jax
import jax.numpy as jnp

from ..core import dtype as _dt
from ..core.random import next_key
from ..core.tensor import Tensor, apply_op


def _d(dtype):
    d = _dt.canonical(dtype)      # documented 64->32 device-boundary policy
    return d if d is not None else _dt.get_default_dtype()


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype=dtype, min=0.0, max=1.0)


def randn(shape, dtype=None, name=None):
    return standard_normal(shape, dtype)


def standard_normal(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(next_key(), tuple(shape), dtype=_d(dtype)))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._data if isinstance(mean, Tensor) else mean
        s = std._data if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        return Tensor(jax.random.normal(next_key(), shp) * s + m)
    shp = tuple(shape) if shape is not None else ()
    return Tensor(jax.random.normal(next_key(), shp, dtype=_dt.get_default_dtype()) * std + mean)


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    return Tensor(jax.random.uniform(next_key(), tuple(shape), dtype=_d(dtype),
                                     minval=min, maxval=max))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    x._data = jax.random.uniform(next_key(), tuple(x._data.shape),
                                 dtype=x._data.dtype, minval=min, maxval=max)
    return x


def randint(low=0, high=None, shape=(1,), dtype=None, name=None):
    if high is None:
        low, high = 0, low
    d = _dt.canonical(dtype) or _dt.canonical(_dt.int64)
    return Tensor(jax.random.randint(next_key(), tuple(shape), low, high, dtype=d))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    return randint(low, high, tuple(x.shape), dtype or x.dtype)


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(next_key(), n).astype(_dt.canonical(dtype)))


def multinomial(x, num_samples=1, replacement=False, name=None):
    def sample(p):
        logits = jnp.log(jnp.maximum(p, 1e-30))
        if replacement:
            return jax.random.categorical(next_key(), logits, axis=-1,
                                          shape=p.shape[:-1] + (num_samples,))
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(next_key(), p.shape)
        _, idx = jax.lax.top_k(logits + g, num_samples)
        return idx
    return Tensor(sample(x._data).astype(_dt.canonical(_dt.int64)))


def bernoulli(x, name=None):
    return Tensor(jax.random.bernoulli(next_key(), x._data).astype(x._data.dtype))


def poisson(x, name=None):
    return Tensor(jax.random.poisson(next_key(), x._data).astype(x._data.dtype))


def exponential_(x, lam=1.0, name=None):
    x._data = jax.random.exponential(next_key(), tuple(x._data.shape),
                                     dtype=x._data.dtype) / lam
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    x._data = jax.random.normal(next_key(), tuple(x._data.shape),
                                dtype=x._data.dtype) * std + mean
    return x
