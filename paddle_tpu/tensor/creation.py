"""Creation ops (reference: python/paddle/tensor/creation.py)."""
import jax.numpy as jnp
import numpy as np

from ..core import dtype as _dt
from ..core.tensor import Tensor, to_tensor, apply_op  # noqa: F401


def _d(dtype):
    d = _dt.canonical(dtype)      # documented 64->32 device-boundary policy
    return d if d is not None else _dt.get_default_dtype()


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(tuple(shape), dtype=_d(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(tuple(shape), dtype=_d(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    return Tensor(jnp.full(tuple(shape), fill_value, dtype=_d(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    return apply_op(lambda a: jnp.zeros_like(a, dtype=_dt.convert_dtype(dtype)), x)


def ones_like(x, dtype=None, name=None):
    return apply_op(lambda a: jnp.ones_like(a, dtype=_dt.convert_dtype(dtype)), x)


def full_like(x, fill_value, dtype=None, name=None):
    return apply_op(lambda a: jnp.full_like(a, fill_value, dtype=_dt.convert_dtype(dtype)), x)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    for v in (start, end, step):
        if isinstance(v, Tensor):
            raise TypeError("arange over Tensor bounds is not supported; pass numbers")
    if end is None:
        start, end = 0, start
    if dtype is None:
        if all(isinstance(v, (int, np.integer)) for v in (start, end, step)):
            dtype = _dt.int64
        else:
            dtype = _dt.get_default_dtype()
    return Tensor(jnp.arange(start, end, step, dtype=_d(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    return Tensor(jnp.linspace(start, stop, int(num), dtype=_d(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(start, stop, int(num), base=base, dtype=_d(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_d(dtype)))


def diag(x, offset=0, padding_value=0, name=None):
    def fn(a):
        if a.ndim == 1:
            out = jnp.diag(a, k=offset)
            if padding_value != 0:
                mask = jnp.diag(jnp.ones_like(a), k=offset).astype(bool)
                out = jnp.where(mask, out, jnp.asarray(padding_value, out.dtype))
            return out
        return jnp.diagonal(a, offset=offset)
    return apply_op(fn, x)


def diagflat(x, offset=0, name=None):
    return apply_op(lambda a: jnp.diagflat(a, k=offset), x)


def tril(x, diagonal=0, name=None):
    return apply_op(lambda a: jnp.tril(a, k=diagonal), x)


def triu(x, diagonal=0, name=None):
    return apply_op(lambda a: jnp.triu(a, k=diagonal), x)


def meshgrid(*args, **kwargs):
    tensors = args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args
    outs = jnp.meshgrid(*[t._data if isinstance(t, Tensor) else jnp.asarray(t) for t in tensors],
                        indexing="ij")
    return [Tensor(o) for o in outs]


def assign(x, output=None):
    src = to_tensor(x) if not isinstance(x, Tensor) else x.clone()
    if output is not None:
        output._replace(src)
        return output
    return src


def clone(x, name=None):
    return x.clone()


def complex(real, imag, name=None):
    return apply_op(lambda r, i: r + 1j * i, real, imag)


def tolist(x):
    return x.tolist()
