"""Op-breadth batch (round 3): tensor ops the reference exposes that were
still missing (VERDICT r2 missing #3).

Reference: python/paddle/tensor/{math,manipulation,linalg,creation}.py.
All shape-static, jit-friendly lowerings; inplace `op_` variants follow the
framework-wide policy of updating the Tensor's buffer in place (the
reference's inplace ops mutate the DenseTensor holder the same way).
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply_op


# ------------------------------------------------------------ linalg-ish

def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply_op(lambda i, a, b: beta * i + alpha * (a @ b), input, x, y)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op(
        lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2), x)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op(
        lambda a: jnp.diagonal(a, offset=offset, axis1=axis1, axis2=axis2), x)


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    def fn(a):
        if axis is None:
            a = a.reshape(-1)
            ax = 0
        else:
            ax = axis
        return jnp.linalg.norm(a, ord=p, axis=ax, keepdims=keepdim)
    return apply_op(fn, x)


def renorm(x, p, axis, max_norm, name=None):
    """Per-slice p-norm clamp along `axis` (reference renorm_kernel)."""
    def fn(a):
        moved = jnp.moveaxis(a, axis, 0)
        flat = moved.reshape(moved.shape[0], -1)
        norms = jnp.linalg.norm(flat, ord=p, axis=1)
        scale_ = jnp.where(norms > max_norm,
                           max_norm / jnp.maximum(norms, 1e-12), 1.0)
        out = flat * scale_[:, None]
        return jnp.moveaxis(out.reshape(moved.shape), 0, axis)
    return apply_op(fn, x)


def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True,
              name=None):
    """Unpack the packed LU factorization (reference lu_unpack op)."""
    def fn(a, piv):
        m, n = a.shape[-2], a.shape[-1]
        k = min(m, n)
        L = jnp.tril(a[..., :, :k], -1) + jnp.eye(m, k, dtype=a.dtype)
        U = jnp.triu(a[..., :k, :])
        # pivots (1-based sequential swaps) -> permutation matrix
        def perm_of(pv):
            idx = jnp.arange(m)

            def body(i, idx):
                j = pv[i] - 1
                a_i, a_j = idx[i], idx[j]
                idx = idx.at[i].set(a_j).at[j].set(a_i)
                return idx

            idx = jax.lax.fori_loop(0, pv.shape[0], body, idx)
            # swaps give perm with A[perm] = L U, i.e. I[perm] @ A = L @ U,
            # so A = I[perm]^T @ L @ U
            return jnp.eye(m, dtype=a.dtype)[idx].T

        batch = piv.shape[:-1]
        if batch:
            P = jax.vmap(perm_of)(piv.reshape(-1, piv.shape[-1]))
            P = P.reshape(batch + (m, m))
        else:
            P = perm_of(piv)
        # A = P @ L @ U with P as produced by the factorization
        return P, L, U
    return apply_op(fn, lu_data, lu_pivots)


# ----------------------------------------------------------- elementwise

def logit(x, eps=None, name=None):
    def fn(a):
        if eps is not None:
            a = jnp.clip(a, eps, 1.0 - eps)
        return jnp.log(a / (1.0 - a))
    return apply_op(fn, x)


def sgn(x, name=None):
    """sign for real; x/|x| for complex (reference sgn_kernel)."""
    def fn(a):
        if jnp.iscomplexobj(a):
            mag = jnp.abs(a)
            return jnp.where(mag == 0, 0.0 + 0.0j, a / jnp.maximum(mag, 1e-38))
        return jnp.sign(a)
    return apply_op(fn, x)


def logcumsumexp(x, axis=None, dtype=None, name=None):
    def fn(a):
        if axis is None:
            a = a.reshape(-1)
            ax = 0
        else:
            ax = axis
        # numerically-stable running logsumexp as one associative scan —
        # logaddexp is associative, so this is O(log n) depth on TPU
        return jax.lax.associative_scan(jnp.logaddexp, a, axis=ax)
    return apply_op(fn, x)


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    """Same list-q / list-axis / q-range conventions as quantile
    (stat.py:665)."""
    from .search import _check_q
    qv = _check_q(q)
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply_op(
        lambda a: jnp.nanquantile(a, jnp.asarray(qv), axis=ax,
                                  keepdims=keepdim), x)


def cast(x, dtype):
    from ..core import dtype as _dt
    d = _dt.canonical(dtype)      # documented 64->32 device-boundary policy
    return apply_op(lambda a: a.astype(d), x)


# --------------------------------------------------------- index/shape ops

def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    from ..core import dtype as _dt

    def fn(a, seq):
        out = jnp.searchsorted(seq, a, side="right" if right else "left")
        return out.astype(jnp.int32 if out_int32
                          else _dt.canonical(jnp.int64))
    return apply_op(fn, x, sorted_sequence)


def index_add(x, index, axis, value, name=None):
    def fn(a, idx, v):
        moved = jnp.moveaxis(a, axis, 0)
        vm = jnp.moveaxis(v, axis, 0)
        out = moved.at[idx].add(vm)
        return jnp.moveaxis(out, 0, axis)
    return apply_op(fn, x, index, value)


def crop(x, shape=None, offsets=None, name=None):
    def fn(a, *rest):
        shp = [int(s) for s in np.asarray(shape).tolist()] if shape is not None \
            else list(a.shape)
        offs = [int(o) for o in np.asarray(offsets).tolist()] if offsets is not None \
            else [0] * a.ndim
        shp = [a.shape[i] - offs[i] if s == -1 else s
               for i, s in enumerate(shp)]
        return jax.lax.dynamic_slice(a, offs, shp)
    return apply_op(fn, x)


def unstack(x, axis=0, num=None, name=None):
    def fn(a):
        n = num if num is not None else a.shape[axis]
        return tuple(jnp.squeeze(s, axis)
                     for s in jnp.split(a, n, axis=axis))
    return apply_op(fn, x)


def tril_indices(row, col=None, offset=0, dtype="int64", name=None):
    col = row if col is None else col
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), jnp.dtype("int32")
                              if dtype in ("int32",) else None))


def triu_indices(row, col=None, offset=0, dtype="int64", name=None):
    col = row if col is None else col
    r, c = np.triu_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), jnp.dtype("int32")
                              if dtype in ("int32",) else None))


def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1, name=None):
    def fn(a, v):
        moved = jnp.moveaxis(a, (dim1, dim2), (-2, -1))
        m, n = moved.shape[-2:]
        i0, j0 = (0, offset) if offset >= 0 else (-offset, 0)
        k = min(m - i0, n - j0)
        ii = i0 + jnp.arange(k)
        jj = j0 + jnp.arange(k)
        vb = jnp.broadcast_to(v, moved.shape[:-2] + (k,)).astype(a.dtype)
        upd = moved.at[..., ii, jj].set(vb)
        return jnp.moveaxis(upd, (-2, -1), (dim1, dim2))
    return apply_op(fn, x, y)


def rank(input, name=None):
    return Tensor(jnp.asarray(input.ndim if hasattr(input, "ndim")
                              else np.ndim(input), jnp.int32))


# ------------------------------------------------------------- inplace ops

def _make_inplace(fn_name):
    """paddle's `op_` inplace variants: compute out-of-place (XLA arrays are
    immutable), then rebind the Tensor's buffer — the same observable
    semantics as the reference's inplace DenseTensor mutation."""
    def inplace(self, *args, **kwargs):
        out = getattr(self, fn_name)(*args, **kwargs)
        self._data = out._data
        return self
    inplace.__name__ = fn_name + "_"
    return inplace


_INPLACE = ["add", "subtract", "multiply", "clip", "scale", "tanh", "erfinv",
            "fill", "flatten", "lerp", "remainder", "squeeze", "unsqueeze",
            "exp", "sqrt", "rsqrt", "reciprocal", "round", "floor", "ceil",
            "sigmoid", "softmax", "cast"]


def fill(x, value, name=None):
    return apply_op(lambda a: jnp.full_like(a, value), x)


def zero_(x):
    x._data = jnp.zeros_like(x._data)
    return x


def fill_diagonal_(x, value, offset=0, wrap=False, name=None):
    if wrap:
        raise NotImplementedError("fill_diagonal_: wrap=True is not "
                                  "supported")

    def fn(a):
        m, n = a.shape[-2], a.shape[-1]
        i0, j0 = (0, offset) if offset >= 0 else (-offset, 0)
        k = min(m - i0, n - j0)
        if k <= 0:
            return a
        i = i0 + jnp.arange(k)
        j = j0 + jnp.arange(k)
        return a.at[..., i, j].set(value)
    x._data = fn(x._data)
    return x


def _patch_inplace():
    from ..core.tensor import Tensor as T
    if not hasattr(T, "fill"):
        T.fill = fill
    if not hasattr(T, "cast"):
        T.cast = cast
    for base in _INPLACE:
        if hasattr(T, base) and not hasattr(T, base + "_"):
            setattr(T, base + "_", _make_inplace(base))
    T.zero_ = zero_
    T.fill_diagonal_ = fill_diagonal_


_patch_inplace()


# ----------------------------------------------- numeric helpers (round 3b)

def vander(x, n=None, increasing=False, name=None):
    return apply_op(lambda a: jnp.vander(a, N=n, increasing=increasing), x)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    def fn(yv, *rest):
        xv = rest[0] if rest else None
        return jnp.trapezoid(yv, x=xv, dx=1.0 if dx is None else dx,
                             axis=axis)
    return apply_op(fn, y) if x is None else apply_op(fn, y, x)


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    import jax.scipy.integrate  # noqa: F401

    def fn(yv, *rest):
        # cumulative trapezoid along axis, no initial zero (paddle semantics)
        yv = jnp.moveaxis(yv, axis, -1)
        if rest:
            xv = jnp.broadcast_to(jnp.moveaxis(rest[0], axis, -1), yv.shape)
            d = jnp.diff(xv, axis=-1)
        else:
            d = 1.0 if dx is None else dx
        avg = (yv[..., 1:] + yv[..., :-1]) / 2.0
        out = jnp.cumsum(avg * d, axis=-1)
        return jnp.moveaxis(out, -1, axis)
    return apply_op(fn, y) if x is None else apply_op(fn, y, x)


def frexp(x, name=None):
    def fn(a):
        m, e = jnp.frexp(a)
        return m, e.astype(jnp.int32)
    return apply_op(fn, x)


def ldexp(x, y, name=None):
    return apply_op(lambda a, b: jnp.ldexp(a, b.astype(jnp.int32)), x, y)


def copysign(x, y, name=None):
    return apply_op(jnp.copysign, x, y)


def nextafter(x, y, name=None):
    return apply_op(jnp.nextafter, x, y)


def hypot(x, y, name=None):
    return apply_op(jnp.hypot, x, y)


def signbit(x, name=None):
    return apply_op(jnp.signbit, x)


def isposinf(x, name=None):
    return apply_op(jnp.isposinf, x)


def isneginf(x, name=None):
    return apply_op(jnp.isneginf, x)


def isreal(x, name=None):
    return apply_op(jnp.isreal, x)


def polar(abs, angle, name=None):
    return apply_op(lambda r, t: (r * jnp.cos(t) + 1j * r * jnp.sin(t))
                    .astype(jnp.complex64), abs, angle)


def view_as_complex(x, name=None):
    return apply_op(lambda a: jax.lax.complex(a[..., 0], a[..., 1]), x)


def view_as_real(x, name=None):
    return apply_op(lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], -1), x)


class _FInfo:
    def __init__(self, dtype):
        self._i = jnp.finfo(dtype)
        for f in ("min", "max", "eps", "tiny", "bits", "dtype"):
            setattr(self, f, getattr(self._i, f, None))
        self.smallest_normal = self._i.tiny
        self.resolution = float(getattr(self._i, "resolution", 0.0))


class _IInfo:
    def __init__(self, dtype):
        self._i = jnp.iinfo(dtype)
        self.min = self._i.min
        self.max = self._i.max
        self.bits = self._i.bits
        self.dtype = str(self._i.dtype)


def finfo(dtype):
    from ..core import dtype as _dtm
    return _FInfo(_dtm.convert_dtype(dtype))


def iinfo(dtype):
    from ..core import dtype as _dtm
    return _IInfo(_dtm.convert_dtype(dtype))


# -------------------------------------------------- linalg stragglers

def matrix_exp(x, name=None):
    return apply_op(lambda a: jax.scipy.linalg.expm(a), x)


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    """Pairwise p-distance between row sets (reference cdist op). p=2 uses
    the Gram-matrix form (one MXU matmul) like the reference's mm path."""
    def fn(a, b):
        if p == 2.0:
            a2 = jnp.sum(a * a, -1)[..., :, None]
            b2 = jnp.sum(b * b, -1)[..., None, :]
            ab = a @ jnp.swapaxes(b, -1, -2)
            return jnp.sqrt(jnp.maximum(a2 + b2 - 2 * ab, 0.0))
        diff = jnp.abs(a[..., :, None, :] - b[..., None, :, :])
        if p == 0.0:
            # hamming: count of non-equal coordinates (torch/reference)
            return jnp.sum((diff > 0).astype(a.dtype), -1)
        if p == float("inf"):
            return jnp.max(diff, -1)
        return jnp.sum(diff ** p, -1) ** (1.0 / p)
    return apply_op(fn, x, y)


def householder_product(x, tau, name=None):
    """Q from Householder reflectors (LAPACK orgqr; reference
    householder_product op): Q = H_0 H_1 ... H_{k-1},
    H_i = I - tau_i v_i v_i^T with v_i = [0..0, 1, x[i+1:, i]]."""
    def fn(a, t):
        m, n = a.shape[-2], a.shape[-1]

        def one(mat, tv):
            q = jnp.eye(m, dtype=mat.dtype)
            for i in range(n):
                v = jnp.concatenate([jnp.zeros(i, mat.dtype),
                                     jnp.ones(1, mat.dtype), mat[i + 1:, i]])
                h = jnp.eye(m, dtype=mat.dtype) - tv[i] * jnp.outer(v, v)
                q = q @ h
            return q[:, :n]
        if a.ndim == 2:
            return one(a, t)
        batch = a.shape[:-2]
        flat = a.reshape((-1,) + a.shape[-2:])
        ft = t.reshape(-1, t.shape[-1])
        outs = jax.vmap(one)(flat, ft)
        return outs.reshape(batch + outs.shape[-2:])
    return apply_op(fn, x, tau)


# ----------------------------------------------- final census stragglers

def cond(x, p=None, name=None):
    """Matrix condition number (reference: tensor/linalg.py cond)."""
    def fn(a):
        return jnp.linalg.cond(a, p=p)
    return apply_op(fn, x)


def frobenius_norm(x, axis=None, keepdim=False, name=None):
    def fn(a):
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        sq = jnp.abs(a) ** 2                # abs first: complex-safe
        if ax is None:
            return jnp.sqrt(jnp.sum(sq))
        return jnp.sqrt(jnp.sum(sq, axis=ax, keepdims=keepdim))
    return apply_op(fn, x)


def is_complex(x):
    return jnp.issubdtype((x._data if isinstance(x, Tensor) else x).dtype,
                          jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype((x._data if isinstance(x, Tensor) else x).dtype,
                          jnp.floating)


def is_integer(x):
    return jnp.issubdtype((x._data if isinstance(x, Tensor) else x).dtype,
                          jnp.integer)


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None):
    """reference tensor/random.py gaussian (the op behind randn). Creation
    op — listed in tensor/__init__._SKIP so it never becomes a Tensor
    method."""
    from ..core.random import next_key
    from ..core import dtype as _dtm
    d = _dtm.convert_dtype(dtype) if dtype else jnp.float32
    if isinstance(shape, int):
        shape = (shape,)
    shape = tuple(int(s) for s in shape)
    # nonzero seed = reproducible draw independent of the global generator
    # (reference gaussian seed attr semantics); seed 0 uses the global stream
    key = jax.random.key(seed) if seed else next_key()
    return Tensor(mean + std * jax.random.normal(key, shape, dtype=d))


def shape(input, name=None):
    """Shape as a tensor (reference tensor/attribute.py shape)."""
    arr = input._data if isinstance(input, Tensor) else jnp.asarray(input)
    return Tensor(jnp.asarray(arr.shape, jnp.int32))
