"""einsum (reference: python/paddle/tensor/einsum.py) — delegates to XLA."""
import jax.numpy as jnp

from ..core.tensor import apply_op


def einsum(equation, *operands):
    return apply_op(lambda *xs: jnp.einsum(equation, *xs), *operands)
