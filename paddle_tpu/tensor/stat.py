"""Statistics ops (reference: python/paddle/tensor/stat.py)."""
import jax.numpy as jnp

from ..core.tensor import apply_op
from .math import _axis


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply_op(lambda a: jnp.std(a, axis=_axis(axis), ddof=1 if unbiased else 0,
                                      keepdims=keepdim), x)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply_op(lambda a: jnp.var(a, axis=_axis(axis), ddof=1 if unbiased else 0,
                                      keepdims=keepdim), x)


def nanmean(x, axis=None, keepdim=False, name=None):
    return apply_op(lambda a: jnp.nanmean(a, axis=_axis(axis), keepdims=keepdim), x)


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    return apply_op(lambda a: jnp.nansum(a, axis=_axis(axis), keepdims=keepdim), x)
