"""Linear algebra ops (reference: python/paddle/tensor/linalg.py:240 matmul)."""
import jax.numpy as jnp

from ..core import dtype as _dt
from ..core.tensor import Tensor, apply_op, _binop


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def fn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b
        return jnp.matmul(a, b)
    return apply_op(fn, x, y)


mm = matmul


def dot(x, y, name=None):
    return apply_op(lambda a, b: jnp.sum(a * b, axis=-1), x, y)


def bmm(x, y, name=None):
    return apply_op(jnp.matmul, x, y)


def mv(x, vec, name=None):
    return apply_op(jnp.matmul, x, vec)


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    def fn(a):
        if p == "fro" or (p == 2 and axis is None):
            return jnp.sqrt(jnp.sum(a * a, axis=axis, keepdims=keepdim))
        if p == float("inf"):
            return jnp.max(jnp.abs(a), axis=axis, keepdims=keepdim)
        if p == float("-inf"):
            return jnp.min(jnp.abs(a), axis=axis, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=axis, keepdims=keepdim)
        return jnp.power(jnp.sum(jnp.power(jnp.abs(a), p), axis=axis, keepdims=keepdim),
                         1.0 / p)
    return apply_op(fn, x)


def dist(x, y, p=2, name=None):
    return norm(x - y, p=float(p) if p != 2 else 2, axis=None)


def cross(x, y, axis=9, name=None):
    def fn(a, b):
        ax = axis if axis != 9 else next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)
    return apply_op(fn, x, y)


def cholesky(x, upper=False, name=None):
    def fn(a):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2) if upper else L
    return apply_op(fn, x)


def inverse(x, name=None):
    return apply_op(jnp.linalg.inv, x)


inv = inverse


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply_op(lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian), x)


def det(x, name=None):
    return apply_op(jnp.linalg.det, x)


def slogdet(x, name=None):
    def fn(a):
        sign, logdet = jnp.linalg.slogdet(a)
        return jnp.stack([sign, logdet])
    return apply_op(fn, x)


def svd(x, full_matrices=False, name=None):
    return apply_op(lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)), x)


def qr(x, mode="reduced", name=None):
    return apply_op(lambda a: tuple(jnp.linalg.qr(a, mode=mode)), x)


def eig(x, name=None):
    import numpy as np
    w, v = np.linalg.eig(np.asarray(x._data))
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigh(x, UPLO="L", name=None):
    return apply_op(lambda a: tuple(jnp.linalg.eigh(a, UPLO=UPLO)), x)


def eigvals(x, name=None):
    import numpy as np
    return Tensor(jnp.asarray(np.linalg.eigvals(np.asarray(x._data))))


def eigvalsh(x, UPLO="L", name=None):
    return apply_op(lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), x)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply_op(lambda a: jnp.linalg.matrix_rank(a, tol=tol), x)


def matrix_power(x, n, name=None):
    return apply_op(lambda a: jnp.linalg.matrix_power(a, n), x)


def solve(x, y, name=None):
    return apply_op(jnp.linalg.solve, x, y)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    import jax
    def fn(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return apply_op(fn, x, y)


def cholesky_solve(x, y, upper=False, name=None):
    import jax
    def fn(b, L):
        return jax.scipy.linalg.cho_solve((L, not upper), b)
    return apply_op(fn, x, y)


def lstsq(x, y, rcond=None, driver=None, name=None):
    def fn(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank, sv
    return apply_op(fn, x, y)


def lu(x, pivot=True, get_infos=False, name=None):
    import jax
    def fn(a):
        lu_, piv = jax.scipy.linalg.lu_factor(a)
        # paddle/LAPACK pivots are 1-based sequential row swaps
        return lu_, piv.astype(jnp.int32) + 1
    return apply_op(fn, x)


def multi_dot(x, name=None):
    return apply_op(lambda *xs: jnp.linalg.multi_dot(xs), *x)


def histogram(input, bins=100, min=0, max=0, name=None):
    def fn(a):
        lo, hi = (min, max) if (min != 0 or max != 0) else (a.min(), a.max())
        h, _ = jnp.histogram(a.reshape(-1), bins=bins, range=(lo, hi))
        return h.astype(_dt.canonical(jnp.int64))
    return apply_op(fn, input)


def bincount(x, weights=None, minlength=0, name=None):
    def fn(a, *w):
        return jnp.bincount(a.reshape(-1).astype(jnp.int32),
                            weights=w[0] if w else None,
                            minlength=minlength)
    args = (x, weights) if weights is not None else (x,)
    return apply_op(fn, *args)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply_op(lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0), x)


def corrcoef(x, rowvar=True, name=None):
    return apply_op(lambda a: jnp.corrcoef(a, rowvar=rowvar), x)
