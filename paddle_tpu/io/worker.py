"""Multi-process DataLoader worker loop over the native shm ring.

Reference: python/paddle/fluid/dataloader/dataloader_iter.py:342
(`_DataLoaderIterMultiProcess`: worker `multiprocessing.Process` pool,
index queues, shared-memory tensor return, watchdog). Here the return
path is the C++ shm ring (paddle_tpu/native/src/shm_ring.cc): workers
pickle numpy batches straight into shared memory; the trainer process
drains, reorders, and converts to device arrays.

Workers never touch JAX — batches stay numpy until the parent converts,
so fork()ing after the parent initialized the TPU backend is safe.
"""
import pickle
import traceback

import numpy as np


class WorkerInfo:
    def __init__(self, wid, num_workers, dataset):
        self.id = wid
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = None


def get_worker_info():
    return _worker_info


def collate(batch, leaf):
    """Shared collate recursion: structure handling lives here once; `leaf`
    decides what a stacked ndarray becomes (numpy in workers, device tensor
    in the trainer)."""
    from ..core.tensor import Tensor

    sample = batch[0]
    if isinstance(sample, (tuple, list)):
        return [collate([b[i] for b in batch], leaf) for i in range(len(sample))]
    if isinstance(sample, dict):
        return {k: collate([b[k] for b in batch], leaf) for k in sample}
    if isinstance(sample, Tensor):
        return leaf(np.stack([np.asarray(b.numpy()) for b in batch]))
    if isinstance(sample, np.ndarray):
        return leaf(np.stack(batch))
    if isinstance(sample, (int, float, np.integer, np.floating)):
        # let numpy promote mixed int/float batches; floats narrow to f32
        # (framework default dtype) instead of numpy's f64
        arr = np.asarray(batch)
        if arr.dtype == np.float64:
            arr = arr.astype(np.float32)
        return leaf(arr)
    return batch


def numpy_collate(batch):
    """Default collate for worker processes: stacks to numpy, never jax."""
    return collate(batch, lambda arr: arr)


def worker_loop(dataset, collate_fn, ring_name, index_queue, worker_init_fn,
                wid, num_workers, base_seed):
    from ..native import ShmRing

    global _worker_info
    _worker_info = WorkerInfo(wid, num_workers, dataset)
    np.random.seed((base_seed + wid) % (2 ** 31))
    ring = ShmRing(ring_name, create=False)
    try:
        if worker_init_fn is not None:
            worker_init_fn(wid)
        while True:
            item = index_queue.get()
            if item is None:
                break
            i, indices = item
            try:
                batch = collate_fn([dataset[j] for j in indices])
                payload = pickle.dumps((i, "ok", batch),
                                       protocol=pickle.HIGHEST_PROTOCOL)
            except Exception:
                payload = pickle.dumps((i, "err", traceback.format_exc()))
            try:
                ring.put(payload)
            except ValueError:
                # batch bigger than the whole ring: report instead of dying
                ring.put(pickle.dumps((
                    i, "err",
                    f"batch {i} pickled to {len(payload)} bytes, larger than "
                    f"the shm ring; raise DataLoader use_shared_memory "
                    f"capacity or reduce batch size")))
    except (EOFError, KeyboardInterrupt):
        pass
    finally:
        ring.release()
