"""paddle.io equivalent: Dataset/DataLoader/samplers.

Reference: python/paddle/fluid/dataloader/ (`_DataLoaderIterMultiProcess`
worker-process pool, dataloader_iter.py:342). Two prefetch engines:

* num_workers>0 + use_shared_memory (default): true worker PROCESSES
  returning batches through the native C++ shm ring
  (paddle_tpu/native/src/shm_ring.cc) — the reference's shared-memory
  tensor path (mmap_allocator.cc) without a Python pipe in the loop.
* fallback (native lib unavailable, IterableDataset, or
  use_shared_memory=False): a prefetch thread running user dataset code.

JAX arrays are always produced in the trainer process; workers stay numpy.
"""
import itertools
import os
import pickle
import queue
import threading
import time

import numpy as np

from ..core.random import _default_generator
from ..core.tensor import Tensor, to_tensor
from ..observability import faults as _faults
from ..observability import metrics as _metrics
from ..profiler import _tracer as _TRACER
from .worker import (WorkerInfo, collate, get_worker_info, numpy_collate,
                     worker_loop)

# unified-registry view of the Dataloader span: how long the training
# loop blocks waiting for each batch (the dataloader-bound step phase)
_DL_WAIT = _metrics.histogram(
    "dataloader_wait_seconds",
    "Time the training loop blocks waiting for the next batch")


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = len(dataset)
    if sum(lengths) != total:
        raise ValueError("sum of lengths must equal dataset length")
    perm = np.random.permutation(total)
    out, off = [], 0
    for ln in lengths:
        out.append(Subset(dataset, perm[off:off + ln].tolist()))
        off += ln
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Reference: python/paddle/fluid/dataloader/batch_sampler.py
    DistributedBatchSampler — shards sample indices over data-parallel ranks."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        from ..distributed import get_rank, get_world_size
        self.nranks = num_replicas if num_replicas is not None else get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices = np.concatenate([indices, indices[:self.total_size - n]])
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices.tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def default_collate_fn(batch):
    return collate(batch, to_tensor)


class DataLoader:
    """Reference: python/paddle/fluid/reader.py:275."""

    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, persistent_workers=False):
        self.dataset = dataset
        self._user_collate_fn = collate_fn is not None
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self._use_shared_memory = use_shared_memory
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    def _iter_batches(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(batch)
        else:
            for indices in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in indices])

    def __iter__(self):
        """Batch iterator, with one Dataloader profiler span per produced
        batch (reference: the Dataloader TracerEventType stamped by
        dataloader_iter.py). With background workers the span measures the
        time the training loop WAITS on data — the dataloader-bound phase
        of the step — not worker-side compute."""
        it = self._base_iter()
        while True:
            # per-batch (not per-op) cost: also feed the flight-recorder
            # ring when one is attached, so a postmortem shows whether the
            # loop was waiting on data when the process wedged
            rec = _TRACER.begin("DataLoader.next", "Dataloader") \
                if (_TRACER.enabled or _TRACER.ring is not None) else None
            t0 = time.perf_counter()
            try:
                _faults.fire("dataloader.next")   # chaos hook (ISSUE 5)
                batch = next(it)
            except StopIteration:
                _TRACER.cancel(rec)
                return
            except BaseException:
                _TRACER.cancel(rec)
                raise
            _DL_WAIT.observe(time.perf_counter() - t0)
            _TRACER.end(rec)
            yield batch

    def _base_iter(self):
        if self.num_workers <= 0:
            yield from self._iter_batches()
            return
        if self._use_shared_memory and not self._iterable_mode:
            from .. import native
            if native.available():
                yield from self._iter_multiprocess()
                return
        yield from self._iter_threaded()

    # -- threaded fallback -------------------------------------------------
    def _iter_threaded(self):
        maxsize = max(2, self.num_workers * self.prefetch_factor)
        q = queue.Queue(maxsize=maxsize)
        sentinel = object()

        def producer():
            try:
                for batch in self._iter_batches():
                    q.put(batch)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            yield item
        t.join()

    # -- multi-process over the native shm ring ----------------------------
    def _iter_multiprocess(self):
        import multiprocessing as mp

        from .. import native

        ctx = mp.get_context("fork")
        ring_name = f"/pt_dl_{os.getpid()}_{next(_RING_SEQ)}"
        ring_cap = max(8 << 20,
                       self.num_workers * self.prefetch_factor * (4 << 20))
        ring = native.ShmRing(ring_name, ring_cap)
        procs = []
        # everything past ring creation runs under the finally so a sampler
        # exception or fork failure can't leak the shm segment / workers
        try:
            index_queue = ctx.Queue()
            batches = list(self.batch_sampler)

            # incremental dispatch: at most num_workers * prefetch_factor
            # batch indices outstanding, so worker-side ring pressure AND
            # parent-side reorder buffering both stay bounded (reference:
            # dataloader_iter.py _try_put_indices / _outstanding_capacity)
            dispatch_iter = iter(enumerate(batches))
            max_outstanding = max(2, self.num_workers * self.prefetch_factor)
            exhausted = [False]

            def dispatch_one():
                if exhausted[0]:
                    return
                item = next(dispatch_iter, None)
                if item is None:
                    exhausted[0] = True
                    for _ in range(self.num_workers):
                        index_queue.put(None)
                    return
                index_queue.put(item)

            for _ in range(max_outstanding):
                dispatch_one()

            worker_collate = (self.collate_fn if self._user_collate_fn
                              else numpy_collate)
            base_seed = int(np.random.randint(0, 2 ** 31))
            procs = [
                ctx.Process(
                    target=worker_loop,
                    args=(self.dataset, worker_collate, ring_name, index_queue,
                          self.worker_init_fn, wid, self.num_workers,
                          base_seed),
                    daemon=True)
                for wid in range(self.num_workers)
            ]
            # fork is deliberate (COW handoff of dataset/sampler objects +
            # the named-shm ring, the reference DataLoader's design) and
            # safe here because workers run a pure numpy loop and never
            # call into JAX; suppress only the fork-vs-threads warnings at
            # this boundary so user runs stay clean
            import warnings
            with warnings.catch_warnings():
                warnings.filterwarnings(
                    "ignore", message=".*fork.*", category=RuntimeWarning)
                warnings.filterwarnings(
                    "ignore", message=".*fork.*", category=DeprecationWarning)
                for p in procs:
                    p.start()

            # timeout=0 (default) means "no deadline" — poll in 10 s slices
            # so a dead worker is still detected promptly (the watchdog role
            # of launch_utils.watch_local_trainers)
            user_deadline_ms = int(self.timeout * 1000) if self.timeout else None
            poll_ms = min(user_deadline_ms, 10000) if user_deadline_ms else 10000
            buffered = {}
            next_idx = 0
            while next_idx < len(batches):
                if next_idx in buffered:
                    yield self._finalize_batch(buffered.pop(next_idx))
                    next_idx += 1
                    continue
                waited_ms = 0
                while True:
                    try:
                        data = ring.get(timeout_ms=poll_ms)
                        break
                    except TimeoutError:
                        dead = [p.pid for p in procs if not p.is_alive()]
                        if dead:
                            raise RuntimeError(
                                f"DataLoader worker(s) {dead} died "
                                f"unexpectedly") from None
                        waited_ms += poll_ms
                        if user_deadline_ms and waited_ms >= user_deadline_ms:
                            raise
                if data is None:
                    raise RuntimeError("DataLoader ring closed early")
                i, status, payload = pickle.loads(data)
                dispatch_one()
                if status == "err":
                    raise RuntimeError(
                        f"DataLoader worker failed on batch {i}:\n{payload}")
                buffered[i] = payload
        finally:
            ring.close()
            for p in procs:
                p.join(timeout=1)
                if p.is_alive():
                    p.terminate()
            ring.release()

    def _finalize_batch(self, batch):
        """numpy structure → device tensors (runs in the trainer process)."""
        if self._user_collate_fn:
            return batch
        if isinstance(batch, list):
            return [self._finalize_batch(b) for b in batch]
        if isinstance(batch, dict):
            return {k: self._finalize_batch(v) for k, v in batch.items()}
        if isinstance(batch, np.ndarray):
            return to_tensor(batch)
        return batch


_RING_SEQ = itertools.count(1)  # itertools.count is atomic under the GIL
