"""paddle.hub (reference: python/paddle/hub.py — list/help/load over a
github/gitee/local 'repo' exposing hubconf.py). Zero-egress build: only
source='local' works; remote sources raise with the local alternative.
"""
import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, "hubconf.py")
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no hubconf.py under {repo_dir!r}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["paddle_tpu_hubconf"] = mod
    spec.loader.exec_module(mod)
    return mod


def _check_source(source):
    if source != "local":
        raise RuntimeError(
            f"hub source {source!r} needs network access (zero-egress "
            f"build); clone the repo yourself and use source='local' with "
            f"repo_dir=<path>")


def _resolve(repo_dir, source):
    """Promote to local ONLY for explicit local paths (absolute or ./-
    prefixed) — a remote-looking 'user/repo' string must never silently
    execute whatever sits at a cwd-relative path."""
    explicit_path = os.path.isabs(repo_dir) or repo_dir.startswith((".", "~"))
    if explicit_path and os.path.isdir(os.path.expanduser(repo_dir)):
        return "local"
    return source


def list(repo_dir, source="github", force_reload=False):  # noqa: A001
    """Entrypoints exposed by the repo's hubconf.py."""
    source = _resolve(repo_dir, source)
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith("_")]


def help(repo_dir, model, source="github", force_reload=False):  # noqa: A001
    source = _resolve(repo_dir, source)
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return getattr(mod, model).__doc__


def load(repo_dir, model, source="github", force_reload=False, **kwargs):
    source = _resolve(repo_dir, source)
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return getattr(mod, model)(**kwargs)
