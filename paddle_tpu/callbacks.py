"""paddle.callbacks namespace (reference: python/paddle/callbacks.py —
re-exports the hapi callbacks)."""
from .hapi.callbacks import (  # noqa: F401
    Callback, EarlyStopping, LRScheduler, ModelCheckpoint, ProgBarLogger,
    ReduceLROnPlateau, VisualDL)

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "VisualDL",
           "LRScheduler", "EarlyStopping", "ReduceLROnPlateau"]
