"""reference: utils/download.py — pretrained-weight fetch. Zero-egress
build: a local cache hit works; a download attempt raises with the path
layout so users know where to place files."""
import os

__all__ = ["get_weights_path_from_url", "get_path_from_url"]

WEIGHTS_HOME = os.path.expanduser("~/.cache/paddle_tpu/weights")


def get_weights_path_from_url(url, md5sum=None):
    fname = os.path.basename(url)
    path = os.path.join(WEIGHTS_HOME, fname)
    if os.path.isfile(path):
        return path
    raise RuntimeError(
        f"cannot download {url} (zero-egress build); place the file at "
        f"{path} and retry")


def get_path_from_url(url, root_dir=None, md5sum=None, check_exist=True):
    root = root_dir or WEIGHTS_HOME
    path = os.path.join(root, os.path.basename(url))
    if os.path.exists(path):
        return path
    raise RuntimeError(
        f"cannot download {url} (zero-egress build); place the file at "
        f"{path} and retry")
