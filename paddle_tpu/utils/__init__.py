"""paddle.utils (reference: python/paddle/utils/__init__.py: deprecated,
run_check, require_version, try_import; submodules unique_name, download)."""
import functools
import importlib
import warnings

from . import unique_name  # noqa: F401
from . import download  # noqa: F401
from . import cpp_extension  # noqa: F401

__all__ = ["deprecated", "run_check", "require_version", "try_import"]


def deprecated(update_to="", since="", reason="", level=0):
    """Decorator marking an API deprecated (reference:
    utils/deprecated.py). level 0/1 warn; level 2 raises on call."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = (f"API '{fn.__module__}.{fn.__name__}' is deprecated "
                   f"since {since or 'an earlier release'}"
                   + (f"; use {update_to} instead" if update_to else "")
                   + (f". Reason: {reason}" if reason else ""))
            if level >= 2:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        return wrapper
    return deco


def run_check():
    """Device self-test (reference: utils/install_check.py run_check):
    run a tiny matmul fwd+bwd on the current backend and report."""
    import numpy as np
    import paddle_tpu as paddle
    x = paddle.to_tensor(np.ones((2, 3), "float32"), stop_gradient=False)
    w = paddle.to_tensor(np.ones((3, 2), "float32"), stop_gradient=False)
    y = (x @ w).sum()
    y.backward()
    assert float(y) == 12.0 and x.grad is not None
    import jax
    print(f"paddle_tpu is installed successfully! backend="
          f"{jax.default_backend()}, devices={jax.device_count()}")


def require_version(min_version, max_version=None):
    """Check the installed version satisfies [min, max] (reference:
    utils/__init__ require_version)."""
    from ..version import full_version

    def parts(v, width):
        ps = [int(x) for x in str(v).split(".") if x.isdigit()]
        return ps + [0] * (width - len(ps))       # zero-pad: 0.1 == 0.1.0

    width = max(len(str(v).split(".")) for v in
                (full_version, min_version, max_version or "0"))
    cur = parts(full_version, width)
    if parts(min_version, width) > cur:
        raise Exception(
            f"installed version {full_version} < required {min_version}")
    if max_version is not None and parts(max_version, width) < cur:
        raise Exception(
            f"installed version {full_version} > allowed {max_version}")
    return True


def try_import(module_name, err_msg=None):
    """Import a module, raising a friendly error when absent (reference:
    utils/lazy_import.py)."""
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(err_msg or
                          f"module {module_name!r} is required but not "
                          f"installed (and this build cannot download)")
