"""paddle.utils.cpp_extension (reference: utils/cpp_extension — setuptools
helpers + JIT `load` for custom C++ ops). This build supports HOST C++
extensions for real: `load` compiles sources with g++ into a shared
library and returns a ctypes handle (the native runtime uses the same
boundary, native/__init__.py). Device kernels use Pallas/custom_vjp per
docs/CUSTOM_OPS.md; CUDAExtension raises accordingly.
"""
import ctypes
import hashlib
import os
import subprocess

__all__ = ["CppExtension", "CUDAExtension", "load", "setup",
           "get_build_directory"]


def get_build_directory(verbose=False):
    d = os.path.expanduser("~/.cache/paddle_tpu/extensions")
    os.makedirs(d, exist_ok=True)
    return d


def CppExtension(sources, *args, **kwargs):
    """setuptools.Extension factory (reference cpp_extension.CppExtension)."""
    from setuptools import Extension
    name = kwargs.pop("name", "paddle_tpu_ext")
    kwargs.setdefault("language", "c++")
    return Extension(name, sources, *args, **kwargs)


def CUDAExtension(sources, *args, **kwargs):
    raise RuntimeError(
        "CUDAExtension targets nvcc; on this TPU backend write device "
        "kernels with Pallas (docs/CUSTOM_OPS.md tier 2) and host code "
        "with CppExtension/load")


def setup(**attrs):
    """reference cpp_extension.setup — setuptools.setup preconfigured for
    the C++ extension build."""
    from setuptools import setup as _setup
    attrs.setdefault("script_args", ["build_ext", "--inplace"])
    return _setup(**attrs)


def load(name, sources, extra_cxx_cflags=None, extra_cuda_cflags=None,
         extra_ldflags=None, extra_include_paths=None, build_directory=None,
         interpreter=None, verbose=False):
    """JIT-compile C++ sources into <name>.so and load via ctypes
    (reference cpp_extension.load returns the imported module; the ctypes
    namespace is this runtime's native-op boundary)."""
    build_dir = build_directory or get_build_directory()
    srcs = [os.path.abspath(s) for s in sources]
    key = hashlib.sha1(
        ("|".join(srcs) + "|" +
         "|".join(open(s, "rb").read().decode("utf-8", "ignore")
                  for s in srcs)).encode()).hexdigest()[:16]
    out = os.path.join(build_dir, f"{name}_{key}.so")
    if not os.path.exists(out):
        cmd = (["g++", "-O2", "-fPIC", "-shared", "-std=c++17"]
               + (extra_cxx_cflags or [])
               + sum([["-I", p] for p in (extra_include_paths or [])], [])
               + srcs + ["-o", out] + (extra_ldflags or []))
        if verbose:
            print(" ".join(cmd))
        subprocess.run(cmd, check=True, capture_output=not verbose)
    return ctypes.CDLL(out)
