"""reference: utils/unique_name.py — process-wide unique name generator
with guard() scoping (used by static layer helpers). guard(prefix) also
namespaces generated names like the reference's generator switch."""
import contextlib

_STACK = [{"counters": {}, "prefix": ""}]


def generate(key):
    top = _STACK[-1]
    c = top["counters"]
    c[key] = c.get(key, -1) + 1
    return f"{top['prefix']}{key}_{c[key]}"


def generate_with_ignorable_key(key):
    return generate(key)


@contextlib.contextmanager
def guard(new_generator=None):
    prefix = new_generator if isinstance(new_generator, str) else ""
    _STACK.append({"counters": {}, "prefix": prefix})
    try:
        yield
    finally:
        _STACK.pop()


def switch(new_generator=None):
    """Replace the current scope's generator state; returns the old one.
    Passing a previously returned state dict RESTORES it (the reference's
    save/restore idiom: pre = switch(); ...; switch(pre))."""
    old = _STACK[-1]
    if isinstance(new_generator, dict) and "counters" in new_generator:
        _STACK[-1] = new_generator
    else:
        prefix = new_generator if isinstance(new_generator, str) else ""
        _STACK[-1] = {"counters": {}, "prefix": prefix}
    return old
