"""reference: utils/unique_name.py — process-wide unique name generator
with guard() scoping (used by static layer helpers)."""
import contextlib

_COUNTERS = [{}]


def generate(key):
    c = _COUNTERS[-1]
    c[key] = c.get(key, -1) + 1
    return f"{key}_{c[key]}"


def generate_with_ignorable_key(key):
    return generate(key)


@contextlib.contextmanager
def guard(new_generator=None):
    _COUNTERS.append({})
    try:
        yield
    finally:
        _COUNTERS.pop()


def switch(new_generator=None):
    _COUNTERS[-1] = {}
