"""Summary views + roofline attribution over host tracer spans.

Reference: python/paddle/profiler/profiler_statistic.py (StatisticData,
EventSummary, _build_table) — the part of the reference framework that
turns raw RecordEvent streams into OverView / OperatorView /
DistributedView / MemoryView tables.

TPU-native extension (the round-5 verdict's ask): `analyze()` joins each
recorded Operator span against the analytical roofline from
cost_model/analytical.py — apply_op records the op callable plus abstract
input shapes, so every (op, shape) bucket can be re-traced abstractly
(jax.make_jaxpr over ShapeDtypeStructs, no execution) and priced as
max(flops/peak, bytes/bw). The result is a per-op MFU decomposition:
achieved host-span time vs roofline time, the top-k gap contributors, and
how much of the recorded compute time the attribution covers.
"""
import numpy as np

__all__ = ["phase_durations_ms", "op_digest", "build_summary", "analyze",
           "AnalyzeReport"]

# phase-level tracer event types (string values of TracerEventType — kept
# as literals so this module never imports its own package mid-init)
_PHASES = ("Dataloader", "Forward", "Backward", "Optimization",
           "Communication")
_OPERATOR_TYPES = ("Operator", "PythonOp", "UserDefined")


# ------------------------------------------------------------ interval math

def _intervals(events, types):
    """[(start_ns, end_ns)] of completed spans of the given types."""
    out = []
    for e in events:
        if e["type"] in types and e["dur"] is not None:
            out.append((e["ts"], e["ts"] + e["dur"]))
    return out


def _merge(intervals):
    """Collapse intervals into a sorted disjoint union."""
    out = []
    for s, e in sorted(intervals):
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((s, e))
    return out


def _union_ns(intervals):
    """Total length of the union of intervals (double counting removed —
    nested same-phase spans collapse)."""
    return sum(e - s for s, e in _merge(intervals))


def _intersect_ns(a, b):
    """Length of intersection of two interval unions."""
    if not a or not b:
        return 0
    a = _merge(a)
    b = _merge(b)
    i = j = 0
    total = 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if lo < hi:
            total += hi - lo
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return total


def phase_durations_ms(events):
    """{phase: union-ms} for the framework phase span types present."""
    out = {}
    for ph in _PHASES:
        ns = _union_ns(_intervals(events, (ph,)))
        if ns:
            out[ph] = round(ns / 1e6, 4)
    return out


def _wall_ns(events):
    """Profiled wall time: union of ProfileStep spans when present, else
    the overall event envelope."""
    steps = _intervals(events, ("ProfileStep",))
    if steps:
        return _union_ns(steps)
    done = [e for e in events if e["dur"] is not None]
    if not done:
        return 0
    return max(e["ts"] + e["dur"] for e in done) - min(e["ts"] for e in done)


# ----------------------------------------------------------- op aggregation

def _shape_key(e):
    attrs = e.get("attrs") or {}
    shapes = attrs.get("input_shapes")
    if shapes is None:
        return ""
    return "x".join(str(tuple(s)) for s in shapes) or "()"


def _op_events(events):
    return [e for e in events
            if e["type"] in _OPERATOR_TYPES and e["dur"] is not None]


def op_digest(events, top=8):
    """Compact per-op digest for the step-timeline JSONL: top ops by total
    host time, shape-bucketed."""
    buckets = {}
    for e in _op_events(events):
        key = (e["name"], _shape_key(e))
        b = buckets.setdefault(key, {"name": e["name"], "shapes": key[1],
                                     "calls": 0, "total_ms": 0.0,
                                     "cache_hits": 0, "cache_misses": 0})
        b["calls"] += 1
        b["total_ms"] += e["dur"] / 1e6
        cache = (e.get("attrs") or {}).get("cache")
        if cache == "hit":
            b["cache_hits"] += 1
        elif cache == "miss":
            b["cache_misses"] += 1
    rows = sorted(buckets.values(), key=lambda b: -b["total_ms"])[:top]
    for r in rows:
        r["total_ms"] = round(r["total_ms"], 4)
    return rows


def _operator_rows(events):
    """OperatorView rows: (name, shapes)-bucketed host-span statistics."""
    buckets = {}
    for e in _op_events(events):
        key = (e["name"], _shape_key(e))
        buckets.setdefault(key, []).append(e)
    rows = []
    for (name, shapes), evs in buckets.items():
        durs = np.asarray([e["dur"] for e in evs], np.float64) / 1e6
        cache = [(e.get("attrs") or {}).get("cache") for e in evs]
        rows.append({
            "name": name, "shapes": shapes, "calls": len(evs),
            "total_ms": float(durs.sum()), "avg_ms": float(durs.mean()),
            "max_ms": float(durs.max()), "min_ms": float(durs.min()),
            "cache_hits": sum(c == "hit" for c in cache),
            "cache_misses": sum(c == "miss" for c in cache),
        })
    return rows


_SORT_FIELDS = {0: "total_ms", 1: "avg_ms", 2: "max_ms", 3: "min_ms",
                4: "total_ms", 5: "avg_ms", 6: "max_ms", 7: "min_ms"}


def _sort_rows(rows, sorted_by):
    field = _SORT_FIELDS.get(sorted_by, "total_ms")
    return sorted(rows, key=lambda r: r[field], reverse=field != "min_ms")


# ------------------------------------------------------------------ tables

_UNITS = {"s": 1e-3, "ms": 1.0, "us": 1e3, "ns": 1e6}


def _fmt_table(headers, rows):
    widths = [max(len(h), *(len(str(r[i])) for r in rows)) if rows else len(h)
              for i, h in enumerate(headers)]
    lines = ["  ".join(f"{h:<{w}}" for h, w in zip(headers, widths))]
    for r in rows:
        lines.append("  ".join(f"{str(c):<{w}}" for c, w in zip(r, widths)))
    return "\n".join(lines)


def _overview_table(events, unit_scale, unit):
    wall = _wall_ns(events)
    if not wall:
        return None
    phases = {}
    for ph in _PHASES:
        ns = _union_ns(_intervals(events, (ph,)))
        if ns:
            phases[ph] = ns
    # top-level operator time not nested inside any phase span
    op_iv = _intervals(events, _OPERATOR_TYPES)
    phase_iv = _intervals(events, _PHASES)
    op_outside = _union_ns(op_iv) - _intersect_ns(op_iv, phase_iv)
    covered = _union_ns(phase_iv) + max(op_outside, 0)
    rows = [["ProfileStep (wall)", f"{wall / 1e6 * unit_scale:.3f}", "100.0%"]]
    for ph, ns in sorted(phases.items(), key=lambda kv: -kv[1]):
        rows.append([ph, f"{ns / 1e6 * unit_scale:.3f}",
                     f"{100.0 * ns / wall:.1f}%"])
    if op_outside > 0:
        rows.append(["Operator (outside phases)",
                     f"{op_outside / 1e6 * unit_scale:.3f}",
                     f"{100.0 * op_outside / wall:.1f}%"])
    other = max(wall - covered, 0)
    rows.append(["Other (python/untracked)",
                 f"{other / 1e6 * unit_scale:.3f}",
                 f"{100.0 * other / wall:.1f}%"])
    return ("-------------------Overview Summary-------------------\n"
            + _fmt_table(["Phase", f"Total({unit})", "Ratio"], rows))


def _operator_table(events, sorted_by, unit_scale, unit):
    rows = _operator_rows(events)
    if not rows:
        return None
    rows = _sort_rows(rows, sorted_by)
    disp = []
    for r in rows:
        cache = ""
        if r["cache_hits"] or r["cache_misses"]:
            cache = f"{r['cache_hits']}/{r['cache_hits'] + r['cache_misses']}"
        disp.append([r["name"], r["shapes"] or "-", r["calls"],
                     f"{r['total_ms'] * unit_scale:.3f}",
                     f"{r['avg_ms'] * unit_scale:.3f}",
                     f"{r['max_ms'] * unit_scale:.3f}",
                     f"{r['min_ms'] * unit_scale:.3f}", cache or "-"])
    return ("-------------------Operator Summary-------------------\n"
            + _fmt_table(["Name", "InputShapes", "Calls", f"Total({unit})",
                          f"Avg({unit})", f"Max({unit})", f"Min({unit})",
                          "CacheHit"], disp))


def _distributed_table(events, unit_scale, unit):
    comm = _intervals(events, ("Communication",))
    if not comm:
        return None
    compute = _intervals(events, ("Operator", "Forward", "Backward",
                                  "Optimization"))
    wall = _wall_ns(events) or 1
    comm_ns = _union_ns(comm)
    comp_ns = _union_ns(compute)
    overlap = _intersect_ns(comm, compute)
    rows = [
        ["Communication", f"{comm_ns / 1e6 * unit_scale:.3f}",
         f"{100.0 * comm_ns / wall:.1f}%"],
        ["Computation", f"{comp_ns / 1e6 * unit_scale:.3f}",
         f"{100.0 * comp_ns / wall:.1f}%"],
        ["Overlap", f"{overlap / 1e6 * unit_scale:.3f}",
         f"{100.0 * overlap / wall:.1f}%"],
    ]
    payload = sum((e.get("attrs") or {}).get("payload_bytes", 0)
                  for e in events if e["type"] == "Communication")
    if payload:
        rows.append(["Payload", f"{payload / 1e6:.2f} MB", "-"])
    return ("-----------------Distributed Summary------------------\n"
            + _fmt_table(["Name", f"Total({unit})", "Ratio"], rows))


def _memory_table(events):
    samples = []
    for e in events:
        for k in ("mem0", "mem1"):
            if e.get(k) is not None:
                samples.append(e[k])
    if not samples:
        return None
    rows = [["peak", f"{max(samples) / 1e6:.2f} MB"],
            ["low", f"{min(samples) / 1e6:.2f} MB"],
            ["net", f"{(samples[-1] - samples[0]) / 1e6:+.2f} MB"]]
    for ph in _PHASES + ("Operator",):
        deltas = [e["mem1"] - e["mem0"] for e in events
                  if e["type"] == ph and e.get("mem0") is not None
                  and e.get("mem1") is not None]
        if deltas:
            rows.append([f"{ph} delta", f"{sum(deltas) / 1e6:+.2f} MB"])
    return ("-------------------Memory Summary---------------------\n"
            + _fmt_table(["Metric", "LiveBytes"], rows))


def build_summary(events, sorted_by=None, views=None, time_unit="ms"):
    """Render the selected SummaryView tables as one string. Default: the
    OverView + OperatorView, plus DistributedView / MemoryView whenever
    comm spans / memory samples were recorded."""
    if not events:
        return ""
    unit_scale = _UNITS.get(time_unit, 1.0)
    if views is not None and not isinstance(views, (list, tuple, set)):
        views = [views]
    # SummaryView numeric values (kept as literals: OverView=1,
    # DistributedView=3, OperatorView=5, MemoryView=6)
    want = set(views) if views is not None else None

    def wanted(v, default_on):
        return (v in want) if want is not None else default_on

    parts = []
    if wanted(1, True):
        parts.append(_overview_table(events, unit_scale, time_unit))
    if wanted(5, True):
        parts.append(_operator_table(events, sorted_by, unit_scale,
                                     time_unit))
    if wanted(3, True):
        parts.append(_distributed_table(events, unit_scale, time_unit))
    if wanted(6, True):
        parts.append(_memory_table(events))
    return "\n\n".join(p for p in parts if p)


# -------------------------------------------------- roofline attribution

_ROOFLINE_CACHE = {}


def _estimate_ref(ref, spec, variant=""):
    """(flops, bytes, roofline_ms) for one op-call ref recorded by apply_op:
    (fn, tensor_idx, avals, statics, nargs, kwargs). Re-traces abstractly —
    statics stay closed over so shape-consuming python ints never become
    tracers. Returns None when the op cannot be priced. `variant` is the
    recorder's digest of the op's non-tensor identity (closure cells,
    defaults) — without it, two lambdas from one call site alias."""
    fn, tensor_idx, avals, statics, nargs, kwargs = ref
    code = getattr(fn, "__code__", None)
    key = (id(code) if code is not None else id(fn), variant,
           tuple((a.shape, str(a.dtype)) for a in avals),
           repr(statics)[:200], repr(sorted(kwargs.items()))[:100],
           spec.name)
    if key in _ROOFLINE_CACHE:
        return _ROOFLINE_CACHE[key]
    from ..cost_model.analytical import estimate

    def call(*tensor_vals):
        full = [None] * nargs
        for i, v in zip(tensor_idx, tensor_vals):
            full[i] = v
        for i, v in statics:
            full[i] = v
        return fn(*full, **kwargs)

    try:
        rep = estimate(call, *avals, device=spec)
        out = (rep.total_flops, rep.total_bytes, rep.time_ms)
    except Exception:                                        # noqa: BLE001
        out = None
    _ROOFLINE_CACHE[key] = out
    return out


class AnalyzeReport:
    """Per-op MFU decomposition of a profiled run.

    rows: one per (op, shape) bucket — achieved host-span ms vs analytical
    roofline ms, flops/bytes, efficiency (roofline/achieved, the op's MFU
    proxy) and gap_ms (achieved - roofline, what eliminating all dispatch/
    layout inefficiency would recover). top_gaps: the top-k gap
    contributors. coverage: attributed achieved-time / total recorded
    compute span time. phases: OverView-style union durations."""

    def __init__(self, device, rows, phases, step_ms_total, coverage,
                 top_k=3):
        self.device = device
        self.rows = rows
        self.phases = phases
        self.step_ms_total = step_ms_total
        self.coverage = coverage
        self.top_gaps = [r for r in
                         sorted(rows, key=lambda r: -(r["gap_ms"] or 0))
                         if r["roofline_ms"] is not None
                         and (r["gap_ms"] or 0) > 0][:top_k]

    def to_dict(self):
        return {"device": self.device.name, "phases": self.phases,
                "step_ms_total": self.step_ms_total,
                "coverage": self.coverage, "rows": self.rows,
                "top_gap_contributors": [r["name"] for r in self.top_gaps]}

    def table(self, top=15):
        rows = sorted(self.rows, key=lambda r: -r["achieved_ms"])[:top]
        out = ["| op | shapes | calls | achieved ms | roofline ms | "
               "efficiency | gap ms |", "|---|---|---|---|---|---|---|"]
        for r in rows:
            rf = "-" if r["roofline_ms"] is None else f"{r['roofline_ms']:.4f}"
            eff = "-" if r["efficiency"] is None else f"{r['efficiency']:.3f}"
            gap = "-" if r["gap_ms"] is None else f"{r['gap_ms']:.4f}"
            out.append(f"| {r['name']} | {r['shapes'] or '-'} | {r['calls']} "
                       f"| {r['achieved_ms']:.4f} | {rf} | {eff} | {gap} |")
        return "\n".join(out)

    def render(self):
        lines = [f"# MFU attribution ({self.device.name})", ""]
        if self.step_ms_total:
            lines.append(f"profiled wall time: {self.step_ms_total:.2f} ms")
        if self.phases:
            lines.append("phase breakdown (ms): " + ", ".join(
                f"{k}={v:.2f}" for k, v in self.phases.items()))
        lines.append(f"roofline coverage of recorded compute span time: "
                     f"{100.0 * self.coverage:.1f}%")
        if self.top_gaps:
            lines.append("top MFU gap contributors: " + ", ".join(
                f"{r['name']} (+{r['gap_ms']:.3f} ms)"
                for r in self.top_gaps))
        if any(r["efficiency"] is not None and r["efficiency"] > 1.0
               for r in self.rows):
            lines.append(
                "note: efficiency > 1 rows are device-bound — jax dispatch "
                "is async, so the host span returned before the kernel "
                "finished; their true time lives in the XPlane capture.")
        lines += ["", self.table()]
        return "\n".join(lines)

    def __repr__(self):
        return (f"AnalyzeReport(device={self.device.name}, "
                f"ops={len(self.rows)}, coverage={self.coverage:.2f})")


def _resolve_device(device):
    from ..cost_model.analytical import DEVICES, DeviceSpec
    if isinstance(device, DeviceSpec):
        return device
    if device is None:
        import os
        device = os.environ.get("PADDLE_TPU_DEVICE_SPEC")
    if device is None:
        import jax
        device = "cpu" if jax.default_backend() == "cpu" else "tpu-v5e"
    return DEVICES[device]


def analyze(events, step_times=None, device=None, top_k=3):
    """Join host spans against the analytical roofline (the verdict's
    'analytical decomposition using the repo's own cost model')."""
    spec = _resolve_device(device)
    phases = phase_durations_ms(events)
    wall_ms = _wall_ns(events) / 1e6
    if not wall_ms and step_times:
        wall_ms = float(np.sum(step_times)) * 1e3

    buckets = {}
    for e in events:
        if e["type"] != "Operator" or e["dur"] is None:
            continue
        # variant keeps same-shaped ops with different closures/defaults
        # (e.g. the two lambdas of one `split`) in separate priced buckets
        key = (e["name"], _shape_key(e),
               (e.get("attrs") or {}).get("variant", ""))
        b = buckets.setdefault(key, {"events": [], "ref": None})
        b["events"].append(e)
        if b["ref"] is None and e.get("_ref") is not None:
            b["ref"] = e["_ref"]

    rows = []
    total_compute_ms = 0.0
    attributed_ms = 0.0
    for (name, shapes, variant), b in buckets.items():
        achieved_ms = sum(e["dur"] for e in b["events"]) / 1e6
        total_compute_ms += achieved_ms
        est = _estimate_ref(b["ref"], spec, variant) \
            if b["ref"] is not None else None
        row = {"name": name, "shapes": shapes, "calls": len(b["events"]),
               "achieved_ms": achieved_ms, "roofline_ms": None,
               "flops": None, "bytes": None, "efficiency": None,
               "gap_ms": None}
        if est is not None:
            flops, bytes_, per_call_ms = est
            roofline_ms = per_call_ms * len(b["events"])
            row.update({
                "roofline_ms": roofline_ms,
                "flops": flops * len(b["events"]),
                "bytes": bytes_ * len(b["events"]),
                "efficiency": (roofline_ms / achieved_ms)
                if achieved_ms > 0 else None,
                "gap_ms": achieved_ms - roofline_ms,
            })
            attributed_ms += achieved_ms
        rows.append(row)

    coverage = attributed_ms / total_compute_ms if total_compute_ms else 0.0
    rows.sort(key=lambda r: -r["achieved_ms"])
    return AnalyzeReport(spec, rows, phases, wall_ms, coverage, top_k=top_k)
