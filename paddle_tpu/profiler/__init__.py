"""paddle.profiler equivalent.

Reference (SURVEY §5): python/paddle/profiler/profiler.py:340 `Profiler`
with scheduler windows, backed by C++ `platform/profiler/` — host_tracer.cc
collects RecordEvent spans (event_tracing.h:49), cuda_tracer.cc wraps CUPTI,
events merge into a tree (event_node.cc) exported as chrome-trace JSON
(chrometracing_logger.cc) plus python statistics tables
(profiler_statistic.py).

TPU-native mapping:
- host tracer  -> in-process span recorder (this file; RecordEvent spans
  with nesting tracked per thread), auto-fed by the framework: apply_op
  emits Operator spans, distributed/collective.py Communication spans,
  io.DataLoader Dataloader spans, hapi/optimizer/autograd the
  Forward/Backward/Optimization phase spans
- CUPTI tracer -> jax.profiler XPlane capture (start_trace/stop_trace),
  viewable in TensorBoard/XProf — device-side kernel timelines come from
  the XLA runtime, the role CUPTI plays for CUDA
- chrome-trace logger -> export_chrome_tracing handler over the host spans
- profiler_statistic  -> statistic.py summary views + the roofline
  attribution join against cost_model/analytical.py (Profiler.analyze)
"""
import contextlib
import json
import os
import threading
import time

import jax
import numpy as np

from ..observability.tracecontext import (
    clear_trace as _clear_trace, current_trace_id as _current_trace_id,
    ensure_trace as _ensure_trace, new_span_id as _new_span_id,
    process_trace_id as _process_trace_id,
)

__all__ = ["Profiler", "ProfilerTarget", "ProfilerState", "RecordEvent",
           "TracerEventType", "SortedKeys", "SummaryView",
           "make_scheduler", "export_chrome_tracing", "export_protobuf",
           "load_profiler_result"]

STEP_TIMELINE_SCHEMA = "paddle_tpu.step_timeline.v1"


class ProfilerTarget:
    CPU = "cpu"
    GPU = "gpu"
    TPU = "tpu"
    CUSTOM_DEVICE = "custom_device"


class ProfilerState:
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(*, closed=0, ready=0, record=1, repeat=0, skip_first=0):
    """Reference: profiler.py make_scheduler — cycle through
    closed/ready/record windows."""
    def scheduler(step):
        s = step - skip_first
        if s < 0:
            return ProfilerState.CLOSED
        cycle = closed + ready + record
        if repeat and s >= cycle * repeat:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return scheduler


# ---------------------------------------------------------------- host tracer

def _live_bytes():
    """Live device bytes right now (the MemoryView sample). jax.live_arrays
    enumerates every jax.Array the process holds a reference to."""
    try:
        return int(sum(a.size * a.dtype.itemsize for a in jax.live_arrays()))
    except Exception:                                        # noqa: BLE001
        return None


class _HostTracer:
    """Span recorder (the host_tracer.cc role). Spans: dicts with name,
    thread id, start/end (ns), nesting depth, optional attrs (shapes,
    payload bytes, cache outcome), optional memory samples, and an
    in-memory `_ref` (fn + avals) for analyze-time roofline re-trace.

    The `enabled` attribute IS the hot-path guard: instrumentation sites
    check it before building any span metadata, so a CLOSED profiler costs
    one attribute load per op.

    Thread safety (serving scheduler workers hammer this from several
    threads at once): every thread owns its own nesting stack in
    `_stacks` (keyed by thread id — a plain dict entry each thread
    mutates alone, readable cross-thread by the flight recorder's
    postmortem dump), span/parent ids are assigned FROM that per-thread
    stack so a span's parent is always a span of the same thread, and
    the shared `events` list is only ever touched under `_lock`.

    Trace context: every span carries a fresh 8-byte `span_id`, its
    same-thread `parent` span id, and the current `trace` id
    (observability.tracecontext) — the fields the PS RPC fabric
    propagates cross-process and export_chrome_tracing emits.

    Flight recorder: when `ring` is attached (observability.
    flight_recorder), closed spans are ALSO pushed there — including
    spans recorded while the profiler is CLOSED, so a postmortem always
    has recent history."""

    def __init__(self):
        self.enabled = False
        self.sample_memory = False
        self.with_flops = True
        self.events = []
        self.ring = None                 # FlightRecorder, when enabled
        self._lock = threading.Lock()
        self._stacks = {}                # thread id -> open-span stack
        self._ref_seen = set()

    def _stack(self):
        tid = threading.get_ident()
        st = self._stacks.get(tid)
        if st is None:
            st = self._stacks.setdefault(tid, [])
        return st

    def begin(self, name, event_type, attrs=None, ref=None):
        if not self.enabled and self.ring is None:
            return None
        st = self._stack()
        rec = {"name": name, "type": event_type,
               "tid": threading.get_ident(),
               "ts": time.perf_counter_ns(), "dur": None,
               "depth": len(st),
               "span_id": _new_span_id(),
               "parent": st[-1]["span_id"] if st else None,
               "trace": _current_trace_id()}
        if not self.enabled:             # ring-only span: keep it out of
            rec["_fr_only"] = True       # the profiler's window events
        if attrs is not None:
            rec["attrs"] = attrs
        if ref is not None:
            rec["_ref"] = ref
        if self.sample_memory:
            rec["mem0"] = _live_bytes()
        st.append(rec)
        return rec

    def end(self, rec):
        if rec is None:
            return
        st = self._stack()
        if st and st[-1] is rec:
            st.pop()
        elif rec in st:                   # unbalanced nesting: drop through
            st.remove(rec)
        if not st:                        # evict: dead threads must not
            self._stacks.pop(threading.get_ident(), None)  # leak entries
        rec["dur"] = time.perf_counter_ns() - rec["ts"]
        if self.sample_memory:
            rec["mem1"] = _live_bytes()
        ring = self.ring
        if ring is not None:
            ring.record_span(rec)
        if rec.pop("_fr_only", False):
            return
        with self._lock:
            self.events.append(rec)

    def cancel(self, rec):
        """Abandon an open span without recording it (e.g. the DataLoader
        span opened around a `next` that raised StopIteration)."""
        if rec is None:
            return
        st = self._stack()
        if st and st[-1] is rec:
            st.pop()
        elif rec in st:
            st.remove(rec)
        if not st:
            self._stacks.pop(threading.get_ident(), None)

    def note(self, key, value):
        """Attach a key to the innermost open span on this thread (used by
        apply_op to mark the eager-cache outcome from inside the dispatch)."""
        st = self._stack()
        if st:
            st[-1].setdefault("attrs", {})[key] = value

    def mark(self):
        with self._lock:
            return len(self.events)

    def since(self, idx):
        with self._lock:
            return list(self.events[idx:])

    def ref_once(self, key):
        """True the first time `key` is seen this window — callers attach
        the heavyweight analyze-ref only then (one per op bucket, not one
        per dispatch)."""
        with self._lock:
            if key in self._ref_seen:
                return False
            self._ref_seen.add(key)
            return True

    def drain(self):
        with self._lock:
            ev, self.events = self.events, []
            self._ref_seen.clear()
        return ev


_tracer = _HostTracer()


class TracerEventType:
    Operator = "Operator"
    Dataloader = "Dataloader"
    ProfileStep = "ProfileStep"
    Forward = "Forward"
    Backward = "Backward"
    Optimization = "Optimization"
    Communication = "Communication"
    PythonOp = "PythonOp"
    UserDefined = "UserDefined"


class RecordEvent:
    """User-code span (reference: platform/profiler/event_tracing.h:49;
    python surface profiler/utils.py RecordEvent). Also forwards to
    jax.profiler.TraceAnnotation so spans show up inside XPlane captures."""

    def __init__(self, name, event_type=TracerEventType.PythonOp, attrs=None):
        self.name = name
        self.event_type = event_type
        self.attrs = attrs
        self._rec = None
        self._ann = None

    def begin(self):
        self._rec = _tracer.begin(self.name, self.event_type, self.attrs)
        if self._rec is not None and _tracer.enabled:
            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()

    def end(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None
        _tracer.end(self._rec)
        self._rec = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


# ------------------------------------------------------------- trace handlers

def _json_safe_attrs(rec):
    attrs = rec.get("attrs")
    if not attrs:
        return None
    out = {}
    for k, v in attrs.items():
        try:
            json.dumps(v)
            out[k] = v
        except TypeError:
            out[k] = repr(v)
    return out


def export_chrome_tracing(dir_name, worker_name=None):
    """Returns an on_trace_ready handler writing chrome://tracing JSON
    (reference: chrometracing_logger.cc).

    Exports the LAST RECORD WINDOW only (an empty window exports as empty —
    never silently the cumulative history), and maps each (thread, nesting
    depth) to its own tid lane with thread_name metadata so nested spans
    render stacked instead of flattened.

    Every span's args carry its trace_id/span_id/parent_span_id, and the
    file's otherData carries clock_sync_ns (wall-clock epoch minus this
    process's perf_counter origin) — the two ingredients
    observability.merge_chrome_traces needs to fold the per-process
    exports of a distributed run into one causally-linked timeline."""
    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}_time_{int(time.time())}"
                            ".paddle_trace.json")
        window = prof._window_events
        if window is None:          # profiler stopped without ever recording
            window = prof._events
        pid = os.getpid()
        lanes = {}                  # (tid, depth) -> lane id
        events = []
        for e in window:
            lane_key = (e["tid"], e["depth"])
            lane = lanes.setdefault(lane_key, len(lanes))
            ev = {"name": e["name"], "cat": e["type"], "ph": "X",
                  "pid": pid, "tid": lane,
                  "ts": e["ts"] / 1000.0, "dur": (e["dur"] or 0) / 1000.0}
            args = _json_safe_attrs(e) or {}
            if e.get("span_id"):
                args["span_id"] = e["span_id"]
            if e.get("parent"):
                args["parent_span_id"] = e["parent"]
            if e.get("trace"):
                args["trace_id"] = e["trace"]
            if args:
                ev["args"] = args
            events.append(ev)
        meta = []
        for (tid, depth), lane in sorted(lanes.items(), key=lambda kv: kv[1]):
            meta.append({"ph": "M", "name": "thread_name", "pid": pid,
                         "tid": lane,
                         "args": {"name": f"thread {tid} · depth {depth}"}})
            meta.append({"ph": "M", "name": "thread_sort_index", "pid": pid,
                         "tid": lane, "args": {"sort_index": lane}})
        with open(path, "w") as f:
            json.dump({"traceEvents": meta + events,
                       "displayTimeUnit": "ms",
                       "otherData": {
                           "clock_sync_ns":
                               time.time_ns() - time.perf_counter_ns(),
                           "pid": pid}}, f)
        prof._exported_path = path
    return handler


def export_protobuf(dir_name, worker_name=None):
    """The reference's protobuf dump; here an alias of chrome tracing (the
    XPlane protobufs are produced by jax.profiler's own capture)."""
    return export_chrome_tracing(dir_name, worker_name)


def load_profiler_result(path):
    with open(path) as f:
        return json.load(f)


# ------------------------------------------------------------------- profiler

class Profiler:
    """Scheduler-windowed profiler (reference: profiler.py:340).

    targets defaults to host + device. timer_only=True skips the device
    XPlane capture (benchmark mode, reference semantics).
    profile_memory=True samples live device bytes at span boundaries
    (MemoryView). with_flops=True (default) lets apply_op attach the op
    callable + abstract shapes so analyze() can price each op with the
    analytical roofline. timeline=<path> appends one JSONL record per
    recorded step (phase durations, op digest, cache stats, memory peak)
    — the artifact tools/perf_report.py renders."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=True, timeline=None):
        if callable(scheduler):
            self._scheduler = scheduler
        elif isinstance(scheduler, (tuple, list)):
            lo, hi = scheduler
            self._scheduler = make_scheduler(closed=lo, record=hi - lo,
                                             repeat=1)
        else:
            self._scheduler = None  # always on
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._profile_memory = bool(profile_memory)
        self._with_flops = bool(with_flops)
        self._timeline_path = timeline
        self._log_dir = "./profiler_log"
        self._step = 0
        self._state = ProfilerState.CLOSED
        self._device_active = False
        self._events = []
        self._step_times = []
        self._step_samples = []
        self._last_t = None
        self._step_rec = None
        self._exported_path = None
        self._window_events = None
        self._step_mark = 0
        self._cache_mark = None

    # ------------------------------------------------------------ lifecycle
    def _target_state(self):
        if self._scheduler is None:
            return ProfilerState.RECORD
        return self._scheduler(self._step)

    def _recording(self):
        return self._state in (ProfilerState.RECORD,
                               ProfilerState.RECORD_AND_RETURN)

    def _transition(self, new):
        recording = self._recording()
        want = new in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        if want and not recording:
            _tracer.sample_memory = self._profile_memory
            _tracer.with_flops = self._with_flops
            _tracer.enabled = True
            if not self._timer_only:
                try:
                    jax.profiler.start_trace(self._log_dir)
                    self._device_active = True
                except Exception:
                    self._device_active = False
        if recording and not want:
            self._collect()
        self._state = new

    def _collect(self):
        _tracer.enabled = False
        _tracer.sample_memory = False
        window = _tracer.drain()
        self._events.extend(window)       # cumulative, for statistics()
        self._window_events = window      # this window only, for export
        if self._device_active:
            jax.profiler.stop_trace()
            self._device_active = False
        if self._on_trace_ready:
            self._on_trace_ready(self)

    def start(self):
        # one trace id for everything this window records — and for every
        # PS RPC issued under it, in every process it reaches. If WE set
        # it, stop() clears it: post-window RPCs must not keep paying the
        # propagation bytes for span ids no export will contain, and the
        # next window gets a fresh trace (one trace id per causal unit).
        # ownership keys on the PROCESS default, not current_trace_id():
        # a thread-local trace_scope would mask the process slot and leave
        # the id ensure_trace() installs here uncleared forever
        self._owns_trace = _process_trace_id() is None
        _ensure_trace()
        self._last_t = time.perf_counter()
        self._transition(self._target_state())
        self._open_step_span()

    def stop(self):
        # timeline records are written per step() call only — stop() closes
        # a partial window that has no step duration to report
        self._close_step_span()
        if self._recording():
            self._collect()
        self._state = ProfilerState.CLOSED
        if getattr(self, "_owns_trace", False):
            _clear_trace()
            self._owns_trace = False

    def _open_step_span(self):
        self._step_mark = _tracer.mark()
        if self._timeline_path is not None and self._recording():
            from ..core.tensor import _CACHE_STATS
            self._cache_mark = dict(_CACHE_STATS)
        else:
            self._cache_mark = None
        self._step_rec = _tracer.begin(f"ProfileStep#{self._step}",
                                       TracerEventType.ProfileStep)

    def _close_step_span(self):
        _tracer.end(self._step_rec)
        self._step_rec = None

    def step(self, num_samples=None):
        now = time.perf_counter()
        dt = now - self._last_t if self._last_t is not None else None
        if dt is not None:
            self._step_times.append(dt)
            self._step_samples.append(num_samples)
        self._last_t = now
        self._close_step_span()
        self._write_timeline_record(dt, num_samples)
        self._step += 1
        self._transition(self._target_state())
        self._open_step_span()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # ----------------------------------------------------------- timeline
    def _write_timeline_record(self, dt, num_samples):
        """One JSONL record for the step that just closed (only while the
        window was recording) — the durable perf evidence a dead TPU grant
        cannot take with it."""
        if self._timeline_path is None or not self._recording():
            return
        from . import statistic as _stat
        window = _tracer.since(self._step_mark)
        step_events = [e for e in window
                       if e["type"] != TracerEventType.ProfileStep]
        rec = {
            "schema": STEP_TIMELINE_SCHEMA,
            "step": self._step,
            "step_ms": None if dt is None else round(dt * 1e3, 4),
            "phases": _stat.phase_durations_ms(step_events),
            "ops": _stat.op_digest(step_events, top=8),
            "num_samples": num_samples,
        }
        if self._cache_mark is not None:
            from ..core.tensor import _CACHE_STATS
            rec["cache"] = {k: _CACHE_STATS[k] - self._cache_mark.get(k, 0)
                            for k in ("hits", "misses", "bypass")}
        mem = [m for e in step_events
               for m in (e.get("mem0"), e.get("mem1")) if m is not None]
        rec["mem_peak_bytes"] = max(mem) if mem else None
        os.makedirs(os.path.dirname(os.path.abspath(self._timeline_path)),
                    exist_ok=True)
        with open(self._timeline_path, "a") as f:
            f.write(json.dumps(rec) + "\n")

    # ------------------------------------------------------------ reporting
    def step_info(self, unit=None):
        """Last-10-steps digest. `unit` labels throughput: with
        step(num_samples=...) provided, ips = samples/s in that unit
        (reference: profiler.py step_info's `unit`); else steps/s."""
        if not self._step_times:
            return ""
        arr = np.asarray(self._step_times[-10:])
        pairs = [(t, s) for t, s in zip(self._step_times[-10:],
                                        self._step_samples[-10:])
                 if s is not None]
        if unit and pairs:
            ips = sum(s for _, s in pairs) / sum(t for t, _ in pairs)
            return (f"avg step {arr.mean() * 1000:.2f} ms, "
                    f"ips {ips:.2f} {unit}/s")
        # without num_samples the only honest rate is steps/s — a unit
        # label here would caption steps/s as e.g. images/s
        return (f"avg step {arr.mean() * 1000:.2f} ms, "
                f"ips {1.0 / arr.mean():.2f} steps/s")

    def statistics(self):
        """Aggregate spans by name (reference: profiler_statistic.py)."""
        by_name = {}
        for e in self._events:
            by_name.setdefault(e["name"], []).append(e["dur"] or 0)
        rows = []
        for name, durs in by_name.items():
            d = np.asarray(durs, dtype=np.float64) / 1e6  # ms
            rows.append({"name": name, "calls": len(durs),
                         "total_ms": float(d.sum()), "avg_ms": float(d.mean()),
                         "max_ms": float(d.max()), "min_ms": float(d.min())})
        rows.sort(key=lambda r: -r["total_ms"])
        return rows

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms", views=None):
        """Print the summary tables (reference: profiler.py summary /
        profiler_statistic.py _build_table). `views`: a SummaryView value
        or list of them; default prints OverView + OperatorView (+
        DistributedView / MemoryView when comm spans / memory samples
        exist)."""
        from . import statistic as _stat
        text = _stat.build_summary(self._events, sorted_by=sorted_by,
                                   views=views, time_unit=time_unit)
        if text:
            print(text)
        elif not self._step_times:
            return
        if self._step_times:
            print(self.step_info())

    def analyze(self, device=None, top_k=3):
        """Join recorded host spans against the analytical roofline
        (cost_model/analytical.py): per-op achieved vs roofline time, the
        top-k MFU gap contributors, phase breakdown, and coverage of the
        recorded compute span time. Returns statistic.AnalyzeReport."""
        from . import statistic as _stat
        return _stat.analyze(self._events, step_times=self._step_times,
                             device=device, top_k=top_k)


class SortedKeys:
    """reference: profiler/profiler_statistic.py SortedKeys — summary sort
    orders. Host spans only (XLA owns the device timeline), so the GPU*
    keys alias their CPU counterparts."""
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class SummaryView:
    """reference: profiler/profiler.py SummaryView — which summary tables
    to print."""
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8
