"""paddle.profiler equivalent (reference: python/paddle/profiler/profiler.py:340
+ C++ host_tracer/cuda_tracer).

TPU-native: wraps jax.profiler (XPlane capture -> TensorBoard/perfetto trace),
which replaces CUPTI. RecordEvent maps to jax.profiler.TraceAnnotation.
Scheduler-window semantics (wait/warmup/active) are preserved.
"""
import contextlib
import time

import jax


class ProfilerTarget:
    CPU = "cpu"
    GPU = "gpu"
    TPU = "tpu"
    CUSTOM_DEVICE = "custom_device"


class ProfilerState:
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(closed=0, ready=0, record=1, repeat=0, skip_first=0):
    def scheduler(step):
        s = step - skip_first
        if s < 0:
            return ProfilerState.CLOSED
        cycle = closed + ready + record
        if repeat and s >= cycle * repeat:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return scheduler


def export_chrome_tracing(dir_name, worker_name=None):
    def handler(prof):
        prof._log_dir = dir_name
    return handler


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False):
        self._scheduler = scheduler if callable(scheduler) else (
            make_scheduler(record=scheduler[1] - scheduler[0], skip_first=scheduler[0])
            if isinstance(scheduler, (tuple, list)) else None)
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._log_dir = "./profiler_log"
        self._step = 0
        self._active = False
        self._step_times = []
        self._last_t = None

    def start(self):
        self._last_t = time.perf_counter()
        if not self._timer_only:
            try:
                jax.profiler.start_trace(self._log_dir)
                self._active = True
            except Exception:
                self._active = False

    def stop(self):
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
        if self._on_trace_ready:
            self._on_trace_ready(self)

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last_t is not None:
            self._step_times.append(now - self._last_t)
        self._last_t = now
        self._step += 1

    def step_info(self, unit=None):
        if not self._step_times:
            return ""
        import numpy as np
        arr = np.asarray(self._step_times[-10:])
        return (f"avg step {arr.mean()*1000:.2f} ms, "
                f"ips {1.0/arr.mean():.2f} steps/s")

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        print(self.step_info())

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class RecordEvent:
    """Reference: platform/profiler/event_tracing.h:49 RecordEvent."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._ctx = None

    def begin(self):
        self._ctx = jax.profiler.TraceAnnotation(self.name)
        self._ctx.__enter__()

    def end(self):
        if self._ctx is not None:
            self._ctx.__exit__(None, None, None)
            self._ctx = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def load_profiler_result(path):
    raise NotImplementedError
