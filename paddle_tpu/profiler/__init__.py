"""paddle.profiler equivalent.

Reference (SURVEY §5): python/paddle/profiler/profiler.py:340 `Profiler`
with scheduler windows, backed by C++ `platform/profiler/` — host_tracer.cc
collects RecordEvent spans (event_tracing.h:49), cuda_tracer.cc wraps CUPTI,
events merge into a tree (event_node.cc) exported as chrome-trace JSON
(chrometracing_logger.cc) plus python statistics tables
(profiler_statistic.py).

TPU-native mapping:
- host tracer  -> in-process span recorder (this file; RecordEvent spans
  with nesting tracked per thread)
- CUPTI tracer -> jax.profiler XPlane capture (start_trace/stop_trace),
  viewable in TensorBoard/XProf — device-side kernel timelines come from
  the XLA runtime, the role CUPTI plays for CUDA
- chrome-trace logger -> export_chrome_tracing handler over the host spans
- profiler_statistic  -> summary() aggregation table
"""
import contextlib
import json
import os
import threading
import time

import jax

__all__ = ["Profiler", "ProfilerTarget", "ProfilerState", "RecordEvent",
           "make_scheduler", "export_chrome_tracing", "export_protobuf",
           "load_profiler_result"]


class ProfilerTarget:
    CPU = "cpu"
    GPU = "gpu"
    TPU = "tpu"
    CUSTOM_DEVICE = "custom_device"


class ProfilerState:
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(*, closed=0, ready=0, record=1, repeat=0, skip_first=0):
    """Reference: profiler.py make_scheduler — cycle through
    closed/ready/record windows."""
    def scheduler(step):
        s = step - skip_first
        if s < 0:
            return ProfilerState.CLOSED
        cycle = closed + ready + record
        if repeat and s >= cycle * repeat:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return scheduler


# ---------------------------------------------------------------- host tracer

class _HostTracer:
    """Span recorder (the host_tracer.cc role). Spans: dicts with name,
    thread id, start/end (ns), nesting depth."""

    def __init__(self):
        self.enabled = False
        self.events = []
        self._lock = threading.Lock()
        self._tls = threading.local()

    def _depth(self):
        return getattr(self._tls, "depth", 0)

    def begin(self, name, event_type):
        if not self.enabled:
            return None
        rec = {"name": name, "type": event_type,
               "tid": threading.get_ident(),
               "ts": time.perf_counter_ns(), "dur": None,
               "depth": self._depth()}
        self._tls.depth = self._depth() + 1
        return rec

    def end(self, rec):
        if rec is None:
            return
        self._tls.depth = max(self._depth() - 1, 0)
        rec["dur"] = time.perf_counter_ns() - rec["ts"]
        with self._lock:
            self.events.append(rec)

    def drain(self):
        with self._lock:
            ev, self.events = self.events, []
        return ev


_tracer = _HostTracer()


class TracerEventType:
    Operator = "Operator"
    Dataloader = "Dataloader"
    ProfileStep = "ProfileStep"
    Forward = "Forward"
    Backward = "Backward"
    Optimization = "Optimization"
    Communication = "Communication"
    PythonOp = "PythonOp"
    UserDefined = "UserDefined"


class RecordEvent:
    """User-code span (reference: platform/profiler/event_tracing.h:49;
    python surface profiler/utils.py RecordEvent). Also forwards to
    jax.profiler.TraceAnnotation so spans show up inside XPlane captures."""

    def __init__(self, name, event_type=TracerEventType.PythonOp):
        self.name = name
        self.event_type = event_type
        self._rec = None
        self._ann = None

    def begin(self):
        self._rec = _tracer.begin(self.name, self.event_type)
        if _tracer.enabled:
            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()

    def end(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None
        _tracer.end(self._rec)
        self._rec = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


# ------------------------------------------------------------- trace handlers

def export_chrome_tracing(dir_name, worker_name=None):
    """Returns an on_trace_ready handler writing chrome://tracing JSON
    (reference: chrometracing_logger.cc)."""
    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}_time_{int(time.time())}"
                            ".paddle_trace.json")
        events = []
        for e in getattr(prof, "_window_events", None) or prof._events:
            events.append({
                "name": e["name"], "cat": e["type"], "ph": "X",
                "pid": os.getpid(), "tid": e["tid"],
                "ts": e["ts"] / 1000.0, "dur": (e["dur"] or 0) / 1000.0,
            })
        with open(path, "w") as f:
            json.dump({"traceEvents": events,
                       "displayTimeUnit": "ms"}, f)
        prof._exported_path = path
    return handler


def export_protobuf(dir_name, worker_name=None):
    """The reference's protobuf dump; here an alias of chrome tracing (the
    XPlane protobufs are produced by jax.profiler's own capture)."""
    return export_chrome_tracing(dir_name, worker_name)


def load_profiler_result(path):
    with open(path) as f:
        return json.load(f)


# ------------------------------------------------------------------- profiler

class Profiler:
    """Scheduler-windowed profiler (reference: profiler.py:340).

    targets defaults to host + device. timer_only=True skips the device
    XPlane capture (benchmark mode, reference semantics)."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False):
        if callable(scheduler):
            self._scheduler = scheduler
        elif isinstance(scheduler, (tuple, list)):
            lo, hi = scheduler
            self._scheduler = make_scheduler(closed=lo, record=hi - lo,
                                             repeat=1)
        else:
            self._scheduler = None  # always on
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._log_dir = "./profiler_log"
        self._step = 0
        self._state = ProfilerState.CLOSED
        self._device_active = False
        self._events = []
        self._step_times = []
        self._last_t = None
        self._step_rec = None
        self._exported_path = None
        self._window_events = None

    # ------------------------------------------------------------ lifecycle
    def _target_state(self):
        if self._scheduler is None:
            return ProfilerState.RECORD
        return self._scheduler(self._step)

    def _transition(self, new):
        recording = self._state in (ProfilerState.RECORD,
                                    ProfilerState.RECORD_AND_RETURN)
        want = new in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        if want and not recording:
            _tracer.enabled = True
            if not self._timer_only:
                try:
                    jax.profiler.start_trace(self._log_dir)
                    self._device_active = True
                except Exception:
                    self._device_active = False
        if recording and not want:
            self._collect()
        self._state = new

    def _collect(self):
        _tracer.enabled = False
        window = _tracer.drain()
        self._events.extend(window)       # cumulative, for statistics()
        self._window_events = window      # this window only, for export
        if self._device_active:
            jax.profiler.stop_trace()
            self._device_active = False
        if self._on_trace_ready:
            self._on_trace_ready(self)

    def start(self):
        self._last_t = time.perf_counter()
        self._transition(self._target_state())
        self._open_step_span()

    def stop(self):
        self._close_step_span()
        if self._state in (ProfilerState.RECORD,
                           ProfilerState.RECORD_AND_RETURN):
            self._collect()
        self._state = ProfilerState.CLOSED

    def _open_step_span(self):
        self._step_rec = _tracer.begin(f"ProfileStep#{self._step}",
                                       TracerEventType.ProfileStep)

    def _close_step_span(self):
        _tracer.end(self._step_rec)
        self._step_rec = None

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last_t is not None:
            self._step_times.append(now - self._last_t)
        self._last_t = now
        self._close_step_span()
        self._step += 1
        self._transition(self._target_state())
        self._open_step_span()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # ------------------------------------------------------------ reporting
    def step_info(self, unit=None):
        if not self._step_times:
            return ""
        import numpy as np
        arr = np.asarray(self._step_times[-10:])
        return (f"avg step {arr.mean() * 1000:.2f} ms, "
                f"ips {1.0 / arr.mean():.2f} steps/s")

    def statistics(self):
        """Aggregate spans by name (reference: profiler_statistic.py)."""
        import numpy as np
        by_name = {}
        for e in self._events:
            by_name.setdefault(e["name"], []).append(e["dur"] or 0)
        rows = []
        for name, durs in by_name.items():
            d = np.asarray(durs, dtype=np.float64) / 1e6  # ms
            rows.append({"name": name, "calls": len(durs),
                         "total_ms": float(d.sum()), "avg_ms": float(d.mean()),
                         "max_ms": float(d.max()), "min_ms": float(d.min())})
        rows.sort(key=lambda r: -r["total_ms"])
        return rows

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        rows = self.statistics()
        if not rows:
            print(self.step_info())
            return
        width = max((len(r["name"]) for r in rows), default=4)
        print(f"{'Name':<{width}}  {'Calls':>6}  {'Total(ms)':>10}  "
              f"{'Avg(ms)':>9}  {'Max(ms)':>9}  {'Min(ms)':>9}")
        for r in rows:
            print(f"{r['name']:<{width}}  {r['calls']:>6}  "
                  f"{r['total_ms']:>10.3f}  {r['avg_ms']:>9.3f}  "
                  f"{r['max_ms']:>9.3f}  {r['min_ms']:>9.3f}")
        if self._step_times:
            print(self.step_info())


class SortedKeys:
    """reference: profiler/profiler_statistic.py SortedKeys — summary sort
    orders."""
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


class SummaryView:
    """reference: profiler/profiler.py SummaryView — which summary tables
    to print."""
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8
