"""paddle.device.cuda (reference: device/cuda/__init__.py). There is no
CUDA device here; the namespace maps onto the accelerator (TPU) so
device-management call sites keep working: streams/events are no-ops
(XLA owns scheduling), memory stats come from PjRt when the backend
exposes them.
"""
import jax

__all__ = ["Stream", "Event", "current_stream", "synchronize",
           "device_count", "empty_cache", "max_memory_allocated",
           "max_memory_reserved", "memory_allocated", "memory_reserved",
           "stream_guard", "get_device_properties", "get_device_name",
           "get_device_capability"]


class Stream:
    """XLA owns stream scheduling; synchronize() drains the device."""

    def __init__(self, device=None, priority=None):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        return None

    def wait_stream(self, stream):
        return None

    def record_event(self, event=None):
        return event or Event()


class Event:
    def __init__(self, enable_timing=False, blocking=False,
                 interprocess=False):
        pass

    def record(self, stream=None):
        return None

    def query(self):
        return True

    def synchronize(self):
        synchronize()


def current_stream(device=None):
    return Stream(device)


def synchronize(device=None):
    (jax.device_put(0) + 0).block_until_ready()


def device_count():
    try:
        return len(jax.devices())
    except RuntimeError:
        return 0


def empty_cache():
    """XLA's BFC allocator manages HBM; jax.clear_caches drops host-side
    executable caches (the closest analogue)."""
    jax.clear_caches()


def _mem_stats():
    try:
        return jax.devices()[0].memory_stats() or {}
    except Exception:
        return {}


def memory_allocated(device=None):
    return int(_mem_stats().get("bytes_in_use", 0))


def max_memory_allocated(device=None):
    return int(_mem_stats().get("peak_bytes_in_use", 0))


def memory_reserved(device=None):
    return int(_mem_stats().get("bytes_reserved",
                                _mem_stats().get("bytes_in_use", 0)))


def max_memory_reserved(device=None):
    return max_memory_allocated(device)


def stream_guard(stream):
    import contextlib
    return contextlib.nullcontext(stream)


def get_device_properties(device=None):
    d = jax.devices()[0]

    class _Props:
        name = getattr(d, "device_kind", str(d))
        major = 0
        minor = 0
        total_memory = int(_mem_stats().get("bytes_limit", 0))
        multi_processor_count = 1

    return _Props()


def get_device_name(device=None):
    return getattr(jax.devices()[0], "device_kind", "TPU")


def get_device_capability(device=None):
    return (0, 0)
