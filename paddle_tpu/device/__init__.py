"""paddle.device equivalent."""
from . import cuda  # noqa: F401
from ..core.device import (  # noqa: F401
    CPUPlace, Place, TPUPlace, device_count, get_device, is_compiled_with_cuda,
    is_compiled_with_npu, is_compiled_with_tpu, is_compiled_with_xpu, set_device,
)


def op_cache_stats():
    """Public view of the eager per-op executable cache (core/tensor.py)
    — the stats device.cuda exposes for HBM, for the dispatch cache:
    {hits, misses, bypass, size, hit_rate}. `size` is the number of cached
    compiled-op runners; `bypass` counts dispatches whose op identity was
    unhashable (correct but uncached).

    These counters are ALSO published to the unified metrics registry as
    `op_cache_*` gauges (via a snapshot-time collector, so the dispatch
    hot path is untouched). Reading `core.tensor._CACHE_STATS` directly
    is deprecated — this function and the registry are the public
    surfaces."""
    from ..core import tensor as _t
    total = _t._CACHE_STATS["hits"] + _t._CACHE_STATS["misses"]
    return {
        "hits": _t._CACHE_STATS["hits"],
        "misses": _t._CACHE_STATS["misses"],
        "bypass": _t._CACHE_STATS["bypass"],
        "size": len(_t._EAGER_CACHE),
        "hit_rate": (_t._CACHE_STATS["hits"] / total) if total else 0.0,
    }


def _collect_op_cache(reg):
    """Metrics-registry collector: mirror op_cache_stats() into gauges at
    snapshot time (gauges, not counters, because reset_op_cache_stats()
    legitimately zeroes the underlying values)."""
    s = op_cache_stats()
    reg.gauge("op_cache_hits",
              "Eager op-cache hits since the last reset").set(s["hits"])
    reg.gauge("op_cache_misses",
              "Eager op-cache misses since the last reset").set(s["misses"])
    reg.gauge("op_cache_bypass",
              "Uncacheable eager dispatches since the last reset"
              ).set(s["bypass"])
    reg.gauge("op_cache_size",
              "Cached compiled-op runners held right now").set(s["size"])
    reg.gauge("op_cache_hit_rate",
              "hits / (hits + misses) since the last reset"
              ).set(s["hit_rate"])


from ..observability import metrics as _metrics  # noqa: E402

_metrics.registry().register_collector(_collect_op_cache)


def reset_op_cache_stats():
    """Zero the eager-cache counters (cached executables stay)."""
    from ..core import tensor as _t
    for k in _t._CACHE_STATS:
        _t._CACHE_STATS[k] = 0


def clear_op_cache():
    """Drop every cached eager-op executable AND zero the counters (the
    dispatch-cache analogue of device.cuda.empty_cache).

    Coherence contract with the persistent tier
    (framework/compile_cache.py): when a process-global compile cache is
    attached, clearing the in-memory op cache ALSO invalidates it —
    every persistent entry committed before this call reads as a miss
    for the rest of this process and is recommitted by the next compile,
    so a cleared cache can never resurrect a pre-clear executable (e.g.
    after an in-process code redefinition). Entries stay on disk for
    FRESH processes, where content-addressed keys (lowering hash +
    framework source fingerprint) guarantee they can only hit for
    byte-identical programs. Engine-private serving caches
    (EngineConfig.compile_cache_dir) are out of scope — they are not op
    caches and follow the serving engine's lifecycle."""
    from ..core import tensor as _t
    from ..framework import compile_cache as _cc
    _t._EAGER_CACHE.clear()
    reset_op_cache_stats()
    _cc.invalidate_active()


def compile_cache_stats():
    """Stats of the process-global persistent compile cache, or None
    when no cache is attached: {hits, misses, bypass, corrupt,
    uncacheable, entries, path}. The same counters feed the metrics
    registry as compile_cache_{hits,misses}_total."""
    from ..framework import compile_cache as _cc
    cache = _cc.active()
    if cache is None:
        return None
    return {**cache.stats, "entries": len(cache.entries()),
            "path": cache.path}


def get_all_custom_device_type():
    return ["tpu"]


def is_compiled_with_custom_device(device_type):
    return device_type == "tpu"


class Stream:
    """Stream API compatibility: XLA owns scheduling; these are no-ops."""

    def synchronize(self):
        import jax
        (jax.device_put(0) + 0).block_until_ready()


def synchronize(device=None):
    import jax
    (jax.device_put(0) + 0).block_until_ready()


def current_stream(device=None):
    return Stream()


def get_cudnn_version():
    """reference: device/__init__.py get_cudnn_version — None when CUDA is
    not the backend."""
    return None


def XPUPlace(index=0):
    from ..core.device import _compat_place
    return _compat_place("XPUPlace", index)


def IPUPlace(index=0):
    from ..core.device import _compat_place
    return _compat_place("IPUPlace", index)


def MLUPlace(index=0):
    from ..core.device import _compat_place
    return _compat_place("MLUPlace", index)


def is_compiled_with_ipu():
    return False


def is_compiled_with_cinn():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_mlu():
    return False


def _all_devices():
    import jax
    devs = list(jax.devices())
    try:
        # the CPU platform always exists even when an accelerator is the
        # default backend (jax.devices() lists only the default)
        devs += [d for d in jax.devices("cpu") if d not in devs]
    except RuntimeError:
        pass
    return devs


def get_all_device_type():
    return sorted({d.platform for d in _all_devices()})


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in _all_devices()]


def get_available_custom_device():
    return []
