"""paddle.device equivalent."""
from . import cuda  # noqa: F401
from ..core.device import (  # noqa: F401
    CPUPlace, Place, TPUPlace, device_count, get_device, is_compiled_with_cuda,
    is_compiled_with_npu, is_compiled_with_tpu, is_compiled_with_xpu, set_device,
)


def get_all_custom_device_type():
    return ["tpu"]


def is_compiled_with_custom_device(device_type):
    return device_type == "tpu"


class Stream:
    """Stream API compatibility: XLA owns scheduling; these are no-ops."""

    def synchronize(self):
        import jax
        (jax.device_put(0) + 0).block_until_ready()


def synchronize(device=None):
    import jax
    (jax.device_put(0) + 0).block_until_ready()


def current_stream(device=None):
    return Stream()


def get_cudnn_version():
    """reference: device/__init__.py get_cudnn_version — None when CUDA is
    not the backend."""
    return None


def XPUPlace(index=0):
    from ..core.device import _compat_place
    return _compat_place("XPUPlace", index)


def IPUPlace(index=0):
    from ..core.device import _compat_place
    return _compat_place("IPUPlace", index)


def MLUPlace(index=0):
    from ..core.device import _compat_place
    return _compat_place("MLUPlace", index)


def is_compiled_with_ipu():
    return False


def is_compiled_with_cinn():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_mlu():
    return False


def _all_devices():
    import jax
    devs = list(jax.devices())
    try:
        # the CPU platform always exists even when an accelerator is the
        # default backend (jax.devices() lists only the default)
        devs += [d for d in jax.devices("cpu") if d not in devs]
    except RuntimeError:
        pass
    return devs


def get_all_device_type():
    return sorted({d.platform for d in _all_devices()})


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in _all_devices()]


def get_available_custom_device():
    return []
