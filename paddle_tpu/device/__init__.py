"""paddle.device equivalent."""
from ..core.device import (  # noqa: F401
    CPUPlace, Place, TPUPlace, device_count, get_device, is_compiled_with_cuda,
    is_compiled_with_npu, is_compiled_with_tpu, is_compiled_with_xpu, set_device,
)


def get_all_custom_device_type():
    return ["tpu"]


def is_compiled_with_custom_device(device_type):
    return device_type == "tpu"


class Stream:
    """Stream API compatibility: XLA owns scheduling; these are no-ops."""

    def synchronize(self):
        import jax
        (jax.device_put(0) + 0).block_until_ready()


def synchronize(device=None):
    import jax
    (jax.device_put(0) + 0).block_until_ready()


def current_stream(device=None):
    return Stream()
