"""Version shims over the installed jax.

The codebase targets the current jax spellings `jax.shard_map(...,
check_vma=)` and `jax.lax.axis_size(name)`. Older installs (<=0.4.x) only
ship `jax.experimental.shard_map.shard_map(..., check_rep=)` — same
semantics, pre-rename — and spell the axis size as `lax.psum(1, name)`
(which constant-folds to a python int inside a manual region). Rather than
sprinkling try/except at every call site (manual collectives, gpt_spmd,
ring attention, pipeline compile, graft entry), install adapters under the
modern names when they are missing. Idempotent; a no-op on jax versions
that already expose them.
"""
import jax


def install():
    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _exp_shard_map

        def shard_map(f, *args, **kwargs):
            if "check_vma" in kwargs:
                kwargs["check_rep"] = kwargs.pop("check_vma")
            return _exp_shard_map(f, *args, **kwargs)

        jax.shard_map = shard_map

    if not hasattr(jax.lax, "axis_size"):
        def axis_size(axis_name):
            return jax.lax.psum(1, axis_name)

        jax.lax.axis_size = axis_size


# jax version that first ships the typed XPlane reader
# jax.profiler.ProfileData (the binding observability.deviceprof prefers
# when present; the stdlib XSpace wire decoder covers everything older)
PROFILE_DATA_MIN_JAX = "0.5.1"


class ProfileDataUnavailableError(ImportError):
    """The running jax has no jax.profiler.ProfileData binding."""


def profile_data():
    """A normalized loader over `jax.profiler.ProfileData` across jax
    versions: returns `load(path) -> ProfileData` resolving the
    `from_file` / `from_serialized_xspace` API drift, or raises a
    curated ProfileDataUnavailableError naming the minimum jax version —
    never a raw ImportError/AttributeError mid-capture (ISSUE 9
    satellite). Callers that can read raw `.xplane.pb` bytes themselves
    (observability.deviceprof) catch it and fall back to the stdlib
    XSpace decoder (`observability/xplane.py`)."""
    import jaxlib

    versions = (f"installed: jax {jax.__version__}, "
                f"jaxlib {jaxlib.__version__}")
    try:
        from jax.profiler import ProfileData
    except ImportError:
        raise ProfileDataUnavailableError(
            f"jax.profiler.ProfileData requires jax>={PROFILE_DATA_MIN_JAX} "
            f"({versions}); paddle_tpu.observability.deviceprof falls back "
            "to its stdlib XSpace decoder automatically — only code that "
            "insists on the native binding needs a jax upgrade") from None
    if hasattr(ProfileData, "from_file"):
        return ProfileData.from_file
    if hasattr(ProfileData, "from_serialized_xspace"):
        def load(path):
            with open(path, "rb") as f:
                return ProfileData.from_serialized_xspace(f.read())
        return load
    raise ProfileDataUnavailableError(
        "jax.profiler.ProfileData exposes neither from_file nor "
        f"from_serialized_xspace ({versions}); this jax's reader API has "
        f"drifted past the shim — jax>={PROFILE_DATA_MIN_JAX} with either "
        "constructor is required for the native path")


install()
