"""Version shims over the installed jax.

The codebase targets the current jax spellings `jax.shard_map(...,
check_vma=)` and `jax.lax.axis_size(name)`. Older installs (<=0.4.x) only
ship `jax.experimental.shard_map.shard_map(..., check_rep=)` — same
semantics, pre-rename — and spell the axis size as `lax.psum(1, name)`
(which constant-folds to a python int inside a manual region). Rather than
sprinkling try/except at every call site (manual collectives, gpt_spmd,
ring attention, pipeline compile, graft entry), install adapters under the
modern names when they are missing. Idempotent; a no-op on jax versions
that already expose them.
"""
import jax


def install():
    if not hasattr(jax, "shard_map"):
        from jax.experimental.shard_map import shard_map as _exp_shard_map

        def shard_map(f, *args, **kwargs):
            if "check_vma" in kwargs:
                kwargs["check_rep"] = kwargs.pop("check_vma")
            return _exp_shard_map(f, *args, **kwargs)

        jax.shard_map = shard_map

    if not hasattr(jax.lax, "axis_size"):
        def axis_size(axis_name):
            return jax.lax.psum(1, axis_name)

        jax.lax.axis_size = axis_size


install()
