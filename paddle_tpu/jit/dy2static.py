"""dygraph-to-static transpiler: compile *unmodified* Paddle-style Python —
including tensor-dependent ``if`` / ``while`` / ``for`` / ``break`` /
``continue`` / ``and`` / ``or`` / ``not`` — into one traceable program.

Reference pipeline (30 AST files):
  fluid/dygraph/dygraph_to_static/program_translator.py:1001 (StaticFunction
  entry), ifelse_transformer.py (hoists branch-assigned names into true/false
  functions), loop_transformer.py (loop-carried name analysis -> while_loop),
  break_continue_transformer.py (break/continue -> flag variables + guards),
  logical_transformer.py (and/or/not -> convert_logical_*),
  convert_operators.py (runtime convert_ifelse/convert_while_loop helpers that
  pick the dygraph or static path per call), convert_call_func.py
  (recursively transform callees).

TPU-native design: same two-phase shape, radically smaller target. The AST
pass only needs to (1) hoist branch/loop-assigned locals into pure functions
and (2) route control flow through runtime helpers; the helpers then decide
per call: concrete (python) values keep plain eager Python semantics, traced
values lower to ``lax.cond`` / ``lax.while_loop`` / ``lax.scan`` — XLA is the
"static program", no ProgramDesc/op-by-op construction tier is needed.
"""
import ast
import functools
import inspect
import textwrap
import types
import warnings
import weakref

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = [
    "Dy2StaticError", "convert_to_static", "convert_call",
    "convert_ifelse", "convert_while", "convert_for", "convert_logical_and",
    "convert_logical_or", "convert_logical_not", "convert_list_append",
    "maybe_range", "assert_not_traced", "ld",
]


class Dy2StaticError(RuntimeError):
    """Raised when tensor-dependent control flow cannot be lowered; the
    message names the offending construct (reference: dy2static/error.py)."""


# --------------------------------------------------------------------------
# undefined-variable sentinel
# --------------------------------------------------------------------------
class _Undefined:
    """Placeholder for a local that is not yet bound when a tensor-dependent
    construct starts (reference: variable_trans_func.py create_undefined_var).
    Registered as an EMPTY pytree node so it can ride through lax.cond /
    while_loop carries; any use raises with the variable story intact."""
    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "<undefined>"

    def _die(self, *a, **k):
        raise Dy2StaticError(
            "a local variable was read before assignment inside "
            "tensor-dependent control flow (it is only assigned on one "
            "branch/path); assign it a value before the if/loop")

    __bool__ = __add__ = __radd__ = __sub__ = __rsub__ = __mul__ = _die
    __rmul__ = __truediv__ = __getitem__ = __call__ = __iter__ = _die
    __neg__ = __lt__ = __le__ = __gt__ = __ge__ = _die
    # eq/ne/hash must die too: object defaults would let `x == y` silently
    # return an identity bool (and `x in {...}` hash) instead of the curated
    # read-before-assignment error
    __eq__ = __ne__ = __hash__ = _die


UNDEF = _Undefined()
jax.tree_util.register_pytree_node(
    _Undefined, lambda u: ((), None), lambda aux, ch: UNDEF)


def ld(name, lcls):
    """Load ``name`` from a locals() snapshot, or the undefined sentinel."""
    return lcls.get(name, UNDEF)


# --------------------------------------------------------------------------
# small runtime utilities
# --------------------------------------------------------------------------
def _raw(x):
    return x._data if isinstance(x, Tensor) else x


def _is_tracer(x):
    return isinstance(_raw(x), jax.core.Tracer)


def _unwrap_tree(tree):
    return jax.tree.map(_raw, tree, is_leaf=lambda x: isinstance(x, Tensor))


def _wrap_like(new, old):
    """Re-wrap jax arrays as Tensor where the original value was a Tensor
    OR where tracing promoted a python scalar to an array."""
    def one(n, o):
        if isinstance(o, Tensor):
            return Tensor(n)
        if isinstance(n, jax.Array) and not isinstance(o, jax.Array):
            return Tensor(n)
        return n
    return jax.tree.map(one, new, old,
                        is_leaf=lambda x: isinstance(x, (Tensor, _Undefined)))


def _tree_has_tracer(tree):
    return any(isinstance(l, jax.core.Tracer)
               for l in jax.tree.leaves(_unwrap_tree(tree)))


def _scalar_bool(x):
    r = _raw(x)
    if isinstance(r, (jax.Array, np.ndarray, np.generic)):
        return bool(np.asarray(r).reshape(()))
    return bool(r)   # python values (lists, dicts, None, ...): plain truth


def assert_not_traced(value, construct):
    """Guard for constructs the transpiler leaves as plain Python: fine
    eagerly, a clear error under trace (reference: error.py suggestions)."""
    if _is_tracer(value):
        raise Dy2StaticError(
            f"dy2static: {construct} depends on a traced tensor and cannot "
            f"be lowered to XLA control flow; restructure the code (e.g. "
            f"move the 'return' out of the branch/loop) or use "
            f"paddle.static.nn.cond / while_loop directly")
    return value


# --------------------------------------------------------------------------
# early-return support: the generated flag/value slot names, plus the UNDEF
# materialization that lets the value slot ride an XLA carry before any
# return has executed (reference: return_transformer.py's RETURN_NO_VALUE
# placeholder — here the placeholder adopts the real return value's aval,
# discovered by abstract evaluation, so carries stay shape-stable)
# --------------------------------------------------------------------------
_RET_FLAG = "__dy2s_ret0"
_RET_VALUE = "__dy2s_rv0"


def _friendly(names):
    """Generated return-slot names -> readable tags in error messages."""
    return ["<return value>" if n == _RET_VALUE else
            "<return flag>" if n == _RET_FLAG else n for n in names]


def _materialize_rv(names, vals, probe_fns):
    """For each generated return-value slot still UNDEF on entry to a
    tensor-dependent construct, abstractly evaluate the arms/body to find
    the aval the slot gets on the returning path and substitute zeros of
    that aval. Sound ONLY for the generated slot: every read is guarded by
    the return flag, so the placeholder is unobservable — user locals keep
    the curated read-before-assignment error instead."""
    vals = list(vals)
    idxs = [i for i, n in enumerate(names)
            if n == _RET_VALUE and isinstance(vals[i], _Undefined)]
    if not idxs:
        return vals
    for fn in probe_fns:
        def probe(ops):
            out = fn(*_wrap_like(list(ops), vals))
            return _unwrap_tree(list(out))
        try:
            outs = jax.eval_shape(probe, _unwrap_tree(list(vals)))
        except Exception:                                    # noqa: BLE001
            continue          # the real lowering will name the problem
        for i in list(idxs):
            o = outs[i] if i < len(outs) else None
            if o is not None and not isinstance(o, _Undefined) \
                    and hasattr(o, "shape"):
                vals[i] = Tensor(jnp.zeros(o.shape, o.dtype))
                idxs.remove(i)
        if not idxs:
            break
    return vals


# depth counter: >0 exactly while a loop body/cond is being traced for
# lax.while_loop / fori_loop / scan (single-threaded: tracing is)
_lax_loop_depth = 0


class _lax_loop_scope:
    def __enter__(self):
        global _lax_loop_depth
        _lax_loop_depth += 1

    def __exit__(self, *exc):
        global _lax_loop_depth
        _lax_loop_depth -= 1
        return False


def convert_list_append(seq, item):
    """`x.append(item)` rewritten by the transpiler. A python list cannot
    ride an XLA loop carry (its length is structure, not data), so an
    append reached while a tensor-dependent loop is being lowered gets the
    curated error; everywhere else — eager code, unrolled concrete-bound
    loops — it is a plain append."""
    if isinstance(seq, list):
        if _lax_loop_depth > 0:
            raise Dy2StaticError(
                "dy2static: list mutation (list.append) inside a "
                "tensor-dependent loop cannot be lowered to XLA control "
                "flow — a loop carry needs a fixed structure, and appending "
                "changes the list's length every iteration. Preallocate a "
                "tensor and index-assign into it, or collect values with "
                "paddle.concat/stack outside the loop")
        return seq.append(item)
    # custom objects: .append is an ordinary method call — keep the
    # recursive convert_call treatment the generic rewrite would have given
    return convert_call(seq.append)(item)


# --------------------------------------------------------------------------
# runtime converters (reference: dy2static/convert_operators.py)
# --------------------------------------------------------------------------
def convert_ifelse(pred, true_fn, false_fn, names, vals):
    """``if pred: ...`` where both arms assign ``names``.

    Concrete pred -> run the chosen arm as plain Python. Traced pred ->
    lax.cond over the carried locals (reference convert_ifelse builds a
    ConditionalBlock; here both arms are traced by lax.cond itself)."""
    if not _is_tracer(pred):
        fn = true_fn if _scalar_bool(pred) else false_fn
        return fn(*vals)

    vals = _materialize_rv(names, vals, (true_fn, false_fn))
    operands = _unwrap_tree(list(vals))

    def arm(fn):
        def inner(ops):
            out = fn(*_wrap_like(ops, list(vals)))
            return _unwrap_tree(list(out))
        return inner

    try:
        outs = jax.lax.cond(jnp.reshape(_raw(pred), ()),
                            arm(true_fn), arm(false_fn), operands)
    except TypeError as e:
        raise Dy2StaticError(
            f"dy2static: the two branches of a tensor-dependent 'if' "
            f"produced mismatched values for locals {_friendly(names)} "
            f"(each branch must leave every assigned local with the same "
            f"shape/dtype; a local assigned on only one branch stays "
            f"<undefined> on the other): {e}") from None
    vals_l = list(vals)
    if len(outs) == len(vals_l):
        return tuple(_wrap_like(outs, vals_l))
    # value-select form (both branches `return expr`): no carried locals
    return tuple(Tensor(o) if isinstance(o, jax.Array) else o for o in outs)


def convert_while(cond_fn, body_fn, names, vals):
    """``while cond: body`` over loop-carried locals ``names``.

    Concrete cond every iteration -> plain Python loop (correct dygraph
    semantics, unrolled under trace only if the carry stays concrete).
    The first traced cond switches the remaining iterations to
    lax.while_loop (reference convert_while_loop)."""
    vals = list(vals)
    while True:
        c = cond_fn(*vals)
        if _is_tracer(c):
            return _lax_while(cond_fn, body_fn, names, vals)
        if not _scalar_bool(c):
            return tuple(vals)
        vals = list(body_fn(*vals))


def _match_carry(out_flat, init_flat, names):
    """Cast body outputs back to the carry avals (weak-type / dtype drift);
    shape drift is a real error, named."""
    res = []
    for o, i in zip(out_flat, init_flat):
        if isinstance(i, _Undefined) or isinstance(o, _Undefined):
            res.append(o)
            continue
        o = jnp.asarray(o)
        i = jnp.asarray(i)
        if o.shape != i.shape:
            raise Dy2StaticError(
                f"dy2static: a loop-carried local changes shape across "
                f"iterations ({i.shape} -> {o.shape}); XLA loops need "
                f"fixed shapes. Carried locals: {list(names)}")
        res.append(jax.lax.convert_element_type(o, i.dtype))
    return res


def _dtype_fixpoint(raw_body, init):
    """Promote carry dtypes to the fixed point of the body's output dtypes:
    eager Python promotes on the first iteration (int accumulator + float ->
    float), but an XLA carry can't change dtype mid-loop, so promote the
    initial values up front instead of silently truncating."""
    for _ in range(4):
        try:
            outs = jax.eval_shape(raw_body, tuple(init))
        except Exception:
            return init   # structural problems surface via the real lowering
        changed = False
        nxt = []
        for o, i in zip(outs, init):
            if isinstance(i, _Undefined) or isinstance(o, _Undefined):
                nxt.append(i)
                continue
            pd = jnp.promote_types(o.dtype, i.dtype)
            if pd != i.dtype:
                i = jax.lax.convert_element_type(i, pd)
                changed = True
            nxt.append(i)
        init = nxt
        if not changed:
            break
    return init


def _lax_while(cond_fn, body_fn, names, vals):
    with _lax_loop_scope():
        vals = _materialize_rv(names, vals, (body_fn,))
        init = [jnp.asarray(d) if not isinstance(d, _Undefined) else d
                for d in _unwrap_tree(vals)]
        # strip weak types so body outputs can be cast to a stable aval
        init = [jax.lax.convert_element_type(d, d.dtype)
                if not isinstance(d, _Undefined) else d for d in init]
        init = _dtype_fixpoint(
            lambda carry: tuple(_unwrap_tree(list(
                body_fn(*_wrap_like(list(carry), vals))))), init)

        def c(carry):
            out = cond_fn(*_wrap_like(list(carry), vals))
            return jnp.reshape(_raw(out), ())

        def b(carry):
            out = body_fn(*_wrap_like(list(carry), vals))
            return tuple(_match_carry(_unwrap_tree(list(out)), carry, names))

        try:
            final = jax.lax.while_loop(c, b, tuple(init))
        except TypeError as e:
            raise Dy2StaticError(
                f"dy2static: tensor-dependent 'while' could not be lowered "
                f"(carried locals {_friendly(names)} must keep a fixed "
                f"shape/dtype/structure across iterations): {e}") from None
    return tuple(_wrap_like(list(final), vals))


class _TracedRange:
    """range() whose bounds include traced scalars (reference: the loop
    transformer turns ``for i in range(n)`` into a while over an index)."""

    def __init__(self, *args):
        a = [_raw(x) for x in args]
        if len(a) == 1:
            self.start, self.stop, self.step = 0, a[0], 1
        elif len(a) == 2:
            self.start, self.stop, self.step = a[0], a[1], 1
        else:
            self.start, self.stop, self.step = a


def maybe_range(*args):
    if any(_is_tracer(x) or isinstance(x, Tensor) for x in args):
        return _TracedRange(*args)
    return range(*(int(_raw(x)) for x in args))


def convert_for(iterable, body_fn, names, vals, tgt0=UNDEF):
    """``for tgt in iterable: body``. body_fn(tgt, *carry) -> (tgt, *carry)
    — the body returns the target's FINAL binding too, because python leaks
    the target (including body reassignments of it) into the enclosing
    scope. Returns ``(tgt_last, *carry)``; tgt0 = the target's pre-loop
    value, leaked back on zero iterations.

    python iterable -> eager loop; _TracedRange -> lax.fori_loop;
    traced/concrete-under-trace Tensor -> lax.scan over the leading axis."""
    vals = tuple(vals)

    def split(out):
        out = list(out)
        return out[0], tuple(out[1:])

    if isinstance(iterable, _TracedRange):
        r = iterable
        with _lax_loop_scope():
            n = jnp.maximum(0, -(-(jnp.asarray(r.stop) - r.start) // r.step))
            vals = tuple(_materialize_rv(
                names, list(vals),
                (lambda *c: list(body_fn(Tensor(jnp.asarray(r.start)),
                                         *c))[1:],)))
            init = tuple(_match_carry(_unwrap_tree(list(vals)),
                                      _unwrap_tree(list(vals)), names))
            init = tuple(_dtype_fixpoint(
                lambda carry: tuple(_unwrap_tree(list(body_fn(
                    Tensor(jnp.asarray(r.start)),
                    *_wrap_like(list(carry), list(vals)))))[1:]), list(init)))
            # target slot rides the carry so body reassignments of it leak;
            # zero-trip edge leaks `start` (documented divergence from
            # python's keep-old-value, which an XLA carry cannot express)
            t0 = jnp.asarray(r.start)

            def b(k, carry):
                tslot, rest = carry[0], carry[1:]
                i = jnp.asarray(r.start) + k * jnp.asarray(r.step)
                out = body_fn(Tensor(i), *_wrap_like(list(rest), list(vals)))
                tlast, crest = split(_unwrap_tree(list(out)))
                return (jax.lax.convert_element_type(jnp.asarray(tlast),
                                                     tslot.dtype),) + \
                    tuple(_match_carry(list(crest), rest, names))

            try:
                final = jax.lax.fori_loop(0, n, b, (t0,) + init)
            except TypeError as e:
                raise Dy2StaticError(
                    f"dy2static: tensor-dependent 'for' over range could "
                    f"not be lowered (carried locals {_friendly(names)} "
                    f"must keep a fixed shape/dtype/structure across "
                    f"iterations): {e}") from None
        return (Tensor(final[0]),) + tuple(
            _wrap_like(list(final[1:]), list(vals)))

    if isinstance(iterable, Tensor) and (
            _is_tracer(iterable) or _tree_has_tracer(vals)):
        xs = _raw(iterable)
        if xs.ndim == 0:
            raise Dy2StaticError(
                "dy2static: cannot iterate a 0-d tensor in a traced 'for'")
        with _lax_loop_scope():
            vals = tuple(_materialize_rv(
                names, list(vals),
                (lambda *c: list(body_fn(Tensor(xs[0]), *c))[1:],)))
            init = tuple(_match_carry(_unwrap_tree(list(vals)),
                                      _unwrap_tree(list(vals)), names))
            init = tuple(_dtype_fixpoint(
                lambda carry: tuple(_unwrap_tree(list(body_fn(
                    Tensor(xs[0]),
                    *_wrap_like(list(carry), list(vals)))))[1:]),
                list(init)))

            def step(carry, row):
                out = body_fn(Tensor(row),
                              *_wrap_like(list(carry), list(vals)))
                tlast, crest = split(_unwrap_tree(list(out)))
                return tuple(_match_carry(list(crest), carry, names)), tlast

            try:
                final, t_hist = jax.lax.scan(step, init, xs)
            except TypeError as e:
                raise Dy2StaticError(
                    f"dy2static: tensor-dependent 'for' over a tensor could "
                    f"not be lowered (carried locals {_friendly(names)} "
                    f"must keep a fixed shape/dtype/structure across "
                    f"iterations): {e}") from None
        last = Tensor(jax.tree.map(lambda h: h[-1], t_hist)) \
            if xs.shape[0] else tgt0
        return (last,) + tuple(_wrap_like(list(final), list(vals)))

    if isinstance(iterable, Tensor):
        it = [Tensor(row) for row in _raw(iterable)]
    else:
        it = iterable
    try:
        iter(it)
    except TypeError:
        raise Dy2StaticError(
            f"dy2static: cannot iterate object of type "
            f"{type(iterable).__name__} in a converted 'for' loop") from None
    tgt = tgt0
    for item in it:
        tgt, vals = split(body_fn(item, *vals))
    return (tgt,) + vals


def convert_logical_and(lhs_fn, rhs_fn):
    """``a and b`` preserving short-circuit for concrete values
    (reference: logical_transformer.py -> convert_logical_and)."""
    a = lhs_fn()
    if not _is_tracer(a):
        return rhs_fn() if _scalar_bool(a) else a
    b = rhs_fn()
    return Tensor(jnp.logical_and(jnp.reshape(_raw(a), ()),
                                  jnp.reshape(_raw(b), ())))


def convert_logical_or(lhs_fn, rhs_fn):
    a = lhs_fn()
    if not _is_tracer(a):
        return a if _scalar_bool(a) else rhs_fn()
    b = rhs_fn()
    return Tensor(jnp.logical_or(jnp.reshape(_raw(a), ()),
                                 jnp.reshape(_raw(b), ())))


def convert_logical_not(x):
    if not _is_tracer(x):
        return not _scalar_bool(x)
    return Tensor(jnp.logical_not(jnp.reshape(_raw(x), ())))


# --------------------------------------------------------------------------
# convert_call: recursively transform user callees
# (reference: convert_call_func.py convert_call)
# --------------------------------------------------------------------------
_SKIP_MODULE_PREFIXES = ("jax", "numpy", "paddle_tpu", "builtins", "math",
                         "functools", "itertools", "operator", "np")
# weak keys: per-call inner functions / temporary Layers must not be pinned
# alive by the cache (reference convert_call_func keeps a module-level dict;
# traces are jit-cached so a missed cache entry only costs at trace time).
# A weak entry only works if the VALUE doesn't reference the key, so
# passthrough results are stored as a sentinel and transformed functions
# drop their functools.wraps __wrapped__ back-reference.
_call_cache = weakref.WeakKeyDictionary()
_PASSTHROUGH = object()


def convert_call(f):
    """Return a dy2static-transformed version of a user function so that
    tensor-dependent control flow inside *callees* also lowers; framework,
    numpy and jax callables pass through untouched."""
    try:
        key = f.__func__ if inspect.ismethod(f) else f
        try:
            out = _call_cache[key]
        except (KeyError, TypeError):
            out = _transform_or_passthrough(key)
            try:
                _call_cache[key] = _PASSTHROUGH if out is key else out
            except TypeError:
                pass   # unhashable/unweakrefable: skip caching
        if out is _PASSTHROUGH:
            out = key
        if inspect.ismethod(f):
            return functools.partial(out, f.__self__) if out is not key else f
        return out
    except Exception:
        return f


def _transform_or_passthrough(f):
    if not isinstance(f, types.FunctionType):
        return f
    if getattr(f, "__dy2static_transformed__", False):
        return f
    mod = getattr(f, "__module__", "") or ""
    if mod.split(".")[0] in _SKIP_MODULE_PREFIXES:
        return f
    try:
        return convert_to_static(f)
    except Exception:
        return f


# --------------------------------------------------------------------------
# AST analysis helpers
# --------------------------------------------------------------------------
def _collect_stores(nodes):
    """Names bound (simple Name targets) anywhere in the statement list —
    the loop-carry / branch-output set (reference: loop_transformer.py
    NameVisitor get_loop_var_names)."""
    out = []

    class V(ast.NodeVisitor):
        def visit_Name(self, node):
            if isinstance(node.ctx, (ast.Store, ast.Del)) \
                    and node.id not in out:
                out.append(node.id)

        def visit_Subscript(self, node):
            # x[i] = v rebinds x's storage: carry the BASE name so the
            # functional update stays inside the lax arm/loop
            if isinstance(node.ctx, ast.Store):
                base = node.value
                while isinstance(base, (ast.Subscript, ast.Attribute)):
                    base = base.value
                if isinstance(base, ast.Name) and base.id not in out:
                    out.append(base.id)
            self.generic_visit(node)

        def visit_FunctionDef(self, node):
            # own scope; function values can't ride XLA carries, so inner
            # defs are recreated in place rather than carried
            pass

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, node):
            pass

    for n in nodes:
        V().visit(n)
    return out


def _has_attr_store(nodes):
    """Object-attribute assignment (self.x = v) inside a tensor-dependent
    construct can't ride an XLA carry; detect it so the construct stays
    Python with a clear traced-guard instead of leaking a tracer."""
    class V(ast.NodeVisitor):
        found = False

        def visit_Attribute(self, node):
            if isinstance(node.ctx, ast.Store):
                self.found = True
            self.generic_visit(node)

        def visit_FunctionDef(self, node):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, node):
            pass

    v = V()
    for n in nodes:
        v.visit(n)
    return v.found


def _has(nodes, *kinds):
    """Like ast.walk-any, but does NOT descend into nested function/lambda
    scopes (generated __dy2s_* defs contain their own Returns)."""
    stack = list(nodes)
    while stack:
        n = stack.pop()
        if isinstance(n, kinds):
            return True
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))
    return False


def _has_toplevel_loop_escape(body):
    """True if `body` contains Return/Break/Continue not nested inside a
    deeper loop (for break/continue) — i.e. escapes *this* construct."""
    class V(ast.NodeVisitor):
        found = False

        def visit_Return(self, node):
            self.found = True

        def visit_For(self, node):
            for s in ast.walk(node):
                if isinstance(s, ast.Return):
                    self.found = True

        visit_While = visit_For

        def visit_FunctionDef(self, node):
            pass

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Lambda(self, node):
            pass

    v = V()
    for n in body:
        v.visit(n)
    return v.found


def _name(id_, ctx=None):
    return ast.Name(id=id_, ctx=ctx or ast.Load())


_JST = "__dy2s_jst__"   # injected helper-module name; must not collide


def _jst(attr, *args):
    return ast.Call(
        func=ast.Attribute(value=_name(_JST), attr=attr, ctx=ast.Load()),
        args=list(args), keywords=[])


def _ld_call(n):
    return _jst("ld", ast.Constant(n),
                ast.Call(func=_name("locals"), args=[], keywords=[]))


def _const_tuple(names):
    return ast.Tuple(elts=[ast.Constant(n) for n in names], ctx=ast.Load())


def _lambda0(expr):
    return ast.Lambda(
        args=ast.arguments(posonlyargs=[], args=[], vararg=None,
                           kwonlyargs=[], kw_defaults=[], kwarg=None,
                           defaults=[]),
        body=expr)


def _fn_def(name, params, body, returns_names):
    body = list(body)
    body.append(ast.Return(value=ast.Tuple(
        elts=[_name(n) for n in returns_names], ctx=ast.Load())))
    node = ast.FunctionDef(
        name=name,
        args=ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=p) for p in params],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[]),
        body=body, decorator_list=[], returns=None)
    node.type_params = []   # py3.12+ ast requires the field
    return node


def _sets_flag(nodes, brk, cont):
    for n in nodes:
        for sub in ast.walk(n):
            if isinstance(sub, ast.Assign):
                for t in sub.targets:
                    if isinstance(t, ast.Name) and t.id in (brk, cont):
                        return True
    return False


# --------------------------------------------------------------------------
# pass 0: early returns -> return flag + value slot
# (reference: return_transformer.py / early_return_transformer.py)
# --------------------------------------------------------------------------
class _EarlyReturnLowering:
    """``return expr`` inside an if/loop becomes ``__dy2s_rv0 = expr;
    __dy2s_ret0 = True`` (plus ``break`` inside loops, which pass 1 then
    lowers through the existing flag machinery); statements that may run
    after a conditional return are guarded by the flag, and the function
    ends with one ``return __dy2s_rv0``. Statically-dead continuations
    (both if-arms return) are dropped so the tracer never has to select
    between a return value and nothing."""

    def transform(self, body):
        if not self._has_construct_return(body):
            return body
        body = list(body)
        if not body or not isinstance(body[-1], ast.Return):
            body.append(ast.Return(value=ast.Constant(None)))
        out, _may, _always = self._block(body, in_loop=False)
        return ([self._assign(_RET_FLAG, ast.Constant(False))] + out +
                [ast.Return(value=_name(_RET_VALUE))])

    @staticmethod
    def _has_construct_return(body):
        return any(isinstance(s, (ast.If, ast.For, ast.While))
                   and _has([s], ast.Return) for s in body)

    @staticmethod
    def _assign(name, value):
        return ast.Assign(targets=[_name(name, ast.Store())], value=value)

    def _block(self, stmts, in_loop):
        """Returns (new_stmts, may_return, always_returns)."""
        out = []
        for i, s in enumerate(stmts):
            if isinstance(s, ast.Return):
                out.append(self._assign(_RET_VALUE,
                                        s.value or ast.Constant(None)))
                out.append(self._assign(_RET_FLAG, ast.Constant(True)))
                if in_loop:
                    out.append(ast.Break())
                return out, True, True      # rest is unreachable
            if isinstance(s, ast.If) and _has([s], ast.Return):
                nb, m1, a1 = self._block(s.body, in_loop)
                no, m2, a2 = (self._block(s.orelse, in_loop)
                              if s.orelse else ([], False, False))
                out.append(ast.If(test=s.test, body=nb, orelse=no))
                if a1 and a2:
                    return out, True, True  # every path returned
                if m1 or m2:
                    return self._guard_rest(out, stmts[i + 1:], in_loop)
                continue
            if isinstance(s, (ast.For, ast.While)) and _has([s], ast.Return):
                nb, _m, _a = self._block(s.body, True)
                if isinstance(s, ast.While):
                    out.append(ast.While(test=s.test, body=nb,
                                         orelse=s.orelse))
                else:
                    out.append(ast.For(target=s.target, iter=s.iter,
                                       body=nb, orelse=s.orelse))
                return self._guard_rest(out, stmts[i + 1:], in_loop)
            out.append(s)
        return out, False, False

    def _guard_rest(self, out, rest_stmts, in_loop):
        """After a construct that may have returned: inside a loop, break
        out (pass 1 turns it into the carry flag); at function level, run
        the continuation only when the flag is still False."""
        rest, _mr, ar = (self._block(rest_stmts, in_loop)
                         if rest_stmts else ([], False, False))
        if in_loop:
            out.append(ast.If(test=_name(_RET_FLAG), body=[ast.Break()],
                              orelse=rest))
        else:
            out.append(ast.If(
                test=ast.UnaryOp(op=ast.Not(), operand=_name(_RET_FLAG)),
                body=rest or [ast.Pass()], orelse=[]))
        return out, True, ar


# --------------------------------------------------------------------------
# pass 1: break/continue -> flag variables + guards
# (reference: break_continue_transformer.py)
# --------------------------------------------------------------------------
class _BreakContinueLowering(ast.NodeTransformer):
    """Within each loop body: ``break`` -> ``__brk_i = True``, ``continue``
    -> ``__cont_i = True``; every statement after a flag-setting statement is
    guarded by ``if not (__brk_i or __cont_i):``; the loop condition gains
    ``and not __brk_i``. The guards are ordinary ifs, which pass 2 then
    lowers when the flags are tensors."""

    def __init__(self):
        self._uid = 0

    def _lower_body(self, body, brk, cont):
        """Rewrite one loop body's statement list with flag guards."""
        def rewrite(stmts):
            out = []
            for i, s in enumerate(stmts):
                s2, sets_flag = self._rewrite_stmt(s, brk, cont)
                out.append(s2)
                if sets_flag and i + 1 < len(stmts):
                    rest = rewrite(stmts[i + 1:])
                    guard = ast.UnaryOp(
                        op=ast.Not(),
                        operand=ast.BoolOp(op=ast.Or(), values=[
                            _name(brk), _name(cont)]))
                    out.append(ast.If(test=guard, body=rest, orelse=[]))
                    break
            return out
        return rewrite(body)

    def _rewrite_stmt(self, s, brk, cont):
        """Returns (new_stmt, may_set_flag). Descends into If statements
        (whose branches may break/continue) but NOT into nested loops —
        those get their own flags via generic visitation later."""
        if isinstance(s, ast.Break):
            return ast.Assign(targets=[_name(brk, ast.Store())],
                              value=ast.Constant(True)), True
        if isinstance(s, ast.Continue):
            return ast.Assign(targets=[_name(cont, ast.Store())],
                              value=ast.Constant(True)), True
        if isinstance(s, ast.If):
            nb = self._lower_body(s.body, brk, cont)
            no = self._lower_body(s.orelse, brk, cont)
            return ast.If(test=s.test, body=nb, orelse=no or []), \
                _sets_flag(nb + no, brk, cont)
        return s, False

    def _transform_loop(self, node):
        self.generic_visit(node)   # inner loops first
        direct = self._direct_break_continue(node.body)
        if not direct:
            return node
        self._uid += 1
        brk = f"__dy2s_brk_{self._uid}"
        cont = f"__dy2s_cont_{self._uid}"
        new_body = [ast.Assign(targets=[_name(cont, ast.Store())],
                               value=ast.Constant(False))]
        new_body += self._lower_body(node.body, brk, cont)
        # both flags init'd BEFORE the loop too: they ride the XLA loop
        # carry, which needs a defined value at entry
        init = [ast.Assign(targets=[_name(brk, ast.Store())],
                           value=ast.Constant(False)),
                ast.Assign(targets=[_name(cont, ast.Store())],
                           value=ast.Constant(False))]
        # python for/while-else runs iff the loop did NOT break: hoist the
        # else body behind a flag guard so the semantics survive lowering
        tail = []
        orelse = node.orelse
        if orelse:
            tail = [ast.If(test=ast.UnaryOp(op=ast.Not(),
                                            operand=_name(brk)),
                           body=orelse, orelse=[])]
            orelse = []
        if isinstance(node, ast.While):
            new_test = ast.BoolOp(op=ast.And(), values=[
                ast.UnaryOp(op=ast.Not(), operand=_name(brk)), node.test])
            loop = ast.While(test=new_test, body=new_body, orelse=orelse)
            return init + [loop] + tail
        # For: wrap the body so iterations after break are no-ops
        guarded = [ast.If(
            test=ast.UnaryOp(op=ast.Not(), operand=_name(brk)),
            body=new_body, orelse=[])]
        loop = ast.For(target=node.target, iter=node.iter, body=guarded,
                       orelse=orelse)
        return init + [loop] + tail

    def _direct_break_continue(self, body):
        """break/continue belonging to THIS loop (not a nested one)."""
        class V(ast.NodeVisitor):
            found = False

            def visit_Break(self, n):
                self.found = True

            def visit_Continue(self, n):
                self.found = True

            def visit_For(self, n):
                pass

            def visit_While(self, n):
                pass

            def visit_FunctionDef(self, n):
                pass

            visit_AsyncFunctionDef = visit_FunctionDef

        v = V()
        for s in body:
            v.visit(s)
        return v.found

    def visit_While(self, node):
        return self._transform_loop(node)

    def visit_For(self, node):
        return self._transform_loop(node)


# --------------------------------------------------------------------------
# pass 2: control flow -> runtime converter calls
# (reference: ifelse_transformer.py / loop_transformer.py /
#  logical_transformer.py / call_transformer.py)
# --------------------------------------------------------------------------
class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self._uid = 0

    def _uid_next(self):
        self._uid += 1
        return self._uid

    # ---- logical operators ------------------------------------------------
    def visit_BoolOp(self, node):
        self.generic_visit(node)
        attr = "convert_logical_and" if isinstance(node.op, ast.And) \
            else "convert_logical_or"
        expr = node.values[-1]
        for v in reversed(node.values[:-1]):
            expr = _jst(attr, _lambda0(v), _lambda0(expr))
        return expr

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return _jst("convert_logical_not", node.operand)
        return node

    # ---- calls ------------------------------------------------------------
    def visit_Call(self, node):
        self.generic_visit(node)
        f = node.func
        if isinstance(f, ast.Name) and f.id in ("locals", "globals", "super",
                                                "range", "print", "len",
                                                "isinstance", "enumerate",
                                                "zip"):
            return node
        if isinstance(f, ast.Attribute) and f.attr == "append" \
                and len(node.args) == 1 and not node.keywords:
            # route through the list-mutation guard: curated error when a
            # python list is appended inside a lax-lowered loop body
            return _jst("convert_list_append", f.value, node.args[0])
        node.func = _jst("convert_call", f)
        return node

    # ---- if ---------------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        if _has_toplevel_loop_escape(node.body) or \
                _has_toplevel_loop_escape(node.orelse):
            return self._if_with_return(node)
        if _has_attr_store(node.body + node.orelse):
            node.test = _jst("assert_not_traced", node.test,
                             ast.Constant("an 'if' whose branch assigns an "
                                          "object attribute"))
            return node
        uid = self._uid_next()
        assigned = _collect_stores(node.body + node.orelse)
        if not assigned:
            # pure side-effect-free branches still need lowering under
            # trace; carry nothing, return nothing
            assigned = []
        tf = _fn_def(f"__dy2s_tf_{uid}", assigned, node.body, assigned)
        ff = _fn_def(f"__dy2s_ff_{uid}", assigned,
                     node.orelse or [ast.Pass()], assigned)
        call = _jst("convert_ifelse", node.test,
                    _name(tf.name), _name(ff.name),
                    _const_tuple(assigned),
                    ast.Tuple(elts=[_ld_call(n) for n in assigned],
                              ctx=ast.Load()))
        if assigned:
            assign = ast.Assign(
                targets=[ast.Tuple(
                    elts=[_name(n, ast.Store()) for n in assigned],
                    ctx=ast.Store())],
                value=call)
        else:
            assign = ast.Expr(value=call)
        return [tf, ff, assign]

    def _if_with_return(self, node):
        """Both arms end in ``return expr`` -> value-select; anything else
        with an escaping return stays Python with a clear traced-guard
        (reference: return_transformer.py handles the general case with
        return-flag lowering; the guard names the restructure)."""
        body, orelse = node.body, node.orelse
        if (len(body) >= 1 and isinstance(body[-1], ast.Return)
                and orelse and isinstance(orelse[-1], ast.Return)
                and not _has(body[:-1] + orelse[:-1], ast.Return)
                and body[-1].value is not None
                and orelse[-1].value is not None):
            uid = self._uid_next()
            tf = _fn_def(f"__dy2s_rtf_{uid}", [], body[:-1], [])
            tf.body[-1] = ast.Return(value=ast.Tuple(
                elts=[body[-1].value], ctx=ast.Load()))
            ff = _fn_def(f"__dy2s_rff_{uid}", [], orelse[:-1], [])
            ff.body[-1] = ast.Return(value=ast.Tuple(
                elts=[orelse[-1].value], ctx=ast.Load()))
            call = _jst("convert_ifelse", node.test,
                        _name(tf.name), _name(ff.name),
                        _const_tuple(["<return value>"]),
                        ast.Tuple(elts=[], ctx=ast.Load()))
            ret = ast.Return(value=ast.Subscript(
                value=call, slice=ast.Constant(0), ctx=ast.Load()))
            return [tf, ff, ret]
        node.test = _jst("assert_not_traced", node.test,
                         ast.Constant("an 'if' whose branch contains an "
                                      "early 'return'"))
        return node

    # ---- while ------------------------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        if _has_toplevel_loop_escape(node.body) or node.orelse or \
                _has_attr_store(node.body):
            what = "a 'while' with an 'else' clause" if node.orelse else (
                "a 'while' whose body assigns an object attribute"
                if _has_attr_store(node.body)
                else "a 'while' whose body contains 'return'")
            node.test = _jst("assert_not_traced", node.test, ast.Constant(what))
            return node
        uid = self._uid_next()
        carried = _collect_stores(node.body)
        if not carried:
            # nothing carried: a tensor-cond loop that changes no locals is
            # either infinite or dead; keep python semantics with a guard
            node.test = _jst("assert_not_traced", node.test,
                             ast.Constant("a 'while' that assigns no locals"))
            return node
        cf = _fn_def(f"__dy2s_wc_{uid}", carried, [], [])
        cf.body = [ast.Return(value=node.test)]
        bf = _fn_def(f"__dy2s_wb_{uid}", carried, node.body, carried)
        call = _jst("convert_while", _name(cf.name), _name(bf.name),
                    _const_tuple(carried),
                    ast.Tuple(elts=[_ld_call(n) for n in carried],
                              ctx=ast.Load()))
        assign = ast.Assign(
            targets=[ast.Tuple(
                elts=[_name(n, ast.Store()) for n in carried],
                ctx=ast.Store())],
            value=call)
        return [cf, bf, assign]

    # ---- for --------------------------------------------------------------
    def visit_For(self, node):
        self.generic_visit(node)
        if _has_toplevel_loop_escape(node.body) or node.orelse or \
                _has_attr_store(node.body):
            what = "a 'for' with an 'else' clause" if node.orelse else (
                "a 'for' whose body assigns an object attribute"
                if _has_attr_store(node.body)
                else "a 'for' whose body contains 'return'")
            node.iter = _jst("assert_not_traced", node.iter, ast.Constant(what))
            return node
        uid = self._uid_next()
        carried = _collect_stores(node.body)
        # the loop target is rebound each iteration, not carried
        tgt_names = _collect_stores(
            [ast.Assign(targets=[node.target], value=ast.Constant(0))])
        carried = [n for n in carried if n not in tgt_names]
        it = node.iter
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                and it.func.id == "range":
            it = _jst("maybe_range", *it.args)
        # body_fn(target, *carried); the loop target LEAKS into the
        # enclosing scope in python, so convert_for returns (last, *carry)
        # and we rebind it (simple-Name targets; tuple targets discard)
        if isinstance(node.target, ast.Name):
            params = [node.target.id] + carried
            prelude = []
            out_names = [node.target.id]
            tgt0 = _ld_call(node.target.id)
            tgt_ret = node.target.id
        else:
            params = ["__dy2s_item"] + carried
            prelude = [ast.Assign(targets=[node.target],
                                  value=_name("__dy2s_item"))]
            out_names = [f"__dy2s_last_{uid}"]
            tgt0 = ast.Constant(None)
            tgt_ret = "__dy2s_item"
        # body returns (target, *carried): python leaks the target's final
        # binding, including reassignments inside the body
        bf = _fn_def(f"__dy2s_fb_{uid}", params, prelude + node.body,
                     [tgt_ret] + carried)
        call = _jst("convert_for", it, _name(bf.name),
                    _const_tuple(carried),
                    ast.Tuple(elts=[_ld_call(n) for n in carried],
                              ctx=ast.Load()),
                    tgt0)
        assign = ast.Assign(
            targets=[ast.Tuple(
                elts=[_name(n, ast.Store()) for n in out_names + carried],
                ctx=ast.Store())],
            value=call)
        return [bf, assign]


# --------------------------------------------------------------------------
# driver
# --------------------------------------------------------------------------
def convert_to_static(fn):
    """Source -> AST -> (break/continue lowering, control-flow rewrite) ->
    recompiled function. Closure variables are materialized as globals of the
    transformed function (reference: program_translator.py transforms to a
    temp file + exec; same trade-off: closure cells are snapshotted)."""
    if getattr(fn, "__dy2static_transformed__", False):
        return fn
    src = textwrap.dedent(inspect.getsource(fn))
    tree = ast.parse(src)
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        raise Dy2StaticError(f"cannot transform {fn!r}: not a function def")
    # constructs the rewrite cannot preserve -> plain ValueError so
    # maybe_transform falls back to raw tracing with a warning
    for sub in ast.walk(fdef):
        if isinstance(sub, (ast.Yield, ast.YieldFrom, ast.Await)):
            raise ValueError("generator/async function")
        if isinstance(sub, (ast.Global, ast.Nonlocal)):
            raise ValueError("global/nonlocal declaration")
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name) \
                and sub.func.id == "super" and not sub.args:
            raise ValueError("zero-argument super() needs its class cell")
    fdef.decorator_list = []
    fdef.body = _apply_passes(fdef.body)
    fdef.name = fn.__name__ + "__dy2static"
    mod = ast.Module(body=[fdef], type_ignores=[])
    ast.fix_missing_locations(mod)
    code = compile(mod, filename=f"<dy2static {fn.__qualname__}>",
                   mode="exec")
    # chain to the LIVE module globals (late rebinding / monkeypatching of
    # module-level helpers keeps working); only the injected helper module
    # and the closure-cell snapshot live in the overlay
    extra = {_JST: _module()}
    if fn.__closure__:
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                extra[name] = cell.cell_contents
            except ValueError:
                pass
    glb = _ChainGlobals(fn.__globals__, extra)
    ns = {}
    exec(code, glb, ns)
    new = ns[fdef.name]
    new = functools.wraps(fn)(new)
    del new.__wrapped__   # a back-ref to fn would defeat the weak caches
    new.__defaults__ = fn.__defaults__
    new.__kwdefaults__ = fn.__kwdefaults__
    new.__dy2static_transformed__ = True
    return new


def _apply_passes(body):
    body = _EarlyReturnLowering().transform(body)
    holder = ast.Module(body=body, type_ignores=[])
    holder = _BreakContinueLowering().visit(holder)
    holder = _ControlFlowTransformer().visit(holder)
    return holder.body


class _ChainGlobals(dict):
    """exec globals overlay: generated names resolve here, everything else
    falls through to the function's live module globals (CPython honors
    __missing__ on dict subclasses for LOAD_GLOBAL)."""

    def __init__(self, base, extra):
        super().__init__(extra)
        self._base = base

    def __missing__(self, key):
        return self._base[key]


def _module():
    import paddle_tpu.jit.dy2static as m
    return m


# one transform per underlying function object, shared by every Layer
# instance / StaticFunction binding (deepcopied encoder stacks would
# otherwise re-parse the same source N times)
_transform_cache = weakref.WeakKeyDictionary()


def maybe_transform(fn):
    """Best-effort entry used by @to_static: transform when source is
    available; fall back to the raw function (plain tracing) otherwise."""
    from . import ProgramTranslator
    if not ProgramTranslator.enable_to_static:
        return fn
    try:
        out = _transform_cache[fn]
        return fn if out is _PASSTHROUGH else out
    except (KeyError, TypeError):
        pass
    try:
        out = convert_to_static(fn)
    except Dy2StaticError:
        raise
    except Exception as e:  # source unavailable, exotic syntax, ...
        warnings.warn(f"dy2static: falling back to plain tracing for "
                      f"{getattr(fn, '__qualname__', fn)}: {e}")
        out = fn
    try:
        _transform_cache[fn] = _PASSTHROUGH if out is fn else out
    except TypeError:
        pass
    return out
