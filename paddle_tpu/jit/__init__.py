"""paddle.jit equivalent.

Reference: @to_static AST-transform pipeline (fluid/dygraph/dygraph_to_static/
program_translator.py:1001) compiling dygraph code to a ProgramDesc.
TPU-native: @to_static wraps the function with jax.jit over the functionalized
layer — the traced jaxpr/HLO *is* the static program, XLA is the executor.
"""
import functools

import jax

from ..core import random as _rng
from ..core.tensor import Tensor, unwrap, wrap
from ..nn.layer.layers import Layer, functional_call, functional_state


class StaticFunction:
    """A jit-compiled callable over a Layer method or free function."""

    def __init__(self, fn, layer=None, input_spec=None):
        self._fn = fn
        self._layer = layer
        self._input_spec = input_spec
        self._cache = {}

    def __get__(self, instance, owner):
        if instance is None:
            return self
        return StaticFunction(self._fn, layer=instance, input_spec=self._input_spec)

    def _build(self, train):
        layer = self._layer

        if layer is None:
            @functools.partial(jax.jit)
            def compiled(seed, *raw_args):
                with _rng.traced_rng(seed):
                    out = self._fn(*wrap(list(raw_args)))
                return unwrap(out)
            return compiled

        @functools.partial(jax.jit)
        def compiled(params, buffers, seed, *raw_args):
            with _rng.traced_rng(seed):
                out, new_buffers = functional_call(
                    layer, params, buffers,
                    args=tuple(Tensor(a) for a in raw_args),
                    train=train, method=self._fn)
            return unwrap(out), new_buffers
        return compiled

    def __call__(self, *args):
        import jax.random as jrandom
        raw = tuple(a._data if isinstance(a, Tensor) else a for a in args)
        seed = _rng.next_key()
        if self._layer is None:
            key = ("free",)
            if key not in self._cache:
                self._cache[key] = self._build(True)
            out = self._cache[key](seed, *raw)
            return wrap(out) if not isinstance(out, (tuple, list)) else wrap(list(out))
        train = self._layer.training
        key = ("layer", train)
        if key not in self._cache:
            self._cache[key] = self._build(train)
        params, buffers = functional_state(self._layer)
        out, new_buffers = self._cache[key](params, buffers, seed, *raw)
        # write back mutated buffers (BN running stats)
        for n, b in self._layer.named_buffers():
            if n in new_buffers:
                b._data = new_buffers[n]
        if isinstance(out, (tuple, list)):
            return type(out)(Tensor(o) for o in out)
        return Tensor(out)


def to_static(function=None, input_spec=None, build_strategy=None, backend=None,
              **kwargs):
    def decorate(fn):
        if isinstance(fn, Layer):
            fn.forward = StaticFunction(fn.forward.__func__
                                        if hasattr(fn.forward, "__func__") else fn.forward,
                                        layer=fn)
            return fn
        return StaticFunction(fn, input_spec=input_spec)
    if function is not None:
        return decorate(function)
    return decorate


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save — persists params + config (AOT executable export is
    handled by paddle_tpu.inference)."""
    from ..framework.io import save as _save
    _save(layer.state_dict(), path + ".pdparams")


def load(path, **configs):
    raise NotImplementedError(
        "paddle_tpu.jit.load: load weights with paddle_tpu.load and rebuild "
        "the Layer; AOT executables via paddle_tpu.inference")


def not_to_static(fn=None):
    return fn


class TracedLayer:
    pass
