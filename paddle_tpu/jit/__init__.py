"""paddle.jit equivalent.

Reference: @to_static AST-transform pipeline (fluid/dygraph/dygraph_to_static/
program_translator.py:1001) compiling dygraph code to a ProgramDesc.
TPU-native: @to_static wraps the function with jax.jit over the functionalized
layer — the traced jaxpr/HLO *is* the static program, XLA is the executor.
"""
import functools

import jax

from ..core import random as _rng
from ..core.tensor import Tensor, unwrap, wrap
from ..nn.layer.layers import Layer, functional_call, functional_state


class StaticFunction:
    """A jit-compiled callable over a Layer method or free function."""

    def __init__(self, fn, layer=None, input_spec=None):
        self._fn = fn
        self._layer = layer
        self._input_spec = input_spec
        self._cache = {}
        self._tfn = None

    def _transformed(self):
        """dy2static-rewritten forward (tensor-dependent if/while/for ->
        lax control flow); falls back to the raw fn when the source can't
        be transformed. Reference: program_translator.py:1001."""
        if self._tfn is None:
            from . import dy2static
            self._tfn = dy2static.maybe_transform(self._fn)
        return self._tfn

    def __get__(self, instance, owner):
        if instance is None:
            return self
        # cache the bound StaticFunction in the instance dict so repeated
        # calls reuse one jit cache (instance attrs shadow this non-data
        # descriptor, so later lookups skip __get__ entirely)
        name = self._fn.__name__
        bound = instance.__dict__.get(name)
        if not (isinstance(bound, StaticFunction) and bound._fn is self._fn):
            bound = StaticFunction(self._fn, layer=instance,
                                   input_spec=self._input_spec)
            instance.__dict__[name] = bound
        return bound

    def _build(self, train):
        layer = self._layer
        fn = self._transformed()

        if layer is None:
            @functools.partial(jax.jit)
            def compiled(seed, *raw_args):
                with _rng.traced_rng(seed):
                    out = fn(*wrap(list(raw_args)))
                return unwrap(out)
            return compiled

        @functools.partial(jax.jit)
        def compiled(params, buffers, seed, *raw_args):
            with _rng.traced_rng(seed):
                out, new_buffers = functional_call(
                    layer, params, buffers,
                    args=tuple(Tensor(a) for a in raw_args),
                    train=train, method=fn)
            return unwrap(out), new_buffers
        return compiled

    def __call__(self, *args):
        import jax.random as jrandom
        raw = tuple(a._data if isinstance(a, Tensor) else a for a in args)
        seed = _rng.next_key()
        if self._layer is None:
            key = ("free",)
            if key not in self._cache:
                self._cache[key] = self._build(True)
            out = self._cache[key](seed, *raw)
            return wrap(out) if not isinstance(out, (tuple, list)) else wrap(list(out))
        train = self._layer.training
        key = ("layer", train)
        if key not in self._cache:
            self._cache[key] = self._build(train)
        params, buffers = functional_state(self._layer)
        out, new_buffers = self._cache[key](params, buffers, seed, *raw)
        # write back mutated buffers (BN running stats)
        for n, b in self._layer.named_buffers():
            if n in new_buffers:
                b._data = new_buffers[n]
        if isinstance(out, (tuple, list)):
            return type(out)(Tensor(o) for o in out)
        return Tensor(out)


def to_static(function=None, input_spec=None, build_strategy=None, backend=None,
              **kwargs):
    def decorate(fn):
        if isinstance(fn, Layer):
            fn.forward = StaticFunction(fn.forward.__func__
                                        if hasattr(fn.forward, "__func__") else fn.forward,
                                        layer=fn, input_spec=input_spec)
            return fn
        return StaticFunction(fn, input_spec=input_spec)
    if function is not None:
        return decorate(function)
    return decorate


MODEL_SUFFIX = ".pdmodel"      # serialized jax.export.Exported (StableHLO)
PARAMS_SUFFIX = ".pdiparams"   # params + buffers payload


def _to_arg_specs(input_spec):
    """InputSpec/Tensor list → ShapeDtypeStructs; None/-1 dims become
    export symbolic dims (shape-polymorphic serving: one artifact, any
    batch size — the reference gets this from ProgramDesc's -1 dims)."""
    import jax
    from jax import export as jexport

    from ..static import InputSpec

    scope = jexport.SymbolicScope()
    specs = []
    sym_by_pos = {}
    for i, s in enumerate(input_spec):
        if isinstance(s, Tensor):
            s = InputSpec.from_tensor(s)
        dims = []
        for j, d in enumerate(s.shape):
            if d is None or (isinstance(d, int) and d < 0):
                # dynamic dims at the same POSITION share one symbol — two
                # [None, 8] inputs get the same batch dim, as a ProgramDesc
                # with -1 dims would; distinct positions stay independent
                if j not in sym_by_pos:
                    sym_by_pos[j] = jexport.symbolic_shape(
                        f"dim{j}", scope=scope)[0]
                dims.append(sym_by_pos[j])
            else:
                dims.append(d)
        specs.append(jax.ShapeDtypeStruct(tuple(dims), s.dtype))
    return specs


def _export_layer(layer, input_spec):
    """Trace the layer's eval-mode forward into a serializable AOT program
    (reference: @to_static capture into ProgramDesc + jit/serializer.cc;
    here the program IS the exported StableHLO)."""
    import jax
    from jax import export as jexport

    params, buffers = functional_state(layer)

    def pure(params, buffers, *inputs):
        out, _ = functional_call(layer, params, buffers,
                                 args=tuple(Tensor(a) for a in inputs),
                                 train=False)
        return unwrap(out)

    shape_of = lambda tree: jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree)
    arg_specs = _to_arg_specs(input_spec)
    exp = jexport.export(jax.jit(pure))(shape_of(params), shape_of(buffers),
                                        *arg_specs)
    return exp, params, buffers


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save equivalent: writes `path.pdmodel` (serialized AOT
    program, shape-polymorphic over None dims) + `path.pdiparams` (weights).

    Reference: python/paddle/fluid/dygraph/jit.py jit.save → TranslatedLayer
    (program + params via fluid/jit/serializer.cc)."""
    from ..framework.io import save as _save

    if isinstance(layer, StaticFunction):
        raise TypeError("pass the Layer itself, not its StaticFunction")
    if input_spec is None:
        # a @to_static(input_spec=...) forward carries the spec already
        fwd = getattr(layer, "forward", None)
        input_spec = getattr(fwd, "_input_spec", None)
    if input_spec is None:
        raise ValueError("paddle_tpu.jit.save requires input_spec (list of "
                         "InputSpec or example Tensors), or a forward "
                         "decorated @to_static(input_spec=...)")
    exp, params, buffers = _export_layer(layer, input_spec)
    # persist REAL feed names so Executor.run can match feeds exactly
    # (reference: the pruned ProgramDesc carries feed_target_names)
    from ..static import InputSpec as _IS
    feed_names = []
    for i, s in enumerate(input_spec):
        n = s.name if isinstance(s, _IS) else getattr(s, "name", None)
        feed_names.append(n or f"input_{i}")
    with open(path + MODEL_SUFFIX, "wb") as f:
        f.write(exp.serialize())
    _save({"params": params, "buffers": buffers, "feed_names": feed_names},
          path + PARAMS_SUFFIX)


class TranslatedLayer(Layer):
    """A deserialized AOT program + weights, callable like the original
    Layer (inference only — the exported program is the eval-mode forward)."""

    def __init__(self, exported, params, buffers, feed_names=None):
        super().__init__()
        self._exported = exported
        self._param_tree = params
        self._buffer_tree = buffers
        self._feed_names = feed_names   # saved input names (None: old artifact)

    def forward(self, *inputs):
        raw = tuple(a._data if isinstance(a, Tensor) else a for a in inputs)
        out = self._exported.call(self._param_tree, self._buffer_tree, *raw)
        if isinstance(out, (tuple, list)):
            return type(out)(Tensor(o, stop_gradient=True) for o in out)
        return Tensor(out, stop_gradient=True)

    def state_dict(self, *a, **k):
        d = dict(self._param_tree)
        d.update(self._buffer_tree)
        return {n: Tensor(v, stop_gradient=True) for n, v in d.items()}


def load(path, **configs):
    """paddle.jit.load equivalent → TranslatedLayer."""
    import jax.numpy as jnp
    from jax import export as jexport

    from ..framework.io import load as _load

    with open(path + MODEL_SUFFIX, "rb") as f:
        exp = jexport.deserialize(f.read())
    payload = _load(path + PARAMS_SUFFIX, return_numpy=True)
    as_jnp = lambda tree: {n: jnp.asarray(v) for n, v in tree.items()}
    return TranslatedLayer(exp, as_jnp(payload["params"]),
                           as_jnp(payload["buffers"]),
                           feed_names=payload.get("feed_names"))


def not_to_static(fn=None):
    """Mark a function as exempt from dy2static rewriting (reference:
    jit/api.py not_to_static); convert_call passes it through untouched.
    Usable bare or as a zero-arg decorator factory."""
    if fn is None:
        return not_to_static
    try:
        fn.__dy2static_transformed__ = True
    except (AttributeError, TypeError):
        pass
    return fn


class TracedLayer:
    """Trace a dygraph Layer once into a static Program (captured jaxpr) +
    frozen eval-mode weights; run it program-style or export it.

    Reference: fluid/dygraph/jit.py:1388 TracedLayer (trace via the dygraph
    Tracer into a ProgramDesc + Executor). Here the program IS the captured
    jaxpr (static.Program.capture); weights are baked in as consts."""

    def __init__(self, layer, program, input_specs):
        self._layer = layer
        self._program = program
        self._input_specs = input_specs

    @staticmethod
    def trace(layer, inputs):
        """Returns (dygraph_outputs, traced_layer), reference-style."""
        from ..static import InputSpec, Program

        inputs = list(inputs)
        out = layer(*inputs)

        params, buffers = functional_state(layer)

        def pure(*raw):
            o, _ = functional_call(layer, params, buffers,
                                   args=tuple(Tensor(a) for a in raw),
                                   train=False)
            o = unwrap(o)
            return o if isinstance(o, (tuple, list)) else (o,)

        specs = [InputSpec.from_tensor(t, name=f"input_{i}")
                 for i, t in enumerate(inputs)]
        prog = Program.capture(pure, *specs)
        return out, TracedLayer(layer, prog, specs)

    def __call__(self, inputs):
        raw = [t._data if isinstance(t, Tensor) else t for t in inputs]
        outs = self._program.run_captured(*raw)
        return [Tensor(o, stop_gradient=True) for o in outs]

    @property
    def program(self):
        return self._program

    def set_strategy(self, build_strategy=None, exec_strategy=None):
        """Execution strategies are XLA-owned; accepted for API parity."""

    def save_inference_model(self, path, feed=None, fetch=None, **configs):
        """Export for Predictor/Executor serving; `feed`/`fetch` are index
        filters over the traced inputs/outputs (reference semantics).
        `feed` may PERMUTE the inputs (the exported program takes them in
        the declared feed order); dropping inputs needs graph pruning the
        traced program does not do — a subset raises."""
        specs = self._input_specs
        layer = self._layer
        if feed is not None:
            if sorted(feed) != list(range(len(specs))):
                raise ValueError(
                    f"TracedLayer.save_inference_model: feed={feed} must be "
                    f"a permutation of all {len(specs)} traced inputs; "
                    f"dropping an input would need program pruning — "
                    f"re-trace the layer with the inputs you want instead")
            specs = [specs[i] for i in feed]
        if feed is not None or fetch is not None:
            layer = _SliceAdapter(layer, feed, fetch)
        save(layer, path, input_spec=list(specs))


class _SliceAdapter(Layer):
    """Feed-permuting / fetch-slicing wrapper used by
    TracedLayer.save_inference_model. The base layer is a REGISTERED
    sublayer so its parameters ride the export payload and eval-mode
    switching reaches it."""

    def __init__(self, base, feed, fetch):
        super().__init__()
        self.base = base
        self._feed = feed
        self._fetch = fetch

    def forward(self, *args):
        if self._feed is not None:
            # args arrive in feed order; restore the original positions
            orig = [None] * len(args)
            for pos, idx in enumerate(self._feed):
                orig[idx] = args[pos]
            args = tuple(orig)
        out = self.base(*args)
        if self._fetch is None:
            return out
        out = out if isinstance(out, (tuple, list)) else [out]
        picked = [out[i] for i in self._fetch]
        return picked[0] if len(picked) == 1 else picked


class ProgramTranslator:
    """reference: dygraph_to_static/program_translator.py:1001 — global
    switch for to_static. Here tracing is always available; enable_to_static
    toggles whether @to_static actually jits (parity switch)."""
    _instance = None
    enable_to_static = True

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, enable_to_static):
        ProgramTranslator.enable_to_static = bool(enable_to_static)


def set_code_level(level=100, also_to_stdout=False):
    """reference: jit/dy2static set_code_level — controls transformed-code
    logging. Tracing has no AST transforms here; records the level."""
    import logging
    logging.getLogger("paddle_tpu.jit").setLevel(
        logging.DEBUG if level else logging.WARNING)


def set_verbosity(level=0, also_to_stdout=False):
    import logging
    logging.getLogger("paddle_tpu.jit").setLevel(
        logging.DEBUG if level else logging.WARNING)
