"""Hybrid-parallel SPMD execution (the reference's fleet static-graph path,
re-designed TPU-first — SURVEY §2.10).

The compute path here is raw-jax functional (no eager tape): one
jit-compiled train step per configuration, shard_map'd over a Mesh with
explicit XLA collectives. This is the performance path used by bench.py and
__graft_entry__.dryrun_multichip.
"""
from .gpt_spmd import (  # noqa: F401
    GPTSpmdConfig, MeshPlan, init_gpt_params, make_train_step, make_forward_fn,
)
from .ring_attention import ring_attention, ulysses_attention  # noqa: F401
