"""Hybrid-parallel GPT: one jit-compiled train step, shard_map'd over a Mesh.

This is the TPU-native equivalent of the reference's entire static-graph
hybrid-parallel stack (SURVEY §2.10): DP (data), MP (Megatron tensor
parallel: mp_layers.py), PP (1F1B SectionWorker / pp_layers.py), sharding
(ZeRO group_sharded), plus SP (ring attention — net-new, absent upstream).
Where the reference composes program rewrites + NCCL ops + stream sync, here
each strategy is a few explicit collectives inside ONE shard_map'd function;
XLA's latency-hiding scheduler overlaps them with compute.

Axes (canonical order): dp, pp, sharding, sp, mp
- batch is sharded over (dp, sharding); sequence over sp; vocab/heads/ffn
  over mp; layers over pp.
- gradients: pmean over (dp, sp); ZeRO-2 update: psum_scatter over
  'sharding' -> per-shard AdamW with f32 master weights -> all_gather.
- pipeline: GPipe microbatch schedule written as lax.scan over
  (microbatches + pp - 1) ticks with ppermute hand-off; autodiff through the
  scan yields the reverse pipeline schedule automatically (the reference
  needed a hand-written SectionWorker for this).
"""
import functools
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .pipeline_schedule import (arrival_tables, build_interleaved_tables,
                                build_tables, required_slots)
from .ring_attention import ring_attention, ulysses_attention

AXES = ("dp", "pp", "sharding", "sp", "mp")

_BLOCK_LEAVES = ("ln1_w", "ln1_b", "w_qkv", "b_qkv", "w_proj", "b_proj",
                 "ln2_w", "ln2_b", "w_fc1", "b_fc1", "w_fc2", "b_fc2")


@dataclass
class GPTSpmdConfig:
    vocab_size: int = 50304
    max_seq_len: int = 1024
    hidden: int = 768
    layers: int = 12
    heads: int = 12
    ffn: int = None
    param_dtype: str = "float32"     # storage dtype ("bfloat16" for bench)
    compute_dtype: str = "float32"   # activation dtype
    # remat: False = none, True = full per-block checkpoint (max HBM saving),
    # "dots" = save matmul outputs, recompute elementwise (recompute is cheap
    # VPU work, the MXU results are kept), "dots+attn" = dots AND the flash
    # attention output: flash is a custom_vjp whose bwd kernel recomputes
    # attention internally, so letting block-level remat recompute its fwd
    # pays the attention FLOPs a third time — saving the (B,S,H) output
    # (16 MB/layer at the bench shape) skips that (best MFU/HBM trade on TPU)
    remat: object = True
    init_std: float = 0.02
    # lax.scan unroll over the layer stack: >1 lets XLA software-pipeline
    # adjacent blocks (weight prefetch overlapping compute) at the cost of
    # program size; values measured via tools/profile_step.py
    scan_unroll: int = 1
    # >1 enables the chunked fused linear-CE LM head (ops/fused_ce.py):
    # logits never materialize, saving ~2.5GB peak f32 at the bench shape
    # for one extra logits matmul of backward recompute. mp=1 only (the
    # vocab-parallel path shards the same memory mp ways instead). Must
    # divide vocab_size.
    fused_ce_chunks: int = 0

    def __post_init__(self):
        if self.ffn is None:
            self.ffn = 4 * self.hidden
        if int(self.scan_unroll) < 1:
            raise ValueError(
                f"scan_unroll must be >= 1, got {self.scan_unroll}")
        if int(self.fused_ce_chunks) > 1 and \
                self.vocab_size % int(self.fused_ce_chunks):
            raise ValueError(
                f"fused_ce_chunks {self.fused_ce_chunks} must divide "
                f"vocab_size {self.vocab_size}")


@dataclass
class MeshPlan:
    dp: int = 1
    pp: int = 1
    sharding: int = 1
    sp: int = 1
    mp: int = 1
    microbatches: int = 1            # pipeline microbatches (per-device batch)
    # pipeline schedule: "1f1b" (activation buffer bounded by pp — the 1F1B
    # memory guarantee), "eager1f1b" (minimum ticks, ~2x the buffer, still
    # O(pp) and M-independent), or "gpipe" (autodiff-through-scan reverse
    # schedule; activation memory grows with microbatches — comparison only)
    schedule: str = "1f1b"
    vpp: int = 1                     # interleaved virtual stages per device
    # sequence-parallel attention flavor: "ring" (K/V ppermute rotation,
    # O(S/sp) residency) or "ulysses" (head<->seq all-to-all, full-S local
    # attention — fewer/larger ICI transfers, flash-kernel friendly)
    sp_mode: str = "ring"

    def __post_init__(self):
        if self.sp_mode not in ("ring", "ulysses"):
            raise ValueError(
                f"unknown sp_mode {self.sp_mode!r}; use 'ring' or 'ulysses'")

    @property
    def dims(self):
        return {"dp": self.dp, "pp": self.pp, "sharding": self.sharding,
                "sp": self.sp, "mp": self.mp}

    @property
    def n_devices(self):
        return self.dp * self.pp * self.sharding * self.sp * self.mp

    def build_mesh(self, devices=None):
        devs = np.asarray(devices if devices is not None else jax.devices())
        dims = tuple(self.dims.values())
        return Mesh(devs[:int(np.prod(dims))].reshape(dims), AXES)


# ---------------------------------------------------------------------------
# Parameters
# ---------------------------------------------------------------------------

def param_specs(cfg: GPTSpmdConfig):
    """PartitionSpec per leaf: pp on the stacked-layer dim, mp megatron-style."""
    return {
        "wte": P("mp", None),            # vocab-parallel embedding rows
        "wpe": P(),
        "ln1_w": P("pp", None), "ln1_b": P("pp", None),
        "w_qkv": P("pp", None, "mp"), "b_qkv": P("pp", "mp"),
        "w_proj": P("pp", "mp", None), "b_proj": P("pp", None),
        "ln2_w": P("pp", None), "ln2_b": P("pp", None),
        "w_fc1": P("pp", None, "mp"), "b_fc1": P("pp", "mp"),
        "w_fc2": P("pp", "mp", None), "b_fc2": P("pp", None),
        "lnf_w": P(), "lnf_b": P(),
    }


def init_gpt_params(cfg: GPTSpmdConfig, key):
    """Global (logical) parameter pytree; stacked over layers for scan/pp."""
    L, H, F, V = cfg.layers, cfg.hidden, cfg.ffn, cfg.vocab_size
    dt = jnp.dtype(cfg.param_dtype)
    ks = jax.random.split(key, 8)
    std = cfg.init_std
    proj_std = std / np.sqrt(2 * L)  # GPT-2 residual-scaled init

    def nrm(k, shape, s):
        return (jax.random.normal(k, shape, jnp.float32) * s).astype(dt)

    return {
        "wte": nrm(ks[0], (V, H), std),
        "wpe": nrm(ks[1], (cfg.max_seq_len, H), std),
        "ln1_w": jnp.ones((L, H), dt), "ln1_b": jnp.zeros((L, H), dt),
        "w_qkv": nrm(ks[2], (L, H, 3 * H), std),
        "b_qkv": jnp.zeros((L, 3 * H), dt),
        "w_proj": nrm(ks[3], (L, H, H), proj_std),
        "b_proj": jnp.zeros((L, H), dt),
        "ln2_w": jnp.ones((L, H), dt), "ln2_b": jnp.zeros((L, H), dt),
        "w_fc1": nrm(ks[4], (L, H, F), std),
        "b_fc1": jnp.zeros((L, F), dt),
        "w_fc2": nrm(ks[5], (L, F, H), proj_std),
        "b_fc2": jnp.zeros((L, H), dt),
        "lnf_w": jnp.ones((H,), dt), "lnf_b": jnp.zeros((H,), dt),
    }


# ---------------------------------------------------------------------------
# Forward pieces (run inside shard_map; shapes are LOCAL shards)
# ---------------------------------------------------------------------------

def _axis_psum(x, axis):
    """psum forward / identity backward (reference mp_ops.py _mp_allreduce).

    Under shard_map(check_vma=False) a raw lax.psum transposes to another
    psum, inflating cotangents by the axis size; since every use here feeds
    axis-replicated downstream compute, the true cotangent is replicated and
    the transpose must be identity — exactly Megatron's g-function.
    """
    @jax.custom_vjp
    def f(v):
        return jax.lax.psum(v, axis)

    def fwd(v):
        return jax.lax.psum(v, axis), None

    def bwd(_, g):
        return (g,)

    f.defvjp(fwd, bwd)
    return f(x)


def _mp_copy(x, plan):
    """Identity forward / psum-over-mp backward — the manual-TP input marker
    (reference: fleet mp_ops.py _c_identity). Needed because each mp rank's
    local backward only sees its own weight shard; upstream (replicated)
    tensors must accumulate cotangents from all ranks."""
    if plan.mp == 1:
        return x

    @jax.custom_vjp
    def f(v):
        return v

    def fwd(v):
        return v, None

    def bwd(_, g):
        return (jax.lax.psum(g, "mp"),)

    f.defvjp(fwd, bwd)
    return f(x)


def _ln(x, w, b, eps=1e-5):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w + b


def _allgather_sp_attention(q, k, v, causal=True):
    """Sequence-parallel attention via all-gather of K/V over the sp axis.

    q/k/v: (B, h_loc, S_loc, d), S_loc = S/sp. K and V are gathered to the
    full sequence (group-scoped collective — safe inside lax.cond, unlike
    ppermute) and attention runs locally over the (S_loc, S) tile with the
    causal mask offset by this shard's global row position.
    """
    from ..ops.flash_attention import flash_attention_bhsd

    S_loc = q.shape[2]
    k_full = jax.lax.all_gather(k, "sp", axis=2, tiled=True)
    v_full = jax.lax.all_gather(v, "sp", axis=2, tiled=True)
    mask = None
    if causal:
        row0 = jax.lax.axis_index("sp") * S_loc
        rows = row0 + jax.lax.broadcasted_iota(
            jnp.int32, (S_loc, k_full.shape[2]), 0)
        cols = jax.lax.broadcasted_iota(
            jnp.int32, (S_loc, k_full.shape[2]), 1)
        mask = jnp.where(rows >= cols, 0.0, -jnp.inf)[None, None]
    return flash_attention_bhsd(q, k_full, v_full, causal=False, mask=mask)


def _attention(h, blk, cfg, plan):
    B, S, _ = h.shape
    heads_loc = cfg.heads // plan.mp
    d = cfg.hidden // cfg.heads
    # w_qkv column layout is head-major [h0:(q|k|v), h1:(q|k|v), ...] so an
    # mp shard of the last dim is a whole number of heads (Megatron layout)
    h = _mp_copy(h, plan)
    qkv = h @ blk["w_qkv"] + blk["b_qkv"]          # (B,S,3H/mp)
    qkv = qkv.reshape(B, S, heads_loc, 3, d)
    q = jnp.moveaxis(qkv[:, :, :, 0], 2, 1)        # (B,h_loc,S,d)
    k = jnp.moveaxis(qkv[:, :, :, 1], 2, 1)
    v = jnp.moveaxis(qkv[:, :, :, 2], 2, 1)
    if plan.sp > 1 and plan.pp > 1:
        # Inside the 1F1B/interleaved tick body, stage compute is gated by
        # lax.cond on the (t, stage)-dependent tick table. XLA lowers
        # ppermute to CollectivePermute, a FULL-participation op (every
        # device must execute it, pairs or not), so the RING's ppermute
        # inside stage-divergent branches deadlocks the mesh. all_gather,
        # all_to_all and psum are group-scoped (replica_groups) and legal
        # there — so pp+sp honors sp_mode="ulysses" and otherwise uses
        # all-gather sequence parallelism instead of the ring.
        if plan.sp_mode == "ulysses":
            o = ulysses_attention(q, k, v, "sp", causal=True)
        else:
            o = _allgather_sp_attention(q, k, v, causal=True)
    elif plan.sp > 1:
        if plan.sp_mode == "ulysses":
            o = ulysses_attention(q, k, v, "sp", causal=True)
        else:
            o = ring_attention(q, k, v, "sp", causal=True)
    else:
        from ..ops.flash_attention import flash_attention_bhsd
        o = flash_attention_bhsd(q, k, v, causal=True)
    o = checkpoint_name(o, "flash_out")
    o = jnp.moveaxis(o, 1, 2).reshape(B, S, cfg.hidden // plan.mp)
    out = o @ blk["w_proj"]                        # partial sums over mp
    if plan.mp > 1:
        out = _axis_psum(out, "mp")
    return out + blk["b_proj"]


def _mlp(h, blk, plan):
    h = _mp_copy(h, plan)
    u = h @ blk["w_fc1"] + blk["b_fc1"]
    u = jax.nn.gelu(u, approximate=True)
    out = u @ blk["w_fc2"]
    if plan.mp > 1:
        out = _axis_psum(out, "mp")
    return out + blk["b_fc2"]


def _block(h, blk, cfg, plan):
    h = h + _attention(_ln(h, blk["ln1_w"], blk["ln1_b"]), blk, cfg, plan)
    h = h + _mlp(_ln(h, blk["ln2_w"], blk["ln2_b"]), blk, plan)
    return h


def _stage_blocks(h, params, cfg, plan):
    """Apply this pp-stage's local stack of blocks via lax.scan."""
    stacked = {k: params[k] for k in _BLOCK_LEAVES}

    def apply_block(h, blk):
        return _block(h, blk, cfg, plan)

    if cfg.remat == "dots":
        apply_block = jax.checkpoint(
            apply_block,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    elif cfg.remat == "dots+attn":
        apply_block = jax.checkpoint(
            apply_block,
            policy=jax.checkpoint_policies.save_from_both_policies(
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                jax.checkpoint_policies.save_only_these_names("flash_out")))
    elif cfg.remat:
        apply_block = jax.checkpoint(apply_block)

    def body(h, blk):
        return apply_block(h, blk), None

    h, _ = jax.lax.scan(body, h, stacked, unroll=int(cfg.scan_unroll))
    return h


def _embed(tokens, params, cfg, plan):
    """Vocab-parallel embedding + position embedding (sp-offset aware)."""
    wte = params["wte"]                            # (V/mp, H) local
    if plan.mp > 1:
        per = wte.shape[0]
        start = jax.lax.axis_index("mp") * per
        ids = tokens.astype(jnp.int32) - start
        ok = (ids >= 0) & (ids < per)
        emb = jnp.take(wte, jnp.clip(ids, 0, per - 1), axis=0)
        emb = jnp.where(ok[..., None], emb, 0)
        emb = _axis_psum(emb, "mp")
    else:
        emb = jnp.take(wte, tokens.astype(jnp.int32), axis=0)
    S_loc = tokens.shape[-1]
    if plan.sp > 1:
        pos0 = jax.lax.axis_index("sp") * S_loc
        emb = emb + jax.lax.dynamic_slice_in_dim(params["wpe"], pos0, S_loc, 0)
    else:
        emb = emb + params["wpe"][:S_loc]
    return emb.astype(jnp.dtype(cfg.compute_dtype))


@jax.custom_vjp
def _logits_matmul(h, wte):
    """bf16 x bf16 -> f32 logits with a bf16-cotangent backward.

    Without this, the backward matmuls (dh = g @ wte, dw = g^T @ h) inherit
    the f32 cotangent as an operand and XLA runs them at the f32 MXU rate
    (~1/4-1/8 of bf16) — and they are the two largest matmuls in the model
    (B*S x V x H). Casting g to the param dtype first keeps full MXU rate;
    accumulation stays f32 via preferred_element_type (the standard
    mixed-precision recipe, and what the reference's fused
    c_softmax_with_cross_entropy kernel does by computing in fp16/bf16
    with fp32 softmax statistics)."""
    return jnp.einsum("bsh,vh->bsv", h, wte,
                      preferred_element_type=jnp.float32)


def _logits_matmul_fwd(h, wte):
    return _logits_matmul(h, wte), (h, wte)


def _logits_matmul_bwd(res, g):
    h, wte = res
    gl = g.astype(h.dtype)
    dh = jnp.einsum("bsv,vh->bsh", gl, wte,
                    preferred_element_type=jnp.float32).astype(h.dtype)
    dw = jnp.einsum("bsv,bsh->vh", gl, h,
                    preferred_element_type=jnp.float32).astype(wte.dtype)
    return dh, dw


_logits_matmul.defvjp(_logits_matmul_fwd, _logits_matmul_bwd)


def _vocab_parallel_loss(h, labels, params, cfg, plan):
    """Tied-embedding LM head + vocab-parallel softmax CE (reference:
    c_softmax_with_cross_entropy). Returns mean NLL over local tokens."""
    h = _ln(h, params["lnf_w"], params["lnf_b"])
    h = _mp_copy(h, plan)
    wte = params["wte"]                            # (V/mp, H) local
    if cfg.fused_ce_chunks > 1:
        # chunked fused linear-CE: logits never materialize (HBM-bound LM
        # head -> online logsumexp over vocab chunks; ops/fused_ce.py).
        # Under mp the op crosses the axis for softmax stats itself and
        # returns a partial dh that _mp_copy's backward psums.
        if wte.shape[0] % cfg.fused_ce_chunks:
            # erroring (not silently falling back to unfused) — the user
            # sized memory around this knob
            raise ValueError(
                f"(InvalidArgument) fused_ce_chunks={cfg.fused_ce_chunks} "
                f"must divide the vocab shard rows {wte.shape[0]} "
                f"(= vocab_size/mp); pick a chunk count that divides the "
                f"LOCAL shard")
        from ..ops.fused_ce import fused_linear_cross_entropy
        B, S, H = h.shape
        nll = fused_linear_cross_entropy(
            h.reshape(B * S, H), wte, labels.reshape(B * S),
            cfg.fused_ce_chunks, "mp" if plan.mp > 1 else None)
        return jnp.mean(nll)
    # bf16 operands, f32 accumulation: full MXU rate with f32-safe softmax
    # statistics downstream (vs. upcasting operands, which halves+ MXU
    # throughput for the biggest matmul in the model)
    logits = _logits_matmul(h, wte)
    local_max = jax.lax.stop_gradient(jnp.max(logits, axis=-1, keepdims=True))
    gmax = jax.lax.stop_gradient(jax.lax.pmax(local_max, "mp")) \
        if plan.mp > 1 else local_max
    shifted = logits - gmax
    sumexp = jnp.sum(jnp.exp(shifted), axis=-1, keepdims=True)
    if plan.mp > 1:
        sumexp = _axis_psum(sumexp, "mp")
    logz = jnp.log(sumexp)[..., 0]
    li = labels.astype(jnp.int32)
    if plan.mp > 1:
        per = wte.shape[0]
        start = jax.lax.axis_index("mp") * per
        lid = li - start
        ok = (lid >= 0) & (lid < per)
        picked = jnp.take_along_axis(shifted, jnp.clip(lid, 0, per - 1)[..., None],
                                     axis=-1)[..., 0]
        picked = _axis_psum(jnp.where(ok, picked, 0.0), "mp")
    else:
        picked = jnp.take_along_axis(shifted, li[..., None], axis=-1)[..., 0]
    return jnp.mean(logz - picked)


# ---------------------------------------------------------------------------
# Pipeline forward (GPipe ticks over ppermute)
# ---------------------------------------------------------------------------

def _pipeline_loss(tokens, labels, params, cfg, plan):
    """tokens/labels: (B_loc, S_loc) local shard. Returns scalar local loss."""
    pp = plan.pp
    if pp == 1:
        h = _embed(tokens, params, cfg, plan)
        h = _stage_blocks(h, params, cfg, plan)
        return _vocab_parallel_loss(h, labels, params, cfg, plan)

    M = plan.microbatches
    B_loc, S_loc = tokens.shape
    B_mb = B_loc // M
    tok_mb = tokens.reshape(M, B_mb, S_loc)
    lab_mb = labels.reshape(M, B_mb, S_loc)
    stage = jax.lax.axis_index("pp")
    is_first = stage == 0
    is_last = stage == pp - 1
    cdt = jnp.dtype(cfg.compute_dtype)
    T = M + pp - 1
    fwd_perm = [(i, (i + 1) % pp) for i in range(pp)]

    def tick(carry, t):
        h_recv, loss_sum = carry
        # first stage feeds microbatch t (clamped); others use received act
        mb_in = jnp.clip(t, 0, M - 1)
        x_first = _embed(tok_mb[mb_in], params, cfg, plan)
        x = jnp.where(is_first, x_first, h_recv)
        h_out = _stage_blocks(x, params, cfg, plan)
        # last stage: loss for microbatch t-(pp-1) when in range
        mb_out = t - (pp - 1)
        valid = (mb_out >= 0) & (mb_out < M)
        lab = lab_mb[jnp.clip(mb_out, 0, M - 1)]
        mb_loss = _vocab_parallel_loss(h_out, lab, params, cfg, plan)
        loss_sum = loss_sum + jnp.where(is_last & valid, mb_loss, 0.0)
        h_send = jax.lax.ppermute(h_out, "pp", fwd_perm)
        return (h_send, loss_sum), None

    h0 = jnp.zeros((B_mb, S_loc, cfg.hidden), cdt)
    (_, loss_sum), _ = jax.lax.scan(tick, (h0, jnp.zeros((), jnp.float32)),
                                    jnp.arange(T))
    # defined on the last stage; broadcast to all pp ranks
    return _axis_psum(jnp.where(is_last, loss_sum / M, 0.0), "pp")


# ---------------------------------------------------------------------------
# 1F1B / interleaved pipeline: manual fwd+bwd schedule (no autodiff-through-
# scan). Reference: fleet/meta_parallel/pipeline_parallel.py:120 (1F1B),
# :464 (interleaved virtual stages). TPU-native design:
#   - the schedule is a static tick table (pipeline_schedule.py); the
#     compiled program is ONE lax.scan whose body runs at most one microbatch
#     forward and one backward per stage per tick, gated by lax.cond — so
#     embedding runs only on stage 0 and the LM head only on the last stage
#     (each pp row shares the predicate, so mp/sp collectives inside the
#     branches stay consistent).
#   - activation memory: only STAGE INPUTS are buffered, in a circular
#     buffer of `slots` = cap+1 entries (pp+1 for 1F1B) — M-independent.
#     The backward recomputes the stage forward from the saved input via
#     jax.vjp (Megatron "full recompute" style), which is also what bounds
#     the buffer to inputs rather than per-layer activations.
#   - gradients accumulate in f32 carries; the tied wte receives its
#     embedding contribution on stage 0 and its LM-head contribution on the
#     last stage (summed by the caller's psum over pp).
# ---------------------------------------------------------------------------

def interleave_permutation(L, pp, vpp):
    """Stacked-layer storage order for interleaved pipelining: device s's
    contiguous local shard holds its vpp chunks back-to-back, chunk c of
    device s being virtual stage k = c*pp + s (logical layers
    [k*L/D, (k+1)*L/D), D = pp*vpp). perm[new_pos] = logical_layer.

    This is a storage LAYOUT only — the pipeline body composes chunks in
    logical order, so the computed function is identical to the unpermuted
    model (checkpoints written under vpp>1 store this layout).
    """
    D = pp * vpp
    Lk = L // D
    perm = []
    for s in range(pp):
        for c in range(vpp):
            k = c * pp + s
            perm.extend(range(k * Lk, (k + 1) * Lk))
    return np.asarray(perm)


def _pipeline_manual_loss_and_grads(tokens, labels, params, cfg, plan):
    """1F1B/interleaved pipeline step: returns (local mean loss, grads pytree)
    with grads already divided by microbatch count (same semantics as
    value_and_grad of the mean loss). Runs inside shard_map."""
    pp, M, V = plan.pp, plan.microbatches, plan.vpp
    stage = jax.lax.axis_index("pp")
    is_first = stage == 0
    is_last = stage == pp - 1
    cdt = jnp.dtype(cfg.compute_dtype)
    B_loc, S_loc = tokens.shape
    B_mb = B_loc // M
    tok_mb = tokens.reshape(M, B_mb, S_loc)
    lab_mb = labels.reshape(M, B_mb, S_loc)
    Hd = cfg.hidden

    if V > 1:
        fwd_tbl, bwd_tbl, _ = build_interleaved_tables(M, pp, V)
    else:
        f_t, b_t, _ = build_tables(M, pp, plan.schedule)
        fwd_tbl, bwd_tbl = f_t[:, :, None], b_t[:, :, None]
    farr, garr = arrival_tables(fwd_tbl, bwd_tbl, pp, V)
    W = required_slots(fwd_tbl, bwd_tbl, farr, garr, M, pp, V)
    T = fwd_tbl.shape[0]
    fwd_tbl = jnp.asarray(fwd_tbl)
    bwd_tbl = jnp.asarray(bwd_tbl)
    farr = jnp.asarray(farr)
    garr = jnp.asarray(garr)

    bp_all = {k: params[k] for k in _BLOCK_LEAVES}
    hp = {k: params[k] for k in ("lnf_w", "lnf_b", "wte")}
    ep = {k: params[k] for k in ("wte", "wpe")}
    L_loc = bp_all["w_qkv"].shape[0]
    Lk = L_loc // V

    def chunk_params(c):
        return {k: jax.lax.slice_in_dim(v, c * Lk, (c + 1) * Lk, axis=0)
                for k, v in bp_all.items()}

    def stage_fn(bp_, x):
        return _stage_blocks(x, bp_, cfg, plan)

    def zeros_like_t(tree):
        return jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, p.dtype), tree)

    zero_act = jnp.zeros((B_mb, S_loc, Hd), cdt)
    fwd_perm = [(i, (i + 1) % pp) for i in range(pp)]
    bwd_perm = [(i, (i - 1) % pp) for i in range(pp)]
    f32 = jnp.float32

    def acc(a_tree, g_tree):
        return jax.tree_util.tree_map(
            lambda a, g: a + g.astype(f32), a_tree, g_tree)

    def tick(carry, t):
        buf, gbuf, fchan, gchan, loss_sum, g_bp, g_hp, g_ep = carry
        new_ys, new_gs = [], []
        for c in range(V):
            f_idx = fwd_tbl[t, stage, c]
            b_idx = bwd_tbl[t, stage, c]
            valid_f = f_idx >= 0
            valid_b = b_idx >= 0
            fi = jnp.clip(f_idx, 0, M - 1)
            bi = jnp.clip(b_idx, 0, M - 1)
            bp_c = chunk_params(c)

            # ---- park arrivals: the ppermute channels are overwritten every
            # tick, so incoming activations/cotangents go into the circular
            # buffers NOW even if this stage runs them later ----
            a_f = farr[t, stage, c]
            inc = fchan[c] if c == 0 else jnp.where(is_first, fchan[c - 1],
                                                    fchan[c])
            buf = jax.lax.cond(
                a_f >= 0,
                lambda: buf.at[c, jnp.clip(a_f, 0, M - 1) % W].set(inc),
                lambda: buf)
            a_g = garr[t, stage, c]
            g_inc = gchan[c] if c == V - 1 else jnp.where(is_last,
                                                          gchan[c + 1],
                                                          gchan[c])
            gbuf = jax.lax.cond(
                a_g >= 0,
                lambda: gbuf.at[c, jnp.clip(a_g, 0, M - 1) % W].set(g_inc),
                lambda: gbuf)

            # ---- forward: stage 0 chunk 0 embeds its input (and parks it
            # for the backward recompute); everyone else reads the buffer ----
            if c == 0:
                x_f = jax.lax.cond(
                    is_first,
                    lambda: _embed(tok_mb[fi], ep, cfg, plan),
                    lambda: buf[c, fi % W])
                buf = jax.lax.cond(
                    valid_f & is_first,
                    lambda: buf.at[c, fi % W].set(x_f),
                    lambda: buf)
            else:
                x_f = buf[c, fi % W]
            # the last virtual stage's output is consumed nowhere (its
            # backward recomputes the forward inside value_and_grad), so
            # skip that compute instead of shipping a dead activation
            run_f = valid_f if c < V - 1 else (valid_f & ~is_last)
            y_f = jax.lax.cond(
                run_f, lambda: stage_fn(bp_c, x_f), lambda: zero_act)
            new_ys.append(y_f)

            # ---- backward: last virtual stage seeds from the loss; others
            # apply the parked cotangent through the stage vjp ----
            x_b = buf[c, bi % W]
            g_in = gbuf[c, bi % W]

            def mid_branch():
                _, vjp = jax.vjp(stage_fn, bp_c, x_b)
                gb, gx = vjp(g_in)
                return jnp.zeros((), f32), gb, zeros_like_t(hp), gx

            if c == V - 1:
                def last_branch():
                    def head(bp_, hp_, x):
                        y = stage_fn(bp_, x)
                        return _vocab_parallel_loss(y, lab_mb[bi], hp_,
                                                    cfg, plan)
                    l, (gb, gh, gx) = jax.value_and_grad(
                        head, argnums=(0, 1, 2))(bp_c, hp, x_b)
                    return l, gb, gh, gx

                def do_b():
                    return jax.lax.cond(is_last, last_branch, mid_branch)
            else:
                do_b = mid_branch

            def skip_b():
                return (jnp.zeros((), f32), zeros_like_t(bp_c),
                        zeros_like_t(hp), zero_act)

            l_b, gb_c, gh_c, g_x = jax.lax.cond(valid_b, do_b, skip_b)
            new_gs.append(g_x)

            if c == 0:
                def emb_b():
                    _, evjp = jax.vjp(
                        lambda e: _embed(tok_mb[bi], e, cfg, plan), ep)
                    return evjp(g_x)[0]
                g_ep = acc(g_ep, jax.lax.cond(
                    is_first & valid_b, emb_b, lambda: zeros_like_t(ep)))
            g_bp = {k: g_bp[k].at[c * Lk:(c + 1) * Lk]
                    .add(gb_c[k].astype(f32)) for k in g_bp}
            g_hp = acc(g_hp, gh_c)
            loss_sum = loss_sum + l_b

        fchan = jax.lax.ppermute(jnp.stack(new_ys), "pp", fwd_perm)
        gchan = jax.lax.ppermute(jnp.stack(new_gs).astype(cdt), "pp",
                                 bwd_perm)
        return (buf, gbuf, fchan, gchan, loss_sum, g_bp, g_hp, g_ep), None

    carry0 = (
        jnp.zeros((V, W, B_mb, S_loc, Hd), cdt),
        jnp.zeros((V, W, B_mb, S_loc, Hd), cdt),
        jnp.zeros((V, B_mb, S_loc, Hd), cdt),
        jnp.zeros((V, B_mb, S_loc, Hd), cdt),
        jnp.zeros((), f32),
        {k: jnp.zeros(v.shape, f32) for k, v in bp_all.items()},
        {k: jnp.zeros(v.shape, f32) for k, v in hp.items()},
        {k: jnp.zeros(v.shape, f32) for k, v in ep.items()},
    )
    (_, _, _, _, loss_sum, g_bp, g_hp, g_ep), _ = jax.lax.scan(
        tick, carry0, jnp.arange(T))

    loss = _axis_psum(jnp.where(is_last, loss_sum / M, 0.0), "pp")
    grads = {k: v / M for k, v in g_bp.items()}
    grads["wte"] = (g_ep["wte"] + g_hp["wte"]) / M
    grads["wpe"] = g_ep["wpe"] / M
    grads["lnf_w"] = g_hp["lnf_w"] / M
    grads["lnf_b"] = g_hp["lnf_b"] / M
    return loss, grads


# ---------------------------------------------------------------------------
# ZeRO-2 sharded AdamW (f32 master weights)
# ---------------------------------------------------------------------------

def init_opt_state_leaf(p, plan):
    n = plan.sharding
    size = int(np.prod(p.shape))
    shard = (size + n - 1) // n
    return {"m": jnp.zeros((shard,), jnp.float32),
            "v": jnp.zeros((shard,), jnp.float32),
            "master": jnp.zeros((shard,), jnp.float32),  # filled on 1st step
            "t": jnp.zeros((), jnp.int32)}


def _zero2_adamw_update(p, g, st, lr, plan, wd=0.1, b1=0.9, b2=0.95, eps=1e-8):
    """Reduce-scatter grad -> shard update -> all-gather params.

    Matches paddle's GroupShardedOptimizerStage2 semantics (reference:
    fleet/meta_parallel/sharding/group_sharded_optimizer_stage2.py:51):
    optimizer states live sharded; comm = 1x reduce-scatter + 1x all-gather.
    """
    n = plan.sharding
    size = int(np.prod(p.shape))
    shard = (size + n - 1) // n
    pad = shard * n - size

    gf = jnp.pad(g.reshape(-1).astype(jnp.float32), (0, pad))
    if n > 1:
        g_sh = jax.lax.psum_scatter(gf, "sharding", scatter_dimension=0,
                                    tiled=True) / n
        idx = jax.lax.axis_index("sharding")
    else:
        g_sh = gf
        idx = 0
    pf = jnp.pad(p.reshape(-1).astype(jnp.float32), (0, pad))
    p_sh = jax.lax.dynamic_slice_in_dim(pf, idx * shard, shard, 0)

    t = st["t"] + 1
    # master weights: on step 1 adopt the (possibly bf16) param value
    master = jnp.where(st["t"] == 0, p_sh, st["master"])
    m = b1 * st["m"] + (1 - b1) * g_sh
    v = b2 * st["v"] + (1 - b2) * g_sh * g_sh
    mhat = m / (1 - b1 ** t.astype(jnp.float32))
    vhat = v / (1 - b2 ** t.astype(jnp.float32))
    master = master * (1 - lr * wd)
    master = master - lr * mhat / (jnp.sqrt(vhat) + eps)

    if n > 1:
        p_full = jax.lax.all_gather(master, "sharding", axis=0, tiled=True)
    else:
        p_full = master
    p_new = p_full[:size].reshape(p.shape).astype(p.dtype)
    return p_new, {"m": m, "v": v, "master": master, "t": t}


# ---------------------------------------------------------------------------
# The train step
# ---------------------------------------------------------------------------

def make_train_step(cfg: GPTSpmdConfig, plan: MeshPlan, mesh=None,
                    learning_rate=3e-4, weight_decay=0.1, grad_clip=1.0):
    """Returns (step_fn, init_fn, mesh). step_fn(params, opt_state, tokens,
    labels, lr=None) -> (loss, params, opt_state), jit-compiled over the
    mesh; lr defaults to the `learning_rate` given here.

    tokens/labels are GLOBAL arrays (B_global, S_global); in_shardings place
    them as (('dp','sharding'), 'sp').
    """
    mesh = mesh or plan.build_mesh()
    specs = param_specs(cfg)
    data_spec = P(("dp", "sharding"), "sp")

    def _state_leaf_spec(pspec):
        # m/v/master are per-device 1-D shards; for params sharded over pp/mp
        # each of those ranks holds genuinely different state, so the logical
        # dim-0 is sharded over (those axes x sharding). Claiming replication
        # would corrupt state on any reshard/checkpoint round-trip.
        axes = tuple(a for ax in (pspec or ()) if ax is not None
                     for a in ((ax,) if isinstance(ax, str) else tuple(ax))
                     if a in ("pp", "mp"))
        v = P(axes + ("sharding",))
        return {"m": v, "v": v, "master": v, "t": P()}

    state_spec = {name: _state_leaf_spec(s) for name, s in specs.items()}

    def local_loss(params, tokens, labels):
        return _pipeline_loss(tokens, labels, params, cfg, plan)

    def sharded_step(params, opt_state, tokens, labels, lr):
        if plan.pp > 1 and (plan.vpp > 1 or plan.schedule != "gpipe"):
            loss, grads = _pipeline_manual_loss_and_grads(
                tokens, labels, params, cfg, plan)
        else:
            loss, grads = jax.value_and_grad(local_loss)(params, tokens, labels)
        # grad sync over all data axes BEFORE clipping so the global-norm
        # clip sees the true batch gradient (paddle semantics). The ZeRO
        # psum_scatter then acts as a slice of the replicated mean.
        sync_axes = tuple(a for a, d in (("dp", plan.dp), ("sp", plan.sp),
                                         ("sharding", plan.sharding)) if d > 1)
        if sync_axes:
            grads = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g, sync_axes), grads)
            loss = jax.lax.pmean(loss, sync_axes)
        if plan.pp > 1:
            # pp-replicated leaves (wte/wpe/lnf) get stage-disjoint grad
            # contributions (embedding on stage 0, LM head on the last);
            # total = psum over pp. pp-sharded leaves already hold their own.
            grads = {n: (jax.lax.psum(g, "pp")
                         if "pp" not in (specs[n] or ()) else g)
                     for n, g in grads.items()}
        # mp grads for replicated-over-mp params need psum? No: every mp rank
        # computes the same loss value; params sharded over mp get their own
        # shard grads; replicated params (ln, wpe) get identical grads on
        # every mp rank because the loss is mp-identical. Same for pp via the
        # psum broadcast in _pipeline_loss.
        if grad_clip:
            # global norm must include all shards of mp/pp-sharded params;
            # _global_grad_sq sums per-leaf with its spec so replicated
            # leaves aren't double counted
            psum_axes = tuple(a for a, d in (("mp", plan.mp), ("pp", plan.pp))
                              if d > 1)
            if psum_axes:
                sq = _global_grad_sq(grads, specs, plan)
            else:
                sq = sum(jnp.sum(g.astype(jnp.float32) ** 2)
                         for g in jax.tree_util.tree_leaves(grads))
            gnorm = jnp.sqrt(sq)
            scale = jnp.minimum(1.0, grad_clip / jnp.maximum(gnorm, 1e-6))
            grads = jax.tree_util.tree_map(lambda g: g * scale, grads)
        new_params, new_state = {}, {}
        for name, p in params.items():
            p_new, s_new = _zero2_adamw_update(
                p, grads[name], opt_state[name], lr, plan, wd=weight_decay)
            new_params[name] = p_new
            new_state[name] = s_new
        return loss, new_params, new_state

    shmapped = jax.shard_map(
        sharded_step, mesh=mesh,
        in_specs=(specs, state_spec, data_spec, data_spec, P()),
        out_specs=(P(), specs, state_spec),
        check_vma=False)
    jitted = jax.jit(shmapped, donate_argnums=(0, 1))

    def step_fn(params, opt_state, tokens, labels, lr=None):
        lr_val = jnp.asarray(learning_rate if lr is None else lr, jnp.float32)
        return jitted(params, opt_state, tokens, labels, lr_val)

    def init_fn(key):
        params = init_gpt_params(cfg, key)
        if plan.vpp > 1:
            # interleaved storage layout (same logical model — see
            # interleave_permutation)
            perm = interleave_permutation(cfg.layers, plan.pp, plan.vpp)
            params = {k: (v[perm] if k in _BLOCK_LEAVES else v)
                      for k, v in params.items()}
        params = jax.tree_util.tree_map(
            lambda p, s: _put_global(p, NamedSharding(mesh, s)),
            params, specs, is_leaf=lambda x: isinstance(x, P))

        def init_state(params):
            return {k: init_opt_state_leaf(p, plan) for k, p in params.items()}

        state = jax.jit(jax.shard_map(
            init_state, mesh=mesh, in_specs=(specs,), out_specs=state_spec,
            check_vma=False))(params)
        return params, state

    return step_fn, init_fn, mesh


def _put_global(x, sharding):
    """Place a host-replicated value onto a (possibly multi-process) mesh.

    Single-controller: plain device_put. Multi-controller (jax.distributed,
    the DCN path): the sharding spans non-addressable devices, so each
    process contributes its addressable shards from the identical host copy
    (reference role: broadcast-from-rank-0 parameter init in
    fleet/meta_parallel — here every host derives the same init from the
    same seed, so no broadcast is needed)."""
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    host = np.asarray(x)
    return jax.make_array_from_callback(host.shape, sharding,
                                        lambda idx: host[idx])


def _global_grad_sq(grads, specs, plan):
    """Sum of squares across ALL logical gradient elements, correcting for
    mp/pp sharding per leaf."""
    total = jnp.zeros((), jnp.float32)
    for name, g in grads.items():
        leaf_sq = jnp.sum(g.astype(jnp.float32) ** 2)
        spec = specs[name]
        axes = [a for a in (spec or ()) if a in ("mp", "pp")]
        for a in axes:
            if (a == "mp" and plan.mp > 1) or (a == "pp" and plan.pp > 1):
                leaf_sq = jax.lax.psum(leaf_sq, a)
        total = total + leaf_sq
    return total


def make_forward_fn(cfg: GPTSpmdConfig):
    """Single-chip jittable forward (logits) for compile checks / serving."""
    plan = MeshPlan()

    def fwd(params, tokens):
        h = _embed(tokens, params, cfg, plan)
        h = _stage_blocks(h, params, cfg, plan)
        h = _ln(h, params["lnf_w"], params["lnf_b"])
        return jnp.einsum("bsh,vh->bsv", h.astype(jnp.float32),
                          params["wte"].astype(jnp.float32))
    return fwd
