"""Pipeline schedules as static tick tables.

The reference hand-codes its schedules in Python control flow over p2p sends
(fleet/meta_parallel/pipeline_parallel.py:120 1F1B, :464 interleaved). On TPU
the whole pipeline is ONE compiled program: a lax.scan over "ticks" where
every tick each pp stage (optionally) runs one microbatch forward and one
microbatch backward, hand-off via ppermute. Which (stage, tick) pair runs
which microbatch is decided HERE, ahead of time, by simulating the schedule
in plain Python; the result is a pair of int32 tables

    fwd_tbl[t, s] = microbatch whose FORWARD stage s runs at tick t (-1 none)
    bwd_tbl[t, s] = microbatch whose BACKWARD stage s runs at tick t (-1 none)

which the compiled scan merely indexes. Any schedule expressible as such
tables (GPipe, 1F1B, eager-1F1B, interleaved virtual stages) compiles to the
same scan body — schedule choice costs nothing at runtime.

Correctness constraints enforced by the simulator:
- F of microbatch j at stage s needs F(j, s-1) at an earlier tick (activation
  ppermuted between ticks); stage 0 sources from the embedded input.
- B of j at stage s needs B(j, s+1) at an earlier tick; the LAST stage needs
  F(j, last) at an earlier-or-equal tick (the tick body runs F before B, so
  the last stage may fold F_j and B_j into one tick — classic 1F1B).
- in-flight microbatches at stage s (F done, B not) never exceed cap(s);
  cap = pp - s gives the 1F1B activation bound, cap = M gives GPipe.
"""
import numpy as np


def simulate_schedule(n_microbatches, pp, cap, max_ticks=100000):
    """Generic event-driven simulator -> (fwd_tbl, bwd_tbl) int32 (T, pp).

    cap: callable stage -> max in-flight microbatches at that stage.
    Every stage greedily runs (at most) one F and one B per tick subject to
    the availability rules above; B preferred implicitly since capacity only
    blocks F.
    """
    M = n_microbatches
    fwd_done = np.full((pp, M), -1, np.int64)   # tick F(j,s) completed
    bwd_done = np.full((pp, M), -1, np.int64)
    nf = [0] * pp
    nb = [0] * pp
    rows_f, rows_b = [], []
    t = 0
    while any(n < M for n in nb) and t < max_ticks:
        row_f = [-1] * pp
        row_b = [-1] * pp
        # Decide per stage: B first (it frees capacity for the same-tick F of
        # the steady state), then F against post-B occupancy. The compiled
        # body still EXECUTES F before B within a tick — that transiently
        # holds cap+1 activations, which is why the buffer has cap+1 slots —
        # and the last stage may fold F_j and B_j into one tick.
        for s in range(pp):
            # forward availability (independent of this tick's B)
            j = nf[s]
            avail_f = j < M and ((s == 0) or (0 <= fwd_done[s - 1][j] < t))
            b = nb[s]
            if b < M:
                if s == pp - 1:
                    ok = (0 <= fwd_done[s][b] < t) or (b == j and avail_f)
                else:
                    ok = 0 <= bwd_done[s + 1][b] < t
                if ok:
                    row_b[s] = b
                    bwd_done[s][b] = t
                    nb[s] += 1
            if avail_f and (nf[s] - nb[s]) < cap(s):
                row_f[s] = j
                fwd_done[s][j] = t
                nf[s] += 1
        rows_f.append(row_f)
        rows_b.append(row_b)
        t += 1
    if any(n < M for n in nb):
        raise RuntimeError(
            f"schedule deadlock: M={M} pp={pp} cap={[cap(s) for s in range(pp)]}")
    return (np.asarray(rows_f, np.int32), np.asarray(rows_b, np.int32))


def build_tables(n_microbatches, pp, schedule="1f1b"):
    """-> (fwd_tbl, bwd_tbl, buffer_slots).

    schedule:
      "1f1b"      cap(s) = pp - s: the 1F1B live-activation bound; steady
                  state alternates one B and one F per stage per tick.
      "eager1f1b" cap 2*pp: every stage forwards as fast as activations
                  arrive (shorter warmup, ~2x the 1F1B activation memory,
                  still O(pp) and independent of M).
      "gpipe"     cap M: all forwards first; activation memory grows with M.
                  (Exists for comparison/tests; prefer "1f1b".)
    """
    M, caps = n_microbatches, None
    if schedule == "1f1b":
        caps = lambda s: pp - s
    elif schedule == "eager1f1b":
        caps = lambda s: 2 * pp
    elif schedule == "gpipe":
        caps = lambda s: M
    else:
        raise ValueError(f"unknown pipeline schedule {schedule!r}; "
                         "expected 1f1b | eager1f1b | gpipe")
    fwd_tbl, bwd_tbl = simulate_schedule(M, pp, caps)
    max_inflight = max(caps(s) for s in range(pp))
    slots = min(M, max_inflight) + 1
    return fwd_tbl, bwd_tbl, slots


def build_interleaved_tables(n_microbatches, pp, vpp):
    """Interleaved virtual stages (Megatron-style; reference
    pipeline_parallel.py:464). Device s hosts vpp chunks; chunk c on device s
    is virtual stage k = c*pp + s. Returns (fwd_tbl, bwd_tbl, slots) with
    shape (T, pp, vpp): the tick table per device per chunk.

    The simulator treats the D = vpp*pp virtual stages as one deep pipeline
    (correctness rules identical), with the extra constraint that a physical
    device runs at most one F and one B per tick ACROSS its chunks — a tick
    is one microbatch-stage of work, so wall-clock per tick stays constant.
    Chunk-depth-first priority (lowest virtual stage first for B, for F the
    chunk whose turn sustains the 1F1B steady state) reproduces the
    interleaved schedule's reduced warmup bubble.
    """
    M, D = n_microbatches, vpp * pp
    fwd_done = np.full((D, M), -1, np.int64)
    bwd_done = np.full((D, M), -1, np.int64)
    nf = [0] * D
    nb = [0] * D
    rows_f, rows_b = [], []
    # per-device in-flight cap: 1F1B bound generalized to interleave — device
    # s may hold up to D - s in-flight (its earliest chunk's bound dominates)
    dev_cap = [D - s for s in range(pp)]
    t = 0
    while any(n < M for n in nb) and t < 200000:
        row_f = np.full((pp, vpp), -1, np.int64)
        row_b = np.full((pp, vpp), -1, np.int64)
        for s in range(pp):
            # one F slot: pick the READY chunk with the fewest forwards done
            # (breadth-first over chunks = Megatron's interleave order)
            inflight = sum(nf[c * pp + s] - nb[c * pp + s] for c in range(vpp))
            if inflight < dev_cap[s]:
                best = None
                for c in range(vpp):
                    k = c * pp + s
                    j = nf[k]
                    if j >= M:
                        continue
                    ok = (k == 0) or (0 <= fwd_done[k - 1][j] < t)
                    if ok and (best is None or nf[k] < nf[best[0] * pp + s] or
                               (nf[k] == nf[best[0] * pp + s] and c < best[0])):
                        best = (c, j)
                if best is not None:
                    c, j = best
                    k = c * pp + s
                    row_f[s, c] = j
                    fwd_done[k][j] = t
                    nf[k] += 1
        for s in range(pp):
            # one B slot: pick the ready chunk with the DEEPEST virtual stage
            # (drain from the end of the pipeline first)
            for c in reversed(range(vpp)):
                k = c * pp + s
                b = nb[k]
                if b >= M:
                    continue
                if k == D - 1:
                    ok = 0 <= fwd_done[k][b] <= t
                else:
                    ok = 0 <= bwd_done[k + 1][b] < t
                if ok:
                    row_b[s, c] = b
                    bwd_done[k][b] = t
                    nb[k] += 1
                    break
        rows_f.append(row_f)
        rows_b.append(row_b)
        t += 1
    if any(n < M for n in nb):
        raise RuntimeError(f"interleaved schedule deadlock: M={M} pp={pp} vpp={vpp}")
    fwd_tbl = np.stack(rows_f).astype(np.int32)
    bwd_tbl = np.stack(rows_b).astype(np.int32)
    slots = min(M, max(dev_cap)) + 1
    return fwd_tbl, bwd_tbl, slots


def arrival_tables(fwd_tbl, bwd_tbl, pp, vpp):
    """When does each (device, chunk) RECEIVE work over the ppermute rings?

    The fwd/bwd channels are overwritten every tick, so arriving activations
    and cotangents must be parked in buffers the tick they arrive (a stage may
    not run them until later — schedule stalls). Arrival times are static:

      farr[t, s, c] = microbatch whose forward ACTIVATION arrives at tick t
                      (sent by the predecessor virtual stage at t-1), -1 none
      garr[t, s, c] = microbatch whose COTANGENT arrives at tick t, -1 none

    Virtual-stage ring: predecessor of (s, c) is (s-1, c); for s == 0 it is
    (pp-1, c-1) (chunk wrap). Virtual stage 0 (s=0, c=0) embeds its own input;
    the last virtual stage seeds its own cotangent from the loss.
    """
    T = fwd_tbl.shape[0]
    farr = np.full((T, pp, vpp), -1, np.int32)
    garr = np.full((T, pp, vpp), -1, np.int32)
    for s in range(pp):
        for c in range(vpp):
            if not (s == 0 and c == 0):
                ps, pc = (s - 1, c) if s > 0 else (pp - 1, c - 1)
                farr[1:, s, c] = fwd_tbl[:-1, ps, pc]
            if not (s == pp - 1 and c == vpp - 1):
                ns, nc = (s + 1, c) if s < pp - 1 else (0, c + 1)
                garr[1:, s, c] = bwd_tbl[:-1, ns, nc]
    return farr, garr


def required_slots(fwd_tbl, bwd_tbl, farr, garr, n_microbatches, pp, vpp):
    """Circular-buffer size: max microbatches simultaneously LIVE at any
    (device, chunk) — live from arrival (or forward, whichever first) until
    backward completes — so slot j % W never collides."""
    T = fwd_tbl.shape[0]
    M = n_microbatches
    worst = 1
    for s in range(pp):
        for c in range(vpp):
            start = np.full(M, T, np.int64)
            g_start = np.full(M, T, np.int64)
            end = np.zeros(M, np.int64)
            for t in range(T):
                for tbl, rec in ((fwd_tbl, start), (farr, start),
                                 (garr, g_start)):
                    j = tbl[t, s, c]
                    if j >= 0:
                        rec[j] = min(rec[j], t)
                j = bwd_tbl[t, s, c]
                if j >= 0:
                    end[j] = t
                    g_start[j] = min(g_start[j], t)
            for st in (start, g_start):
                for t in range(T):
                    live = int(((st <= t) & (end >= t)).sum())
                    worst = max(worst, live)
    return worst + 1


def build_serving_tables(n_microbatches, pp, tokens_per_tick=1):
    """Forward-only tick table for SERVING pipelines (ISSUE 13): the
    1F1B machinery above minus the backward half — microbatch g enters
    stage 0 at tick g and rides the stage ring one hop per tick, so

        tbl[t, s] = microbatch stage s processes at tick t (-1 idle)

    over T = M + pp - 1 ticks. After the (pp-1)-tick fill every stage
    works every tick (the steady-state ring); the only idle entries are
    the fill/drain triangles, so the schedule's bubble fraction is
    (pp-1)/(M + pp - 1) — shrinking with the microbatch count, which is
    what `serving_pp_bubble_fraction` gauges and the metrics_report
    failure-class rule watch.

    tokens_per_tick (ISSUE 14): W > 1 grows a third dimension — each
    (tick, stage) cell carries the W token slots of its microbatch's
    verify window (a speculative γ+1-token window riding the ring):

        tbl[t, s, w] = global token slot g * W + w (-1 idle)

    Same T, same fill/drain triangles — but one ring pass now moves up
    to M·W tokens instead of M, so the fill/drain cost AMORTIZES per
    emitted token by the window width: idle stage-ticks per emitted
    token fall from (pp-1)·pp/M to (pp-1)·pp/(M·W·rate), where `rate`
    is the fraction of window tokens the verify rule accepts. That
    amortization is the spec×pp composition's second win next to the
    per-verify token multiplier (docs/PERF_NOTES.md prices both)."""
    M, pp = int(n_microbatches), int(pp)
    W = int(tokens_per_tick)
    if M < 1 or pp < 1 or W < 1:
        raise ValueError(f"need M >= 1, pp >= 1 and tokens_per_tick >= 1, "
                         f"got M={M} pp={pp} W={W}")
    T = M + pp - 1
    if W == 1:
        tbl = np.full((T, pp), -1, np.int32)
        for t in range(T):
            for s in range(pp):
                g = t - s
                if 0 <= g < M:
                    tbl[t, s] = g
        return tbl
    tbl = np.full((T, pp, W), -1, np.int32)
    for t in range(T):
        for s in range(pp):
            g = t - s
            if 0 <= g < M:
                tbl[t, s] = g * W + np.arange(W, dtype=np.int32)
    return tbl


def serving_schedule_stats(tbl):
    """Diagnostics for a `build_serving_tables` table: total ticks,
    per-stage busy fraction, and the bubble fraction the gauges carry.
    A 3-D (tokens-per-tick) table additionally reports the window width
    and `ticks_per_token_max` = T/(M·W), the per-emitted-token tick
    bill at full acceptance — the figure the spec×pp bubble
    amortization divides."""
    if tbl.ndim == 3:
        T, pp, W = tbl.shape
        busy2 = (tbl >= 0).any(-1)
        M = int(busy2[:, 0].sum())
        busy = busy2.sum(0)
        work = int(busy2.sum())
        return {"ticks": int(T),
                "stage_busy": [float(b) / T for b in busy],
                "bubble_frac": float(1.0 - work / (T * pp)),
                "tokens_per_tick": int(W),
                "ticks_per_token_max": float(T) / (M * W)}
    T, pp = tbl.shape
    busy = (tbl >= 0).sum(0)
    work = int((tbl >= 0).sum())
    return {"ticks": int(T),
            "stage_busy": [float(b) / T for b in busy],
            "bubble_frac": float(1.0 - work / (T * pp))}


def schedule_stats(fwd_tbl, bwd_tbl):
    """Diagnostics: total ticks, bubble fraction, peak in-flight per stage."""
    T = fwd_tbl.shape[0]
    pp = fwd_tbl.shape[1]
    work = (fwd_tbl >= 0).reshape(T, -1).sum() + (bwd_tbl >= 0).reshape(T, -1).sum()
    capacity = T * np.prod(fwd_tbl.shape[1:]) * 2
    peak = []
    for s in range(pp):
        f = np.cumsum((fwd_tbl[:, s] >= 0).reshape(T, -1).sum(-1))
        b = np.cumsum((bwd_tbl[:, s] >= 0).reshape(T, -1).sum(-1))
        peak.append(int((f - b).max()))
    return {"ticks": int(T), "bubble_frac": float(1 - work / capacity),
            "peak_inflight": peak}
