"""Ring attention — sequence/context parallelism over a mesh axis.

NET-NEW vs the reference (SURVEY §2.10: sequence parallelism is ABSENT in
ShawnNew/Paddle; its long-sequence support stops at fused MHA + TP head
splitting). Design: blockwise attention with online-softmax accumulation
(RingAttention, Liu et al. 2023); K/V blocks rotate around the 'sp' mesh
axis via jax.lax.ppermute (ICI neighbor exchange), so each device only ever
holds S/sp keys — sequence length scales linearly with the mesh axis.

Call INSIDE shard_map with q/k/v already sequence-sharded:
    q, k, v: (B, H, S_local, D) on each device; axis_name: the sp mesh axis.
Causality uses global positions: shard i owns rows [i*S_local, (i+1)*S_local).
"""
import jax
import jax.numpy as jnp


def _block_attend(q, k, v, m, l, o, row_off, col_off, causal, scale):
    """One (q-block x kv-block) step of online softmax, f32 accumulators.

    q: (B,H,Sq,D); k,v: (B,H,Sk,D); m,l: (B,H,Sq); o: (B,H,Sq,D).
    row_off/col_off: global offsets of the q rows / kv cols (traced scalars).
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        rows = row_off + jnp.arange(q.shape[2])[:, None]
        cols = col_off + jnp.arange(k.shape[2])[None, :]
        s = jnp.where(rows >= cols, s, -jnp.inf)
    m_blk = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    # guard fully-masked rows (m_new == -inf)
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
    l_new = l * corr + jnp.sum(p, axis=-1)
    o_new = o * corr[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return m_new, l_new, o_new


def ring_attention(q, k, v, axis_name, causal=True, scale=None):
    """Blockwise ring attention over `axis_name` (manual/shard_map context)."""
    B, H, S_loc, D = q.shape
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    n = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)

    qf = q.astype(jnp.float32)
    m0 = jnp.full((B, H, S_loc), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, S_loc), jnp.float32)
    o0 = jnp.zeros((B, H, S_loc, D), jnp.float32)
    row_off = my * S_loc

    perm = [(i, (i + 1) % n) for i in range(n)]

    def attend(step, m, l, o, k_cur, v_cur):
        # kv currently held originates from shard (my - step) mod n
        col_off = jnp.mod(my - step, n) * S_loc
        return _block_attend(qf, k_cur.astype(jnp.float32),
                             v_cur.astype(jnp.float32),
                             m, l, o, row_off, col_off, causal, scale)

    def body(step, carry):
        m, l, o, k_cur, v_cur = carry
        m, l, o = attend(step, m, l, o, k_cur, v_cur)
        # rotate kv to the next device (ring over ICI)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return m, l, o, k_nxt, v_nxt

    # n-1 rotated steps, final block attended outside the loop (no wasted
    # trailing ppermute pair)
    m, l, o, k_last, v_last = jax.lax.fori_loop(0, n - 1, body,
                                                (m0, l0, o0, k, v))
    m, l, o = attend(n - 1, m, l, o, k_last, v_last)
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def ring_attention_bshd(q, k, v, axis_name, causal=True, scale=None):
    """(B, S, H, D) wrapper matching paddle's MHA layout."""
    qt, kt, vt = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
    return jnp.swapaxes(ring_attention(qt, kt, vt, axis_name, causal, scale), 1, 2)


def ulysses_attention(q, k, v, axis_name, causal=True, scale=None,
                      attn_fn=None):
    """Ulysses-style sequence parallelism (also NET-NEW vs the reference):
    one all-to-all re-shards each of q/k/v from sequence-sharded
    (B, H, S/sp, D) to head-sharded (B, H/sp, S, D), full-sequence
    attention runs locally per head group, and one all-to-all restores the
    sequence sharding (DeepSpeed-Ulysses; Jacobs et al. 2023).

    Trade-off vs ring_attention: 2x4 all-to-alls of activation size instead
    of (sp-1) K/V ppermute rounds — fewer, larger ICI transfers and the
    full-length attention can use the Pallas flash kernel (`attn_fn`
    defaults to the flash dispatch); requires H % sp == 0, and each device
    briefly holds S_full x H/sp activations.

    Call INSIDE shard_map with q/k/v sequence-sharded (B, H, S_loc, D).
    """
    B, H, S_loc, D = q.shape
    n = jax.lax.axis_size(axis_name)
    if H % n:
        raise ValueError(f"ulysses_attention needs heads ({H}) divisible "
                         f"by the sp axis size ({n})")
    if attn_fn is None:
        from ..ops.flash_attention import flash_attention_bhsd

        def attn_fn(q, k, v):
            return flash_attention_bhsd(q, k, v, causal=causal, scale=scale)

    # seq-sharded -> head-sharded: split the head dim across the axis,
    # gather the sequence dim. q/k/v ride ONE fused tiled all_to_all: ICI
    # collectives are latency-bound at these shard sizes, so one launch
    # beats three of the same total bytes. all_to_all hands rank r the
    # CONTIGUOUS r-th chunk of the split axis, so the stack interleaves
    # per-rank chunks as [q_r | k_r | v_r] blocks (a plain concat would
    # scramble q/k/v across ranks).
    h_loc = H // n

    def chunks(t):                                   # (B,H,S_loc,D) ->
        return t.reshape(B, n, h_loc, S_loc, D)      # (B,n,h_loc,S_loc,D)

    qkv = jnp.concatenate([chunks(q), chunks(k), chunks(v)], axis=2)
    qkv = qkv.reshape(B, 3 * H, S_loc, D)            # [r][q|k|v][h_loc]
    qkv_h = jax.lax.all_to_all(qkv, axis_name, split_axis=1, concat_axis=2,
                               tiled=True)           # (B, 3*h_loc, S, D)
    qh = qkv_h[:, :h_loc]
    kh = qkv_h[:, h_loc:2 * h_loc]
    vh = qkv_h[:, 2 * h_loc:]
    out = attn_fn(qh, kh, vh)                        # (B, h_loc, S, D)
    return jax.lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)            # (B, H, S_loc, D)
