import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def t(a, sg=True):
    return paddle.to_tensor(np.asarray(a, np.float32), stop_gradient=sg)


class TestLinear:
    def test_forward_shape_and_math(self):
        layer = nn.Linear(4, 3)
        x = t(np.random.rand(2, 4))
        out = layer(x)
        assert out.shape == [2, 3]
        ref = x.numpy() @ layer.weight.numpy() + layer.bias.numpy()
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)

    def test_no_bias(self):
        layer = nn.Linear(4, 3, bias_attr=False)
        assert layer.bias is None


class TestConvPool:
    def test_conv2d_shapes(self):
        x = t(np.random.rand(2, 3, 8, 8))
        assert nn.Conv2D(3, 6, 3)(x).shape == [2, 6, 6, 6]
        assert nn.Conv2D(3, 6, 3, padding=1)(x).shape == [2, 6, 8, 8]
        assert nn.Conv2D(3, 6, 3, stride=2, padding=1)(x).shape == [2, 6, 4, 4]
        assert nn.Conv2D(3, 6, 3, groups=3, padding=1)(x).shape == [2, 6, 8, 8]

    def test_conv2d_matches_manual(self):
        x = np.random.rand(1, 1, 4, 4).astype(np.float32)
        w = np.random.rand(1, 1, 3, 3).astype(np.float32)
        out = F.conv2d(t(x), t(w))
        ref = np.zeros((1, 1, 2, 2), np.float32)
        for i in range(2):
            for j in range(2):
                ref[0, 0, i, j] = (x[0, 0, i:i+3, j:j+3] * w[0, 0]).sum()
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)

    def test_conv_transpose(self):
        x = t(np.random.rand(2, 4, 5, 5))
        out = nn.Conv2DTranspose(4, 3, 3, stride=2, padding=1, output_padding=1)(x)
        assert out.shape == [2, 3, 10, 10]

    def test_pools(self):
        x = t(np.random.rand(2, 3, 8, 8))
        assert nn.MaxPool2D(2, 2)(x).shape == [2, 3, 4, 4]
        assert nn.AvgPool2D(2, 2)(x).shape == [2, 3, 4, 4]
        assert nn.AdaptiveAvgPool2D(1)(x).shape == [2, 3, 1, 1]
        np.testing.assert_allclose(
            nn.AdaptiveAvgPool2D(1)(x).numpy()[..., 0, 0],
            x.numpy().mean((2, 3)), rtol=1e-5)

    def test_maxpool_matches_numpy(self):
        x = np.random.rand(1, 1, 4, 4).astype(np.float32)
        out = F.max_pool2d(t(x), 2, 2).numpy()
        ref = x.reshape(1, 1, 2, 2, 2, 2).max((3, 5))
        np.testing.assert_allclose(out, ref, rtol=1e-6)


class TestNorm:
    def test_batchnorm_train_eval(self):
        bn = nn.BatchNorm2D(3)
        x = t(np.random.rand(4, 3, 5, 5) * 2 + 1)
        bn.train()
        out = bn(x)
        np.testing.assert_allclose(out.numpy().mean((0, 2, 3)), np.zeros(3),
                                   atol=1e-5)
        np.testing.assert_allclose(out.numpy().std((0, 2, 3)), np.ones(3),
                                   atol=1e-3)
        # running stats moved toward batch stats
        assert not np.allclose(bn._mean.numpy(), np.zeros(3))
        bn.eval()
        out2 = bn(x)
        assert out2.shape == [4, 3, 5, 5]

    def test_layernorm(self):
        ln = nn.LayerNorm(8)
        x = t(np.random.rand(2, 4, 8) * 3)
        out = ln(x).numpy()
        np.testing.assert_allclose(out.mean(-1), np.zeros((2, 4)), atol=1e-5)
        np.testing.assert_allclose(out.std(-1), np.ones((2, 4)), atol=1e-2)

    def test_groupnorm(self):
        gn = nn.GroupNorm(2, 4)
        x = t(np.random.rand(2, 4, 3, 3))
        assert gn(x).shape == [2, 4, 3, 3]


class TestEmbeddingDropout:
    def test_embedding(self):
        emb = nn.Embedding(10, 4)
        ids = paddle.to_tensor(np.array([[1, 2], [3, 4]], np.int64))
        out = emb(ids)
        assert out.shape == [2, 2, 4]
        np.testing.assert_allclose(out.numpy()[0, 0], emb.weight.numpy()[1],
                                   rtol=1e-6)

    def test_dropout_train_eval(self):
        d = nn.Dropout(0.5)
        x = t(np.ones((100, 100)))
        d.train()
        out = d(x).numpy()
        frac = (out == 0).mean()
        assert 0.3 < frac < 0.7
        # upscale_in_train preserves expectation
        assert abs(out.mean() - 1.0) < 0.1
        d.eval()
        np.testing.assert_array_equal(d(x).numpy(), x.numpy())


class TestActivationsLosses:
    def test_activations(self):
        x = t(np.array([-1.0, 0.0, 2.0]))
        np.testing.assert_allclose(nn.ReLU()(x).numpy(), [0, 0, 2], rtol=1e-6)
        np.testing.assert_allclose(F.sigmoid(x).numpy(),
                                   1 / (1 + np.exp([1.0, 0.0, -2.0])), rtol=1e-5)
        s = F.softmax(t(np.random.rand(3, 5))).numpy()
        np.testing.assert_allclose(s.sum(-1), np.ones(3), rtol=1e-5)

    def test_cross_entropy_loss(self):
        logits = np.random.randn(4, 5).astype(np.float32)
        labels = np.array([0, 1, 2, 3], np.int64)
        loss = nn.CrossEntropyLoss()(t(logits), paddle.to_tensor(labels))
        import scipy.special
        logp = scipy.special.log_softmax(logits, axis=1)
        ref = -logp[np.arange(4), labels].mean()
        assert float(loss) == pytest.approx(ref, rel=1e-4)

    def test_mse_bce(self):
        a, b = np.random.rand(3, 4), np.random.rand(3, 4)
        assert float(nn.MSELoss()(t(a), t(b))) == pytest.approx(
            ((a - b) ** 2).mean(), rel=1e-4)
        p = np.clip(np.random.rand(8), 0.01, 0.99)
        y = (np.random.rand(8) > 0.5).astype(np.float32)
        ref = -(y * np.log(p) + (1 - y) * np.log(1 - p)).mean()
        assert float(nn.BCELoss()(t(p), t(y))) == pytest.approx(ref, rel=1e-3)


class TestContainersState:
    def test_sequential_layerlist(self):
        seq = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        x = t(np.random.rand(3, 4))
        assert seq(x).shape == [3, 2]
        assert len(seq) == 3
        ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
        assert len(list(ll.parameters())) == 6

    def test_named_parameters_state_dict(self):
        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(4, 8)
                self.bn = nn.BatchNorm1D(8)
                self.fc2 = nn.Linear(8, 2)

            def forward(self, x):
                return self.fc2(self.bn(self.fc1(x)))

        net = Net()
        names = [n for n, _ in net.named_parameters()]
        assert "fc1.weight" in names and "bn.weight" in names
        sd = net.state_dict()
        assert "bn._mean" in sd  # persistable buffer
        net2 = Net()
        net2.set_state_dict(sd)
        np.testing.assert_array_equal(net2.fc1.weight.numpy(),
                                      net.fc1.weight.numpy())

    def test_train_eval_propagation(self):
        seq = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
        seq.eval()
        assert not seq[1].training
        seq.train()
        assert seq[1].training

    def test_save_load_roundtrip(self, tmp_path):
        net = nn.Linear(3, 3)
        path = str(tmp_path / "model.pdparams")
        paddle.save(net.state_dict(), path)
        loaded = paddle.load(path)
        net2 = nn.Linear(3, 3)
        net2.set_state_dict(loaded)
        np.testing.assert_array_equal(net.weight.numpy(), net2.weight.numpy())


class TestTransformer:
    def test_mha_shapes(self):
        mha = nn.MultiHeadAttention(16, 4)
        x = t(np.random.rand(2, 5, 16))
        assert mha(x, x, x).shape == [2, 5, 16]

    def test_encoder(self):
        layer = nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0)
        enc = nn.TransformerEncoder(layer, 2)
        x = t(np.random.rand(2, 5, 16))
        assert enc(x).shape == [2, 5, 16]

    def test_sdpa_causal(self):
        q = np.random.rand(1, 4, 2, 8).astype(np.float32)
        out = F.scaled_dot_product_attention(t(q), t(q), t(q), is_causal=True)
        assert out.shape == [1, 4, 2, 8]
        # first position attends only to itself -> equals v[0]
        np.testing.assert_allclose(out.numpy()[0, 0], q[0, 0], rtol=1e-4)


class TestRNN:
    def test_lstm_gru(self):
        lstm = nn.LSTM(4, 8, num_layers=1)
        x = t(np.random.rand(2, 5, 4))
        out, (h, c) = lstm(x)
        assert out.shape == [2, 5, 8]
        assert h.shape == [1, 2, 8]
        gru = nn.GRU(4, 8)
        out, h = gru(x)
        assert out.shape == [2, 5, 8]


class TestGradClip:
    def test_global_norm_clip(self):
        p = paddle.to_tensor(np.zeros(4, np.float32), stop_gradient=False)
        p.grad = paddle.to_tensor(np.full(4, 10.0, np.float32))
        clip = nn.ClipGradByGlobalNorm(1.0)
        out = clip([(p, p.grad)])
        norm = np.linalg.norm(out[0][1].numpy())
        assert norm == pytest.approx(1.0, rel=1e-4)
