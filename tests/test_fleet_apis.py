"""Fleet user-facing parallel APIs: PipelineLayer/1F1B train_batch,
group_sharded_parallel, meta-optimizer strategy flags.

Mirrors the reference's hybrid_parallel_pp_*.py / dygraph_group_sharded_*
suites: parallel wrappers must match the single-model golden run step by
step (SURVEY §4)."""
import numpy as np
import pytest

import jax

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.meta_parallel import (LayerDesc,
                                                        PipelineLayer,
                                                        PipelineParallel,
                                                        SharedLayerDesc)
from paddle_tpu.distributed.sharding import group_sharded_parallel


def _data(n=32, d=8, c=4, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, d).astype("float32")
    y = rng.randint(0, c, n)
    return paddle.to_tensor(x), paddle.to_tensor(y)


# ------------------------------------------------------------- PipelineLayer

def test_pipeline_layer_segmentation():
    descs = [LayerDesc(nn.Linear, 8, 8) for _ in range(6)]
    pl = PipelineLayer(descs, num_stages=3, loss_fn=nn.CrossEntropyLoss())
    assert pl.get_num_stages() == 3
    sizes = [len(pl.get_stage_layers(s)) for s in range(3)]
    assert sum(sizes) == 6 and sizes == [2, 2, 2]


def test_pipeline_layer_param_segmentation():
    descs = [LayerDesc(nn.Linear, 8, 8),       # small
             LayerDesc(nn.Linear, 8, 128),     # big
             LayerDesc(nn.Linear, 128, 8),     # big
             LayerDesc(nn.Linear, 8, 8)]       # small
    pl = PipelineLayer(descs, num_stages=2, seg_method="param")
    sizes = [len(pl.get_stage_layers(s)) for s in range(2)]
    assert sum(sizes) == 4
    assert all(s >= 1 for s in sizes)


def test_pipeline_shared_layer_is_same_object():
    descs = [
        SharedLayerDesc("embed", nn.Linear, None, "weight", 8, 8),
        LayerDesc(nn.Linear, 8, 8),
        SharedLayerDesc("embed", nn.Linear, None, "weight", 8, 8),
    ]
    pl = PipelineLayer(descs, num_stages=1)
    layers = pl.get_stage_layers(0)
    assert layers[0] is layers[2]      # tied weights by construction


def test_pipeline_train_batch_matches_serial():
    """PP micro-batching must be numerically identical to the plain model
    (reference: hybrid_parallel_pp_alexnet.py compares against single-rank)."""
    paddle.seed(7)
    descs = [LayerDesc(nn.Linear, 8, 32), LayerDesc(nn.ReLU),
             LayerDesc(nn.Linear, 32, 4)]
    pl = PipelineLayer(descs, num_stages=2, loss_fn=nn.CrossEntropyLoss())

    # golden: same weights, plain accumulate-free run
    golden = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))
    golden.set_state_dict({k.replace("seg_0.", "0.").replace("seg_2.", "2."): v
                           for k, v in pl.state_dict().items()})

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs["pp_degree"] = 2
    strategy.hybrid_configs["dp_degree"] = 4
    strategy.pipeline_configs["accumulate_steps"] = 4
    fleet.init(is_collective=True, strategy=strategy)
    model = fleet.distributed_model(pl)
    assert isinstance(model, PipelineParallel)

    o_pp = opt.SGD(0.1, parameters=pl.parameters())
    o_g = opt.SGD(0.1, parameters=golden.parameters())
    x, y = _data()
    loss_pp = model.train_batch((x, y), o_pp)

    lf = nn.CrossEntropyLoss()
    loss_g = lf(golden(x), y)
    loss_g.backward()
    o_g.step()
    o_g.clear_grad()

    np.testing.assert_allclose(float(loss_pp), float(loss_g), rtol=2e-5)
    w_pp = dict(pl.named_parameters())["seg_0.weight"].numpy()
    w_g = dict(golden.named_parameters())["0.weight"].numpy()
    np.testing.assert_allclose(w_pp, w_g, rtol=2e-5, atol=2e-6)


def test_compiled_pipeline_shards_params_per_stage():
    """Per-stage param ownership (VERDICT r2 weak #5): the compiled step's
    packed param buffer holds ~1/pp of the total on each device instead of
    replicating everything, and its gradients still match value_and_grad."""
    from jax.sharding import Mesh
    from paddle_tpu.distributed.fleet.meta_parallel.pp_compiled import \
        make_compiled_pipeline_step
    from paddle_tpu.nn.layer.layers import functional_state

    paddle.seed(11)
    descs = [LayerDesc(nn.Linear, 16, 64), LayerDesc(nn.ReLU),
             LayerDesc(nn.Linear, 64, 64), LayerDesc(nn.ReLU),
             LayerDesc(nn.Linear, 64, 64), LayerDesc(nn.ReLU),
             LayerDesc(nn.Linear, 64, 4)]
    pl = PipelineLayer(descs, num_stages=2, loss_fn=nn.CrossEntropyLoss(),
                       seg_method="param")
    mesh = Mesh(np.asarray(jax.devices()[:2]).reshape(2), ("pp",))
    step = make_compiled_pipeline_step(pl, mesh, microbatches=4)

    total = sum(int(np.prod(p.shape)) * 4 for _, p in pl.named_parameters())
    # per-device packed bytes ~ total/pp (max stage), far below replication
    assert step.packed_bytes_per_device < 0.75 * total, \
        (step.packed_bytes_per_device, total)
    assert step.replicated_param_bytes == 0   # no shared layers here

    # the packed operand really is sharded over pp: each device holds 1 row
    params, buffers = functional_state(pl)
    prow = step.pack(params)
    assert prow.shape[0] == 2
    assert len(prow.addressable_shards) == 2
    for s in prow.addressable_shards:
        assert s.data.shape[0] == 1          # one stage row per device

    # gradient parity vs plain value_and_grad on the same weights
    x, y = _data(n=16, d=16)
    loss, grads, _ = step(params, buffers, x._data, y._data)

    def ref_loss(p):
        from paddle_tpu.nn.layer.layers import functional_call
        out, _ = functional_call(pl, p, buffers, args=(x,), train=True)
        return (pl._loss_fn(out, y))._data

    ref_l, ref_g = jax.value_and_grad(ref_loss)(params)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=2e-5)
    for n in grads:
        np.testing.assert_allclose(np.asarray(grads[n]),
                                   np.asarray(ref_g[n]),
                                   rtol=2e-4, atol=2e-5, err_msg=n)


def test_compiled_pipeline_shared_layer_replicated():
    """SharedLayerDesc params (used by 2 stages) stay on the replicated +
    psum path and still receive both stages' grad contributions."""
    from jax.sharding import Mesh
    from paddle_tpu.distributed.fleet.meta_parallel.pp_compiled import \
        make_compiled_pipeline_step
    from paddle_tpu.nn.layer.layers import functional_state, functional_call

    paddle.seed(13)
    descs = [SharedLayerDesc("tied", nn.Linear, forward_func=None,
                             shared_weight_attr="weight",
                             in_features=8, out_features=8),
             LayerDesc(nn.ReLU),
             LayerDesc(nn.Linear, 8, 8), LayerDesc(nn.ReLU),
             SharedLayerDesc("tied", nn.Linear, forward_func=None,
                             shared_weight_attr="weight",
                             in_features=8, out_features=8)]
    pl = PipelineLayer(descs, num_stages=2, loss_fn=nn.MSELoss())
    mesh = Mesh(np.asarray(jax.devices()[:2]).reshape(2), ("pp",))
    step = make_compiled_pipeline_step(pl, mesh, microbatches=2)
    assert step.replicated_param_bytes > 0

    params, buffers = functional_state(pl)
    rng = np.random.RandomState(3)
    x = paddle.to_tensor(rng.rand(8, 8).astype("float32"))
    y = paddle.to_tensor(rng.rand(8, 8).astype("float32"))
    loss, grads, _ = step(params, buffers, x._data, y._data)

    def ref_loss(p):
        out, _ = functional_call(pl, p, buffers, args=(x,), train=True)
        return (pl._loss_fn(out, y))._data

    ref_l, ref_g = jax.value_and_grad(ref_loss)(params)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=2e-5)
    for n in grads:
        np.testing.assert_allclose(np.asarray(grads[n]),
                                   np.asarray(ref_g[n]),
                                   rtol=2e-4, atol=2e-5, err_msg=n)


def test_pipeline_eval_batch():
    descs = [LayerDesc(nn.Linear, 8, 4)]
    pl = PipelineLayer(descs, num_stages=1, loss_fn=nn.CrossEntropyLoss())
    pp = PipelineParallel(pl)
    x, y = _data()
    l = pp.eval_batch((x, y))
    assert np.isfinite(float(l))


# ------------------------------------------------------- group_sharded (ZeRO)

def _sharding_mesh():
    from paddle_tpu.distributed.env import build_mesh
    return build_mesh({"dp": 2, "sharding": 4})


def test_group_sharded_stage3_shards_params():
    _sharding_mesh()
    net = nn.Sequential(nn.Linear(8, 64), nn.ReLU(), nn.Linear(64, 4))
    o = opt.Adam(1e-3, parameters=net.parameters())
    net, o, _ = group_sharded_parallel(net, o, "p_g_os")
    w = net[0].weight
    # the 64-dim is divisible by sharding=4: the param must live sharded
    assert "sharding" in str(w._data.sharding.spec)
    # training still works on sharded params
    x, y = _data()
    l = nn.CrossEntropyLoss()(net(x), y)
    l.backward()
    o.step()
    o.clear_grad()
    assert np.isfinite(float(l))


def test_group_sharded_stage2_shards_opt_state():
    _sharding_mesh()
    net = nn.Linear(8, 64)
    base = opt.Adam(1e-3, parameters=net.parameters())
    net, o, _ = group_sharded_parallel(net, base, "os_g")
    params = {n: p._data for n, p in net.named_parameters()}
    st = o.functional_state(params)
    m1 = st["weight"]["moment1"]
    assert "sharding" in str(m1.sharding.spec)
    # params stay replicated at stage 2 (plain single/replicated placement)
    assert "sharding" not in str(getattr(net.weight._data.sharding, "spec", ""))


def test_group_sharded_bad_level():
    net = nn.Linear(4, 4)
    with pytest.raises(ValueError):
        group_sharded_parallel(net, opt.SGD(parameters=net.parameters()),
                               "stage9")


# ------------------------------------------------------- meta-optimizer flags

def test_strategy_lars_substitution():
    strategy = fleet.DistributedStrategy()
    strategy.lars = True
    fleet.init(is_collective=True, strategy=strategy)
    net = nn.Linear(8, 4)
    o = fleet.distributed_optimizer(
        opt.Momentum(0.1, parameters=net.parameters()), strategy)
    from paddle_tpu.optimizer import LarsMomentum
    assert isinstance(o._inner_opt, LarsMomentum)
    x, y = _data()
    l = nn.CrossEntropyLoss()(net(x), y)
    l.backward()
    o.step()
    o.clear_grad()


def test_gradient_merge_minimize_not_bypassed():
    strategy = fleet.DistributedStrategy()
    strategy.gradient_merge = True
    strategy.gradient_merge_configs = {"k_steps": 2, "avg": True}
    fleet.init(is_collective=True, strategy=strategy)
    net = nn.Linear(8, 4)
    w0 = net.weight.numpy().copy()
    o = fleet.distributed_optimizer(
        opt.SGD(0.1, parameters=net.parameters()), strategy)
    x, y = _data()
    # minimize() must respect the merge window (first call: no update)
    o.minimize(nn.CrossEntropyLoss()(net(x), y))
    np.testing.assert_array_equal(net.weight.numpy(), w0)
    o.minimize(nn.CrossEntropyLoss()(net(x), y))
    assert not np.allclose(net.weight.numpy(), w0)


def test_strategy_gradient_merge():
    strategy = fleet.DistributedStrategy()
    strategy.gradient_merge = True
    strategy.gradient_merge_configs = {"k_steps": 2, "avg": True}
    fleet.init(is_collective=True, strategy=strategy)
    paddle.seed(3)
    net = nn.Linear(8, 4)
    w0 = net.weight.numpy().copy()
    o = fleet.distributed_optimizer(
        opt.SGD(0.1, parameters=net.parameters()), strategy)
    x, y = _data()
    lf = nn.CrossEntropyLoss()
    # first step: accumulate only, no update
    lf(net(x), y).backward()
    o.step()
    o.clear_grad()
    np.testing.assert_array_equal(net.weight.numpy(), w0)
    # second step: merged update fires
    lf(net(x), y).backward()
    o.step()
    o.clear_grad()
    assert not np.allclose(net.weight.numpy(), w0)
