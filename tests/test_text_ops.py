"""viterbi_decode, ctc_greedy_decoder, and the new NLL losses
(reference: python/paddle/text/viterbi_decode.py, fluid/layers/nn.py:5619,
nn/functional/loss.py)."""
import itertools

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def _brute_viterbi(pot, trans, length, bos_eos):
    """Enumerate all tag paths of the live prefix (numpy golden)."""
    T, N = pot.shape
    L = int(length)
    n_real = N
    best, best_path = -1e30, None
    for path in itertools.product(range(n_real), repeat=L):
        s = pot[0, path[0]]
        if bos_eos:
            s += trans[N - 1, path[0]]
        for t in range(1, L):
            s += trans[path[t - 1], path[t]] + pot[t, path[t]]
        if bos_eos:
            # kernel adds the stop ROW over tags (viterbi_decode_kernel.cc:249
            # stop_trans = trans[N-2, :] added elementwise to alpha)
            s += trans[N - 2, path[L - 1]]
        if s > best:
            best, best_path = s, path
    return best, list(best_path) + [0] * (T - L)


def test_viterbi_matches_bruteforce():
    rng = np.random.RandomState(3)
    B, T, N = 3, 4, 3
    pot = rng.rand(B, T, N).astype("float32")
    trans = rng.rand(N, N).astype("float32")
    lens = np.array([4, 2, 3], "int64")
    for bos_eos in (False, True):
        scores, paths = paddle.text.viterbi_decode(
            paddle.to_tensor(pot), paddle.to_tensor(trans),
            paddle.to_tensor(lens), include_bos_eos_tag=bos_eos)
        for b in range(B):
            gs, gp = _brute_viterbi(pot[b], trans, lens[b], bos_eos)
            np.testing.assert_allclose(float(scores.numpy()[b]), gs,
                                       rtol=1e-5)
            assert paths.numpy()[b].tolist() == gp, (b, bos_eos)


def test_viterbi_decoder_layer():
    rng = np.random.RandomState(0)
    pot = paddle.to_tensor(rng.rand(2, 5, 4).astype("float32"))
    trans = paddle.to_tensor(rng.rand(4, 4).astype("float32"))
    lens = paddle.to_tensor(np.array([5, 3], "int64"))
    dec = paddle.text.ViterbiDecoder(trans)
    scores, paths = dec(pot, lens)
    assert tuple(paths.shape) == (2, 5)
    assert paths.numpy()[1, 3:].tolist() == [0, 0]


def test_ctc_greedy_decoder():
    # classes: 0..3, blank=3; batch of 2
    probs = np.zeros((2, 6, 4), "float32")
    seq0 = [0, 0, 3, 1, 1, 2]       # -> merge -> 0 3 1 2 -> drop blank -> 0 1 2
    seq1 = [3, 2, 2, 3, 2, 3]       # -> 3 2 3 2 3 -> 2 2
    for t, c in enumerate(seq0):
        probs[0, t, c] = 1.0
    for t, c in enumerate(seq1):
        probs[1, t, c] = 1.0
    dec, lens = F.ctc_greedy_decoder(paddle.to_tensor(probs), blank=3,
                                     padding_value=-1)
    assert lens.numpy().ravel().tolist() == [3, 2]
    assert dec.numpy()[0, :3].tolist() == [0, 1, 2]
    assert dec.numpy()[1, :2].tolist() == [2, 2]
    assert (dec.numpy()[0, 3:] == -1).all()

    # input_length truncates
    dec2, lens2 = F.ctc_greedy_decoder(
        paddle.to_tensor(probs), blank=3,
        input_length=paddle.to_tensor(np.array([[2], [6]], "int64")))
    assert lens2.numpy().ravel().tolist() == [1, 2]


def test_poisson_and_gaussian_nll():
    x = paddle.to_tensor(np.array([0.5, 1.0], "float32"))
    y = paddle.to_tensor(np.array([1.0, 2.0], "float32"))
    out = F.poisson_nll_loss(x, y, reduction="none")
    np.testing.assert_allclose(
        out.numpy(), np.exp([0.5, 1.0]) - [0.5, 2.0], rtol=1e-6)

    var = paddle.to_tensor(np.array([0.5, 2.0], "float32"))
    out = F.gaussian_nll_loss(x, y, var, reduction="none")
    np.testing.assert_allclose(
        out.numpy(),
        0.5 * (np.log([0.5, 2.0]) + np.square([0.5 - 1.0, 1.0 - 2.0]) /
               np.array([0.5, 2.0])), rtol=1e-6)


def test_teacher_student_sigmoid_loss():
    x_np = np.array([[0.3], [-0.2], [1.0], [0.5]], "float32")
    # labels: -2 (no teacher, no click), -1 (no teacher, click),
    #         0.7 (teacher 0.7, no click), 1.4 (teacher 0.4, click)
    lab_np = np.array([[-2.0], [-1.0], [0.7], [1.4]], "float32")
    out = F.teacher_student_sigmoid_loss(
        paddle.to_tensor(x_np), paddle.to_tensor(lab_np))

    def sp(x, z):
        return max(x, 0) - x * z + np.log1p(np.exp(-abs(x)))

    exp = [sp(0.3, 0.0),
           sp(-0.2, 1.0),
           sp(1.0, 0.0) + sp(1.0, 0.7),
           sp(0.5, 1.0) + sp(0.5, 0.4)]
    np.testing.assert_allclose(out.numpy().ravel(), exp, rtol=1e-5)
