import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from op_test import check_grad


class TestBackward:
    def test_simple_chain(self):
        x = paddle.to_tensor(np.array([2.0, 3.0], np.float32), stop_gradient=False)
        y = (x * x).sum()
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0], rtol=1e-6)

    def test_grad_accumulation(self):
        x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
        (x * 2).sum().backward()
        (x * 3).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0] * 3, rtol=1e-6)
        x.clear_grad()
        assert x.grad is None

    def test_broadcast_grad(self):
        check_grad(lambda a, b: a + b,
                   [np.random.rand(3, 4), np.random.rand(4)])
        check_grad(lambda a, b: a * b,
                   [np.random.rand(2, 1, 4), np.random.rand(3, 1)])

    def test_matmul_grad(self):
        check_grad(paddle.matmul, [np.random.rand(3, 4), np.random.rand(4, 2)])

    def test_nonlinear_grads(self):
        check_grad(paddle.tanh, [np.random.rand(3, 3) * 0.5])
        check_grad(paddle.exp, [np.random.rand(3, 3) * 0.5])
        check_grad(lambda x: F.softmax(x, -1), [np.random.randn(2, 5) * 0.5])
        check_grad(lambda x: F.gelu(x), [np.random.randn(3, 3) * 0.5], rtol=2e-2)

    def test_reduction_grads(self):
        check_grad(lambda x: paddle.mean(x, axis=0), [np.random.rand(3, 4)])
        check_grad(lambda x: paddle.sum(x * x, axis=1), [np.random.rand(3, 4)])

    def test_indexing_grad(self):
        x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3),
                             stop_gradient=False)
        x[0].sum().backward()
        np.testing.assert_allclose(x.grad.numpy(),
                                   [[1, 1, 1], [0, 0, 0]], rtol=1e-6)

    def test_stop_gradient(self):
        x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
        y = paddle.to_tensor(np.ones(3, np.float32))  # stopped
        (x * y).sum().backward()
        assert x.grad is not None
        assert y.grad is None

    def test_detach(self):
        x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
        y = (x * 2).detach()
        assert y.stop_gradient
        z = x.detach() * 3
        assert z.stop_gradient

    def test_no_grad(self):
        x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
        with paddle.no_grad():
            y = (x * 2).sum()
        assert y._node is None

    def test_multi_output_op(self):
        a = np.random.rand(3, 4).astype(np.float32)
        x = paddle.to_tensor(a, stop_gradient=False)
        vals, idx = paddle.topk(x, 2, axis=1)
        vals.sum().backward()
        g = x.grad.numpy()
        assert g.sum() == pytest.approx(6.0)

    def test_shared_subexpression(self):
        x = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
        h = x * x          # used twice
        y = (h + h).sum()
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [8.0], rtol=1e-6)

    def test_backward_nonscalar_with_grad(self):
        x = paddle.to_tensor(np.ones((2, 2), np.float32), stop_gradient=False)
        y = x * 3
        y.backward(paddle.to_tensor(np.full((2, 2), 2.0, np.float32)))
        np.testing.assert_allclose(x.grad.numpy(), np.full((2, 2), 6.0), rtol=1e-6)


class TestPaddleGrad:
    def test_grad_api(self):
        x = paddle.to_tensor(np.array([3.0], np.float32), stop_gradient=False)
        y = x * x
        (gx,) = paddle.grad(y, x)
        np.testing.assert_allclose(gx.numpy(), [6.0], rtol=1e-6)
        # .grad untouched
        assert x.grad is None


class TestPyLayer:
    def test_custom_fn(self):
        from paddle_tpu.autograd import PyLayer

        class Double(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * 2

            @staticmethod
            def backward(ctx, dy):
                return dy * 2

        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32), stop_gradient=False)
        y = Double.apply(x)
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0], rtol=1e-6)


class TestFunctionalGrads:
    def test_conv2d_grad(self):
        check_grad(lambda x, w: F.conv2d(x, w, stride=1, padding=1),
                   [np.random.rand(1, 2, 5, 5), np.random.rand(3, 2, 3, 3)],
                   rtol=2e-2, atol=2e-3)

    def test_layer_norm_grad(self):
        check_grad(lambda x, w, b: F.layer_norm(x, 4, w, b),
                   [np.random.rand(3, 4), np.random.rand(4), np.random.rand(4)],
                   rtol=2e-2, atol=2e-3)

    def test_cross_entropy_grad(self):
        logits = np.random.randn(4, 5).astype(np.float32)
        labels = np.array([0, 2, 1, 4], np.int64)
        x = paddle.to_tensor(logits, stop_gradient=False)
        loss = F.cross_entropy(x, paddle.to_tensor(labels))
        loss.backward()
        # analytic: softmax - onehot, / N
        import scipy.special
        p = scipy.special.softmax(logits, axis=1)
        onehot = np.eye(5)[labels]
        np.testing.assert_allclose(x.grad.numpy(), (p - onehot) / 4,
                                   rtol=1e-4, atol=1e-5)


class TestHooksAndDoubleGrad:
    """register_hook + create_graph double grad (VERDICT r1 item 9;
    reference: imperative/hooks.h, eager/general_grad.h)."""

    def test_register_hook_scales_grad(self):
        x = paddle.to_tensor(np.array([1., 2., 3.], np.float32),
                             stop_gradient=False)
        seen = []
        h = x.register_hook(lambda g: seen.append(g.numpy()) or g * 2)
        (x * x).sum().backward()
        np.testing.assert_allclose(seen[0], [2., 4., 6.])
        np.testing.assert_allclose(x.grad.numpy(), [4., 8., 12.])
        h.remove()
        x.clear_grad()
        (x * x).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [2., 4., 6.])

    def test_hook_on_intermediate(self):
        x = paddle.to_tensor(np.array([2.0], np.float32),
                             stop_gradient=False)
        y = x * 3.0
        y.register_hook(lambda g: g * 10.0)
        (y * y).backward()          # dy = 2y = 12 -> hook -> 120 -> dx = 360
        np.testing.assert_allclose(x.grad.numpy(), [360.])

    def test_hook_on_stop_gradient_raises(self):
        x = paddle.to_tensor(np.ones(3, np.float32))
        with pytest.raises(RuntimeError):
            x.register_hook(lambda g: g)

    def test_double_grad_gradient_penalty(self):
        import paddle_tpu.autograd as pag
        x = paddle.to_tensor(np.array([2.0], np.float32),
                             stop_gradient=False)
        y = x * x * x
        (g,) = pag.grad(y, x, create_graph=True)
        np.testing.assert_allclose(g.numpy(), [12.])
        (g * g).sum().backward()    # d/dx 9x^4 = 36 x^3
        np.testing.assert_allclose(x.grad.numpy(), [288.])

    def test_double_grad_matmul_matches_jax(self):
        import jax
        import paddle_tpu.autograd as pag
        rng = np.random.RandomState(0)
        xv = rng.rand(3, 4).astype("float32")
        Wv = rng.rand(4, 2).astype("float32")
        x = paddle.to_tensor(xv, stop_gradient=False)
        W = paddle.to_tensor(Wv, stop_gradient=False)
        (gx,) = pag.grad(((x @ W) ** 2).sum(), x, create_graph=True)
        (gx ** 2).sum().backward()
        gfn = jax.grad(lambda xx: ((xx @ Wv) ** 2).sum())
        pfn = jax.grad(lambda xx: (gfn(xx) ** 2).sum())
        np.testing.assert_allclose(x.grad.numpy(), np.asarray(pfn(xv)),
                                   rtol=1e-4)

    def test_grad_no_create_graph_side_effect_free(self):
        import paddle_tpu.autograd as pag
        x = paddle.to_tensor(np.array([3.0], np.float32),
                             stop_gradient=False)
        (g,) = pag.grad(x * x, x)
        np.testing.assert_allclose(g.numpy(), [6.])
        assert x.grad is None
