"""Round-5 fixes: ADVICE r4 items + the int64 numpy-boundary guard
(VERDICT r4 weak #8 / next #8)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


# ---- ADVICE r4 #1: interpolate argument validation
def test_interpolate_requires_size_or_scale():
    x = paddle.to_tensor(np.random.rand(1, 3, 8, 8).astype("float32"))
    with pytest.raises(ValueError, match="size or scale_factor"):
        paddle.nn.functional.interpolate(x)


def test_interpolate_mode_rank_mismatch():
    x5 = paddle.to_tensor(np.random.rand(1, 3, 4, 8, 8).astype("float32"))
    with pytest.raises(ValueError, match="bilinear"):
        paddle.nn.functional.interpolate(x5, size=[2, 4, 4], mode="bilinear")
    x3 = paddle.to_tensor(np.random.rand(1, 3, 8).astype("float32"))
    with pytest.raises(ValueError, match="trilinear"):
        paddle.nn.functional.interpolate(x3, size=4, mode="trilinear")
    # valid combos still work
    out = paddle.nn.functional.interpolate(x5, size=[2, 4, 4],
                                           mode="trilinear")
    assert tuple(out.shape) == (1, 3, 2, 4, 4)


# ---- ADVICE r4 #2: zero-length rows keep their initial state
def test_rnn_zero_length_holds_initial_state():
    paddle.seed(3)
    cell = nn.GRUCell(4, 5)
    rnn = nn.RNN(cell)
    x = paddle.to_tensor(np.random.RandomState(0)
                         .rand(3, 6, 4).astype("float32"))
    init = paddle.to_tensor(np.random.RandomState(1)
                            .rand(3, 5).astype("float32"))
    seq_len = paddle.to_tensor(np.asarray([6, 0, 3], np.int32))
    out, final = rnn(x, initial_states=init, sequence_length=seq_len)
    # row 1 has length 0: final state must equal its initial state
    np.testing.assert_allclose(final.numpy()[1], init.numpy()[1], rtol=1e-6)
    # and its outputs are all zeros
    np.testing.assert_allclose(out.numpy()[1], np.zeros((6, 5)), atol=0)


def test_rnn_zero_length_no_initial_state_zero():
    paddle.seed(4)
    cell = nn.GRUCell(4, 5)
    rnn = nn.RNN(cell)
    x = paddle.to_tensor(np.random.RandomState(2)
                         .rand(2, 4, 4).astype("float32"))
    seq_len = paddle.to_tensor(np.asarray([4, 0], np.int32))
    _, final = rnn(x, sequence_length=seq_len)
    # default initial state is zeros: the zero-length row holds zeros
    np.testing.assert_allclose(final.numpy()[1], np.zeros(5), atol=0)


# ---- ADVICE r4 #3: tuner fallbacks never persist to the disk cache
def test_autotune_fallback_not_persisted(tmp_path, monkeypatch):
    from paddle_tpu.incubate import autotune as at

    cache = str(tmp_path / "blocks.json")
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_CACHE", cache)
    at.record_flash_blocks(8, 1024, 64, True, (256, 256), persist=False)
    import os
    assert not os.path.exists(cache)       # in-memory only
    # measured winners DO persist
    at.record_flash_blocks(8, 2048, 64, True, (512, 512), persist=True)
    assert os.path.exists(cache)
    import json
    data = json.load(open(cache))
    keys = [tuple(json.loads(k)) for k in data]
    assert all(k[2] != 1024 for k in keys)   # fallback geometry absent


# ---- int64 numpy-boundary escape hatch
def test_numpy_force_int64():
    t = paddle.to_tensor(np.asarray([1, 2, 3], np.int64))
    assert t.numpy().dtype == np.int32              # documented device policy
    assert t.numpy(force_int64=True).dtype == np.int64
    paddle.set_flags({"FLAGS_int64_numpy_boundary": True})
    try:
        assert t.numpy().dtype == np.int64
    finally:
        paddle.set_flags({"FLAGS_int64_numpy_boundary": False})
    # floats untouched by the flag
    f = paddle.to_tensor(np.asarray([1.0], np.float32))
    assert f.numpy(force_int64=True).dtype == np.float32


def test_checkpoint_roundtrip_reference_int64_state(tmp_path):
    """A reference-written state_dict holding int64 arrays loads, applies,
    and round-trips; the boundary guard recovers int64 for type-checking
    consumers."""
    import pickle

    ref_state = {"steps": np.asarray([100], np.int64),
                 "emb": np.random.RandomState(0).rand(4, 3).astype("float32")}
    p = str(tmp_path / "ref_state.pkl")
    with open(p, "wb") as f:
        pickle.dump(ref_state, f)

    with open(p, "rb") as f:
        loaded = pickle.load(f)
    t = paddle.to_tensor(loaded["steps"])
    assert "int32" in str(t.dtype)                  # canonicalized on device
    back = t.numpy(force_int64=True)
    assert back.dtype == np.int64 and back[0] == 100
    # paddle.save/load round-trip preserves the recovered int64 payload
    paddle.save({"steps": back}, str(tmp_path / "rt.pdparams"))
    rt = paddle.load(str(tmp_path / "rt.pdparams"), return_numpy=True)
    assert rt["steps"].dtype == np.int64 and rt["steps"][0] == 100
