"""Cross-host PS transport (VERDICT r2 missing #5): keys actually move
between processes. Reference: brpc_ps_client/server request flow.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

from paddle_tpu.distributed.ps import (AsyncCommunicator,
                                       DistributedSparseTable, PSClient,
                                       PSServer, SparseEmbedding,
                                       SparseTable, shard_for)

DIM = 8


@pytest.fixture
def two_shard_cluster():
    """Two in-process servers (separate tables = separate 'hosts')."""
    servers = [PSServer(SparseTable(DIM, rule="sgd", lr=1.0, seed=s))
               for s in range(2)]
    client = PSClient([s.endpoint for s in servers], DIM)
    yield servers, client
    client.close()
    for s in servers:
        s.shutdown()


def test_pull_routes_by_shard(two_shard_cluster):
    servers, client = two_shard_cluster
    keys = np.array([0, 1, 2, 3, 10, 11], np.int64)
    vals = client.pull(keys)
    assert vals.shape == (6, DIM)
    # routing: even keys live on server 0, odd on server 1 (key % 2)
    own = shard_for(keys, 2)
    for i, k in enumerate(keys):
        local = servers[own[i]].table.pull(np.array([k]))
        np.testing.assert_allclose(vals[i], local[0])
    # and the other server must NOT hold the row's value
    assert not np.allclose(vals[0],
                           servers[1].table.pull(np.array([0]))[0])


def test_push_updates_remote_table(two_shard_cluster):
    servers, client = two_shard_cluster
    keys = np.array([4, 5], np.int64)
    before = client.pull(keys)
    grads = np.ones((2, DIM), np.float32)
    client.push(keys, grads)
    after = client.pull(keys)
    # sgd rule with lr=1.0: value decreases by exactly the grad
    np.testing.assert_allclose(after, before - 1.0, rtol=1e-5)


def test_sparse_embedding_over_distributed_table(two_shard_cluster):
    _, client = two_shard_cluster
    dtable = DistributedSparseTable.__new__(DistributedSparseTable)
    dtable.dim = DIM
    dtable.client = client
    emb = SparseEmbedding(DIM, table=dtable)
    import paddle_tpu as paddle
    ids = paddle.to_tensor(np.array([[1, 2], [3, 4]]))
    out = emb(ids)
    assert list(out.shape) == [2, 2, DIM]


def test_async_communicator_over_rpc(two_shard_cluster):
    _, client = two_shard_cluster
    dtable = DistributedSparseTable.__new__(DistributedSparseTable)
    dtable.dim = DIM
    dtable.client = client
    keys = np.array([20, 21], np.int64)
    before = client.pull(keys)
    comm = AsyncCommunicator(dtable, merge_batches=2)
    comm.start()
    comm.push_sparse(keys, np.ones((2, DIM), np.float32))
    comm.push_sparse(keys, np.ones((2, DIM), np.float32))
    comm.flush()
    comm.stop()
    after = client.pull(keys)
    np.testing.assert_allclose(after, before - 2.0, rtol=1e-5)


SERVER_SCRIPT = r"""
import sys
sys.path.insert(0, sys.argv[2])
from paddle_tpu.distributed.ps import PSServer, SparseTable
srv = PSServer(SparseTable(8, rule="sgd", lr=1.0, seed=7), port=0)
with open(sys.argv[1], "w") as f:
    f.write(srv.endpoint)
import time
while not srv._stop.is_set():
    time.sleep(0.1)
"""


def test_true_cross_process_pull_push(tmp_path):
    """The server lives in a DIFFERENT process: bytes really cross a
    process boundary through the socket."""
    ep_file = str(tmp_path / "ep.txt")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen([sys.executable, "-c", SERVER_SCRIPT, ep_file,
                             repo], env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    try:
        import time
        for _ in range(100):
            if os.path.exists(ep_file) and open(ep_file).read().strip():
                break
            time.sleep(0.1)
        endpoint = open(ep_file).read().strip()
        client = PSClient([endpoint], DIM)
        assert client.ping()
        keys = np.array([100, 200, 300], np.int64)
        v0 = client.pull(keys)
        client.push(keys, np.full((3, DIM), 0.5, np.float32))
        v1 = client.pull(keys)
        np.testing.assert_allclose(v1, v0 - 0.5, rtol=1e-5)
        client.stop_servers()
        client.close()
        proc.wait(timeout=10)
    finally:
        if proc.poll() is None:
            proc.kill()
