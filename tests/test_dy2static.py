"""dy2static: unmodified Paddle-style Python with tensor-dependent control
flow compiles under @to_static.

Reference suites: test_ifelse_basic.py / test_loop.py /
test_break_continue.py / test_logical.py under
python/paddle/fluid/tests/unittests/dygraph_to_static/ — same behavioral
contract, lowered to lax.cond / while_loop / scan instead of ProgramDesc
ConditionalBlock / While ops.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.jit import to_static
from paddle_tpu.jit.dy2static import Dy2StaticError, convert_to_static


def _t(a, dtype="float32"):
    return paddle.to_tensor(np.asarray(a, dtype=dtype))


# ------------------------------------------------------------------ if/else
def test_tensor_if_else():
    def fn(x):
        if x.mean() > 0:
            y = x + 1
        else:
            y = x - 1
        return y

    st = to_static(fn)
    xp = _t([1.0, 2.0])
    xn = _t([-1.0, -2.0])
    np.testing.assert_allclose(st(xp).numpy(), xp.numpy() + 1)
    np.testing.assert_allclose(st(xn).numpy(), xn.numpy() - 1)


def test_tensor_if_no_else():
    def fn(x):
        y = x * 2
        if x.sum() > 100:
            y = y + 100
        return y

    st = to_static(fn)
    x = _t([1.0, 2.0])
    np.testing.assert_allclose(st(x).numpy(), [2.0, 4.0])
    big = _t([200.0, 1.0])
    np.testing.assert_allclose(st(big).numpy(), [500.0, 102.0])


def test_nested_tensor_if():
    def fn(x):
        if x.mean() > 0:
            if x.max() > 10:
                y = x * 3
            else:
                y = x * 2
        else:
            y = -x
        return y

    st = to_static(fn)
    np.testing.assert_allclose(st(_t([20.0])).numpy(), [60.0])
    np.testing.assert_allclose(st(_t([1.0])).numpy(), [2.0])
    np.testing.assert_allclose(st(_t([-3.0])).numpy(), [3.0])


def test_if_both_branches_return():
    def fn(x):
        if x.sum() > 0:
            return x * 10
        else:
            return x * -1

    st = to_static(fn)
    np.testing.assert_allclose(st(_t([2.0])).numpy(), [20.0])
    np.testing.assert_allclose(st(_t([-2.0])).numpy(), [2.0])


def test_if_branch_mismatch_raises():
    def fn(x):
        if x.sum() > 0:
            y = x + 1          # y undefined on the false path
        return y

    st = to_static(fn)
    with pytest.raises(Dy2StaticError):
        st(_t([1.0]))


def test_python_bool_if_stays_eager():
    calls = []

    def fn(x, flag=True):
        if flag:                    # python bool: plain python branch
            calls.append("t")
            y = x + 1
        else:
            calls.append("f")
            y = x - 1
        return y

    out = convert_to_static(fn)(_t([1.0]))
    np.testing.assert_allclose(out.numpy(), [2.0])
    assert calls == ["t"]           # false branch never executed


# ------------------------------------------------------------------ logical
def test_logical_and_or_not():
    def fn(x):
        if x.mean() > 0 and x.max() < 10:
            y = x + 1
        elif not (x.min() > -5):
            y = x - 1
        else:
            y = x * 0
        return y

    st = to_static(fn)
    np.testing.assert_allclose(st(_t([1.0])).numpy(), [2.0])    # and-true
    np.testing.assert_allclose(st(_t([-9.0])).numpy(), [-10.0])  # not-branch
    np.testing.assert_allclose(st(_t([-1.0])).numpy(), [0.0])   # else


def test_short_circuit_preserved_for_python_values():
    def fn(x, lst=None):
        if lst is not None and len(lst) > 0:
            return x + 1
        return x

    # lst is None: the rhs (len(None)) must never evaluate
    out = convert_to_static(fn)(_t([1.0]))
    np.testing.assert_allclose(out.numpy(), [1.0])


# ------------------------------------------------------------------- while
def test_tensor_while():
    def fn(x):
        while x.sum() < 100:
            x = x * 2
        return x

    st = to_static(fn)
    got = st(_t([3.0])).numpy()
    want = np.array([3.0])
    while want.sum() < 100:
        want = want * 2
    np.testing.assert_allclose(got, want)


def test_while_multi_carry():
    def fn(x):
        i = paddle.to_tensor(np.int32(0))
        s = paddle.zeros_like(x)
        while i < 5:
            s = s + x * i.astype("float32")
            i = i + 1
        return s

    st = to_static(fn)
    np.testing.assert_allclose(st(_t([1.0, 2.0])).numpy(),
                               [10.0, 20.0])   # (0+1+2+3+4)


def test_while_with_break():
    def fn(x):
        i = paddle.to_tensor(np.int32(0))
        while i < 100:
            x = x + 1
            if x.sum() > 10:
                break
            i = i + 1
        return x

    st = to_static(fn)
    got = st(_t([0.0])).numpy()
    np.testing.assert_allclose(got, [11.0])


def test_while_shape_change_raises():
    def fn(x):
        while x.sum() < 100:
            x = paddle.concat([x, x])
        return x

    st = to_static(fn)
    with pytest.raises(Dy2StaticError):
        st(_t([3.0]))


# --------------------------------------------------------------------- for
def test_for_python_range_unrolls():
    def fn(x):
        s = paddle.zeros_like(x)
        for i in range(4):
            s = s + x * float(i)
        return s

    st = to_static(fn)
    np.testing.assert_allclose(st(_t([1.0])).numpy(), [6.0])


def test_for_traced_range():
    def fn(x, n):
        s = paddle.zeros_like(x)
        for i in range(n):
            s = s + x + i.astype("float32")
        return s

    st = to_static(fn)
    got = st(_t([10.0]), paddle.to_tensor(np.int32(3))).numpy()
    np.testing.assert_allclose(got, [33.0])    # 3*10 + (0+1+2)


def test_for_over_tensor_rows():
    def fn(xs):
        s = paddle.zeros([2])
        for row in xs:
            s = s + row
        return s

    st = to_static(fn)
    xs = _t([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])
    np.testing.assert_allclose(st(xs).numpy(), [9.0, 12.0])


def test_for_with_continue():
    def fn(xs):
        s = paddle.zeros([])
        for row in xs:
            if row.sum() < 0:
                continue
            s = s + row.sum()
        return s

    st = to_static(fn)
    xs = _t([[1.0], [-5.0], [3.0]])
    np.testing.assert_allclose(st(xs).numpy(), 4.0)


def test_for_with_break():
    def fn(xs):
        s = paddle.zeros([])
        for row in xs:
            if s > 3:
                break
            s = s + row.sum()
        return s

    st = to_static(fn)
    xs = _t([[1.0], [3.0], [100.0]])
    np.testing.assert_allclose(st(xs).numpy(), 4.0)


# ------------------------------------------------------------ early returns
# (reference: return_transformer.py — the __return__ flag + value ride the
# same carry machinery as break/continue)
def test_early_return_in_if():
    def fn(x):
        if x.sum() > 0:
            return x * 10
        return x - 1

    st = to_static(fn)
    np.testing.assert_allclose(st(_t([2.0])).numpy(), [20.0])
    np.testing.assert_allclose(st(_t([-2.0])).numpy(), [-3.0])


def test_early_return_in_while_loop():
    def fn(x):
        while x.sum() < 100:
            x = x * 2
            if x.sum() > 50:
                return x
        return x

    st = to_static(fn)
    # 3 -> 6 -> 12 -> 24 -> 48 -> 96: the in-loop return fires at 96
    np.testing.assert_allclose(st(_t([3.0])).numpy(), [96.0])
    # 60: one doubling then the return path
    np.testing.assert_allclose(st(_t([60.0])).numpy(), [120.0])


def test_early_return_in_for_loop():
    def fn(x):
        s = x * 0
        for _ in range(10):
            s = s + x
            if s.sum() > 5:
                return s
        return s - 1

    st = to_static(fn)
    np.testing.assert_allclose(st(_t([2.0])).numpy(), [6.0])
    # never crosses the threshold: falls through to the trailing return
    np.testing.assert_allclose(st(_t([0.1])).numpy(), [0.0], atol=1e-6)


def test_early_return_statements_after_skipped():
    def fn(x):
        if x.sum() > 0:
            return x + 100
        x = x * 2          # must not run on the returning path
        return x

    st = to_static(fn)
    np.testing.assert_allclose(st(_t([1.0])).numpy(), [101.0])
    np.testing.assert_allclose(st(_t([-1.0])).numpy(), [-2.0])


# --------------------------------------------------- clear unsupported errors
def test_list_append_in_traced_loop_clear_error():
    def fn(x):
        out = []
        while x.sum() < 100:
            x = x * 2
            out.append(x)
        return x

    st = to_static(fn)
    with pytest.raises(Dy2StaticError, match="list mutation"):
        st(_t([3.0]))


def test_list_append_in_unrolled_loop_still_works():
    def fn(x):
        out = []
        for i in range(3):
            out.append(x * i)
        return out[0] + out[1] + out[2]

    st = to_static(fn)
    np.testing.assert_allclose(st(_t([1.0])).numpy(), [3.0])


# ------------------------------------------------------------- convert_call
def test_helper_function_transformed_recursively():
    def helper(v):
        if v.mean() > 0:
            return v * 2
        else:
            return v * -3

    def fn(x):
        return helper(x) + 1

    st = to_static(fn)
    np.testing.assert_allclose(st(_t([1.0])).numpy(), [3.0])
    np.testing.assert_allclose(st(_t([-1.0])).numpy(), [4.0])


# ---------------------------------------------------------- Layer + jit.save
class _GatedNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(4, 4)

    @to_static
    def forward(self, x):
        h = self.fc(x)
        if h.mean() > 0:
            out = paddle.nn.functional.relu(h)
        else:
            out = h * 0.1
        return out


def test_layer_forward_with_tensor_if():
    net = _GatedNet()
    x = _t(np.random.RandomState(0).randn(2, 4))
    got = net(x).numpy()
    # reproduce eagerly
    h = net.fc(x)
    want = (np.maximum(h.numpy(), 0) if h.numpy().mean() > 0
            else h.numpy() * 0.1)
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_jit_save_load_dy2static_model(tmp_path):
    from paddle_tpu.static import InputSpec
    net = _GatedNet()
    net.eval()
    x = _t(np.random.RandomState(1).randn(3, 4))
    want = net(x).numpy()
    path = str(tmp_path / "gated")
    paddle.jit.save(net, path, input_spec=[InputSpec([None, 4], "float32")])
    loaded = paddle.jit.load(path)
    np.testing.assert_allclose(loaded(x).numpy(), want, rtol=1e-5, atol=1e-6)


# ------------------------------------------------- review-finding regressions
def test_python_container_truthiness():
    def fn(x, opts=None, idx=None):
        opts = opts or {"scale": 2.0}
        if not idx:
            x = x * opts["scale"]
        if idx and x.sum() > 0:
            x = x + 1
        return x

    st = convert_to_static(fn)
    np.testing.assert_allclose(st(_t([3.0])).numpy(), [6.0])
    np.testing.assert_allclose(st(_t([3.0]), idx=[1]).numpy(), [4.0])


def test_bool_tensor_int_arithmetic():
    x = _t([1.0, -1.0])
    got = ((x > 0) * 3).numpy()
    np.testing.assert_allclose(got, [3.0, 0.0])
    np.testing.assert_allclose(((x > 0) + 1).numpy(), [2.0, 1.0])


def test_int_scalar_keeps_int_dtype():
    i = paddle.to_tensor(np.int32(5))
    assert "int32" in str((i + 1).dtype)
    assert "float32" in str((i + 1.5).dtype)


def test_concrete_cond_traced_carry_unrolls():
    def fn(x):
        i = 0
        while i < 3:               # python cond: unrolled, shape may change
            x = paddle.concat([x, x])
            i = i + 1
        return x

    st = to_static(fn)
    assert st(_t([1.0])).shape[0] == 8


def test_static_method_bound_once():
    net = _GatedNet()
    assert net.forward is net.forward     # cached in instance dict


def test_not_to_static_factory_form():
    from paddle_tpu.jit import not_to_static

    @not_to_static()
    def f(x):
        return x + 1

    assert f(1) == 2
    assert f.__dy2static_transformed__


def test_carry_dtype_promotion():
    def fn(x):
        s = 0
        while x.sum() < 3:
            s = s + x.mean()
            x = x + 1
        return s

    st = to_static(fn)
    got = st(_t([0.5])).numpy()
    # eager: 0 + 0.5 (x->1.5) + 1.5 (x->2.5) + 2.5 (x->3.5) = 4.5
    np.testing.assert_allclose(got, 4.5)


def test_subscript_store_in_branch_carried():
    def fn(x):
        if x.sum() > 100:
            x[0] = 0.0
        return x * 1.0

    st = to_static(fn)
    np.testing.assert_allclose(st(_t([1.0, 2.0])).numpy(), [1.0, 2.0])
    np.testing.assert_allclose(st(_t([200.0, 2.0])).numpy(), [0.0, 2.0])


def test_attr_store_in_branch_clear_error():
    class Box:
        pass

    b = Box()

    def fn(x):
        if x.sum() > 0:
            b.hits = 1
        return x

    convert_to_static(fn)(_t([1.0]))             # eager: plain python
    assert b.hits == 1
    with pytest.raises(Dy2StaticError, match="attribute"):
        to_static(fn)(_t([1.0]))                 # traced: named error


def test_for_else_with_break_semantics():
    hits = []

    def fn(vals):
        for v in vals:
            if v == 2:
                break
        else:
            hits.append("else")
        return vals

    st = convert_to_static(fn)
    st([1, 2, 3])
    assert hits == []          # break taken: else must NOT run
    st([5, 6])
    assert hits == ["else"]    # exhausted: else runs


def test_loop_target_leaks_after_for():
    def fn(x):
        for i in range(3):
            x = x + i
        return x * i            # python: i leaks as 2

    st = to_static(fn)
    np.testing.assert_allclose(st(_t([1.0])).numpy(), [8.0])   # (1+0+1+2)*2


def test_loop_target_leaks_traced_iterable():
    def fn(xs):
        s = paddle.zeros([2])
        for row in xs:
            s = s + row
        return s + row          # last row leaks

    st = to_static(fn)
    xs = _t([[1.0, 2.0], [3.0, 4.0]])
    np.testing.assert_allclose(st(xs).numpy(), [7.0, 10.0])


def test_loop_target_body_reassignment_leaks():
    def fn(x):
        for i in range(3):
            i = i * 10
            x = x + i
        return x * i            # python: i leaks as 20

    st = to_static(fn)
    # x = 1 + 0 + 10 + 20 = 31; * 20 = 620
    np.testing.assert_allclose(st(_t([1.0])).numpy(), [620.0])


def test_convert_call_cache_not_pinning():
    import gc
    import weakref as wr

    from paddle_tpu.jit.dy2static import convert_call

    def make():
        def inner(v):
            if v.mean() > 0:
                return v
            else:
                return -v
        return inner

    f = make()
    convert_call(f)
    ref = wr.ref(f)
    del f
    gc.collect()
    assert ref() is None        # cache must not keep the function alive


def test_elif_chain_all_return():
    def fn(x):
        if x.mean() > 1:
            return x + 1
        elif x.mean() > 0:
            return x + 2
        else:
            return x - 1

    st = to_static(fn)
    np.testing.assert_allclose(st(_t([3.0])).numpy(), [4.0])
    np.testing.assert_allclose(st(_t([0.5])).numpy(), [2.5])
    np.testing.assert_allclose(st(_t([-1.0])).numpy(), [-2.0])


def test_monkeypatched_global_seen():
    import tests_dy2s_helper_mod as helper_mod
    st = convert_to_static(helper_mod.entry)
    assert float(st(_t([1.0]))[0]) == 2.0
    orig = helper_mod.helper
    try:
        helper_mod.helper = lambda v: v * 10
        assert float(st(_t([1.0]))[0]) == 10.0     # live global rebinding
    finally:
        helper_mod.helper = orig


# -------------------------------------------------------- translator switch
def test_program_translator_disable():
    from paddle_tpu.jit import ProgramTranslator
    ProgramTranslator.get_instance().enable(False)
    try:
        def fn(x):
            if x.mean() > 0:
                return x + 1
            else:
                return x - 1
        st = to_static(fn)
        with pytest.raises(Exception):
            st(_t([1.0]))      # plain tracing: tracer-bool error
    finally:
        ProgramTranslator.get_instance().enable(True)


# ------------------------------------------------- undefined-local equality
def test_undefined_local_eq_hash_curated_error():
    """`==`/`!=`/hash on a local that is unbound when tensor-dependent
    control flow starts must raise the curated read-before-assignment
    error — object-identity defaults used to silently return a bool
    (ISSUE 2 satellite)."""
    def fn(x):
        if x.sum() > 0:
            y = x + 1
        else:
            if y == 3:                  # y compared before any assignment
                y = x
            y = x - 1
        return y

    with pytest.raises(Dy2StaticError, match="read before assignment"):
        to_static(fn)(_t([1.0]))

    from paddle_tpu.jit.dy2static import UNDEF
    for bad in (lambda: UNDEF == 3, lambda: UNDEF != 3, lambda: hash(UNDEF),
                lambda: UNDEF in {1: "a"}, lambda: 3 == UNDEF):
        with pytest.raises(Dy2StaticError, match="read before assignment"):
            bad()
