"""OpTest harness — the equivalent of the reference's
python/paddle/fluid/tests/unittests/op_test.py:309.

check_output: runs the op and compares against a numpy reference.
check_grad: compares tape gradients against numeric finite differences
(reference op_test.py:126 get_numeric_gradient / :1868 check_grad).
"""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor


def numeric_grad(fn, tensors, wrt_index, out_reduce=None, delta=1e-3):
    """Central-difference gradient of sum(fn(*tensors)) w.r.t. tensors[wrt_index]."""
    base = [t.numpy().astype(np.float64) for t in tensors]

    def eval_sum(arrays):
        ts = [paddle.to_tensor(a.astype(np.float32)) for a in arrays]
        out = fn(*ts)
        outs = out if isinstance(out, (tuple, list)) else [out]
        total = 0.0
        for o in outs:
            total += float(np.asarray(o.numpy(), dtype=np.float64).sum())
        return total

    x = base[wrt_index]
    grad = np.zeros_like(x)
    flat = x.reshape(-1)
    gflat = grad.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + delta
        fp = eval_sum(base)
        flat[i] = orig - delta
        fm = eval_sum(base)
        flat[i] = orig
        gflat[i] = (fp - fm) / (2 * delta)
    return grad


def check_grad(fn, arrays, rtol=1e-2, atol=1e-3, delta=1e-3):
    """Analytic (tape) grads vs finite differences for every float input."""
    tensors = [paddle.to_tensor(a.astype(np.float32), stop_gradient=False)
               for a in arrays]
    out = fn(*tensors)
    outs = out if isinstance(out, (tuple, list)) else [out]
    total = outs[0].sum()
    for o in outs[1:]:
        total = total + o.sum()
    total.backward()
    for i, t in enumerate(tensors):
        num = numeric_grad(fn, [paddle.to_tensor(a.astype(np.float32)) for a in arrays],
                           i, delta=delta)
        ana = t.grad.numpy().astype(np.float64)
        np.testing.assert_allclose(ana, num, rtol=rtol, atol=atol,
                                   err_msg=f"grad mismatch for input {i}")


def check_output(fn, arrays, numpy_fn, rtol=1e-5, atol=1e-6):
    tensors = [paddle.to_tensor(a) for a in arrays]
    out = fn(*tensors)
    ref = numpy_fn(*arrays)
    outs = out if isinstance(out, (tuple, list)) else [out]
    refs = ref if isinstance(ref, (tuple, list)) else [ref]
    for o, r in zip(outs, refs):
        np.testing.assert_allclose(np.asarray(o.numpy(), np.float64),
                                   np.asarray(r, np.float64), rtol=rtol, atol=atol)
