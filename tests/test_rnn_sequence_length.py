"""Variable-length RNN batches via sequence_length (the documented LoD
replacement; reference rnn op SequenceLength semantics): outputs past each
sample's length are zero, final states are the states AT the last valid
step, and reverse direction flips each valid segment in place. Goldens:
torch packed sequences."""
import numpy as np
import torch

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _copy_lstm_weights(pl, tl):
    sd = {
        "weight_ih_l0": torch.tensor(np.asarray(pl.cells[0].weight_ih.numpy())),
        "weight_hh_l0": torch.tensor(np.asarray(pl.cells[0].weight_hh.numpy())),
        "bias_ih_l0": torch.tensor(np.asarray(pl.cells[0].bias_ih.numpy())),
        "bias_hh_l0": torch.tensor(np.asarray(pl.cells[0].bias_hh.numpy())),
    }
    tl.load_state_dict(sd)


def test_lstm_sequence_length_matches_torch_packed():
    B, T, I, H = 3, 5, 4, 6
    rng = np.random.RandomState(0)
    x = rng.randn(B, T, I).astype("float32")
    lens = np.array([5, 3, 1], "int64")
    pl = nn.LSTM(I, H)
    tl = torch.nn.LSTM(I, H, batch_first=True)
    _copy_lstm_weights(pl, tl)
    out, (h, c) = pl(paddle.to_tensor(x),
                     sequence_length=paddle.to_tensor(lens))
    packed = torch.nn.utils.rnn.pack_padded_sequence(
        torch.tensor(x), torch.tensor(lens), batch_first=True)
    po, (th, tc) = tl(packed)
    to, _ = torch.nn.utils.rnn.pad_packed_sequence(
        po, batch_first=True, total_length=T)
    np.testing.assert_allclose(np.asarray(out.numpy()), to.detach().numpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(h.numpy())[0],
                               th.detach().numpy()[0], rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(c.numpy())[0],
                               tc.detach().numpy()[0], rtol=1e-5, atol=1e-5)


def test_bidirectional_masking_and_reverse_segments():
    B, T, I, H = 3, 5, 4, 6
    rng = np.random.RandomState(1)
    x = rng.randn(B, T, I).astype("float32")
    lens = np.array([5, 3, 1], "int64")
    pg = nn.GRU(I, H, direction="bidirect")
    og, _ = pg(paddle.to_tensor(x), sequence_length=paddle.to_tensor(lens))
    og = np.asarray(og.numpy())
    assert (og[1, 3:] == 0).all() and (og[2, 1:] == 0).all()
    assert not (og[1, :3] == 0).all()
    # reverse half at step 0 equals a fwd pass over the flipped valid
    # segment: for sample 2 (len 1) both directions see only x[2, 0]
    fwd_half, bwd_half = og[2, 0, :H], og[2, 0, H:]
    pg2 = nn.GRU(I, H)
    pg2.cells[0].weight_ih.set_value(pg.cells_bw[0].weight_ih)
    pg2.cells[0].weight_hh.set_value(pg.cells_bw[0].weight_hh)
    pg2.cells[0].bias_ih.set_value(pg.cells_bw[0].bias_ih)
    pg2.cells[0].bias_hh.set_value(pg.cells_bw[0].bias_hh)
    o2, _ = pg2(paddle.to_tensor(x[2:3, :1]))
    np.testing.assert_allclose(bwd_half, np.asarray(o2.numpy())[0, 0],
                               rtol=1e-5, atol=1e-6)


def test_no_sequence_length_unchanged():
    B, T, I, H = 2, 4, 3, 5
    rng = np.random.RandomState(2)
    x = rng.randn(B, T, I).astype("float32")
    m = nn.SimpleRNN(I, H)
    o1, s1 = m(paddle.to_tensor(x))
    o2, s2 = m(paddle.to_tensor(x),
               sequence_length=paddle.to_tensor(np.array([T, T], "int64")))
    np.testing.assert_allclose(np.asarray(o1.numpy()),
                               np.asarray(o2.numpy()), rtol=1e-5, atol=1e-6)


def test_initial_states_threaded_matches_torch():
    """Multi-layer LSTM must consume user (h0, c0) in the paddle
    (L*D, B, H) layout — previously silently dropped."""
    B, T, I, H = 2, 4, 3, 5
    rng = np.random.RandomState(3)
    x = rng.randn(B, T, I).astype("float32")
    h0 = rng.randn(1, B, H).astype("float32")
    c0 = rng.randn(1, B, H).astype("float32")
    pl = nn.LSTM(I, H)
    tl = torch.nn.LSTM(I, H, batch_first=True)
    _copy_lstm_weights(pl, tl)
    out, (h, c) = pl(paddle.to_tensor(x),
                     (paddle.to_tensor(h0), paddle.to_tensor(c0)))
    to, (th, tc) = tl(torch.tensor(x), (torch.tensor(h0), torch.tensor(c0)))
    np.testing.assert_allclose(np.asarray(out.numpy()), to.detach().numpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(h.numpy()), th.detach().numpy(),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(c.numpy()), tc.detach().numpy(),
                               rtol=1e-5, atol=1e-5)
