"""geometric message passing, LBFGS/BFGS minimizers, jacobian/hessian,
op_bench tool."""
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import geometric
from paddle_tpu.incubate.optimizer.functional import (minimize_bfgs,
                                                      minimize_lbfgs)


def test_send_u_recv_sum():
    x = paddle.to_tensor(np.asarray([[1., 2.], [3., 4.], [5., 6.]],
                                    np.float32))
    src = np.asarray([0, 1, 2, 0])
    dst = np.asarray([1, 2, 1, 0])
    out = geometric.send_u_recv(x, src, dst, reduce_op="sum")
    want = np.zeros((3, 2), np.float32)
    want[1] = x.numpy()[0] + x.numpy()[2]
    want[2] = x.numpy()[1]
    want[0] = x.numpy()[0]
    np.testing.assert_array_equal(out.numpy(), want)


def test_send_u_recv_mean_max():
    x = paddle.to_tensor(np.asarray([[2.], [4.], [6.]], np.float32))
    src = np.asarray([0, 1, 2])
    dst = np.asarray([0, 0, 0])
    mean = geometric.send_u_recv(x, src, dst, reduce_op="mean", out_size=1)
    np.testing.assert_allclose(mean.numpy(), [[4.]])
    mx = geometric.send_u_recv(x, src, dst, reduce_op="max", out_size=1)
    np.testing.assert_allclose(mx.numpy(), [[6.]])


def test_send_ue_recv():
    x = paddle.to_tensor(np.asarray([[1.], [2.]], np.float32))
    e = paddle.to_tensor(np.asarray([[10.], [20.]], np.float32))
    out = geometric.send_ue_recv(x, e, np.asarray([0, 1]),
                                 np.asarray([0, 0]), message_op="add",
                                 reduce_op="sum", out_size=2)
    np.testing.assert_allclose(out.numpy(), [[33.], [0.]])


def test_segment_ops_differentiable():
    x = paddle.to_tensor(np.asarray([[1., 1.], [2., 2.], [3., 3.]],
                                    np.float32), stop_gradient=False)
    seg = np.asarray([0, 0, 1])
    out = geometric.segment_sum(x, seg)
    np.testing.assert_array_equal(out.numpy(), [[3., 3.], [3., 3.]])
    out.sum().backward()
    np.testing.assert_array_equal(x.grad.numpy(), np.ones((3, 2)))


def test_lbfgs_rosenbrock():
    def rosen(x):
        return ((1 - x[0]) ** 2 + 100 * (x[1] - x[0] ** 2) ** 2)

    res = minimize_lbfgs(rosen, paddle.to_tensor(np.asarray([-1.2, 1.0],
                                                            np.float32)),
                         max_iters=1000)
    assert bool(res.is_converge.numpy()) or float(res.fx) < 1e-5
    np.testing.assert_allclose(res.x.numpy(), [1.0, 1.0], atol=1e-2)


def test_bfgs_quadratic():
    A = np.asarray([[3., 1.], [1., 2.]], np.float32)
    b = np.asarray([1., -1.], np.float32)

    def quad(x):
        return 0.5 * (x * paddle.to_tensor(A) @ x).sum() - \
            (paddle.to_tensor(b) * x).sum()

    # minimum at A x = b
    res = minimize_bfgs(lambda x: 0.5 * paddle.matmul(
        paddle.matmul(x.reshape([1, 2]), paddle.to_tensor(A)),
        x.reshape([2, 1])).sum() - (paddle.to_tensor(b) * x).sum(),
        paddle.to_tensor(np.zeros(2, np.float32)), max_iters=100)
    want = np.linalg.solve(A, b)
    np.testing.assert_allclose(res.x.numpy(), want, atol=1e-3)


def test_jacobian_hessian():
    from paddle_tpu.autograd import hessian, jacobian

    def f(x):
        return (x * x).sum()

    x = paddle.to_tensor(np.asarray([1., 2., 3.], np.float32))
    j = jacobian(f, x)
    np.testing.assert_allclose(j.numpy(), 2 * x.numpy())
    h = hessian(f, x)
    np.testing.assert_allclose(h.numpy(), 2 * np.eye(3), atol=1e-6)


def test_op_bench_tool():
    out = subprocess.run(
        [sys.executable, "tools/op_bench.py", "--op", "matmul",
         "--shape", "64x64,64x64", "--repeat", "3"],
        capture_output=True, text=True, timeout=300,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": "."})
    assert out.returncode == 0, out.stderr
    import json
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["op"] == "matmul" and rec["jit_us"] > 0
