"""ExponentialFamily Bregman KL + register_kl dispatch (reference:
distribution/exponential_family.py, distribution/kl.py)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.distribution import (Distribution, ExponentialFamily, Normal,
                                     kl_divergence, register_kl)


class _NormalEF(ExponentialFamily):
    """Normal as exponential family: nat = (mu/s^2, -1/(2 s^2)),
    log-normalizer = -n1^2/(4 n2) - log(-2 n2)/2."""

    def __init__(self, loc, scale):
        self.loc = paddle.to_tensor(np.asarray(loc, "float32"))
        self.scale = paddle.to_tensor(np.asarray(scale, "float32"))

    @property
    def _natural_parameters(self):
        s2 = self.scale * self.scale
        return (self.loc / s2, -0.5 / s2)

    def _log_normalizer(self, n1, n2):
        import jax.numpy as jnp
        a = n1._data if hasattr(n1, "_data") else n1
        b = n2._data if hasattr(n2, "_data") else n2
        return paddle.Tensor(-a * a / (4 * b) - 0.5 * jnp.log(-2.0 * b))


def test_expfamily_bregman_kl_matches_closed_form():
    p = _NormalEF([0.0, 1.0], [1.0, 2.0])
    q = _NormalEF([0.5, -1.0], [2.0, 1.0])
    kl = kl_divergence(p, q).numpy()
    # closed-form Normal KL
    mu_p, s_p = np.array([0.0, 1.0]), np.array([1.0, 2.0])
    mu_q, s_q = np.array([0.5, -1.0]), np.array([2.0, 1.0])
    expect = (np.log(s_q / s_p) + (s_p**2 + (mu_p - mu_q)**2) / (2 * s_q**2)
              - 0.5)
    np.testing.assert_allclose(kl, expect, rtol=1e-5)


def test_register_kl_dispatch_and_priority():
    class A(Distribution):
        pass

    class B(A):
        pass

    @register_kl(A, A)
    def _kl_aa(p, q):          # noqa: ANN001
        return "aa"

    @register_kl(B, A)
    def _kl_ba(p, q):          # noqa: ANN001
        return "ba"

    assert kl_divergence(B(), A()) == "ba"     # most-derived first
    assert kl_divergence(A(), A()) == "aa"
    assert kl_divergence(B(), B()) == "ba"     # falls back through MRO


def test_builtin_normal_kl_still_works():
    p = Normal(paddle.to_tensor([0.0]), paddle.to_tensor([1.0]))
    q = Normal(paddle.to_tensor([1.0]), paddle.to_tensor([1.0]))
    np.testing.assert_allclose(kl_divergence(p, q).numpy(), [0.5], rtol=1e-6)
