"""Speculative multi-token decode (ISSUE 7 tentpole b): greedy output
must be BIT-IDENTICAL to the one-token loop — through the engine, the
scheduler, mid-stream preemption, and an eos landing inside an accepted
window — with the compile count bounded (one draft decode executable,
one fixed-shape verify executable, prefills per bucket) and acceptance
telemetry flowing through the request records and metrics registry.
"""
import os
import sys

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.serving import (
    PagedGenerationEngine, Scheduler, SpecDecodeConfig, SpeculativeEngine,
    truncated_draft,
)
from paddle_tpu.serving import sampling
from paddle_tpu.text.models import GPTForGeneration, gpt_tiny

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_ROOT, "tools"))
import load_harness  # noqa: E402
import serve_report  # noqa: E402


@pytest.fixture(scope="module")
def tiny():
    m = gpt_tiny()
    m.eval()
    return m


def _prompt(seed, n, vocab=1000):
    return np.random.RandomState(seed).randint(0, vocab, n)


def _reference_tokens(model, prompt, max_new, eos=None):
    gen = GPTForGeneration(model)
    ids = paddle.to_tensor(np.asarray(prompt)[None, :].astype("int64"))
    out, lengths = gen.generate(ids, max_new_tokens=max_new,
                                eos_token_id=eos)
    return list(out.numpy()[0][:int(lengths.numpy()[0])])


# ---------------------------------------------------------- verify rule
def test_greedy_verify_rule():
    """Unit contract of the accept/resample rule: n_acc = length of the
    matching run, emitted = choices[:n_acc+1], last = correction or
    bonus."""
    V = 10
    # logits whose argmax per position is [3, 5, 7, 2]
    argmaxes = np.asarray([[3, 5, 7, 2]])
    logits = np.zeros((1, 4, V), np.float32)
    for i, a in enumerate(argmaxes[0]):
        logits[0, i, a] = 9.0
    # window [t0, d1, d2, d3] with drafts [3, 5, 9]: d1,d2 accepted, d3
    # rejected -> correction from position 2 (choice 7)
    window = np.asarray([[1, 3, 5, 9]], np.int32)
    choices, n_acc, last = sampling.greedy_verify(
        jnp.asarray(logits), jnp.asarray(window))
    assert list(np.asarray(choices)[0]) == [3, 5, 7, 2]
    assert int(n_acc[0]) == 2 and int(last[0]) == 7
    # full accept -> bonus token from the final position
    window = np.asarray([[1, 3, 5, 7]], np.int32)
    _, n_acc, last = sampling.greedy_verify(
        jnp.asarray(logits), jnp.asarray(window))
    assert int(n_acc[0]) == 3 and int(last[0]) == 2
    # first draft wrong -> nothing accepted, correction is position 0
    window = np.asarray([[1, 4, 5, 7]], np.int32)
    _, n_acc, last = sampling.greedy_verify(
        jnp.asarray(logits), jnp.asarray(window))
    assert int(n_acc[0]) == 0 and int(last[0]) == 3


# ------------------------------------------------------- engine parity
def _spec_stream(engine, slot_prompts, n_tokens):
    rows = [[engine.prefill(s, p)] for s, p in enumerate(slot_prompts)]
    while min(len(r) for r in rows) < n_tokens:
        toks, n_emit = engine.decode_many()
        for s in range(len(slot_prompts)):
            for j in range(int(n_emit[s])):
                rows[s].append(int(toks[s, j]))
    return [r[:n_tokens] for r in rows]


@pytest.mark.parametrize("gamma", (1, 3, 5))
def test_spec_stream_bit_identical_to_one_token_loop(tiny, gamma):
    """The acceptance bar, at several window widths: every emitted token
    equals the one-token paged loop's (== the Layer-level oracle's)."""
    prompts = [_prompt(0, 9), _prompt(1, 17)]
    plain = PagedGenerationEngine(tiny, slots=2, max_len=64, block_size=8)
    rows_p = [[plain.prefill(s, p)] for s, p in enumerate(prompts)]
    for _ in range(11):
        st = plain.decode()
        for s in range(2):
            rows_p[s].append(int(st[s]))
    spec = SpeculativeEngine(tiny, slots=2, max_len=64, block_size=8,
                             gamma=gamma, draft_layers=1)
    rows_s = _spec_stream(spec, prompts, 12)
    assert rows_s == rows_p
    for s, p in enumerate(prompts):
        assert rows_s[s] == _reference_tokens(tiny, p, 12)
    # compile discipline: ONE draft decode, ONE verify, no one-token path
    assert spec.trace_counts["spec_verify"] == 1
    assert spec.trace_counts["draft_decode"] == 1
    assert spec.trace_counts["decode"] == 0
    assert list(spec.trace_counts["draft_prefill"]) == [32]


def test_spec_with_kernel_attention_impl(tiny):
    """Both tentpoles composed: the verify window runs through the
    Pallas in-kernel block-table walk and the stream stays exact."""
    prompts = [_prompt(2, 7), _prompt(3, 12)]
    spec = SpeculativeEngine(tiny, slots=2, max_len=64, block_size=8,
                             gamma=3, attention_impl="kernel")
    rows = _spec_stream(spec, prompts, 8)
    for s, p in enumerate(prompts):
        assert rows[s] == _reference_tokens(tiny, p, 8)


def test_spec_with_distinct_draft_model(tiny):
    """A separately-built draft from the same artifact family (same
    vocab, fewer layers, its own weights) — correctness must not depend
    on the draft's quality, only the acceptance rate may."""
    from paddle_tpu.text.models import GPT
    import dataclasses
    draft = GPT(dataclasses.replace(tiny.cfg, num_layers=1))
    draft.eval()                              # random weights: bad draft
    spec = SpeculativeEngine(tiny, slots=1, max_len=64, block_size=8,
                             gamma=4, draft=draft)
    rows = _spec_stream(spec, [_prompt(4, 10)], 9)
    assert rows[0] == _reference_tokens(tiny, _prompt(4, 10), 9)


def test_truncated_draft_shares_target_arrays(tiny):
    draft = truncated_draft(tiny, 1)
    assert draft.cfg.num_layers == 1
    sd, st = draft.state_dict(), tiny.state_dict()
    assert sd["wte.weight"]._data is st["wte.weight"]._data
    assert sd["blocks.0.attn.qkv.weight"]._data \
        is st["blocks.0.attn.qkv.weight"]._data
    with pytest.raises(ValueError, match="draft_layers"):
        truncated_draft(tiny, 99)


def test_spec_config_validation(tiny):
    with pytest.raises(ValueError, match="greedy"):
        SpecDecodeConfig(decode_strategy="sampling")
    with pytest.raises(ValueError, match="gamma"):
        SpecDecodeConfig(gamma=0)
    with pytest.raises(ValueError, match="vocabulary"):
        from paddle_tpu.text.models import GPT, GPTConfig
        alien = GPT(GPTConfig(hidden_size=64, num_layers=1, num_heads=2,
                              vocab_size=77, max_position_embeddings=64))
        SpeculativeEngine(tiny, slots=1, max_len=32, draft=alien)


def test_verify_window_grows_blocks_lazily(tiny):
    """A gamma+1 window crossing several block boundaries in one step:
    ensure_slot_capacity provisions every needed block up front
    (decode_write_tokens wide), and the stream stays exact."""
    spec = SpeculativeEngine(tiny, slots=1, max_len=64, block_size=2,
                             gamma=5, draft_layers=1)
    assert spec.decode_write_tokens == 6     # window == gamma+1
    rows = _spec_stream(spec, [_prompt(5, 3)], 14)
    assert rows[0] == _reference_tokens(tiny, _prompt(5, 3), 14)


# ------------------------------------------------- scheduler integration
def test_scheduler_spec_streams_exact_with_preemption(tiny):
    """Mid-stream preemption under an oversubscribed pool: every request
    still completes DONE with its exact greedy stream (recompute restart
    replays through prefill, draft included), and no blocks leak."""
    rng = np.random.RandomState(7)
    prompts = [rng.randint(0, 1000, 6) for _ in range(4)]
    eng = SpeculativeEngine(tiny, slots=3, max_len=32, block_size=4,
                            num_blocks=8, enable_prefix_cache=False,
                            gamma=3)
    sched = Scheduler(eng, max_queue=16)
    hs = [sched.submit(p, max_new_tokens=6) for p in prompts]
    sched.run_until_idle()
    assert sched.counts["serving.preempted"] > 0
    for h, p in zip(hs, prompts):
        assert h.status == "DONE", (h.status, h.error)
        assert h.tokens == _reference_tokens(tiny, p, 6)
        assert h.spec_proposed > 0
    assert eng.block_pool.in_use == 0


def test_eos_inside_accepted_window_truncates_exactly(tiny):
    """An eos accepted mid-window must end the stream exactly where the
    one-token loop would — no trailing window tokens leak out."""
    prompt = _prompt(7, 6)
    base = _reference_tokens(tiny, prompt, 8)
    eos = base[3]                    # fourth generated token becomes eos
    want = _reference_tokens(tiny, prompt, 8, eos=eos)
    assert len(want) < len(base)     # the eos really truncates
    eng = SpeculativeEngine(tiny, slots=1, max_len=64, block_size=8,
                            gamma=4, eos_token_id=eos)
    sched = Scheduler(eng, max_queue=4)
    h = sched.submit(prompt, max_new_tokens=8)
    sched.run_until_idle()
    assert h.status == "DONE"
    assert h.tokens == want


def test_spec_fields_flow_to_serve_report_and_registry(tiny, tmp_path):
    """Per-request spec_proposed/spec_accepted ride the JSONL (schema-
    validated), the summary reports the acceptance rate, and the
    registry counters tick."""
    from paddle_tpu.observability import metrics as _metrics
    metrics = str(tmp_path / "serve_metrics.jsonl")
    eng = SpeculativeEngine(tiny, slots=2, max_len=64, block_size=8,
                            gamma=3)
    sched = Scheduler(eng, max_queue=8, metrics_path=metrics)
    hs = [sched.submit(_prompt(i, 8), max_new_tokens=6) for i in range(2)]
    sched.drain()
    assert all(h.status == "DONE" for h in hs)
    records = serve_report.load(metrics)
    assert serve_report.validate_records(records) == []
    summary = serve_report.summarize(records)
    assert summary["spec_proposed"] > 0
    assert 0.0 <= summary["spec_acceptance_rate"] <= 1.0
    assert "spec-decode acceptance rate" in serve_report.render(summary)
    m = sched.metrics()
    assert m["spec_proposed"] == summary["spec_proposed"]
    snap = {s["name"]: s for s in ({"name": mm["name"]}
            for mm in _metrics.registry().snapshot()["metrics"])}
    assert "serving_spec_proposed_total" in snap
    assert "serving_spec_accepted_total" in snap
    assert "serving_spec_draft_seconds" in snap
    assert "serving_spec_verify_seconds" in snap


def test_one_token_engines_write_zero_spec_fields(tiny, tmp_path):
    """The serve_report schema holds for non-speculative engines too:
    spec fields present and zero."""
    metrics = str(tmp_path / "m.jsonl")
    eng = PagedGenerationEngine(tiny, slots=1, max_len=32, block_size=8)
    sched = Scheduler(eng, max_queue=4, metrics_path=metrics)
    h = sched.submit(_prompt(0, 4), max_new_tokens=2)
    sched.drain()
    assert h.status == "DONE" and h.spec_proposed == 0
    records = serve_report.load(metrics)
    assert serve_report.validate_records(records) == []
    reqs = [r for r in records if r["kind"] == "request"]
    assert all(r["spec_proposed"] == 0 and r["spec_accepted"] == 0
               for r in reqs)
    assert serve_report.summarize(records)["spec_acceptance_rate"] is None


def test_serve_report_accepts_pre_spec_records():
    """Files written before the spec fields landed (PR 3-6 artifacts)
    must still validate and summarize — absent spec fields read as 0."""
    old = [{"kind": "request", "request_id": 1, "status": "DONE",
            "prompt_len": 4, "tokens": 3, "priority": 1, "preempted": 0,
            "prefix_hit": False, "ttft_s": 0.1, "decode_s": 0.2}]
    assert serve_report.validate_records(old) == []
    summary = serve_report.summarize(old)
    assert summary["spec_proposed"] == 0
    assert summary["spec_acceptance_rate"] is None


# --------------------------------------------------- load-harness arm
def test_load_harness_spec_arm(tiny):
    """The harness's spec arm completes the same deterministic trace at
    the same KV budget as paged, reports an acceptance rate, and keeps
    the compile counts bounded."""
    traffic = load_harness.TrafficConfig(
        users=4, requests=8, rate_rps=500.0, prefix_pool=2, prefix_len=16,
        suffix_min=2, suffix_max=6, max_new_tokens=4, seed=0)
    paged = load_harness.run_harness(
        tiny, "paged", traffic, slots=4, max_len=64, block_size=8,
        num_blocks=24, virtual_step_s=0.05)
    spec = load_harness.run_harness(
        tiny, "spec", traffic, slots=4, max_len=64, block_size=8,
        num_blocks=24, virtual_step_s=0.05, gamma=3)
    assert spec["kv_memory_tokens"] == paged["kv_memory_tokens"]
    assert spec["by_status"] == {"DONE": 8}
    assert spec["spec_proposed"] > 0
    assert 0.0 <= spec["spec_acceptance_rate"] <= 1.0
    assert spec["trace_counts"]["spec_verify"] == 1
    assert spec["trace_counts"]["draft_decode"] == 1
    assert spec["trace_counts"]["decode"] == 0
    assert spec["ttft_p50_s"] is not None
    assert spec["ttft_p99_s"] >= spec["ttft_p50_s"]
