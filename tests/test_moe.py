"""MoE gating, dispatch, expert-parallel all-to-all, and eager MoELayer.

Mirrors the reference's MoE tests (unittests/collective/...global_scatter /
test_moe_api) but on a virtual 8-device CPU mesh instead of NCCL ranks.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.incubate.distributed.moe import (
    MoELayer, gshard_dispatch, init_moe_experts, moe_forward)


def test_dispatch_weights_normalized_and_capacity():
    T, E, C, k = 32, 4, 4, 2
    gates = jax.nn.softmax(jax.random.normal(jax.random.key(0), (T, E)))
    combine, dispatch, aux = gshard_dispatch(gates, k, C)
    assert combine.shape == (T, E, C)
    # each (expert, slot) holds at most one token
    per_slot = np.asarray(dispatch).sum(axis=0)
    assert per_slot.max() <= 1
    # per-expert load never exceeds capacity
    assert np.asarray(dispatch).sum(axis=(0, 2)).max() <= C
    # routed tokens have weights summing to 1
    w = np.asarray(combine).sum(axis=(1, 2))
    routed = np.asarray(dispatch).any(axis=(1, 2))
    np.testing.assert_allclose(w[routed], 1.0, atol=1e-5)
    assert float(aux) > 0


def test_capacity_drops_overflow():
    # all tokens prefer expert 0 → only C survive
    T, E, C = 16, 4, 3
    gates = jnp.tile(jnp.asarray([[0.97, 0.01, 0.01, 0.01]]), (T, 1))
    combine, dispatch, _ = gshard_dispatch(gates, 1, C)
    assert int(np.asarray(dispatch)[:, 0, :].sum()) == C


def test_moe_matches_dense_single_expert():
    # E=1, k=1, capacity >= T: routing is the identity → plain FFN
    T, d, h = 16, 8, 32
    x = jax.random.normal(jax.random.key(1), (T, d))
    params = init_moe_experts(jax.random.key(2), 1, d, h)
    gate_w = jnp.zeros((d, 1))
    out, _ = moe_forward(x, gate_w, params, k=1, capacity_factor=float(T))
    ref = jax.nn.gelu(x @ params["w1"][0] + params["b1"][0]) @ params["w2"][0] \
        + params["b2"][0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_expert_parallel_matches_local():
    """all_to_all dispatch over ep=4 must be numerically identical to the
    single-device computation with the same global expert stack."""
    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs 4 virtual devices")
    from jax.sharding import Mesh, PartitionSpec as P

    T, d, h, E, ep = 64, 16, 32, 8, 4
    x = jax.random.normal(jax.random.key(3), (T, d))
    gate_w = jax.random.normal(jax.random.key(4), (d, E)) * 0.1
    params = init_moe_experts(jax.random.key(5), E, d, h)

    ref, ref_aux = moe_forward(x, gate_w, params, k=2, capacity_factor=2.0)

    mesh = Mesh(np.asarray(devs[:ep]), ("ep",))

    def spmd(x, gate_w, params):
        out, aux = moe_forward(x, gate_w, params, k=2, capacity_factor=2.0,
                               axis_name="ep", num_experts=E)
        return out, aux

    shmapped = jax.jit(jax.shard_map(
        spmd, mesh=mesh,
        in_specs=(P(), P(), P("ep")),
        out_specs=(P(), P()),
        check_vma=False))
    out, aux = shmapped(x, gate_w, params)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(float(aux), float(ref_aux), atol=1e-5)


def test_expert_parallel_grads():
    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs 4 virtual devices")
    from jax.sharding import Mesh, PartitionSpec as P

    T, d, h, E, ep = 32, 8, 16, 4, 4
    x = jax.random.normal(jax.random.key(6), (T, d))
    gate_w = jax.random.normal(jax.random.key(7), (d, E)) * 0.1
    params = init_moe_experts(jax.random.key(8), E, d, h)
    mesh = Mesh(np.asarray(devs[:ep]), ("ep",))

    def loss_spmd(x, gate_w, params):
        out, aux = moe_forward(x, gate_w, params, k=2, capacity_factor=2.0,
                               axis_name="ep", num_experts=E)
        return jnp.sum(out ** 2) + 0.01 * aux

    def loss_ref(x, gate_w, params):
        out, aux = moe_forward(x, gate_w, params, k=2, capacity_factor=2.0)
        return jnp.sum(out ** 2) + 0.01 * aux

    grad_spmd = jax.jit(jax.shard_map(
        jax.grad(loss_spmd, argnums=2), mesh=mesh,
        in_specs=(P(), P(), P("ep")), out_specs=P("ep"),
        check_vma=False))
    g = grad_spmd(x, gate_w, params)
    g_ref = jax.grad(loss_ref, argnums=2)(x, gate_w, params)
    # x is replicated: every rank computes the same loss over the same
    # tokens, and the all_to_all transpose sums the ep identical cotangent
    # streams into the expert owners — so SPMD grads are exactly ep × local.
    for name in ("w1", "b1", "w2", "b2"):
        np.testing.assert_allclose(np.asarray(g[name]),
                                   ep * np.asarray(g_ref[name]),
                                   atol=1e-4, rtol=1e-4,
                                   err_msg=f"grad {name}")


def test_moe_grad_clip():
    """ClipGradForMOEByGlobalNorm (reference moe/grad_clip.py): expert +
    non-expert squared norms combine into one global norm; with no expert
    separation it equals the plain global-norm clip."""
    import paddle_tpu as paddle
    from paddle_tpu.incubate.distributed.moe import ClipGradForMOEByGlobalNorm
    from paddle_tpu.nn.clip import ClipGradByGlobalNorm

    rng = np.random.RandomState(0)
    ps, gs = [], []
    for i, shape in enumerate([(4, 4), (8,), (3, 5)]):
        ps.append(paddle.to_tensor(rng.rand(*shape).astype("float32")))
        gs.append(paddle.to_tensor(rng.rand(*shape).astype("float32") * 3))
    pairs = list(zip(ps, gs))

    clipped = ClipGradForMOEByGlobalNorm(
        1.0, is_expert_param_func=lambda p: p is ps[2])(pairs)
    ref = ClipGradByGlobalNorm(1.0)(pairs)
    for (_, a), (_, b) in zip(clipped, ref):
        np.testing.assert_allclose(a.numpy(), b.numpy(), rtol=1e-6)
    # clipped global norm == clip_norm when the raw norm exceeds it
    total = np.sqrt(sum(float((g.numpy() ** 2).sum())
                        for _, g in clipped))
    np.testing.assert_allclose(total, 1.0, rtol=1e-5)


def test_eager_moe_layer_trains():
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt

    layer = MoELayer(d_model=8, d_hidden=16, num_experts=4, gate="gshard")
    o = opt.Adam(1e-2, parameters=layer.parameters())
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.rand(32, 8).astype("float32"))
    target = paddle.to_tensor(rng.rand(32, 8).astype("float32"))

    losses = []
    for step in range(12):
        out = layer(x)
        loss = ((out - target) ** 2).mean() + 0.01 * layer.aux_loss
        loss.backward()
        if step == 0:
            # expert weights actually receive gradient
            assert layer.w1.grad is not None
            assert float(np.abs(np.asarray(layer.w1.grad.numpy())).max()) > 0
        o.step()
        o.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_moe_layer_state_dict():
    layer = MoELayer(d_model=4, d_hidden=8, num_experts=2, gate="switch")
    sd = layer.state_dict()
    assert any("w1" in k for k in sd)
    assert any("gate" in k for k in sd)
