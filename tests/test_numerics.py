"""ISSUE 19: the numerics health plane.

Three layers under test:

  1. the in-trace sentinel vocabulary (stats vectors, sink scopes,
     per-layer taps) and its host-side twins;
  2. the online detector (nonfinite/saturation/drift latching, rolling
     healthy-only baselines) + the bisection localizer;
  3. the arming contract across every engine kind: taps DISABLED is
     bit-identical (token streams AND trace counts) to the pre-ISSUE
     engine, taps ENABLED still compiles once and emits the same
     tokens — plus the chaos drill: a NaN planted in one decode
     tensor is latched, bisection-localized to the guilty layer, and
     bundled within ONE engine step.

Satellites ride along: host-tier requant saturation, the kvledger
`sat` field + serve_report residency join, metrics_report gating,
bench_trend NUMERIC classification, optimizer-side taps.
"""
import json
import math
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu.observability import faults, numerics
from paddle_tpu.serving import (GenerationEngine, PagedGenerationEngine,
                                SpeculativeEngine)
from paddle_tpu.text.models.gpt import gpt_tiny

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_ROOT, "tools"))
import bench_trend  # noqa: E402
import metrics_report  # noqa: E402
import serve_report  # noqa: E402

PROMPT = np.arange(1, 9, dtype=np.int32)


@pytest.fixture(scope="module")
def tiny():
    m = gpt_tiny()
    m.eval()
    return m


# ------------------------------------------------------------- stats math

def test_stats_vector_masks_nonfinite():
    import jax
    import jax.numpy as jnp
    x = jnp.asarray([1.0, -3.0, jnp.nan, 2.0])
    vec = np.asarray(jax.jit(numerics.stats_vector)(x))
    ff, absmax, rms, sat = (float(v) for v in vec)
    assert ff == pytest.approx(0.75)
    # the NaN is masked OUT of the magnitude channels
    assert absmax == pytest.approx(3.0)
    assert rms == pytest.approx(math.sqrt((1 + 9 + 0 + 4) / 4))
    assert sat == 0.0
    # host-side twin agrees with the traced vector
    np.testing.assert_allclose(
        numerics.np_stats(np.asarray([1.0, -3.0, np.nan, 2.0],
                                     np.float32)),
        vec, rtol=1e-6)


def test_stats_vector_saturation_threshold():
    codes = np.asarray([127, -127, 3, 0], np.int8)
    vec = numerics.np_stats(codes, sat_threshold=127)
    assert vec[0] == 1.0
    assert vec[3] == pytest.approx(0.5)
    assert numerics.stats_unhealthy(vec, sat_frac_max=0.25)
    assert not numerics.stats_unhealthy(
        numerics.np_stats(np.asarray([1.0, 2.0], np.float32)))


def test_tree_stats_fuse_leaves():
    a = np.ones((2, 3), np.float32)
    b = np.full((6,), 2.0, np.float32)
    ff, absmax, rms, _ = numerics.np_tree_stats([a, b])
    assert ff == 1.0
    assert absmax == 2.0
    assert rms == pytest.approx(math.sqrt((6 * 1 + 6 * 4) / 12))


def test_tap_is_noop_without_sink():
    # the bit-identical-when-disabled contract at its root: no ambient
    # sink means tap() never touches jax at all
    numerics.tap("anywhere", object())
    with numerics.sink_scope() as sink:
        numerics.tap("site", np.ones(3, np.float32))
    assert "site" in sink
    # layer taps stay dormant without a layer filter, even armed
    with numerics.sink_scope() as sink:
        numerics.tap_layer(0, "act", np.ones(3, np.float32))
    assert not sink
    with numerics.sink_scope(layers=(1,)) as sink:
        numerics.tap_layer(0, "act", np.ones(3, np.float32))
        numerics.tap_layer(1, "act", np.ones(3, np.float32))
    assert list(sink) == ["layer1.act"]


# --------------------------------------------------------------- detector

def test_monitor_latches_three_kinds(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_POSTMORTEM_DIR", str(tmp_path))
    mon = numerics.NumericsMonitor(min_history=3, auto_bundle=True)
    for _ in range(4):
        assert mon.observe("s", [1.0, 2.0, 1.0, 0.0]) == []
    assert mon.observe("s", [0.5, 2.0, 1.0, 0.0]) == ["nonfinite"]
    assert mon.observe("s", [1.0, 2.0, 1.0, 0.9]) == ["saturation"]
    assert mon.observe("s", [1.0, 2.0, 100.0, 0.0]) == ["drift"]
    assert mon.total() == 3
    assert set(mon.counts()) == {"s:nonfinite", "s:saturation", "s:drift"}
    # auto_bundle dumped ONE postmortem, on the FIRST anomaly
    assert mon.bundle_path and os.path.exists(mon.bundle_path)


def test_monitor_baseline_extends_only_on_healthy():
    mon = numerics.NumericsMonitor(min_history=3, auto_bundle=False)
    for _ in range(3):
        mon.observe("s", [1.0, 2.0, 1.0, 0.0])
    # the drifted value latches and must NOT teach the baseline
    assert mon.observe("s", [1.0, 2.0, 50.0, 0.0]) == ["drift"]
    assert mon.observe("s", [1.0, 2.0, 50.0, 0.0]) == ["drift"]
    # the healthy value is still healthy against the unmoved baseline
    assert mon.observe("s", [1.0, 2.0, 1.0, 0.0]) == []


def test_bisect_first_unhealthy():
    assert numerics.bisect_first_unhealthy(8, lambda k: k >= 3) == 3
    assert numerics.bisect_first_unhealthy(8, lambda k: True) == 0
    assert numerics.bisect_first_unhealthy(8, lambda k: False) is None
    assert numerics.bisect_first_unhealthy(0, lambda k: True) is None
    # O(log n): count probe evaluations
    calls = []
    numerics.bisect_first_unhealthy(
        1024, lambda k: (calls.append(k), k >= 700)[1])
    assert len(calls) <= 12


# ----------------------------------------------- arming across engine kinds

def _build(kind, model, taps):
    if kind == "dense":
        return GenerationEngine(model, slots=2, max_len=64,
                                numerics_taps=taps)
    if kind == "paged":
        return PagedGenerationEngine(model, slots=2, max_len=64,
                                     block_size=8, numerics_taps=taps)
    if kind == "spec":
        return SpeculativeEngine(model, slots=2, max_len=64, block_size=8,
                                 gamma=2, numerics_taps=taps)
    if kind == "tp":
        from paddle_tpu.serving.distributed.tp import (
            TensorParallelPagedEngine)
        return TensorParallelPagedEngine(model, tp=2, slots=2, max_len=64,
                                         block_size=8, numerics_taps=taps)
    if kind == "pp":
        from paddle_tpu.serving.distributed.pp import (
            PipelineParallelPagedEngine)
        return PipelineParallelPagedEngine(model, pp=2, slots=2, max_len=64,
                                           block_size=8, numerics_taps=taps)
    from paddle_tpu.serving.distributed.pp import (
        PipelineParallelSpeculativeEngine)
    return PipelineParallelSpeculativeEngine(
        model, pp=2, slots=2, max_len=64, block_size=8, gamma=2,
        numerics_taps=taps)


def _drive(eng, kind):
    if kind in ("spec", "spec_pp"):
        eng.prefill(0, PROMPT)
        out = []
        for _ in range(3):
            toks, n = eng.decode_many()
            out.extend(int(x) for x in toks[0, :int(n[0])])
        return out
    out = [eng.prefill(0, PROMPT)]
    for _ in range(3):
        out.append(int(eng.decode()[0]))
    return out


@pytest.mark.parametrize("kind", ["dense", "paged", "spec",
                                  "tp", "pp", "spec_pp"])
def test_taps_disabled_bit_identical_enabled_compiles_once(kind, tiny):
    """THE arming contract, per engine kind: disabled taps are the
    pre-ISSUE program (same tokens, same trace counts); enabled taps
    emit the SAME tokens from a program still compiled once, with the
    sink ingested into the engine monitor (zero anomalies healthy)."""
    off = _build(kind, tiny, False)
    toks_off = _drive(off, kind)
    assert off.numerics_monitor is None
    on = _build(kind, tiny, True)
    toks_on = _drive(on, kind)
    assert toks_on == toks_off
    assert on.trace_counts == off.trace_counts
    assert on.numerics_monitor.total() == 0
    assert on.last_numerics, "armed engine ingested no sink"
    for site, st in on.last_numerics.items():
        assert st["finite_frac"] == 1.0, (site, st)


def test_paged_int8_taps_cover_quant_surfaces(tiny):
    eng = PagedGenerationEngine(tiny, slots=2, max_len=64, block_size=8,
                                kv_dtype="int8", weight_dtype="int8",
                                numerics_taps=True)
    eng.prefill(0, PROMPT)
    eng.decode()
    sites = set(eng.last_numerics)
    assert {"decode.logits", "kv.codes", "kv.scale",
            "weights.q", "weights.scale"} <= sites
    assert eng.numerics_monitor.total() == 0
    assert eng.trace_counts["decode"] == 1


# ----------------------------------------------------------------- chaos

def test_chaos_nan_detected_localized_bundled_one_step(tiny, tmp_path,
                                                       monkeypatch):
    """The acceptance drill: numerics.corrupt plants a NaN in layer 1's
    ln weight; ONE decode step later the anomaly is latched, the
    bisection localizer names layer 1, and the postmortem bundle is on
    disk — with the probe traces counted under numerics_probe, never
    decode."""
    monkeypatch.setenv("PADDLE_TPU_POSTMORTEM_DIR", str(tmp_path))
    assert "numerics.corrupt" in faults.SITES
    eng = PagedGenerationEngine(tiny, slots=2, max_len=64, block_size=8,
                                numerics_taps=True)
    eng.prefill(0, PROMPT)
    faults.arm("numerics.corrupt", mode="nan", nth=1, max_fires=1,
               target="blocks.1.ln1.weight")
    try:
        eng.decode()
    finally:
        faults.disarm_all()
    mon = eng.numerics_monitor
    assert mon.counts().get("decode.logits:nonfinite", 0) >= 1, mon.counts()
    loc = eng.last_localization
    assert loc is not None
    assert loc["first_unhealthy_layer"] == 1
    assert loc["site"] == "layer1.act"
    assert loc["stats"]["finite_frac"] < 1.0
    assert loc["layers"] == tiny.cfg.num_layers
    assert mon.bundle_path and os.path.exists(mon.bundle_path)
    with open(mon.bundle_path) as f:
        bundle = json.load(f)
    assert "numerics" in json.dumps(bundle)
    # compile discipline: the step executable never retraced; probes
    # have their own counter
    assert eng.trace_counts["decode"] == 1
    assert eng.trace_counts["numerics_probe"] >= 1
    # the prefill/master params were never poisoned (dict-copy contract)
    mon2 = PagedGenerationEngine(tiny, slots=2, max_len=64, block_size=8,
                                 numerics_taps=True)
    mon2.prefill(0, PROMPT)
    mon2.decode()
    assert mon2.numerics_monitor.total() == 0

    # ... and metrics_report --compare names the latched counter (rc=1)
    def snap(anoms):
        return {"schema": metrics_report.SCHEMA, "ts": 1.0, "pid": 1,
                "metrics": [{
                    "name": "numerics_anomaly_total", "type": "counter",
                    "help": "", "labelnames": ["site", "kind"],
                    "samples": [{"labels": {"site": "decode.logits",
                                            "kind": "nonfinite"},
                                 "value": anoms}]}]}
    pa, pb = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    for path, rec in ((pa, snap(0)), (pb, snap(mon.total()))):
        with open(path, "w") as f:
            f.write(json.dumps(rec) + "\n")
    cli = [sys.executable, os.path.join(_ROOT, "tools",
                                        "metrics_report.py")]
    bad = subprocess.run(cli + ["--compare", pa, pb],
                         capture_output=True, text=True, timeout=60)
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert "numerics_anomaly_total" in bad.stdout


def test_chaos_scale_zero_drifts_weight_scales(tiny):
    """scale_zero zeroes an int8 weight entry's scale: nothing goes
    non-finite, but the weights.scale rms collapses and the drift rule
    latches against the rolling baseline built on healthy steps."""
    eng = PagedGenerationEngine(tiny, slots=2, max_len=64, block_size=8,
                                kv_dtype="int8", weight_dtype="int8",
                                numerics_taps=True)
    eng.prefill(0, PROMPT)
    n_healthy = eng.numerics_monitor.min_history + 1
    for _ in range(n_healthy):
        eng.decode()
    assert eng.numerics_monitor.total() == 0
    faults.arm("numerics.corrupt", mode="scale_zero", nth=1, max_fires=1,
               target="blocks.0.mlp.fc1.weight")
    try:
        eng.decode()
    finally:
        faults.disarm_all()
    kinds = eng.numerics_monitor.counts()
    assert kinds.get("weights.scale:drift", 0) >= 1, kinds
    assert eng.trace_counts["decode"] == 1


def test_corrupt_spec_parses_target_from_env():
    specs = faults.load_env(
        "numerics.corrupt=nan:nth=2:max=1:target=blocks.0.attn.weight")
    try:
        assert len(specs) == 1
        assert specs[0].mode == "nan"
        assert specs[0].target == "blocks.0.attn.weight"
        assert specs[0].nth == 2
        # nan is caller-interpreted: fire() returns the spec, raises
        # nothing
        assert faults.fire("numerics.corrupt") is None   # nth=2: not yet
        assert faults.fire("numerics.corrupt") is specs[0]
    finally:
        faults.disarm_all()


# ------------------------------------------------------ host-tier requant

def test_host_tier_records_requant_saturation():
    from paddle_tpu.serving.kv_tiers.host import HostTier
    tier = HostTier(8, dtype="int8")
    blk = {"ns": None, "parent": None, "quant": False,
           "arrays": {"k0": np.ones((8, 2, 4), np.float32)}}
    tier.put("a", blk)
    # constant input: every code lands exactly on the ±127 rail
    assert tier.last_put_saturation == pytest.approx(1.0)
    ramp = np.linspace(0.01, 1.0, 8 * 2 * 4, dtype=np.float32)
    tier.put("b", {"ns": None, "parent": None, "quant": False,
                   "arrays": {"k0": ramp.reshape(8, 2, 4)}})
    assert tier.last_put_saturation < 0.5
    st = tier.saturation_stats()
    assert st["samples"] == 2
    assert st["max"] == pytest.approx(1.0)
    assert 0.0 < st["mean"] <= 1.0
    # float32 tier never requantizes: no saturation sample
    f32 = HostTier(8, dtype="float32")
    f32.put("a", blk)
    assert f32.last_put_saturation is None
    assert f32.saturation_stats()["samples"] == 0


def test_host_tier_feeds_process_monitor():
    from paddle_tpu.serving.kv_tiers.host import HostTier
    mon = numerics.NumericsMonitor(sat_frac_max=0.25, auto_bundle=False)
    prev = numerics.set_monitor(mon)
    try:
        tier = HostTier(8, dtype="int8")
        tier.put("a", {"ns": None, "parent": None, "quant": False,
                       "arrays": {"k0": np.ones((8, 2, 4), np.float32)}})
    finally:
        numerics.set_monitor(prev)
    assert mon.counts().get("kv_tier.requant_codes:saturation", 0) >= 1


def test_ledger_demote_carries_sat_and_serve_report_joins(tmp_path):
    from paddle_tpu.observability.kvledger import KVLedger
    led = KVLedger(num_blocks=4)
    led.tier_demote((1,), "key1", "host", "default", sat=0.5)
    led.tier_demote((2,), "key2", "host", "default", sat=0.3)
    led.tier_demote((), "key3", "disk", "default")     # no sat: f32 path
    evs = [e for e in led.events if e["event"] == "tier_demote"]
    assert evs[0]["sat"] == pytest.approx(0.5)
    assert "sat" not in evs[2]
    # the serving-JSONL records validate with the new optional field...
    recs = [dict(e, kind="kvledger",
                 schema=serve_report.KVLEDGER_SCHEMA,
                 request_id=None, tenant="default", origin=None)
            for e in evs]
    assert serve_report.validate_records(recs) == []
    # ...and the residency join summarizes per-tier requant saturation
    res = serve_report.kv_residency(recs)
    host = res["tiers"]["host"]
    assert host["requant_sat"]["samples"] == 2
    assert host["requant_sat"]["mean"] == pytest.approx(0.4)
    assert host["requant_sat"]["max"] == pytest.approx(0.5)
    assert res["tiers"]["disk"]["requant_sat"] is None


def test_store_stats_surface_requant_saturation():
    from paddle_tpu.serving.kv_tiers.host import HostTier
    from paddle_tpu.serving.kv_tiers.store import TieredBlockStore
    store = TieredBlockStore.__new__(TieredBlockStore)
    store.host = HostTier(8, dtype="int8")
    store.disk = None
    store.host.put("a", {"ns": None, "parent": None, "quant": False,
                         "arrays": {"k0": np.ones((8, 2, 4), np.float32)}})
    st = store.stats()
    assert st["host_requant_saturation"]["samples"] == 1
    assert st["host_requant_saturation"]["max"] == pytest.approx(1.0)


# -------------------------------------------------------- metrics gating

def test_metrics_compare_gates_finite_frac_drop(tmp_path):
    def snap(ff):
        return {"schema": metrics_report.SCHEMA, "ts": 1.0, "pid": 1,
                "metrics": [{
                    "name": "numerics_site_finite_frac", "type": "gauge",
                    "help": "", "labelnames": ["site"],
                    "samples": [{"labels": {"site": "decode.logits"},
                                 "value": ff}]}]}
    regs = metrics_report.compare_counters(snap(1.0), snap(0.5))
    why = {k: w for k, *_, w in regs}
    assert any("finite fraction dropped" in w for w in why.values()), regs
    # identical runs stay clean
    assert metrics_report.compare_counters(snap(1.0), snap(1.0)) == []


# -------------------------------------------------- bench_trend NUMERIC

def _trend_doc(n, rc, parsed, tail=""):
    return {"n": n, "cmd": "bench", "rc": rc, "tail": tail,
            "parsed": parsed}


def test_bench_trend_classifies_numeric_casualties(tmp_path):
    docs = {
        "BENCH_r01.json": _trend_doc(
            1, 0, {"metric": "m", "value": 0.4,
                   "extra": {"numerics": {"anomalies": 0}}}),
        "BENCH_r02.json": _trend_doc(
            2, 1, {"metric": "m", "value": 0.0,
                   "error": "numerics anomalies latched on the healthy "
                            "train rung: {'decode.logits:nonfinite': 1}"}),
        "BENCH_r03.json": _trend_doc(
            3, 1, {"metric": "m", "value": 0.0,
                   "extra": {"numerics": {"anomalies": 3}}}),
        "BENCH_r04.json": _trend_doc(
            4, 124, {"metric": "m", "value": 0.0,
                     "error": "backend probe hung"}),
        "BENCH_r05.json": _trend_doc(
            5, 1, {"metric": "m", "value": 0.0, "error": "HBM OOM"}),
    }
    paths = []
    for name, doc in docs.items():
        p = str(tmp_path / name)
        with open(p, "w") as f:
            json.dump(doc, f)
        paths.append(p)
    rows = bench_trend.load_rows(paths)
    cls = {r["run"]: r["class"] for r in rows}
    assert cls == {"r01": bench_trend.HEALTHY,
                   "r02": bench_trend.NUMERIC,
                   "r03": bench_trend.NUMERIC,
                   "r04": bench_trend.WEDGED,
                   "r05": bench_trend.WEDGED}
    # NUMERIC rounds can never be picked as the compare baseline
    assert bench_trend.healthy_baseline(rows)["run"] == "r01"
    table = bench_trend.render(rows)
    assert "numeric casualties" in table
    assert "r02, r03" in table


# ---------------------------------------------------------- optimizer taps

def test_functional_update_taps_in_trace():
    import jax
    import jax.numpy as jnp
    o = opt.SGD(learning_rate=0.1)
    params = {"w": jnp.ones((4,)), "b": jnp.zeros((2,))}
    grads = {"w": jnp.full((4,), 0.5), "b": jnp.ones((2,))}
    state = o.functional_state(params)

    def step(p, g, s):
        with numerics.sink_scope() as sink:
            new_p, new_s = o.apply_gradients_functional(p, g, s)
        return new_p, new_s, sink

    new_p, _, sink = jax.jit(step)(params, grads, state)
    assert set(sink) == {"train.grad_norm", "train.param_norm"}
    gstats = numerics.stats_dict(np.asarray(sink["train.grad_norm"]))
    assert gstats["finite_frac"] == 1.0
    assert gstats["absmax"] == pytest.approx(1.0)
    np.testing.assert_allclose(np.asarray(new_p["w"]), np.full(4, 0.95),
                               rtol=1e-6)
    # disarmed: same update, no sink, no extra outputs
    p2, _ = o.apply_gradients_functional(params, grads, state)
    np.testing.assert_allclose(np.asarray(p2["w"]), np.asarray(new_p["w"]))


def test_eager_step_observes_into_process_monitor():
    mon = numerics.NumericsMonitor(auto_bundle=False)
    prev = numerics.set_monitor(mon)
    try:
        p = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                             stop_gradient=False)
        o = opt.SGD(learning_rate=0.1, parameters=[p])
        (p * p).sum().backward()
        o.step()
        assert mon.total() == 0
        assert {"train.grad_norm", "train.param_norm"} <= \
            set(mon.site_stats())
        # a NaN grad is latched by the same observation point
        p.clear_grad()
        (p * float("nan")).sum().backward()
        o.step()
        assert mon.counts().get("train.grad_norm:nonfinite", 0) >= 1
    finally:
        numerics.set_monitor(prev)
