"""Round-3 vision zoo additions: every model builds, runs a forward pass at
the right output shape, and takes one training step with a falling loss
path available (forward+backward are traceable).

Reference: python/paddle/vision/models tests (test_vision_models.py runs
each model on a 224 input)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.vision import models

# small inputs keep CPU runtime sane; num_classes=10 shrinks the heads
BUILDS = [
    ("alexnet", lambda: models.alexnet(num_classes=10), 127),
    ("squeezenet1_1", lambda: models.squeezenet1_1(num_classes=10), 96),
    ("densenet121", lambda: models.densenet121(num_classes=10), 64),
    ("shufflenet_v2_x0_25",
     lambda: models.shufflenet_v2_x0_25(num_classes=10), 64),
    ("mobilenet_v3_small",
     lambda: models.mobilenet_v3_small(num_classes=10), 64),
    ("googlenet", lambda: models.googlenet(num_classes=10), 96),
]


@pytest.mark.parametrize("name,build,size", BUILDS,
                         ids=[b[0] for b in BUILDS])
def test_model_forward_shape(name, build, size):
    paddle.seed(0)
    net = build()
    net.eval()
    x = paddle.to_tensor(
        np.random.RandomState(0).rand(2, 3, size, size).astype("float32"))
    out = net(x)
    assert list(out.shape) == [2, 10], (name, out.shape)
    assert np.isfinite(np.asarray(out.numpy())).all()


def test_inception_v3_forward():
    paddle.seed(0)
    net = models.inception_v3(num_classes=10)
    net.eval()
    # inception v3 stem needs a larger input
    x = paddle.to_tensor(
        np.random.RandomState(1).rand(1, 3, 160, 160).astype("float32"))
    out = net(x)
    assert list(out.shape) == [1, 10]


def test_new_zoo_model_trains():
    paddle.seed(1)
    net = models.shufflenet_v2_x0_25(num_classes=4)
    o = opt.SGD(0.05, parameters=net.parameters())
    lf = nn.CrossEntropyLoss()
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.rand(4, 3, 64, 64).astype("float32"))
    y = paddle.to_tensor(rng.randint(0, 4, 4))
    l0 = None
    for _ in range(3):
        loss = lf(net(x), y)
        loss.backward()
        o.step()
        o.clear_grad()
        l0 = l0 or float(loss)
    assert float(loss) < l0 + 1e-3   # moving (usually falling) loss
