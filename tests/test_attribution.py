"""Request attribution plane (ISSUE 15): tenant labels end-to-end, the
scheduler decision audit log, and per-tenant SLO burn.

The load-bearing properties:
  - every scheduler decision (admit/shed/preempt/place/...) leaves a
    `paddle_tpu.decisions.v1` record whose INPUTS reproduce its outcome
    through the same replay rules the live path used — validated after
    a JSON round trip, so the on-disk audit log is the proof;
  - a two-tenant load-harness run with an injected burst sheds/preempts
    under pressure, every such decision is replay-reproducible, the
    per-tenant summary decomposes TTFT per tenant, and
    `serving_slo_burn{slo,window,tenant}` gauges exist in a fleet-merged
    snapshot — the ROADMAP item-5 isolation substrate;
  - tenant labels are OBSERVABILITY-ONLY: a labeled run's greedy token
    streams and engine trace counts are bit-identical to an unlabeled
    run over the same engine config (zero compile-count changes);
  - tools/bench_trend.py classifies the committed wedged-grant rounds
    (BENCH_r03-r05) as WEDGED, keeping them out of the trend line and
    the compare-baseline choice.
"""
import json
import os
import sys

import numpy as np
import pytest

from paddle_tpu.observability import decisions as dec
from paddle_tpu.observability import fleet
from paddle_tpu.observability import metrics
from paddle_tpu.serving import PagedGenerationEngine, Scheduler
from paddle_tpu.text.models import gpt_tiny

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_ROOT, "tools"))
import bench_trend  # noqa: E402
import load_harness  # noqa: E402
import serve_report  # noqa: E402


@pytest.fixture(scope="module")
def tiny():
    m = gpt_tiny()
    m.eval()
    return m


# ------------------------------------------------------ the replay rules

def test_replay_shed_matches_rule():
    base = {"priority": 2, "shed_priority": 2, "queue_depth": 5,
            "shed_watermark": 4, "pool_free_fraction": None,
            "shed_pool_free": None}
    assert "watermark" in dec.replay_shed(base)
    assert dec.replay_shed(dict(base, priority=0)) is None
    assert dec.replay_shed(dict(base, queue_depth=3)) is None
    pool = dict(base, shed_watermark=None, pool_free_fraction=0.05,
                shed_pool_free=0.25)
    assert "free fraction" in dec.replay_shed(pool)


def test_replay_victim_worst_class_most_slack_slot_order_ties():
    cands = [
        {"slot": 0, "request_id": 1, "tenant": "a", "priority": 0,
         "deadline_slack_s": 1.0},
        {"slot": 1, "request_id": 2, "tenant": "b", "priority": 2,
         "deadline_slack_s": 3.0},
        {"slot": 2, "request_id": 3, "tenant": "b", "priority": 2,
         "deadline_slack_s": None},     # no deadline: infinite slack
    ]
    assert dec.replay_victim(cands)["slot"] == 2
    assert dec.replay_victim(cands, worse_than=2) is None
    # slot-order tie break: first strictly-greater key wins
    tie = [dict(c, deadline_slack_s=1.0, priority=1) for c in cands]
    assert dec.replay_victim(tie)["slot"] == 0


def test_replay_place_fewest_inflight_lowest_index():
    assert dec.replay_place({"loads": {"0": 2, "1": 1, "2": 1}}) == "1"
    assert dec.replay_place({"loads": {1: 0, 0: 0}}) == 0


def test_validator_catches_tampered_records():
    rec = dec.build_record(
        "preempt",
        {"worse_than": None, "candidates": [
            {"slot": 0, "request_id": 7, "tenant": "a", "priority": 2,
             "deadline_slack_s": None}]},
        {"victim_slot": 0, "victim_request_id": 7}, "scheduler", 1.0)
    assert dec.validate_records([rec]) == []
    bad = json.loads(json.dumps(rec))
    bad["outcome"]["victim_slot"] = 1      # tampered outcome: caught
    assert any("victim slot" in e for e in dec.validate_records([bad]))
    shed = dec.build_record(
        "shed", {"priority": 2, "shed_priority": 2, "queue_depth": 9,
                 "shed_watermark": 4},
        {"reason": "queue depth 9 >= watermark 4"}, "scheduler", 1.0,
        tenant="b")
    assert dec.validate_records([shed]) == []
    shed["inputs"]["queue_depth"] = 1      # inputs no longer shed
    assert any("do not shed" in e for e in dec.validate_records([shed]))


# ------------------------------- the two-tenant burst acceptance (ISSUE 15)

def test_two_tenant_burst_decisions_and_per_tenant_burn(tiny, tmp_path):
    """THE acceptance run: tenant `spike` bursts 8x into a small pool
    behind tenant `steady`. Sheds and preemptions happen; every one is
    reproducible from its decisions.v1 record after a JSON round trip;
    the per-tenant summary decomposes TTFT per tenant; and the
    per-tenant burn gauges land in a fleet-merged snapshot."""
    jsonl = str(tmp_path / "serve.jsonl")
    traffic = load_harness.TrafficConfig(
        users=6, requests=24, prefix_len=8, max_new_tokens=4, seed=3,
        tenants={"steady": 100.0, "spike": 100.0},
        burst={"tenant": "spike", "t0": 0.0, "dur_s": 0.2, "mult": 8.0})
    decisions = []
    summary = load_harness.run_harness(
        tiny, "paged", traffic, slots=3, max_len=32, block_size=4,
        num_blocks=10, prefix_cache=False, max_queue=64,
        shed_watermark=3, virtual_step_s=0.01,
        serve_jsonl=jsonl, decision_sink=decisions,
        metrics_out=str(tmp_path / "metrics.jsonl"))
    # the mix actually stressed the scheduler
    sheds = [d for d in decisions if d["action"] == "shed"]
    preempts = [d for d in decisions if d["action"] == "preempt"]
    assert summary["shed"] > 0 and sheds
    assert summary["preempted"] > 0 and preempts
    # reproducibility through the artifact: parse the JSONL back and
    # replay every decision from its recorded inputs
    recs = [json.loads(line) for line in open(jsonl) if line.strip()]
    assert serve_report.validate_records(recs) == []
    disk_decs = [r for r in recs if r["kind"] == "decision"]
    assert len(disk_decs) == len(decisions)
    assert dec.validate_records(disk_decs) == []
    # preempt records carry the candidate table their victim beat
    assert all(len(d["inputs"]["candidates"]) >= 1 for d in preempts)
    # per-tenant replay summary: both tenants decompose
    ts = summary["tenants"]
    assert set(ts) == {"steady", "spike"}
    for t in ts.values():
        assert t["requests"] > 0
    assert any(t["ttft_p99_s"] is not None for t in ts.values())
    # the per-tenant burn actually REGISTERED the burst: spike shed
    # requests, so its failure SLO burns over the replay window (the
    # baseline primes fresh tenants' series at zero — first sight must
    # not swallow the burst)
    burn = summary["tenant_slo_burn"]
    shed_tenants = [t for t, s in ts.items() if s["shed"] > 0]
    assert shed_tenants                       # the burst shed someone
    for t in shed_tenants:
        assert burn[f"failures@{t}"]["fast"] > 0.0, (t, burn)
    # the tenant-labeled burn gauges exist — and survive a fleet merge
    snap = metrics.registry().snapshot()
    merged = fleet.merge_snapshots(
        [{"worker_id": "w0", "role": "decode", "snapshot": snap}])
    flat = metrics.flatten_snapshot(merged)
    for t in ("steady", "spike"):
        key = (f"serving_slo_burn{{role=decode,slo=ttft,tenant={t},"
               f"window=fast,worker_id=w0}}")
        assert key in flat, sorted(
            k for k in flat if "slo=ttft" in k)
    # the shed growth is attributed per tenant in the counters
    shed_flat = {k: v for k, v in
                 metrics.flatten_snapshot(snap).items()
                 if k.startswith("serving_shed_total{")}
    assert any("tenant=" in k for k in shed_flat)
    # ... and the serve_report render names tenants in its tables
    text = serve_report.render(serve_report.summarize(recs))
    assert "decision audit log" in text
    assert "preemption-victim attribution" in text


def test_tenant_labels_are_observability_only(tiny):
    """The zero-cost contract: identical engine configs, one scheduler
    labeled and one not — greedy token streams AND engine trace counts
    are bit-identical, because tenant/cohort never reach the engine."""
    rng = np.random.RandomState(17)
    prompts = [rng.randint(0, 1000, 5).tolist() for _ in range(3)]
    streams, traces = [], []
    for label in (None, "acme"):
        eng = PagedGenerationEngine(tiny, slots=2, max_len=32,
                                    block_size=4, num_blocks=12,
                                    enable_prefix_cache=False)
        sched = Scheduler(eng, max_queue=8)
        hs = [sched.submit(p, max_new_tokens=4, tenant=label,
                           cohort="interactive" if label else None)
              for p in prompts]
        sched.run_until_idle()
        assert all(h.status == "DONE" for h in hs)
        streams.append([h.tokens for h in hs])
        traces.append(json.dumps(
            {k: (sorted(v.items()) if isinstance(v, dict) else v)
             for k, v in eng.trace_counts.items()}, default=str))
    assert streams[0] == streams[1]        # bit-identical output
    assert traces[0] == traces[1]          # zero trace/compile changes


# ----------------------------------------------------------- bench trend

def test_bench_trend_classifies_the_committed_history(tmp_path):
    """r01 is the only healthy committed round; r03-r05 are the wedged
    grant (rc=124 / backend-probe-hung zeros) and must be excluded from
    the trend AND never chosen as the compare baseline; r02 (a real
    OOM) is FAILED, not WEDGED."""
    paths = sorted(
        os.path.join(_ROOT, f) for f in os.listdir(_ROOT)
        if f.startswith("BENCH_r") and f.endswith(".json"))
    assert len(paths) >= 5
    rows = bench_trend.load_rows(paths)
    by_run = {r["run"]: r for r in rows}
    assert by_run["r01"]["class"] == bench_trend.HEALTHY
    assert by_run["r01"]["value"] > 0
    assert by_run["r02"]["class"] == bench_trend.FAILED
    for r in ("r03", "r04", "r05"):
        assert by_run[r]["class"] == bench_trend.WEDGED, by_run[r]
    base = bench_trend.healthy_baseline(rows)
    assert base["run"] == "r01"
    # JSONL + render round trip
    out = str(tmp_path / "trend.jsonl")
    assert bench_trend.main([*paths, "--jsonl", out]) == 0
    trend = [json.loads(line) for line in open(out)]
    assert all(t["schema"] == bench_trend.SCHEMA for t in trend)
    text = bench_trend.render(rows)
    assert "WEDGED" in text and "compare baseline: r01" in text
