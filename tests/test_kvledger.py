"""KV-memory attribution plane (ISSUE 16): block lifecycle ledger,
per-tenant HBM accounting, and the live leak/invariant watchdog.

The load-bearing properties:
  - a mixed two-tenant load-harness run (priority mix, burst-driven
    sheds/preemptions, prefix-cache hits) streams kvledger.v1 records
    into the serving JSONL, and replaying them after a JSON round trip
    reconstructs the real BlockPool's final free list and refcounts
    EXACTLY — the on-disk event log is the proof there is no leak;
  - the injected `serving.kv_ledger_leak` fault (pool skips one
    free-list return the ledger recorded) is caught by LedgerReconciler
    at the very step boundary it happened, latches
    `serving_kv_ledger_divergence_total{invariant=free_list}`, dumps a
    postmortem once, and gates `metrics_report --compare` as a
    failure-class regression from a clean baseline;
  - the ledger is OBSERVABILITY-ONLY: disabled vs enabled, every engine
    kind (dense/paged/spec/tp/pp) emits bit-identical token streams
    with identical trace counts;
  - PrefixCache.evictable() and eviction accounting stay consistent
    with the ledger's shadow model under COW chain sharing;
  - per-tenant residency lands everywhere it should: load_harness
    summaries + serving_load_tenant_kv_blocks_* gauges, fleet-merged
    serving_kv_blocks{tenant,kind} series, serve_report's residency and
    prefix-share tables;
  - tools/bench_trend.py --json emits the machine-readable document.
"""
import json
import os
import sys

import numpy as np
import pytest

from paddle_tpu.observability import faults, fleet, flight_recorder
from paddle_tpu.observability import kvledger
from paddle_tpu.observability import metrics
from paddle_tpu.serving import (BlockPool, PagedGenerationEngine,
                                PrefixCache, Scheduler)
from paddle_tpu.text.models import gpt_tiny

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_ROOT, "tools"))
import bench_trend  # noqa: E402
import load_harness  # noqa: E402
import metrics_report  # noqa: E402
import serve_report  # noqa: E402


@pytest.fixture(scope="module")
def tiny():
    m = gpt_tiny()
    m.eval()
    return m


def _divergence_total():
    snap = metrics.registry().snapshot()
    return sum(s["value"] for m in snap["metrics"]
               if m["name"] == "serving_kv_ledger_divergence_total"
               for s in m["samples"])


# ------------------------------------------------- the shadow model rules

def test_shadow_records_impossible_transitions():
    sh = kvledger.ShadowPool(4)
    sh.apply({"seq": 0, "event": "alloc", "blocks": [1], "tenant": "a"})
    sh.apply({"seq": 1, "event": "alloc", "blocks": [1], "tenant": "a"})
    sh.apply({"seq": 2, "event": "ref", "blocks": [2], "tenant": "a"})
    sh.apply({"seq": 3, "event": "unref", "blocks": [3], "tenant": "a"})
    sh.apply({"seq": 4, "event": "free", "blocks": [1], "tenant": "a"})
    assert len(sh.errors) == 4          # double alloc, ref/unref of
    assert "double alloc" in sh.errors[0]       # free, free with refs
    # the shadow keeps tracking a diverged pool instead of raising
    assert 1 not in sh.allocated


def test_holder_classification_and_drop_preference():
    """One block, three holders of three kinds; unrefs drop the right
    one: the evict-origin drops the cache's own, a request-id match
    drops that request's, tenant fallbacks come after."""
    led = kvledger.KVLedger(8)
    with kvledger.attribution(request_id=1, tenant="a", origin="prefill"):
        led.pool_alloc([3])                           # a/private
        with kvledger.origin_scope("prefix_cache.insert"):
            led.pool_ref(3)                           # a/cached
        led.cache_insert((3,))
    with kvledger.attribution(request_id=2, tenant="b", origin="prefill"):
        with kvledger.origin_scope("prefix_cache.match"):
            led.pool_ref(3)                           # b/shared
        led.cache_share((3,), tokens=4)
    tk = led.shadow.tenant_kind_blocks()
    assert tk == {("a", "private"): 1, ("a", "cached"): 1,
                  ("b", "shared"): 1}
    # request 2 retires: its shared holding drops, cache + private stay
    with kvledger.attribution(request_id=2, tenant="b", origin="retire"):
        led.pool_unref(3)
    assert led.shadow.tenant_kind_blocks() == \
        {("a", "private"): 1, ("a", "cached"): 1}
    # eviction drops the cache's own reference, not request 1's
    with kvledger.attribution(request_id=None, tenant=None,
                              origin="prefix_cache.evict"):
        led.cache_evict((3,))
        led.pool_unref(3)
    assert led.shadow.tenant_kind_blocks() == {("a", "private"): 1}
    assert led.shadow.cached == {}
    with kvledger.attribution(request_id=1, tenant="a", origin="retire"):
        led.pool_unref(3)
        led.pool_free(3)
    assert not led.shadow.errors
    assert led.shadow.tenant_resident_totals() == {}
    assert led.shadow.free_set() == {1, 2, 3, 4, 5, 6, 7}


def test_attribution_context_nests_and_restores():
    assert kvledger.current_attribution() is None
    with kvledger.attribution(request_id=7, tenant="t", origin="prefill"):
        with kvledger.origin_scope("prefix_cache.match"):
            cur = kvledger.current_attribution()
            assert cur == {"request_id": 7, "tenant": "t",
                           "origin": "prefix_cache.match"}
        assert kvledger.current_attribution()["origin"] == "prefill"
    assert kvledger.current_attribution() is None


# -------------------- evictable()/eviction accounting under COW sharing

def test_prefix_cache_evictable_and_eviction_accounting_under_cow():
    """Satellite: the cache's evictable() figure and its eviction
    bookkeeping agree with the ledger's shadow at every stage of a COW
    chain's life — insert, cross-request share, staggered retires,
    leaf-first eviction — with every reconciler invariant (including
    the evictable one) green throughout."""
    pool = BlockPool(num_blocks=8, block_size=4)
    ledger = kvledger.KVLedger(8, block_bytes=64)
    pool.attach_ledger(ledger)
    cache = PrefixCache(pool, 4)
    cache.attach_ledger(ledger)
    recon = kvledger.LedgerReconciler(ledger, pool, cache)
    prompt = list(range(12))
    with kvledger.attribution(request_id=1, tenant="a", origin="prefill"):
        row = pool.alloc(3)
        cache.insert(prompt, row, 8)          # 2 full blocks cached
    assert recon.check() == []
    assert cache.evictable() == 0             # request 1 still co-owns
    with kvledger.attribution(request_id=2, tenant="b", origin="prefill"):
        ids, n = cache.match(prompt)          # COW share of the chain
    assert ids == row[:2] and n == 8
    assert recon.check() == []
    tk = ledger.shadow.tenant_kind_blocks()
    assert tk[("a", "private")] == 3
    assert tk[("a", "cached")] == 2
    assert tk[("b", "shared")] == 2
    # nothing evictable while shared, and evict() must not free anything
    assert cache.evictable() == 0
    assert cache.evict(8) == 0 and len(cache) == 2
    assert recon.check() == []
    with kvledger.attribution(request_id=1, tenant="a", origin="retire"):
        for b in row:
            pool.unref(b)                     # row[2] frees, chain stays
    with kvledger.attribution(request_id=2, tenant="b", origin="retire"):
        for b in ids:
            pool.unref(b)
    assert recon.check() == []
    assert cache.evictable() == 2             # cache-only now
    assert ledger.shadow.tenant_kind_blocks() == {("a", "cached"): 2}
    # leaf-first eviction drains the chain and the pool reconstructs
    assert cache.evict(8) == 2 and len(cache) == 0
    assert recon.check() == []
    assert pool.available == pool.capacity
    assert ledger.shadow.free_set() == set(pool._free)
    assert not ledger.shadow.errors


# -------------------------- THE acceptance run: mixed load, exact replay

def test_mixed_burst_run_ledger_replay_reconstructs_the_pool(
        tiny, tmp_path):
    """Two-tenant burst through a small paged pool WITH the prefix
    cache: priority mix, sheds, preemptions, prefix hits. The full
    kvledger.v1 stream lands in the serving JSONL; parsed back, it
    replays into the pool's exact final free list + refcounts, the
    per-tenant residency reaches the harness summary, the
    serving_load_tenant/serving_kv gauges, the fleet merge, and
    serve_report's tables — with zero reconciler divergences."""
    div0 = _divergence_total()
    jsonl = str(tmp_path / "serve.jsonl")
    traffic = load_harness.TrafficConfig(
        users=6, requests=24, prefix_len=8, max_new_tokens=4, seed=3,
        tenants={"steady": 100.0, "spike": 100.0},
        burst={"tenant": "spike", "t0": 0.0, "dur_s": 0.2, "mult": 8.0})
    engines = []
    summary = load_harness.run_harness(
        tiny, "paged", traffic, slots=3, max_len=32, block_size=4,
        num_blocks=10, prefix_cache=True, max_queue=64,
        shed_watermark=3, virtual_step_s=0.01, serve_jsonl=jsonl,
        engine_sink=engines,
        metrics_out=str(tmp_path / "metrics.jsonl"))
    engine = engines[0]
    ledger = engine.kv_ledger
    assert ledger is not None and len(ledger.events) > 0
    # the mix actually exercised every lifecycle path
    assert summary["shed"] > 0
    assert summary["preempted"] > 0
    events_by_kind = {}
    recs = [json.loads(line) for line in open(jsonl) if line.strip()]
    kv_recs = [r for r in recs if r["kind"] == "kvledger"]
    for r in kv_recs:
        events_by_kind[r["event"]] = events_by_kind.get(r["event"], 0) + 1
    assert events_by_kind.get("share", 0) > 0          # prefix hits
    assert events_by_kind.get("cache_insert", 0) > 0
    # every event reached the JSONL, schema-valid
    assert len(kv_recs) == len(ledger.events)
    assert serve_report.validate_records(recs) == []
    # THE replay: the round-tripped stream reconstructs the real pool
    pool = engine.block_pool
    shadow = kvledger.replay_events(kv_recs, pool.num_blocks)
    assert not shadow.errors
    assert shadow.refs == [int(r) for r in pool._refs]
    assert shadow.free_set() == set(int(b) for b in pool._free)
    # zero leaks: everything still resident is a prefix-cache holding
    assert set(shadow.allocated) == set(shadow.cached)
    assert _divergence_total() == div0          # reconciler stayed green
    # per-tenant residency in the harness summary...
    ts = summary["tenants"]
    assert set(ts) == {"steady", "spike"}
    assert summary["kv_blocks_peak"] > 0
    assert any(t["kv_blocks_peak"] > 0 for t in ts.values())
    assert all("kv_blocks_mean" in t for t in ts.values())
    # ...in the harness gauges...
    flat = metrics.flatten_snapshot(metrics.registry().snapshot())
    assert any(k.startswith("serving_load_tenant_kv_blocks_peak{")
               for k in flat)
    assert any(k.startswith("serving_load_tenant_kv_blocks_mean{")
               for k in flat)
    # ...and relabeled per worker through the fleet merge
    merged = fleet.merge_snapshots(
        [{"worker_id": "w0", "role": "decode",
          "snapshot": metrics.registry().snapshot()}])
    mflat = metrics.flatten_snapshot(merged)
    kv_keys = [k for k in mflat if k.startswith("serving_kv_blocks{")
               and "worker_id=w0" in k and "tenant=" in k]
    assert kv_keys, sorted(k for k in mflat
                           if k.startswith("serving_kv"))[:10]
    # serve_report renders the residency + prefix-share tables
    digest = serve_report.summarize(recs)
    assert digest["kvledger_events"] == len(kv_recs)
    res = digest["kv_residency"]
    assert set(res["tenants"]) <= {"steady", "spike", "default"}
    text = serve_report.render(digest)
    assert "KV residency" in text
    assert "prefix-chain sharing" in text


# ------------------------------ the leak chaos test + the compare gate

def test_injected_leak_caught_within_one_step_and_gates_compare(
        tiny, tmp_path, capsys):
    """Chaos: `serving.kv_ledger_leak` (truncate) makes the pool skip
    one free-list return. The reconciler must latch the free_list
    divergence AT the step boundary of the very step the leak happened,
    name the leaked block, dump one postmortem — and the divergence
    counter must gate `metrics_report --compare` as failure-class from
    a clean zero baseline."""
    flight_recorder.enable(dir=str(tmp_path / "pm"))
    engine = PagedGenerationEngine(tiny, slots=2, max_len=32,
                                   block_size=4, num_blocks=12,
                                   enable_prefix_cache=False)
    sched = Scheduler(engine, max_queue=8)
    assert sched._kv_reconciler is not None
    baseline = str(tmp_path / "base.jsonl")
    after = str(tmp_path / "after.jsonl")
    metrics.registry().write_snapshot(baseline)
    rng = np.random.RandomState(5)
    spec = faults.arm("serving.kv_ledger_leak", "truncate", nth=1,
                      max_fires=1)
    try:
        hs = [sched.submit(rng.randint(0, 1000, 5).tolist(),
                           max_new_tokens=4) for _ in range(2)]
        while True:
            more = sched.step()
            if spec.fires:
                # caught at the SAME step boundary the damage happened
                assert sched._kv_reconciler.divergences, \
                    "leak not latched within one scheduler step"
                break
            if not more:
                break
        assert spec.fires == 1, "fault never fired (no block was freed)"
        msgs = sched._kv_reconciler.divergences
        assert any("free_list" in m and "leaked" in m for m in msgs), msgs
        sched.run_until_idle()
        assert all(h.status == "DONE" for h in hs)
        # one postmortem, latched once
        pm = sched._kv_reconciler.last_postmortem
        assert pm and os.path.exists(pm)
        metrics.registry().write_snapshot(after)
    finally:
        faults.disarm("serving.kv_ledger_leak")
    # the CI gate: divergence growth from the primed-zero baseline is a
    # failure-class regression
    rc = metrics_report.main(["--compare", baseline, after])
    out = capsys.readouterr().out
    assert rc == 1
    assert "serving_kv_ledger_divergence_total" in out


def test_metrics_report_failure_class_matches_divergence_and_leak():
    assert metrics_report._FAIL_PAT.search(
        "serving_kv_ledger_divergence_total")
    assert metrics_report._FAIL_PAT.search("serving_kv_ledger_leak")


# ----------------------- the zero-cost contract across every engine kind

@pytest.mark.parametrize("kind", ["dense", "paged", "spec", "tp", "pp"])
def test_ledger_disabled_streams_bit_identical(tiny, kind):
    """Ledger enabled vs disabled: identical greedy token streams AND
    identical trace counts for every engine kind — observability must
    never touch device code or compile behavior."""
    import jax
    need = {"tp": 2, "pp": 2}.get(kind, 1)
    if len(jax.devices()) < need:
        pytest.skip(f"{kind} needs {need} devices")
    rng = np.random.RandomState(11)
    prompts = [rng.randint(0, 1000, 5).tolist() for _ in range(2)]
    streams, traces, ledgers = [], [], []
    for on in (True, False):
        (kvledger.enable if on else kvledger.disable)()
        try:
            eng = load_harness.build_engine(
                tiny, kind, slots=2, max_len=32, block_size=4,
                num_blocks=12, prefix_cache=False, tp=2, pp=2,
                draft_layers=1)
        finally:
            kvledger.enable()
        sched = Scheduler(eng, max_queue=8)
        hs = [sched.submit(p, max_new_tokens=4) for p in prompts]
        sched.run_until_idle()
        assert all(h.status == "DONE" for h in hs)
        streams.append([h.tokens for h in hs])
        traces.append(json.dumps(
            {k: (sorted(v.items(), key=str) if isinstance(v, dict)
                 else v)
             for k, v in eng.trace_counts.items()}, default=str))
        ledgers.append(getattr(eng, "kv_ledger", None))
    assert streams[0] == streams[1]        # bit-identical output
    assert traces[0] == traces[1]          # zero trace/compile changes
    # enabled run attached a ledger exactly when there is a pool
    assert ledgers[1] is None
    if kind == "dense":
        assert ledgers[0] is None
    else:
        assert ledgers[0] is not None and len(ledgers[0].events) > 0


def test_block_bytes_priced_from_pool_dtype(tiny):
    """serving_kv_bytes prices a block from the engine's pool dtype:
    the f32/int8 figures must mirror bench's equal-HBM block math."""
    f32 = PagedGenerationEngine(tiny, slots=2, max_len=32, block_size=4,
                                num_blocks=6, enable_prefix_cache=False)
    cfg = tiny.cfg
    h, d = cfg.num_heads, cfg.hidden_size // cfg.num_heads
    assert f32._kv_block_bytes() == 2 * (4 * h * d * 4) * cfg.num_layers
    q = PagedGenerationEngine(tiny, slots=2, max_len=32, block_size=4,
                              num_blocks=6, enable_prefix_cache=False,
                              kv_dtype="int8")
    assert q._kv_block_bytes() == 2 * (4 * h * d + 4 * h) * cfg.num_layers
    assert f32.kv_ledger.block_bytes == f32._kv_block_bytes()


def test_fleet_priming_creates_kv_children_at_zero():
    fleet.prime_tenant_series(["primed_t"])
    flat = metrics.flatten_snapshot(metrics.registry().snapshot())
    for kind in ("private", "shared", "cached"):
        assert flat[
            f"serving_kv_blocks{{kind={kind},tenant=primed_t}}"] == 0
        assert flat[
            f"serving_kv_bytes{{kind={kind},tenant=primed_t}}"] == 0


# ----------------------------------------------------- bench trend --json

def test_bench_trend_json_document(capsys):
    paths = sorted(
        os.path.join(_ROOT, f) for f in os.listdir(_ROOT)
        if f.startswith("BENCH_r") and f.endswith(".json"))
    assert bench_trend.main([*paths, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["schema"] == bench_trend.SCHEMA
    assert len(doc["rows"]) == len(paths)
    assert doc["baseline"]["run"] == "r01"
    assert doc["rows"] == bench_trend.load_rows(paths)
