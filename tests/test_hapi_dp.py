"""Model.fit auto data parallelism (VERDICT r1 item 7; BASELINE "BERT-base
DP over 8 cores via the high-level API").

Reference: hapi/model.py:190 wraps the network in DataParallel and feeds a
DistributedBatchSampler. TPU-native: when a global mesh with a 'dp' axis is
installed, Model's jit-compiled train step shards the batch over 'dp' via
in_shardings and the GSPMD partitioner inserts the gradient all-reduce —
numerically identical to single-device training.
"""
import numpy as np
import pytest

import jax

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed import env as dist_env
from paddle_tpu.text.models import Bert, BertConfig


@pytest.fixture
def dp_mesh():
    prev = dist_env.get_mesh()
    mesh = dist_env.build_mesh({"dp": 8})
    yield mesh
    dist_env._global_mesh = prev


def _mlp_losses(n_steps=4, batch=16):
    paddle.seed(3)
    net = nn.Sequential(nn.Flatten(), nn.Linear(12, 32), nn.ReLU(),
                        nn.Linear(32, 4))
    m = paddle.Model(net)
    m.prepare(opt.Adam(1e-2, parameters=net.parameters()),
              nn.CrossEntropyLoss())
    rng = np.random.RandomState(0)
    losses = []
    for _ in range(n_steps):
        x = rng.rand(batch, 12).astype("float32")
        y = rng.randint(0, 4, batch)
        (l,), _ = m.train_batch([x], [y])
        losses.append(l)
    return losses


def test_model_fit_dp_matches_single_device(dp_mesh):
    dp_losses = _mlp_losses()
    dist_env._global_mesh = None
    single = _mlp_losses()
    np.testing.assert_allclose(dp_losses, single, rtol=2e-5, atol=1e-6)


def test_model_dp_step_is_really_sharded(dp_mesh):
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 4))
    m = paddle.Model(net)
    m.prepare(opt.SGD(0.1, parameters=net.parameters()),
              nn.CrossEntropyLoss())
    x = np.random.rand(16, 8).astype("float32")
    y = np.random.randint(0, 4, 16)
    m.train_batch([x], [y])
    assert m._dp_mesh() is dp_mesh          # the sharded step was built


def test_model_dp_ragged_batch_falls_back(dp_mesh):
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 4))
    m = paddle.Model(net)
    m.prepare(opt.SGD(0.1, parameters=net.parameters()),
              nn.CrossEntropyLoss())
    for b in (16, 13):                      # 13 % 8 != 0 -> replicated path
        x = np.random.rand(b, 8).astype("float32")
        y = np.random.randint(0, 4, b)
        (l,), _ = m.train_batch([x], [y])
        assert np.isfinite(l)
    assert m._train_step_plain is not None


def test_bert_tiny_fit_dp8(dp_mesh):
    """BASELINE row: BERT (tiny config) trains DP x 8 through Model.fit."""
    paddle.seed(5)
    cfg = BertConfig(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                     num_attention_heads=2, intermediate_size=64,
                     max_position_embeddings=32)

    class BertCls(nn.Layer):
        def __init__(self):
            super().__init__()
            self.bert = Bert(cfg)
            self.head = nn.Linear(32, 2)

        def forward(self, ids):
            seq, pooled = self.bert(ids)
            return self.head(pooled)

    net = BertCls()
    m = paddle.Model(net)
    m.prepare(opt.Adam(1e-3, parameters=net.parameters()),
              nn.CrossEntropyLoss())
    rng = np.random.RandomState(0)
    losses = []
    for _ in range(3):
        ids = rng.randint(0, 128, (16, 16))
        y = rng.randint(0, 2, 16)
        (l,), _ = m.train_batch([ids], [y])
        losses.append(l)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] + 0.5     # training, not diverging


def test_reduce_lr_on_plateau_callback():
    """hapi ReduceLROnPlateau (reference hapi/callbacks.py): flat metric
    shrinks the LR every `patience` epochs; improvement resets the wait."""
    import paddle_tpu.optimizer as opt
    from paddle_tpu.hapi.callbacks import ReduceLROnPlateau

    net = paddle.nn.Linear(4, 2)
    m = paddle.Model(net)
    o = opt.SGD(0.1, parameters=net.parameters())
    m.prepare(o, paddle.nn.CrossEntropyLoss())
    cb = ReduceLROnPlateau(patience=1, factor=0.5, verbose=0)
    cb.model = m
    cb.on_train_begin()
    cb.on_epoch_end(0, {"loss": 1.0})          # sets best
    cb.on_epoch_end(1, {"loss": 1.0})          # plateau -> 0.05
    assert abs(o.get_lr() - 0.05) < 1e-9
    cb.on_epoch_end(2, {"loss": 0.5})          # improvement resets wait
    cb.on_epoch_end(3, {"loss": 0.5})          # plateau -> 0.025
    assert abs(o.get_lr() - 0.025) < 1e-9
