"""Real multi-process distributed execution (VERDICT r2 missing #1).

TestDistBase-equivalent (reference test_dist_base.py:792-1029): fork 2 actual
worker processes that rendezvous via jax.distributed (coordination service),
then assert (a) an 8-way cross-process psum value and (b) that the 2-process
DP loss trajectory equals the 1-process golden bit-for-bit-close.
"""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
WORKER = os.path.join(HERE, "dist_worker.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _scrubbed_env():
    env = dict(os.environ)
    # never touch a real accelerator from the forked trainers
    for k in list(env):
        if (k.startswith(("TPU_", "LIBTPU", "PJRT_", "AXON_", "PALLAS_AXON_"))
                or k in ("JAX_PLATFORM_NAME", "XLA_FLAGS", "JAX_PLATFORMS")):
            env.pop(k)
    env["PYTHONPATH"] = os.path.dirname(HERE)
    return env


def _run_workers(nproc, tmpdir, worker=WORKER, prefix="worker", timeout=300):
    port = _free_port()
    procs, outs = [], []
    for pid in range(nproc):
        out = os.path.join(tmpdir, f"{prefix}_{nproc}_{pid}.json")
        outs.append(out)
        procs.append(subprocess.Popen(
            [sys.executable, worker, str(pid), str(nproc), str(port), out],
            env=_scrubbed_env(),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    results = []
    for p, out in zip(procs, outs):
        stdout, stderr = p.communicate(timeout=timeout)
        assert p.returncode == 0, \
            f"worker rc={p.returncode}\nstdout:{stdout[-2000:]}\nstderr:{stderr[-4000:]}"
        with open(out) as f:
            results.append(json.load(f))
    return results


@pytest.fixture(scope="module")
def runs(tmp_path_factory):
    tmpdir = str(tmp_path_factory.mktemp("dist"))
    golden = _run_workers(1, tmpdir)[0]
    two = _run_workers(2, tmpdir)
    return golden, two


def test_two_process_rendezvous(runs):
    _, two = runs
    assert [r["process_count"] for r in two] == [2, 2]


def test_cross_process_psum(runs):
    golden, two = runs
    # sum of ranks+1 over 8 global devices = 36, on every process
    assert golden["psum"] == 36.0
    assert [r["psum"] for r in two] == [36.0, 36.0]


def test_eager_cross_process_collectives(runs):
    """Eager all_reduce/broadcast/barrier across 2 processes (VERDICT r3
    item 6): per-process values reduced OUTSIDE any trace, same result on
    both; barrier() rendezvoused (worker asserts the count internally)."""
    golden, two = runs
    # 1-process world: all_reduce over one rank is identity
    assert golden["eager_allreduce"] == [1.0, 1.0, 1.0]
    # 2-process: sum of (1, 2) = 3 on BOTH processes
    assert [r["eager_allreduce"] for r in two] == [[3.0] * 3, [3.0] * 3]
    assert [r["eager_max"] for r in two] == [[2.0] * 2, [2.0] * 2]
    # broadcast from process 1: both see process 1's value (20)
    assert [r["eager_bcast"] for r in two] == [[20.0] * 2, [20.0] * 2]


def test_dp_loss_matches_single_process_golden(runs):
    golden, two = runs
    for r in two:
        np.testing.assert_allclose(r["losses"], golden["losses"], rtol=1e-6)
    # and training actually progressed
    assert golden["losses"][-1] < golden["losses"][0]


# --------------------------------------------------------------------------
# HYBRID plans across the process boundary (VERDICT r4 next #3): the
# flagship train step with pp (plan 1) / mp (plan 2) axes spanning both
# processes — the single-controller DCN claim behind the FleetExecutor
# descope, now executed rather than asserted.
# --------------------------------------------------------------------------
HYBRID_WORKER = os.path.join(HERE, "dist_hybrid_worker.py")


@pytest.fixture(scope="module")
def hybrid_runs(tmp_path_factory):
    tmpdir = str(tmp_path_factory.mktemp("dist_hybrid"))
    kw = dict(worker=HYBRID_WORKER, prefix="hybrid", timeout=900)
    golden = _run_workers(1, tmpdir, **kw)[0]
    two = _run_workers(2, tmpdir, **kw)
    return golden, two


def test_hybrid_pp_across_process_boundary(hybrid_runs):
    """dp2 x pp2 x mp2 with pipeline stage 1 living entirely on process 1:
    3-step loss trajectory must match the single-process golden."""
    golden, two = hybrid_runs
    assert [r["process_count"] for r in two] == [2, 2]
    for r in two:
        np.testing.assert_allclose(r["dp2_pp2_mp2_pp_cross"],
                                   golden["dp2_pp2_mp2_pp_cross"], rtol=1e-5)
    assert golden["dp2_pp2_mp2_pp_cross"][-1] < \
        golden["dp2_pp2_mp2_pp_cross"][0]


def test_hybrid_mp_across_process_boundary(hybrid_runs):
    """dp4 x mp2 with each tensor-parallel pair split across the two
    processes: the mp allreduce rides the host boundary every step."""
    golden, two = hybrid_runs
    for r in two:
        np.testing.assert_allclose(r["dp4_mp2_mp_cross"],
                                   golden["dp4_mp2_mp_cross"], rtol=1e-5)
    assert golden["dp4_mp2_mp_cross"][-1] < golden["dp4_mp2_mp_cross"][0]


def test_hybrid_sharding_across_process_boundary(hybrid_runs):
    """dp4 x sharding2 (ZeRO-2) with each sharding pair split across the
    two processes: the grad reduce-scatter and param all-gather cross the
    host boundary every step; 3-step losses must match the 1-process
    golden."""
    golden, two = hybrid_runs
    for r in two:
        np.testing.assert_allclose(r["dp4_sharding2_sharding_cross"],
                                   golden["dp4_sharding2_sharding_cross"],
                                   rtol=1e-5)
    assert golden["dp4_sharding2_sharding_cross"][-1] < \
        golden["dp4_sharding2_sharding_cross"][0]
