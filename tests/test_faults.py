"""Fault-injection harness + self-healing fabric (ISSUE 5).

The registry itself (deterministic triggers, env arming, stacking), the
PS RPC retry/dedup/breaker machinery under injected drops and delays
(recovery must be BIT-EXACT vs the fault-free run), serving decode
degradation (quarantine + reprobe instead of a wedged scheduler), the
AsyncCommunicator lossless-flush contract, and the metrics_report
failure-class treatment of retry counters. The chaos smoke at the
bottom is the tier-1 guard: a short training loop with low-probability
faults armed must land on the fault-free table state exactly.
"""
import os
import sys
import time

import numpy as np
import pytest

from paddle_tpu.distributed.ps import (AsyncCommunicator, PSClient,
                                       PSServer, PSUnavailableError,
                                       RetryPolicy, SparseTable)
from paddle_tpu.observability import faults, metrics

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))
import metrics_report  # noqa: E402

DIM = 4


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.disarm_all()
    yield
    faults.disarm_all()


def _counter_value(name, **labels):
    flat = metrics.flatten_snapshot(metrics.registry().snapshot(),
                                    kinds=("counter",))
    key = name
    if labels:
        key += "{" + ",".join(f"{k}={labels[k]}"
                              for k in sorted(labels)) + "}"
    return flat.get(key, 0.0)


# ---------------------------------------------------------------- registry

def test_spec_probability_is_seed_deterministic():
    a = faults.FaultSpec("x.site", "delay", p=0.3, seed=5)
    b = faults.FaultSpec("x.site", "delay", p=0.3, seed=5)
    seq_a = [a._should_fire() for _ in range(200)]
    seq_b = [b._should_fire() for _ in range(200)]
    assert seq_a == seq_b
    assert any(seq_a) and not all(seq_a)
    c = faults.FaultSpec("x.site", "delay", p=0.3, seed=6)
    assert [c._should_fire() for _ in range(200)] != seq_a


def test_nth_trigger_and_max_fires():
    faults.arm("t.nth", "raise", nth=3)
    fired = []
    for i in range(1, 10):
        try:
            faults.fire("t.nth")
            fired.append(False)
        except faults.FaultInjected:
            fired.append(True)
    assert [i for i, f in zip(range(1, 10), fired) if f] == [3, 6, 9]

    faults.disarm_all()
    faults.arm("t.max", "raise", nth=2, max_fires=1)
    hits = 0
    for _ in range(8):
        try:
            faults.fire("t.max")
        except faults.FaultInjected:
            hits += 1
    assert hits == 1


def test_disarmed_site_is_quiet_and_free():
    assert faults.fire("never.armed") is None
    faults.arm("other.site", "raise")
    assert faults.fire("never.armed") is None


def test_env_parsing_and_stacking():
    specs = faults.load_env(
        "ps.rpc.send=drop:p=0.25:seed=7;ps.rpc.send=delay:delay=0.01;"
        "checkpoint.write=truncate:nth=2:max=1")
    assert len(specs) == 3
    send = faults.armed("ps.rpc.send")
    assert [s.mode for s in send] == ["drop", "delay"]
    assert send[0].p == 0.25 and send[0].seed == 7
    assert send[1].delay_s == 0.01
    ck = faults.armed("checkpoint.write")[0]
    assert (ck.mode, ck.nth, ck.max_fires) == ("truncate", 2, 1)
    with pytest.raises(ValueError):
        faults.load_env("justasite")
    with pytest.raises(ValueError):
        faults.load_env("a.site=raise:bogus=1")


def test_truncate_outranks_delay_when_both_fire():
    """truncate + delay stacked on one site (the SIGKILL-window combo):
    the caller must receive the truncate spec regardless of arm order."""
    faults.arm("t.combo", "truncate")
    faults.arm("t.combo", "delay", delay_s=0.0)
    assert faults.fire("t.combo").mode == "truncate"
    faults.disarm_all()
    faults.arm("t.combo", "delay", delay_s=0.0)
    faults.arm("t.combo", "truncate")
    assert faults.fire("t.combo").mode == "truncate"


def test_fired_fault_counts_in_registry():
    before = _counter_value("faults_injected_total", site="t.metric",
                            mode="delay")
    faults.arm("t.metric", "delay", delay_s=0.0)
    faults.fire("t.metric")
    after = _counter_value("faults_injected_total", site="t.metric",
                           mode="delay")
    assert after == before + 1


# ------------------------------------------------------------ PS self-heal

def _fast_retry(**kw):
    kw.setdefault("max_attempts", 6)
    kw.setdefault("base_delay_s", 0.001)
    kw.setdefault("seed", 0)
    return RetryPolicy(**kw)


def _cluster(n=2, **client_kw):
    servers = [PSServer(SparseTable(DIM, rule="sgd", lr=1.0, seed=s))
               for s in range(n)]
    client_kw.setdefault("retry", _fast_retry())
    client = PSClient([s.endpoint for s in servers], DIM, **client_kw)
    return servers, client


def _teardown(servers, client):
    client.close()
    for s in servers:
        s.shutdown()


def _workload(client, steps=6):
    """Deterministic pull/push loop; returns the final pulled rows."""
    keys = np.array([0, 1, 2, 3, 10, 11], np.int64)
    for step in range(steps):
        rows = client.pull(keys)
        grads = (rows * 0.1 + step).astype(np.float32)
        client.push(keys, grads)
    return client.pull(keys)


def test_injected_drops_recover_bit_exact():
    servers, client = _cluster()
    want = _workload(client)
    _teardown(servers, client)

    r0 = _counter_value("ps_retries_total", verb="PULL") + \
        _counter_value("ps_retries_total", verb="PUSH")
    faults.arm("ps.rpc.send", "drop", p=0.15, seed=3)
    servers, client = _cluster()
    try:
        got = _workload(client)
    finally:
        faults.disarm_all()
        _teardown(servers, client)
    np.testing.assert_array_equal(got, want)
    r1 = _counter_value("ps_retries_total", verb="PULL") + \
        _counter_value("ps_retries_total", verb="PUSH")
    assert r1 > r0, "the fault schedule must have forced at least one retry"


def test_push_dedup_applies_exactly_once():
    """Reply-lost PUSH: the server applied it, the client retries it, the
    dedup id must keep the gradient from landing twice."""
    servers, client = _cluster(n=1)
    try:
        keys = np.array([42], np.int64)
        before = client.pull(keys)
        # fire #2 is the post-send window of the first PUSH attempt
        faults.arm("ps.rpc.send", "drop", nth=2, max_fires=1)
        client.push(keys, np.ones((1, DIM), np.float32))
        faults.disarm_all()
        after = client.pull(keys)
        # sgd lr=1.0: exactly ONE application decrements by exactly 1.0
        np.testing.assert_allclose(after, before - 1.0, rtol=1e-6)
    finally:
        faults.disarm_all()
        _teardown(servers, client)


def test_push_dedup_concurrent_retry_waits_for_inflight_apply():
    """Check-then-act race: a client-timeout retry arriving while the
    ORIGINAL apply is still running server-side must wait on the
    in-progress sentinel, not apply again."""

    class _SlowTable:
        def __init__(self, inner):
            self.inner, self.dim = inner, inner.dim
            self.pushes = 0

        def pull(self, keys):
            return self.inner.pull(keys)

        def push(self, keys, grads):
            self.pushes += 1
            time.sleep(0.4)          # longer than the client's timeout
            self.inner.push(keys, grads)

    slow = _SlowTable(SparseTable(DIM, rule="sgd", lr=1.0, seed=0))
    server = PSServer(slow)
    probe = PSClient([server.endpoint], DIM)          # no timeout
    client = PSClient([server.endpoint], DIM, request_timeout_s=0.15,
                      retry=_fast_retry(max_attempts=5, base_delay_s=0.01))
    try:
        keys = np.array([7], np.int64)
        before = probe.pull(keys)
        try:
            client.push(keys, np.ones((1, DIM), np.float32))
        except PSUnavailableError:
            pass                     # budget may expire; the apply may not
        time.sleep(1.0)              # let every server thread settle
        after = probe.pull(keys)
        np.testing.assert_allclose(after, before - 1.0, rtol=1e-6)
        assert slow.pushes == 1      # the retries never re-applied
    finally:
        probe.close()
        client.close()
        server.shutdown()


def test_server_error_restores_pooled_socket_timeout():
    """A PSServerError reply keeps the socket; the deadline-shrunken
    per-attempt timeout must not leak onto it."""
    from paddle_tpu.distributed.ps.rpc import PSServerError
    server = PSServer(table=None)    # PULL raises a serving error
    client = PSClient([server.endpoint], DIM,
                      retry=RetryPolicy(max_attempts=2, base_delay_s=0.01,
                                        deadline_s=30.0, seed=0))
    try:
        with pytest.raises(PSServerError):
            client.pull(np.array([1], np.int64))
        assert client._socks[0] is not None          # socket was kept
        assert client._socks[0].gettimeout() == client._request_timeout
    finally:
        client.close()
        server.shutdown()


def test_push_identity_rerandomizes_across_fork():
    """Parent and forked child must never emit colliding (client_id,
    seq) pairs — the dedup LRU would silently drop real gradients."""
    c = PSClient(["127.0.0.1:1"], DIM)
    cid1, seq1 = c._next_push_reqid()
    assert (cid1, seq1)[1] == 1
    # simulate a fork: the cached identity carries a foreign pid
    pid, cid, ctr = c._push_ident
    c._push_ident = (pid - 1, cid, ctr)
    cid2, seq2 = c._next_push_reqid()
    assert cid2 != cid1          # fresh 64-bit id (collision p ~ 2^-64)
    assert seq2 == 1             # and a fresh sequence
    c.close()


def test_push_seen_trim_never_evicts_inflight_sentinel(monkeypatch):
    """LRU overflow must only evict APPLIED markers — evicting a live
    in-progress Event reopens the double-apply race."""
    from paddle_tpu.distributed.ps import rpc as rpc_mod
    monkeypatch.setattr(rpc_mod, "_PUSH_SEEN_CAP", 3)
    server = PSServer(SparseTable(DIM, rule="sgd", lr=1.0, seed=0))
    try:
        state, ev = server._push_begin(("inflight", 0))
        assert state == "mine"
        for i in range(6):
            st, e2 = server._push_begin(("done", i))
            assert st == "mine"
            server._push_end(("done", i), e2, applied=True)
        assert server._push_seen[("inflight", 0)] is ev   # survived
        assert sum(1 for v in server._push_seen.values()
                   if v is True) <= 3
    finally:
        server.shutdown()


def test_breaker_opens_then_half_open_probe_recovers():
    servers, client = _cluster(
        n=1, retry=_fast_retry(max_attempts=1),
        breaker_threshold=2, breaker_cooldown_s=0.1)
    try:
        endpoint = servers[0].endpoint
        gauge = metrics.registry().gauge("ps_breaker_state",
                                         labelnames=("endpoint",))
        faults.arm("ps.rpc.send", "drop", max_fires=2)
        with pytest.raises(PSUnavailableError):
            client.ping()                       # failure 1
        with pytest.raises(PSUnavailableError):
            client.ping()                       # failure 2 -> OPEN
        assert gauge.labels(endpoint=endpoint).value == 1
        with pytest.raises(PSUnavailableError, match="breaker is open"):
            client.ping()                       # fast-fail, no socket work
        time.sleep(0.15)                        # cooldown elapses
        assert client.ping()                    # half-open probe succeeds
        assert gauge.labels(endpoint=endpoint).value == 0
    finally:
        faults.disarm_all()
        _teardown(servers, client)


def test_connect_failure_counts_and_surfaces_cleanly():
    before = _counter_value("ps_errors_total", side="client")
    client = PSClient(["127.0.0.1:1"], DIM, connect_timeout_s=0.2,
                      retry=_fast_retry(max_attempts=2))
    t0 = time.monotonic()
    with pytest.raises(PSUnavailableError):
        client.ping()
    assert time.monotonic() - t0 < 5.0
    assert _counter_value("ps_errors_total", side="client") >= before + 2
    client.close()


def test_timeout_knobs_env_and_kwargs(monkeypatch):
    monkeypatch.delenv("PTN_PS_CONNECT_TIMEOUT_S", raising=False)
    monkeypatch.delenv("PTN_PS_REQUEST_TIMEOUT_S", raising=False)
    c = PSClient(["127.0.0.1:1"], DIM)
    # the pre-retry fabric's 30s socket timeout is the default — a hung
    # server must surface, not block forever
    assert c._request_timeout == 30.0 and c._connect_timeout == 30.0
    c.close()
    c = PSClient(["127.0.0.1:1"], DIM, request_timeout_s=0)
    assert c._request_timeout is None        # 0 opts into blocking
    c.close()
    monkeypatch.setenv("PTN_PS_CONNECT_TIMEOUT_S", "1.5")
    monkeypatch.setenv("PTN_PS_REQUEST_TIMEOUT_S", "2.5")
    c = PSClient(["127.0.0.1:1"], DIM)
    assert c._connect_timeout == 1.5
    assert c._request_timeout == 2.5
    c.close()
    # kwargs win over env
    c = PSClient(["127.0.0.1:1"], DIM, connect_timeout_s=0.5,
                 request_timeout_s=0.75)
    assert c._connect_timeout == 0.5
    assert c._request_timeout == 0.75
    c.close()


def test_deadline_bounds_a_wedged_shard():
    """A server that accepts but never replies must not hang a caller
    whose verb carries a deadline — the remaining budget becomes the
    attempt's socket timeout."""
    import socket as socketlib
    lsock = socketlib.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(1)
    host, port = lsock.getsockname()
    client = PSClient(
        [f"{host}:{port}"], DIM,
        retry=RetryPolicy(max_attempts=3, base_delay_s=0.01,
                          deadline_s=0.3, seed=0))
    try:
        t0 = time.monotonic()
        with pytest.raises(PSUnavailableError):
            client.ping()
        assert time.monotonic() - t0 < 3.0
    finally:
        client.close()
        lsock.close()


def test_deadline_expiry_during_backoff_counts_one_failure():
    """A deadline that lapses while SLEEPING between retries must not
    register a second breaker failure — one real fault, one count."""
    servers, client = _cluster(
        n=1, retry=RetryPolicy(max_attempts=5, base_delay_s=0.2,
                               jitter=0.0, deadline_s=0.05, seed=0),
        breaker_threshold=10)
    try:
        faults.arm("ps.rpc.send", "drop", max_fires=1)
        with pytest.raises(PSUnavailableError, match="deadline exhausted"):
            client.ping()
        assert client._breakers[0]._fails == 1
    finally:
        faults.disarm_all()
        _teardown(servers, client)


def test_deadline_bounds_connect_time():
    """The per-verb deadline clamps the TCP connect timeout too — a
    blackholed shard cannot consume the full connect_timeout."""
    client = PSClient(
        ["10.255.255.1:9", ], DIM, connect_timeout_s=5.0,
        retry=RetryPolicy(max_attempts=2, base_delay_s=0.01,
                          deadline_s=0.3, seed=0))
    try:
        t0 = time.monotonic()
        with pytest.raises(PSUnavailableError):
            client.ping()
        assert time.monotonic() - t0 < 4.0
    finally:
        client.close()


def test_per_verb_deadline():
    policy = RetryPolicy(deadline_s={"PULL": 0.5, "PUSH": 2.0})
    assert policy.deadline_for("PULL") == 0.5
    assert policy.deadline_for("PUSH") == 2.0
    assert policy.deadline_for("GSAMPLE") is None
    flat = RetryPolicy(deadline_s=1.0)
    assert flat.deadline_for("PULL") == 1.0


# ------------------------------------------------------- chaos train smoke

def test_chaos_smoke_converges_to_fault_free_state():
    """Tier-1 chaos guard: drop(p=0.05) + delay(p=0.05) armed on the PS
    send path, a short embedding training loop must land BIT-EXACTLY on
    the fault-free final table state (retries are invisible to the
    math; PUSH dedup keeps gradients exactly-once)."""
    servers, client = _cluster()
    want = _workload(client, steps=8)
    _teardown(servers, client)

    faults.arm("ps.rpc.send", "drop", p=0.05, seed=3)
    faults.arm("ps.rpc.send", "delay", p=0.05, delay_s=0.002, seed=4)
    servers, client = _cluster()
    try:
        got = _workload(client, steps=8)
    finally:
        faults.disarm_all()
        _teardown(servers, client)
    np.testing.assert_array_equal(got, want)


SERVER_SCRIPT = r"""
import sys
sys.path.insert(0, sys.argv[3])
from paddle_tpu.distributed.ps import PSServer, SparseTable
srv = PSServer(SparseTable(4, rule="sgd", lr=1.0, seed=int(sys.argv[2])))
with open(sys.argv[1], "w") as f:
    f.write(srv.endpoint)
import time
while not srv._stop.is_set():
    time.sleep(0.1)
"""


def _forked_cluster(tmp_path, tag):
    import subprocess
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo
    env["JAX_PLATFORMS"] = "cpu"
    env.pop(faults.ENV_VAR, None)   # faults are CLIENT-side in this test
    procs, endpoints = [], []
    for seed in range(2):
        ep_file = str(tmp_path / f"ep_{tag}_{seed}.txt")
        procs.append(subprocess.Popen(
            [sys.executable, "-c", SERVER_SCRIPT, ep_file, str(seed), repo],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))
        endpoints.append(ep_file)
    eps = []
    for ep_file in endpoints:
        for _ in range(200):
            if os.path.exists(ep_file) and open(ep_file).read().strip():
                break
            time.sleep(0.1)
        eps.append(open(ep_file).read().strip())
    return procs, eps


def test_two_forked_server_chaos_run_bit_exact(tmp_path):
    """The acceptance run: real server processes, drop+delay armed on the
    client's PS send path at p=0.05 — the training loop's final table
    state must equal the fault-free run's exactly."""
    finals = []
    for tag, with_faults in (("clean", False), ("chaos", True)):
        procs, eps = _forked_cluster(tmp_path, tag)
        client = PSClient(eps, DIM, retry=_fast_retry())
        try:
            if with_faults:
                faults.arm("ps.rpc.send", "drop", p=0.05, seed=3)
                faults.arm("ps.rpc.send", "delay", p=0.05, delay_s=0.002,
                           seed=4)
            finals.append(_workload(client, steps=6))
        finally:
            faults.disarm_all()
            client.stop_servers()
            client.close()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except Exception:       # noqa: BLE001
                    p.kill()
    np.testing.assert_array_equal(finals[0], finals[1])


# ------------------------------------------------------ serving degradation

class _StubConfig:
    eos_token_id = None
    max_len = 64


class _StubEngine:
    """Minimal engine contract for Scheduler: decode() runs through the
    real fault site semantics."""

    def __init__(self, slots=2):
        self.config = _StubConfig()
        self.slots = slots
        self.max_prompt_len = 8
        self.resets = []

    def prefill(self, slot, prompt):
        return 1

    def decode(self):
        faults.fire("serving.decode_step")
        return np.full((self.slots,), 2, np.int32)

    def reset_slot(self, slot):
        self.resets.append(slot)


def test_decode_failure_fails_only_inflight_and_reprobes():
    from paddle_tpu.serving.scheduler import DONE, ERROR, Scheduler
    eng = _StubEngine(slots=2)
    s = Scheduler(eng, max_queue=8, default_max_new_tokens=3)
    h1 = s.submit([1, 2])
    h2 = s.submit([3, 4])
    h3 = s.submit([5, 6])
    fail_before = _counter_value("serving_decode_failures_total")
    faults.arm("serving.decode_step", "raise", max_fires=1)
    s.step()            # both slots prefill, decode raises
    assert h1.status == ERROR and h2.status == ERROR
    assert h1.done() and h2.done()
    assert "fault-injection" in h1.error
    assert h1.tokens == [1]                  # partial output survives
    assert _counter_value("serving_decode_failures_total") == fail_before + 1
    # quarantine: one probe slot released, the other held out
    assert len(s._quarantined) == 1
    s.step()            # probe slot serves h3; success lifts quarantine
    assert s._quarantined == set()
    s.run_until_idle()
    assert h3.status == DONE
    assert h3.tokens == [1, 2, 2]
    assert s.counts["serving.error"] == 2
    assert s.counts["serving.completed"] == 1


def test_decode_failure_quarantines_free_slots_too():
    """With free slots at failure time, the refill must still be limited
    to ONE probe — not a whole batch fed into the next failing step."""
    from paddle_tpu.serving.scheduler import ERROR, Scheduler
    eng = _StubEngine(slots=4)
    s = Scheduler(eng, max_queue=16, default_max_new_tokens=3)
    h1 = s.submit([1, 2])                    # ONE request, 3 slots free
    faults.arm("serving.decode_step", "raise", max_fires=1)
    s.step()                                 # h1 prefills; decode raises
    assert h1.status == ERROR
    assert len(s._quarantined) == eng.slots - 1
    later = [s.submit([9, 9]) for _ in range(6)]
    s.step()                                 # only the probe slot refills
    assert sum(1 for q in later if q.status != "QUEUED") == 1
    s.run_until_idle()
    assert all(q.done() for q in later)


def test_prefill_failure_contained_and_scheduler_continues():
    """A prefill exception fails only the request being placed; the
    scheduler keeps running and later requests still complete."""
    from paddle_tpu.serving.scheduler import DONE, ERROR, Scheduler

    class _PrefillOnceBroken(_StubEngine):
        def __init__(self, slots=2):
            super().__init__(slots)
            self.fail_next_prefill = True

        def prefill(self, slot, prompt):
            if self.fail_next_prefill:
                self.fail_next_prefill = False
                raise RuntimeError("prefill boom")
            return 1

    eng = _PrefillOnceBroken(slots=2)
    s = Scheduler(eng, max_queue=8, default_max_new_tokens=2)
    h1 = s.submit([1, 2])
    h2 = s.submit([3, 4])
    s.run_until_idle()
    assert h1.status == ERROR and "prefill boom" in h1.error
    assert h1.done()                          # the future never leaks
    assert h2.status == DONE
    assert s.counts["serving.error"] == 1


def test_predictor_generate_is_loud_on_decode_failure():
    """The batch API has no consumer of handle.status — a decode failure
    must raise, never return silently truncated generations."""
    from paddle_tpu.inference import Predictor
    from paddle_tpu.serving.scheduler import Scheduler
    eng = _StubEngine(slots=2)
    sched = Scheduler(eng, max_queue=8, default_max_new_tokens=3)
    pred = Predictor.__new__(Predictor)
    pred._generation_scheduler = lambda **kw: sched
    faults.arm("serving.decode_step", "raise", max_fires=1)
    with pytest.raises(RuntimeError, match="decode failed"):
        Predictor.generate(pred, [[1, 2], [3, 4]], max_new_tokens=3)
    faults.disarm_all()
    # healthy engine: same call path succeeds
    sched2 = Scheduler(_StubEngine(slots=2), max_queue=8,
                       default_max_new_tokens=3)
    pred._generation_scheduler = lambda **kw: sched2
    out = Predictor.generate(pred, [[1, 2]], max_new_tokens=3)
    assert out == [[1, 2, 2]]


def test_decode_failure_never_wedges_drain():
    from paddle_tpu.serving.scheduler import Scheduler
    eng = _StubEngine(slots=2)
    s = Scheduler(eng, max_queue=8, default_max_new_tokens=2)
    handles = [s.submit([i]) for i in range(5)]
    faults.arm("serving.decode_step", "raise", nth=2)   # every 2nd step
    s.drain(max_steps=200)
    assert all(h.done() for h in handles)


# --------------------------------------------------- communicator lossless

class _BlockingTable:
    def __init__(self):
        import threading
        self.release = threading.Event()
        self.dim = DIM

    def push(self, keys, grads):
        self.release.wait(10)


class _FailingTable:
    dim = DIM

    def push(self, keys, grads):
        raise ConnectionError("shard dark")


def test_flush_timeout_reports_unflushed_count():
    t = _BlockingTable()
    comm = AsyncCommunicator(t, merge_batches=1)
    comm.start()
    comm.push_sparse(np.array([1], np.int64), np.ones((1, DIM), np.float32))
    with pytest.raises(TimeoutError) as ei:
        comm.flush(timeout=0.2)
    assert ei.value.unflushed >= 1
    t.release.set()
    comm.flush(timeout=5.0)          # drains cleanly once unblocked
    comm.stop()


def test_flush_surfaces_background_push_failure():
    comm = AsyncCommunicator(_FailingTable(), merge_batches=1)
    comm.start()
    comm.push_sparse(np.array([1], np.int64), np.ones((1, DIM), np.float32))
    with pytest.raises(RuntimeError, match="dropped") as ei:
        comm.flush(timeout=5.0)
    assert isinstance(ei.value.__cause__, ConnectionError)
    comm.stop()


# ----------------------------------------------------- metrics_report gate

def _snap(**counters):
    mets = []
    for name, samples in counters.items():
        mets.append({"name": name, "type": "counter", "help": "",
                     "labelnames": sorted({k for s, _ in samples
                                           for k in s}),
                     "samples": [{"labels": labels, "value": v}
                                 for labels, v in samples]})
    return {"schema": metrics_report.SCHEMA, "ts": 0.0, "pid": 1,
            "metrics": mets}


def test_retries_are_failure_class_in_compare():
    a = _snap(ps_retries_total=[({"verb": "PULL"}, 2.0)],
              serving_tokens_total=[({}, 100.0)])
    b = _snap(ps_retries_total=[({"verb": "PULL"}, 40.0)],
              serving_tokens_total=[({}, 100.0)])
    regs = metrics_report.compare_counters(a, b)
    assert len(regs) == 1
    key, _, _, _, why = regs[0]
    assert key.startswith("ps_retries_total")
    assert why == "failure counter grew"
    # and the same growth in a work counter is NOT a regression
    a2 = _snap(serving_tokens_total=[({}, 2.0)])
    b2 = _snap(serving_tokens_total=[({}, 40.0)])
    assert metrics_report.compare_counters(a2, b2) == []
