"""End-to-end slice (SURVEY §7 step 4): models train and loss decreases."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.io import DataLoader
from paddle_tpu.vision.datasets import FakeData


class TinyCNN(nn.Layer):
    def __init__(self, num_classes=10):
        super().__init__()
        self.conv1 = nn.Conv2D(3, 8, 3, padding=1)
        self.bn1 = nn.BatchNorm2D(8)
        self.relu = nn.ReLU()
        self.pool = nn.MaxPool2D(2, 2)
        self.conv2 = nn.Conv2D(8, 16, 3, padding=1)
        self.fc = nn.Linear(16 * 8 * 8, num_classes)

    def forward(self, x):
        x = self.pool(self.relu(self.bn1(self.conv1(x))))
        x = self.pool(self.relu(self.conv2(x)))
        return self.fc(x.flatten(1))


def test_eager_training_loss_decreases():
    """Learnable synthetic task: label = argmax over channel means."""
    rng = np.random.RandomState(0)
    images = rng.rand(64, 3, 32, 32).astype(np.float32)
    labels = images.mean(axis=(2, 3)).argmax(axis=1).astype(np.int64)

    net = TinyCNN(num_classes=3)
    optimizer = opt.Adam(learning_rate=1e-3, parameters=net.parameters())
    loss_fn = nn.CrossEntropyLoss()

    first = last = None
    for epoch in range(8):
        total = 0.0
        for i in range(0, 64, 16):
            x = paddle.to_tensor(images[i:i + 16])
            y = paddle.to_tensor(labels[i:i + 16])
            loss = loss_fn(net(x), y)
            loss.backward()
            optimizer.step()
            optimizer.clear_grad()
            total += float(loss)
        if first is None:
            first = total
        last = total
    assert last < first * 0.7, f"loss did not decrease: {first} -> {last}"


def test_model_fit_api():
    """Model.fit over the compiled functional train step."""
    from paddle_tpu.metric import Accuracy

    train_ds = FakeData(num_samples=64, image_shape=(3, 16, 16), num_classes=4)

    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(3 * 16 * 16, 32)
            self.relu = nn.ReLU()
            self.fc2 = nn.Linear(32, 4)

        def forward(self, x):
            return self.fc2(self.relu(self.fc1(x.flatten(1))))

    model = paddle.Model(MLP())
    model.prepare(optimizer=opt.Adam(learning_rate=1e-3,
                                     parameters=model.parameters()),
                  loss=nn.CrossEntropyLoss(),
                  metrics=Accuracy())
    model.fit(train_ds, batch_size=16, epochs=2, verbose=0)
    res = model.evaluate(train_ds, batch_size=16)
    assert "acc" in res

    preds = model.predict(train_ds, batch_size=16, stack_outputs=True)
    assert preds[0].shape == (64, 4)


def test_model_fit_bn_buffers_update():
    """BN running stats must update through the jit path."""
    net = TinyCNN(num_classes=3)
    model = paddle.Model(net)
    model.prepare(optimizer=opt.SGD(learning_rate=0.01,
                                    parameters=model.parameters()),
                  loss=nn.CrossEntropyLoss())
    ds = FakeData(num_samples=16, image_shape=(3, 32, 32), num_classes=3)
    before = net.bn1._mean.numpy().copy()
    model.fit(ds, batch_size=8, epochs=1, verbose=0)
    after = net.bn1._mean.numpy()
    assert not np.allclose(before, after)


def test_dataloader():
    ds = FakeData(num_samples=20, image_shape=(3, 8, 8), num_classes=2)
    dl = DataLoader(ds, batch_size=6, shuffle=True, drop_last=False)
    batches = list(dl)
    assert len(batches) == 4
    assert batches[0][0].shape == [6, 3, 8, 8]
    assert batches[-1][0].shape == [2, 3, 8, 8]
    dl = DataLoader(ds, batch_size=6, drop_last=True, num_workers=2)
    assert sum(1 for _ in dl) == 3


def test_lenet_forward():
    from paddle_tpu.vision.models import LeNet
    net = LeNet()
    x = paddle.to_tensor(np.random.rand(2, 1, 28, 28).astype(np.float32))
    assert net(x).shape == [2, 10]


def test_resnet18_forward_and_one_step():
    from paddle_tpu.vision.models import resnet18
    net = resnet18(num_classes=10)
    x = paddle.to_tensor(np.random.rand(2, 3, 32, 32).astype(np.float32))
    out = net(x)
    assert out.shape == [2, 10]
    loss = nn.CrossEntropyLoss()(out, paddle.to_tensor(np.array([1, 2], np.int64)))
    loss.backward()
    o = opt.SGD(learning_rate=0.01, parameters=net.parameters())
    o.step()
    assert all(p._grad_data is not None or p.stop_gradient
               for p in net.parameters())


def test_gpt_tiny_forward_loss():
    from paddle_tpu.text.models import gpt_tiny
    net = gpt_tiny()
    ids = paddle.to_tensor(np.random.randint(0, 1024, (2, 16)).astype(np.int64))
    logits = net(ids)
    assert logits.shape == [2, 16, 1024]
    labels = paddle.to_tensor(np.random.randint(0, 1024, (2, 16)).astype(np.int64))
    loss = net.loss(ids, labels)
    loss.backward()
    assert float(loss) > 0


def test_to_static_jit():
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    fn = paddle.jit.to_static(net)
    x = paddle.to_tensor(np.random.rand(3, 4).astype(np.float32))
    eager_out = net(x).numpy()
    jit_out = fn.forward(x).numpy() if hasattr(fn, "forward") else fn(x).numpy()
    np.testing.assert_allclose(eager_out, jit_out, rtol=1e-5, atol=1e-6)
