"""Worker for the HYBRID multi-process distributed test (VERDICT r4 next #3).

The DCN-shaped proof behind the FleetExecutor descope: the flagship
make_train_step hybrid plans run over a 2-process global mesh whose device
array is reordered so a MODEL axis — pp (pipeline send/recv) in plan 1,
mp (tensor-parallel allreduce) in plan 2 — crosses the process boundary,
not just dp. The reference does this with brpc p2p across pods
(fleet/meta_parallel/pp_utils/p2p_communication.py:286, ProcessGroupHeter);
here the single-controller SPMD program spans both processes and XLA's
cross-host collectives carry the axis.

Invoked as: dist_hybrid_worker.py <process_id> <num_processes> <port> <out>
num_processes=1 produces the single-process golden on the same 8 devices.
"""
import json
import os
import sys


def main():
    pid, nproc, port, out_path = (int(sys.argv[1]), int(sys.argv[2]),
                                  sys.argv[3], sys.argv[4])
    n_local = 8 // nproc
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={n_local}").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PADDLE_TRAINERS_NUM"] = str(nproc)
    os.environ["PADDLE_TRAINER_ID"] = str(pid)
    os.environ["PADDLE_MASTER"] = f"127.0.0.1:{port}"

    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    import paddle_tpu as paddle

    paddle.distributed.init_parallel_env()
    assert jax.process_count() == nproc, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()

    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from paddle_tpu.parallel import GPTSpmdConfig, MeshPlan, make_train_step
    from paddle_tpu.parallel.gpt_spmd import AXES

    devs = np.asarray(jax.devices())
    results = {"process_count": jax.process_count()}

    def global_arr(np_val, mesh, spec):
        np_val = np.asarray(np_val)
        sh = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(np_val.shape, sh,
                                            lambda idx: np_val[idx])

    def run(plan, mesh, tag):
        cfg = GPTSpmdConfig(vocab_size=64 * plan.mp, max_seq_len=64,
                            hidden=16 * plan.mp, layers=2 * plan.pp,
                            heads=plan.mp * 2, ffn=32 * plan.mp,
                            remat=False, fused_ce_chunks=4)
        B = 4 * plan.dp * plan.sharding * plan.microbatches
        S = 16 * plan.sp
        step_fn, init_fn, _ = make_train_step(cfg, plan, mesh=mesh,
                                              learning_rate=1e-3)
        params, state = init_fn(jax.random.key(0))
        rng = np.random.RandomState(0)
        data_spec = P(("dp", "sharding"), "sp")
        toks = global_arr(rng.randint(0, cfg.vocab_size, (B, S)),
                          mesh, data_spec)
        labs = global_arr(rng.randint(0, cfg.vocab_size, (B, S)),
                          mesh, data_spec)
        lr = global_arr(np.float32(1e-3), mesh, P())
        losses = []
        for _ in range(3):
            loss, params, state = step_fn(params, state, toks, labs, lr)
            losses.append(float(np.asarray(jax.device_get(loss))))
        results[tag] = losses

    # plan 1: dp2 x pp2 x mp2 with the PIPELINE axis crossing the process
    # boundary — device array reordered so pp is the slowest-varying axis
    # (pp stage 0 = devices 0-3 = process 0; stage 1 = process 1)
    plan1 = MeshPlan(dp=2, pp=2, mp=2, microbatches=2)
    arr1 = devs.reshape(plan1.pp, plan1.dp, plan1.mp).transpose(1, 0, 2)
    mesh1 = Mesh(arr1.reshape(plan1.dp, plan1.pp, 1, 1, plan1.mp), AXES)
    run(plan1, mesh1, "dp2_pp2_mp2_pp_cross")

    # plan 2: dp4 x mp2 with the TENSOR-PARALLEL allreduce crossing the
    # boundary (mp group spans both processes)
    plan2 = MeshPlan(dp=4, mp=2)
    arr2 = devs.reshape(plan2.mp, plan2.dp).transpose(1, 0)
    mesh2 = Mesh(arr2.reshape(plan2.dp, 1, 1, 1, plan2.mp), AXES)
    run(plan2, mesh2, "dp4_mp2_mp_cross")

    # plan 3: dp4 x sharding2 with the ZeRO-2 SHARDING axis crossing the
    # boundary — each reduce-scatter/all-gather pair {devs[d], devs[d+4]}
    # spans both processes (sharding is the slowest-varying axis)
    plan3 = MeshPlan(dp=4, sharding=2)
    arr3 = devs.reshape(plan3.sharding, plan3.dp).transpose(1, 0)
    mesh3 = Mesh(arr3.reshape(plan3.dp, 1, plan3.sharding, 1, 1), AXES)
    run(plan3, mesh3, "dp4_sharding2_sharding_cross")

    with open(out_path, "w") as f:
        json.dump(results, f)


if __name__ == "__main__":
    main()
