"""auto_parallel: ProcessMesh / shard_tensor annotations / Engine.

Mirrors the reference's auto-parallel suites
(unittests/auto_parallel/test_engine_api.py etc.) on the virtual 8-device
CPU mesh from conftest.
"""
import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed.auto_parallel import (Engine, ProcessMesh,
                                                  Strategy, shard_op,
                                                  shard_tensor)
from paddle_tpu.io import Dataset


class _RandDataset(Dataset):
    def __init__(self, n=64, d=8, classes=4):
        rng = np.random.RandomState(0)
        self.x = rng.rand(n, d).astype("float32")
        self.y = (self.x.sum(1) * classes / self.x.sum(1).max()).clip(
            0, classes - 1e-3).astype("int64")

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def test_process_mesh_shapes():
    pm = ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["dp", "mp"])
    assert pm.shape == [2, 4]
    assert pm.mesh.axis_names == ("dp", "mp")
    assert pm.mesh.size == 8


def test_shard_tensor_places_and_annotates():
    pm = ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["x", "y"])
    t = paddle.to_tensor(np.ones((4, 8), np.float32))
    out = shard_tensor(t, pm, ["x", "y"])
    assert out is t
    assert t._dist_attr[1] == PartitionSpec("x", "y")
    # the placed array is actually distributed over the mesh
    assert len(t._data.sharding.device_set) == 8
    # dims_mapping int form
    t2 = shard_tensor(paddle.to_tensor(np.ones((4, 8), np.float32)),
                      dist_attr={"process_mesh": pm, "dims_mapping": [0, -1]})
    assert t2._dist_attr[1] == PartitionSpec("x", None)


def test_shard_op_wraps():
    pm = ProcessMesh(np.arange(8), dim_names=["dp"])
    f = shard_op(paddle.matmul, pm,
                 in_shard_specs=[["dp", None], None],
                 out_shard_specs=[["dp", None]])
    a = paddle.to_tensor(np.ones((8, 4), np.float32))
    b = paddle.to_tensor(np.ones((4, 2), np.float32))
    out = f(a, b)
    np.testing.assert_allclose(out.numpy(), np.full((8, 2), 4.0))


def test_engine_fit_loss_decreases():
    pm = ProcessMesh(np.arange(8).reshape(4, 2), dim_names=["dp", "mp"])
    net = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))
    # Megatron-ish annotation: split the first Linear's columns over mp
    shard_tensor(net[0].weight, pm, [None, "mp"])
    engine = Engine(net, loss=nn.CrossEntropyLoss(),
                    optimizer=opt.Adam(5e-3, parameters=net.parameters()),
                    process_mesh=pm)
    hist = engine.fit(_RandDataset(), epochs=4, batch_size=16, verbose=0)
    losses = hist["loss"]
    assert losses[-1] < losses[0] * 0.9, losses


def test_engine_evaluate_and_predict():
    pm = ProcessMesh(np.arange(8), dim_names=["dp"])
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    engine = Engine(net, loss=nn.CrossEntropyLoss(),
                    optimizer=opt.SGD(1e-2, parameters=net.parameters()),
                    metrics=paddle.metric.Accuracy(),
                    process_mesh=pm)
    ds = _RandDataset()
    engine.fit(ds, epochs=1, batch_size=16, verbose=0)
    res = engine.evaluate(ds, batch_size=16)
    assert "loss" in res and np.isfinite(res["loss"])
    preds = engine.predict(ds, batch_size=16)
    assert preds[0].shape == (16, 4)


def test_engine_save_load_roundtrip(tmp_path):
    pm = ProcessMesh(np.arange(8), dim_names=["dp"])
    net = nn.Linear(8, 4)
    engine = Engine(net, loss=nn.CrossEntropyLoss(),
                    optimizer=opt.SGD(1e-2, parameters=net.parameters()),
                    process_mesh=pm)
    ds = _RandDataset()
    engine.fit(ds, epochs=1, batch_size=16, verbose=0)
    w_after = net.weight.numpy().copy()
    engine.save(str(tmp_path / "ckpt"))

    net2 = nn.Linear(8, 4)
    engine2 = Engine(net2, loss=nn.CrossEntropyLoss(),
                     optimizer=opt.SGD(1e-2, parameters=net2.parameters()),
                     process_mesh=pm)
    engine2.load(str(tmp_path / "ckpt"))
    np.testing.assert_allclose(net2.weight.numpy(), w_after)


def test_engine_strategy_amp_recompute():
    pm = ProcessMesh(np.arange(8), dim_names=["dp"])
    strat = Strategy()
    strat.amp.enable = True
    strat.recompute.enable = True
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    engine = Engine(net, loss=nn.CrossEntropyLoss(),
                    optimizer=opt.Adam(5e-3, parameters=net.parameters()),
                    strategy=strat, process_mesh=pm)
    hist = engine.fit(_RandDataset(), epochs=2, batch_size=16, verbose=0)
    assert np.isfinite(hist["loss"][-1])
