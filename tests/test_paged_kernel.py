"""Pallas paged-attention kernel (ISSUE 7 tentpole a): the in-kernel
block-table walk must be exact against the gather path in interpret
mode, hold the PR 6 NaN regressions without the dense view, serve its
tile caps through the shipped autotune table with the
fall-back-don't-raise contract, and drive the paged engine token-exactly
behind the `attention_impl="kernel"` flag with compile counts intact.
"""
import json
import os
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from paddle_tpu.incubate import autotune
from paddle_tpu.ops.pallas.paged_attention import (
    _largest_divisor_leq, paged_attention)
from paddle_tpu.serving import GenerationEngine, PagedGenerationEngine
from paddle_tpu.serving import blocks as blk
from paddle_tpu.text.models import gpt_tiny

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_ROOT, "tools"))


@pytest.fixture(scope="module")
def tiny():
    m = gpt_tiny()
    m.eval()
    return m


def _paged_state(seed, S, bs, nb, N, H=4, D=8, poison_garbage=False):
    """A valid paged KV state: every slot's table is filled with real
    blocks front-to-garbage-back, so any pos within the allocated run is
    backed (the engine invariant: blocks are allocated+written before
    they become visible)."""
    rng = np.random.RandomState(seed)
    kp = rng.randn(N, bs, H, D).astype(np.float32)
    vp = rng.randn(N, bs, H, D).astype(np.float32)
    if poison_garbage:
        kp[blk.GARBAGE_BLOCK] = np.nan
        vp[blk.GARBAGE_BLOCK] = np.inf
    # distinct physical blocks 1..N-1 dealt to slots round-robin
    perm = rng.permutation(np.arange(1, N))
    tables = np.zeros((S, nb), np.int32)
    flat = iter(perm)
    for s in range(S):
        for j in range(nb):
            tables[s, j] = next(flat)
    return jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(tables)


def _assert_matches_gather(q, kp, vp, tables, pos, **kw):
    want = np.asarray(blk.attend(q, kp, vp, tables, pos))
    got = np.asarray(paged_attention(q, kp, vp, tables, pos, **kw))
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


# ------------------------------------------------------- kernel exactness
def test_kernel_matches_gather_across_block_boundaries():
    """Decode shape (T=1) at positions crossing every boundary of the
    block ladder — including pos exactly at a block edge and one short
    of it."""
    bs, nb = 4, 6
    S = 7
    kp, vp, tables = _paged_state(0, S, bs, nb, N=S * nb + 1)
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(S, 1, 4, 8).astype(np.float32))
    # 0, edge-1, edge, edge+1, mid, last-1, last
    pos = jnp.asarray([0, 3, 4, 5, 13, 22, 23], jnp.int32)
    _assert_matches_gather(q, kp, vp, tables, pos)


def test_kernel_matches_gather_prefill_shapes():
    """Multi-token windows (prefill buckets / spec verify windows) with
    ragged per-slot occupancy."""
    bs, nb = 4, 8
    S = 3
    kp, vp, tables = _paged_state(2, S, bs, nb, N=S * nb + 1)
    rng = np.random.RandomState(3)
    for T in (2, 8, 16):
        q = jnp.asarray(rng.randn(S, T, 4, 8).astype(np.float32))
        pos = jnp.asarray([0, 5, nb * bs - T], jnp.int32)   # ragged
        _assert_matches_gather(q, kp, vp, tables, pos)


def test_kernel_poisoned_garbage_block_stays_finite():
    """The PR 6 NaN regression, in-kernel: the garbage block holds
    inf/NaN scatter junk; masked probabilities and the never-visible V
    rows must keep every output finite AND equal to the gather path."""
    bs, nb = 4, 4
    S = 2
    kp, vp, tables = _paged_state(4, S, bs, nb, N=S * nb + 1,
                                  poison_garbage=True)
    # tail table entries point at the (poisoned) garbage block — the
    # unallocated-logical-block layout prefill actually produces
    tables = np.asarray(tables).copy()
    tables[0, 2:] = blk.GARBAGE_BLOCK
    tables[1, 1:] = blk.GARBAGE_BLOCK
    tables = jnp.asarray(tables)
    rng = np.random.RandomState(5)
    q = jnp.asarray(rng.randn(S, 2, 4, 8).astype(np.float32))
    pos = jnp.asarray([6, 2], jnp.int32)     # writes stay inside owned blocks
    _assert_matches_gather(q, kp, vp, tables, pos)


def test_kernel_all_masked_rows_emit_zeros():
    """A slot with no visible key (pos<0 models a hole) emits exact
    zeros even over a fully-poisoned pool — the l==0 guard."""
    bs, nb = 4, 2
    kp, vp, tables = _paged_state(6, 1, bs, nb, N=3, poison_garbage=True)
    kp = jnp.asarray(np.full(kp.shape, np.nan, np.float32))
    vp = jnp.asarray(np.full(vp.shape, np.nan, np.float32))
    q = jnp.asarray(np.random.RandomState(7).randn(1, 1, 4, 8)
                    .astype(np.float32))
    out = np.asarray(paged_attention(q, kp, vp, tables,
                                     jnp.asarray([-1], jnp.int32)))
    assert (out == 0.0).all()


def test_kernel_tiling_caps_do_not_change_results():
    """Every (q_tile, head_tile) cap combination — divisor or not — is
    clamped to a valid tile and yields the same output."""
    bs, nb = 4, 4
    S = 2
    kp, vp, tables = _paged_state(8, S, bs, nb, N=S * nb + 1)
    rng = np.random.RandomState(9)
    q = jnp.asarray(rng.randn(S, 6, 4, 8).astype(np.float32))
    pos = jnp.asarray([1, 9], jnp.int32)
    want = np.asarray(paged_attention(q, kp, vp, tables, pos,
                                      q_tile=6, head_tile=4))
    for qt, ht in ((1, 1), (2, 2), (3, 4), (4, 3), (100, 100)):
        got = np.asarray(paged_attention(q, kp, vp, tables, pos,
                                         q_tile=qt, head_tile=ht))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_largest_divisor_clamp():
    assert _largest_divisor_leq(12, 4) == 4
    assert _largest_divisor_leq(12, 5) == 4
    assert _largest_divisor_leq(7, 4) == 1
    assert _largest_divisor_leq(1, 128) == 1
    assert _largest_divisor_leq(192, 128) == 96


# --------------------------------------------------- autotune integration
def test_shipped_table_serves_paged_entries(tmp_path, monkeypatch):
    """commit_shipped_table(kernel='paged') round-trips through
    lookup_paged_blocks; stale/poisoned entries FALL BACK to None
    instead of raising (the PR 6 contract, extended to this kernel);
    flash entries in the same file are untouched."""
    import jax
    path = str(tmp_path / "tuned.json")
    autotune.commit_shipped_table({(4, 64, 8, 4): (128, 2)},
                                  backend=jax.default_backend(),
                                  kernel="paged", path=path)
    autotune.commit_shipped_table({(4, 64, 8, True): (32, 32)},
                                  backend=jax.default_backend(),
                                  kernel="flash", path=path)
    monkeypatch.setattr(autotune, "_SHIPPED_PATH", path)
    monkeypatch.setattr(autotune, "_disk_loaded", False)
    monkeypatch.setattr(autotune, "_disk_cache", {})
    monkeypatch.setattr(autotune, "_block_cache", {})
    assert autotune.lookup_paged_blocks(4, 64, 8, 4) == (128, 2)
    assert autotune.lookup_flash_blocks(1, 4, 64, 8, True) == (32, 32)
    assert autotune.lookup_paged_blocks(4, 128, 8, 4) is None  # other geom
    # hand-rot the paged entry: lookup falls back, never raises
    raw = json.load(open(path))
    for k in list(raw):
        if json.loads(k)[0] == "paged":
            raw[k] = [0, -3]
    json.dump(raw, open(path, "w"))
    monkeypatch.setattr(autotune, "_disk_loaded", False)
    monkeypatch.setattr(autotune, "_disk_cache", {})
    assert autotune.lookup_paged_blocks(4, 64, 8, 4) is None


def test_commit_rejects_nonsense_paged_entries(tmp_path):
    with pytest.raises(ValueError, match="positive"):
        autotune.commit_shipped_table({(4, 64, 8, 4): (0, 2)},
                                      kernel="paged",
                                      path=str(tmp_path / "t.json"))
    with pytest.raises(ValueError, match="multiple"):
        autotune.commit_shipped_table({(4, 63, 8, 4): (8, 2)},
                                      kernel="paged",
                                      path=str(tmp_path / "t.json"))


def test_shipped_file_carries_both_kernels():
    """The tree's shipped table serves the flash entries it always had
    AND the new paged tile caps."""
    cache = autotune._read_cache_file(autotune._SHIPPED_PATH)
    assert any(k[0] == "paged" for k in cache)
    assert any(k[0] != "paged" for k in cache)    # untagged flash entries
    assert cache[("tpu", 12, 1024, 64, True)] == (512, 512)
    assert cache[("paged", "tpu", 12, 1024, 64, 16)] == (128, 4)


# ------------------------------------------------- engine behind the flag
def test_kernel_engine_token_exact_vs_dense(tiny):
    """The acceptance bar: attention_impl='kernel' reproduces the dense
    engine's exact greedy token streams across block-boundary prompt
    lengths, and still compiles once per executable."""
    lengths = (1, 7, 8, 9, 17, 31)
    prompts = [np.random.RandomState(20 + i).randint(0, 1000, n)
               for i, n in enumerate(lengths)]
    for i in range(0, len(lengths), 2):
        pair = prompts[i:i + 2]
        dense = GenerationEngine(tiny, slots=2, max_len=64)
        kern = PagedGenerationEngine(tiny, slots=2, max_len=64,
                                     block_size=8,
                                     attention_impl="kernel")
        rows_d = [[dense.prefill(s, p)] for s, p in enumerate(pair)]
        rows_k = [[kern.prefill(s, p)] for s, p in enumerate(pair)]
        for _ in range(5):
            sd, sk = dense.decode(), kern.decode()
            for s in range(2):
                rows_d[s].append(int(sd[s]))
                rows_k[s].append(int(sk[s]))
        assert rows_k == rows_d, \
            f"kernel diverged at lengths {[len(p) for p in pair]}"
        assert kern.trace_counts["decode"] == 1


def test_kernel_engine_ragged_occupancy_and_refill(tiny):
    """Mid-flight retire + refill at a different length (ragged slot
    occupancy) stays exact under the kernel impl — the scenario where a
    stale dense view would betray a gather bug."""
    kern = PagedGenerationEngine(tiny, slots=2, max_len=64, block_size=8,
                                 attention_impl="kernel")
    ref = PagedGenerationEngine(tiny, slots=2, max_len=64, block_size=8)
    for eng in (kern, ref):
        eng.prefill(0, _p(0, 9))
        eng.prefill(1, _p(1, 21))
        for _ in range(3):
            eng.decode()
        eng.reset_slot(0)
        eng.prefill(0, _p(2, 5))
    rows_k, rows_r = [[], []], [[], []]
    for _ in range(4):
        sk, sr = kern.decode(), ref.decode()
        for s in range(2):
            rows_k[s].append(int(sk[s]))
            rows_r[s].append(int(sr[s]))
    assert rows_k == rows_r
    assert kern.trace_counts["decode"] == 1


def _p(seed, n):
    return np.random.RandomState(seed).randint(0, 1000, n)


def test_config_rejects_unknown_impl(tiny):
    with pytest.raises(ValueError, match="attention_impl"):
        PagedGenerationEngine(tiny, slots=1, max_len=32,
                              attention_impl="fused")
