"""Quantized serving (ISSUE 11): int8 KV block pools + int8 decode weights.

Acceptance, mapped:
  - quantizing write / dequantizing gather round-trip within the int8
    scale bound, immutable fully-written blocks
    (test_quant_write_roundtrip_*);
  - int8 kernel attend == int8 gather attend on CPU: elementwise to
    float32 tolerance AND token-exact greedy streams between the two
    impls (test_quant_kernel_*);
  - quality gate: quantized engine vs the f32 oracle — teacher-forced
    greedy match >= 0.99, tiny logit KL, serving_quant_* gauges + the
    serve-report `run` record (test_quant_engine_matches_f32_oracle);
  - weight path: decode weights are exactly the fake-quant math over
    `channel_abs_max` scales, prefill params stay float
    (test_quant_weights_*);
  - composition: SpeculativeEngine with a quantized draft, and the TP
    engine with head-sharded pools + per-shard scales
    (test_spec_quant_*, test_tp_quant_*; slow tier with the chaos run —
    each builds one more engine family, the tier-1 budget is full);
  - versioned KV handoff: v2 quantized bundles round-trip losslessly
    (vs the engine's own dequant), truncation and scale-count lies are
    KVWireError, v1 stays readable (test_quant_handoff_*);
  - chaos: the serving.kv_quant fault site corrupts one block's scale
    and the quality gate catches it via metrics_report --compare
    (test_kv_quant_chaos_*);
  - quantization/observers.py: threshold determinism + the non-finite
    collect fix (test_observer_*).
"""
import json
import os
import sys

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu  # noqa: F401
from paddle_tpu.observability import faults, metrics
from paddle_tpu.quantization import fake_quant
from paddle_tpu.quantization.observers import (
    HistogramObserver, channel_abs_max, hist_percentile_threshold,
    kl_threshold, mse_threshold)
from paddle_tpu.serving import PagedGenerationEngine, blocks
from paddle_tpu.serving.distributed.kv_handoff import (
    BUNDLE_VERSION, KVWireError, QUANT_BUNDLE_VERSION, pack_kv_bundle,
    unpack_kv_bundle)
from paddle_tpu.serving.spec_decode import SpeculativeEngine
from paddle_tpu.text.models import gpt_tiny

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_ROOT, "tools"))
import load_harness  # noqa: E402
import metrics_report  # noqa: E402
import serve_report  # noqa: E402

VOCAB = 1024
ENGINE_KW = dict(slots=3, max_len=64, block_size=8)


@pytest.fixture(scope="module")
def tiny():
    m = gpt_tiny()
    m.eval()
    return m


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.RandomState(7)
    return [rng.randint(0, VOCAB, int(rng.randint(6, 20))).tolist()
            for _ in range(3)]


@pytest.fixture(scope="module")
def quant_stream(tiny, prompts):
    """One gather-impl quantized engine driven 12 greedy steps — the
    reference stream the kernel/spec/TP composition tests compare to."""
    eng = PagedGenerationEngine(tiny, kv_dtype="int8", weight_dtype="int8",
                                **ENGINE_KW)
    firsts = [eng.prefill(s, p) for s, p in enumerate(prompts)]
    stream = [[] for _ in prompts]
    for _ in range(12):
        toks = eng.decode()
        for s in range(len(prompts)):
            stream[s].append(int(toks[s]))
    return eng, firsts, stream


# ---------------------------------------------------------------- blocks

def test_quant_write_roundtrip_and_immutable_full_blocks():
    rng = np.random.RandomState(0)
    S, H, D, bs, nb, N = 2, 4, 8, 4, 4, 12
    pool = jnp.zeros((N, bs, H, D), jnp.int8)
    scale = jnp.zeros((N, H), jnp.float32)
    tables = jnp.asarray(
        rng.permutation(np.arange(1, 1 + S * nb)).reshape(S, nb), jnp.int32)
    pos = jnp.zeros((S,), jnp.int32)
    written = []
    for t in range(9):                       # crosses two block boundaries
        new = jnp.asarray(rng.randn(S, 1, H, D), jnp.float32)
        written.append(np.asarray(new)[:, 0])
        pool, scale = blocks.quant_write(pool, scale, new, tables, pos)
        if t == 3:                           # block 0 just filled (bs=4)
            frozen_codes = np.asarray(pool[tables[:, 0]])
            frozen_scale = np.asarray(scale[tables[:, 0]])
        pos = pos + 1
    # dequantized view matches the written f32 values within the int8
    # bound: |err| <= scale / (2 * 127) per element, plus bounded
    # requantization drift while a block fills
    dense = np.asarray(blocks.gather_quant(pool, scale, tables))
    want = np.stack(written, axis=1)         # [S, 9, H, D]
    err = np.abs(dense[:, :9] - want)
    bound = np.abs(want).max() * (1.5 / 127.0) + 1e-6
    assert err.max() <= bound, (err.max(), bound)
    # a fully-written block is never touched again — codes AND scale
    np.testing.assert_array_equal(np.asarray(pool[tables[:, 0]]),
                                  frozen_codes)
    np.testing.assert_array_equal(np.asarray(scale[tables[:, 0]]),
                                  frozen_scale)
    # positions never written dequantize to exact zeros (no junk scale)
    assert np.all(dense[:, 9:4 * nb] == 0.0)


def test_quant_write_valid_excludes_padding_from_scale():
    """Bucket-padded prefill: tokens past `valid` must neither ride the
    per-block abs-max scale (a one-time inflated rounding) nor leave
    nonzero codes — the quant analogue of the float path's 'padding is
    invisible' invariant."""
    rng = np.random.RandomState(2)
    H, D, bs = 2, 4, 8
    pool = jnp.zeros((3, bs, H, D), jnp.int8)
    scale = jnp.zeros((3, H), jnp.float32)
    tables = jnp.asarray([[1, 2]], jnp.int32)
    pos = jnp.zeros((1,), jnp.int32)
    new = rng.randn(1, bs, H, D).astype(np.float32)
    new[:, 5:] *= 100.0                       # huge padding junk
    p_all, s_all = blocks.quant_write(pool, scale, jnp.asarray(new),
                                      tables, pos)
    p_v, s_v = blocks.quant_write(pool, scale, jnp.asarray(new), tables,
                                  pos, valid=jnp.asarray([5], jnp.int32))
    assert float(s_all[1].max()) > 50.0       # junk DID inflate unmasked
    np.testing.assert_allclose(np.asarray(s_v[1]),
                               np.abs(new[0, :5]).max(axis=(0, 2)),
                               rtol=1e-6)
    got = np.asarray(blocks.gather_quant(p_v, s_v, tables))[0]
    assert np.all(got[5:bs] == 0.0)           # padding codes are zeros
    np.testing.assert_allclose(got[:5], new[0, :5],
                               atol=np.abs(new[0, :5]).max() / 127 + 1e-6)


def test_quant_kernel_matches_gather_attend():
    """int8 kernel attend == int8 gather attend on CPU: identical
    dequantized inputs by construction, outputs equal to f32 tolerance
    (the same contract the f32 kernel tests assert)."""
    rng = np.random.RandomState(1)
    S, T, H, D, bs, nb = 2, 4, 4, 16, 8, 3
    N = 1 + S * nb
    codes = rng.randint(-127, 128, (N, bs, H, D)).astype(np.int8)
    kc, vc = jnp.asarray(codes), jnp.asarray(codes[::-1].copy())
    ks = jnp.asarray(rng.rand(N, H).astype(np.float32) + 0.1)
    vs = jnp.asarray(rng.rand(N, H).astype(np.float32) + 0.1)
    tables = jnp.asarray(np.arange(1, N).reshape(S, nb), jnp.int32)
    pos = jnp.asarray([5, 17], jnp.int32)
    q = jnp.asarray(rng.randn(S, T, H, D), jnp.float32)
    want = blocks.attend_quant(q, kc, vc, ks, vs, tables, pos)
    got = blocks.attend_kernel_quant(q, kc, vc, ks, vs, tables, pos)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_quant_kernel_rejects_half_scales():
    from paddle_tpu.ops.pallas.paged_attention import paged_attention
    q = jnp.zeros((1, 1, 2, 4))
    pool = jnp.zeros((2, 2, 2, 4), jnp.int8)
    with pytest.raises(ValueError, match="BOTH"):
        paged_attention(q, pool, pool, jnp.zeros((1, 1), jnp.int32),
                        jnp.zeros((1,), jnp.int32),
                        k_scale=jnp.zeros((2, 2)))


# ---------------------------------------------------------------- engines

@pytest.fixture(scope="module")
def quality(tiny, tmp_path_factory):
    """One healthy quality-harness run (f32 oracle + quant engine),
    shared by the gate test and the chaos test's baseline."""
    serve_jsonl = str(tmp_path_factory.mktemp("quant") / "serve.jsonl")
    out = load_harness.quant_quality(
        tiny, slots=2, max_len=64, block_size=8, steps=12, seed=0,
        serve_metrics_path=serve_jsonl)
    return out, serve_jsonl


def test_quant_engine_matches_f32_oracle(quality):
    """The quality gate end-to-end: teacher-forced greedy match vs the
    f32 paged oracle >= 0.99 (it is 1.0 on this seed), logit KL tiny,
    gauges exported, and the serve-report `run` record appended +
    schema-valid + rendered."""
    out, serve_jsonl = quality
    assert out["greedy_match"] >= 0.99, out
    assert out["logit_kl"] < 1e-3, out
    snap = metrics.registry().snapshot()
    flat = {m["name"]: m["samples"][0]["value"] for m in snap["metrics"]
            if m["name"].startswith("serving_quant_")}
    assert flat["serving_quant_greedy_match"] == out["greedy_match"]
    assert flat["serving_quant_logit_kl"] == out["logit_kl"]
    records = serve_report.load(serve_jsonl)
    assert serve_report.validate_records(records) == []
    summary = serve_report.summarize(records)
    assert summary["kv_dtype"] == "int8"
    assert summary["weight_dtype"] == "int8"
    assert summary["quant_greedy_match"] == out["greedy_match"]
    assert "quant quality vs f32 oracle" in serve_report.render(summary)


def test_quant_kernel_engine_token_exact_vs_gather_engine(
        tiny, prompts, quant_stream):
    """'int8 kernel attend == int8 gather attend exactly on CPU' at the
    stream level: the same quantized engine under the two impls emits
    IDENTICAL greedy tokens, and both compile decode exactly once."""
    geng, gfirsts, gstream = quant_stream
    keng = PagedGenerationEngine(tiny, kv_dtype="int8", weight_dtype="int8",
                                 attention_impl="kernel", **ENGINE_KW)
    kfirsts = [keng.prefill(s, p) for s, p in enumerate(prompts)]
    assert kfirsts == gfirsts
    for step in range(6):
        toks = keng.decode()
        for s in range(len(prompts)):
            assert int(toks[s]) == gstream[s][step], (step, s)
    assert geng.trace_counts["decode"] == 1
    assert keng.trace_counts["decode"] == 1


def test_quant_weights_fake_quant_math_and_float_prefill(tiny):
    """weight_dtype='int8' decode params ARE the fake-quant math over
    channel_abs_max scales (the dormant PTQ subsystem's rule); prefill
    keeps the untouched float params; non-matmul params pass through."""
    eng = PagedGenerationEngine(tiny, weight_dtype="int8", **ENGINE_KW)
    name = "blocks.0.attn.qkv.weight"
    entry = eng._decode_params[name]
    assert isinstance(entry, dict) and entry["q"].dtype == jnp.int8
    w = np.asarray(eng._params[name], np.float32)
    ref = np.asarray(fake_quant(jnp.asarray(w),
                                jnp.asarray(channel_abs_max(w, 1)),
                                bits=8, channel_axis=1))
    got = np.asarray(entry["q"], np.float32) \
        * np.asarray(entry["scale"]) / 127.0
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-7)
    # the tied head quantizes per vocab ROW (axis 0)
    assert eng._decode_params["wte.weight"]["scale"].shape == (VOCAB, 1)
    # lookups and norms stay float: wpe, layer norms, biases
    assert not isinstance(eng._decode_params["wpe.weight"], dict)
    assert not isinstance(eng._decode_params["blocks.0.ln1.weight"], dict)
    assert not isinstance(eng._decode_params["blocks.0.attn.qkv.bias"],
                          dict)
    # prefill serves the original float dict object
    assert eng._params[name] is not None
    assert not any(isinstance(v, dict) for v in eng._params.values())


@pytest.mark.slow
def test_spec_quant_composes(tiny, prompts, quant_stream):
    """SpeculativeEngine(kv_dtype=weight_dtype='int8'): quantized draft
    + quantized verify agree with the one-token quantized engine's
    greedy stream, within the spec compile bounds."""
    _, gfirsts, gstream = quant_stream
    se = SpeculativeEngine(tiny, gamma=2, kv_dtype="int8",
                           weight_dtype="int8", **ENGINE_KW)
    sfirsts = [se.prefill(s, p) for s, p in enumerate(prompts)]
    assert sfirsts == gfirsts
    stream = [[] for _ in prompts]
    for _ in range(4):
        toks, n_emit = se.decode_many()
        for s in range(len(prompts)):
            stream[s] += [int(t) for t in toks[s, :n_emit[s]]]
    # spec writes KV per γ+1-token verify window while the one-token
    # loop requantizes blocks as they fill, so the quantization noise
    # differs slightly — the streams must still agree overwhelmingly
    agree = np.mean(np.concatenate([
        np.asarray(stream[s][:n]) == np.asarray(gstream[s][:n])
        for s in range(len(prompts))
        for n in [min(8, len(stream[s]), len(gstream[s]))]]))
    assert agree >= 0.9, (agree, stream, gstream)
    assert se.trace_counts["spec_verify"] == 1
    assert se.trace_counts["draft_decode"] == 1
    assert se.trace_counts["decode"] == 0
    # the draft's decode matmuls ride quantized params too
    assert isinstance(
        se._draft_decode_params["blocks.0.attn.qkv.weight"], dict)


@pytest.mark.slow
def test_tp_quant_token_exact_and_sharded_scales(tiny, prompts,
                                                 quant_stream):
    """The TP engine with int8 pools: token-exact vs the single-device
    quantized engine, decode compiled once, pool codes AND scales
    genuinely head-sharded (per-shard scales follow the head split)."""
    from paddle_tpu.serving.distributed.tp import TensorParallelPagedEngine
    _, gfirsts, gstream = quant_stream
    tp = TensorParallelPagedEngine(tiny, tp=2, kv_dtype="int8",
                                   weight_dtype="int8", **ENGINE_KW)
    firsts = [tp.prefill(s, p) for s, p in enumerate(prompts)]
    assert firsts == gfirsts
    for step in range(6):
        toks = tp.decode()
        for s in range(len(prompts)):
            assert int(toks[s]) == gstream[s][step], (step, s)
    assert tp.trace_counts["decode"] == 1
    heads = tiny.cfg.num_heads
    assert set(tp.kv_shard_report().values()) == {heads // 2}
    scale_shards = {s.data.shape[1]
                    for s in tp._pool[0].k_scale.addressable_shards}
    assert scale_shards == {heads // 2}
    # column-split qkv weight: quantized codes shard like the original,
    # per-channel scale vector splits with it
    q = tp._decode_params["blocks.0.attn.qkv.weight"]
    assert {s.data.shape[1] for s in q["q"].addressable_shards} == \
        {tiny.cfg.hidden_size * 3 // 2}
    assert {s.data.shape[1] for s in q["scale"].addressable_shards} == \
        {tiny.cfg.hidden_size * 3 // 2}


# ---------------------------------------------------------------- handoff

def test_quant_handoff_bundle_v2_roundtrip_and_rejection(quant_stream):
    """v2 quantized bundles: unpack-dequant == the engine's own dequant
    (lossless at the wire), ~4x smaller than f32 bundles, truncation at
    any cut and scale-count lies raise KVWireError, v1 stays readable."""
    eng, _, _ = quant_stream
    wire = eng.extract_kv_wire(0)
    bundle = pack_kv_bundle(
        wire["ks"], wire["vs"], meta={"plen": wire["plen"]},
        k_scales=wire["k_scales"], v_scales=wire["v_scales"],
        scale_block=wire["scale_block"])
    ks_f32, vs_f32, plen = eng.extract_kv(0)
    ks, vs, meta = unpack_kv_bundle(bundle)
    assert meta["quantized"] is True and meta["plen"] == plen
    for a, b in zip(ks + vs, ks_f32 + vs_f32):
        np.testing.assert_array_equal(a, b)
    # the f32 bundle of the same request is ~4x the bytes
    f32_bundle = pack_kv_bundle(ks_f32, vs_f32, meta={})
    assert len(f32_bundle) > 3.5 * len(bundle)
    # truncation rejection holds for the versioned bundle — every cut
    # class: inside head, inside header, inside codes, one short byte
    for cut in (4, 20, len(bundle) // 2, len(bundle) - 1):
        with pytest.raises(KVWireError):
            unpack_kv_bundle(bundle[:cut])
    # scale-count lie: a header whose scale rows cannot tile its tokens
    import struct
    magic, hlen = struct.unpack_from("<II", bundle, 0)
    hdr = json.loads(bytes(bundle[8:8 + hlen]))
    assert hdr["v"] == QUANT_BUNDLE_VERSION
    hdr["scale_blocks"] += 1
    blob = json.dumps(hdr).encode()
    with pytest.raises(KVWireError, match="scale count"):
        unpack_kv_bundle(struct.pack("<II", magic, len(blob)) + blob
                         + bytes(bundle[8 + hlen:]))
    # quantized bundles must declare int8
    with pytest.raises(KVWireError, match="int8"):
        pack_kv_bundle(ks_f32, vs_f32, k_scales=wire["k_scales"],
                       v_scales=wire["v_scales"],
                       scale_block=wire["scale_block"])
    # v1 float bundles stay readable forever
    k1, v1, _ = unpack_kv_bundle(f32_bundle)
    hdr1 = json.loads(bytes(f32_bundle[8:8 + struct.unpack_from(
        "<II", f32_bundle, 0)[1]]))
    assert hdr1["v"] == BUNDLE_VERSION
    np.testing.assert_array_equal(k1[0], ks_f32[0])


# ------------------------------------------------------------------ chaos

@pytest.mark.slow
def test_kv_quant_chaos_caught_by_quality_gate(tiny, quality):
    """Corrupt ONE block's scale through the serving.kv_quant fault site
    (truncate mode: the engine performs the damage): the greedy-match
    rate collapses and metrics_report --compare gates the drop as
    failure-class."""
    assert "serving.kv_quant" in faults.SITES
    healthy, _ = quality
    faults.arm("serving.kv_quant", mode="truncate", nth=1, max_fires=1)
    try:
        sick = load_harness.quant_quality(tiny, slots=2, max_len=64,
                                          block_size=8, steps=12, seed=0)
    finally:
        faults.disarm_all()
    assert sick["greedy_match"] < healthy["greedy_match"], (healthy, sick)
    assert sick["logit_kl"] > healthy["logit_kl"]
    mk = lambda g: {  # noqa: E731
        "schema": metrics_report.SCHEMA, "ts": 1.0, "pid": 1,
        "metrics": [{"name": n, "type": "gauge", "help": "",
                     "labelnames": [],
                     "samples": [{"labels": {}, "value": v}]}
                    for n, v in g.items()]}
    regs = metrics_report.compare_counters(
        mk({"serving_quant_greedy_match": healthy["greedy_match"],
            "serving_quant_logit_kl": healthy["logit_kl"]}),
        mk({"serving_quant_greedy_match": sick["greedy_match"],
            "serving_quant_logit_kl": sick["logit_kl"]}),
        max_regress_pct=5.0, min_delta=0.001)
    why = {k: w for k, *_, w in regs}
    assert why.get("serving_quant_greedy_match") == \
        "quantized greedy-match rate vs f32 oracle dropped"


# -------------------------------------------------------------- observers

def test_observer_thresholds_deterministic():
    rng = np.random.RandomState(3)
    data = [rng.randn(512) * (1 + i) for i in range(4)]

    def run():
        obs = HistogramObserver(bins=256)
        for batch in data:
            obs.collect(batch)
        return {algo: obs.threshold(algo)
                for algo in ("abs_max", "min_max", "avg", "hist", "KL",
                             "mse")}

    a, b = run(), run()
    assert a == b                              # bit-deterministic
    # thresholds land in the histogram range (edges may overshoot the
    # batch abs-max by up to a bin: the range doubles to absorb batches)
    hi = 2 * a["abs_max"]
    assert 0 < a["KL"] <= hi
    assert 0 < a["mse"] <= hi
    assert 0 < a["hist"] <= hi
    # direct threshold helpers: deterministic on a fixed histogram
    hist = np.asarray([int(x) for x in np.linspace(100, 0, 64)],
                      np.float64)
    assert kl_threshold(hist, 0.1) == kl_threshold(hist, 0.1)
    assert mse_threshold(hist, 0.1) == mse_threshold(hist, 0.1)
    p = hist_percentile_threshold(hist, 0.1, 0.9999)
    assert p == hist_percentile_threshold(hist, 0.1, 0.9999)
    assert 0 < p <= 6.4


def test_observer_empty_and_nonfinite_edges():
    obs = HistogramObserver(bins=64)
    # empty histogram: every algo answers 0.0, nothing raises
    for algo in ("abs_max", "min_max", "avg", "hist", "KL", "mse"):
        assert obs.threshold(algo) == 0.0
    assert hist_percentile_threshold(np.zeros(64), 0.1, 0.99) == 0.0
    assert kl_threshold(np.zeros(64), 0.1) == 0.0
    # an inf sample must NOT hang the range-doubling loop or poison the
    # scale; NaN must not poison vmin/vmax (the pre-fix failure modes)
    obs.collect(np.asarray([1.0, np.inf, np.nan, -2.0, np.nan]))
    obs.collect(np.asarray([np.nan, np.nan]))      # all-dropped batch
    obs.collect(np.asarray([], np.float32))        # empty batch
    for algo in ("abs_max", "min_max", "avg", "hist", "KL", "mse"):
        t = obs.threshold(algo)
        # finite and inside the (finite!) histogram range — pre-fix,
        # KL/mse would hang or return inf/nan here
        assert np.isfinite(t) and 0 < t <= 2.0 * obs.hi, (algo, t)
    assert obs.vmin == -2.0 and obs.vmax == 1.0


def test_channel_abs_max_axes():
    w = np.asarray([[1.0, -5.0], [-3.0, 2.0], [0.5, 4.0]])   # (in=3, out=2)
    np.testing.assert_array_equal(channel_abs_max(w, 1), [3.0, 5.0])
    np.testing.assert_array_equal(channel_abs_max(w, 0), [5.0, 3.0, 4.0])
    w4 = np.arange(24.0).reshape(2, 3, 2, 2) - 12
    np.testing.assert_array_equal(channel_abs_max(w4, 0), [12.0, 11.0])
