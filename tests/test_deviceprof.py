"""ISSUE 9: the device-profile closed loop, validated against REAL output.

The XPlane half of the profiler had never produced a validated artifact
(VERDICT weak #21: xplane_summary.py untested, zero captures in two
rounds). These tests run the ENTIRE pipeline on the CPU backend — a real
`jax.profiler.trace` capture of a real jitted step, the typed parser
over the real `.xplane.pb`, the deviceprof.v1 JSONL round-trip, and the
cost-model join — plus the orchestration: `bench.py --xplane` end to
end, the wedged-run postmortem carrying the armed-but-unfired capture,
and the serving scheduler's capture-N-decode-steps hook.
"""
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import _jax_compat
from paddle_tpu.cost_model import analytical
from paddle_tpu.observability import deviceprof, flight_recorder

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_ROOT, "tools"))
import perf_report  # noqa: E402


def _step_fn():
    @jax.jit
    def step(x, w):
        return jnp.tanh(x @ w).sum()
    return step


@pytest.fixture(scope="module")
def capture(tmp_path_factory):
    """One real CPU capture of a tiny jitted step, parsed+joined once for
    the whole module: (record, cost-model per-op dict, out_dir)."""
    out = str(tmp_path_factory.mktemp("xplane"))
    step = _step_fn()
    x = jnp.ones((64, 64))
    w = jnp.ones((64, 64))
    step(x, w).block_until_ready()          # compile OUTSIDE the window
    _, rec = deviceprof.capture(lambda: step(x, w), out, iters=3)
    rep = analytical.estimate(step, x, w, device="cpu")
    per_op = {name: 1e3 * rep.device.roofline_s(c.flops, c.bytes)
              for name, c in rep.by_op.items()}
    deviceprof.join_cost_model(rec, per_op, steps=3)
    return rec, per_op, out


# ------------------------------------------------------- capture + parse

def test_capture_parses_real_device_events(capture):
    """The parser finds real XLA op events in a CPU-backend capture: a
    matmul step must surface a `dot` op with nonzero device time."""
    rec, _, _ = capture
    assert rec["schema"] == deviceprof.SCHEMA
    assert rec["decoder"] in ("purepy", "native")
    assert rec["total_device_ms"] > 0
    assert rec["n_events"] > 0
    ops = {o["op"]: o for o in rec["ops"]}
    assert "dot" in ops, f"no dot op in {sorted(ops)}"
    assert ops["dot"]["device_ms"] > 0
    assert ops["dot"]["calls"] >= 3                 # one per traced iter
    assert ops["dot"]["prim"] == "dot_general"      # HLO -> framework op
    assert ops["dot"]["hlo_module"] and "jit" in ops["dot"]["hlo_module"]
    # fractions form a distribution over the chosen lanes
    assert abs(sum(o["frac"] for o in rec["ops"]) - 1.0) < 1e-3


def test_line_normalization_rejects_python_lane(capture):
    """The hardened pick rule: the python tracer lane (whose top event is
    the multi-second trace context itself) must never be the device
    lane — the old inline 'largest total' rule picked exactly that."""
    rec, _, out = capture
    assert "python" not in rec["line"].lower()
    assert rec["line_rule"] in ("hlo_stats", "xla_ops")
    # and the python lane IS the largest-total line of the plane, so the
    # legacy rule would have chosen it: prove the hazard is real
    planes, _ = deviceprof._load_planes(deviceprof.find_xplane(out))
    plane = next(p for p in planes
                 if any(ln.name == "python" for ln in p.lines))
    largest = max((ln for ln in plane.lines
                   if deviceprof._line_total_ns(ln) > 0),
                  key=deviceprof._line_total_ns)
    assert largest.name == "python"


def _fake(name, events=(), lines=None):
    class _Obj:
        pass
    o = _Obj()
    o.name = name
    if lines is not None:
        o.lines = lines
    else:
        o.events = list(events)
    return o


def _ev(name, dur_ns, offset_ns=0, stats=None):
    class _E:
        pass
    e = _E()
    e.name = name
    e.duration_ns = dur_ns
    e.offset_ns = offset_ns
    e.occurrences = 1
    e.stats = stats or {}
    return e


def test_pick_lines_rules_synthetic():
    """Rule order on synthetic planes: 'XLA Ops' wins exactly once (TPU
    hierarchy lanes are parallel views of the same nanoseconds); hlo-stat
    thread lanes are ALL kept (disjoint work); host-only traces fall back
    to largest-total and say so."""
    xla_ops = _fake("XLA Ops", [_ev("fusion.1", 100)])
    steps = _fake("Steps", [_ev("step 0", 1000)])
    fw = _fake("Framework Ops", [_ev("jit(step)", 1000)])
    tpu_plane = _fake("/device:TPU:0", lines=[steps, xla_ops, fw])
    picked = deviceprof.pick_lines(tpu_plane)
    assert [(ln.name, rule) for ln, rule in picked] == \
        [("XLA Ops", "xla_ops")]

    hlo = {"hlo_op": "dot.1", "hlo_module": "jit_step"}
    t1 = _fake("tf_XLA/1", [_ev("dot.1", 500, stats=hlo)])
    t2 = _fake("tf_XLA/2", [_ev("dot.2", 100, stats=hlo)])
    python = _fake("python", [_ev("$trace", 10_000_000)])
    cpu_plane = _fake("/host:CPU", lines=[python, t1, t2])
    picked = deviceprof.pick_lines(cpu_plane)
    assert [(ln.name, rule) for ln, rule in picked] == \
        [("tf_XLA/1", "hlo_stats"), ("tf_XLA/2", "hlo_stats")]

    host_only = _fake("/host:CPU", lines=[python])
    (line, rule), = deviceprof.pick_lines(host_only)
    assert rule == "largest_total"
    # ...and device_planes refuses a host-only CPU plane entirely
    assert deviceprof.device_planes([host_only]) == []


def test_self_time_unnests_containers():
    """`while`/`call` container events enclose their body ops on the SAME
    lane (measured: 1161/1501 events nested on a real capture) — the
    aggregation must count self time, not re-count the body."""
    hlo = {"hlo_op": "x"}
    events = [
        _ev("while.1", 1000, offset_ns=0, stats=hlo),
        _ev("dot.1", 600, offset_ns=100, stats=hlo),
        _ev("add.1", 200, offset_ns=700, stats=hlo),
        _ev("dot.2", 300, offset_ns=1200, stats=hlo),  # sibling after
    ]
    line = _fake("tf_XLA/1", events)
    ops, _, _ = deviceprof._aggregate(line, "hlo_stats")
    assert ops["dot"]["device_ns"] == 900          # 600 + 300, unchanged
    assert ops["add"]["device_ns"] == 200
    assert ops["while"]["device_ns"] == 200        # 1000 - 600 - 200
    total = sum(r["device_ns"] for r in ops.values())
    assert total == 1300                           # union, not 2100


def test_hlo_base_name_normalization():
    assert deviceprof.hlo_base_name("dot.4") == "dot"
    assert deviceprof.hlo_base_name("%loop_fusion.3") == "loop_fusion"
    assert deviceprof.hlo_base_name(
        "divide_subtract_fusion.5.clone") == "divide_subtract_fusion"
    assert deviceprof.hlo_base_name("reduce-window") == "reduce-window"
    assert deviceprof.hlo_to_prim("dot") == "dot_general"
    assert deviceprof.hlo_to_prim("loop_fusion") is None


# --------------------------------------------------- schema + round-trip

def test_jsonl_round_trip_through_schema(capture, tmp_path):
    rec, _, _ = capture
    assert deviceprof.validate_record(rec) == []
    path = str(tmp_path / "deviceprof.jsonl")
    deviceprof.write_record(rec, path)
    loaded = deviceprof.load_records(path)
    assert len(loaded) == 1
    assert loaded[0] == json.loads(json.dumps(rec))   # JSON-stable
    # the offline tool cross-validates with its OWN independent validator
    recs2 = perf_report.load_deviceprof(path)
    assert perf_report.validate_deviceprof_record(recs2[-1]) == []
    md = perf_report.render_deviceprof(recs2)
    assert "dot" in md and "device profile" in md


def test_schema_catches_rot(capture, tmp_path):
    rec, _, _ = capture
    bad = dict(rec, schema="other.v9")
    assert deviceprof.validate_record(bad) != []
    bad = dict(rec, ops=[])
    assert deviceprof.validate_record(bad) != []
    bad = dict(rec, ops=[{"op": "dot"}])          # missing calls/ms/frac
    assert deviceprof.validate_record(bad) != []
    with pytest.raises(ValueError):
        deviceprof.write_record(bad, str(tmp_path / "x.jsonl"))
    good_path = str(tmp_path / "ok.jsonl")
    deviceprof.write_record(rec, good_path)
    with open(good_path, "a") as f:
        f.write(json.dumps(dict(rec, total_device_ms=-1)) + "\n")
    with pytest.raises(ValueError):
        deviceprof.load_records(good_path)


# ------------------------------------------------------------- the join

def test_join_produces_nonzero_efficiency_and_reconciles(capture):
    """The closed loop's deliverable: at least one per-op row joins a
    measured device time to a cost-model prediction with a nonzero
    efficiency, and the device total reconciles against the host wall
    window (device <= wall)."""
    rec, per_op, _ = capture
    join = rec["join"]
    assert join["steps"] == 3
    assert join["device_ms_per_step"] > 0
    assert join["host_window_ms"] > 0
    assert join["device_wall_ratio"] is not None
    assert join["reconciles"], \
        f"device {join['device_ms_per_step']} > wall " \
        f"{join['wall_ms_per_step']} ms/step"
    dot = next(r for r in join["per_op"] if r["op"] == "dot")
    assert dot["predicted_ms"] == pytest.approx(per_op["dot_general"],
                                                rel=1e-3)
    assert dot["efficiency"] is not None and dot["efficiency"] > 0
    assert 0 < join["coverage"] <= 1.0


def test_join_gauges_exported(capture):
    from paddle_tpu.observability import metrics
    deviceprof.export_gauges(capture[0])
    flat = metrics.flatten_snapshot(metrics.registry().snapshot())
    assert flat["deviceprof_total_device_ms_per_step"] > 0
    assert 0 < flat["deviceprof_device_wall_ratio"] <= 1.0
    assert flat["deviceprof_min_op_efficiency"] > 0
    assert any(k.startswith("deviceprof_op_efficiency{op=dot")
               for k in flat), sorted(flat)


# --------------------------------------------------- compat guard satellite

def test_profile_data_guard_is_curated():
    """_jax_compat.profile_data() either works (newer jax) or raises the
    curated error naming the minimum jax version — never a raw
    ImportError whose message is just a module path."""
    try:
        load = _jax_compat.profile_data()
    except _jax_compat.ProfileDataUnavailableError as e:
        msg = str(e)
        assert _jax_compat.PROFILE_DATA_MIN_JAX in msg
        assert "installed: jax" in msg
        assert "XSpace decoder" in msg       # names the fallback
    else:
        assert callable(load)


def test_parser_works_without_native_binding(capture):
    """Whatever the jax version, the purepy decoder must parse the real
    capture (it is the floor the pipeline stands on)."""
    _, _, out = capture
    from paddle_tpu.observability import xplane
    space = xplane.XSpace.from_file(deviceprof.find_xplane(out))
    assert any("hlo_op" in ev.stats
               for p in space.planes for ln in p.lines for ev in ln.events)


# ------------------------------------------------ xplane_summary thin CLI

def test_xplane_summary_cli_over_real_capture(capture, tmp_path):
    _, _, out = capture
    jsonl = str(tmp_path / "cli.jsonl")
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "xplane_summary.py"),
         out, "5", "--jsonl", jsonl],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "| dot |" in proc.stdout
    assert "device profile" in proc.stdout
    perf_report.load_deviceprof(jsonl)        # schema-valid artifact


def test_xplane_summary_cli_fails_loudly(tmp_path):
    """An empty/absent capture exits NONZERO with the reason — the
    silently-empty xplane_top_ops.md failure mode is closed."""
    empty = str(tmp_path / "empty")
    os.makedirs(empty)
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "xplane_summary.py"),
         empty],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1
    assert "FAILED" in proc.stderr
    assert "no .xplane.pb" in proc.stderr


# -------------------------------------------- bench --xplane orchestration

_BENCH_ENV = dict(
    JAX_PLATFORMS="cpu",
    BENCH_B="2", BENCH_S="64", BENCH_LAYERS="2", BENCH_HIDDEN="64",
    BENCH_HEADS="4", BENCH_VOCAB="512", BENCH_INIT_BUDGET_S="120")


@pytest.fixture(scope="module")
def bench_xplane(tmp_path_factory):
    out_dir = str(tmp_path_factory.mktemp("bench_xplane"))
    env = dict(os.environ, **_BENCH_ENV)
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py"),
         "--xplane", out_dir, "--steps", "2"],
        capture_output=True, text=True, timeout=480, cwd=_ROOT, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    return out_dir, rec


def test_bench_xplane_produces_validated_artifacts(bench_xplane):
    """Acceptance: `bench.py --xplane` on CPU produces a real .xplane.pb,
    a schema-valid deviceprof.v1 JSONL, and a join report whose device
    times reconcile (device <= wall) with predicted-vs-measured rows."""
    out_dir, rec = bench_xplane
    assert "error" not in rec, rec
    dp = rec["extra"]["deviceprof"]
    assert dp["state"] == "reported"
    assert os.path.exists(dp["xplane"])
    assert dp["xplane"].endswith(".xplane.pb")
    assert os.path.dirname(dp["jsonl"]) == out_dir
    records = deviceprof.load_records(dp["jsonl"])   # raises on rot
    join = records[-1]["join"]
    assert join["reconciles"], join
    assert dp["reconciles"]
    assert dp["total_device_ms"] > 0
    assert dp["device_wall_ratio"] <= 1.0
    # top-k ops carry predicted-vs-measured rows, joined to the SAME
    # cost-model block the bench emits
    assert rec["extra"]["cost_model"]["per_op"]
    effs = [r for r in dp["top_ops"] if r["efficiency"] is not None]
    assert effs, dp["top_ops"]
    dot = next(r for r in dp["top_ops"] if r["prim"] == "dot_general")
    assert dot["predicted_ms"] == pytest.approx(
        rec["extra"]["cost_model"]["per_op"]["dot_general"]["predicted_ms"],
        rel=1e-3)
    # the join report renders
    assert "### join" in open(dp["report"]).read()


def test_bench_xplane_gauges_ride_profile_artifacts(tmp_path):
    """--xplane + --profile in one run: the deviceprof_* gauges land in
    the metrics snapshot artifact, where --compare gates them."""
    import metrics_report
    out_dir = str(tmp_path / "both")
    env = dict(os.environ, **_BENCH_ENV)
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py"),
         "--xplane", os.path.join(out_dir, "xplane"), "--profile",
         "--profile-dir", out_dir, "--steps", "2"],
        capture_output=True, text=True, timeout=480, cwd=_ROOT, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    snaps = metrics_report.load_snapshots(
        rec["extra"]["profile_artifacts"]["metrics"])
    names = {m["name"] for m in snaps[-1]["metrics"]}
    for g in ("deviceprof_total_device_ms_per_step",
              "deviceprof_device_wall_ratio",
              "deviceprof_op_efficiency"):
        assert g in names, f"{g} missing from {sorted(names)}"


def test_wedged_run_postmortem_records_armed_capture(tmp_path):
    """Acceptance: a run that wedges BEFORE the healthy window leaves the
    armed-but-unfired capture in its postmortem instead of losing it."""
    out_dir = str(tmp_path / "wedged_xplane")
    env = dict(os.environ, **_BENCH_ENV,
               BENCH_INJECT_WEDGE_S="2",
               PADDLE_TPU_POSTMORTEM_DIR=str(tmp_path / "postmortem"))
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py"),
         "--xplane", out_dir],
        capture_output=True, text=True, timeout=240, cwd=_ROOT, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert "wedged" in rec["error"]
    pm_path = rec["extra"]["postmortem"]
    pm = json.load(open(pm_path))
    note = pm["annotations"]["deviceprof.bench"]
    assert note["state"] == "armed", note      # armed, never fired
    assert note["dir"] == os.path.abspath(out_dir)
    assert not os.path.exists(os.path.join(out_dir, "deviceprof.jsonl"))


# ------------------------------------- serving capture-N-decode-steps hook

def test_scheduler_capture_decode_steps(tmp_path):
    from paddle_tpu.serving import GenerationEngine, Scheduler
    from paddle_tpu.text.models import gpt_tiny
    tiny = gpt_tiny()
    tiny.eval()
    eng = GenerationEngine(tiny, slots=2, max_len=48)
    sched = Scheduler(eng, max_queue=8)
    out = str(tmp_path / "serving_xplane")
    ctrl = sched.capture_decode_steps(steps=2, out_dir=out)
    rng = np.random.RandomState(0)
    for i in range(2):
        sched.submit(rng.randint(0, tiny.cfg.vocab_size, 4 + i),
                     max_new_tokens=8)
    # the FIRST active step is warmup (compile), never captured
    sched.step()
    assert ctrl.armed
    sched.run_until_idle()
    assert ctrl.state == "reported", (ctrl.state, ctrl.error)
    block = sched.last_capture
    assert block["state"] == "reported"
    records = deviceprof.load_records(block["jsonl"])
    join = records[-1]["join"]
    assert join["steps"] == 2
    assert join["device_ms_per_step"] > 0
    # decode-step wall alignment: the join's wall is the scheduler's own
    # measured decode wall, and the device side must fit inside it
    assert join["wall_ms_per_step"] > 0
    assert join["reconciles"], join
    fr_note = flight_recorder.get().annotations.get("deviceprof.serving")
    assert fr_note and fr_note["state"] == "reported"


def test_scheduler_capture_abort_is_never_silent(tmp_path, monkeypatch):
    """A decode failure while a capture is pending: an ARMED capture is
    marked failed (not left 'armed' forever in the annotations), a
    MID-WINDOW capture is closed and reported with `aborted_by` — and
    the sick window's gauges are NOT exported into the registry that
    --compare gates."""
    from paddle_tpu.observability import metrics
    from paddle_tpu.serving import GenerationEngine, Scheduler
    from paddle_tpu.text.models import gpt_tiny
    tiny = gpt_tiny()
    tiny.eval()

    # --- armed, first active step fails before any healthy step
    eng = GenerationEngine(tiny, slots=1, max_len=32)
    sched = Scheduler(eng, max_queue=4)
    ctrl = sched.capture_decode_steps(
        steps=2, out_dir=str(tmp_path / "armed"))
    monkeypatch.setattr(eng, "decode",
                        lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    sched.submit([1, 2, 3], max_new_tokens=4)
    sched.step()
    assert ctrl.state == "failed", ctrl.state
    assert sched.last_capture["state"] == "failed"
    assert "boom" in sched.last_capture["aborted_by"]
    note = flight_recorder.get().annotations["deviceprof.serving"]
    assert note["state"] == "failed"

    # --- mid-window: one healthy captured step, then a failure
    eng2 = GenerationEngine(tiny, slots=1, max_len=32)
    sched2 = Scheduler(eng2, max_queue=4)
    out2 = str(tmp_path / "midwindow")
    ctrl2 = sched2.capture_decode_steps(steps=10, out_dir=out2)
    sched2.submit([4, 5, 6], max_new_tokens=8)
    sched2.step()                       # warmup (uncaptured)
    sched2.step()                       # captured step 1 of 10
    assert ctrl2.state == "capturing"
    metrics.registry().reset()          # clean slate for the gauge check
    real_decode = eng2.decode
    monkeypatch.setattr(eng2, "decode",
                        lambda: (_ for _ in ()).throw(RuntimeError("sick")))
    sched2.step()
    monkeypatch.setattr(eng2, "decode", real_decode)
    block = sched2.last_capture
    assert block["state"] == "reported"
    assert "sick" in block["aborted_by"]
    rec = deviceprof.load_records(block["jsonl"])[-1]
    assert "sick" in rec["aborted_by"]  # marker PERSISTED in the record
    assert rec["join"]["steps"] == 1    # only the captured step counted
    flat = metrics.flatten_snapshot(metrics.registry().snapshot())
    assert flat.get("deviceprof_total_device_ms_per_step", 0.0) == 0.0, \
        "sick-window gauges must not reach the --compare gate"
