"""Second batch of subtle op-semantics pins, cross-checked against the
reference implementations' documented corners (median: stat.py:376; clip:
clip kernel min-then-max order; histogram: histogram_kernel.cc range
exclusion) and torch/numpy goldens where the semantics coincide."""
import numpy as np
import torch

import paddle_tpu as paddle


def t(a):
    return paddle.to_tensor(np.asarray(a))


class TestMedianReferenceExact:
    def test_flatten_returns_shape_1_float32(self):
        # reference: axis=None flattens, output shape [1], f32 even for int
        m = paddle.median(t(np.array([[3, 1, 2, 4]], "int32")))
        assert m.shape == [1]
        assert str(m.dtype).endswith("float32")
        np.testing.assert_allclose(np.asarray(m.numpy()), [2.5])

    def test_flatten_keepdim_ones_shape(self):
        m = paddle.median(t(np.zeros((2, 3, 4), "float32")), keepdim=True)
        assert m.shape == [1, 1, 1]

    def test_even_count_averages(self):
        x = np.array([1.0, 9.0, 3.0, 7.0])
        m = paddle.median(t(x.astype("float32")), axis=0)
        np.testing.assert_allclose(float(m.numpy()), 5.0)

    def test_inf_poisons_slice_like_reference(self):
        # reference adds sum(isnan(x)*x) (stat.py:455): 0*inf = NaN, so a
        # slice containing an infinity medians to NaN
        m = paddle.median(t(np.array([1.0, 2.0, np.inf], "float32")))
        assert np.isnan(np.asarray(m.numpy()))[0]

    def test_non_int_axis_raises(self):
        import pytest
        with pytest.raises(ValueError, match="axis should be none or an"):
            paddle.median(t(np.ones((2, 3), "float32")), axis=(0, 1))

    def test_axis_matches_torch(self):
        rng = np.random.RandomState(0)
        x = rng.randn(5, 7).astype("float32")
        got = np.asarray(paddle.median(t(x), axis=1).numpy())
        # torch.median picks the LOWER middle; paddle averages — compare to
        # numpy (which also averages), and to torch.quantile(0.5)
        np.testing.assert_allclose(got, np.median(x, axis=1), rtol=1e-6)
        tq = torch.quantile(torch.tensor(x), 0.5, dim=1).numpy()
        np.testing.assert_allclose(got, tq, rtol=1e-5)

    def test_nan_propagates(self):
        m = paddle.median(t(np.array([1.0, np.nan, 3.0], "float32")))
        assert np.isnan(np.asarray(m.numpy()))[0]


class TestClipSemantics:
    def test_min_greater_than_max_max_wins(self):
        # reference clip applies max(x, min) then min(., max): max wins
        c = paddle.clip(t(np.array([1.0, 5.0, 9.0], "float32")),
                        min=6.0, max=3.0)
        np.testing.assert_allclose(np.asarray(c.numpy()), [3.0, 3.0, 3.0])
        tc = torch.clamp(torch.tensor([1.0, 5.0, 9.0]), min=6.0, max=3.0)
        np.testing.assert_allclose(np.asarray(c.numpy()), tc.numpy())


class TestTieBreaks:
    def test_argmax_first_occurrence(self):
        a = paddle.argmax(t(np.array([2.0, 7.0, 7.0, 1.0], "float32")))
        assert int(a.numpy()) == 1

    def test_argmin_first_occurrence(self):
        a = paddle.argmin(t(np.array([2.0, 0.5, 0.5, 1.0], "float32")))
        assert int(a.numpy()) == 1


class TestShapeArgConventions:
    def test_expand_minus_one_keeps_dim(self):
        e = paddle.expand(t(np.ones((1, 3), "float32")), shape=[4, -1])
        assert e.shape == [4, 3]

    def test_split_minus_one_infers(self):
        parts = paddle.split(t(np.arange(10, dtype="float32")), [3, -1, 2])
        assert [p.shape for p in parts] == [[3], [5], [2]]
        np.testing.assert_allclose(np.asarray(parts[1].numpy()),
                                   np.arange(3, 8, dtype="float32"))


class TestLerpQuantile:
    def test_lerp_matches_torch(self):
        rng = np.random.RandomState(1)
        x = rng.randn(4, 5).astype("float32")
        y = rng.randn(4, 5).astype("float32")
        w = rng.rand(5).astype("float32")          # broadcast weight
        got = np.asarray(paddle.lerp(t(x), t(y), t(w)).numpy())
        ref = torch.lerp(torch.tensor(x), torch.tensor(y),
                         torch.tensor(w)).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-6)

    def test_quantile_matches_torch_linear(self):
        rng = np.random.RandomState(2)
        x = rng.randn(6, 8).astype("float32")
        got = np.asarray(paddle.quantile(t(x), 0.3, axis=1).numpy())
        ref = torch.quantile(torch.tensor(x), 0.3, dim=1).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-5)


class TestHistogramRangeExclusion:
    def test_out_of_range_values_not_counted(self):
        # reference histogram_kernel.cc:71 counts only min<=v<=max
        x = np.array([-5.0, 0.5, 1.5, 2.5, 99.0], "float32")
        h = paddle.histogram(t(x), bins=3, min=0.0, max=3.0)
        assert int(np.asarray(h.numpy()).sum()) == 3
        ref = torch.histc(torch.tensor(x), bins=3, min=0.0, max=3.0)
        np.testing.assert_array_equal(np.asarray(h.numpy()),
                                      ref.numpy().astype(np.int64))


class TestNanmedianQuantileSignatures:
    def test_nanmedian_keepdim_defaults_true(self):
        # reference stat.py:278 — keepdim default is TRUE (unlike median)
        x = t(np.array([[np.nan, 2.0, 3.0], [0.0, 1.0, 2.0]], "float32"))
        y = paddle.nanmedian(x, axis=1)
        assert y.shape == [2, 1]
        np.testing.assert_allclose(np.asarray(y.numpy()), [[2.5], [1.0]])
        y2 = paddle.nanmedian(x, axis=1, keepdim=False)
        assert y2.shape == [2]

    def test_nanmedian_list_axis_and_dtype(self):
        x = t(np.array([[np.nan, 2.0], [4.0, 1.0]], "float32"))
        y = paddle.nanmedian(x, axis=[0, 1])
        assert y.shape == [1, 1]
        np.testing.assert_allclose(np.asarray(y.numpy()), [[2.0]])

    def test_quantile_list_q_leading_dim(self):
        x = t(np.arange(8, dtype="float32").reshape(4, 2))
        y = paddle.quantile(x, q=[0.3, 0.5], axis=0)
        assert y.shape == [2, 2]
        ref = np.quantile(np.arange(8, dtype="float64").reshape(4, 2),
                          [0.3, 0.5], axis=0)
        np.testing.assert_allclose(np.asarray(y.numpy()), ref, rtol=1e-6)

    def test_quantile_list_axis_and_nan_row(self):
        x = np.arange(8, dtype="float32").reshape(4, 2)
        y = paddle.quantile(t(x), q=0.5, axis=[0, 1])
        np.testing.assert_allclose(float(y.numpy()), 3.5)
        x[0, 0] = np.nan
        y2 = paddle.quantile(t(x), q=0.8, axis=1, keepdim=True)
        got = np.asarray(y2.numpy())
        assert got.shape == (4, 1)
        assert np.isnan(got[0, 0]) and not np.isnan(got[1:]).any()

    def test_quantile_out_of_range_q_raises(self):
        import pytest
        with pytest.raises(ValueError, match="range"):
            paddle.quantile(t(np.ones((3,), "float32")), q=1.5)
        with pytest.raises(ValueError, match="range"):
            paddle.nanquantile(t(np.ones((3,), "float32")), q=[-0.2, 0.5])

    def test_median_zero_dim_axis_raises(self):
        import pytest
        with pytest.raises(ValueError, match="axis should be none"):
            paddle.median(t(np.float32(3.0)), axis=0)

    def test_quantile_single_element_list_is_scalar_shaped(self):
        # reference stacks a leading dim only for len(q) > 1 (stat.py:595)
        x = t(np.arange(8, dtype="float32").reshape(4, 2))
        y = paddle.quantile(x, q=[0.5], axis=0)
        assert y.shape == [2]
        y2 = paddle.nanquantile(x, q=[0.5], axis=0)
        assert y2.shape == [2]

    def test_empty_q_and_axis_raise(self):
        import pytest
        x = t(np.ones((3, 2), "float32"))
        with pytest.raises(ValueError, match="q should not be empty"):
            paddle.quantile(x, q=[])
        with pytest.raises(ValueError, match="Axis list should not be empty"):
            paddle.nanmedian(x, axis=[])


class TestDropoutModes:
    def test_downscale_in_infer_scales_at_eval(self):
        # reference dropout_op: this mode leaves training values unscaled
        # and multiplies by (1-p) at inference
        import paddle_tpu.nn.functional as F
        x = t(np.ones((4, 4), "float32"))
        y = F.dropout(x, p=0.25, training=False, mode="downscale_in_infer")
        np.testing.assert_allclose(np.asarray(y.numpy()), 0.75, rtol=1e-6)
        # upscale mode: eval is identity
        y2 = F.dropout(x, p=0.25, training=False)
        np.testing.assert_allclose(np.asarray(y2.numpy()), 1.0)
        # downscale train: surviving values are UNscaled
        y3 = F.dropout(x, p=0.5, training=True, mode="downscale_in_infer")
        v = np.asarray(y3.numpy())
        assert set(np.unique(v)).issubset({0.0, 1.0})

    def test_bad_mode_raises(self):
        import pytest
        import paddle_tpu.nn.functional as F
        with pytest.raises(ValueError, match="upscale_in_train"):
            F.dropout(t(np.ones((2,), "float32")), mode="bogus")


class TestInitializerGain:
    def test_calculate_gain_reference_table(self):
        import math
        import pytest
        from paddle_tpu.nn.initializer import calculate_gain
        assert calculate_gain("tanh") == 5.0 / 3
        assert calculate_gain("relu") == math.sqrt(2.0)
        assert calculate_gain("selu") == 3.0 / 4
        # param=0 is a VALID leaky slope -> sqrt(2); only None means 0.01
        assert calculate_gain("leaky_relu", 0) == math.sqrt(2.0)
        assert calculate_gain("leaky_relu", 1.0) == 1.0
        assert abs(calculate_gain("leaky_relu")
                   - math.sqrt(2.0 / (1 + 0.01 ** 2))) < 1e-12
        assert calculate_gain("conv2d_transpose") == 1.0
        with pytest.raises(ValueError, match="not suppported"):
            calculate_gain("softmax")

    def test_kaiming_honors_nonlinearity(self):
        import math
        from paddle_tpu.nn.initializer import KaimingNormal
        w = KaimingNormal(nonlinearity="tanh")((256, 512), "float32")
        # std should be (5/3)/sqrt(256): loose 3-sigma-ish band on the
        # sample std over 128k values
        std = float(np.std(np.asarray(w.numpy() if hasattr(w, "numpy")
                                      else w)))
        want = (5.0 / 3) / math.sqrt(256)
        assert abs(std - want) / want < 0.05

    def test_dropout_p_out_of_range_raises(self):
        import pytest
        import paddle_tpu.nn.functional as F
        with pytest.raises(ValueError, match="p argument"):
            F.dropout(t(np.ones((2,), "float32")), p=1.5)
        with pytest.raises(ValueError, match="p argument"):
            F.dropout(t(np.ones((2,), "float32")), p=-0.1, training=False)


class TestAdaptivePoolUneven:
    def test_adaptive_avg_pool2d_uneven_matches_torch(self):
        rng = np.random.RandomState(3)
        x = rng.randn(2, 3, 7, 5).astype("float32")
        import paddle_tpu.nn.functional as F
        got = np.asarray(F.adaptive_avg_pool2d(t(x), [3, 2]).numpy())
        ref = torch.nn.functional.adaptive_avg_pool2d(
            torch.tensor(x), (3, 2)).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-6)

    def test_adaptive_max_pool1d_uneven_matches_torch(self):
        rng = np.random.RandomState(4)
        x = rng.randn(2, 3, 10).astype("float32")
        import paddle_tpu.nn.functional as F
        got = np.asarray(F.adaptive_max_pool1d(t(x), 4).numpy())
        ref = torch.nn.functional.adaptive_max_pool1d(
            torch.tensor(x), 4).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-6)


class TestAccuracyMetric:
    def test_one_hot_labels(self):
        # reference Accuracy.compute argmaxes one-hot labels
        import paddle_tpu as paddle
        m = paddle.metric.Accuracy(topk=(1, 2))
        pred = t(np.array([[0.1, 0.7, 0.2], [0.8, 0.15, 0.05]], "float32"))
        onehot = t(np.array([[0, 1, 0], [0, 0, 1]], "float32"))
        correct = m.compute(pred, onehot)
        accs = m.update(correct)
        # row 1 (label 1): top-1 = [1] correct; row 2 (label 2): top-1 = [0]
        # wrong and top-2 = [0, 1] still wrong (values untied on purpose)
        assert accs[0] == 0.5
        assert accs[1] == 0.5


class TestSmoothL1Huber:
    def test_delta_not_one_matches_huber(self):
        """paddle smooth_l1_loss == torch huber_loss (the kernel it wraps),
        NOT torch smooth_l1_loss(beta) which divides the quadratic branch."""
        import paddle_tpu.nn.functional as F
        rng = np.random.RandomState(5)
        x = rng.randn(6, 4).astype("float32") * 3
        y = rng.randn(6, 4).astype("float32")
        got = float(F.smooth_l1_loss(t(x), t(y), delta=2.0).numpy())
        ref = float(torch.nn.functional.huber_loss(
            torch.tensor(x), torch.tensor(y), delta=2.0))
        np.testing.assert_allclose(got, ref, rtol=1e-6)
        # and differs from torch's smooth_l1(beta=2) by design
        beta_ref = float(torch.nn.functional.smooth_l1_loss(
            torch.tensor(x), torch.tensor(y), beta=2.0))
        assert abs(got - beta_ref) > 1e-3


class TestKlDiv:
    def test_nonpositive_target_contributes_zero(self):
        # reference kldiv kernel: target <= 0 -> 0 exactly
        import paddle_tpu.nn.functional as F
        logp = t(np.array([[-1.0, -2.0, -3.0]], "float32"))
        y = t(np.array([[0.5, 0.0, -0.5]], "float32"))
        loss = F.kl_div(logp, y, reduction="none")
        got = np.asarray(loss.numpy())
        assert got[0, 1] == 0.0 and got[0, 2] == 0.0
        ref = torch.nn.functional.kl_div(
            torch.tensor([[-1.0, -2.0, -3.0]]),
            torch.tensor([[0.5, 0.0, -0.5]]), reduction="none").numpy()
        # torch computes y*(log y - x) with nan at y<=0 unless zeroed; the
        # paddle kernel zeroes — compare only the valid entry
        np.testing.assert_allclose(got[0, 0], ref[0, 0], rtol=1e-6)

    def test_batchmean_matches_torch(self):
        import paddle_tpu.nn.functional as F
        rng = np.random.RandomState(6)
        logp = np.log(np.random.RandomState(7).dirichlet(
            np.ones(5), size=4).astype("float32"))
        y = rng.dirichlet(np.ones(5), size=4).astype("float32")
        got = float(F.kl_div(t(logp), t(y), reduction="batchmean").numpy())
        ref = float(torch.nn.functional.kl_div(
            torch.tensor(logp), torch.tensor(y), reduction="batchmean"))
        np.testing.assert_allclose(got, ref, rtol=1e-5)


class TestTakeModes:
    def test_output_has_index_shape_and_negative_raise(self):
        x = t(np.arange(12, dtype="float32").reshape(3, 4))
        idx = t(np.array([[0, -1], [5, -12]], "int64"))
        got = np.asarray(paddle.take(x, idx).numpy())       # mode='raise'
        assert got.shape == (2, 2)
        # negative indices wrap by +numel in raise mode (reference math.py)
        np.testing.assert_allclose(got, [[0.0, 11.0], [5.0, 0.0]])
        ref = torch.take(torch.tensor(np.arange(12, dtype="float32")),
                         torch.tensor([[0, -1], [5, -12]])).numpy()
        np.testing.assert_allclose(got, ref)

    def test_wrap_and_clip(self):
        x = t(np.arange(6, dtype="float32"))
        idx = t(np.array([-1, 6, 13], "int64"))
        wrap = np.asarray(paddle.take(x, idx, mode="wrap").numpy())
        np.testing.assert_allclose(wrap, [5.0, 0.0, 1.0])
        clip = np.asarray(paddle.take(x, idx, mode="clip").numpy())
        np.testing.assert_allclose(clip, [0.0, 5.0, 5.0])

    def test_bad_mode_raises(self):
        import pytest
        with pytest.raises(ValueError, match="'mode' in 'take'"):
            paddle.take(t(np.ones((2,), "float32")),
                        t(np.zeros((1,), "int64")), mode="bogus")

    def test_raise_mode_bounds_checks_eagerly(self):
        import pytest
        x = t(np.arange(6, dtype="float32"))
        with pytest.raises(ValueError, match="index out of range"):
            paddle.take(x, t(np.array([6], "int64")))
        with pytest.raises(ValueError, match="index out of range"):
            paddle.take(x, t(np.array([-7], "int64")))


class TestConvPaddingForms:
    def test_nchw_pair_spec(self):
        """The reference conv accepts the 4-pair NCHW spec
        [[0,0],[0,0],[ph,ph],[pw,pw]]; it must not be parsed as a flat
        2*spatial list."""
        import paddle_tpu.nn.functional as F
        rng = np.random.RandomState(0)
        x = rng.randn(1, 3, 8, 8).astype("float32")
        w = rng.randn(4, 3, 3, 3).astype("float32") * 0.2
        y = F.conv2d(t(x), t(w), padding=[[0, 0], [0, 0], [1, 1], [2, 2]])
        ref = torch.nn.functional.conv2d(torch.tensor(x), torch.tensor(w),
                                         padding=(1, 2)).numpy()
        np.testing.assert_allclose(np.asarray(y.numpy()), ref,
                                   rtol=2e-4, atol=1e-4)

    def test_asymmetric_flat_spec(self):
        import paddle_tpu.nn.functional as F
        rng = np.random.RandomState(1)
        x = rng.randn(1, 2, 6, 6).astype("float32")
        w = rng.randn(2, 2, 3, 3).astype("float32") * 0.2
        # flat [top, bottom, left, right]
        y = F.conv2d(t(x), t(w), padding=[1, 0, 2, 1])
        assert list(y.shape) == [1, 2, 5, 7]

    def test_nhwc_pair_spec_positions(self):
        """Channels-last pair spec: spatial pairs sit at positions 1..S."""
        import paddle_tpu.nn.functional as F
        rng = np.random.RandomState(2)
        x = rng.randn(1, 8, 8, 3).astype("float32")
        w = rng.randn(4, 3, 3, 3).astype("float32") * 0.2
        y = F.conv2d(t(x), t(w), padding=[[0, 0], [1, 1], [2, 2], [0, 0]],
                     data_format="NHWC")
        assert list(y.shape) == [1, 8, 10, 4]

    def test_nonzero_batch_channel_padding_raises(self):
        import pytest
        import paddle_tpu.nn.functional as F
        x = t(np.ones((1, 3, 8, 8), "float32"))
        w = t(np.ones((4, 3, 3, 3), "float32"))
        with pytest.raises(ValueError, match="batch/channel"):
            F.conv2d(x, w, padding=[[1, 1], [0, 0], [2, 2], [3, 3]])


class TestPoolCeilMode:
    def test_ceil_mode_matches_torch(self):
        """ceil_mode was silently ignored before: output shapes and values
        must match torch on configs where no window starts in padding
        (where torch's drop rule and paddle's no-drop formula agree)."""
        import paddle_tpu.nn.functional as F
        rng = np.random.RandomState(0)
        x = rng.randn(1, 2, 7, 7).astype("float32")
        tx = torch.tensor(x)
        for k, s, p in [(3, 2, 0), (3, 2, 1), (2, 2, 0), (4, 3, 1)]:
            got = np.asarray(F.max_pool2d(t(x), k, s, p,
                                          ceil_mode=True).numpy())
            ref = torch.nn.functional.max_pool2d(
                tx, k, s, p, ceil_mode=True).numpy()
            assert got.shape == ref.shape, (k, s, p)
            np.testing.assert_allclose(got, ref, rtol=1e-6)
            ga = np.asarray(F.avg_pool2d(t(x), k, s, p,
                                         ceil_mode=True).numpy())
            ra = torch.nn.functional.avg_pool2d(
                tx, k, s, p, ceil_mode=True,
                count_include_pad=False).numpy()
            assert ga.shape == ra.shape, (k, s, p)
            np.testing.assert_allclose(ga, ra, rtol=1e-5)

    def test_ceil_mode_no_drop_rule_unlike_torch(self):
        """The reference PoolOutputSize (pooling.h:368) has NO torch-style
        drop-last-window rule: k=2,s=2,p=1 on 3x3 gives 3x3 (torch: 2x2)."""
        import paddle_tpu.nn.functional as F
        y = np.arange(9, dtype="float32").reshape(1, 1, 3, 3)
        gp = F.max_pool2d(t(y), 2, 2, 1, ceil_mode=True)
        assert list(gp.shape) == [1, 1, 3, 3]

    def test_valid_padding_with_ceil_raises(self):
        import pytest
        import paddle_tpu.nn.functional as F
        y = t(np.ones((1, 1, 4, 4), "float32"))
        with pytest.raises(ValueError, match="VALID"):
            F.max_pool2d(y, 2, 2, "VALID", ceil_mode=True)

    def test_include_pad_divisor_clamped_on_ceil_windows(self):
        """exclusive=False divides by the window's overlap with
        input+original padding (pooling.cc:79-84), not the kernel size,
        on ceil-extra windows."""
        import paddle_tpu.nn.functional as F
        x = np.ones((1, 1, 3, 3), "float32")
        ga = np.asarray(F.avg_pool2d(t(x), 2, 2, 0, ceil_mode=True,
                                     exclusive=False).numpy())
        ra = torch.nn.functional.avg_pool2d(
            torch.tensor(x), 2, 2, 0, ceil_mode=True,
            count_include_pad=True).numpy()
        np.testing.assert_allclose(ga, ra, rtol=1e-6)


    def test_all_padding_window_is_finite_lowest(self):
        """Reference MaxPool initial() is -FLT_MAX (pooling.h:46), not
        -inf: a ceil-extra window lying entirely in padding stays finite."""
        import paddle_tpu.nn.functional as F
        x = np.ones((1, 1, 3, 3), "float32")
        out = np.asarray(F.max_pool2d(t(x), 2, 2, 1, ceil_mode=True).numpy())
        assert out.shape == (1, 1, 3, 3)
        assert np.isfinite(out).all()
        assert out[0, 0, 2, 2] == np.finfo(np.float32).min


class TestActivationConstants:
    def test_constants_match_torch(self):
        """hardsigmoid slope/offset, hardswish, selu alpha/scale, softplus
        beta/threshold cutover, elu alpha, mish, silu, soft/hard/tanh-shrink
        — all pinned against torch (same constants as the reference)."""
        import paddle_tpu.nn.functional as F
        x = np.linspace(-4, 4, 17).astype("float32")
        tx = torch.tensor(x)
        tt = t(x)
        cases = [
            (F.hardsigmoid(tt), torch.nn.functional.hardsigmoid(tx)),
            (F.hardswish(tt), torch.nn.functional.hardswish(tx)),
            (F.selu(tt), torch.nn.functional.selu(tx)),
            (F.softplus(tt, beta=2.0, threshold=10.0),
             torch.nn.functional.softplus(tx, beta=2.0, threshold=10.0)),
            (F.elu(tt, alpha=0.5), torch.nn.functional.elu(tx, alpha=0.5)),
            (F.mish(tt), torch.nn.functional.mish(tx)),
            (F.silu(tt), torch.nn.functional.silu(tx)),
            (F.softshrink(tt, threshold=0.7),
             torch.nn.functional.softshrink(tx, lambd=0.7)),
            (F.hardshrink(tt, threshold=0.7),
             torch.nn.functional.hardshrink(tx, lambd=0.7)),
            (F.tanhshrink(tt), torch.nn.functional.tanhshrink(tx)),
        ]
        for got, ref in cases:
            np.testing.assert_allclose(np.asarray(got.numpy()), ref.numpy(),
                                       rtol=1e-5, atol=1e-6)


class TestUnfoldFold:
    def test_unfold_fold_match_torch(self):
        import paddle_tpu.nn.functional as F
        x = np.arange(2 * 3 * 6 * 6, dtype="float32").reshape(2, 3, 6, 6)
        got = np.asarray(F.unfold(t(x), kernel_sizes=3, strides=2,
                                  paddings=1, dilations=1).numpy())
        ref = torch.nn.functional.unfold(torch.tensor(x), 3, padding=1,
                                         stride=2).numpy()
        np.testing.assert_allclose(got, ref)
        # fold scatter-adds overlaps back (col2im)
        f = np.asarray(F.fold(t(got), output_sizes=[6, 6], kernel_sizes=3,
                              strides=2, paddings=1).numpy())
        rf = torch.nn.functional.fold(torch.tensor(ref), (6, 6), 3,
                                      padding=1, stride=2).numpy()
        np.testing.assert_allclose(f, rf)

    def test_fold_dilation_grad_and_validation(self):
        import pytest
        import paddle_tpu.nn.functional as F
        x = np.random.RandomState(0).randn(1, 2 * 2 * 2, 4).astype("float32")
        f = np.asarray(F.fold(t(x), output_sizes=[5, 5], kernel_sizes=2,
                              strides=2, dilations=2).numpy())
        rf = torch.nn.functional.fold(torch.tensor(x), (5, 5), 2,
                                      stride=2, dilation=2).numpy()
        np.testing.assert_allclose(f, rf)
        # backward: fold is a scatter-add, so d(sum)/dx == 1 everywhere
        xt = t(x)
        xt.stop_gradient = False
        F.fold(xt, output_sizes=[5, 5], kernel_sizes=2,
               strides=2, dilations=2).sum().backward()
        np.testing.assert_allclose(np.asarray(xt.grad), np.ones_like(x))
        with pytest.raises(ValueError, match="sliding positions"):
            F.fold(t(x[:, :, :3]), output_sizes=[5, 5], kernel_sizes=2,
                   strides=2, dilations=2)
        with pytest.raises(ValueError, match="kernel area"):
            F.fold(t(np.ones((1, 5, 4), "float32")), output_sizes=[5, 5],
                   kernel_sizes=2, strides=2, dilations=2)


class TestNpairAdaptive3d:
    def test_npair_loss_matches_reference_formula(self):
        import paddle_tpu.nn.functional as F
        rng = np.random.RandomState(7)
        a = rng.randn(4, 6).astype("float32")
        pos = rng.randn(4, 6).astype("float32")
        lab = np.array([0, 1, 0, 2], "int64")
        got = float(F.npair_loss(t(a), t(pos), t(lab), l2_reg=0.002).numpy())
        # replicate the reference python composition exactly
        n = 4
        eq = (lab.reshape(n, 1) == lab.reshape(1, n)).astype("float32")
        soft = eq / eq.sum(1, keepdims=True)
        l2 = (np.mean((a * a).sum(1)) + np.mean((pos * pos).sum(1))) \
            * 0.25 * 0.002
        sim = a @ pos.T
        lse = np.log(np.exp(sim - sim.max(1, keepdims=True)).sum(1,
                     keepdims=True)) + sim.max(1, keepdims=True)
        ce_rows = (soft * (lse - sim)).sum(1)
        ce = np.mean((soft * ce_rows[:, None]).sum(0))
        np.testing.assert_allclose(got, l2 + ce, rtol=1e-5)

    def test_adaptive_pool3d_uneven_matches_torch(self):
        import paddle_tpu.nn.functional as F
        rng = np.random.RandomState(8)
        x = rng.randn(1, 2, 5, 7, 6).astype("float32")
        got = np.asarray(F.adaptive_avg_pool3d(t(x), [2, 3, 4]).numpy())
        ref = torch.nn.functional.adaptive_avg_pool3d(
            torch.tensor(x), (2, 3, 4)).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-5)
        gm = np.asarray(F.adaptive_max_pool3d(t(x), [2, 3, 4]).numpy())
        rm = torch.nn.functional.adaptive_max_pool3d(
            torch.tensor(x), (2, 3, 4)).numpy()
        np.testing.assert_allclose(gm, rm, rtol=1e-6)


class TestClassCenterSample:
    def test_positives_kept_and_remapped(self):
        import paddle_tpu.nn.functional as F
        lab = t(np.array([3, 7, 3, 11], "int64"))
        remapped, sampled = F.class_center_sample(lab, 20, 8)
        s = np.asarray(sampled.numpy())
        r = np.asarray(remapped.numpy())
        assert len(s) == 8 and len(set(s.tolist())) == 8
        for c in (3, 7, 11):
            assert c in s
        # remap consistency: sampled[remapped[i]] == label[i]
        np.testing.assert_array_equal(s[r], [3, 7, 3, 11])
        assert (np.sort(s) == s).all()

    def test_more_positives_than_samples_keeps_all(self):
        import paddle_tpu.nn.functional as F
        lab = t(np.arange(6, dtype="int64"))
        remapped, sampled = F.class_center_sample(lab, 10, 4)
        assert len(np.asarray(sampled.numpy())) == 6

    def test_label_range_validated(self):
        import pytest
        import paddle_tpu.nn.functional as F
        with pytest.raises(ValueError, match="labels must lie"):
            F.class_center_sample(t(np.array([25], "int64")), 20, 8)

    def test_unfold_fold_asymmetric_paddings(self):
        """[top, left, bottom, right] spec (reference common.py:148-162)."""
        import paddle_tpu.nn.functional as F
        x = np.random.RandomState(9).randn(1, 2, 5, 5).astype("float32")
        got = np.asarray(F.unfold(t(x), 2, strides=2,
                                  paddings=[1, 0, 0, 1]).numpy())
        # torch unfold only does symmetric padding; golden via explicit pad
        xp = np.pad(x, [(0, 0), (0, 0), (1, 0), (0, 1)])
        ref = torch.nn.functional.unfold(torch.tensor(xp), 2,
                                         stride=2).numpy()
        np.testing.assert_allclose(got, ref)
        f = np.asarray(F.fold(t(got), [5, 5], 2, strides=2,
                              paddings=[1, 0, 0, 1]).numpy())
        rf = torch.nn.functional.fold(torch.tensor(ref), (6, 6), 2,
                                      stride=2).numpy()[:, :, 1:, :-1]
        np.testing.assert_allclose(f, rf)

    def test_zero_stride_raises(self):
        import pytest
        import paddle_tpu.nn.functional as F
        with pytest.raises(ValueError, match="strides and dilations"):
            F.unfold(t(np.ones((1, 1, 4, 4), "float32")), 2, strides=0)


class TestInterpolateModes:
    def _x(self):
        return np.random.RandomState(0).randn(1, 2, 5, 7).astype("float32")

    def test_bilinear_both_corner_modes(self):
        import paddle_tpu.nn.functional as F
        x = self._x(); tx = torch.tensor(x)
        for corners in (False, True):
            g = np.asarray(F.interpolate(t(x), size=[8, 11], mode="bilinear",
                                         align_corners=corners).numpy())
            r = torch.nn.functional.interpolate(
                tx, size=(8, 11), mode="bilinear",
                align_corners=corners).numpy()
            np.testing.assert_allclose(g, r, rtol=1e-5, atol=1e-6)

    def test_nearest_is_floor_rule(self):
        # paddle nearest: src = floor(ratio*i) (interpolate_kernel.cc:211),
        # same as torch 'nearest'
        import paddle_tpu.nn.functional as F
        x = self._x(); tx = torch.tensor(x)
        g = np.asarray(F.interpolate(t(x), size=[3, 4],
                                     mode="nearest").numpy())
        r = torch.nn.functional.interpolate(tx, size=(3, 4),
                                            mode="nearest").numpy()
        np.testing.assert_allclose(g, r)

    def test_area_is_adaptive_avg(self):
        import paddle_tpu.nn.functional as F
        x = self._x(); tx = torch.tensor(x)
        g = np.asarray(F.interpolate(t(x), size=[3, 4], mode="area").numpy())
        r = torch.nn.functional.interpolate(tx, size=(3, 4),
                                            mode="area").numpy()
        np.testing.assert_allclose(g, r, rtol=1e-5)

    def test_bicubic_uses_minus_075_kernel(self):
        # reference A = -0.75 (interpolate_function.h:43); jax.image's
        # cubic is A = -0.5 and visibly diverges — pinned vs torch
        import paddle_tpu.nn.functional as F
        x = self._x(); tx = torch.tensor(x)
        for corners in (False, True):
            g = np.asarray(F.interpolate(t(x), size=[8, 11], mode="bicubic",
                                         align_corners=corners).numpy())
            r = torch.nn.functional.interpolate(
                tx, size=(8, 11), mode="bicubic",
                align_corners=corners).numpy()
            np.testing.assert_allclose(g, r, rtol=1e-4, atol=1e-5)

    def test_align_mode_1_asymmetric(self):
        # paddle-only knob: src = ratio*i for the linear family
        import paddle_tpu.nn.functional as F
        x1 = np.arange(8, dtype="float32").reshape(1, 1, 8)
        g = np.asarray(F.interpolate(t(x1), size=[4], mode="linear",
                                     align_mode=1, data_format="NCW").numpy())
        np.testing.assert_allclose(g[0, 0], [0.0, 2.0, 4.0, 6.0])

    def test_trilinear_corners(self):
        import paddle_tpu.nn.functional as F
        x3 = np.random.RandomState(1).randn(1, 2, 3, 4, 5).astype("float32")
        g = np.asarray(F.interpolate(t(x3), size=[5, 6, 7], mode="trilinear",
                                     align_corners=True,
                                     data_format="NCDHW").numpy())
        r = torch.nn.functional.interpolate(
            torch.tensor(x3), size=(5, 6, 7), mode="trilinear",
            align_corners=True).numpy()
        np.testing.assert_allclose(g, r, rtol=1e-5, atol=1e-6)

    def test_area_nhwc_and_scalar_size(self):
        import paddle_tpu.nn.functional as F
        x = np.random.RandomState(2).randn(1, 5, 7, 2).astype("float32")
        g = np.asarray(F.interpolate(t(x), size=[3, 4], mode="area",
                                     data_format="NHWC").numpy())
        r = torch.nn.functional.interpolate(
            torch.tensor(x).permute(0, 3, 1, 2), size=(3, 4),
            mode="area").permute(0, 2, 3, 1).numpy()
        assert g.shape == (1, 3, 4, 2)
        np.testing.assert_allclose(g, r, rtol=1e-5)
        xc = np.random.RandomState(3).randn(1, 2, 5, 7).astype("float32")
        g2 = F.interpolate(t(xc), size=8, mode="bilinear")
        assert list(g2.shape) == [1, 2, 8, 8]
        import pytest
        with pytest.raises(ValueError, match="spatial sizes"):
            F.interpolate(t(xc), size=[8], mode="bilinear")
