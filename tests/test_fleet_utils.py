"""fleet.utils (recompute/LocalFS/HDFSClient) + static.amp (reference:
distributed/fleet/utils, static/amp)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed import fleet


def test_recompute_layer_value_and_grad_parity():
    """Layer path: params thread through jax.checkpoint; values and ALL
    grads (input + weights) match the direct call."""
    paddle.seed(0)
    block = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 4))
    x = paddle.to_tensor(np.random.RandomState(0).rand(2, 4)
                         .astype("float32"), stop_gradient=False)
    out_r = fleet.recompute(block, x)
    out_d = block(x)
    np.testing.assert_allclose(out_r.numpy(), out_d.numpy(), rtol=1e-5)
    out_r.sum().backward()
    gx = x.grad.numpy().copy()
    gws = [p.grad.numpy().copy() for p in block.parameters()]
    x.clear_grad()
    for p in block.parameters():
        p.clear_grad()
    block(x).sum().backward()
    np.testing.assert_allclose(gx, x.grad.numpy(), rtol=1e-5)
    for g, p in zip(gws, block.parameters()):
        np.testing.assert_allclose(g, p.grad.numpy(), rtol=1e-5)


def test_recompute_plain_function_fallback():
    """Closure-captured params can't be discovered: falls back to a plain
    call — grads stay correct (remat skipped)."""
    paddle.seed(1)
    lin1 = nn.Linear(4, 4)
    x = paddle.to_tensor(np.random.RandomState(1).rand(2, 4)
                         .astype("float32"), stop_gradient=False)

    def block(t):
        return lin1(t)

    out = fleet.recompute(block, x)
    out.sum().backward()
    assert lin1.weight.grad is not None and x.grad is not None


def test_local_fs_and_hdfs(tmp_path):
    fs = fleet.utils.LocalFS()
    p = str(tmp_path / "a")
    fs.mkdirs(p)
    fs.touch(os.path.join(p, "f.txt"))
    dirs, files = fs.ls_dir(str(tmp_path))
    assert dirs == ["a"] and files == []
    fs.mv(os.path.join(p, "f.txt"), str(tmp_path / "g.txt"))
    assert fs.is_file(str(tmp_path / "g.txt"))
    fs.delete(p)
    assert not fs.is_exist(p)
    with pytest.raises(RuntimeError, match="hadoop"):
        fleet.utils.HDFSClient()


def test_static_amp_decorate_trains():
    paddle.seed(0)
    net = nn.Linear(4, 1)
    deco = paddle.static.amp.decorate(
        opt.SGD(0.05, parameters=net.parameters()))
    x = paddle.to_tensor(np.ones((8, 4), "float32"))
    y = paddle.to_tensor(np.ones((8, 1), "float32") * 3)
    first = last = None
    for _ in range(15):
        loss = ((net(x) - y) ** 2).mean()
        deco.minimize(loss)
        deco.clear_grad()
        if first is None:
            first = float(loss)
        last = float(loss)
    assert last < first


def test_custom_op_lists():
    ls = paddle.static.amp.CustomOpLists(custom_white_list=["matmul"])
    assert "matmul" in ls.white_list
