"""PP-YOLOE + ERNIE model-zoo tests: forward shapes, loss decreases, PP
descs integrate with PipelineLayer (BASELINE driver configs)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.text.models import (ErnieForPretraining,
                                    ErnieForSequenceClassification,
                                    ernie_pipeline_descs, ernie_tiny,
                                    ernie_tiny_config)
from paddle_tpu.vision.models import PPYOLOE, PPYOLOEConfig, ppyoloe_loss


def _tiny_det(sync_bn=False):
    return PPYOLOE(PPYOLOEConfig(num_classes=4, width_mult=0.25,
                                 depth_mult=0.33, sync_bn=sync_bn))


def test_ppyoloe_forward_shapes():
    m = _tiny_det()
    x = paddle.to_tensor(np.random.RandomState(0)
                         .rand(2, 3, 64, 64).astype("float32"))
    cls, reg = m(x)
    L = (64 // 8) ** 2 + (64 // 16) ** 2 + (64 // 32) ** 2
    assert list(cls.shape) == [2, L, 4]
    assert list(reg.shape) == [2, L, 4 * (16 + 1)]
    pts, strides = m.anchor_points((64, 64))
    assert pts.shape == (L, 2) and strides.shape == (L,)


def test_ppyoloe_loss_trains():
    paddle.seed(0)
    m = _tiny_det()
    o = opt.Adam(1e-3, parameters=m.parameters())
    rng = np.random.RandomState(1)
    x = paddle.to_tensor(rng.rand(2, 3, 64, 64).astype("float32"))
    gt_boxes = paddle.to_tensor(np.asarray(
        [[[8, 8, 40, 40], [0, 0, 0, 0]],
         [[16, 16, 56, 56], [4, 4, 20, 20]]], np.float32))
    gt_class = paddle.to_tensor(np.asarray([[1, 0], [2, 3]], np.int64))
    gt_mask = paddle.to_tensor(np.asarray([[1, 0], [1, 1]], np.float32))

    losses = []
    for _ in range(5):
        loss = ppyoloe_loss(m, x, gt_boxes, gt_class, gt_mask)
        loss.backward()
        o.step()
        o.clear_grad()
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_ppyoloe_sync_bn_variant():
    m = _tiny_det(sync_bn=True)
    x = paddle.to_tensor(np.ones((1, 3, 32, 32), np.float32))
    cls, reg = m(x)
    assert np.isfinite(cls.numpy()).all()


def test_ernie_forward_and_classification():
    cfg = ernie_tiny_config()
    m = ErnieForSequenceClassification(cfg, num_classes=3)
    ids = paddle.to_tensor(np.random.RandomState(0)
                           .randint(0, cfg.vocab_size, (2, 16)))
    logits = m(ids)
    assert list(logits.shape) == [2, 3]


def test_ernie_pretraining_loss_decreases():
    paddle.seed(1)
    cfg = ernie_tiny_config()
    m = ErnieForPretraining(cfg)
    o = opt.Adam(5e-4, parameters=m.parameters())
    rng = np.random.RandomState(2)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (4, 16)))
    labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (4, 16)))
    losses = []
    for _ in range(8):
        loss = m.loss(ids, labels)
        loss.backward()
        o.step()
        o.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.3, losses


def test_ernie_pipeline_descs():
    from paddle_tpu.distributed.fleet.meta_parallel import PipelineLayer
    cfg = ernie_tiny_config()
    descs = ernie_pipeline_descs(cfg)
    pl = PipelineLayer(descs, num_stages=2, loss_fn=nn.CrossEntropyLoss())
    assert pl.get_num_stages() == 2
    ids = paddle.to_tensor(np.random.RandomState(3)
                           .randint(0, cfg.vocab_size, (2, 8)))
    out = pl(ids)
    assert list(out.shape) == [2, 8, cfg.vocab_size]
