"""incubate.autotune: real kernel tiling autotune with a persistent cache
(reference: python/paddle/incubate/autotune.py + phi/kernels/autotune)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.incubate import autotune


def test_config_surface():
    autotune.set_config({"kernel": {"enable": True}})
    assert autotune.get_config()["kernel"]["enable"]
    assert autotune.kernel_tuning_enabled()


def test_autotune_picks_a_valid_block_and_caches(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_CACHE",
                       str(tmp_path / "cache.json"))
    autotune._block_cache.clear()
    autotune._disk_cache.clear()
    autotune._disk_loaded = False
    bq, bk = autotune.autotune_flash_blocks(1, 2, 256, 64, causal=True,
                                            dtype="float32",
                                            candidates=(128, 256),
                                            n_iters=1)
    assert 256 % bq == 0 and 256 % bk == 0
    # cached in memory and on disk
    assert autotune.lookup_flash_blocks(1, 2, 256, 64, True) == (bq, bk)
    assert (tmp_path / "cache.json").exists()
    # a fresh process (empty memory cache, disk not yet read) reloads
    autotune._block_cache.clear()
    autotune._disk_cache.clear()
    autotune._disk_loaded = False
    assert autotune.lookup_flash_blocks(1, 2, 256, 64, True) == (bq, bk)


def test_tuned_blocks_feed_the_flash_entry(monkeypatch):
    """ops.flash_attention consults the cache: a valid tuned entry is
    passed through to the kernel, while a poisoned entry (stale disk
    table: blocks that don't divide S, or a non-square causal pair)
    falls back to the kernel default instead of raising mid-forward
    (ISSUE 6 satellite: the block-table fix)."""
    import importlib

    import jax
    import jax.numpy as jnp

    fa_mod = importlib.import_module("paddle_tpu.ops.flash_attention")
    pallas_mod = importlib.import_module(
        "paddle_tpu.ops.pallas.flash_attention")

    seen = {}

    def fake_flash(q, k, v, block_q=None, block_k=None, **kw):
        seen["blocks"] = (block_q, block_k)
        return q

    monkeypatch.setattr(pallas_mod, "flash_attention", fake_flash)
    autotune._block_cache.clear()
    key = (jax.default_backend(), 2, 256, 64, True)
    q = jnp.ones((1, 2, 256, 64), jnp.float32)

    autotune._block_cache[key] = (128, 128)     # valid: divides S=256
    fa_mod._pallas_flash_bhsd(q, q, q, True, 0.125)
    assert seen["blocks"] == (128, 128)

    autotune._block_cache[key] = (96, 96)       # poisoned: 256 % 96 != 0
    fa_mod._pallas_flash_bhsd(q, q, q, True, 0.125)
    assert seen["blocks"] == (None, None)       # fell back, no raise

    autotune._block_cache[key] = (128, 256)     # causal needs square blocks
    fa_mod._pallas_flash_bhsd(q, q, q, True, 0.125)
    assert seen["blocks"] == (None, None)
    autotune._block_cache.clear()
