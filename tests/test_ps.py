"""Parameter server: native sparse table, async communicator, embedding op.

Mirrors the reference's PS suites (test_the_one_ps.py, memory_sparse_table
gtests, test_dist_fleet_ps*.py) in the in-process form the reference itself
uses for testing (ps_local_client)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native toolchain unavailable")


def test_table_pull_deterministic_init():
    t = native.SparseTable(8, rule="sgd", lr=0.1, init_range=0.05, seed=42)
    rows = t.pull([5, 9, 5])
    assert rows.shape == (3, 8)
    np.testing.assert_array_equal(rows[0], rows[2])     # same key, same row
    assert (np.abs(rows) <= 0.05).all()
    assert len(t) == 2
    # a second table with the same seed inits identically
    t2 = native.SparseTable(8, rule="sgd", lr=0.1, init_range=0.05, seed=42)
    np.testing.assert_array_equal(t2.pull([5]), rows[:1])
    t.destroy()
    t2.destroy()


def test_table_sgd_push():
    t = native.SparseTable(4, rule="sgd", lr=0.5, init_range=0.0)
    before = t.pull([7])
    np.testing.assert_array_equal(before, np.zeros((1, 4)))
    t.push([7], np.ones((1, 4), np.float32))
    after = t.pull([7])
    np.testing.assert_allclose(after, np.full((1, 4), -0.5))
    t.destroy()


def test_table_adagrad_scales_updates():
    t = native.SparseTable(2, rule="adagrad", lr=1.0, init_range=0.0)
    g = np.array([[1.0, 4.0]], np.float32)
    t.push([1], g)
    w1 = t.pull([1])[0]
    # adagrad: delta = lr * g / sqrt(g^2) -> both dims move ~1.0 despite 4x grad
    np.testing.assert_allclose(w1, [-1.0, -1.0], atol=1e-4)
    t.destroy()


def test_table_save_load_roundtrip(tmp_path):
    t = native.SparseTable(4, rule="adagrad", lr=0.1, seed=1)
    t.pull(np.arange(100))
    t.push(np.arange(100), np.ones((100, 4), np.float32))
    want = t.pull([3, 50])
    t.save(str(tmp_path / "t.bin"))

    t2 = native.SparseTable(4, rule="adagrad", lr=0.1, seed=999)
    t2.load(str(tmp_path / "t.bin"))
    assert len(t2) == 100
    np.testing.assert_array_equal(t2.pull([3, 50]), want)
    # optimizer slots restored too: same push gives same result on both
    t.push([3], np.ones((1, 4), np.float32))
    t2.push([3], np.ones((1, 4), np.float32))
    np.testing.assert_allclose(t2.pull([3]), t.pull([3]), rtol=1e-6)
    t.destroy()
    t2.destroy()


def test_table_concurrent_push():
    import threading
    t = native.SparseTable(4, rule="sgd", lr=0.01, init_range=0.0)
    keys = np.arange(64)

    def worker():
        for _ in range(50):
            t.push(keys, np.ones((64, 4), np.float32))

    ts = [threading.Thread(target=worker) for _ in range(4)]
    for th in ts:
        th.start()
    for th in ts:
        th.join()
    # 4 threads * 50 pushes * lr 0.01 = -2.0 exactly (updates serialized per shard)
    np.testing.assert_allclose(t.pull(keys), np.full((64, 4), -2.0),
                               rtol=1e-5)
    t.destroy()


def test_async_communicator_merges():
    from paddle_tpu.distributed.ps import AsyncCommunicator
    t = native.SparseTable(4, rule="sgd", lr=1.0, init_range=0.0)
    c = AsyncCommunicator(t, merge_batches=3)
    c.start()
    for _ in range(6):
        c.push_sparse([1, 2], np.ones((2, 4), np.float32))
    c.flush()
    np.testing.assert_allclose(t.pull([1, 2]), np.full((2, 4), -6.0))
    c.stop()
    t.destroy()


def test_sparse_embedding_trains():
    """End-to-end: PS-backed embedding + dense layer learns a mapping
    (the reference's dist_fleet_ctr pattern, in-process)."""
    from paddle_tpu.distributed.ps import PSContext
    ctx = PSContext()
    ctx.create_table("emb", dim=8, rule="adagrad", lr=0.5, seed=3)
    emb = ctx.embedding("emb")
    head = nn.Linear(8, 2)
    opt = paddle.optimizer.Adam(1e-2, parameters=head.parameters())
    lf = nn.CrossEntropyLoss()

    rng = np.random.RandomState(0)
    ids = rng.randint(0, 50, size=(128,))
    labels = (ids % 2).astype("int64")

    losses = []
    for ep in range(15):
        for i in range(0, 128, 32):
            x = emb(paddle.to_tensor(ids[i:i + 32]))
            loss = lf(head(x), paddle.to_tensor(labels[i:i + 32]))
            loss.backward()
            opt.step()
            opt.clear_grad()
        losses.append(float(loss))
    ctx.barrier()
    assert losses[-1] < losses[0] * 0.7, losses
    assert len(ctx.table("emb")) == len(np.unique(ids))
    ctx.shutdown()


def test_ps_context_save_load(tmp_path):
    from paddle_tpu.distributed.ps import PSContext
    ctx = PSContext()
    ctx.create_table("emb", dim=4, rule="sgd", lr=0.1, async_push=False)
    ctx.table("emb").pull([1, 2, 3])
    ctx.save(str(tmp_path / "ps"))

    ctx2 = PSContext()
    ctx2.create_table("emb", dim=4, rule="sgd", lr=0.1, async_push=False)
    ctx2.load(str(tmp_path / "ps"))
    np.testing.assert_array_equal(ctx2.table("emb").pull([1, 2, 3]),
                                  ctx.table("emb").pull([1, 2, 3]))
    ctx.shutdown()
    ctx2.shutdown()


def test_shard_for_routing():
    from paddle_tpu.distributed.ps import shard_for
    s = shard_for([0, 1, 2, 3, 4, 5], 3)
    np.testing.assert_array_equal(s, [0, 1, 2, 0, 1, 2])
