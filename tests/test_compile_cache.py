"""Persistent compile cache + AOT serving warmup (ISSUE 8).

The load-bearing properties:

  - a process (or engine) restarted against a warm cache performs ZERO
    fresh compilations for the serving executable set — proven by the
    engine trace counters staying 0 (they tick only when jax traces)
    plus compile_cache hits, and by `bench.py --cold-start` reporting a
    warm process strictly faster to serving-ready than a cold one;
  - cache corruption in every flavor (torn write via fault injection,
    SIGKILL inside the commit window, post-commit truncation, version
    skew) degrades to a miss-and-recompile — never a crash, never a
    wrong executable;
  - `device.clear_op_cache()` is coherent across tiers: a cleared
    in-memory cache cannot resurrect a pre-clear persistent entry.

Crash cases reuse the test_checkpoint.py kill-window pattern and the
`observability/faults.py` `checkpoint.write` site, which fires inside
`ckpt_commit.atomic_commit` — the same protocol cache entries commit
through.
"""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.device as device
from paddle_tpu.framework import ckpt_commit
from paddle_tpu.framework import compile_cache as cc
from paddle_tpu.observability import faults
from paddle_tpu.serving import EngineConfig, GenerationEngine

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state():
    yield
    faults.disarm_all()
    cc.detach()


def _mul_add(x, y):
    return x * y + 1.0


# ------------------------------------------------------------ fundamentals

def test_cached_jit_roundtrip_and_stats(tmp_path):
    import jax.numpy as jnp
    cache = cc.CompileCache(str(tmp_path))
    a, b = jnp.ones((4, 4)), jnp.full((4, 4), 2.0)
    f1 = cc.cached_jit(_mul_add, "t.f", static_sig={"v": 1}, cache=cache)
    r1 = np.asarray(f1(a, b))
    assert cache.stats == {"hits": 0, "misses": 1, "bypass": 0,
                           "corrupt": 0, "uncacheable": 0, "evicted": 0}
    assert len(cache.entries()) == 1
    # a FRESH CachedFunction (fresh jit, as in a restarted process)
    # deserializes instead of compiling
    f2 = cc.cached_jit(_mul_add, "t.f", static_sig={"v": 1}, cache=cache)
    np.testing.assert_array_equal(np.asarray(f2(a, b)), r1)
    assert cache.stats["hits"] == 1 and cache.stats["misses"] == 1
    # a different static signature is a different program
    f3 = cc.cached_jit(_mul_add, "t.f", static_sig={"v": 2}, cache=cache)
    assert f3.warm(a, b) == "miss"
    # a different aval signature too
    assert f2.warm(jnp.ones((2, 2)), jnp.ones((2, 2))) == "miss"
    # no cache anywhere: transparently plain jit
    f4 = cc.cached_jit(_mul_add, "t.f", static_sig={"v": 1})
    assert f4.warm(a, b) == "off"
    np.testing.assert_array_equal(np.asarray(f4(a, b)), r1)


def test_lowering_mode_is_content_addressed(tmp_path):
    import jax.numpy as jnp
    cache = cc.CompileCache(str(tmp_path))
    a = jnp.ones((3, 3))
    cc.cached_jit(_mul_add, "op.x", key_mode="lowering", cache=cache)(a, a)
    before = cache.stats["hits"]
    # a DIFFERENT python callable with the SAME program content hits
    other = cc.cached_jit(lambda x, y: x * y + 1.0, "op.x",
                          key_mode="lowering", cache=cache)
    other(a, a)
    assert cache.stats["hits"] == before + 1
    # a semantically different program misses
    changed = cc.cached_jit(lambda x, y: x * y + 2.0, "op.x",
                            key_mode="lowering", cache=cache)
    assert changed.warm(a, a) == "miss"


# ------------------------------------------------- op-cache tier coherence

def test_eager_op_runners_use_persistent_tier(tmp_path):
    cc.attach(str(tmp_path))
    device.clear_op_cache()            # drop pre-test runners; stamp is
    cc.active()._min_ts = 0.0          # reset so this test sees its writes
    t = paddle.to_tensor(np.arange(6.0, dtype=np.float32))
    base = dict(cc.active().stats)
    r = (t * 3.0)
    np.testing.assert_array_equal(r.numpy(), np.arange(6.0) * 3.0)
    assert cc.active().stats["misses"] == base["misses"] + 1
    assert any(e.startswith("op.") for e in cc.active().entries())
    # a fresh runner for the same op (in-memory cache cleared, stamp
    # bypassed for entries already re-committed AFTER the clear) hits
    stamp = cc.active()._min_ts
    device.clear_op_cache()
    assert cc.active()._min_ts > stamp


def test_clear_op_cache_cannot_resurrect_stale_entry(tmp_path):
    """Satellite regression: after clear_op_cache(), a persistent entry
    committed BEFORE the clear must not be served again in this process
    (in-memory clear + persistent bypass are one coherent operation)."""
    cc.attach(str(tmp_path))
    device.clear_op_cache()            # fresh runners; then re-open the
    cc.active()._min_ts = 0.0          # stamp so this test's writes serve
    t = paddle.to_tensor(np.ones(4, np.float32))
    _ = (t + 7.0)
    stats0 = dict(cc.active().stats)
    n_entries = len(cc.active().entries())
    assert n_entries >= 1
    device.clear_op_cache()
    _ = (t + 7.0)                      # same op identity, post-clear
    stats1 = dict(cc.active().stats)
    # served as a bypass-miss and recompiled — NOT a hit on the old entry
    assert stats1["hits"] == stats0["hits"]
    assert stats1["bypass"] > stats0["bypass"]
    assert stats1["misses"] > stats0["misses"]
    # the entry was recommitted (fresh timestamp): hits again within the
    # post-clear epoch
    t2 = paddle.to_tensor(np.ones(4, np.float32))
    from paddle_tpu.core import tensor as _ct
    _ct._EAGER_CACHE.clear()           # in-memory only, no invalidate
    _ = (t2 + 7.0)
    assert cc.active().stats["hits"] == stats1["hits"] + 1


# --------------------------------------------------------- crash/corruption

def test_injected_torn_write_never_commits(tmp_path):
    """faults `checkpoint.write` truncate fires inside the entry commit:
    the store fails CONTAINED (warning, no entry), the call still
    returns, and the next lookup recompiles."""
    import jax.numpy as jnp
    cache = cc.CompileCache(str(tmp_path))
    a = jnp.ones((4,))
    faults.arm("checkpoint.write", mode="truncate", nth=1)
    with pytest.warns(UserWarning, match="commit .* failed|not persisted"):
        f = cc.cached_jit(_mul_add, "t.torn", cache=cache)
        out = np.asarray(f(a, a))      # computes fine despite the tear
    np.testing.assert_array_equal(out, np.ones(4) * 2.0)
    assert cache.entries() == []
    assert cache.stats["uncacheable"] == 1
    faults.disarm_all()
    # with the fault gone the same program commits and then hits
    f2 = cc.cached_jit(_mul_add, "t.torn", cache=cache)
    f2(a, a)
    assert len(cache.entries()) == 1
    f3 = cc.cached_jit(_mul_add, "t.torn", cache=cache)
    assert f3.warm(a, a) == "hit"


def test_sigkill_mid_commit_recovers(tmp_path):
    """Kill -9 inside the commit window (data files written, manifest
    not): the survivor sees no entry — hidden tempdir only — and
    recompiles; the stale tempdir is swept by the next commit."""
    cache_dir = str(tmp_path / "cache")
    script = f"""
import os
import paddle_tpu
from paddle_tpu.framework import compile_cache as cc
import jax.numpy as jnp
cache = cc.CompileCache({cache_dir!r})
f = cc.cached_jit(lambda x: x * 2.0 + 1.0, "t.kill", cache=cache)
print("READY", flush=True)
f(jnp.ones((8,)))                      # commit blocks in the delay window
print("DONE", flush=True)
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PTN_FAULTS="checkpoint.write=delay:delay=120:max=1")
    proc = subprocess.Popen([sys.executable, "-c", script],
                            stdout=subprocess.PIPE, text=True, env=env,
                            cwd=_ROOT)
    try:
        assert proc.stdout.readline().strip() == "READY"
        # the child is now compiling, then holds the commit open for
        # 120s; give the data files time to land, then kill the window
        deadline = time.time() + 120
        while time.time() < deadline:
            if any(n.startswith(".") for n in
                   os.listdir(cache_dir) if os.path.isdir(
                       os.path.join(cache_dir, n))):
                break
            time.sleep(0.1)
        time.sleep(0.3)                # inside the held-open window
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == -signal.SIGKILL
    # survivor: nothing committed, lookup is a clean miss + recompile
    cache = cc.CompileCache(cache_dir)
    assert cache.entries() == []
    import jax.numpy as jnp
    f = cc.cached_jit(lambda x: x * 2.0 + 1.0, "t.kill", cache=cache)
    out = np.asarray(f(jnp.ones((8,))))
    np.testing.assert_array_equal(out, np.full(8, 3.0))
    assert cache.stats == {"hits": 0, "misses": 1, "bypass": 0,
                           "corrupt": 0, "uncacheable": 0, "evicted": 0}
    assert len(cache.entries()) == 1
    # the dead child's hidden tempdir was swept by the commit
    assert not any(n.startswith(".") and ".tmp." in n
                   for n in os.listdir(cache_dir))


def test_truncated_entry_recovers(tmp_path):
    """Post-commit bit rot: a truncated entry file fails manifest
    verification at load — the entry is deleted and recompiled, the call
    succeeds."""
    import jax.numpy as jnp
    cache = cc.CompileCache(str(tmp_path))
    a = jnp.ones((5,))
    cc.cached_jit(_mul_add, "t.rot", cache=cache)(a, a)
    (entry,) = cache.entries()
    victim = None
    for name in os.listdir(str(tmp_path / entry)):
        if name != ckpt_commit.MANIFEST:
            victim = os.path.join(str(tmp_path / entry), name)
            break
    size = os.path.getsize(victim)
    with open(victim, "r+b") as f:
        f.truncate(size // 2)
    f2 = cc.cached_jit(_mul_add, "t.rot", cache=cache)
    with pytest.warns(UserWarning, match="failed verification"):
        out = np.asarray(f2(a, a))
    np.testing.assert_array_equal(out, np.full(5, 2.0))
    assert cache.stats["corrupt"] == 1
    # recompiled and recommitted: a third function hits cleanly
    assert len(cache.entries()) == 1
    f3 = cc.cached_jit(_mul_add, "t.rot", cache=cache)
    assert f3.warm(a, a) == "hit"


def test_version_skew_entry_rejected(tmp_path):
    """Defense in depth: an entry whose manifest verifies but whose meta
    names another jax build reads as a miss (deleted + recompiled),
    never a deserialization of a foreign executable."""
    import jax.numpy as jnp
    cache = cc.CompileCache(str(tmp_path))
    a = jnp.ones((3,))
    cc.cached_jit(_mul_add, "t.skew", cache=cache)(a, a)
    (entry,) = cache.entries()
    full = str(tmp_path / entry)
    with open(os.path.join(full, cc.ENTRY_META)) as f:
        meta = json.load(f)
    meta["jax_version"] = "0.0.0"
    # recommit THROUGH the protocol so the manifest stays valid — only
    # the meta lies
    with ckpt_commit.atomic_commit(full) as tmp:
        with open(os.path.join(tmp, cc.ENTRY_META), "w") as f:
            json.dump(meta, f)
        import shutil
        for name in os.listdir(full):
            if name not in (cc.ENTRY_META, ckpt_commit.MANIFEST):
                shutil.copy2(os.path.join(full, name),
                             os.path.join(tmp, name))
    f2 = cc.cached_jit(_mul_add, "t.skew", cache=cache)
    with pytest.warns(UserWarning, match="failed to load"):
        out = np.asarray(f2(a, a))
    np.testing.assert_array_equal(out, np.full(3, 2.0))
    assert cache.stats["corrupt"] == 1


# ------------------------------------------------------ serving AOT warmup

def test_engine_restart_zero_compiles(tmp_path):
    """The acceptance core, engine-level: a second engine over a warm
    cache deserializes its whole executable set — trace counters stay 0
    through precompile AND live serving, and tokens are exact."""
    from paddle_tpu.text.models import gpt_tiny
    model = gpt_tiny()
    model.eval()
    mk = lambda: EngineConfig(slots=2, max_len=32,  # noqa: E731
                              compile_cache_dir=str(tmp_path))
    e1 = GenerationEngine(model, mk())
    rep = e1.precompile()
    assert set(rep) == set(e1.executable_names())
    assert all(v == "miss" for v in rep.values())
    assert e1.trace_counts["decode"] == 1

    e2 = GenerationEngine(model, mk())
    rep2 = e2.precompile()
    assert all(v == "hit" for v in rep2.values()), rep2
    assert e2.trace_counts["decode"] == 0
    assert e2.trace_counts["prefill"] == {}
    assert e2.compile_cache.stats["misses"] == 0

    prompt = np.random.RandomState(3).randint(0, model.cfg.vocab_size, 6)
    t1 = [e1.prefill(0, prompt)]
    t2 = [e2.prefill(0, prompt)]
    for _ in range(4):
        t1.append(int(e1.decode()[0]))
        t2.append(int(e2.decode()[0]))
    assert t1 == t2
    # the proof the ISSUE names: zero fresh compilations at serve time
    assert e2.trace_counts["decode"] == 0
    assert e2.trace_counts["prefill"] == {}
    assert e2.compile_cache.stats["hits"] >= 2


def test_spec_engine_restart_zero_compiles(tmp_path):
    """The speculative set (draft decode/prefill + the [slots, γ+1]
    verify) rides the same cache: a restarted spec engine deserializes
    ALL of it and decodes bit-identically with zero traces."""
    from paddle_tpu.serving import SpecDecodeConfig, SpeculativeEngine
    from paddle_tpu.text.models import gpt_tiny
    model = gpt_tiny()
    model.eval()
    mk = lambda: SpecDecodeConfig(  # noqa: E731
        slots=2, max_len=32, block_size=8, gamma=2, draft_layers=1,
        compile_cache_dir=str(tmp_path))
    e1 = SpeculativeEngine(model, mk())
    rep1 = e1.precompile()
    assert set(rep1) == set(e1.executable_names())
    assert all(v == "miss" for v in rep1.values()), rep1

    e2 = SpeculativeEngine(model, mk())
    rep2 = e2.precompile()
    assert all(v == "hit" for v in rep2.values()), rep2
    for k in ("decode", "draft_decode", "spec_verify"):
        assert e2.trace_counts[k] == 0
    assert e2.trace_counts["prefill"] == {}
    assert e2.trace_counts["draft_prefill"] == {}

    prompt = [3, 1, 4, 1, 5]
    e1.prefill(0, prompt)
    e2.prefill(0, prompt)
    t1, _ = e1.decode_many()
    t2, _ = e2.decode_many()
    np.testing.assert_array_equal(t1, t2)
    for k in ("decode", "draft_decode", "spec_verify"):
        assert e2.trace_counts[k] == 0
    assert e2.trace_counts["prefill"] == {}
    assert e2.trace_counts["draft_prefill"] == {}
    assert e2.compile_cache.stats["misses"] == 0


def test_cold_predictor_serves_warm_with_zero_compiles(tmp_path):
    """Process-restart acceptance: a builder PROCESS precompiles the
    artifact's executable set; this (restarted) process loads a cold
    Predictor whose engine never traces — compile_cache hits are the
    only source of executables — and generates token-exactly."""
    artifact = str(tmp_path / "gpt")
    script = f"""
import paddle_tpu
from paddle_tpu.serving import EngineConfig, save_for_generation
from paddle_tpu.text.models import gpt_tiny
m = gpt_tiny(); m.eval()
rep = save_for_generation(m, {artifact!r},
                          engine_config=EngineConfig(slots=2, max_len=32),
                          precompile=True)
assert all(v == "miss" for v in rep.values()), rep
print("BUILT", len(rep), flush=True)
"""
    out = subprocess.run([sys.executable, "-c", script],
                         capture_output=True, text=True, timeout=420,
                         env=dict(os.environ, JAX_PLATFORMS="cpu"),
                         cwd=_ROOT)
    assert out.returncode == 0, out.stderr[-2000:]
    assert out.stdout.startswith("BUILT")

    from paddle_tpu.inference import Config, create_predictor
    pred = create_predictor(Config(artifact + ".pdmodel",
                                   artifact + ".pdiparams"))
    engine = pred._gen_sched.engine
    assert engine.trace_counts["decode"] == 0
    assert engine.trace_counts["prefill"] == {}
    assert engine.compile_cache.stats["misses"] == 0
    assert engine.compile_cache.stats["hits"] >= 2
    got = pred.generate([[5, 6, 7, 8]], max_new_tokens=4)[0]
    # still zero compiles after serving real requests
    assert engine.trace_counts["decode"] == 0
    assert engine.trace_counts["prefill"] == {}
    # never a wrong executable: token-exact vs a cache-free engine over
    # the same loaded weights
    ref = GenerationEngine(engine._model, EngineConfig(slots=2, max_len=32))
    want = [ref.prefill(0, [5, 6, 7, 8])]
    for _ in range(3):
        want.append(int(ref.decode()[0]))
    assert got == want
    # explicit engine kwargs still win over the recorded engine: the
    # auto-built scheduler is replaced, not silently kept
    got2 = pred.generate([[5, 6]], max_new_tokens=2, slots=3, max_len=16)
    assert pred._gen_sched.engine.config.slots == 3
    assert len(got2[0]) == 2


def test_gencfg_records_executable_set(tmp_path):
    """The sidecar carries the serving record even without precompile,
    so any later loader knows the full executable set."""
    from paddle_tpu.serving import save_for_generation
    from paddle_tpu.text.models import gpt_tiny
    m = gpt_tiny()
    m.eval()
    path = str(tmp_path / "gpt")
    save_for_generation(m, path,
                        engine_config=EngineConfig(slots=2, max_len=32))
    with open(path + ".gencfg") as f:
        meta = json.load(f)
    assert meta["serving"]["engine"] == "dense"
    assert meta["serving"]["config"]["slots"] == 2
    assert "decode" in meta["serving"]["executables"]
    assert "prefill[32]" in meta["serving"]["executables"]
    # precompile without an engine_config is a loud error
    with pytest.raises(ValueError, match="engine_config"):
        save_for_generation(m, path, precompile=True)


def test_bench_cold_start_rung(tmp_path):
    """`bench.py --cold-start` emits the driver schema, the warm child
    beats the cold child to serving-ready, and the rung's own
    zero-compile assertions held (it would have failed otherwise)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_INIT_BUDGET_S="120",
               BENCH_COLDSTART_DIR=str(tmp_path))
    out = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py"), "--cold-start"],
        capture_output=True, text=True, timeout=560, env=env, cwd=_ROOT)
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "gpt_cold_start_warm_ready_s"
    assert "error" not in rec, rec
    extra = rec["extra"]
    assert extra["warm_beats_cold"] is True
    assert rec["vs_baseline"] > 1.0
    assert extra["warm"]["compile_cache"]["misses"] == 0
    assert extra["warm"]["trace_counts"]["decode"] == 0
    assert extra["cold"]["compile_cache"]["misses"] >= 2
    assert extra["warm"]["first_token"] == extra["cold"]["first_token"]


def test_retention_cap_evicts_lru_by_mtime(tmp_path):
    """ISSUE 10 satellite (ROADMAP item 5 retention debt): a capped
    cache keeps at most max_entries committed entries, sweeping
    least-recently-USED at commit time — lookups refresh recency, the
    just-committed entry is never evicted, and evicted entries simply
    recompile (miss, never a crash)."""
    import jax.numpy as jnp
    cache = cc.CompileCache(str(tmp_path), max_entries=3)
    a = jnp.ones((4, 4))
    fns = [cc.cached_jit(_mul_add, "t.ret", static_sig={"v": i},
                         cache=cache) for i in range(5)]
    for i in range(3):
        fns[i](a, a)
        time.sleep(0.05)               # distinct mtimes
    assert len(cache.entries()) == 3
    # touch v=0 via a warm lookup from a fresh function: it becomes the
    # most recently USED even though it was committed first
    f0 = cc.cached_jit(_mul_add, "t.ret", static_sig={"v": 0},
                       cache=cache)
    assert f0.warm(a, a) == "hit"
    time.sleep(0.05)
    fns[3](a, a)                       # 4th entry: evicts v=1 (LRU)...
    time.sleep(0.05)
    fns[4](a, a)                       # 5th: evicts v=2
    assert len(cache.entries()) == 3
    assert cache.stats["evicted"] == 2
    # v=0 survived BECAUSE the lookup refreshed it; v=1/v=2 are gone
    assert cc.cached_jit(_mul_add, "t.ret", static_sig={"v": 0},
                         cache=cache).warm(a, a) == "hit"
    assert cc.cached_jit(_mul_add, "t.ret", static_sig={"v": 1},
                         cache=cache).warm(a, a) == "miss"
    # the flag wires the same cap into flag-built caches
    from paddle_tpu.framework import flags as _flags
    _flags.set_flags({"FLAGS_compile_cache_max_entries": 7})
    try:
        assert cc.CompileCache(str(tmp_path)).max_entries == 7
    finally:
        _flags.set_flags({"FLAGS_compile_cache_max_entries": 0})
    assert cc.CompileCache(str(tmp_path)).max_entries == 0  # unlimited
