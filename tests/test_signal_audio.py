"""paddle.signal (stft/istft/frame/overlap_add) + paddle.audio features.

Reference: python/paddle/signal.py, python/paddle/audio. STFT/iSTFT are
verified bit-close against torch; mel/mfcc verified structurally (peak
bins, shapes, differentiability).
"""
import numpy as np
import pytest

import paddle_tpu as paddle

torch = pytest.importorskip("torch")

SR, T, N_FFT, HOP = 16000, 4000, 512, 128


def _sig():
    t = np.arange(T) / SR
    return (np.sin(2 * np.pi * 440 * t)
            + 0.5 * np.sin(2 * np.pi * 880 * t)).astype("float32")


def test_stft_matches_torch():
    x = _sig()
    win = paddle.audio.functional.get_window("hann", N_FFT)
    spec = paddle.signal.stft(paddle.to_tensor(x[None]), N_FFT, HOP,
                              window=win)
    ref = torch.stft(torch.tensor(x[None]), N_FFT, HOP,
                     window=torch.hann_window(N_FFT, periodic=True),
                     center=True, pad_mode="reflect",
                     return_complex=True).numpy()
    ours = np.asarray(spec.numpy())
    assert ours.shape == ref.shape
    assert np.abs(ours - ref).max() / np.abs(ref).max() < 1e-5


def test_istft_roundtrip_and_torch_parity():
    x = _sig()
    win = paddle.audio.functional.get_window("hann", N_FFT)
    spec = paddle.signal.stft(paddle.to_tensor(x[None]), N_FFT, HOP,
                              window=win)
    rec = np.asarray(paddle.signal.istft(spec, N_FFT, HOP, window=win,
                                         length=T).numpy())[0]
    ref = torch.istft(torch.tensor(np.asarray(spec.numpy())), N_FFT, HOP,
                      window=torch.hann_window(N_FFT),
                      length=T).numpy()[0]
    assert np.abs(rec - ref).max() < 1e-4
    assert np.abs(rec[:3900] - x[:3900]).max() < 1e-4


def test_frame_overlap_add_inverse():
    x = np.arange(32, dtype="float32")
    # paddle layout: axis=-1 -> (frame_length, num_frames)
    fr = paddle.signal.frame(paddle.to_tensor(x), 8, 8)   # non-overlapping
    assert list(fr.shape) == [8, 4]
    np.testing.assert_allclose(np.asarray(fr.numpy())[:, 0],
                               np.arange(8, dtype="float32"))
    back = paddle.signal.overlap_add(fr, 8)
    np.testing.assert_allclose(np.asarray(back.numpy()), x)
    # axis=0 layout: (num_frames, frame_length)
    fr0 = paddle.signal.frame(paddle.to_tensor(x), 8, 8, axis=0)
    assert list(fr0.shape) == [4, 8]
    back0 = paddle.signal.overlap_add(fr0, 8, axis=0)
    np.testing.assert_allclose(np.asarray(back0.numpy()), x)


def test_mel_mfcc_features():
    x = _sig()
    mel = paddle.audio.features.MelSpectrogram(sr=SR, n_fft=N_FFT,
                                               hop_length=HOP, n_mels=40)
    m = mel(paddle.to_tensor(x[None]))
    assert list(m.shape)[:2] == [1, 40]
    mm = np.asarray(m.numpy())[0].mean(-1)
    assert 1 <= int(np.argmax(mm)) <= 15          # energy near 440/880 Hz

    mfcc = paddle.audio.features.MFCC(sr=SR, n_mfcc=13, n_fft=N_FFT,
                                      hop_length=HOP, n_mels=40)
    c = mfcc(paddle.to_tensor(x[None]))
    assert list(c.shape)[:2] == [1, 13]

    lm = paddle.audio.features.LogMelSpectrogram(
        sr=SR, n_fft=N_FFT, hop_length=HOP, n_mels=40, top_db=80.0)
    out = np.asarray(lm(paddle.to_tensor(x[None])).numpy())
    assert np.isfinite(out).all()
    assert out.max() - out.min() <= 80.0 + 1e-3


def test_spectrogram_is_differentiable():
    x = paddle.to_tensor(_sig()[None], stop_gradient=False)
    spec = paddle.audio.features.Spectrogram(n_fft=256, hop_length=64)
    out = spec(x)
    out.sum().backward()
    g = np.asarray(x.grad.numpy())
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_window_and_fbank_shapes():
    w = paddle.audio.functional.get_window("hamming", 128)
    assert list(w.shape) == [128]
    fb = paddle.audio.functional.compute_fbank_matrix(SR, N_FFT, n_mels=40)
    assert list(fb.shape) == [40, N_FFT // 2 + 1]
    # every filter has nonnegative weights, most have some energy
    fbn = np.asarray(fb.numpy())
    assert (fbn >= 0).all() and (fbn.sum(1) > 0).mean() > 0.9
    dct = paddle.audio.functional.create_dct(13, 40)
    assert list(dct.shape) == [40, 13]


def test_rfftn_roundtrip():
    x = np.random.RandomState(0).rand(4, 6, 8).astype("float32")
    X = paddle.fft.rfftn(paddle.to_tensor(x))
    back = paddle.fft.irfftn(X, s=(4, 6, 8))
    np.testing.assert_allclose(np.asarray(back.numpy()), x, atol=1e-5)
