"""paddle.utils / hub / callbacks / sysconfig / nn.utils / device
completions (reference: python/paddle/{utils,hub,callbacks,sysconfig}.py,
nn/utils/)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def test_weight_norm_roundtrip_and_training():
    paddle.seed(0)
    layer = nn.Linear(4, 3)
    w0 = layer.weight.numpy().copy()
    nn.utils.weight_norm(layer, "weight", dim=1)
    names = dict(layer.named_parameters())
    assert "weight_g" in names and "weight_v" in names \
        and "weight" not in names
    x = paddle.to_tensor(np.ones((2, 4), "float32"))
    out = layer(x)
    # initial reparameterization reproduces the original weight
    ref = nn.Linear(4, 3)
    ref.weight.set_value(paddle.to_tensor(w0))
    ref.bias.set_value(layer.bias)
    np.testing.assert_allclose(out.numpy(), ref(x).numpy(), rtol=1e-5,
                               atol=1e-6)
    # gradients flow to g and v
    out.sum().backward()
    assert names["weight_g"].grad is not None
    assert names["weight_v"].grad is not None
    # remove restores a single trainable weight with the same value
    nn.utils.remove_weight_norm(layer, "weight")
    names = dict(layer.named_parameters())
    assert "weight" in names and "weight_g" not in names
    np.testing.assert_allclose(layer.weight.numpy(), w0, rtol=1e-5,
                               atol=1e-6)


def test_spectral_norm_utility_caps_sigma():
    paddle.seed(0)
    layer = nn.Linear(6, 6)
    # inflate the weight so sigma >> 1
    layer.weight.set_value(paddle.to_tensor(
        np.eye(6, dtype="float32") * 10))
    nn.utils.spectral_norm(layer, "weight", n_power_iterations=5)
    x = paddle.to_tensor(np.ones((1, 6), "float32"))
    layer(x)
    w = np.asarray(layer.weight.numpy())
    sigma = np.linalg.svd(w, compute_uv=False)[0]
    np.testing.assert_allclose(sigma, 1.0, rtol=1e-2)


def test_spectral_norm_power_iteration_accumulates():
    """u must persist across forwards (code-review finding): with
    n_power_iterations=1, repeated forwards converge to sigma=1."""
    paddle.seed(0)
    layer = nn.Linear(8, 8)
    rng = np.random.RandomState(7)
    w = rng.randn(8, 8).astype("float32") * 3
    layer.weight.set_value(paddle.to_tensor(w))
    nn.utils.spectral_norm(layer, "weight", n_power_iterations=1)
    x = paddle.to_tensor(np.ones((1, 8), "float32"))
    for _ in range(30):
        layer(x)
    sigma = np.linalg.svd(np.asarray(layer.weight.numpy()),
                          compute_uv=False)[0]
    np.testing.assert_allclose(sigma, 1.0, rtol=5e-2)


def test_subm_conv_stride_raises():
    import pytest
    from paddle_tpu import sparse
    with pytest.raises(NotImplementedError, match="stride"):
        sparse.nn.SubmConv3D(2, 3, 3, stride=2)


def test_parameters_vector_roundtrip():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(3, 4), nn.Linear(4, 2))
    vec = nn.utils.parameters_to_vector(net.parameters())
    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    assert tuple(vec.shape) == (total,)
    doubled = paddle.to_tensor(vec.numpy() * 2)
    nn.utils.vector_to_parameters(doubled, net.parameters())
    vec2 = nn.utils.parameters_to_vector(net.parameters())
    np.testing.assert_allclose(vec2.numpy(), vec.numpy() * 2, rtol=1e-6)


def test_utils_deprecated_and_versions(capsys):
    @paddle.utils.deprecated(update_to="paddle.new_api", since="0.1")
    def old():
        return 42

    with pytest.warns(DeprecationWarning):
        assert old() == 42
    assert paddle.utils.require_version("0.0.1")
    with pytest.raises(Exception):
        paddle.utils.require_version("99.0")
    paddle.utils.run_check()
    assert "successfully" in capsys.readouterr().out
    np = paddle.utils.try_import("numpy")
    assert np is not None
    with pytest.raises(ImportError):
        paddle.utils.try_import("definitely_not_a_module_xyz")


def test_unique_name_and_download():
    a = paddle.utils.unique_name.generate("fc")
    b = paddle.utils.unique_name.generate("fc")
    assert a != b
    with paddle.utils.unique_name.guard():
        c = paddle.utils.unique_name.generate("fc")
        assert c == "fc_0"
    with pytest.raises(RuntimeError, match="zero-egress"):
        paddle.utils.download.get_weights_path_from_url(
            "https://example.com/w.pdparams")


def test_hub_local(tmp_path):
    (tmp_path / "hubconf.py").write_text(
        "def tiny_model(scale=1):\n"
        "    'A tiny model.'\n"
        "    return {'scale': scale}\n")
    assert "tiny_model" in paddle.hub.list(str(tmp_path), source="local")
    assert "tiny" in paddle.hub.help(str(tmp_path), "tiny_model",
                                     source="local")
    m = paddle.hub.load(str(tmp_path), "tiny_model", source="local",
                        scale=3)
    assert m == {"scale": 3}
    with pytest.raises(RuntimeError, match="zero-egress"):
        paddle.hub.load("user/repo", "m", source="github")


def test_callbacks_namespace_and_device_helpers():
    assert paddle.callbacks.EarlyStopping is not None
    assert paddle.callbacks.ReduceLROnPlateau is not None
    import os
    assert os.path.isdir(paddle.sysconfig.get_lib())
    assert paddle.device.get_cudnn_version() is None
    assert not paddle.device.is_compiled_with_rocm()
    assert "cpu" in paddle.device.get_all_device_type()
    assert paddle.device.get_available_device()


def test_bilinear_initializer():
    from paddle_tpu.nn.initializer import Bilinear
    w = np.asarray(Bilinear()((2, 2, 4, 4), "float32"))
    assert w.shape == (2, 2, 4, 4)
    # symmetric triangle filter, peak at center
    np.testing.assert_allclose(w[0, 0], w[0, 0][::-1, ::-1], rtol=1e-6)
    assert w[0, 0, 1:3, 1:3].min() > w[0, 0, 0, 0]
