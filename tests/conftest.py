"""Test env: 8 virtual CPU devices (SURVEY §4 — mirrors the reference's
subprocess-faked multi-device topology with XLA's host-platform device count)."""
import os

# Force CPU with 8 virtual devices (the shell env points JAX at the real TPU
# via JAX_PLATFORMS=axon; tests must not run there).
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# The axon sitecustomize pins the TPU backend regardless of JAX_PLATFORMS;
# jax.config wins over it.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu", "tests must run on CPU"
assert jax.device_count() == 8, "tests expect 8 virtual CPU devices"

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# Tiering (VERDICT r3 weak #8): the suite is compile-bound on one core and
# past 40 min; the model-zoo / multi-model / multi-process files below are
# the top of the measured --durations profile and carry the `slow` marker.
# Fast iteration tier: `pytest -m "not slow"`; full (CI) tier: everything,
# ideally `-n 2` (xdist) to overlap subprocess-heavy with compile-heavy.
_SLOW_FILES = {
    "test_det_nlp_models.py",       # ppyoloe trains: 512s
    "test_vision_zoo_r3.py",        # per-model forwards: 30-190s each
    "test_e2e_training.py",         # resnet18 + eager loops: 50-74s
    "test_hapi_dp.py",              # bert-tiny dp8 fit: 53s
    "test_hapi_hybrid.py",          # ernie pipeline fits: 21-67s
    "test_pipeline_schedules.py",   # schedule parity sweeps: ~20s each
    "test_parallel_spmd.py",        # hybrid shard_map compiles: ~20s each
    "test_multiprocess_dist.py",    # forked 2-process trainers
    "test_moe.py",                  # expert-parallel grads: 20s
    "test_examples.py",             # subprocess example smokes: ~60s each
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if os.path.basename(str(item.fspath)) in _SLOW_FILES:
            item.add_marker(pytest.mark.slow)


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu
    paddle_tpu.seed(2024)
    np.random.seed(2024)
    yield
