"""Test env: 8 virtual CPU devices (SURVEY §4 — mirrors the reference's
subprocess-faked multi-device topology with XLA's host-platform device count)."""
import os

# Force CPU with 8 virtual devices (the shell env points JAX at the real TPU
# via JAX_PLATFORMS=axon; tests must not run there).
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# The axon sitecustomize pins the TPU backend regardless of JAX_PLATFORMS;
# jax.config wins over it.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu", "tests must run on CPU"
assert jax.device_count() == 8, "tests expect 8 virtual CPU devices"

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu
    paddle_tpu.seed(2024)
    np.random.seed(2024)
    yield
