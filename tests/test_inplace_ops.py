"""In-place op variants (reference: python/paddle/tensor generate_inplace_fn
and @inplace_apis_in_dygraph_only surface)."""
import numpy as np

import paddle_tpu as paddle


def test_unary_inplace_identity_and_value():
    x = paddle.to_tensor(np.array([0.5, -0.25, 2.0], "float32"))
    ref = np.tanh(x.numpy())
    out = paddle.tanh_(x)
    assert out is x
    np.testing.assert_allclose(x.numpy(), ref, rtol=1e-6)

    x = paddle.to_tensor(np.array([-2.0, 0.3, 9.0], "float32"))
    x.clip_(0.0, 1.0)
    np.testing.assert_allclose(x.numpy(), [0.0, 0.3, 1.0])

    x = paddle.to_tensor(np.array([1.0, 4.0], "float32"))
    x.sqrt_()
    np.testing.assert_allclose(x.numpy(), [1.0, 2.0])


def test_shape_changing_inplace():
    x = paddle.to_tensor(np.zeros((2, 1, 3), "float32"))
    x.squeeze_(1)
    assert tuple(x.shape) == (2, 3)
    x.unsqueeze_(0)
    assert tuple(x.shape) == (1, 2, 3)
    x.flatten_()
    assert tuple(x.shape) == (6,)
    x.reshape_([3, 2])
    assert tuple(x.shape) == (3, 2)


def test_binary_and_indexed_inplace():
    x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], "float32"))
    y = paddle.to_tensor(np.array([10.0, 20.0, 30.0], "float32"))
    x.lerp_(y, 0.5)
    np.testing.assert_allclose(x.numpy(), [5.5, 11.0, 16.5])

    x = paddle.to_tensor(np.array([7.0, 8.0, 9.0], "float32"))
    x.remainder_(paddle.to_tensor(np.array([4.0, 4.0, 4.0], "float32")))
    np.testing.assert_allclose(x.numpy(), [3.0, 0.0, 1.0])

    x = paddle.to_tensor(np.zeros((3, 2), "float32"))
    upd = paddle.to_tensor(np.ones((2, 2), "float32"))
    idx = paddle.to_tensor(np.array([0, 2]))
    x.scatter_(idx, upd)
    np.testing.assert_allclose(x.numpy(), [[1, 1], [0, 0], [1, 1]])

    x = paddle.to_tensor(np.zeros((3, 3), "float32"))
    v = paddle.to_tensor(np.ones((2, 3), "float32"))
    x.index_add_(paddle.to_tensor(np.array([0, 1])), 0, v)
    assert float(x.numpy().sum()) == 6.0


def test_inplace_gradient_flows_through_tape():
    """In-place ops must adopt the tape node (code-review finding): backward
    through y.tanh_() must include the tanh derivative."""
    x = paddle.to_tensor(np.array([0.5, 1.0], "float32"), stop_gradient=False)
    y = x * 2.0
    y.tanh_()
    loss = y.sum()
    loss.backward()
    expect = 2.0 * (1.0 - np.tanh(np.array([1.0, 2.0])) ** 2)
    np.testing.assert_allclose(x.grad.numpy(), expect, rtol=1e-5)


def test_gaussian_seed_and_int_shape():
    a = paddle.tensor.extras.gaussian(4, seed=123)
    b = paddle.tensor.extras.gaussian(4, seed=123)
    np.testing.assert_array_equal(a.numpy(), b.numpy())
    assert tuple(a.shape) == (4,)
    c = paddle.tensor.extras.gaussian([4])
    d = paddle.tensor.extras.gaussian([4])
    assert not np.array_equal(c.numpy(), d.numpy())


def test_inplace_on_grad_leaf_raises():
    """Reference dygraph raises for inplace on a grad-requiring leaf; the
    gradient would otherwise silently land on a hidden snapshot."""
    import pytest
    x = paddle.to_tensor(np.array([0.5], "float32"), stop_gradient=False)
    with pytest.raises(RuntimeError, match="leaf"):
        x.tanh_()


def test_inplace_under_no_grad_preserves_trainability():
    x = paddle.to_tensor(np.array([2.0], "float32"), stop_gradient=False)
    with paddle.no_grad():
        x.clip_(0.0, 1.0)
    assert not x.stop_gradient
    np.testing.assert_allclose(x.numpy(), [1.0])


def test_inplace_version_mismatch_raises():
    """Mutating a tensor another op already consumed must raise in backward,
    not silently produce wrong gradients (reference: inplace version
    counters, imperative/variable_wrapper.h)."""
    import pytest
    a = paddle.to_tensor(np.array([1.0], "float32"), stop_gradient=False)
    y = a * 2.0
    z = y * 3.0
    y.tanh_()                      # mutates y AFTER z recorded it
    with pytest.raises(RuntimeError, match="version"):
        z.backward()


def test_activation_inplace_and_swish():
    import paddle_tpu.nn.functional as F
    x = paddle.to_tensor(np.array([-1.0, 1.0], "float32"))
    F.elu_(x)
    np.testing.assert_allclose(x.numpy(), [np.exp(-1) - 1, 1.0], rtol=1e-6)

    x = paddle.to_tensor(np.array([0.0, 1.0], "float32"))
    F.softmax_(x)
    np.testing.assert_allclose(x.numpy().sum(), 1.0, rtol=1e-6)

    x = paddle.to_tensor(np.array([2.0], "float32"))
    np.testing.assert_allclose(F.swish(x).numpy(),
                               2.0 / (1 + np.exp(-2.0)), rtol=1e-6)
