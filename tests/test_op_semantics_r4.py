"""Subtle op semantics ported from the reference test suite, cross-checked
against torch/numpy golden implementations (reference:
test_cross_entropy_loss.py, test_scatter_nd_op.py, test_gather_nd_op.py,
test_put_along_axis_op.py — the behavioral corners, not the harnesses).
"""
import numpy as np
import torch

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def t(a):
    return paddle.to_tensor(np.asarray(a))


class TestCrossEntropySemantics:
    def test_soft_label_matches_torch(self):
        rng = np.random.RandomState(0)
        logits = rng.randn(6, 5).astype("float32")
        soft = rng.rand(6, 5).astype("float32")
        soft /= soft.sum(1, keepdims=True)
        got = F.cross_entropy(t(logits), t(soft), soft_label=True).numpy()
        want = torch.nn.functional.cross_entropy(
            torch.tensor(logits), torch.tensor(soft)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_ignore_index_mean_denominator(self):
        """paddle (and torch) divide the mean by the count of NON-ignored
        rows, not the batch size."""
        rng = np.random.RandomState(1)
        logits = rng.randn(8, 4).astype("float32")
        labels = rng.randint(0, 4, 8).astype("int64")
        labels[[2, 5, 6]] = -100
        got = F.cross_entropy(t(logits), t(labels)).numpy()
        want = torch.nn.functional.cross_entropy(
            torch.tensor(logits), torch.tensor(labels)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_weighted_ignore_index_mean(self):
        """weighted mean divides by the sum of LIVE example weights
        (reference cross_entropy kernel's weighted path)."""
        rng = np.random.RandomState(2)
        logits = rng.randn(8, 4).astype("float32")
        labels = rng.randint(0, 4, 8).astype("int64")
        labels[3] = -100
        w = np.asarray([0.1, 0.5, 2.0, 1.0], np.float32)
        got = F.cross_entropy(t(logits), t(labels), weight=t(w)).numpy()
        want = torch.nn.functional.cross_entropy(
            torch.tensor(logits), torch.tensor(labels),
            weight=torch.tensor(w)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_all_ignored_is_finite(self):
        logits = np.ones((3, 4), np.float32)
        labels = np.full(3, -100, np.int64)
        got = float(F.cross_entropy(t(logits), t(labels)))
        assert np.isfinite(got) and got == 0.0


class TestScatterGatherNd:
    def test_scatter_nd_add_accumulates_duplicates(self):
        """Duplicate indices ACCUMULATE (reference scatter_nd_add_op) —
        the corner that at[].set would get wrong."""
        x = np.zeros(5, np.float32)
        idx = np.asarray([[1], [1], [1], [3]], np.int64)
        upd = np.asarray([1.0, 2.0, 3.0, 7.0], np.float32)
        got = paddle.scatter_nd_add(t(x), t(idx), t(upd)).numpy()
        np.testing.assert_allclose(got, [0, 6, 0, 7, 0])

    def test_scatter_overwrite_false_sums(self):
        """paddle.scatter(overwrite=False): duplicate rows SUM, and the
        destination row is zeroed first (not added to)."""
        x = np.full((3, 2), 10.0, np.float32)
        idx = np.asarray([1, 1], np.int64)
        upd = np.asarray([[1.0, 2.0], [3.0, 4.0]], np.float32)
        got = paddle.scatter(t(x), t(idx), t(upd), overwrite=False).numpy()
        np.testing.assert_allclose(got, [[10, 10], [4, 6], [10, 10]])

    def test_gather_nd_partial_index_returns_slices(self):
        """index depth < x.ndim gathers slices (reference gather_nd_op)."""
        x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
        idx = np.asarray([[0, 2], [1, 0]], np.int64)   # depth 2 of 3
        got = paddle.gather_nd(t(x), t(idx)).numpy()
        np.testing.assert_allclose(got, np.stack([x[0, 2], x[1, 0]]))

    def test_put_along_axis_reduce_modes(self):
        x = np.ones((2, 3), np.float32)
        idx = np.asarray([[0], [2]], np.int64)
        v = np.asarray([[5.0], [7.0]], np.float32)
        got_add = paddle.put_along_axis(t(x), t(idx), t(v), axis=1,
                                        reduce="add").numpy()
        want = torch.ones(2, 3).scatter_add_(
            1, torch.tensor(idx), torch.tensor(v)).numpy()
        np.testing.assert_allclose(got_add, want)
        got_mul = paddle.put_along_axis(t(x) * 2, t(idx), t(v), axis=1,
                                        reduce="mul").numpy()
        np.testing.assert_allclose(got_mul, [[10, 2, 2], [2, 2, 14]])


class TestSoftmaxWithCrossEntropy:
    def test_return_softmax(self):
        rng = np.random.RandomState(3)
        logits = rng.randn(4, 6).astype("float32")
        labels = rng.randint(0, 6, (4, 1)).astype("int64")
        out = F.softmax_with_cross_entropy(t(logits), t(labels),
                                           return_softmax=True)
        assert isinstance(out, (tuple, list)) and len(out) == 2, type(out)
        loss, sm = out
        np.testing.assert_allclose(
            sm.numpy(),
            torch.softmax(torch.tensor(logits), 1).numpy(), rtol=1e-5)
        want = torch.nn.functional.cross_entropy(
            torch.tensor(logits), torch.tensor(labels.squeeze(1)),
            reduction="none").numpy()
        np.testing.assert_allclose(loss.numpy().squeeze(), want, rtol=1e-5)
