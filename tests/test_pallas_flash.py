"""Pallas flash-attention kernels vs. plain-XLA reference (interpret mode).

Mirrors the reference's fused-attention op tests
(python/paddle/fluid/tests/unittests/test_fused_attention_op.py): forward
parity and analytic-gradient parity against an unfused implementation.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas.flash_attention import flash_attention


def ref_attention(q, k, v, causal, scale):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        S = s.shape[-1]
        mask = jnp.tril(jnp.ones((S, S), dtype=bool))
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def make_qkv(B=2, H=2, S=256, D=64, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (B, H, S, D)
    q = jax.random.normal(ks[0], shape, dtype)
    k = jax.random.normal(ks[1], shape, dtype)
    v = jax.random.normal(ks[2], shape, dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_reference(causal):
    q, k, v = make_qkv()
    scale = 1.0 / (q.shape[-1] ** 0.5)
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128,
                          interpret=True)
    ref = ref_attention(q, k, v, causal, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-3, rtol=2e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_reference(causal):
    q, k, v = make_qkv(B=1, H=2, S=128, D=64, seed=1)
    scale = 1.0 / (q.shape[-1] ** 0.5)
    w = jax.random.normal(jax.random.key(7), q.shape)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, interpret=True)
        return jnp.sum(o * w)

    def loss_ref(q, k, v):
        return jnp.sum(ref_attention(q, k, v, causal, scale) * w)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-3, rtol=5e-3,
                                   err_msg=f"d{name} mismatch")


def test_multi_block_causal_grads():
    # exercises block-skip logic: nq = nk = 2
    q, k, v = make_qkv(B=1, H=1, S=256, D=64, seed=2)

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       interpret=True) ** 2)

    def loss_ref(q, k, v):
        scale = 1.0 / (q.shape[-1] ** 0.5)
        return jnp.sum(ref_attention(q, k, v, True, scale) ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-3, rtol=5e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_s512_grads_match_xla_fallback(causal):
    # S=512 with block 128 => nq = nk = 4: pins the dkv grid-order fix
    # (grid (b, j, i) vs _kv_index_map's logical (b, i, j)) for both the
    # causal and non-causal paths against the unfused XLA reference.
    q, k, v = make_qkv(B=1, H=2, S=512, D=64, seed=4)
    scale = 1.0 / (q.shape[-1] ** 0.5)
    w = jax.random.normal(jax.random.key(11), q.shape)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128,
                            interpret=True)
        return jnp.sum(o * w)

    def loss_ref(q, k, v):
        return jnp.sum(ref_attention(q, k, v, causal, scale) * w)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-3, rtol=5e-3,
                                   err_msg=f"d{name} mismatch (causal={causal})")


def test_auto_block_sizes_for_non_512_multiples():
    # DEFAULT_BLOCK=512 must degrade to a divisor of S (r3 review finding:
    # S=640/768 are multiples of 128 but not 512)
    from paddle_tpu.ops.pallas.flash_attention import _auto_block
    assert _auto_block(1024) == 512
    assert _auto_block(768) == 256
    assert _auto_block(640) == 128
    assert _auto_block(64) == 64
    q, k, v = make_qkv(B=1, H=2, S=640, D=64, seed=5)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = ref_attention(q, k, v, True, 1.0 / (q.shape[-1] ** 0.5))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-3, rtol=2e-3)


def test_bf16_forward():
    q, k, v = make_qkv(S=128, dtype=jnp.bfloat16, seed=3)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = ref_attention(q, k, v, True, 1.0 / (q.shape[-1] ** 0.5))
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32),
                               np.asarray(ref, dtype=np.float32),
                               atol=3e-2, rtol=3e-2)
