"""Observability layer: auto-instrumented spans, statistic views, roofline
attribution, step-timeline JSONL (reference: test_profiler_statistic.py)."""
import json
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.profiler import (Profiler, ProfilerState, RecordEvent,
                                 TracerEventType, export_chrome_tracing,
                                 load_profiler_result, make_scheduler)
from paddle_tpu.profiler import statistic as stat


# ------------------------------------------------------- scheduler edge cases

def test_scheduler_repeat_expiry_stays_closed():
    sched = make_scheduler(closed=1, record=1, repeat=2)
    states = [sched(i) for i in range(8)]
    assert states[1] == ProfilerState.RECORD_AND_RETURN
    assert states[3] == ProfilerState.RECORD_AND_RETURN
    assert all(s == ProfilerState.CLOSED for s in states[4:])


def test_scheduler_skip_first_shifts_whole_cycle():
    sched = make_scheduler(closed=1, record=2, skip_first=3)
    assert [sched(i) for i in range(3)] == [ProfilerState.CLOSED] * 3
    assert sched(3) == ProfilerState.CLOSED       # cycle pos 0
    assert sched(4) == ProfilerState.RECORD
    assert sched(5) == ProfilerState.RECORD_AND_RETURN


def test_scheduler_record_1_degenerate_window():
    # record=1, no closed/ready: EVERY step is its own flushing window
    sched = make_scheduler(record=1, repeat=3)
    assert [sched(i) for i in range(3)] == \
        [ProfilerState.RECORD_AND_RETURN] * 3
    assert sched(3) == ProfilerState.CLOSED       # repeat exhausted


# ------------------------------------------------------- operator auto-spans

def test_apply_op_emits_operator_spans_with_shapes_and_cache():
    prof = Profiler(timer_only=True)
    with prof:
        x = paddle.to_tensor(np.ones((4, 8), np.float32))
        y = x * 2.0
        _ = y * 2.0          # same op identity again -> cache hit
    ops = [e for e in prof._events if e["type"] == TracerEventType.Operator
           and e["name"] == "multiply"]
    assert len(ops) >= 2
    attrs = ops[0]["attrs"]
    assert (4, 8) in attrs["input_shapes"]
    assert "float32" in attrs["input_dtypes"]
    outcomes = [e["attrs"].get("cache") for e in ops]
    assert "hit" in outcomes     # at least the repeat dispatch hit


def test_closed_profiler_records_nothing():
    from paddle_tpu.profiler import _tracer
    before = len(_tracer.events)
    x = paddle.to_tensor(np.ones((2, 2), np.float32))
    _ = x + 1.0
    assert len(_tracer.events) == before
    assert not _tracer.enabled


def test_communication_and_dataloader_spans():
    import paddle_tpu.distributed as dist
    from paddle_tpu.io import DataLoader, TensorDataset

    x = paddle.to_tensor(np.ones((8, 4), np.float32))
    ds = TensorDataset([x])
    prof = Profiler(timer_only=True)
    with prof:
        for (batch,) in DataLoader(ds, batch_size=4):
            pass
        t = paddle.to_tensor(np.ones(4, np.float32))
        dist.all_reduce(t)
    comm = [e for e in prof._events
            if e["type"] == TracerEventType.Communication]
    dl = [e for e in prof._events
          if e["type"] == TracerEventType.Dataloader]
    assert comm and comm[0]["attrs"]["collective"] == "all_reduce"
    assert comm[0]["attrs"]["payload_bytes"] == 16
    assert len(dl) == 2          # one span per produced batch, none extra


def test_phase_spans_backward_and_optimizer():
    net = nn.Linear(8, 4)
    opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
    x = paddle.to_tensor(np.ones((2, 8), np.float32))
    prof = Profiler(timer_only=True)
    with prof:
        loss = net(x).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    types = {e["type"] for e in prof._events}
    assert TracerEventType.Backward in types
    assert TracerEventType.Optimization in types


# --------------------------------------------------- nested depth vs threads

def test_nested_depth_across_threads():
    prof = Profiler(timer_only=True)
    barrier = threading.Barrier(2)

    def work(tag):
        barrier.wait()
        with RecordEvent(f"outer_{tag}"):
            with RecordEvent(f"inner_{tag}"):
                pass

    with prof:
        threads = [threading.Thread(target=work, args=(i,)) for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    by_name = {e["name"]: e for e in prof._events}
    for tag in (0, 1):
        outer, inner = by_name[f"outer_{tag}"], by_name[f"inner_{tag}"]
        assert outer["depth"] == 0 and inner["depth"] == 1
        assert outer["tid"] == inner["tid"]
    assert by_name["outer_0"]["tid"] != by_name["outer_1"]["tid"]


# --------------------------------------------------------- chrome trace fixes

def test_chrome_trace_empty_window_exports_empty(tmp_path):
    """An empty RECORD window must export as an empty trace — never fall
    back to the cumulative event history (the `or prof._events` bug)."""
    d = str(tmp_path / "trace")
    prof = Profiler(scheduler=None, timer_only=True)
    prof._events = [{"name": "stale", "type": "UserDefined", "tid": 1,
                     "ts": 0, "dur": 10, "depth": 0}]
    prof._window_events = []
    export_chrome_tracing(d)(prof)
    data = load_profiler_result(prof._exported_path)
    assert data["traceEvents"] == []


def test_chrome_trace_valid_window_scoped_with_depth_lanes(tmp_path):
    d = str(tmp_path / "trace")
    sched = make_scheduler(closed=1, record=1, repeat=2)
    prof = Profiler(scheduler=sched, timer_only=True,
                    on_trace_ready=export_chrome_tracing(d))
    prof.start()                      # step0: CLOSED
    with RecordEvent("closed_work"):
        pass
    prof.step()                       # step1: RECORD_AND_RETURN
    with RecordEvent("outer"):
        with RecordEvent("inner"):
            pass
    prof.step()                       # flush -> export
    prof.stop()
    data = load_profiler_result(prof._exported_path)
    spans = [e for e in data["traceEvents"] if e.get("ph") == "X"]
    names = {e["name"] for e in spans}
    assert "outer" in names and "inner" in names
    assert "closed_work" not in names          # window-scoped
    assert all(e["ph"] == "X" for e in spans)
    by_name = {e["name"]: e for e in spans}
    # depth-derived lanes: nested span rides a different tid lane
    assert by_name["outer"]["tid"] != by_name["inner"]["tid"]
    meta = [e for e in data["traceEvents"] if e.get("ph") == "M"]
    assert any(m["name"] == "thread_name" for m in meta)
    json.dumps(data)                           # round-trips as valid JSON


# ----------------------------------------------------------- step_info(unit)

def test_step_info_honors_unit_and_samples():
    prof = Profiler(timer_only=True)
    prof.start()
    for _ in range(3):
        prof.step(num_samples=32)
    prof.stop()
    out = prof.step_info(unit="images")
    assert "images/s" in out and "avg step" in out
    # throughput must reflect num_samples, not bare steps/s
    plain = prof.step_info()
    assert "steps/s" in plain


# ------------------------------------------------------------ cache stat API

def test_public_op_cache_stats_api():
    import paddle_tpu.device as device
    device.reset_op_cache_stats()
    x = paddle.to_tensor(np.ones((3, 3), np.float32))
    _ = x + x
    _ = x + x
    s = device.op_cache_stats()
    assert s["hits"] + s["misses"] + s["bypass"] >= 2
    assert 0.0 <= s["hit_rate"] <= 1.0
    assert s["size"] >= 0
    device.reset_op_cache_stats()
    s2 = device.op_cache_stats()
    assert s2["hits"] == s2["misses"] == s2["bypass"] == 0


# ----------------------------------------------------- views + attribution

def _eager_transformer_step():
    paddle.seed(0)
    net = nn.TransformerEncoderLayer(d_model=32, nhead=4,
                                     dim_feedforward=64)
    opt = paddle.optimizer.SGD(0.01, parameters=net.parameters())
    x = paddle.to_tensor(np.random.RandomState(0)
                         .rand(2, 8, 32).astype("float32"))
    out = net(x)
    loss = (out ** 2).mean()
    loss.backward()
    opt.step()
    opt.clear_grad()


def test_summary_views_render(capsys):
    prof = Profiler(timer_only=True, profile_memory=True)
    with prof:
        _eager_transformer_step()
        prof.step()
    prof.summary()
    out = capsys.readouterr().out
    assert "Overview Summary" in out
    assert "Operator Summary" in out
    assert "Memory Summary" in out
    assert "avg step" in out


def test_analyze_roofline_attribution_covers_compute():
    prof = Profiler(timer_only=True)
    with prof:
        _eager_transformer_step()
        prof.step()
    rep = prof.analyze(top_k=3)
    assert rep.rows, "no operator rows recorded"
    # acceptance: roofline attribution covers >=90% of recorded compute
    assert rep.coverage >= 0.9, f"coverage {rep.coverage}"
    assert len(rep.top_gaps) == 3
    for r in rep.top_gaps:
        assert r["gap_ms"] is not None and r["roofline_ms"] is not None
    matmul_rows = [r for r in rep.rows
                   if r["flops"] and r["roofline_ms"] is not None]
    assert matmul_rows, "no FLOP-carrying rows priced"
    md = rep.render()
    assert "top MFU gap contributors" in md and "roofline" in md


def test_analyze_phase_rows_sum_to_step_time_hapi_fit(tmp_path):
    """End-to-end: fit 3 steps under the profiler -> analyze() phases
    account for the bulk of wall time and never exceed it."""
    from paddle_tpu.hapi.model import Model
    from paddle_tpu.io import TensorDataset

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    model = Model(net)
    model.prepare(
        optimizer=paddle.optimizer.SGD(0.05, parameters=net.parameters()),
        loss=nn.MSELoss())
    rng = np.random.RandomState(0)
    ds = TensorDataset([paddle.to_tensor(rng.rand(12, 8).astype("float32")),
                        paddle.to_tensor(rng.rand(12, 4).astype("float32"))])
    tl = str(tmp_path / "fit.jsonl")
    prof = Profiler(timer_only=True, timeline=tl)
    prof.start()
    from paddle_tpu.io import DataLoader
    for xb, yb in DataLoader(ds, batch_size=4):
        model.train_batch([xb], [yb])
        prof.step()
    prof.stop()
    rep = prof.analyze()
    assert "Forward" in rep.phases and "Optimization" in rep.phases
    phase_sum = sum(rep.phases.values())
    assert rep.step_ms_total > 0
    # phases are non-overlapping unions inside the step wall time
    assert phase_sum <= rep.step_ms_total * 1.05
    assert phase_sum >= rep.step_ms_total * 0.5, \
        f"phases {rep.phases} vs wall {rep.step_ms_total}"
    # the timeline JSONL recorded one schema-valid record per step
    import sys, os
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    import perf_report
    records = perf_report.load_timeline(tl)
    assert len(records) == 3
    assert all(perf_report.validate_record(r) == [] for r in records)


def test_analyze_prices_same_shape_different_closure_separately():
    """Two `split` lambdas share a code object and input shape but close
    over different sections — each must get its own roofline estimate,
    and the heavyweight analyze-ref must attach once per bucket, not once
    per dispatch."""
    prof = Profiler(timer_only=True)
    with prof:
        x = paddle.to_tensor(np.ones((10, 64), np.float32))
        for _ in range(3):
            a, b = paddle.split(x, [2, 8])
    split_evs = [e for e in prof._events
                 if e["type"] == TracerEventType.Operator
                 and "split" in e["name"]]
    assert len(split_evs) == 6
    variants = {(e["attrs"] or {}).get("variant") for e in split_evs}
    assert len(variants) == 2, variants
    assert sum(e.get("_ref") is not None for e in split_evs) == 2
    rep = prof.analyze()
    split_rows = [r for r in rep.rows if "split" in r["name"]]
    assert len(split_rows) == 2
    priced = {r["bytes"] for r in split_rows if r["bytes"] is not None}
    assert len(priced) == 2, f"2-row and 8-row sections priced alike: {priced}"


def test_statistic_interval_union_and_intersection():
    a = [(0, 10), (5, 15), (20, 30)]
    assert stat._union_ns(a) == 25
    b = [(8, 22)]
    assert stat._intersect_ns(a, b) == 9       # (8,15) + (20,22)
    assert stat._intersect_ns([], b) == 0
