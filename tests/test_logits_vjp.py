"""bf16-cotangent logits backward (gpt_spmd._logits_matmul custom vjp):
in f32 it must be bit-identical to autodiff; in bf16 close to the f32
reference (the cast touches only the cotangent operand)."""
import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.parallel.gpt_spmd import _logits_matmul


def _loss(fn, h, w, labels):
    logits = fn(h, w)
    lp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.take_along_axis(lp, labels[..., None],
                                         axis=-1))


def test_f32_matches_plain_autodiff_exactly():
    rng = np.random.RandomState(0)
    h = jnp.asarray(rng.rand(2, 8, 16).astype("float32"))
    w = jnp.asarray(rng.rand(32, 16).astype("float32") * 0.1)
    labels = jnp.asarray(rng.randint(0, 32, (2, 8)))

    def plain(h, w):
        return jnp.einsum("bsh,vh->bsv", h, w,
                          preferred_element_type=jnp.float32)

    g1 = jax.grad(lambda h, w: _loss(_logits_matmul, h, w, labels),
                  argnums=(0, 1))(h, w)
    g2 = jax.grad(lambda h, w: _loss(plain, h, w, labels),
                  argnums=(0, 1))(h, w)
    for a, b in zip(g1, g2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_bf16_close_to_f32_reference():
    rng = np.random.RandomState(1)
    h32 = rng.rand(2, 8, 16).astype("float32")
    w32 = (rng.rand(32, 16).astype("float32") * 0.1)
    labels = jnp.asarray(rng.randint(0, 32, (2, 8)))
    h = jnp.asarray(h32, jnp.bfloat16)
    w = jnp.asarray(w32, jnp.bfloat16)
    gh, gw = jax.grad(lambda h, w: _loss(_logits_matmul, h, w, labels),
                      argnums=(0, 1))(h, w)
    assert gh.dtype == jnp.bfloat16 and gw.dtype == jnp.bfloat16
    rh, rw = jax.grad(
        lambda h, w: _loss(lambda a, b: jnp.einsum(
            "bsh,vh->bsv", a, b, preferred_element_type=jnp.float32),
            h, w, labels), argnums=(0, 1))(
        jnp.asarray(h32), jnp.asarray(w32))
    np.testing.assert_allclose(np.asarray(gh, np.float32), np.asarray(rh),
                               atol=2e-2, rtol=0.2)
    np.testing.assert_allclose(np.asarray(gw, np.float32), np.asarray(rw),
                               atol=2e-2, rtol=0.2)
