"""CI guard for the perf-evidence pipeline: `bench.py --profile --steps 2`
on CPU must emit a schema-valid step-timeline JSONL + attribution report,
and tools/perf_report.py must render both — so the artifacts a dead TPU
grant leaves behind can never silently rot."""
import json
import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_ROOT, "tools"))
import perf_report  # noqa: E402


@pytest.fixture(scope="module")
def bench_artifacts(tmp_path_factory):
    out_dir = str(tmp_path_factory.mktemp("benchprof"))
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               BENCH_B="2", BENCH_S="64", BENCH_LAYERS="2",
               BENCH_HIDDEN="64", BENCH_HEADS="4", BENCH_VOCAB="512",
               BENCH_INIT_BUDGET_S="120")
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py"),
         "--profile", "--steps", "2", "--profile-dir", out_dir],
        capture_output=True, text=True, timeout=480, cwd=_ROOT, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    return out_dir, json.loads(line)


def test_bench_profile_emits_metric_and_artifacts(bench_artifacts):
    out_dir, rec = bench_artifacts
    assert "error" not in rec, rec
    assert rec["metric"] == "gpt350m_train_mfu_1chip"
    assert rec["value"] > 0
    arts = rec["extra"]["profile_artifacts"]
    assert os.path.exists(arts["timeline"])
    assert os.path.exists(arts["attribution"])
    assert os.path.dirname(arts["timeline"]) == out_dir


def test_timeline_jsonl_schema_valid(bench_artifacts):
    out_dir, rec = bench_artifacts
    records = perf_report.load_timeline(out_dir)   # raises on any violation
    assert len(records) == 2                       # one record per step
    for r in records:
        assert perf_report.validate_record(r) == []
        assert r["schema"] == perf_report.SCHEMA
        assert "Forward" in r["phases"]            # the dispatch span
        assert r["step_ms"] is None or r["step_ms"] > 0


def test_attribution_report_names_phases(bench_artifacts):
    out_dir, rec = bench_artifacts
    text = open(os.path.join(out_dir, "attribution.md")).read()
    assert "MFU attribution" in text
    assert "Forward" in text
    assert "config: B=2 S=64" in text


def test_perf_report_renders_and_compares(bench_artifacts):
    out_dir, rec = bench_artifacts
    records = perf_report.load_timeline(out_dir)
    md = perf_report.render(records, title="smoke")
    assert "phase breakdown" in md and "avg step" in md
    cmp_md = perf_report.render_compare(records, records, "a", "b")
    assert "avg step ms" in cmp_md and "+0.0%" in cmp_md


def test_validate_record_catches_rot():
    good = {"schema": perf_report.SCHEMA, "step": 0, "step_ms": 1.0,
            "phases": {"Forward": 1.0}, "ops": [], "num_samples": None,
            "mem_peak_bytes": None}
    assert perf_report.validate_record(good) == []
    assert perf_report.validate_record({}) != []
    bad = dict(good, phases={"Forward": -1.0})
    assert perf_report.validate_record(bad) != []
    bad = dict(good, ops=[{"name": "x"}])       # missing calls/total_ms
    assert perf_report.validate_record(bad) != []
    bad = dict(good, schema="other.v9")
    assert perf_report.validate_record(bad) != []
