"""CI guard for the perf-evidence pipeline: `bench.py --profile --steps 2`
on CPU must emit a schema-valid step-timeline JSONL + attribution report,
and tools/perf_report.py must render both — so the artifacts a dead TPU
grant leaves behind can never silently rot.

ISSUE 4 extends the same guard to the unified metrics registry: the run
also leaves a metrics-snapshot JSONL (paddle_tpu.metrics.v1) and a
Prometheus text dump, both schema-validated here, and
tools/metrics_report.py --compare (the counter-regression gate) is
exercised against them."""
import json
import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_ROOT, "tools"))
import metrics_report  # noqa: E402
import perf_report  # noqa: E402


@pytest.fixture(scope="module")
def bench_artifacts(tmp_path_factory):
    out_dir = str(tmp_path_factory.mktemp("benchprof"))
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               BENCH_B="2", BENCH_S="64", BENCH_LAYERS="2",
               BENCH_HIDDEN="64", BENCH_HEADS="4", BENCH_VOCAB="512",
               BENCH_INIT_BUDGET_S="120")
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py"),
         "--profile", "--steps", "2", "--profile-dir", out_dir],
        capture_output=True, text=True, timeout=480, cwd=_ROOT, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    return out_dir, json.loads(line)


def test_bench_profile_emits_metric_and_artifacts(bench_artifacts):
    out_dir, rec = bench_artifacts
    assert "error" not in rec, rec
    assert rec["metric"] == "gpt350m_train_mfu_1chip"
    assert rec["value"] > 0
    arts = rec["extra"]["profile_artifacts"]
    assert os.path.exists(arts["timeline"])
    assert os.path.exists(arts["attribution"])
    assert os.path.dirname(arts["timeline"]) == out_dir


def test_timeline_jsonl_schema_valid(bench_artifacts):
    out_dir, rec = bench_artifacts
    records = perf_report.load_timeline(out_dir)   # raises on any violation
    assert len(records) == 2                       # one record per step
    for r in records:
        assert perf_report.validate_record(r) == []
        assert r["schema"] == perf_report.SCHEMA
        assert "Forward" in r["phases"]            # the dispatch span
        assert r["step_ms"] is None or r["step_ms"] > 0


def test_attribution_report_names_phases(bench_artifacts):
    out_dir, rec = bench_artifacts
    text = open(os.path.join(out_dir, "attribution.md")).read()
    assert "MFU attribution" in text
    assert "Forward" in text
    assert "config: B=2 S=64" in text


def test_perf_report_renders_and_compares(bench_artifacts):
    out_dir, rec = bench_artifacts
    records = perf_report.load_timeline(out_dir)
    md = perf_report.render(records, title="smoke")
    assert "phase breakdown" in md and "avg step" in md
    cmp_md = perf_report.render_compare(records, records, "a", "b")
    assert "avg step ms" in cmp_md and "+0.0%" in cmp_md


def test_metrics_snapshot_artifact_schema_valid(bench_artifacts):
    """The unified registry's JSONL snapshot rides the --profile artifact
    set and must stay schema-valid (paddle_tpu.metrics.v1)."""
    out_dir, rec = bench_artifacts
    arts = rec["extra"]["profile_artifacts"]
    assert os.path.exists(arts["metrics"])
    snaps = metrics_report.load_snapshots(arts["metrics"])  # raises on rot
    assert all(metrics_report.validate_snapshot(s) == [] for s in snaps)
    names = {m["name"] for m in snaps[-1]["metrics"]}
    # the migrated producers register on import — a bench process must
    # carry at least the op-cache and live-memory families
    for expected in ("op_cache_hits", "op_cache_misses",
                     "live_device_bytes", "serving_tokens_total",
                     "dataloader_wait_seconds"):
        assert expected in names, f"{expected} missing from {names}"


def test_metrics_prometheus_dump_valid(bench_artifacts):
    out_dir, rec = bench_artifacts
    path = rec["extra"]["profile_artifacts"]["metrics_prom"]
    assert os.path.exists(path)
    text = open(path).read()
    errs = metrics_report.validate_prometheus(text)
    assert errs == [], errs
    assert "# TYPE op_cache_hits gauge" in text


def test_metrics_report_compare_gates_regressions(bench_artifacts, tmp_path):
    """The CI regression gate: --compare of a run against itself passes;
    a failure counter that grew past the threshold exits nonzero."""
    out_dir, rec = bench_artifacts
    mpath = rec["extra"]["profile_artifacts"]["metrics"]
    cli = [sys.executable, os.path.join(_ROOT, "tools", "metrics_report.py")]
    ok = subprocess.run(cli + ["--compare", mpath, mpath],
                        capture_output=True, text=True, timeout=60)
    assert ok.returncode == 0, ok.stdout + ok.stderr

    # inject a grown failure counter into a copy: the gate must trip
    snap = metrics_report.load_snapshots(mpath)[-1]

    def with_counter(value):
        doc = json.loads(json.dumps(snap))
        doc["metrics"].append({
            "name": "probe_timeouts_total", "type": "counter", "help": "",
            "labelnames": [], "samples": [{"labels": {}, "value": value}]})
        return doc

    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    with open(a, "w") as f:
        f.write(json.dumps(with_counter(1)) + "\n")
    with open(b, "w") as f:
        f.write(json.dumps(with_counter(10)) + "\n")
    bad = subprocess.run(cli + ["--compare", a, b],
                         capture_output=True, text=True, timeout=60)
    assert bad.returncode == 1, bad.stdout + bad.stderr
    assert "probe_timeouts_total" in bad.stdout
    assert "REGRESSIONS" in bad.stdout


def _snapshot_with(counters):
    """Minimal valid paddle_tpu.metrics.v1 snapshot with given counter
    name->value pairs."""
    return {"schema": metrics_report.SCHEMA, "ts": 1.0, "pid": 1,
            "metrics": [
                {"name": n, "type": "counter", "help": "", "labelnames": [],
                 "samples": [{"labels": {}, "value": v}]}
                for n, v in counters.items()]}


def test_metrics_compare_flags_shed_preempt_and_prefix_rate(tmp_path):
    """ISSUE 6 gate: shed/preempt counter growth and a prefix-cache
    hit-RATE drop are failure-class regressions, even when the absolute
    hit count grew with traffic."""
    a = _snapshot_with({"serving_shed_total": 1,
                        "serving_preempted_total": 2,
                        "serving_prefix_cache_hits_total": 80,
                        "serving_prefix_cache_misses_total": 20,
                        "serving_tokens_total": 1000})
    b = _snapshot_with({"serving_shed_total": 10,
                        "serving_preempted_total": 9,
                        "serving_prefix_cache_hits_total": 100,  # grew...
                        "serving_prefix_cache_misses_total": 100,  # rate 0.5
                        "serving_tokens_total": 1000})
    regs = metrics_report.compare_counters(a, b)
    why = {k: w for k, _, _, _, w in regs}
    assert why["serving_shed_total"] == "failure counter grew"
    assert why["serving_preempted_total"] == "failure counter grew"
    assert why["serving_prefix_cache_misses_total"] == "failure counter grew"
    assert why["serving_prefix_cache_hit_rate"] == "hit rate dropped"
    # identical runs stay clean, and the CLI exit code reflects the gate
    assert metrics_report.compare_counters(a, a) == []
    pa, pb = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    for path, rec in ((pa, a), (pb, b)):
        with open(path, "w") as f:
            f.write(json.dumps(rec) + "\n")
    cli = [sys.executable, os.path.join(_ROOT, "tools", "metrics_report.py")]
    bad = subprocess.run(cli + ["--compare", pa, pb],
                         capture_output=True, text=True, timeout=60)
    assert bad.returncode == 1
    assert "serving_prefix_cache_hit_rate" in bad.stdout
    # a pure traffic-growth run (rate intact) passes the rate rule
    c = _snapshot_with({"serving_prefix_cache_hits_total": 800,
                        "serving_prefix_cache_misses_total": 200,
                        "serving_tokens_total": 9000})
    assert not any(w == "hit rate dropped" for *_, w in
                   metrics_report.compare_counters(a, c))


def test_metrics_compare_flags_spec_acceptance_rate_drop(tmp_path):
    """ISSUE 7 gate: a spec-decode acceptance-RATE drop is failure-class
    even when the absolute accepted count grew with traffic — and a
    traffic-growth run with the rate intact passes."""
    a = _snapshot_with({"serving_spec_accepted_total": 75,
                        "serving_spec_proposed_total": 100,
                        "serving_tokens_total": 500})
    b = _snapshot_with({"serving_spec_accepted_total": 90,   # grew...
                        "serving_spec_proposed_total": 300,  # rate 0.30
                        "serving_tokens_total": 500})
    regs = metrics_report.compare_counters(a, b)
    why = {k: w for k, *_, w in regs}
    assert why.get("serving_spec_acceptance_rate") == "hit rate dropped"
    # the CLI gate exits nonzero on the drop
    pa, pb = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    for path, rec in ((pa, a), (pb, b)):
        with open(path, "w") as f:
            f.write(json.dumps(rec) + "\n")
    cli = [sys.executable, os.path.join(_ROOT, "tools", "metrics_report.py")]
    bad = subprocess.run(cli + ["--compare", pa, pb],
                         capture_output=True, text=True, timeout=60)
    assert bad.returncode == 1
    assert "serving_spec_acceptance_rate" in bad.stdout
    # pure growth at the same rate: clean
    c = _snapshot_with({"serving_spec_accepted_total": 750,
                        "serving_spec_proposed_total": 1000,
                        "serving_tokens_total": 5000})
    assert not any(w == "hit rate dropped" for *_, w in
                   metrics_report.compare_counters(a, c))


def _snapshot_with_labeled(counters):
    """Snapshot whose counters carry per-sample labels:
    {name: [(labels_dict, value), ...]}."""
    return {"schema": metrics_report.SCHEMA, "ts": 1.0, "pid": 1,
            "metrics": [
                {"name": n, "type": "counter", "help": "",
                 "labelnames": sorted({k for lb, _ in samples
                                       for k in lb}),
                 "samples": [{"labels": lb, "value": v}
                             for lb, v in samples]}
                for n, samples in counters.items()]}


def test_metrics_compare_flags_spec_acceptance_rate_drop_pp_arm(tmp_path):
    """ISSUE 14 gate: the spec counters are labeled per ENGINE KIND, and
    the acceptance-rate rule pairs + gates each labelset separately — a
    spec×pp draft rotting on the pipeline ring is flagged even while
    the single-device engine's rate stays healthy (and must not drag
    the healthy series into the regression list)."""
    a = _snapshot_with_labeled({
        "serving_spec_accepted_total": [({"engine": "spec"}, 80),
                                        ({"engine": "spec_pp"}, 75)],
        "serving_spec_proposed_total": [({"engine": "spec"}, 100),
                                        ({"engine": "spec_pp"}, 100)]})
    b = _snapshot_with_labeled({
        "serving_spec_accepted_total": [({"engine": "spec"}, 160),
                                        ({"engine": "spec_pp"}, 90)],
        "serving_spec_proposed_total": [({"engine": "spec"}, 200),
                                        ({"engine": "spec_pp"}, 300)]})
    regs = metrics_report.compare_counters(a, b)
    why = {k: w for k, *_, w in regs}
    assert why.get("serving_spec_acceptance_rate{engine=spec_pp}") == \
        "hit rate dropped"
    assert "serving_spec_acceptance_rate{engine=spec}" not in why
    # the CLI gate exits nonzero and names the labeled series
    pa, pb = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    for path, rec in ((pa, a), (pb, b)):
        with open(path, "w") as f:
            f.write(json.dumps(rec) + "\n")
    cli = [sys.executable, os.path.join(_ROOT, "tools",
                                        "metrics_report.py")]
    bad = subprocess.run(cli + ["--compare", pa, pb],
                         capture_output=True, text=True, timeout=60)
    assert bad.returncode == 1
    assert "serving_spec_acceptance_rate{engine=spec_pp}" in bad.stdout


def test_metrics_compare_spans_label_schema_boundary():
    """A baseline recorded BEFORE the spec counters grew the engine
    label must still gate: the labeled run's family aggregate pairs
    with the bare baseline rate, and the bare-vs-labeled key mismatch
    is read as a schema change — never as counters vanishing/appearing
    ('work counter shrank' false positives)."""
    old = _snapshot_with({"serving_spec_accepted_total": 75,
                          "serving_spec_proposed_total": 100})
    new_bad = _snapshot_with_labeled({
        "serving_spec_accepted_total": [({"engine": "spec"}, 90)],
        "serving_spec_proposed_total": [({"engine": "spec"}, 300)]})
    regs = metrics_report.compare_counters(old, new_bad)
    why = {k: w for k, *_, w in regs}
    assert why.get("serving_spec_acceptance_rate") == "hit rate dropped"
    assert not any(w == "work counter shrank" for w in why.values())
    # same rate and volume across the boundary: clean both directions
    # (the bare row compares against the labeled side's family SUM, so
    # the volume rules keep gating across the schema change too)
    new_ok = _snapshot_with_labeled({
        "serving_spec_accepted_total": [({"engine": "spec"}, 75)],
        "serving_spec_proposed_total": [({"engine": "spec"}, 100)]})
    assert metrics_report.compare_counters(old, new_ok) == []
    assert metrics_report.compare_counters(new_ok, old) == []
    # two LABELED runs with identical per-engine rates but a shifted
    # traffic mix: the per-labelset series gate, and the bare family
    # aggregate must NOT fire on the mix shift (Simpson's paradox)
    mix_a = _snapshot_with_labeled({
        "serving_spec_accepted_total": [({"engine": "spec"}, 90),
                                        ({"engine": "spec_pp"}, 30)],
        "serving_spec_proposed_total": [({"engine": "spec"}, 100),
                                        ({"engine": "spec_pp"}, 100)]})
    mix_b = _snapshot_with_labeled({
        "serving_spec_accepted_total": [({"engine": "spec"}, 90),
                                        ({"engine": "spec_pp"}, 300)],
        "serving_spec_proposed_total": [({"engine": "spec"}, 100),
                                        ({"engine": "spec_pp"}, 1000)]})
    assert not any(w == "hit rate dropped" for *_, w in
                   metrics_report.compare_counters(mix_a, mix_b))
    # a labeled MEMBER vanishing between two labeled runs is NOT a
    # schema change: an engine dropping out of the fleet must keep
    # tripping the counter rules
    gone = _snapshot_with_labeled({
        "serving_spec_accepted_total": [({"engine": "spec"}, 90)],
        "serving_spec_proposed_total": [({"engine": "spec"}, 100)]})
    regs = metrics_report.compare_counters(mix_a, gone)
    assert any(k == "serving_spec_accepted_total{engine=spec_pp}"
               and w == "work counter shrank" for k, *_, w in regs)
    # volume rules bridge too: a 99% collapse in spec WORK across the
    # boundary gates even while the acceptance rate holds — the bare
    # row compares against the labeled side's family sum
    tiny_new = _snapshot_with_labeled({
        "serving_spec_accepted_total": [({"engine": "spec"}, 7)],
        "serving_spec_proposed_total": [({"engine": "spec"}, 10)]})
    regs = metrics_report.compare_counters(old, tiny_new)
    why = {k: w for k, *_, w in regs}
    assert why.get("serving_spec_accepted_total") == "work counter shrank"
    assert why.get("serving_spec_proposed_total") == "work counter shrank"


def test_metrics_compare_flags_quant_quality_regressions(tmp_path):
    """ISSUE 11 gate: a `serving_quant_greedy_match` drop (the quantized
    path disagreeing with its f32 oracle) and a `serving_quant_logit_kl`
    growth are failure-class — int8 serving that drifts from float is a
    correctness regression, however fast. Both directions exercised
    through compare_counters AND the CLI exit code."""
    a = _snapshot_with_gauges(gauges={"serving_quant_greedy_match": 1.0,
                                      "serving_quant_logit_kl": 0.001,
                                      "serving_load_tokens_per_s": 100.0})
    b = _snapshot_with_gauges(gauges={"serving_quant_greedy_match": 0.62,
                                      "serving_quant_logit_kl": 0.9,
                                      "serving_load_tokens_per_s": 100.0})
    regs = metrics_report.compare_counters(a, b, min_delta=0.001)
    why = {k: w for k, *_, w in regs}
    assert why["serving_quant_greedy_match"] == \
        "quantized greedy-match rate vs f32 oracle dropped"
    assert why["serving_quant_logit_kl"] == \
        "quantized logit KL vs f32 oracle grew"
    assert metrics_report.compare_counters(a, a, min_delta=0.001) == []
    pa, pb = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    for path, rec in ((pa, a), (pb, b)):
        with open(path, "w") as f:
            f.write(json.dumps(rec) + "\n")
    cli = [sys.executable, os.path.join(_ROOT, "tools", "metrics_report.py")]
    bad = subprocess.run(cli + ["--compare", pa, pb, "--min-delta", "0.001"],
                         capture_output=True, text=True, timeout=60)
    assert bad.returncode == 1
    assert "serving_quant_greedy_match" in bad.stdout
    # an unchanged-quality run with MORE traffic stays clean
    c = _snapshot_with_gauges(gauges={"serving_quant_greedy_match": 1.0,
                                      "serving_quant_logit_kl": 0.001,
                                      "serving_load_tokens_per_s": 900.0})
    assert metrics_report.compare_counters(a, c, min_delta=0.001) == []


def test_metrics_compare_flags_numerics_anomalies(tmp_path):
    """ISSUE 19 gate: `numerics_anomaly_total` growth (a latched
    sentinel anomaly, per site×kind labelset) and a
    `numerics_site_finite_frac` gauge drop are failure-class — a run
    that went non-finite is broken however fast it was. Exercised
    through compare_counters AND the CLI exit code."""
    a = _snapshot_with_labeled({
        "numerics_anomaly_total": [({"site": "decode.logits",
                                     "kind": "nonfinite"}, 0)]})
    b = _snapshot_with_labeled({
        "numerics_anomaly_total": [({"site": "decode.logits",
                                     "kind": "nonfinite"}, 2)]})
    regs = metrics_report.compare_counters(a, b)
    why = {k: w for k, *_, w in regs}
    assert why.get("numerics_anomaly_total{kind=nonfinite,"
                   "site=decode.logits}") == "failure counter grew"
    assert metrics_report.compare_counters(a, a) == []
    # the finite-fraction gauge dropping fires the gauge-drop rule
    ga = _snapshot_with_gauges(
        gauges={"numerics_site_finite_frac": 1.0})
    gb = _snapshot_with_gauges(
        gauges={"numerics_site_finite_frac": 0.5})
    gregs = metrics_report.compare_counters(ga, gb)
    gwhy = {k: w for k, *_, w in gregs}
    assert any("finite fraction dropped" in w for w in gwhy.values()), gregs
    # the CLI gate exits nonzero and names the counter
    pa, pb = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    for path, rec in ((pa, a), (pb, b)):
        with open(path, "w") as f:
            f.write(json.dumps(rec) + "\n")
    cli = [sys.executable, os.path.join(_ROOT, "tools", "metrics_report.py")]
    bad = subprocess.run(cli + ["--compare", pa, pb],
                         capture_output=True, text=True, timeout=60)
    assert bad.returncode == 1
    assert "numerics_anomaly_total" in bad.stdout


def test_metrics_compare_flags_gray_failure_plane(tmp_path):
    """ISSUE 20 gate: deadline-miss growth (router- or worker-side),
    suspect-reason migrations, and retry-budget exhaustion are
    failure-class, and the hedge primary-win RATE dropping fires even
    while both hedge counters grew with traffic. Drain-reason
    migrations are deliberate rolling-restart traffic and must pass.
    Exercised through compare_counters AND the CLI exit code."""
    a = _snapshot_with_labeled({
        "serving_deadline_missed_total": [({"where": "router"}, 1)],
        "serving_migrations_total": [({"reason": "suspect"}, 1),
                                     ({"reason": "drain"}, 2)],
        "serving_retry_budget_exhausted_total": [({"worker": "0"}, 0)],
        "serving_hedge_primary_total": [({"verb": "POLL"}, 90)],
        "serving_hedge_fired_total": [({"verb": "POLL"}, 10)]})
    b = _snapshot_with_labeled({
        "serving_deadline_missed_total": [({"where": "router"}, 10)],
        "serving_migrations_total": [({"reason": "suspect"}, 9),
                                     ({"reason": "drain"}, 40)],
        "serving_retry_budget_exhausted_total": [({"worker": "0"}, 6)],
        "serving_hedge_primary_total": [({"verb": "POLL"}, 100)],  # grew..
        "serving_hedge_fired_total": [({"verb": "POLL"}, 100)]})   # rate .5
    regs = metrics_report.compare_counters(a, b)
    why = {k: w for k, *_, w in regs}
    assert why.get("serving_deadline_missed_total{where=router}") \
        == "failure counter grew"
    assert why.get("serving_migrations_total{reason=suspect}") \
        == "failure counter grew"
    assert why.get("serving_retry_budget_exhausted_total{worker=0}") \
        == "failure counter grew"
    assert why.get("serving_hedge_primary_rate{verb=POLL}") \
        == "hit rate dropped"
    # drain-reason migrations grew 20x and must NOT gate: a rolling
    # restart migrating every stream is the feature working
    assert "serving_migrations_total{reason=drain}" not in why
    # identical runs stay clean
    assert metrics_report.compare_counters(a, a) == []
    # the CLI gate exits nonzero and names the new failure classes
    pa, pb = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    for path, rec in ((pa, a), (pb, b)):
        with open(path, "w") as f:
            f.write(json.dumps(rec) + "\n")
    cli = [sys.executable, os.path.join(_ROOT, "tools", "metrics_report.py")]
    bad = subprocess.run(cli + ["--compare", pa, pb],
                         capture_output=True, text=True, timeout=60)
    assert bad.returncode == 1
    assert "serving_deadline_missed_total" in bad.stdout
    assert "serving_migrations_total{reason=suspect}" in bad.stdout
    assert "serving_hedge_primary_rate" in bad.stdout


def test_bench_train_rung_runs_numerics_armed(bench_artifacts):
    """ISSUE 19 satellite: the healthy bench train rung runs with the
    sentinel plane armed, asserts ZERO latched anomalies, and ships the
    per-site stats in extra — so every committed BENCH record doubles
    as a numerics-health attestation."""
    out_dir, rec = bench_artifacts
    num = rec["extra"]["numerics"]
    assert num["anomalies"] == 0
    assert num["counts"] == {}
    sites = num["sites"]
    assert "train.param_global_norm" in sites
    assert "train.loss" in sites
    for site, st in sites.items():
        assert st["finite_frac"] == 1.0, (site, st)


def test_bench_emits_cost_model_delta(bench_artifacts):
    """ISSUE 8 satellite (ROADMAP item 1 debt): every bench run carries
    the analytical predicted-vs-measured block in extra, and the
    prediction/measurement gauges ride the metrics artifact so
    --compare can gate the gap."""
    out_dir, rec = bench_artifacts
    cm = rec["extra"]["cost_model"]
    assert "error" not in cm, cm
    assert cm["predicted_step_ms"] > 0
    assert cm["measured_step_ms"] > 0
    assert cm["measured_vs_predicted"] == pytest.approx(
        cm["measured_step_ms"] / cm["predicted_step_ms"], rel=1e-3)
    assert cm["per_op"], "per-op prediction table is empty"
    for row in cm["per_op"].values():
        assert row["predicted_ms"] >= 0
        assert "delta_ms" in row and "measured_share_ms" in row
    # the gauges landed in the registry snapshot artifact
    snaps = metrics_report.load_snapshots(
        rec["extra"]["profile_artifacts"]["metrics"])
    names = {m["name"] for m in snaps[-1]["metrics"]}
    for g in ("bench_cost_model_predicted_step_ms",
              "bench_cost_model_measured_step_ms",
              "bench_cost_model_measured_vs_predicted"):
        assert g in names, f"{g} missing from snapshot"


def _snapshot_with_gauges(counters=None, gauges=None):
    metrics = [
        {"name": n, "type": "counter", "help": "", "labelnames": [],
         "samples": [{"labels": {}, "value": v}]}
        for n, v in (counters or {}).items()]
    metrics += [
        {"name": n, "type": "gauge", "help": "", "labelnames": [],
         "samples": [{"labels": {}, "value": v}]}
        for n, v in (gauges or {}).items()]
    return {"schema": metrics_report.SCHEMA, "ts": 1.0, "pid": 1,
            "metrics": metrics}


def test_metrics_compare_flags_compile_cache_hit_rate_drop(tmp_path):
    """ISSUE 8 gate: a persistent compile-cache hit-RATE drop is a
    failure-class regression (restarts started compiling again) even
    when the absolute hit count grew with more executables."""
    a = _snapshot_with({"compile_cache_hits_total": 9,
                        "compile_cache_misses_total": 1,
                        "serving_tokens_total": 100})
    b = _snapshot_with({"compile_cache_hits_total": 10,   # grew...
                        "compile_cache_misses_total": 10,  # rate 0.9 -> 0.5
                        "serving_tokens_total": 100})
    regs = metrics_report.compare_counters(a, b)
    why = {k: w for k, *_, w in regs}
    assert why.get("compile_cache_hit_rate") == "hit rate dropped"
    # growth at the same rate passes the rate rule
    c = _snapshot_with({"compile_cache_hits_total": 90,
                        "compile_cache_misses_total": 10,
                        "serving_tokens_total": 1000})
    assert not any(w == "hit rate dropped" for *_, w in
                   metrics_report.compare_counters(a, c))
    # and the CLI gate exits nonzero on the drop
    pa, pb = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    for path, rec in ((pa, a), (pb, b)):
        with open(path, "w") as f:
            f.write(json.dumps(rec) + "\n")
    cli = [sys.executable, os.path.join(_ROOT, "tools", "metrics_report.py")]
    bad = subprocess.run(cli + ["--compare", pa, pb],
                         capture_output=True, text=True, timeout=60)
    assert bad.returncode == 1
    assert "compile_cache_hit_rate" in bad.stdout


def test_metrics_compare_flags_cost_model_gap_growth(tmp_path):
    """ISSUE 8 satellite gate: the measured/predicted step-time gauge
    GROWING past the threshold is failure-class; shrinking (we got
    faster than the model expected) is not."""
    a = _snapshot_with_gauges(
        gauges={"bench_cost_model_measured_vs_predicted": 2.0,
                "bench_cost_model_predicted_step_ms": 10.0})
    b = _snapshot_with_gauges(
        gauges={"bench_cost_model_measured_vs_predicted": 3.5,
                "bench_cost_model_predicted_step_ms": 10.0})
    regs = metrics_report.compare_counters(a, b)
    why = {k: w for k, *_, w in regs}
    assert why.get("bench_cost_model_measured_vs_predicted") == \
        "measured/predicted gap widened"
    # improvement or stability: clean
    assert metrics_report.compare_counters(a, a) == []
    assert metrics_report.compare_counters(b, a) == []
    # the CLI gate trips on the widened gap
    pa, pb = str(tmp_path / "ga.jsonl"), str(tmp_path / "gb.jsonl")
    for path, rec in ((pa, a), (pb, b)):
        with open(path, "w") as f:
            f.write(json.dumps(rec) + "\n")
    cli = [sys.executable, os.path.join(_ROOT, "tools", "metrics_report.py")]
    bad = subprocess.run(cli + ["--compare", pa, pb],
                         capture_output=True, text=True, timeout=60)
    assert bad.returncode == 1
    assert "gap widened" in bad.stdout


def test_metrics_compare_flags_pp_bubble_growth(tmp_path):
    """ISSUE 13 gate: the pipeline-serving bubble fraction GROWING past
    the threshold is failure-class (stages started idling — schedule
    rot or microbatch imbalance); shrinking or stable stays clean."""
    a = _snapshot_with_gauges(gauges={"serving_pp_bubble_fraction": 0.20})
    b = _snapshot_with_gauges(gauges={"serving_pp_bubble_fraction": 0.45})
    regs = metrics_report.compare_counters(a, b)
    why = {k: w for k, *_, w in regs}
    assert why.get("serving_pp_bubble_fraction") == \
        "pipeline-serving bubble fraction grew"
    assert metrics_report.compare_counters(a, a) == []
    assert metrics_report.compare_counters(b, a) == []
    pa, pb = str(tmp_path / "pa.jsonl"), str(tmp_path / "pb.jsonl")
    for path, rec in ((pa, a), (pb, b)):
        with open(path, "w") as f:
            f.write(json.dumps(rec) + "\n")
    cli = [sys.executable, os.path.join(_ROOT, "tools", "metrics_report.py")]
    bad = subprocess.run(cli + ["--compare", pa, pb],
                         capture_output=True, text=True, timeout=60)
    assert bad.returncode == 1
    assert "bubble fraction grew" in bad.stdout


def test_metrics_compare_flags_deviceprof_regressions(tmp_path):
    """ISSUE 9 gate: the device-profile gauges are failure classes —
    total device ms/step GROWING past the threshold (the kernels got
    slower) and per-op efficiency DROPPING past it (an op moved away
    from its roofline) both trip --compare; improvement stays clean."""
    a = _snapshot_with_gauges(
        gauges={"deviceprof_total_device_ms_per_step": 10.0,
                "deviceprof_min_op_efficiency": 0.8,
                "deviceprof_device_wall_ratio": 0.5})
    b = _snapshot_with_gauges(
        gauges={"deviceprof_total_device_ms_per_step": 20.0,   # grew 2x
                "deviceprof_min_op_efficiency": 0.3,           # dropped
                "deviceprof_device_wall_ratio": 0.5})
    regs = metrics_report.compare_counters(a, b)
    why = {k: w for k, *_, w in regs}
    assert why.get("deviceprof_total_device_ms_per_step") == \
        "device time per step grew"
    assert why.get("deviceprof_min_op_efficiency") == \
        "per-op device efficiency dropped"
    # labeled per-op efficiency gauges trip the same drop rule
    a2 = {"schema": metrics_report.SCHEMA, "ts": 1.0, "pid": 1,
          "metrics": [{"name": "deviceprof_op_efficiency", "type": "gauge",
                       "help": "", "labelnames": ["op"],
                       "samples": [{"labels": {"op": "dot"}, "value": 0.9}]}]}
    b2 = json.loads(json.dumps(a2))
    b2["metrics"][0]["samples"][0]["value"] = 0.2
    regs2 = metrics_report.compare_counters(a2, b2)
    assert any(k.startswith("deviceprof_op_efficiency{op=dot") and
               w == "per-op device efficiency dropped"
               for k, *_, w in regs2), regs2
    # getting FASTER / more efficient is not a regression
    assert metrics_report.compare_counters(b, a) == []
    assert metrics_report.compare_counters(a, a) == []
    # and the CLI gate exits nonzero on the regressed pair
    pa, pb = str(tmp_path / "dpa.jsonl"), str(tmp_path / "dpb.jsonl")
    for path, rec in ((pa, a), (pb, b)):
        with open(path, "w") as f:
            f.write(json.dumps(rec) + "\n")
    cli = [sys.executable, os.path.join(_ROOT, "tools", "metrics_report.py")]
    bad = subprocess.run(cli + ["--compare", pa, pb],
                         capture_output=True, text=True, timeout=60)
    assert bad.returncode == 1
    assert "device time per step grew" in bad.stdout
    assert "per-op device efficiency dropped" in bad.stdout


def test_validate_record_catches_rot():
    good = {"schema": perf_report.SCHEMA, "step": 0, "step_ms": 1.0,
            "phases": {"Forward": 1.0}, "ops": [], "num_samples": None,
            "mem_peak_bytes": None}
    assert perf_report.validate_record(good) == []
    assert perf_report.validate_record({}) != []
    bad = dict(good, phases={"Forward": -1.0})
    assert perf_report.validate_record(bad) != []
    bad = dict(good, ops=[{"name": "x"}])       # missing calls/total_ms
    assert perf_report.validate_record(bad) != []
    bad = dict(good, schema="other.v9")
    assert perf_report.validate_record(bad) != []


def _snapshot_with_hist(counters, hists):
    """Valid snapshot with counters plus histogram samples given as
    {name: {bucket_edge: cumulative_count}} (+Inf must be present)."""
    rec = _snapshot_with(counters)
    for name, buckets in hists.items():
        count = buckets["+Inf"]
        mean_edge = max((float(e) for e in buckets if e != "+Inf"),
                        default=1.0)
        rec["metrics"].append(
            {"name": name, "type": "histogram", "help": "",
             "labelnames": [],
             "samples": [{"labels": {}, "buckets": buckets,
                          "sum": mean_edge * count, "count": count}]})
    return rec


def test_metrics_compare_flags_failover_and_swap_drops(tmp_path):
    """ISSUE 10 gate: serving_failover_total growth (requests re-routed
    off dead hosts) and ANY serving_swap_dropped_requests_total growth
    (a hot-swap that dropped traffic — zero by construction) are
    failure-class regressions."""
    a = _snapshot_with({"serving_failover_total": 0,
                        "serving_swap_dropped_requests_total": 0,
                        "serving_tokens_total": 1000})
    b = _snapshot_with({"serving_failover_total": 4,
                        "serving_swap_dropped_requests_total": 2,
                        "serving_tokens_total": 1000})
    regs = metrics_report.compare_counters(a, b)
    why = {k: w for k, *_, w in regs}
    assert why["serving_failover_total"] == "failure counter grew"
    assert why["serving_swap_dropped_requests_total"] == \
        "failure counter grew"
    assert metrics_report.compare_counters(a, a) == []
    pa, pb = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    for path, rec in ((pa, a), (pb, b)):
        with open(path, "w") as f:
            f.write(json.dumps(rec) + "\n")
    cli = [sys.executable, os.path.join(_ROOT, "tools",
                                        "metrics_report.py")]
    bad = subprocess.run(cli + ["--compare", pa, pb],
                         capture_output=True, text=True, timeout=60)
    assert bad.returncode == 1
    assert "serving_failover_total" in bad.stdout


def test_metrics_compare_flags_kv_handoff_p99_regression(tmp_path):
    """ISSUE 10 gate: the serving_kv_handoff_seconds approximate p99
    (from cumulative buckets) GROWING past the threshold is
    failure-class — a handoff-latency tail stalls decode admission even
    when every transfer succeeds. Same-tail traffic growth passes."""
    fast = {"0.005": 90, "0.01": 99, "0.05": 100, "+Inf": 100}
    slow = {"0.005": 10, "0.01": 30, "0.05": 99, "+Inf": 100}
    a = _snapshot_with_hist({"serving_tokens_total": 100},
                            {"serving_kv_handoff_seconds": fast})
    b = _snapshot_with_hist({"serving_tokens_total": 100},
                            {"serving_kv_handoff_seconds": slow})
    regs = metrics_report.compare_counters(a, b)
    why = {k: w for k, *_, w in regs}
    assert why.get("serving_kv_handoff_seconds:p99") == \
        "KV handoff p99 grew", regs
    # same shape at 10x the traffic: the p99 is unchanged -> clean
    fast10 = {k: v * 10 for k, v in fast.items()}
    c = _snapshot_with_hist({"serving_tokens_total": 1000},
                            {"serving_kv_handoff_seconds": fast10})
    assert not any(w == "KV handoff p99 grew" for *_, w in
                   metrics_report.compare_counters(a, c))
    # an unrelated histogram's tail moving is NOT gated
    d = _snapshot_with_hist({"serving_tokens_total": 100},
                            {"serving_decode_step_seconds": slow})
    e = _snapshot_with_hist({"serving_tokens_total": 100},
                            {"serving_decode_step_seconds": fast})
    assert not any("p99" in k for k, *_ in
                   metrics_report.compare_counters(d, e))
    # and the CLI gate exits nonzero on the regression
    pa, pb = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    for path, rec in ((pa, a), (pb, b)):
        with open(path, "w") as f:
            f.write(json.dumps(rec) + "\n")
    cli = [sys.executable, os.path.join(_ROOT, "tools",
                                        "metrics_report.py")]
    bad = subprocess.run(cli + ["--compare", pa, pb],
                         capture_output=True, text=True, timeout=60)
    assert bad.returncode == 1
    assert "serving_kv_handoff_seconds:p99" in bad.stdout


def _snapshot_with_labeled_gauges(gauges):
    """Minimal valid metrics.v1 snapshot of labeled gauges:
    {name: [(labels, value), ...]}."""
    return {"schema": metrics_report.SCHEMA, "ts": 1.0, "pid": 1,
            "metrics": [
                {"name": n, "type": "gauge", "help": "",
                 "labelnames": sorted(samples[0][0]),
                 "samples": [{"labels": dict(lbl), "value": v}
                             for lbl, v in samples]}
                for n, samples in gauges.items()]}


def test_metrics_compare_gates_slo_burn_through_cli(tmp_path):
    """ISSUE 12 gate, through the CLI: `serving_slo_burn` crossing 1.0
    from a clean baseline and a `serving_slo_degraded` 0 -> 1 flip are
    failure-class — zero baselines, where every percentage rule must
    skip, are exactly where the watchdog gauges live in a healthy run.
    Burn GROWTH from a nonzero baseline trips the percentage rule."""
    burn = ("serving_slo_burn", ({"slo": "ttft", "window": "fast"},))
    healthy = _snapshot_with_labeled_gauges({
        "serving_slo_burn": [(burn[1][0], 0.0)],
        "serving_slo_degraded": [({}, 0.0)]})
    breached = _snapshot_with_labeled_gauges({
        "serving_slo_burn": [(burn[1][0], 25.0)],
        "serving_slo_degraded": [({}, 1.0)]})
    regs = metrics_report.compare_counters(healthy, breached)
    why = {k.split("{")[0]: w for k, *_, w in regs}
    assert "serving_slo_burn" in why and "serving_slo_degraded" in why
    assert metrics_report.compare_counters(healthy, healthy) == []
    # sub-1.0 burn from a clean baseline stays clean (budget not yet
    # consumed faster than allowed); degraded flips on ANY nonzero
    warm = _snapshot_with_labeled_gauges({
        "serving_slo_burn": [(burn[1][0], 0.5)],
        "serving_slo_degraded": [({}, 0.0)]})
    assert metrics_report.compare_counters(healthy, warm) == []
    # nonzero-baseline growth rides the percentage rule
    grown = _snapshot_with_labeled_gauges({
        "serving_slo_burn": [(burn[1][0], 2.0)],
        "serving_slo_degraded": [({}, 0.0)]})
    assert any(w == "SLO burn rate grew" for *_, w in
               metrics_report.compare_counters(warm, grown))
    pa, pb = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    for path, rec in ((pa, healthy), (pb, breached)):
        with open(path, "w") as f:
            f.write(json.dumps(rec) + "\n")
    cli = [sys.executable, os.path.join(_ROOT, "tools",
                                        "metrics_report.py")]
    bad = subprocess.run(cli + ["--compare", pa, pb],
                         capture_output=True, text=True, timeout=60)
    assert bad.returncode == 1
    assert "serving_slo_degraded" in bad.stdout
    assert "serving_slo_burn" in bad.stdout


def test_metrics_compare_tenant_membership_and_per_tenant_rules(tmp_path):
    """ISSUE 15 gate, through the CLI: per-tenant shed growth and a
    per-tenant SLO-burn flip fire on exactly the tenant that regressed;
    a tenant present in only one run is MEMBERSHIP-SKIPPED (the PR 12
    worker-intersection machinery generalized to the tenant dimension),
    and the `_all` (unscoped) SLO rows always participate."""
    a = _snapshot_with_labeled({
        "serving_shed_total": [({"tenant": "a"}, 2.0),
                               ({"tenant": "b"}, 2.0)],
        "serving_tokens_total": [({"tenant": "a"}, 1000.0),
                                 ({"tenant": "b"}, 1000.0)]})
    b = _snapshot_with_labeled({
        "serving_shed_total": [({"tenant": "a"}, 2.0),
                               ({"tenant": "b"}, 40.0),
                               ({"tenant": "c"}, 50.0)],
        "serving_tokens_total": [({"tenant": "a"}, 1000.0),
                                 ({"tenant": "b"}, 1000.0),
                                 ({"tenant": "c"}, 5.0)]})
    regs = metrics_report.compare_counters(a, b)
    keys = [k for k, *_ in regs]
    assert "serving_shed_total{tenant=b}" in keys          # the regressor
    assert not any("tenant=a" in k for k in keys)          # healthy tenant
    # tenant c exists only in B (onboarded between runs): its series
    # must not read as failure counters appearing from zero
    assert not any("tenant=c" in k for k in keys), keys
    # per-tenant burn flip from a clean baseline + the _all row's growth
    ga = _snapshot_with_labeled_gauges({"serving_slo_burn": [
        ({"slo": "ttft", "window": "fast", "tenant": "a"}, 0.0),
        ({"slo": "ttft", "window": "fast", "tenant": "b"}, 0.0),
        ({"slo": "ttft", "window": "fast", "tenant": "_all"}, 0.5)]})
    gb = _snapshot_with_labeled_gauges({"serving_slo_burn": [
        ({"slo": "ttft", "window": "fast", "tenant": "a"}, 0.2),
        ({"slo": "ttft", "window": "fast", "tenant": "b"}, 30.0),
        ({"slo": "ttft", "window": "fast", "tenant": "_all"}, 2.0)]})
    gregs = metrics_report.compare_counters(ga, gb, min_delta=0.01)
    gkeys = [k for k, *_ in gregs]
    assert any("tenant=b" in k for k in gkeys), gkeys      # b crossed 1.0
    assert any("tenant=_all" in k for k in gkeys), gkeys   # _all grew
    assert not any(",tenant=a," in k for k in gkeys), gkeys
    # the CLI exit code reflects the per-tenant gate
    pa, pb = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    for path, rec in ((pa, a), (pb, b)):
        with open(path, "w") as f:
            f.write(json.dumps(rec) + "\n")
    cli = [sys.executable, os.path.join(_ROOT, "tools",
                                        "metrics_report.py")]
    bad = subprocess.run(cli + ["--compare", pa, pb],
                         capture_output=True, text=True, timeout=60)
    assert bad.returncode == 1
    assert "serving_shed_total{tenant=b}" in bad.stdout


def test_metrics_compare_flags_rate_limit_and_ns_eviction_growth(tmp_path):
    """ISSUE 17 gate, through the CLI: serving_rate_limited_total{tenant}
    and serving_prefix_ns_evicted_total{namespace} growth are
    failure-class. Membership intersection covers BOTH label dimensions:
    a tenant (or namespace) present in only one run is skipped — churn in
    the tenant roster must not read as counters appearing from zero."""
    a = _snapshot_with_labeled({
        "serving_rate_limited_total": [({"tenant": "a"}, 1.0),
                                       ({"tenant": "b"}, 1.0)],
        "serving_prefix_ns_evicted_total": [({"namespace": "ns-a"}, 2.0)],
        "serving_tokens_total": [({}, 1000.0)]})
    b = _snapshot_with_labeled({
        "serving_rate_limited_total": [({"tenant": "a"}, 1.0),
                                       ({"tenant": "b"}, 40.0),
                                       ({"tenant": "c"}, 99.0)],
        "serving_prefix_ns_evicted_total": [({"namespace": "ns-a"}, 30.0),
                                            ({"namespace": "ns-new"}, 50.0)],
        "serving_tokens_total": [({}, 1000.0)]})
    regs = metrics_report.compare_counters(a, b)
    why = {k: w for k, *_, w in regs}
    assert why.get("serving_rate_limited_total{tenant=b}") == \
        "failure counter grew"
    assert why.get("serving_prefix_ns_evicted_total{namespace=ns-a}") == \
        "failure counter grew"
    keys = list(why)
    # the regressors fire on exactly the member that regressed...
    assert not any("tenant=a" in k for k in keys)
    # ...and roster churn (tenant c / ns-new exist only in B) is skipped
    assert not any("tenant=c" in k for k in keys), keys
    assert not any("ns-new" in k for k in keys), keys
    assert metrics_report.compare_counters(a, a) == []
    # the CLI exit code reflects the gate and names the labeled series
    pa, pb = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    for path, rec in ((pa, a), (pb, b)):
        with open(path, "w") as f:
            f.write(json.dumps(rec) + "\n")
    cli = [sys.executable, os.path.join(_ROOT, "tools",
                                        "metrics_report.py")]
    bad = subprocess.run(cli + ["--compare", pa, pb],
                         capture_output=True, text=True, timeout=60)
    assert bad.returncode == 1
    assert "serving_rate_limited_total{tenant=b}" in bad.stdout
    assert "serving_prefix_ns_evicted_total{namespace=ns-a}" in bad.stdout


@pytest.mark.slow
def test_bench_serve_dist_emits_fleet_artifacts(tmp_path):
    """ISSUE 12 CI: `bench.py --serve-dist` leaves the fleet
    observability artifact set — a schema-valid `fleet_metrics.jsonl`
    (merged metrics.v1 stream with worker_id/role-labeled series and
    _fleet aggregates), ONE merged Prometheus exposition, and a
    `timelines.jsonl` whose reqtimeline.v1 records validate (phase sums
    within the 5% gate is part of validation) with one record per
    completed request."""
    import serve_report

    obs = str(tmp_path / "obs")
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_INIT_BUDGET_S="120",
               BENCH_DIST_REQUESTS="6", BENCH_DIST_MAXNEW="4",
               BENCH_DIST_DECODE_WORKERS="2", BENCH_DIST_OBS_DIR=obs)
    out = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py"), "--serve-dist"],
        capture_output=True, text=True, timeout=560, env=env, cwd=_ROOT)
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert "error" not in rec, rec
    extra = rec["extra"]["dist"]
    assert extra["fleet_polls"] >= 1
    assert extra["timeline_phase_means_s"].get("prefill", 0) > 0
    assert extra["tail_attribution"]["dominant"]

    snaps = metrics_report.load_snapshots(
        os.path.join(obs, "fleet_metrics.jsonl"))   # raises on rot
    members = {(s.get("labels") or {}).get("worker_id")
               for m in snaps[-1]["metrics"] for s in m["samples"]}
    assert {"decode0", "decode1", "prefill0", "router",
            "_fleet"} <= members, members
    prom = open(os.path.join(obs, "fleet_metrics.prom")).read()
    assert metrics_report.validate_prometheus(prom) == []
    assert 'worker_id="_fleet"' in prom

    stream = [json.loads(x) for x in
              open(os.path.join(obs, "timelines.jsonl")) if x.strip()]
    errs = serve_report.validate_records(stream)
    assert errs == [], errs[:5]
    # the stream interleaves decisions.v1 records (ISSUE 15) with the
    # timelines: one timeline per request, plus replay-valid placement
    # decisions
    timelines = [r for r in stream if r["kind"] == "timeline"]
    assert len(timelines) == rec["extra"]["requests"]
    assert any(r["kind"] == "decision" and r["action"] == "place"
               for r in stream)
    phases = {s["phase"] for t in timelines for s in t["phases"]}
    assert {"queue", "prefill", "place", "decode"} <= phases, phases
    assert any(s["phase"] == "kv_handoff"
               for t in timelines for s in t["phases"])


def test_metrics_compare_flags_kv_tier_regressions(tmp_path):
    """ISSUE 18 gate, all three failure-class rules of the KV memory
    hierarchy: a per-tier hit-RATE drop (the generic hits/misses pair,
    per tier label — fires even when hit counts grew with traffic),
    serving_kv_restore_seconds p99 growth (promotion losing its race
    against recompute), and corrupt/drop counter growth (corrupt from a
    zero baseline — a single verify failure gates)."""
    fast = {"0.005": 95, "0.01": 99, "0.05": 100, "+Inf": 100}
    slow = {"0.005": 5, "0.01": 40, "0.05": 99, "+Inf": 100}

    def snap(hits, misses, drops, corrupt, buckets):
        rec = _snapshot_with_labeled(
            {"serving_kv_tier_hits_total": [({"tier": "host"}, hits)],
             "serving_kv_tier_misses_total": [({"tier": "host"}, misses)],
             "serving_kv_tier_drop_total": [({"tier": "host"}, drops)]})
        rec["metrics"].append(
            {"name": "serving_kv_tier_corrupt_total", "type": "counter",
             "help": "", "labelnames": [],
             "samples": [{"labels": {}, "value": corrupt}]})
        count = buckets["+Inf"]
        rec["metrics"].append(
            {"name": "serving_kv_restore_seconds", "type": "histogram",
             "help": "", "labelnames": [],
             "samples": [{"labels": {}, "buckets": buckets,
                          "sum": 0.01 * count, "count": count}]})
        return rec

    a = snap(hits=80, misses=20, drops=0, corrupt=0, buckets=fast)
    b = snap(hits=100, misses=100,       # hits grew, rate 0.8 -> 0.5
             drops=6, corrupt=2, buckets=slow)
    regs = metrics_report.compare_counters(a, b)
    why = {k: w for k, *_, w in regs}
    assert why.get("serving_kv_tier_hit_rate{tier=host}") \
        == "hit rate dropped", regs
    assert why.get("serving_kv_tier_corrupt_total") \
        == "failure counter grew", regs
    assert why.get("serving_kv_tier_drop_total{tier=host}") \
        == "failure counter grew", regs
    assert why.get("serving_kv_restore_seconds:p99") \
        == "KV tier restore p99 grew", regs
    # identical runs stay clean; traffic growth at the same rate and
    # tail fires neither the rate rule nor the p99 rule (the raw miss
    # counter growing 10x with traffic is the failure-counter rule's
    # business, same as every other hits/misses family)
    assert metrics_report.compare_counters(a, a) == []
    c = snap(hits=800, misses=200, drops=0, corrupt=0,
             buckets={k: v * 10 for k, v in fast.items()})
    assert not any(w in ("hit rate dropped", "KV tier restore p99 grew")
                   for *_, w in metrics_report.compare_counters(a, c))
    # and the CLI gate exits nonzero on the regressed run
    pa, pb = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    for path, rec in ((pa, a), (pb, b)):
        with open(path, "w") as f:
            f.write(json.dumps(rec) + "\n")
    cli = [sys.executable, os.path.join(_ROOT, "tools",
                                        "metrics_report.py")]
    bad = subprocess.run(cli + ["--compare", pa, pb],
                         capture_output=True, text=True, timeout=60)
    assert bad.returncode == 1
    assert "serving_kv_tier_corrupt_total" in bad.stdout
