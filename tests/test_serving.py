"""Serving engine slice: static KV cache, prefill/decode split, continuous
batching, and the serving metrics contract.

The two load-bearing properties (ISSUE 3 acceptance):
  - the decode step compiles exactly once per (model, slot-config) and is
    token-exact against the uncached full-forward recompute;
  - iteration-level batching demonstrably refills: a retired slot is
    reused mid-flight by a queued request while other slots keep
    decoding, and the backpressure/timeout paths fire.
"""
import os
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.serving import (
    GenerationEngine, QueueFullError, Scheduler, save_for_generation,
)
from paddle_tpu.text.models import GPTForGeneration, gpt_tiny

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_ROOT, "tools"))
import serve_report  # noqa: E402


@pytest.fixture(scope="module")
def tiny():
    m = gpt_tiny()
    m.eval()
    return m


def _prompt(seed, n, vocab=1000):
    return np.random.RandomState(seed).randint(0, vocab, n)


def _reference_tokens(model, prompt, max_new):
    """Single-request greedy trajectory through the Layer-level cache."""
    gen = GPTForGeneration(model)
    ids = paddle.to_tensor(np.asarray(prompt)[None, :].astype("int64"))
    out, _ = gen.generate(ids, max_new_tokens=max_new)
    return list(out.numpy()[0])


# ---------------------------------------------------------------- parity
def test_cached_generate_matches_uncached(tiny):
    """Acceptance: cached generate() is token-exact vs the no-cache
    full-forward recompute argmax trajectory."""
    gen = GPTForGeneration(tiny)
    ids = paddle.to_tensor(
        np.stack([_prompt(0, 9), _prompt(1, 9)]).astype("int64"))
    cached, cached_len = gen.generate(ids, max_new_tokens=10, use_cache=True)
    plain, plain_len = gen.generate(ids, max_new_tokens=10, use_cache=False)
    np.testing.assert_array_equal(cached.numpy(), plain.numpy())
    np.testing.assert_array_equal(cached_len.numpy(), plain_len.numpy())


def test_cached_prompt_logits_match_full_forward(tiny):
    ids = paddle.to_tensor(_prompt(3, 11)[None, :].astype("int64"))
    want = tiny(ids).numpy()
    cache = tiny.gen_cache(1, 32)
    got, cache = tiny(ids, cache=cache)
    np.testing.assert_allclose(got.numpy(), want, rtol=1e-5, atol=1e-5)
    assert int(np.asarray(cache.pos._data)[0]) == 11


def test_mha_static_decode_cache_matches_growing_cache():
    """MultiHeadAttention: the fixed-shape decode cache and the
    reference's growing concat cache produce the same outputs token by
    token."""
    mha = nn.MultiHeadAttention(32, 4)
    mha.eval()
    x = paddle.to_tensor(
        np.random.RandomState(7).rand(2, 6, 32).astype("float32"))

    growing = mha.gen_cache(x[:, :1])          # empty growing cache
    static = mha.gen_static_decode_cache(2, 8)
    for t in range(6):
        tok = x[:, t:t + 1]
        out_g, growing = mha(tok, tok, tok, None, cache=growing)
        out_s, static = mha(tok, tok, tok, None, cache=static)
        np.testing.assert_allclose(out_s.numpy(), out_g.numpy(),
                                   rtol=1e-5, atol=1e-5)


def test_generation_sampling_strategies_run(tiny):
    gen = GPTForGeneration(tiny)
    ids = paddle.to_tensor(_prompt(5, 6)[None, :].astype("int64"))
    out, _ = gen.generate(ids, max_new_tokens=4, decode_strategy="sampling",
                          temperature=0.8, top_k=16, top_p=0.9)
    toks = out.numpy()
    assert toks.shape == (1, 4)
    assert ((toks >= 0) & (toks < tiny.cfg.vocab_size)).all()


def test_generate_rejects_over_length(tiny):
    """Position lookups clamp under XLA, so a request that would run past
    max_position_embeddings must raise instead of silently degrading."""
    gen = GPTForGeneration(tiny)
    max_pos = tiny.cfg.max_position_embeddings
    ids = paddle.to_tensor(_prompt(0, max_pos - 4)[None, :].astype("int64"))
    with pytest.raises(ValueError, match="max_position_embeddings"):
        gen.generate(ids, max_new_tokens=20)
    with pytest.raises(ValueError, match="max_cache_len"):
        gen.generate(paddle.to_tensor(_prompt(0, 8)[None, :].astype("int64")),
                     max_new_tokens=20, max_cache_len=16)


def test_generate_eos_stops_and_pads(tiny):
    gen = GPTForGeneration(tiny)
    ids = paddle.to_tensor(_prompt(0, 5)[None, :].astype("int64"))
    free, _ = gen.generate(ids, max_new_tokens=6)
    eos = int(free.numpy()[0, 1])      # force eos at the 2nd generated token
    out, length = gen.generate(ids, max_new_tokens=6, eos_token_id=eos)
    toks = out.numpy()[0]
    n = int(length.numpy()[0])
    assert toks[n - 1] == eos
    assert (toks[n:] == eos).all()     # eos-padded tail


# --------------------------------------------------------- compile-once
def test_decode_compiles_exactly_once(tiny):
    """Acceptance: 16+ decode steps after warmup add ZERO new
    compilations (the jitted decode body's python trace counter stays 1)."""
    eng = GenerationEngine(tiny, slots=2, max_len=64, prefill_buckets=(16,))
    eng.prefill(0, _prompt(0, 5))
    eng.prefill(1, _prompt(1, 12))
    eng.decode()                               # warmup: the one compile
    assert eng.trace_counts["decode"] == 1
    for _ in range(16):
        eng.decode()
    assert eng.trace_counts["decode"] == 1     # zero new compilations
    assert eng.trace_counts["prefill"] == {16: 1}

    # refill a slot with a different-length prompt in the same bucket:
    # still no new executables anywhere
    eng.reset_slot(0)
    eng.prefill(0, _prompt(2, 9))
    for _ in range(4):
        eng.decode()
    assert eng.trace_counts["decode"] == 1
    assert eng.trace_counts["prefill"] == {16: 1}


def test_engine_matches_layer_level_generate(tiny):
    """The engine's prefill+decode trajectory is token-exact vs the
    Layer-level cached generate for every slot."""
    prompts = [_prompt(0, 4), _prompt(1, 11)]
    eng = GenerationEngine(tiny, slots=2, max_len=64)
    firsts = [eng.prefill(s, p) for s, p in enumerate(prompts)]
    rows = [[f] for f in firsts]
    for _ in range(5):
        step = eng.decode()
        for s in range(2):
            rows[s].append(int(step[s]))
    for s, p in enumerate(prompts):
        assert rows[s] == _reference_tokens(tiny, p, 6)


# -------------------------------------------------- continuous batching
def test_refill_mid_flight(tiny):
    """Acceptance: a short request retires mid-flight and a queued request
    takes its slot while the other slot keeps decoding; every request's
    stream is token-exact vs its single-request trajectory."""
    eng = GenerationEngine(tiny, slots=2, max_len=64)
    sched = Scheduler(eng, max_queue=4)
    pa, pb, pc = _prompt(0, 3), _prompt(1, 5), _prompt(2, 7)
    ha = sched.submit(pa, max_new_tokens=2)    # retires early
    hb = sched.submit(pb, max_new_tokens=9)    # keeps decoding throughout
    hc = sched.submit(pc, max_new_tokens=3)    # queued; takes A's slot

    sched.step()                               # A,B prefilled + 1 decode
    assert hc.status == "QUEUED"
    while not ha.done():
        sched.step()
    assert ha.status == "DONE" and len(ha.tokens) == 2
    sched.step()                               # refill: C takes A's slot
    assert hc.status == "RUNNING"
    assert not hb.done()                       # B still mid-flight
    sched.run_until_idle()

    assert ha.tokens == _reference_tokens(tiny, pa, 2)
    assert hb.tokens == _reference_tokens(tiny, pb, 9)
    assert hc.tokens == _reference_tokens(tiny, pc, 3)
    # the whole run used the one decode executable
    assert eng.trace_counts["decode"] == 1


def test_queue_cap_rejection(tiny):
    eng = GenerationEngine(tiny, slots=1, max_len=32)
    sched = Scheduler(eng, max_queue=1)
    sched.submit(_prompt(0, 3), max_new_tokens=2)
    with pytest.raises(QueueFullError, match="full"):
        sched.submit(_prompt(1, 3), max_new_tokens=2)
    assert sched.counts["serving.rejected"] == 1
    sched.run_until_idle()


def test_one_token_request_gets_exactly_one(tiny):
    """A max_new_tokens=1 request completes at prefill — the same step's
    decode must not append a second token — and its slot refills
    immediately."""
    eng = GenerationEngine(tiny, slots=1, max_len=32)
    sched = Scheduler(eng, max_queue=4)
    h1 = sched.submit(_prompt(0, 3), max_new_tokens=1)
    h2 = sched.submit(_prompt(1, 4), max_new_tokens=2)
    sched.step()       # prefill h1 -> done at once; h2 takes the slot
    assert h1.status == "DONE" and len(h1.tokens) == 1
    assert h1.tokens == _reference_tokens(tiny, _prompt(0, 3), 1)
    sched.run_until_idle()
    assert h2.status == "DONE" and len(h2.tokens) == 2


def test_submit_validates_engine_limits(tiny):
    """Admission rejects what prefill cannot serve instead of stranding
    the request inside step(); odd max_len still gets a terminal bucket."""
    eng = GenerationEngine(tiny, slots=1, max_len=48)
    assert eng.config.prefill_buckets[-1] == 48
    assert eng.max_prompt_len == 47
    sched = Scheduler(eng)
    with pytest.raises(ValueError, match="empty prompt"):
        sched.submit([])
    with pytest.raises(ValueError, match="engine limits"):
        sched.submit(_prompt(0, 40), max_new_tokens=20)
    # over the bucket ladder even with headroom for max_new
    eng2 = GenerationEngine(tiny, slots=1, max_len=64,
                            prefill_buckets=(16,))
    sched2 = Scheduler(eng2)
    with pytest.raises(ValueError, match="engine limits"):
        sched2.submit(_prompt(0, 20), max_new_tokens=4)
    h = sched2.submit(_prompt(0, 12), max_new_tokens=2)
    sched2.run_until_idle()
    assert h.status == "DONE"


def test_request_timeouts(tiny):
    """Deadline paths: a queued request expires before ever running; a
    running request is cut off mid-generation keeping partial output."""
    now = [0.0]
    eng = GenerationEngine(tiny, slots=1, max_len=64)
    sched = Scheduler(eng, clock=lambda: now[0])
    running = sched.submit(_prompt(0, 3), max_new_tokens=50, timeout_s=10.0)
    queued = sched.submit(_prompt(1, 3), max_new_tokens=5, timeout_s=1.0)
    sched.step()
    assert running.status == "RUNNING"
    now[0] = 5.0                       # queued's deadline (1.0) passed
    sched.step()
    assert queued.status == "TIMEOUT" and queued.tokens == []
    now[0] = 50.0                      # running's deadline passed mid-flight
    sched.step()
    assert running.status == "TIMEOUT"
    assert 0 < len(running.tokens) < 50          # partial stream kept
    assert sched.counts["serving.timeout"] == 2


def test_drain_rejects_new_work(tiny):
    eng = GenerationEngine(tiny, slots=1, max_len=32)
    sched = Scheduler(eng)
    h = sched.submit(_prompt(0, 3), max_new_tokens=2)
    sched.drain()
    assert h.status == "DONE"
    with pytest.raises(QueueFullError, match="drain"):
        sched.submit(_prompt(1, 3))


# ------------------------------------------------------- smoke + metrics
def test_serving_smoke_mixed_lengths(tiny, tmp_path):
    """CI smoke: N mixed-length requests all complete, streamed token
    order is correct per request, and the metrics JSONL validates against
    the serve_report schema."""
    metrics = str(tmp_path / "serve_metrics.jsonl")
    eng = GenerationEngine(tiny, slots=2, max_len=64)
    sched = Scheduler(eng, max_queue=8, metrics_path=metrics)
    lengths = (3, 9, 14, 5, 7)
    handles = [sched.submit(_prompt(i, n), max_new_tokens=3 + i % 3)
               for i, n in enumerate(lengths)]
    sched.drain()

    for i, (h, n) in enumerate(zip(handles, lengths)):
        assert h.status == "DONE"
        assert h.tokens == _reference_tokens(tiny, _prompt(i, n), 3 + i % 3)
        assert h.ttft_s is not None and h.ttft_s >= 0

    records = serve_report.load(metrics)
    assert serve_report.validate_records(records) == []
    summary = serve_report.summarize(records)
    assert summary["requests"] == {"DONE": len(lengths)}
    assert summary["decode_tokens_per_s"] is None \
        or summary["decode_tokens_per_s"] > 0
    assert "serving report" in serve_report.render(summary)

    m = sched.metrics()
    assert m["tokens_generated"] == sum(3 + i % 3 for i in range(len(lengths)))
    assert m["requests"]["serving.completed"] == len(lengths)
    assert m["decode_tokens_per_s"] > 0


# ------------------------------------------------- predictor integration
def test_predictor_generate_cold_load(tiny, tmp_path):
    """save_for_generation -> cold Predictor -> generate, token-exact vs
    the live model."""
    from paddle_tpu.inference import Config, create_predictor
    path = str(tmp_path / "gpt")
    save_for_generation(tiny, path)
    assert os.path.exists(path + ".gencfg")

    pred = create_predictor(Config(path + ".pdmodel", path + ".pdiparams"))
    prompts = [_prompt(0, 4), _prompt(1, 9)]
    outs = pred.generate(prompts, max_new_tokens=4, slots=2, max_len=32)
    for p, got in zip(prompts, outs):
        assert got == _reference_tokens(tiny, p, 4)


def test_bench_decode_rung_runs():
    """bench.py --decode emits the schema the driver parses."""
    import json
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_INIT_BUDGET_S="120",
               BENCH_DECODE_STEPS="2", BENCH_DECODE_SLOTS="2",
               BENCH_DECODE_MAXLEN="32", BENCH_DECODE_PROMPT="4")
    out = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py"), "--decode"],
        capture_output=True, text=True, timeout=560, env=env, cwd=_ROOT)
    line = out.stdout.strip().splitlines()[-1]
    rec = json.loads(line)
    assert rec["metric"] == "gpt_decode_tokens_per_s"
    assert "error" not in rec, rec
    assert rec["value"] > 0
    assert rec["extra"]["trace_counts"]["decode"] == 1
