"""The custom-op extension story (docs/CUSTOM_OPS.md) actually works.

Reference counterpart: custom-op registration tests
(python/paddle/fluid/tests/custom_op/). Three tiers: PyLayer composite,
custom_vjp+pallas device kernel via apply_op, ctypes host code.
"""
import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.core.tensor import apply_op


# ---------------- tier 1: PyLayer with custom backward ----------------

class ClippedExp(paddle.autograd.PyLayer):
    @staticmethod
    def forward(ctx, x):
        y = paddle.exp(paddle.clip(x, -5.0, 5.0))
        ctx.save_for_backward(y)
        return y

    @staticmethod
    def backward(ctx, dy):
        (y,) = ctx.saved_tensor
        return dy * y


def test_pylayer_custom_op():
    x = paddle.to_tensor(np.array([0.5, -1.0], "float32"),
                         stop_gradient=False)
    out = ClippedExp.apply(x)
    out.sum().backward()
    np.testing.assert_allclose(np.asarray(out.numpy()),
                               np.exp([0.5, -1.0]), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(x.grad.numpy()),
                               np.exp([0.5, -1.0]), rtol=1e-6)


# -------- tier 2: pallas kernel + custom_vjp through apply_op ---------

def _scale_shift_kernel(x_ref, o_ref, *, a, b):
    o_ref[...] = x_ref[...] * a + b


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _scale_shift(x, a, b):
    from jax.experimental import pallas as pl
    return pl.pallas_call(
        functools.partial(_scale_shift_kernel, a=a, b=b),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=jax.default_backend() != "tpu")(x)


def _ss_fwd(x, a, b):
    return _scale_shift(x, a, b), None


def _ss_bwd(a, b, _, g):
    return (g * a,)


_scale_shift.defvjp(_ss_fwd, _ss_bwd)


def scale_shift(x, a=2.0, b=1.0):
    return apply_op(lambda xa: _scale_shift(xa, a, b), x)


def test_pallas_custom_kernel_op():
    x = paddle.to_tensor(np.ones((8, 128), "float32") * 3.0,
                         stop_gradient=False)
    out = scale_shift(x, a=2.0, b=1.0)
    out.sum().backward()
    np.testing.assert_allclose(np.asarray(out.numpy()),
                               np.full((8, 128), 7.0), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(x.grad.numpy()),
                               np.full((8, 128), 2.0), rtol=1e-6)


def test_custom_kernel_op_under_jit():
    # the same op must compose with jit tracing (hapi/jit path)
    @jax.jit
    def f(xa):
        return _scale_shift(xa, 3.0, 0.0).sum()

    val = f(jnp.ones((8, 128)))
    assert float(val) == pytest.approx(3.0 * 8 * 128)


# ------------------- tier 3: ctypes host-side code --------------------

def test_ctypes_host_binding():
    """The framework's own native boundary doubles as the user recipe."""
    import ctypes
    libm = ctypes.CDLL("libm.so.6")
    libm.cbrt.restype = ctypes.c_double
    libm.cbrt.argtypes = [ctypes.c_double]
    assert libm.cbrt(27.0) == pytest.approx(3.0)
